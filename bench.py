"""Headline benchmark for the driver: bf16 matmul TFLOP/s per chip.

Prints exactly ONE JSON line in every outcome:
  success: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
  failure: same keys with value 0.0 plus {"error", "stage", "detail",
  "last_good_artifact"} — the last field an informational pointer to the
  newest committed probe measurement (never a substitute value)

``--serve-paged`` runs the CPU-runnable paged-vs-dense serving
microbench instead (same one-JSON-line contract): peak concurrent slots
and decode tokens/s at a fixed simulated HBM budget.

``--serve-spec`` runs the speculative-vs-plain engine comparison (same
contract) as an explicit ``JAX_PLATFORMS=cpu`` fallback arm tagged
``"backend": "cpu-fallback"`` — the on-chip probe has been wedged at
``backend_init`` since BENCH_r05, and this arm keeps the perf
trajectory recording comparative numbers (accepted-tokens/dispatch,
spec vs plain decode tokens/s, int8 vs fp paged-pool capacity) instead
of only the failure record while the device tunnel is down.

``--serve-attn`` gates the ragged paged-attention kernel (same
contract, CPU fallback arm per the --serve-spec precedent): paired
pallas-paged vs xla-gather decode arms at fixed batch/pages, greedy
outputs asserted token-identical before any number is reported. The
headline is the MODELED decode-read bytes ratio (gather's 4 full-width
HBM passes vs the kernel's single live-page walk,
ops/paged_attention.paged_decode_bytes) at the arms' realized fill —
gate >= 1.2x (vs_baseline = ratio/1.2); wall-clock tokens/s for both
arms rides in the detail but the interpreter-mode Pallas arm's time is
a CPU artifact, not the transferable number.

``--serve-tp`` gates tensor-parallel serving (same contract, CPU
fallback arm per the --serve-attn precedent): tp_shards=2 over a
forced 2-virtual-device host vs the single-chip engine, greedy outputs
asserted token-identical first. The headline is the MODELED per-chip
KV page bytes ratio (models/quant.kv_page_bytes at tp_shards=2 over 1)
— gate <= 0.55x (vs_baseline = 0.55/ratio); both arms' tokens/s ride
in the detail, and the worker prints a serve_tp(...) mesh probe line
in the dryrun_multichip format so "tunnel wedged" and "TP untested"
stay distinguishable. The >= 1.6x 2-chip decode tokens/s gate applies
to the on-chip arm when the tunnel recovers.

``--serve-obs`` measures the observability layer's decode overhead
(same contract): decode tokens/s with tracing+histograms on vs off;
the <5% budget from ISSUE 2, vs_baseline = overhead/5.

``--serve-tier`` gates the host KV page tier (same contract): warm-turn
restore latency (tier swap-in + suffix prefill) vs cold re-prefill at a
512-token prompt, gate <= 1/3 (vs_baseline = ratio*3, <=1.0 passes),
with restorable-session capacity at a fixed page pool vs the no-tier
engine (gate >= 8x) carried in the detail.

``--serve-router`` gates the scale-out router tier (same contract): two
in-process replica servers behind a real router HTTP hop, multi-turn
sessions driven through policy affinity vs policy random; sticky must
keep >= 90% of warm turns on a warm cache (tier swap-in or prompt-cache
hit) while the round-robin baseline stays <= 60%, and the router's
measured proxy overhead p50 must stay <= 5% of the request p50
(vs_baseline = sticky_rate/0.90, >= 1.0 passes all three in detail).

``--serve-autoscale`` gates the autoscaler subsystem (same contract):
the whole loop cluster-free — a LocalProcessActuator fleet of real
server subprocesses, the router hot-reloading membership from the
actuator's replicas file, and the controller scraping real /metrics.
Under loadgen's ramp the fleet must scale 1->2 and back with zero
failed requests, and a session parked by the scale-down drain protocol
(released with spill=true) must serve its next turn warm on the
survivor: restore <= 1/3 of a cold re-prefill, the --serve-tier bound
(vs_baseline = ratio*3, <=1.0 passes; scale/zero-fail gates in detail).

``--serve-canary`` gates the correctness watchdog (same contract): a
2-replica routed fleet under threaded loadgen, paired arms with the
blackbox canary probing at 1 Hz (all four known-answer paths) vs
canary-off; the prober must cost <= 5% of loadgen throughput
(vs_baseline = overhead/5) AND, with gen_corrupt armed on one replica
(silent token corruption, /healthz stays green), flag the mismatch
within two probe rounds (detection gate in detail).

``--serve-qos`` gates the SLO-aware QoS layer (same contract): one
qos+tier replica at 2x overload (concurrency = 2x engine slots, split
interactive:batch); interactive p99 TTFT (streamed, first-token timed)
must stay within the class SLO while EVERY batch request completes —
predictive-admission 503s retried per Retry-After, shed means delayed,
never lost (vs_baseline = p99/SLO; no-batch-lost gate in detail).

``--train-obs`` is the training twin (same contract): median step time
of a short CPU train loop with TrainObs metrics on (K3STPU_TRAIN_OBS=1,
the default) vs off; <=5% step-time budget, vs_baseline = overhead/5.

``--trace-obs`` gates the distributed-tracing layer (same contract):
decode tokens/s with the full W3C edge path per request (traceparent
parse, trace-id propagation into the engine, exemplar-bearing
OpenMetrics scrape, echo mint) vs trace-id-free submits; <=5% budget
on the paired-arms --train-obs idiom, vs_baseline = overhead/5.

``--node-obs`` gates the fleet tier (same contract, no jax at all):
CPU cost of one node-exporter /metrics render over a synthetic 4-chip
sysfs + 8 drop files, as percent of one core at a 1 Hz scrape; <=5%
budget, vs_baseline = pct/5.

Baseline (BASELINE.md): the reference publishes no numbers, so the target is
BASELINE.json's north star — >=50% MFU on v5e => 98.5 bf16 TFLOP/s per chip.
``vs_baseline`` is achieved/98.5 (so 1.0 == the 50%-MFU target; 2.0 == peak).

Capture-robustness (the chip is reached through a tunnel that can wedge; a
bare ``jax.devices()`` has been observed to hang indefinitely): the parent
process never imports jax. Backend init is probed in a killable subprocess
with a bounded timeout and one retry; the measurement itself runs in a second
subprocess the same way. On timeout the whole process group is SIGKILLed so
no stray process is left holding the chip claim. A hung tunnel therefore
degrades to a structured one-line error, never a traceback or a hang.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Group-killed bounded subprocesses (shared wedge-proof discipline); pulls in
# k3stpu/utils only — the parent still never imports jax.
from k3stpu.utils.env import env_int as _env_int  # noqa: E402
from k3stpu.utils.subproc import kill_active_groups, run_bounded  # noqa: E402

BASELINE_TFLOPS = 98.5  # 50% MFU on v5e (197 bf16 peak) — BASELINE.md
# Probe bounds are env-overridable so a wedged-tunnel failure (BENCH_r05
# died at backend_init) can be triaged — longer timeout, more attempts —
# without editing code. Malformed values fall back to the defaults (same
# degrade-not-crash semantics as the K3STPU_RDV_* knobs; parser shared in
# k3stpu/utils/env.py).

PROBE_TIMEOUT_S = _env_int("K3STPU_BENCH_PROBE_TIMEOUT_S", 120)
PROBE_ATTEMPTS = max(1, _env_int("K3STPU_BENCH_PROBE_ATTEMPTS", 2))
MEASURE_TIMEOUT_S = 480  # compile (~20-40s first time) + timed loop
RETRY_WAIT_S = 10
RETRY_FAST_S = 60       # only failures faster than this are worth retrying
# Worst case (defaults): probe 2x120 + 10, then measure 480 (a timeout is
# never retried — a wedge that ate the full budget will eat the retry too —
# and an rc!=0 failure is retried only if it failed fast, < RETRY_FAST_S,
# so the retry leg adds at most 60 + 10 + 480) ~= 800s. Callers must wrap
# with a timeout ABOVE that (see verify skill: 900s); raising the probe
# env knobs raises the worst case accordingly.

# Per-stage wall-times, recorded as each stage ends: a failure line says
# WHERE the budget went (e.g. backend_init ate 2x120s) — _fail attaches it.
_stage_s: "dict[str, float]" = {}

def _on_term(signum, frame):
    # If the bench itself is killed (e.g. an outer `timeout`), take the
    # chip-holding child down with us — an orphaned wedged jax process
    # would keep the device claim and hang every later run.
    kill_active_groups()
    sys.exit(128 + signum)

_PROBE_SRC = (
    "import jax; ds = jax.devices(); "
    "print('PROBE_OK', ds[0].platform, len(ds), "
    "getattr(ds[0], 'device_kind', 'unknown'))"
)


def _emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def _last_good_artifact() -> "str | None":
    """Pointer to the newest committed probe artifact with a BENCH_JSON
    line — informational context for a failure line ONLY (value stays
    0.0: a wedged live run is a wedged live run; the pointer just tells
    the reader where the last real measurement lives)."""
    import glob
    import re

    def _round_no(path: str) -> int:
        # Numeric round order: probe_r10.log must outrank probe_r9.log
        # (lexicographic sort puts r10 before r9 and would pin the
        # pointer to an old round forever once rounds hit two digits).
        m = re.search(r"probe_r(\d+)\.log$", path)
        return int(m.group(1)) if m else -1

    for path in sorted(glob.glob(os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "artifacts", "probe_r*.log")), key=_round_no, reverse=True):
        try:
            with open(path) as f:
                # A probe log holds one BENCH_JSON per measurement; the
                # LAST is the final (post-warmup, post-retry) number —
                # the first can be a cold-compile throwaway.
                matches = re.findall(r'BENCH_JSON ({.*})', f.read())
            if matches:
                d = json.loads(matches[-1])
                return (f"{os.path.basename(path)}: {d.get('tflops')} "
                        f"TF/s (mfu {d.get('mfu')}) at "
                        f"{d.get('m')}^3 {d.get('dtype')}")
        except (OSError, ValueError, json.JSONDecodeError):
            continue
    return None


def _fail(stage: str, detail: str, *,
          metric: str = "pjit_matmul_bf16_tflops_per_chip",
          unit: str = "TFLOP/s/chip") -> int:
    _emit({
        "metric": metric,
        "value": 0.0,
        "unit": unit,
        "vs_baseline": 0.0,
        "error": f"benchmark failed at stage '{stage}'",
        "stage": stage,
        "detail": detail[-2000:],
        "stage_s": {k: round(v, 2) for k, v in _stage_s.items()} or None,
        "last_good_artifact": _last_good_artifact(),
    })
    return 0  # structured failure IS the output; don't turn it into an rc


def _run_with_retry(cmd: list[str], timeout_s: int, *,
                    retry_on_timeout: bool, attempts: int = 2,
                    stage: "str | None" = None):
    """Up to ``attempts`` bounded tries. A timeout is only retried when
    asked (it already consumed the full budget), and an rc!=0 failure only
    when it failed fast — a slow crash retried would blow the documented
    worst-case budget. The stage's cumulative wall-time (waits included)
    lands in ``_stage_s`` for failure-line triage.
    Returns (ok, rc, out, err)."""
    t0 = time.monotonic()
    try:
        for attempt in range(1, attempts + 1):
            ta = time.monotonic()
            rc, out, err = run_bounded(cmd, timeout_s)
            elapsed = time.monotonic() - ta
            retry = (retry_on_timeout if rc is None
                     else rc != 0 and elapsed < RETRY_FAST_S)
            if rc == 0 or not retry or attempt == attempts:
                return rc == 0, rc, out, err
            time.sleep(RETRY_WAIT_S)
    finally:
        if stage is not None:
            _stage_s[stage] = time.monotonic() - t0


def _worker() -> int:
    """The actual measurement (runs in a bounded subprocess)."""
    import jax

    from k3stpu.ops.matmul import measure_matmul, measure_pjit_matmul

    devices = jax.devices()
    on_accel = devices[0].platform != "cpu"
    # The HEADLINE stays pinned to 8192^3 — the shape the probe measures
    # and every prior round's BENCH used, so the trend is apples to
    # apples (the round-3 lesson: harness deltas masquerade as hardware
    # deltas). 16384^3 is measured additionally on real hardware and
    # reported alongside; its compile hits the persistent cache on
    # re-runs. A failure in one shape (e.g. an OOM or tunnel flake on
    # the big one) must not void the other's measurement.
    headline_dim = 8192 if on_accel else 512
    dims = (headline_dim, 16384) if on_accel else (headline_dim,)
    iters = 50 if on_accel else 5

    mesh = None
    if len(devices) > 1:
        from k3stpu.parallel.mesh import make_mesh

        mesh = make_mesh(len(devices), model_parallelism=1,
                         axis_names=("data", "model"))

    results, errors = {}, {}
    for dim in dims:
        try:
            if mesh is not None:
                results[dim] = measure_pjit_matmul(mesh, m=dim, n=dim,
                                                   k=dim, iters=iters)
            else:
                results[dim] = measure_matmul(m=dim, n=dim, k=dim,
                                              iters=iters)
        except Exception as e:  # noqa: BLE001 — keep the other shape
            errors[dim] = f"{type(e).__name__}: {e}"[:300]
    if not results:
        raise RuntimeError(f"every shape failed: {errors}")
    res = results.get(headline_dim)
    # A surviving non-headline shape must NOT be promoted into the
    # headline metric: larger shapes run at higher MFU, so substitution
    # would break the apples-to-apples trend the pin exists for. The
    # headline reads failed (value 0.0 + error/stage/detail, the same
    # schema as every other failure line) and the surviving shapes stay
    # visible under all_shapes.
    doc = {
        "metric": "pjit_matmul_bf16_tflops_per_chip",
        "all_shapes": [r.to_dict() for r in results.values()],
        "shape_errors": errors or None,
        "device_kind": getattr(devices[0], "device_kind", "unknown"),
        "n_devices": len(devices),
    }
    if res is not None:
        doc.update(value=round(res.tflops, 2), unit="TFLOP/s/chip",
                   vs_baseline=round(res.tflops / BASELINE_TFLOPS, 4),
                   detail=res.to_dict())
    else:
        # Full failure schema (value 0.0 + error/stage/detail/
        # last_good_artifact), matching _fail's lines so consumers need
        # one failure shape only — NOT the surviving shape promoted into
        # the headline.
        doc.update(value=0.0, unit="TFLOP/s/chip", vs_baseline=0.0,
                   error=f"headline shape {headline_dim}^3 failed",
                   stage="headline_shape",
                   detail=errors.get(headline_dim, "unknown"),
                   last_good_artifact=_last_good_artifact())
    _emit(doc)
    return 0


def _serve_paged_worker() -> int:
    """Paged-vs-dense serving microbench (runs in a bounded subprocess).

    CPU-runnable by design: the question is allocator capacity and the
    gather-attention overhead, not chip FLOP/s, so a tiny model on the
    CPU backend answers it. Both engines get the SAME simulated HBM
    budget — 4 dense rows of max_seq tokens (512 token-slots) — and the
    same offered load of 16 concurrent requests. Dense can hold 4 slots
    in that budget; paged holds 16 slots over a 32-page pool of the same
    token capacity. Reported: peak concurrent slots and decode tokens/s
    (busy-time normalized, post-warmup) for each."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import threading

    import numpy as np

    from k3stpu.models.transformer import transformer_lm_tiny
    from k3stpu.serve.engine import GenerateEngine

    max_seq, page_size = 128, 16
    dense_slots = 4
    budget_tokens = dense_slots * max_seq          # 512 token-slots
    paged_slots = 16
    num_pages = 1 + budget_tokens // page_size     # 32 usable + sink
    n_reqs, prompt_len, new_tokens = 16, 8, 24

    model = transformer_lm_tiny(max_seq_len=max_seq)
    params = model.init(jax.random.key(0),
                        np.zeros((1, 1), np.int32))["params"]

    def drive(engine):
        # Warmup covers prefill + decode compiles, then the measured
        # wave runs against reset counters so tokens_per_s is pure
        # steady-state decode.
        engine.submit([[1, 2, 3]], max_new_tokens=4)
        engine.reset_stats()
        results = [None] * n_reqs

        def go(i):
            prompt = [((i * 7 + j) % 97) + 1 for j in range(prompt_len)]
            results[i] = engine.submit([prompt], max_new_tokens=new_tokens)

        threads = [threading.Thread(target=go, args=(i,))
                   for i in range(n_reqs)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if not all(r is not None and len(r[0]) == new_tokens
                   for r in results):
            raise RuntimeError("a request failed or came back short")
        return engine.stats()

    dense = GenerateEngine(model, params, slots=dense_slots, seed=0)
    try:
        ds = drive(dense)
    finally:
        dense.close()
    paged = GenerateEngine(model, params, slots=paged_slots, seed=0,
                           page_size=page_size, num_pages=num_pages)
    try:
        ps = drive(paged)
    finally:
        paged.close()

    slot_ratio = ps["peak_active_slots"] / max(ds["peak_active_slots"], 1)
    tps_ratio = (ps["tokens_per_s"] / ds["tokens_per_s"]
                 if ds["tokens_per_s"] else 0.0)
    doc = {
        # Headline: concurrency multiplier at a FIXED HBM budget — the
        # number the paged pool exists to move. >=2.0 is the bar;
        # vs_baseline is achieved/2.0 so 1.0 == the bar, like the matmul
        # line's 1.0 == the MFU target.
        "metric": "serve_paged_capacity_ratio",
        "value": round(slot_ratio, 2),
        "unit": "x_concurrent_slots_at_fixed_hbm",
        "vs_baseline": round(slot_ratio / 2.0, 4),
        "detail": {
            "hbm_budget_token_slots": budget_tokens,
            "page_size": page_size,
            "dense_slots": dense_slots,
            "paged_slots": paged_slots,
            "dense_peak_active_slots": ds["peak_active_slots"],
            "paged_peak_active_slots": ps["peak_active_slots"],
            "dense_decode_tokens_per_s": ds["tokens_per_s"],
            "paged_decode_tokens_per_s": ps["tokens_per_s"],
            "decode_tps_ratio": round(tps_ratio, 4),
            "paged_density_ratio": ps.get("paged_density_ratio"),
            "page_utilization_at_end": ps.get("page_utilization"),
        },
    }
    # BENCH_JSON first for artifact greps (probe-log convention); the
    # bare dict line after it is what the parent re-emits.
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _serve_spec_worker() -> int:
    """Speculative-decoding microbench (bounded subprocess).

    Deliberately a CPU fallback arm: acceptance rate and verify-width
    amortization are scheduling properties, not chip FLOP/s, so the CPU
    backend answers them — and with the on-chip probe wedged at
    backend_init, this keeps comparative numbers flowing. The JSON is
    tagged ``"backend": "cpu-fallback"`` so no reader mistakes it for a
    device measurement.

    Four arms share one tiny paged model: {speculate on, off} x
    {repetitive-suffix greedy prompts, non-repetitive sampled traffic}.
    Headline: accepted draft tokens per verify dispatch on the
    repetitive arm (> 1.5 is the bar — each verify costs ~one plain
    dispatch, so 1.5 accepted + 1 correction token is a >2x
    tokens-per-round-trip win). The non-repetitive arm samples at
    temperature 0.7: genuinely non-repetitive streams the drafter gets
    no foothold on (and verify is argmax-only), so the engine takes its
    plain path and tokens/s must sit at parity — the "speculation never
    slows traffic it can't accelerate" check. The greedy repetitive
    arm's tokens/s ratio is ALSO reported but is a CPU artifact: a
    W-wide verify costs W x the compute of a 1-token decode on CPU,
    while on a TPU decode is HBM-bound and the width is nearly free —
    the transferable number is tokens-per-dispatch. Detail further
    carries the int8-vs-fp paged-pool capacity ratios at a fixed byte
    budget (models/quant.kv_pages_for_budget). Outputs are asserted
    token-identical between the spec and plain engines (same seed) on
    both arms before any number is reported."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import dataclasses
    import threading

    import numpy as np

    from k3stpu.models.quant import kv_page_bytes, kv_pages_for_budget
    from k3stpu.models.transformer import transformer_lm_tiny
    from k3stpu.serve.engine import GenerateEngine

    max_seq, page_size, slots = 128, 16, 8
    num_pages = 1 + slots * max_seq // page_size
    n_reqs, new_tokens = 8, 32

    model = transformer_lm_tiny(max_seq_len=max_seq)
    params = model.init(jax.random.key(0),
                        np.zeros((1, 1), np.int32))["params"]

    # Repetitive-suffix prompts (templated/code-like traffic, the
    # prompt-lookup drafter's home turf) vs prompts with every token
    # distinct (no n-gram in the prompt ever recurs).
    rep_prompts = [[(i % 5) + 1, ((i + 3) % 7) + 1] * 6
                   for i in range(n_reqs)]
    rng = np.random.default_rng(7)
    plain_prompts = [rng.permutation(np.arange(1, 97))[:12].tolist()
                     for _ in range(n_reqs)]

    def drive(engine, prompts, temperature=0.0):
        # Warmup prompt REPEATS a bigram so a speculative engine actually
        # proposes and compiles its verify program here — otherwise the
        # first measured dispatch pays the JIT and poisons tokens_per_s.
        engine.submit([[1, 2] * 4], max_new_tokens=8)
        if temperature > 0.0:
            engine.submit([[1, 2] * 4], max_new_tokens=8,
                          temperature=temperature)  # sampled-path compile
        engine.reset_stats()
        results = [None] * len(prompts)

        def go(i):
            results[i] = engine.submit([prompts[i]],
                                       max_new_tokens=new_tokens,
                                       temperature=temperature)

        threads = [threading.Thread(target=go, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if not all(r is not None and len(r[0]) == new_tokens
                   for r in results):
            raise RuntimeError("a request failed or came back short")
        return engine.stats(), [tuple(r[0]) for r in results]

    def run_arm(speculate, prompts, temperature=0.0):
        # decode_block=1 makes the arms compare dispatch-for-dispatch:
        # one speculative verify replaces ONE plain decode dispatch (the
        # engine's spec path preempts the whole block, so leaving the
        # default K=4 would measure block amortization, not speculation).
        engine = GenerateEngine(model, params, slots=slots, seed=0,
                                decode_block=1,
                                page_size=page_size, num_pages=num_pages,
                                speculate=speculate, spec_gamma=4)
        try:
            return drive(engine, prompts, temperature)
        finally:
            engine.close()

    spec_rep, out_spec_rep = run_arm(True, rep_prompts)
    plain_rep, out_plain_rep = run_arm(False, rep_prompts)
    # Sampled outputs are not comparable across engines (the sampling
    # key rides the dispatch counter, which speculation advances
    # differently) — exactness is a greedy-arm property, pinned hard in
    # tests/test_spec_engine.py; here it gates the greedy numbers.
    spec_non, _ = run_arm(True, plain_prompts, 0.7)
    plain_non, _ = run_arm(False, plain_prompts, 0.7)
    if out_spec_rep != out_plain_rep:
        raise RuntimeError("speculative output diverged from the plain "
                           "engine — exactness is broken, numbers void")

    acc_per_dispatch = (spec_rep["spec_accepted"]
                        / max(spec_rep["spec_dispatches"], 1))
    # int8-vs-fp pool capacity at the byte budget THIS pool occupies.
    cfg_fp32 = dataclasses.replace(model.config, dtype=jax.numpy.float32)
    cfg_int8 = dataclasses.replace(model.config, kv_cache_dtype="int8")
    budget = num_pages * kv_page_bytes(cfg_fp32, page_size)
    pages_fp32 = kv_pages_for_budget(budget, cfg_fp32, page_size)
    pages_int8 = kv_pages_for_budget(budget, cfg_int8, page_size)
    doc = {
        # Headline: accepted draft tokens per verify dispatch on
        # repetitive-suffix prompts. > 1.5 is the bar; vs_baseline =
        # achieved/1.5 so 1.0 == the bar.
        "metric": "serve_spec_accepted_tokens_per_dispatch",
        "value": round(acc_per_dispatch, 2),
        "unit": "accepted_tokens_per_verify_dispatch",
        "vs_baseline": round(acc_per_dispatch / 1.5, 4),
        "backend": "cpu-fallback",
        "detail": {
            "spec_gamma": 4,
            "slots": slots,
            "new_tokens_per_request": new_tokens,
            "repetitive": {
                "spec_accept_rate": spec_rep.get("spec_accept_rate"),
                "spec_tokens_per_dispatch":
                    spec_rep.get("spec_tokens_per_dispatch"),
                "spec_decode_tokens_per_s": spec_rep["tokens_per_s"],
                "plain_decode_tokens_per_s": plain_rep["tokens_per_s"],
                "spec_vs_plain_tps": round(
                    spec_rep["tokens_per_s"] / plain_rep["tokens_per_s"],
                    4) if plain_rep["tokens_per_s"] else None,
            },
            "non_repetitive": {
                "temperature": 0.7,
                "spec_dispatches": spec_non["spec_dispatches"],
                "spec_decode_tokens_per_s": spec_non["tokens_per_s"],
                "plain_decode_tokens_per_s": plain_non["tokens_per_s"],
                "spec_vs_plain_tps": round(
                    spec_non["tokens_per_s"] / plain_non["tokens_per_s"],
                    4) if plain_non["tokens_per_s"] else None,
            },
            "int8_paged_kv": {
                "pool_byte_budget": budget,
                "page_size": page_size,
                "pages_fp32": pages_fp32,
                "pages_int8": pages_int8,
                "capacity_ratio_vs_fp32": round(pages_int8 / pages_fp32,
                                                2),
                "capacity_ratio_vs_bf16": round(
                    kv_page_bytes(model.config, page_size)
                    / kv_page_bytes(cfg_int8, page_size), 2),
            },
        },
    }
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _serve_spec_main() -> int:
    """Bounded-subprocess wrapper for --serve-spec (parent never imports
    jax; same wedge-proof discipline as every other arm)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__), "--serve-spec-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="serve_spec")
    skw = {"metric": "serve_spec_accepted_tokens_per_dispatch",
           "unit": "accepted_tokens_per_verify_dispatch"}
    if not ok:
        why = (f"spec bench did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("serve_spec", f"{why}; stderr: {err.strip()}", **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def _serve_attn_worker() -> int:
    """Paged-attention backend microbench (bounded subprocess).

    A CPU fallback arm by design (the on-chip probe has been wedged at
    backend_init since BENCH_r03-r05): the Pallas kernel runs in
    INTERPRETER mode here, so its wall-clock is a Python-loop artifact
    that cannot beat compiled XLA — the transferable number is the
    modeled HBM byte ratio, which is what decode time is made of on a
    TPU (decode attention is memory-streaming; docs/ATTN_ROOFLINE.md).
    Both arms run the same fp32 tiny model over the same ragged greedy
    prompts at fixed batch/pages and must emit IDENTICAL tokens before
    any number is reported. The >= 1.2x gate applies to the modeled
    ratio at the arms' realized mid-decode fill; the wall-clock gate
    moves to the on-chip arm when the tunnel recovers."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import threading

    import numpy as np

    from k3stpu.models.transformer import transformer_lm_tiny
    from k3stpu.ops.paged_attention import paged_decode_bytes
    from k3stpu.serve.engine import GenerateEngine

    max_seq, page_size, slots = 64, 8, 4
    num_pages = 1 + slots * max_seq // page_size
    new_tokens = 12
    # Ragged on purpose: short rows are where early-stop pays; the long
    # row pins the page-boundary walk.
    prompts = [[5, 6, 7], [3, 4, 5, 6, 7, 8, 9, 10],
               list(range(1, 21)), [40, 41]]

    model = transformer_lm_tiny(max_seq_len=max_seq,
                                dtype=jax.numpy.float32)
    params = model.init(jax.random.key(0),
                        np.zeros((1, 1), np.int32))["params"]

    def run_arm(backend):
        engine = GenerateEngine(model, params, slots=slots, seed=0,
                                decode_block=1, page_size=page_size,
                                num_pages=num_pages,
                                attn_backend=backend)
        try:
            engine.submit([[1, 2, 3]], max_new_tokens=4)  # compile
            engine.reset_stats()
            results = [None] * len(prompts)

            def go(i):
                results[i] = engine.submit([prompts[i]],
                                           max_new_tokens=new_tokens)

            threads = [threading.Thread(target=go, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if not all(r is not None and len(r[0]) == new_tokens
                       for r in results):
                raise RuntimeError("a request failed or came back short")
            stats = engine.stats()
            if stats["attn_backend"] != backend:
                raise RuntimeError(f"stats report "
                                   f"{stats['attn_backend']}, arm ran "
                                   f"{backend}")
            return stats, [tuple(r[0]) for r in results]
        finally:
            engine.close()

    gather, out_gather = run_arm("xla-gather")
    paged, out_paged = run_arm("pallas-paged")
    if out_gather != out_paged:
        raise RuntimeError("pallas-paged output diverged from the "
                           "xla-gather engine — exactness is broken, "
                           "numbers void")

    # Modeled decode-read bytes at the realized mid-decode fill: each
    # row's live length halfway through its generation budget.
    cfg = model.config
    mid_lens = [len(p) + new_tokens // 2 for p in prompts]
    bb = paged_decode_bytes(slots, mid_lens, max_seq,
                            cfg.n_kv_heads or cfg.n_heads,
                            cfg.d_model // cfg.n_heads, page_size,
                            dtype_bytes=4.0)
    ratio = bb["bytes_ratio"]
    doc = {
        # Headline: modeled gather-read bytes over kernel-walk bytes
        # per decode step. >= 1.2 is the gate; vs_baseline = ratio/1.2
        # so 1.0 == the bar.
        "metric": "serve_attn_decode_bytes_ratio",
        "value": round(ratio, 3),
        "unit": "xla_gather_bytes_over_pallas_paged_bytes",
        "vs_baseline": round(ratio / 1.2, 4),
        "backend": "cpu-fallback",
        "detail": {
            "slots": slots, "page_size": page_size,
            "num_pages": num_pages, "max_seq": max_seq,
            "new_tokens_per_request": new_tokens,
            "mid_decode_lengths": mid_lens,
            "live_tokens": bb["live_tokens"],
            "full_tokens": bb["full_tokens"],
            "xla_gather_bytes": bb["xla_gather_bytes"],
            "pallas_paged_bytes": bb["pallas_paged_bytes"],
            "tokens_identical": True,
            # Interpreter-arm wall clock — a CPU artifact (the Pallas
            # interpreter is a Python loop), recorded for trend only.
            "xla_gather_tokens_per_s": gather["tokens_per_s"],
            "pallas_interpret_tokens_per_s": paged["tokens_per_s"],
            "dispatches": gather["dispatches"],
        },
    }
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _serve_attn_main() -> int:
    """Bounded-subprocess wrapper for --serve-attn (parent never
    imports jax; same wedge-proof discipline as every other arm)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__), "--serve-attn-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="serve_attn")
    skw = {"metric": "serve_attn_decode_bytes_ratio",
           "unit": "xla_gather_bytes_over_pallas_paged_bytes"}
    if not ok:
        why = (f"attn bench did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("serve_attn", f"{why}; stderr: {err.strip()}", **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def _serve_tp_worker() -> int:
    """Tensor-parallel serving microbench (bounded subprocess).

    A CPU fallback arm per the --serve-attn precedent (the on-chip
    probe rides the same wedged tunnel): tp_shards=2 over a forced
    2-virtual-device host vs the single-chip engine, same fp32 tiny
    model, same ragged greedy prompts, outputs asserted
    TOKEN-IDENTICAL before any number is reported. On CPU the 2-shard
    wall clock is an emulation artifact, so the transferable headline
    is the MODELED per-chip KV page bytes ratio
    (models/quant.kv_page_bytes at tp_shards=2 over tp_shards=1 —
    exactly the HBM the pool costs each chip); gate <= 0.55x. The
    >= 1.6x 2-chip decode tokens/s gate moves to the on-chip arm when
    the tunnel recovers. The probe line (serve_tp(...): mesh={...})
    records the realized serving mesh the same way the
    dryrun_multichip line does, so a missing TP measurement reads as
    "tunnel wedged", never "TP untested"."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2")
    import jax

    jax.config.update("jax_platforms", "cpu")

    import threading

    import numpy as np

    from k3stpu.models.quant import kv_page_bytes
    from k3stpu.models.transformer import transformer_lm_tiny
    from k3stpu.serve.engine import GenerateEngine

    max_seq, page_size, slots = 64, 8, 4
    num_pages = 1 + slots * max_seq // page_size
    new_tokens = 12
    prompts = [[5, 6, 7], [3, 4, 5, 6, 7, 8, 9, 10],
               list(range(1, 21)), [40, 41]]

    model = transformer_lm_tiny(max_seq_len=max_seq,
                                dtype=jax.numpy.float32)
    params = model.init(jax.random.key(0),
                        np.zeros((1, 1), np.int32))["params"]

    def run_arm(tp):
        engine = GenerateEngine(model, params, slots=slots, seed=0,
                                decode_block=1, page_size=page_size,
                                num_pages=num_pages, tp_shards=tp)
        try:
            if tp > 1:
                # The serving-mesh probe line, in the dryrun_multichip
                # record format: what mesh actually materialized.
                print(f"serve_tp(shards={tp}): "
                      f"mesh={dict(engine.mesh.shape)} "
                      f"devices={len(jax.devices())} "
                      f"backend={jax.default_backend()}", flush=True)
            engine.submit([[1, 2, 3]], max_new_tokens=4)  # compile
            engine.reset_stats()
            results = [None] * len(prompts)

            def go(i):
                results[i] = engine.submit([prompts[i]],
                                           max_new_tokens=new_tokens)

            threads = [threading.Thread(target=go, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if not all(r is not None and len(r[0]) == new_tokens
                       for r in results):
                raise RuntimeError("a request failed or came back short")
            stats = engine.stats()
            if stats["tp_shards"] != tp:
                raise RuntimeError(f"stats report tp_shards="
                                   f"{stats['tp_shards']}, arm ran {tp}")
            mesh_shape = (dict(engine.mesh.shape)
                          if engine.mesh is not None else None)
            return stats, [tuple(r[0]) for r in results], mesh_shape
        finally:
            engine.close()

    mono, out_mono, _ = run_arm(1)
    tp, out_tp, tp_mesh = run_arm(2)
    if out_mono != out_tp:
        raise RuntimeError("tp_shards=2 output diverged from the "
                           "single-chip engine — exactness is broken, "
                           "numbers void")

    # Modeled per-chip KV pool bytes: the shard's slice of every page
    # (kv_heads/tp of the head axis), the quantity that halves each
    # chip's HBM bill and doubles the page budget a slice can hold.
    cfg = model.config
    per_chip_1 = kv_page_bytes(cfg, page_size)
    per_chip_2 = kv_page_bytes(cfg, page_size, tp_shards=2)
    ratio = per_chip_2 / per_chip_1
    if ratio > 0.55:
        raise RuntimeError(f"per-chip KV bytes ratio {ratio:.3f} "
                           f"exceeds the 0.55x gate")
    doc = {
        # Headline: 2-shard per-chip KV page bytes over single-chip.
        # <= 0.55 is the gate; vs_baseline = 0.55/ratio so 1.0 == the
        # bar and bigger is better.
        "metric": "serve_tp_per_chip_kv_bytes_ratio",
        "value": round(ratio, 4),
        "unit": "tp2_kv_page_bytes_over_tp1_kv_page_bytes",
        "vs_baseline": round(0.55 / ratio, 4),
        "backend": "cpu-fallback",
        "detail": {
            "slots": slots, "page_size": page_size,
            "num_pages": num_pages, "max_seq": max_seq,
            "new_tokens_per_request": new_tokens,
            "serving_mesh": tp_mesh,
            "kv_page_bytes_tp1": per_chip_1,
            "kv_page_bytes_tp2": per_chip_2,
            "pool_bytes_per_shard": tp["page_bytes_per_shard"],
            "pool_bytes_mono": mono["page_bytes_per_shard"],
            "tokens_identical": True,
            # Emulated-mesh wall clock — a CPU artifact (2 shards
            # timeshare one host), recorded for trend only; the
            # >= 1.6x tokens/s gate applies on hardware.
            "tp1_tokens_per_s": mono["tokens_per_s"],
            "tp2_tokens_per_s": tp["tokens_per_s"],
            "dispatches": mono["dispatches"],
        },
    }
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _serve_tp_main() -> int:
    """Bounded-subprocess wrapper for --serve-tp (parent never imports
    jax; same wedge-proof discipline as every other arm)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__), "--serve-tp-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="serve_tp")
    skw = {"metric": "serve_tp_per_chip_kv_bytes_ratio",
           "unit": "tp2_kv_page_bytes_over_tp1_kv_page_bytes"}
    if not ok:
        why = (f"tp bench did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("serve_tp", f"{why}; stderr: {err.strip()}", **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def _serve_obs_worker() -> int:
    """Observability overhead microbench (bounded subprocess).

    The obs layer's budget is <5% on decode throughput (ISSUE 2): run
    the SAME CPU decode microbench as --serve-paged's drive (16
    concurrent requests, tiny model) with tracing/histograms OFF
    (engine obs=None — the exact pre-obs code path) and ON, and compare
    busy-time-normalized tokens/s. Best-of-3 per arm: the quantity is a
    ceiling on per-dispatch bookkeeping cost, and min-noise beats
    mean-of-noise for that."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import threading

    import numpy as np

    from k3stpu.models.transformer import transformer_lm_tiny
    from k3stpu.obs import ServeObs
    from k3stpu.serve.engine import GenerateEngine

    max_seq, slots = 128, 8
    n_reqs, prompt_len, new_tokens = 16, 8, 24

    model = transformer_lm_tiny(max_seq_len=max_seq)
    params = model.init(jax.random.key(0),
                        np.zeros((1, 1), np.int32))["params"]

    def drive(engine):
        engine.submit([[1, 2, 3]], max_new_tokens=4)  # warm compiles
        engine.reset_stats()
        results = [None] * n_reqs

        def go(i):
            prompt = [((i * 7 + j) % 97) + 1 for j in range(prompt_len)]
            results[i] = engine.submit([prompt],
                                       max_new_tokens=new_tokens)

        threads = [threading.Thread(target=go, args=(i,))
                   for i in range(n_reqs)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if not all(r is not None and len(r[0]) == new_tokens
                   for r in results):
            raise RuntimeError("a request failed or came back short")
        return engine.stats()

    def best_tps(obs) -> float:
        engine = GenerateEngine(model, params, slots=slots, seed=0,
                                obs=obs)
        try:
            best = 0.0
            for _ in range(3):
                s = drive(engine)
                best = max(best, s["tokens_per_s"] or 0.0)
            return best
        finally:
            engine.close()

    off = best_tps(None)
    on = best_tps(ServeObs())
    overhead = (1.0 - on / off) * 100.0 if off else 0.0
    doc = {
        # Headline: decode tokens/s lost to tracing+histograms, in
        # percent. The bar is 5%; vs_baseline = value/5 so <=1.0 means
        # within budget (negative just means run-to-run noise exceeded
        # the true overhead).
        "metric": "serve_obs_overhead_pct",
        "value": round(overhead, 2),
        "unit": "pct_decode_tokens_per_s",
        "vs_baseline": round(overhead / 5.0, 4),
        "detail": {
            "budget_pct": 5.0,
            "tokens_per_s_obs_off": off,
            "tokens_per_s_obs_on": on,
            "runs_per_arm": 3,
            "requests_per_run": n_reqs,
            "new_tokens_per_request": new_tokens,
        },
    }
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _serve_obs_main() -> int:
    """Bounded-subprocess wrapper for --serve-obs (same wedge-proof
    discipline as the other serve benches)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__), "--serve-obs-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="serve_obs")
    skw = {"metric": "serve_obs_overhead_pct",
           "unit": "pct_decode_tokens_per_s"}
    if not ok:
        why = (f"obs bench did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("serve_obs", f"{why}; stderr: {err.strip()}", **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def _serve_tier_worker() -> int:
    """Host KV page tier gate (bounded subprocess, CPU tiny model).

    Arm A (the headline): a 512-token session's warm second turn —
    tier swap-in of the parked chain + suffix-only prefill — timed
    against the same turn on a tierless engine that must re-prefill the
    whole grown prompt. Gate: warm <= cold/3. Best-of-3 with distinct
    prompts; both arms pay identical submit/loop overheads, so the
    ratio isolates restore-vs-reprefill.

    Arm B (in the detail): at one fixed page pool, how many sessions
    remain warm-restorable — chain still pinned in the prompt cache OR
    parked in the host tier — after S sessions run a turn each. The
    no-tier engine keeps chains only while HBM pages last; the tier
    engine parks every released chain in host RAM. Gate: >= 8x."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from k3stpu.models.transformer import transformer_lm_tiny
    from k3stpu.serve.engine import GenerateEngine
    from k3stpu.serve.tiering import HostPageStore

    # max_seq 2048: the grown turn-2 prompt (512 + reply + 2) buckets
    # to a 1024-wide prefill, which must still fit under the cache.
    max_seq, page, slots = 2048, 64, 2
    prompt_len, reply = 512, 8
    pool_pages = 41  # sink + 40 usable: ~3 pinned chains + working room

    model = transformer_lm_tiny(max_seq_len=max_seq)
    params = model.init(jax.random.key(0),
                        np.zeros((1, 1), np.int32))["params"]

    def prompt_for(i: int) -> "list[int]":
        rng = np.random.default_rng(100 + i)
        return rng.integers(1, 1000, size=(prompt_len,)).tolist()

    def make_engine(tier):
        return GenerateEngine(model, params, slots=slots, seed=0,
                              page_size=page, num_pages=pool_pages,
                              prompt_cache=64, tier=tier)

    def turn(engine, p, sid, n_new):
        t0 = time.perf_counter()
        out = engine.submit([p], max_new_tokens=n_new, session=sid)
        return time.perf_counter() - t0, out[0]

    # -- Arm A: warm restore vs cold re-prefill ------------------------
    tier = HostPageStore(256 << 20)
    eng_t, eng_c = make_engine(tier), make_engine(None)
    warm_s: "list[float]" = []
    cold_s: "list[float]" = []
    try:
        # Warm every program the measured turns hit (turn-1 prefill
        # bucket, suffix bucket, swap gather/scatter) on BOTH engines.
        for eng, rel in ((eng_t, True), (eng_c, False)):
            _, rep = turn(eng, prompt_for(99), "w", reply)
            if rel:
                eng.release_session("w")
            turn(eng, prompt_for(99) + rep + [1, 2], "w", 1)
            if rel:
                eng.release_session("w")
        for i in range(3):
            p = prompt_for(i)
            _, rep = turn(eng_t, p, f"s{i}", reply)
            eng_t.release_session(f"s{i}")  # chain parks on host
            p2 = p + rep + [3, 4]
            dt, _ = turn(eng_t, p2, f"s{i}", 1)  # swap-in + suffix
            warm_s.append(dt)
            eng_t.release_session(f"s{i}")
            dt, _ = turn(eng_c, p2, None, 1)  # full re-prefill
            cold_s.append(dt)
    finally:
        eng_t.close()
        eng_c.close()

    # -- Arm B: restorable sessions at a fixed pool --------------------
    n_sessions = 40
    tier_b = HostPageStore(256 << 20)
    caps = {}
    for label, t_store, rel in (("tier", tier_b, True),
                                ("no_tier", None, False)):
        eng = make_engine(t_store)
        try:
            for i in range(n_sessions):
                eng.submit([prompt_for(200 + i)], max_new_tokens=reply,
                           session=f"b{i}")
                if rel:
                    eng.release_session(f"b{i}")
        finally:
            eng.close()  # quiesce the loop before reading its ledgers
        caps[label] = sum(
            1 for key in eng._sessions.values()
            if key in eng._pcache
            or (t_store is not None and t_store.contains(key)))

    ratio = min(warm_s) / max(min(cold_s), 1e-9)
    capacity_x = caps["tier"] / max(caps["no_tier"], 1)
    doc = {
        # Headline: warm-turn restore time over cold re-prefill time.
        # The bar is 1/3; vs_baseline = ratio*3 so <=1.0 passes.
        "metric": "serve_tier_warm_restore_ratio",
        "value": round(ratio, 4),
        "unit": "warm_turn_s_over_cold_reprefill_s",
        "vs_baseline": round(ratio * 3.0, 4),
        "detail": {
            "gate_warm_over_cold_max": round(1.0 / 3.0, 4),
            "warm_gate_passed": ratio <= 1.0 / 3.0,
            "warm_turn_s": round(min(warm_s), 6),
            "cold_reprefill_s": round(min(cold_s), 6),
            "prompt_tokens": prompt_len,
            "runs_per_arm": 3,
            "session_capacity_x": round(capacity_x, 2),
            "gate_session_capacity_min_x": 8.0,
            "capacity_gate_passed": capacity_x >= 8.0,
            "sessions_run_per_arm": n_sessions,
            "sessions_tier_restorable": caps["tier"],
            "sessions_no_tier_restorable": caps["no_tier"],
            "fixed_pool_pages": pool_pages - 1,
            "page_size": page,
        },
    }
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _serve_tier_main() -> int:
    """Bounded-subprocess wrapper for --serve-tier (same wedge-proof
    discipline as the other serve benches)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__),
         "--serve-tier-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="serve_tier")
    skw = {"metric": "serve_tier_warm_restore_ratio",
           "unit": "warm_turn_s_over_cold_reprefill_s"}
    if not ok:
        why = (f"tier bench did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("serve_tier", f"{why}; stderr: {err.strip()}", **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def _serve_router_worker() -> int:
    """Router-tier gate (bounded subprocess, CPU tiny model, loopback).

    Two REAL InferenceServer replicas (continuous batching + paged KV +
    prompt cache + host tier, distinct ``instance`` names) serve behind
    two router arms over the same fleet: ``--policy affinity`` (sticky
    sessions + prefix hash) vs ``--policy random`` (the deterministic
    round-robin baseline). Each arm drives S sessions x T turns
    sequentially through the router's real HTTP hop, releasing the
    session between turns (the drain/park path), so every warm turn
    either lands where its parked chain lives (tier swap-in / prompt
    cache hit) or pays a cold re-prefill on the wrong replica.

    Gates: sticky warm-turn hit rate >= 0.90, random <= 0.60, and the
    router's own proxy-overhead histogram p50 <= 5% of the client-side
    request p50 — the tier must buy cache locality without becoming a
    latency tax."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    import numpy as np

    from k3stpu.router.router import Router, make_router_app
    from k3stpu.serve.server import InferenceServer, make_app

    prompt_len, reply = 48, 4
    n_sessions, n_turns = 6, 3
    warm_turns = n_sessions * (n_turns - 1)

    def prompt_for(seed: int) -> "list[int]":
        rng = np.random.default_rng(seed)
        return rng.integers(1, 1000, size=(prompt_len,)).tolist()

    def post(url: str, path: str, body: dict) -> dict:
        req = urllib.request.Request(
            url + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read().decode())

    servers: list = []
    httpds: list = []
    urls: "list[str]" = []

    def run_arm(policy: str, seed_base: int):
        """Returns (warm-turn hit rate, request p50 s, proxy overhead
        p50 s) for one policy over the shared fleet."""
        router = Router(urls, policy=policy, prefix_tokens=16,
                        health_period_s=0.5,
                        instance=f"bench-router-{policy}")
        rhttpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                     make_router_app(router))
        threading.Thread(target=rhttpd.serve_forever, daemon=True).start()
        rurl = f"http://127.0.0.1:{rhttpd.server_address[1]}"

        # A warm turn is a HIT when any warm-path counter moved on any
        # replica while it ran (a single restore can tick both a tier
        # swap-in and a prompt-cache hit — count turns, not counters).
        def warm_marks() -> int:
            return sum(srv._engine.stats()[k] for srv in servers
                       for k in ("pcache_hits", "pcache_prefix_hits",
                                 "tier_swap_ins"))

        lat: "list[float]" = []
        hits = 0
        try:
            for i in range(n_sessions):
                sid = f"{policy}-s{i}"
                toks = prompt_for(seed_base + i)
                for turn in range(n_turns):
                    before = warm_marks()
                    t0 = time.perf_counter()
                    out = post(rurl, "/v1/generate",
                               {"prompt_tokens": [toks],
                                "max_new_tokens": reply, "session": sid})
                    lat.append(time.perf_counter() - t0)
                    if turn > 0 and warm_marks() > before:
                        hits += 1
                    toks = toks + out["tokens"][0] + [11, 13]
                    # Park the chain between turns — the scale-down /
                    # migration path the pin table is built around.
                    post(rurl, "/v1/session/release", {"session": sid})
            lat.sort()
            return (hits / warm_turns, lat[len(lat) // 2],
                    router._obs.proxy_overhead.quantile(0.5) or 0.0)
        finally:
            rhttpd.shutdown()
            router.close()

    try:
        for name in ("bench-rep-a", "bench-rep-b"):
            srv = InferenceServer(
                model_name="transformer-tiny", seq_len=256,
                batch_window_ms=0.0, continuous_batching=True,
                decode_block=4, prompt_cache=32, kv_page_size=16,
                kv_pages=128, tier_host_mb=64, shard_devices=None,
                instance=name)
            servers.append(srv)
            httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(srv))
            httpds.append(httpd)
            threading.Thread(target=httpd.serve_forever,
                             daemon=True).start()
            urls.append(f"http://127.0.0.1:{httpd.server_address[1]}")

        # Warm every jitted program the measured turns hit (turn-width
        # prefills, swap gather/scatter, decode) on BOTH replicas, then
        # zero the counters so compiles don't poison either arm.
        for srv in servers:
            toks = prompt_for(999)
            for _ in range(n_turns):
                rep = srv.generate_tokens([toks], max_new_tokens=reply,
                                          session="warm")[0]
                srv.release_session("warm")
                toks = toks + rep + [7]
            srv.reset_stats()

        sticky_rate, req_p50_s, overhead_p50_s = run_arm("affinity", 300)
        random_rate, _, _ = run_arm("random", 400)
    finally:
        for httpd in httpds:
            httpd.shutdown()
        for srv in servers:
            srv.close()

    overhead_frac = overhead_p50_s / max(req_p50_s, 1e-9)
    doc = {
        # Headline: fraction of warm session turns the sticky router
        # landed on a warm cache. Target 0.90 => vs_baseline >= 1.0.
        "metric": "serve_router_sticky_hit_rate",
        "value": round(sticky_rate, 4),
        "unit": "warm_turn_cache_hit_fraction",
        "vs_baseline": round(sticky_rate / 0.90, 4),
        "detail": {
            "gate_sticky_min": 0.90,
            "sticky_gate_passed": sticky_rate >= 0.90,
            "random_hit_rate": round(random_rate, 4),
            "gate_random_max": 0.60,
            "random_gate_passed": random_rate <= 0.60,
            "proxy_overhead_p50_s": round(overhead_p50_s, 6),
            "request_p50_s": round(req_p50_s, 6),
            "proxy_overhead_frac": round(overhead_frac, 4),
            "gate_overhead_frac_max": 0.05,
            "overhead_gate_passed": overhead_frac <= 0.05,
            "sessions": n_sessions,
            "turns_per_session": n_turns,
            "warm_turns": warm_turns,
            "replicas": 2,
            "prompt_tokens": prompt_len,
        },
    }
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _serve_router_main() -> int:
    """Bounded-subprocess wrapper for --serve-router (same wedge-proof
    discipline as the other serve benches)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__),
         "--serve-router-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="serve_router")
    skw = {"metric": "serve_router_sticky_hit_rate",
           "unit": "warm_turn_cache_hit_fraction"}
    if not ok:
        why = (f"router bench did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("serve_router", f"{why}; stderr: {err.strip()}", **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def _serve_canary_worker() -> int:
    """Correctness-canary gate (bounded subprocess, CPU tiny model,
    loopback HTTP).

    Paired arms over ONE live 2-replica routed fleet: threaded loadgen
    through the router with the canary OFF, then the identical loadgen
    with the canary probing at 1 Hz (all four paths: router, per-
    replica, two-turn session, SSE stream). Best-of-N throughput per
    arm (the --serve-obs noise idiom); the watchdog must cost <= 5% of
    loadgen throughput — its probes ride the same continuous batches
    as organic traffic, so the marginal cost is a few extra rows, not
    extra dispatches.

    Then the detection leg, the reason the subsystem exists: arm
    ``gen_corrupt`` on one replica (every output token perturbed,
    request still completes with nominal status/latency) and the
    canary must flag the token mismatch within TWO probe rounds while
    the corrupt replica's own /healthz stays green."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    import numpy as np

    from k3stpu.canary import Canary, CanaryObs
    from k3stpu.chaos import FaultInjector
    from k3stpu.router.router import Router, make_router_app
    from k3stpu.serve.server import InferenceServer, make_app

    prompt_len, reply = 48, 8
    n_threads, reqs_per_thread, runs_per_arm = 3, 16, 3
    probe_interval_s = 1.0

    def prompt_for(seed: int) -> "list[int]":
        rng = np.random.default_rng(seed)
        return rng.integers(1, 1000, size=(prompt_len,)).tolist()

    servers: list = []
    httpds: list = []
    urls: "list[str]" = []
    inj = FaultInjector()  # armed only for the detection leg
    try:
        for name, chaos in (("bench-can-a", None), ("bench-can-b", inj)):
            # prompt_cache=0 on purpose: the arms replay the SAME
            # prompts (paired), so any cache would hand the second arm
            # free prefills and bias the overhead negative. It also
            # charges the canary full prefill per probe — the honest
            # worst case for the 5% budget.
            srv = InferenceServer(
                model_name="transformer-tiny", seq_len=256,
                batch_window_ms=0.0, continuous_batching=True,
                decode_block=4, prompt_cache=0, kv_page_size=16,
                kv_pages=128, shard_devices=None, instance=name,
                chaos=chaos)
            servers.append(srv)
            httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(srv))
            httpds.append(httpd)
            threading.Thread(target=httpd.serve_forever,
                             daemon=True).start()
            urls.append(f"http://127.0.0.1:{httpd.server_address[1]}")
        router = Router(urls, health_period_s=5.0,
                        instance="bench-canary-router")
        rhttpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                     make_router_app(router))
        threading.Thread(target=rhttpd.serve_forever, daemon=True).start()
        rurl = f"http://127.0.0.1:{rhttpd.server_address[1]}"

        # Warm every jitted program both arms touch: the loadgen
        # prompt shape on each replica, then one full probe round
        # (probe-prompt buckets, session park/restore, SSE path).
        for srv in servers:
            srv.generate_tokens([prompt_for(999)], max_new_tokens=reply)
        can = Canary(rurl, prompts=((1, 2, 3, 4),), max_new_tokens=4,
                     timeout_s=60.0, obs=CanaryObs(instance="bench"))
        can.record_golden()
        if not all(r.verdict == "ok" for r in can.probe_round()):
            raise RuntimeError("clean probe round failed — fleet broken")

        def post(body: dict) -> dict:
            req = urllib.request.Request(
                rurl + "/v1/generate", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read().decode())

        def loadgen_once(seed_base: int) -> float:
            """One timed loadgen run; returns organic requests/s."""
            def go(tid: int):
                for j in range(reqs_per_thread):
                    out = post({"prompt_tokens":
                                [prompt_for(seed_base + tid * 100 + j)],
                                "max_new_tokens": reply})
                    assert len(out["tokens"][0]) == reply
            threads = [threading.Thread(target=go, args=(i,))
                       for i in range(n_threads)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return (n_threads * reqs_per_thread) / (time.perf_counter()
                                                    - t0)

        def arm(with_canary: bool, seed_base: int) -> float:
            stop = threading.Event()
            prober = None
            if with_canary:
                def probe_loop():
                    # Fire immediately, then on the interval — a short
                    # run must still overlap at least one probe round
                    # or the on-arm measures nothing.
                    while True:
                        can.probe_round()
                        if stop.wait(probe_interval_s):
                            return
                prober = threading.Thread(target=probe_loop, daemon=True)
                prober.start()
            try:
                return max(loadgen_once(seed_base + r * 1000)
                           for r in range(runs_per_arm))
            finally:
                stop.set()
                if prober is not None:
                    prober.join()

        loadgen_once(5_000)  # unmeasured warm pass: caches, threads
        rps_off = arm(False, 10_000)
        rps_on = arm(True, 10_000)  # same prompts: paired arms
        overhead_pct = ((1.0 - rps_on / rps_off) * 100.0
                        if rps_off else 0.0)

        # Detection leg: silent corruption on replica B, flagged fast.
        inj.arm("gen_corrupt", times=100_000)
        rounds_to_flag = 0
        for i in range(2):
            if any(r.verdict == "mismatch" for r in can.probe_round()):
                rounds_to_flag = i + 1
                break
        with urllib.request.urlopen(urls[1] + "/healthz",
                                    timeout=10) as r:
            bad_healthz_ok = bool(json.loads(r.read()).get("ok"))
    finally:
        try:
            rhttpd.shutdown()
            router.close()
        except NameError:
            pass
        for httpd in httpds:
            httpd.shutdown()
        for srv in servers:
            srv.close()

    doc = {
        # Headline: loadgen throughput lost to the 1 Hz prober, in
        # percent. The bar is 5%; vs_baseline = value/5 so <=1.0 means
        # within budget (negative = run-to-run noise exceeded the true
        # cost). Detection gate rides in detail.
        "metric": "serve_canary_overhead_pct",
        "value": round(overhead_pct, 2),
        "unit": "pct_loadgen_requests_per_s",
        "vs_baseline": round(overhead_pct / 5.0, 4),
        "detail": {
            "budget_pct": 5.0,
            "overhead_gate_passed": overhead_pct <= 5.0,
            "requests_per_s_canary_off": round(rps_off, 3),
            "requests_per_s_canary_on": round(rps_on, 3),
            "probe_interval_s": probe_interval_s,
            "runs_per_arm": runs_per_arm,
            "loadgen_threads": n_threads,
            "requests_per_thread": reqs_per_thread,
            "rounds_to_flag_corruption": rounds_to_flag,
            "gate_detect_within_rounds": 2,
            "detection_gate_passed": 1 <= rounds_to_flag <= 2,
            "corrupt_replica_healthz_ok": bad_healthz_ok,
            "replicas": 2,
            "prompt_tokens": prompt_len,
        },
    }
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _serve_canary_main() -> int:
    """Bounded-subprocess wrapper for --serve-canary (same wedge-proof
    discipline as the other serve benches)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__),
         "--serve-canary-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="serve_canary")
    skw = {"metric": "serve_canary_overhead_pct",
           "unit": "pct_loadgen_requests_per_s"}
    if not ok:
        why = (f"canary bench did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("serve_canary", f"{why}; stderr: {err.strip()}", **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def _obs_pipeline_worker() -> int:
    """Embedded metrics pipeline gate (bounded subprocess, CPU tiny
    model, loopback HTTP).

    Paired arms over ONE live 2-replica routed fleet: threaded loadgen
    through the router with the collector OFF, then the identical
    loadgen with the collector scraping every fleet /metrics endpoint
    at 1 Hz AND running the full shipped rule set (the chart's qos
    render — 12 rules, loaded from the golden by the collector's own
    zero-dep reader) on every round. Best-of-N throughput per arm; the
    pipeline must cost <= 5% of loadgen throughput — scrapes are reads
    off the replicas' telemetry locks plus pure-Python rule evals, so
    the marginal cost is render time, not serving time."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    import numpy as np

    from k3stpu.obs.collector import Collector
    from k3stpu.obs.promql import load_rule_groups
    from k3stpu.router.router import Router, make_router_app
    from k3stpu.serve.server import InferenceServer, make_app

    prompt_len, reply = 48, 8
    n_threads, reqs_per_thread, runs_per_arm = 3, 16, 3
    scrape_interval_s = 1.0

    rules_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "tests", "golden", "chart", "qos.yaml")
    with open(rules_path) as f:
        groups = load_rule_groups(f.read())

    def prompt_for(seed: int) -> "list[int]":
        rng = np.random.default_rng(seed)
        return rng.integers(1, 1000, size=(prompt_len,)).tolist()

    servers: list = []
    httpds: list = []
    urls: "list[str]" = []
    try:
        for name in ("bench-obs-a", "bench-obs-b"):
            srv = InferenceServer(
                model_name="transformer-tiny", seq_len=256,
                batch_window_ms=0.0, continuous_batching=True,
                decode_block=4, prompt_cache=0, kv_page_size=16,
                kv_pages=128, shard_devices=None, instance=name)
            servers.append(srv)
            httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(srv))
            httpds.append(httpd)
            threading.Thread(target=httpd.serve_forever,
                             daemon=True).start()
            urls.append(f"http://127.0.0.1:{httpd.server_address[1]}")
        router = Router(urls, health_period_s=5.0,
                        instance="bench-obs-router")
        rhttpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                     make_router_app(router))
        threading.Thread(target=rhttpd.serve_forever, daemon=True).start()
        rurl = f"http://127.0.0.1:{rhttpd.server_address[1]}"

        col = Collector(router_url=rurl, groups=groups)
        n_targets = len(col.discover_targets())

        for srv in servers:
            srv.generate_tokens([prompt_for(999)], max_new_tokens=reply)
        col.step(time.time())  # warm the scrape + eval path

        def post(body: dict) -> dict:
            req = urllib.request.Request(
                rurl + "/v1/generate", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read().decode())

        def loadgen_once(seed_base: int) -> float:
            """One timed loadgen run; returns organic requests/s."""
            def go(tid: int):
                for j in range(reqs_per_thread):
                    out = post({"prompt_tokens":
                                [prompt_for(seed_base + tid * 100 + j)],
                                "max_new_tokens": reply})
                    assert len(out["tokens"][0]) == reply
            threads = [threading.Thread(target=go, args=(i,))
                       for i in range(n_threads)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return (n_threads * reqs_per_thread) / (time.perf_counter()
                                                    - t0)

        def arm(with_pipeline: bool, seed_base: int) -> float:
            stop = threading.Event()
            scraper = None
            if with_pipeline:
                def scrape_loop():
                    # Fire immediately, then on the interval — a short
                    # run must still overlap at least one full scrape +
                    # rule-eval round or the on-arm measures nothing.
                    while True:
                        col.step(time.time())
                        if stop.wait(scrape_interval_s):
                            return
                scraper = threading.Thread(target=scrape_loop,
                                           daemon=True)
                scraper.start()
            try:
                return max(loadgen_once(seed_base + r * 1000)
                           for r in range(runs_per_arm))
            finally:
                stop.set()
                if scraper is not None:
                    scraper.join()

        loadgen_once(5_000)  # unmeasured warm pass: caches, threads
        rps_off = arm(False, 10_000)
        rps_on = arm(True, 10_000)  # same prompts: paired arms
        overhead_pct = ((1.0 - rps_on / rps_off) * 100.0
                        if rps_off else 0.0)
        rounds = int(col.obs.scrapes.value) // max(1, n_targets)
    finally:
        try:
            rhttpd.shutdown()
            router.close()
        except NameError:
            pass
        for httpd in httpds:
            httpd.shutdown()
        for srv in servers:
            srv.close()

    doc = {
        # Headline: loadgen throughput lost to the 1 Hz scrape + rule
        # pipeline, in percent. The bar is 5%; vs_baseline = value/5 so
        # <=1.0 means within budget (negative = run-to-run noise
        # exceeded the true cost).
        "metric": "obs_pipeline_overhead_pct",
        "value": round(overhead_pct, 2),
        "unit": "pct_loadgen_requests_per_s",
        "vs_baseline": round(overhead_pct / 5.0, 4),
        "detail": {
            "budget_pct": 5.0,
            "overhead_gate_passed": overhead_pct <= 5.0,
            "requests_per_s_pipeline_off": round(rps_off, 3),
            "requests_per_s_pipeline_on": round(rps_on, 3),
            "scrape_interval_s": scrape_interval_s,
            "scrape_targets": n_targets,
            "scrape_rounds": rounds,
            "rules_evaluated": len(col.engine.rules),
            "series_in_store": col.store.series_count(),
            "samples_ingested": int(col.obs.samples_ingested.value),
            "alerts_firing": len(col.engine.firing()),
            "runs_per_arm": runs_per_arm,
            "loadgen_threads": n_threads,
            "requests_per_thread": reqs_per_thread,
            "replicas": 2,
            "prompt_tokens": prompt_len,
        },
    }
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _obs_pipeline_main() -> int:
    """Bounded-subprocess wrapper for --obs-pipeline (same wedge-proof
    discipline as the other serve benches)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__),
         "--obs-pipeline-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="obs_pipeline")
    skw = {"metric": "obs_pipeline_overhead_pct",
           "unit": "pct_loadgen_requests_per_s"}
    if not ok:
        why = (f"pipeline bench did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("obs_pipeline", f"{why}; stderr: {err.strip()}",
                     **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def _serve_qos_worker() -> int:
    """SLO-aware QoS gate (bounded subprocess, CPU tiny model,
    loopback HTTP).

    ONE qos+tier replica at 2x overload: concurrency is twice the
    engine's slot count, split evenly between interactive (short,
    streamed, TTFT timed at the first SSE token frame) and batch
    (long, non-streaming) clients. The two halves of the acceptance
    bar (docs/QOS.md):

      * interactive p99 TTFT stays within the configured class SLO —
        the class-weighted admission walk plus loss-free preemption
        must keep the latency class ahead of the backlog;
      * batch degrades GRACEFULLY: every batch request completes.
        Predictive-admission 503s are retried per their Retry-After,
        so shed means delayed, never lost.

    The preemption/rejection counters ride in detail straight off the
    replica's /metrics so the gate also proves the mechanism (not just
    the outcome) engaged under overload."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import re
    import threading
    import urllib.error
    import urllib.request
    from http.server import ThreadingHTTPServer

    import numpy as np

    from k3stpu.serve.server import InferenceServer, make_app

    slots = 2
    # 4 concurrent clients over 2 slots = 2x overload. Three of the
    # four are batch so the batch class genuinely saturates the slots
    # (one batch always pending): every interactive arrival faces
    # fully-occupied hardware and must go through the preemption path,
    # not get lucky with an idle slot.
    inter_threads, batch_threads = 1, 3
    inter_reqs, batch_reqs = 24, 4      # per thread
    inter_len, batch_len = 32, 64
    # Batch decodes LONG (96 tokens) so slots stay occupied when the
    # interactive class arrives — the regime where the preemption and
    # class-weighted-admission machinery must carry the SLO, not idle
    # slot luck.
    inter_reply, batch_reply = 4, 96
    slo_ms = 10_000.0  # CPU-scaled interactive TTFT budget
    max_attempts = 50  # per batch request; bounds a pathological shed

    def prompt_for(seed: int, n: int) -> "list[int]":
        rng = np.random.default_rng(seed)
        return rng.integers(1, 1000, size=(n,)).tolist()

    srv = InferenceServer(
        model_name="transformer-tiny", seq_len=512,
        batch_window_ms=0.0, continuous_batching=True,
        engine_slots=slots, decode_block=4, prompt_cache=8,
        kv_page_size=16, kv_pages=256, shard_devices=None,
        instance="bench-qos", tier_host_mb=64, qos=True,
        interactive_ttft_slo_ms=slo_ms)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(srv))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"

    lock = threading.Lock()
    stats = {"ttfts": [], "inter_shed": 0, "batch_retries": 0,
             "batch_done": 0}

    def _post(body: dict, timeout: float = 120.0):
        req = urllib.request.Request(
            url + "/v1/generate", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        return urllib.request.urlopen(req, timeout=timeout)

    def interactive_once(seed: int) -> None:
        """One streamed interactive request; records TTFT at the first
        SSE token frame. A shed (pre-header 503 or in-stream error
        frame) is counted and retried — the TTFT sample then times the
        admitted attempt, which is what the SLO governs."""
        body = {"prompt_tokens": [prompt_for(seed, inter_len)],
                "max_new_tokens": inter_reply, "temperature": 0.0,
                "priority": "interactive", "stream": True}
        for _ in range(max_attempts):
            t0 = time.perf_counter()
            try:
                with _post(body) as r:
                    ttft = None
                    for raw in r:
                        line = raw.decode()
                        if not line.startswith("data: "):
                            continue
                        doc = json.loads(line[len("data: "):])
                        if doc.get("error"):
                            raise urllib.error.HTTPError(
                                url, 503, doc["error"], {}, None)
                        if ttft is None and doc.get("rows"):
                            ttft = time.perf_counter() - t0
                        if doc.get("done"):
                            assert len(doc["tokens"][0]) == inter_reply
                            with lock:
                                stats["ttfts"].append(ttft)
                            return
            except urllib.error.HTTPError as e:
                if e.code != 503:
                    raise
                with lock:
                    stats["inter_shed"] += 1
                time.sleep(min(float(e.headers.get("Retry-After") or 1),
                               5.0))
        raise RuntimeError(f"interactive request {seed} never admitted")

    def batch_once(seed: int) -> None:
        """One batch request, retried per Retry-After until it lands:
        the no-request-lost half of the gate."""
        body = {"prompt_tokens": [prompt_for(seed, batch_len)],
                "max_new_tokens": batch_reply, "temperature": 0.0,
                "priority": "batch"}
        for _ in range(max_attempts):
            try:
                with _post(body) as r:
                    out = json.loads(r.read().decode())
                assert len(out["tokens"][0]) == batch_reply
                with lock:
                    stats["batch_done"] += 1
                return
            except urllib.error.HTTPError as e:
                if e.code != 503:
                    raise
                with lock:
                    stats["batch_retries"] += 1
                time.sleep(min(float(e.headers.get("Retry-After") or 1),
                               5.0))
        raise RuntimeError(f"batch request {seed} lost after retries")

    try:
        # Warm every jitted program both classes touch (prefill shapes
        # + decode blocks) so the timed window measures scheduling, not
        # XLA compiles.
        srv.generate_tokens([prompt_for(999, inter_len)],
                            max_new_tokens=inter_reply)
        srv.generate_tokens([prompt_for(998, batch_len)],
                            max_new_tokens=batch_reply)
        # A preempted batch resumes with prompt+collected tokens, so
        # its re-prefill lands in WIDER pow2 buckets than any fresh
        # request — warm them too or the first preemption charges an
        # XLA compile to whichever interactive request queued behind it.
        for n in (100, 180):
            srv.generate_tokens([prompt_for(900 + n, n)],
                                max_new_tokens=inter_reply)

        errs: list = []

        def run(fn, tid: int, n: int, base: int) -> None:
            try:
                for j in range(n):
                    fn(base + tid * 1000 + j)
            except BaseException as e:  # noqa: BLE001 — join + reraise
                errs.append(e)

        threads = (
            [threading.Thread(target=run,
                              args=(interactive_once, i, inter_reqs,
                                    10_000))
             for i in range(inter_threads)] +
            [threading.Thread(target=run,
                              args=(batch_once, i, batch_reqs, 20_000))
             for i in range(batch_threads)])
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]

        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            metrics = r.read().decode()
    finally:
        httpd.shutdown()
        srv.close()

    def counter(pat: str) -> int:
        m = re.search(pat, metrics)
        return int(m.group(1)) if m else 0

    ttfts = sorted(stats["ttfts"])
    p99_ms = ttfts[max(0, int(0.99 * (len(ttfts) - 1)))] * 1000.0
    batch_submitted = batch_threads * batch_reqs
    doc = {
        # Headline: interactive p99 TTFT under 2x overload, in ms.
        # vs_baseline = p99/SLO so <=1.0 passes; the no-batch-lost
        # gate rides in detail.
        "metric": "serve_qos_interactive_p99_ttft_ms",
        "value": round(p99_ms, 1),
        "unit": "ms",
        "vs_baseline": round(p99_ms / slo_ms, 4),
        "detail": {
            "interactive_ttft_slo_ms": slo_ms,
            "ttft_gate_passed": p99_ms <= slo_ms,
            "interactive_requests": len(ttfts),
            "interactive_shed_503": stats["inter_shed"],
            "batch_submitted": batch_submitted,
            "batch_completed": stats["batch_done"],
            "batch_lost": batch_submitted - stats["batch_done"],
            "batch_retries_503": stats["batch_retries"],
            "no_batch_lost_gate_passed":
                stats["batch_done"] == batch_submitted,
            "preemptions": counter(
                r"k3stpu_serve_preemptions_total (\d+)"),
            "admission_rejected_interactive": counter(
                r'k3stpu_serve_admission_rejected_total'
                r'\{class="interactive"\} (\d+)'),
            "admission_rejected_batch": counter(
                r'k3stpu_serve_admission_rejected_total'
                r'\{class="batch"\} (\d+)'),
            "engine_slots": slots,
            "concurrency": inter_threads + batch_threads,
            "overload_factor": (inter_threads + batch_threads) / slots,
        },
    }
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _serve_qos_main() -> int:
    """Bounded-subprocess wrapper for --serve-qos (same wedge-proof
    discipline as the other serve benches)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__),
         "--serve-qos-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="serve_qos")
    skw = {"metric": "serve_qos_interactive_p99_ttft_ms", "unit": "ms"}
    if not ok:
        why = (f"qos bench did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("serve_qos", f"{why}; stderr: {err.strip()}", **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def _serve_disagg_worker() -> int:
    """Disaggregated prefill/decode gate (bounded subprocess, CPU tiny
    model, loopback HTTP).

    Arm A (the headline): the short class's p99 TPOT under mixed
    traffic. The same loadgen mix (short:long=9:1, streaming) drives
    two fleets: a monolithic replica whose continuous-batch loop runs
    every long prompt's 512-wide prefill between its own decode steps,
    and a prefill+decode pair where the decode replica imports each
    prompt's KV chain from its prefill peer, so the decode loop only
    ever decodes. The monolithic arm runs without the prompt cache —
    loadgen replays one deterministic prompt per class, and a pcache
    hit on a replayed prompt would model traffic that never re-prefills
    (real mixed traffic has distinct long prompts). Gate: disagg short
    p99 TPOT <= 0.5x monolithic.

    Arm B (in the detail): the handoff must cost less than what it
    replaces — export_chain + import_chain wall time <= 1/3 the cold
    prefill it saves at a 512-token prompt (in-process engines,
    max_seq 2048 / page 64, best-of-5 with distinct prompts)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    import numpy as np

    from k3stpu.models.transformer import transformer_lm_tiny
    from k3stpu.serve.engine import GenerateEngine
    from k3stpu.serve.loadgen import _gen_prompt, run_mixed
    from k3stpu.serve.server import InferenceServer, make_app

    short_len, long_len, reply = 48, 512, 8
    mix_long_len = 1024  # arm A's interference prompts: 2 pcache-miss
    bench_s, n_clients = 6.0, 6  # 6 @ 2:1 -> 4 short + 2 long clients

    # -- Arm B first (in-process, no HTTP): transfer vs cold prefill ---
    max_seq, page = 2048, 64
    model = transformer_lm_tiny(max_seq_len=max_seq)
    params = model.init(jax.random.key(0),
                        np.zeros((1, 1), np.int32))["params"]

    def prompt_for(i: int) -> "list[int]":
        rng = np.random.default_rng(500 + i)
        return rng.integers(1, 1000, size=(long_len,)).tolist()

    def make_engine():
        return GenerateEngine(model, params, slots=2, seed=0,
                              page_size=page, num_pages=41,
                              prompt_cache=64)

    e_src, e_dst, e_cold = make_engine(), make_engine(), make_engine()
    transfer_s: "list[float]" = []
    warm_sub_s: "list[float]" = []
    cold_sub_s: "list[float]" = []
    try:
        # Warm every jitted program the measured rounds hit (512-wide
        # prefill on both sides, export gather, import scatter, the
        # exact-hit decode step) before timing anything.
        wp = prompt_for(99)
        e_dst.import_chain(e_src.export_chain(wp))
        e_dst.submit([wp], max_new_tokens=1)
        e_cold.submit([prompt_for(98)], max_new_tokens=1)
        for i in range(5):
            p = prompt_for(i)
            # Stage the chain on the source (the prefill replica's
            # steady state: the prompt is already in its cache when a
            # decode peer asks), then time only the handoff machinery.
            e_src.export_chain(p)
            t0 = time.perf_counter()
            data = e_src.export_chain(p)  # pcache hit: gather+encode
            assert e_dst.import_chain(data)
            transfer_s.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            e_dst.submit([p], max_new_tokens=1)  # exact hit: no prefill
            warm_sub_s.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            e_cold.submit([p], max_new_tokens=1)  # full 512 prefill
            cold_sub_s.append(time.perf_counter() - t0)
        transfer_bytes = len(data)
    finally:
        for e in (e_src, e_dst, e_cold):
            e.close()

    # The prefill the transfer dodges: cold submit minus the warm
    # (exact-hit) submit — both pay the same admission + one decode
    # step, so the difference isolates the 512-wide prefill.
    cold_prefill_s = max(min(cold_sub_s) - min(warm_sub_s), 1e-9)
    transfer_ratio = min(transfer_s) / cold_prefill_s

    # -- Arm A: short-class TPOT tail under mixed traffic --------------
    def serve(**kw):
        srv = InferenceServer(
            model_name="transformer-tiny", seq_len=max_seq,
            batch_window_ms=0.0, continuous_batching=True,
            decode_block=4, kv_page_size=page, kv_pages=128,
            shard_devices=None, **kw)
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(srv))
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return srv, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"

    def warm_http(url: str):
        # One HTTP request per class so the measured window never sees
        # a first-use path (handler, SSE framing, disagg prefetch).
        for rows in (short_len, mix_long_len):
            body = json.dumps({"prompt_tokens": [_gen_prompt(rows)],
                               "max_new_tokens": 2}).encode()
            req = urllib.request.Request(
                url + "/v1/generate", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=300) as r:
                r.read()

    def measure(url: str) -> dict:
        return run_mixed(url, clients=n_clients, seconds=bench_s,
                         mix=(2, 1), rows=short_len,
                         long_rows=mix_long_len, generate_tokens=reply)

    mono_srv, mono_httpd, mono_url = serve(prompt_cache=0,
                                           instance="bench-mono")
    try:
        warm_http(mono_url)
        mono = measure(mono_url)
    finally:
        mono_httpd.shutdown()
        mono_srv.close()

    pre_srv, pre_httpd, pre_url = serve(prompt_cache=32, role="prefill",
                                        instance="bench-prefill")
    dec_srv, dec_httpd, dec_url = serve(prompt_cache=32, role="decode",
                                        prefill_upstream=pre_url,
                                        instance="bench-decode")
    try:
        warm_http(dec_url)
        disagg = measure(dec_url)
        kv_imports = dec_srv._engine.stats()["kv_imports"]
        fallbacks = dec_srv._engine.stats()["transfer_fallbacks"]
    finally:
        dec_httpd.shutdown()
        pre_httpd.shutdown()
        dec_srv.close()
        pre_srv.close()

    short_mono = mono["classes"]["short"]["tpot_p99_ms"]
    short_dis = disagg["classes"]["short"]["tpot_p99_ms"]
    tpot_ratio = short_dis / max(short_mono, 1e-9)
    doc = {
        # Headline: disagg short-class p99 TPOT over monolithic. The
        # bar is 0.5; vs_baseline = ratio*2 so <=1.0 passes.
        "metric": "serve_disagg_short_tpot_ratio",
        "value": round(tpot_ratio, 4),
        "unit": "disagg_short_p99_tpot_over_monolithic",
        "vs_baseline": round(tpot_ratio * 2.0, 4),
        "detail": {
            "gate_tpot_ratio_max": 0.5,
            "tpot_gate_passed": tpot_ratio <= 0.5,
            "short_tpot_p99_ms_monolithic": short_mono,
            "short_tpot_p99_ms_disagg": short_dis,
            "short_tpot_p50_ms_monolithic":
                mono["classes"]["short"]["tpot_p50_ms"],
            "short_tpot_p50_ms_disagg":
                disagg["classes"]["short"]["tpot_p50_ms"],
            "short_requests_monolithic":
                mono["classes"]["short"]["requests"],
            "short_requests_disagg":
                disagg["classes"]["short"]["requests"],
            "errors_monolithic": mono["errors"],
            "errors_disagg": disagg["errors"],
            "kv_imports": kv_imports,
            "transfer_fallbacks": fallbacks,
            "transfer_ratio": round(transfer_ratio, 4),
            "gate_transfer_ratio_max": round(1.0 / 3.0, 4),
            "transfer_gate_passed": transfer_ratio <= 1.0 / 3.0,
            "transfer_s": round(min(transfer_s), 6),
            "cold_prefill_s": round(cold_prefill_s, 6),
            "transfer_bytes": transfer_bytes,
            "transfer_rounds": len(transfer_s),
            "mix": mono["mix"],
            "clients": n_clients,
            "seconds_per_arm": bench_s,
            "short_prompt_tokens": short_len,
            "long_prompt_tokens": mix_long_len,
            "transfer_prompt_tokens": long_len,
            "gen_tokens_per_request": reply,
            "page_size": page,
        },
    }
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _serve_disagg_main() -> int:
    """Bounded-subprocess wrapper for --serve-disagg (same wedge-proof
    discipline as the other serve benches)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__),
         "--serve-disagg-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="serve_disagg")
    skw = {"metric": "serve_disagg_short_tpot_ratio",
           "unit": "disagg_short_p99_tpot_over_monolithic"}
    if not ok:
        why = (f"disagg bench did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("serve_disagg", f"{why}; stderr: {err.strip()}", **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def _serve_autoscale_worker() -> int:
    """Autoscaler gate (bounded subprocess; the parent process of this
    worker never imports jax — the replicas are REAL server
    subprocesses spawned by the LocalProcessActuator, sharing one spill
    dir and one compilation cache).

    Topology: actuator fleet of ``python -m k3stpu.serve.server``
    processes; in-process Router with a FileWatcher on the actuator's
    replicas file (the same handshake production uses); in-process
    Controller scraping the replicas' real /metrics through the
    router's /debug/router membership.

    Gates (all three must hold):
    - scale 1->2 and back: loadgen's ramp (1x -> 8x -> 2x, 2 engine
      slots per replica so the surge actually queues) must push queue
      depth over the bar and the recede must drain it back under.
    - zero failed requests: ramp errors == 0 and no client gave up
      on 503s — scale-up, drain, and kill are all invisible to traffic.
    - warm restore after scale-down: a session pinned to the victim is
      released with spill=true by the drain protocol; its next turn on
      the survivor must cost <= 1/3 of a cold re-prefill (the
      --serve-tier bound) AND move the survivor's tier swap-in counter
      (time could lie; the counter can't)."""
    import random
    import tempfile
    import threading
    import urllib.error
    import urllib.request
    from http.server import ThreadingHTTPServer

    import numpy as np

    from k3stpu.autoscaler import Controller, DecisionPolicy, LocalProcessActuator
    from k3stpu.router import FileWatcher, Router, make_router_app
    from k3stpu.serve.loadgen import run_ramp

    # 512-token prompts with --seq-len 2048 (the tier gate's geometry):
    # the grown turn-2 prompt (512 + reply + 2) buckets to a 1024-wide
    # prefill, so "cold" costs a real re-prefill while the warm turn
    # pays a swap-in + a 64-bucket suffix.
    prompt_len, reply = 512, 8
    workdir = tempfile.mkdtemp(prefix="bench-autoscale-")
    tier_dir = os.path.join(workdir, "tier")
    os.makedirs(tier_dir, exist_ok=True)
    replicas_file = os.path.join(workdir, "replicas.txt")
    base_port = random.randint(20000, 40000)

    def spawn(index: int, port: int) -> "list[str]":
        return [sys.executable, "-m", "k3stpu.serve.server",
                "--model", "transformer-tiny", "--seq-len", "2048",
                "--port", str(port), "--batch-window-ms", "0",
                "--continuous-batching", "--engine-slots", "2",
                "--decode-block", "4", "--prompt-cache", "8",
                "--kv-page-size", "64", "--kv-pages", "64",
                "--tier-host-mb", "64", "--tier-dir", tier_dir,
                "--no-warmup", "--instance", f"as-rep-{index}"]

    def prompt_for(seed: int) -> "list[int]":
        rng = np.random.default_rng(seed)
        return rng.integers(1, 1000, size=(prompt_len,)).tolist()

    def post(url: str, path: str, body: dict, timeout: float = 180.0) -> dict:
        data = json.dumps(body).encode()
        for attempt in range(4):
            req = urllib.request.Request(
                url + path, data=data, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return json.loads(r.read().decode())
            except urllib.error.HTTPError as e:
                with e:
                    detail = e.read()[:200]
                if e.code == 503 and attempt < 3:  # shed/drain: retry
                    time.sleep(0.5)
                    continue
                raise RuntimeError(f"{path} -> {e.code}: {detail!r}")
        raise RuntimeError(f"{path}: retries exhausted")

    def counter(url: str, name: str) -> float:
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        for line in text.splitlines():
            if line.startswith(name + " "):
                return float(line.split()[1])
        return 0.0

    def warm_replica(url: str, seed: int) -> None:
        """Compile every program the measured turns hit on THIS
        replica: turn-1 512-bucket prefill + decode, suffix 64-bucket
        prefill, host-park restore, and the disk-spill load path."""
        p = prompt_for(seed)
        rep = post(url, "/v1/generate",
                   {"prompt_tokens": [p], "max_new_tokens": reply,
                    "session": "warmup"})["tokens"][0]
        post(url, "/v1/session/release", {"session": "warmup"})
        p2 = p + rep + [1, 2]
        post(url, "/v1/generate",
             {"prompt_tokens": [p2], "max_new_tokens": 1,
              "session": "warmup"})
        post(url, "/v1/session/release",
             {"session": "warmup", "spill": True})
        post(url, "/v1/generate",
             {"prompt_tokens": [p2 + [3]], "max_new_tokens": 1,
              "session": "warmup"})
        post(url, "/v1/session/release", {"session": "warmup"})

    def until(cond, deadline_s: float, every: float = 0.25) -> bool:
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            if cond():
                return True
            time.sleep(every)
        return cond()

    def healthy(url: str) -> bool:
        try:
            with urllib.request.urlopen(url + "/healthz",
                                        timeout=2.0) as r:
                return r.status == 200
        except OSError:
            return False

    actuator = LocalProcessActuator(
        spawn, base_port=base_port, replicas_file=replicas_file,
        ready_timeout_s=180.0, kill_timeout_s=30.0)
    router = Router([], allow_empty=True, health_period_s=0.5,
                    proxy_timeout_s=180.0, instance="bench-autoscale")
    # Without the poller a replica ejected during its boot window (the
    # watcher adds it at Popen; /healthz serves ~15s later) would stay
    # ejected forever and never take a placement.
    router.start_health_poller()
    rhttpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                 make_router_app(router))
    threading.Thread(target=rhttpd.serve_forever, daemon=True).start()
    rurl = f"http://127.0.0.1:{rhttpd.server_address[1]}"
    watcher = FileWatcher(router, replicas_file, period_s=0.2)

    # Queue depth is the only live signal (the latency histograms are
    # cumulative, so a surge would block scale-down forever — neutralize
    # them); 8 clients against 2 engine slots queues well past 1.0.
    policy = DecisionPolicy(
        min_replicas=1, max_replicas=2, queue_high=1.0, queue_low=0.25,
        pages_free_low=0.05, queue_wait_high_s=1e9, ttft_high_s=1e9,
        scale_up_cooldown_s=5.0, scale_down_cooldown_s=1.0)
    controller = Controller(actuator, policy, router_url=rurl,
                            drain_deadline_s=15.0, drain_poll_s=0.1)
    reports: "list[dict]" = []
    ctl_stop = threading.Event()
    ctl_hold = threading.Event()  # measurement scaffolding: pause steps

    def ctl_loop() -> None:
        while not ctl_stop.wait(0.5):
            if ctl_hold.is_set():
                continue
            try:
                reports.append(controller.step())
            except Exception as e:  # noqa: BLE001 — loop must survive
                print(f"bench: controller step failed: {e}", flush=True)

    try:
        actuator.scale_to(1)
        watcher.poll_once()
        watcher.start()
        rep0 = actuator.urls()[0]
        warm_replica(rep0, 9000)
        # Two sessions pinned to replica 0 BEFORE the surge: the victim
        # pick is fewest-pins, so the scale-up replica (one parked
        # session) is the victim and ITS session must migrate.
        parked0 = []
        for i in range(2):
            p = prompt_for(100 + i)
            rep = post(rurl, "/v1/generate",
                       {"prompt_tokens": [p], "max_new_tokens": reply,
                        "session": f"park-a{i}"})["tokens"][0]
            parked0.append(p + rep + [5, 6])

        threading.Thread(target=ctl_loop, daemon=True).start()
        ramp_result: dict = {}

        def ramp_thread() -> None:
            ramp_result.update(run_ramp(
                rurl, phases=[(1, 4.0), (8, 30.0), (2, 8.0)],
                rows=32, input_shape=(), input_dtype="int32",
                generate_tokens=32))

        rt = threading.Thread(target=ramp_thread, daemon=True)
        rt.start()

        scaled_up = until(lambda: actuator.current() == 2
                          and len(router.replicas()) == 2, 40.0)
        victim_session, victim_prompt, victim_url = None, None, None
        if scaled_up:
            # Hold the controller while warming/parking on the new
            # replica: once the ramp recedes it would otherwise drain
            # and kill exactly this replica (fewest pins) mid-warm.
            ctl_hold.set()
            new_url = [u for u in actuator.urls() if u != rep0][0]
            # current() counts the replica from Popen on; boot (the jax
            # import + model build) finishes inside the actuator's own
            # health-wait. Gate the warm-up on the replica serving.
            if not until(lambda: healthy(new_url), 120.0):
                raise RuntimeError(f"scale-up replica {new_url} "
                                   "never became healthy")
            warm_replica(new_url, 9100)
            # Land one session on the scale-up replica (prefix-hash
            # placement: distinct prompts spread ~50/50, so a handful
            # of tries suffices).
            for i in range(16):
                sid = f"park-b{i}"
                p = prompt_for(500 + i)
                rep = post(rurl, "/v1/generate",
                           {"prompt_tokens": [p],
                            "max_new_tokens": reply,
                            "session": sid})["tokens"][0]
                pinned = router.state()["pins"].get(sid)
                if pinned == new_url:
                    victim_session = sid
                    victim_prompt = p + rep + [5, 6]
                    victim_url = new_url
                    break
                post(rurl, "/v1/session/release", {"session": sid})
            ctl_hold.clear()
        rt.join(timeout=120.0)

        scaled_down = until(
            lambda: any(r["action"] == "down" for r in reports)
            and actuator.current() == 1, 90.0)
        ctl_stop.set()
        until(lambda: len(router.replicas()) == 1, 10.0)
        survivor = actuator.urls()[0] if actuator.urls() else rep0

        warm_s, swap_delta, cold_med = -1.0, 0.0, -1.0
        warm_client_s, cold_client_s = -1.0, -1.0
        if scaled_down and victim_session is not None \
                and survivor != victim_url:
            # Warm and cold are read from the SURVIVOR's own e2e
            # histogram (sum delta around each single request): the
            # restore-vs-reprefill comparison is a server-side
            # property, and a one-shot client wall time folds in
            # router/GIL jitter from the processes this bench itself
            # is running. Client wall times ride along in the detail.
            e2e = "k3stpu_request_e2e_seconds_sum"
            swapc = "k3stpu_tier_swap_ins_total"
            swaps0 = counter(survivor, swapc)
            # Best-of-3 like the tier gate: the first attempt is the
            # true post-drain disk restore; between attempts the
            # session re-parks with spill=true so every attempt stays
            # a tier restore. An attempt only COUNTS if its own
            # swap-in delta moved — a pcache hit sneaking in (however
            # it got there) must not masquerade as a restore.
            warm_tries, warm_client = [], []
            for k in range(3):
                s0 = counter(survivor, swapc)
                e0 = counter(survivor, e2e)
                t0 = time.perf_counter()
                post(rurl, "/v1/generate",
                     {"prompt_tokens": [victim_prompt],
                      "max_new_tokens": 1, "session": victim_session})
                wall = time.perf_counter() - t0
                if counter(survivor, swapc) - s0 >= 1.0:
                    warm_client.append(wall)
                    warm_tries.append(counter(survivor, e2e) - e0)
                if k < 2:
                    post(rurl, "/v1/session/release",
                         {"session": victim_session, "spill": True})
            if warm_tries:
                warm_s = min(warm_tries)
                warm_client_s = min(warm_client)
            swap_delta = counter(survivor, swapc) - swaps0
            try:  # lifecycle breakdown of the measured turn (stderr,
                #   keeps the stdout BENCH_JSON contract clean)
                with urllib.request.urlopen(
                        survivor + "/debug/requests", timeout=10) as r:
                    dbg = json.loads(r.read().decode())
                print("warm turn trace: "
                      + json.dumps(dbg.get("requests", dbg)[-1:]),
                      file=sys.stderr, flush=True)
            except Exception as e:  # noqa: BLE001 — diagnostics only
                print(f"warm turn trace unavailable: {e}",
                      file=sys.stderr, flush=True)
            cold_s, cold_client = [], []
            for i in range(3):
                rng = np.random.default_rng(700 + i)
                cold_p = rng.integers(
                    1, 1000, size=(len(victim_prompt),)).tolist()
                e0 = counter(survivor, e2e)
                t0 = time.perf_counter()
                post(rurl, "/v1/generate",
                     {"prompt_tokens": [cold_p], "max_new_tokens": 1})
                cold_client.append(time.perf_counter() - t0)
                cold_s.append(counter(survivor, e2e) - e0)
            cold_med = sorted(cold_s)[1]
            cold_client_s = sorted(cold_client)[1]
    finally:
        ctl_stop.set()
        watcher.stop()
        rhttpd.shutdown()
        router.close()
        actuator.close()

    ratio = (warm_s / max(cold_med, 1e-9)) if warm_s > 0 else 99.0
    scale_events = [r["action"] for r in reports
                    if r["action"] in ("up", "down")]
    zero_failed = (bool(ramp_result)
                   and ramp_result.get("errors", 1) == 0
                   and ramp_result.get("gave_up_503", 1) == 0)
    doc = {
        # Headline: the migrated session's warm-turn cost over a cold
        # re-prefill on the survivor. Bar 1/3; vs_baseline = ratio*3.
        "metric": "serve_autoscale_warm_restore_ratio",
        "value": round(ratio, 4),
        "unit": "warm_turn_s_over_cold_reprefill_s",
        "vs_baseline": round(ratio * 3.0, 4),
        "detail": {
            "gate_warm_over_cold_max": round(1.0 / 3.0, 4),
            "warm_gate_passed": ratio <= 1.0 / 3.0 and swap_delta >= 1,
            "scale_gate_passed": scaled_up and scaled_down,
            "zero_failed_gate_passed": zero_failed,
            "warm_turn_s": round(warm_s, 6),
            "cold_reprefill_s": round(cold_med, 6),
            "warm_turn_client_s": round(warm_client_s, 6),
            "cold_reprefill_client_s": round(cold_client_s, 6),
            "survivor_swap_ins_delta": swap_delta,
            "scale_events": scale_events,
            "controller_steps": len(reports),
            "ramp_requests": ramp_result.get("requests", 0),
            "ramp_errors": ramp_result.get("errors", -1),
            "ramp_retries_503": ramp_result.get("retries_503", -1),
            "ramp_gave_up_503": ramp_result.get("gave_up_503", -1),
            "ramp_phase_p50_ms": [ph.get("p50_ms")
                                  for ph in ramp_result.get(
                                      "ramp_phases", [])],
            "prompt_tokens": prompt_len,
            "replicas_peak": 2,
        },
    }
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _serve_autoscale_main() -> int:
    """Bounded-subprocess wrapper for --serve-autoscale (same
    wedge-proof discipline as the other serve benches)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__),
         "--serve-autoscale-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False,
        stage="serve_autoscale")
    skw = {"metric": "serve_autoscale_warm_restore_ratio",
           "unit": "warm_turn_s_over_cold_reprefill_s"}
    if not ok:
        why = (f"autoscale bench did not finish within "
               f"{MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("serve_autoscale", f"{why}; stderr: {err.strip()}",
                     **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def _train_obs_worker() -> int:
    """TrainObs overhead microbench (bounded subprocess).

    The training funnel's budget is <=5% on step time: run the SAME
    in-process train_job.main twice per round — K3STPU_TRAIN_OBS=0
    (emit prints, every metric update a no-op) vs 1 (histograms,
    goodput accounting, step spans, recompile probe) — and compare
    post-warmup step_s. The per-arm statistic is a 20% trimmed mean
    (step_s is logged at 0.1ms granularity, so at ~4ms CPU steps a
    median of rounded values can only move in 2-3% quanta; the mean
    averages the quantization out, and the trim drops scheduler
    outliers). An untimed throwaway round warms the persistent compile
    cache first. The headline is the MEDIAN over 5 rounds of the
    PAIRED on/off ratio: host-load drift on a shared box moves ~4ms
    CPU steps by far more than the ~10us hook cost, so comparing arms
    from different moments (min-of-arm-means) measured the machine,
    not the funnel — pairing each round's arms back-to-back cancels
    drift slower than a round, and the median survives rounds where a
    throttle landed between the two arms."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import contextlib
    import io
    import tempfile

    from k3stpu.parallel import train_job

    # Keep the enabled arm's telemetry writer off the real drop path.
    os.environ["K3STPU_TELEMETRY_DROP"] = os.path.join(
        tempfile.gettempdir(), f"k3stpu-bench-telemetry-{os.getpid()}.json")
    steps, warmup = 60, 5
    argv = ["--model", "tiny", "--steps", str(steps),
            "--batch", "4", "--seq", "32"]

    def trimmed_mean_step_s(enabled: bool) -> float:
        os.environ["K3STPU_TRAIN_OBS"] = "1" if enabled else "0"
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = train_job.main(argv)
        if rc != 0:
            raise RuntimeError(f"train_job exited rc={rc}")
        vals = []
        for line in buf.getvalue().splitlines():
            if not line.startswith("{"):
                continue
            rec = json.loads(line)
            if rec.get("event") == "step":
                vals.append(rec["step_s"])
        if len(vals) != steps:
            raise RuntimeError(f"expected {steps} step events, "
                               f"got {len(vals)}")
        vals = sorted(vals[warmup:])
        trim = len(vals) // 5
        kept = vals[trim:len(vals) - trim]
        return sum(kept) / len(kept)

    trimmed_mean_step_s(False)  # throwaway: compile-cache warmup
    rounds = 5
    ratios, pairs = [], []
    for _ in range(rounds):
        off = trimmed_mean_step_s(False)
        on = trimmed_mean_step_s(True)
        ratios.append(on / off if off else 1.0)
        pairs.append((round(off, 6), round(on, 6)))
    overhead = (sorted(ratios)[rounds // 2] - 1.0) * 100.0
    doc = {
        # Headline: median step time added by the TrainObs funnel, in
        # percent. The bar is 5%; vs_baseline = value/5 so <=1.0 means
        # within budget (negative just means run-to-run noise exceeded
        # the true overhead).
        "metric": "train_obs_overhead_pct",
        "value": round(overhead, 2),
        "unit": "pct_step_time",
        "vs_baseline": round(overhead / 5.0, 4),
        "detail": {
            "budget_pct": 5.0,
            "paired_trimmed_mean_step_s_off_on": pairs,
            "per_round_overhead_pct":
                [round((r - 1.0) * 100.0, 2) for r in ratios],
            "rounds": rounds,
            "steps_per_run": steps,
            "warmup_steps_excluded": warmup,
        },
    }
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _train_obs_main() -> int:
    """Bounded-subprocess wrapper for --train-obs (same wedge-proof
    discipline as the other CPU benches)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__), "--train-obs-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="train_obs")
    skw = {"metric": "train_obs_overhead_pct", "unit": "pct_step_time"}
    if not ok:
        why = (f"obs bench did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("train_obs", f"{why}; stderr: {err.strip()}", **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def _trace_obs_worker() -> int:
    """Trace-propagation + exemplar overhead microbench (bounded
    subprocess).

    ISSUE 7's budget: the W3C trace-context path must cost <=5% of
    decode throughput. Both arms run the SAME engine with the SAME
    ServeObs — the delta is ONLY the new tracing surface. The traced
    arm pays, per request, exactly what a real edge request pays:
    mint+parse an inbound traceparent, thread the id through
    submit() into the engine's ReqTrace, exemplar stores on every
    histogram observe, and an outbound echo mint; plus one
    exemplar-bearing OpenMetrics render per run (a concurrent scrape).
    The untraced arm submits id-free and renders the default
    exposition. Paired rounds with a median-of-ratios headline (the
    --train-obs idiom): host-load drift moves tokens/s far more than
    the ~µs id cost, pairing arms back-to-back cancels drift slower
    than a round, and the median survives a throttled round."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import threading

    import numpy as np

    from k3stpu.models.transformer import transformer_lm_tiny
    from k3stpu.obs import (
        ServeObs,
        format_traceparent,
        new_span_id,
        new_trace_id,
        parse_traceparent,
    )
    from k3stpu.serve.engine import GenerateEngine

    max_seq, slots = 128, 8
    n_reqs, prompt_len, new_tokens = 16, 8, 24

    model = transformer_lm_tiny(max_seq_len=max_seq)
    params = model.init(jax.random.key(0),
                        np.zeros((1, 1), np.int32))["params"]

    obs = ServeObs()
    engine = GenerateEngine(model, params, slots=slots, seed=0, obs=obs)

    def drive(traced: bool) -> float:
        engine.reset_stats()
        results = [None] * n_reqs

        def go(i):
            prompt = [((i * 7 + j) % 97) + 1 for j in range(prompt_len)]
            tid = None
            if traced:
                header = format_traceparent(new_trace_id(), new_span_id())
                tid = parse_traceparent(header)[0]
            results[i] = engine.submit([prompt],
                                       max_new_tokens=new_tokens,
                                       trace_id=tid)
            if traced:
                format_traceparent(tid, new_span_id())  # response echo

        threads = [threading.Thread(target=go, args=(i,))
                   for i in range(n_reqs)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if not all(r is not None and len(r[0]) == new_tokens
                   for r in results):
            raise RuntimeError("a request failed or came back short")
        if traced:
            obs.render_openmetrics()
        else:
            obs.render_prometheus()
        return engine.stats()["tokens_per_s"] or 0.0

    try:
        engine.submit([[1, 2, 3]], max_new_tokens=4)  # warm compiles
        drive(False)  # throwaway: steady-state warmup
        rounds = 5
        ratios, pairs = [], []
        for _ in range(rounds):
            off = drive(False)
            on = drive(True)
            ratios.append(on / off if off else 1.0)
            pairs.append((round(off, 1), round(on, 1)))
    finally:
        engine.close()

    overhead = (1.0 - sorted(ratios)[rounds // 2]) * 100.0
    doc = {
        # Headline: median decode tokens/s lost to trace propagation +
        # exemplars, in percent. The bar is 5%; vs_baseline =
        # overhead/5 so <=1.0 means within budget (negative just means
        # run-to-run noise exceeded the true overhead).
        "metric": "trace_obs_overhead_pct",
        "value": round(overhead, 2),
        "unit": "pct_decode_tokens_per_s",
        "vs_baseline": round(overhead / 5.0, 4),
        "detail": {
            "budget_pct": 5.0,
            "paired_tokens_per_s_off_on": pairs,
            "per_round_overhead_pct":
                [round((1.0 - r) * 100.0, 2) for r in ratios],
            "rounds": rounds,
            "requests_per_run": n_reqs,
            "new_tokens_per_request": new_tokens,
        },
    }
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _trace_obs_main() -> int:
    """Bounded-subprocess wrapper for --trace-obs (same wedge-proof
    discipline as the other CPU benches)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__), "--trace-obs-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="trace_obs")
    skw = {"metric": "trace_obs_overhead_pct",
           "unit": "pct_decode_tokens_per_s"}
    if not ok:
        why = (f"trace obs bench did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("trace_obs", f"{why}; stderr: {err.strip()}", **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def _node_obs_worker() -> int:
    """Node-exporter scrape-cost microbench (bounded subprocess, no jax).

    The fleet tier's budget: collecting one /metrics render — sysfs
    chip walk + reading/merging 8 per-process drop files + rebuilding
    every gauge family — must cost <=5% of one CPU core at a 1 Hz
    scrape. Measured as process_time over 200 renders against a
    synthetic 4-chip sysfs tree and 8 fresh drop files (4 devices
    each), after one warm render; reported as percent of one core
    consumed if Prometheus scraped once per second."""
    import shutil
    import tempfile

    from k3stpu.obs.node_exporter import NodeCollector

    root = tempfile.mkdtemp(prefix="k3stpu-node-obs-")
    try:
        # Synthetic host: 4 v5e chips in sysfs + matching /dev/accel*.
        pci = os.path.join(root, "sys", "bus", "pci", "devices")
        for i in range(4):
            ddir = os.path.join(pci, f"0000:0{i}:00.0")
            os.makedirs(ddir)
            with open(os.path.join(ddir, "vendor"), "w") as f:
                f.write("0x1ae0\n")
            with open(os.path.join(ddir, "device"), "w") as f:
                f.write("0x0062\n")
        dev = os.path.join(root, "dev")
        os.makedirs(dev)
        for i in range(4):
            open(os.path.join(dev, f"accel{i}"), "w").close()
        # 8 per-process drops (8 workload pods on the node), 4 devices
        # each, in the utils/telemetry.py payload shape.
        drops = os.path.join(root, "run", "k3stpu")
        os.makedirs(drops)
        now = int(time.time())
        for p in range(8):
            payload = {"ts": now, "devices": [
                {"index": i, "bytes_in_use": (p + 1) * 2**28,
                 "bytes_limit": 16 * 2**30, "duty_cycle_pct": 50,
                 "source": "pjrt"} for i in range(4)]}
            with open(os.path.join(drops, f"metrics-pod{p}-1.json"),
                      "w") as f:
                json.dump(payload, f)

        coll = NodeCollector(drop_dir=drops, host_root_path=root,
                             expected_chips=4,
                             stale_after_s=10**9, gc_after_s=10**9)
        coll.render()  # warm: first-render allocations out of the timing
        iters = 200
        t0 = time.process_time()
        for _ in range(iters):
            coll.render()
        cpu_s = (time.process_time() - t0) / iters
    finally:
        shutil.rmtree(root, ignore_errors=True)

    pct = cpu_s * 100.0  # 1 Hz scrape: cpu_s per second of wall-clock
    doc = {
        # Headline: share of one CPU core the exporter costs at a 1 Hz
        # scrape. The bar is 5%; vs_baseline = value/5 so <=1.0 means
        # within budget.
        "metric": "node_obs_scrape_cpu_pct",
        "value": round(pct, 3),
        "unit": "pct_of_one_core_at_1hz",
        "vs_baseline": round(pct / 5.0, 4),
        "detail": {
            "budget_pct": 5.0,
            "cpu_s_per_scrape": round(cpu_s, 6),
            "renders_timed": iters,
            "drop_files": 8,
            "chips": 4,
        },
    }
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _node_obs_main() -> int:
    """Bounded-subprocess wrapper for --node-obs (same wedge-proof
    discipline as the other CPU benches; the worker never imports jax
    but the bounded-run + one-JSON-line contract is identical)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__), "--node-obs-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="node_obs")
    skw = {"metric": "node_obs_scrape_cpu_pct",
           "unit": "pct_of_one_core_at_1hz"}
    if not ok:
        why = (f"node obs bench did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("node_obs", f"{why}; stderr: {err.strip()}", **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def _sim_worker() -> int:
    """Fleet digital-twin acceptance soak (bounded subprocess, no jax).

    Runs the ``diurnal-1000`` scenario — a 1000-replica fleet, 100k
    requests over a compressed diurnal day, the FULL chaos fault matrix
    (all 19 injection points plus the fleet-scale faults), the shipped
    autoscaler/router/admission policy code driven BY IDENTITY inside
    the simulator. The headline metric is interactive TTFT SLO
    attainment (bar: >=0.999 good at 2.5s — vs_baseline = value/0.999
    so >=1.0 means within budget); lost requests, oscillations and the
    sim's own wall-clock ride in detail. The wall-clock lives HERE, not
    in the sim report — the report is byte-stable by construction and
    must never contain wall time."""
    from k3stpu.sim import scenarios
    from k3stpu.sim.report import build_report

    t0 = time.monotonic()
    fleet = scenarios.run_scenario("diurnal-1000", seed=0)
    wall_s = time.monotonic() - t0
    report = build_report(fleet)

    inter = report["latency"].get("interactive") or {}
    att = inter.get("attainment")
    target = inter.get("slo_target") or 0.999
    doc = {
        "metric": "sim_fleet_interactive_slo_attainment",
        "value": round(att, 6) if att is not None else 0.0,
        "unit": "frac_good_at_2.5s",
        "vs_baseline": (round(att / target, 4)
                        if att is not None else 0.0),
        "detail": {
            "scenario": report["scenario"],
            "seed": report["seed"],
            "slo_target": target,
            "requests_total": report["requests"]["total"],
            "requests_lost": report["requests"]["lost"],
            "requests_completed": report["requests"]["completed"],
            "faults_applied": report["faults"]["applied"],
            "faults_scheduled": report["faults"]["scheduled"],
            "oscillations": len(report["autoscaler"]["oscillations"]),
            "actuations": len(report["autoscaler"]["actuations"]),
            "final_replicas": report["autoscaler"]["final_replicas"],
            "events_processed": report["events_processed"],
            "wall_s": round(wall_s, 2),
            "events_per_s": (round(report["events_processed"] / wall_s)
                             if wall_s > 0 else None),
            "interactive_p99_ttft_s": inter.get("p99_s"),
            "calibration": report["calibration"],
        },
    }
    print("BENCH_JSON " + json.dumps(doc), flush=True)
    _emit(doc)
    return 0


def _sim_main() -> int:
    """Bounded-subprocess wrapper for --sim (the worker never imports
    jax — the twin is pure-python — but the bounded-run + one-JSON-line
    contract is identical to every other bench stage)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__), "--sim-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="sim")
    skw = {"metric": "sim_fleet_interactive_slo_attainment",
           "unit": "frac_good_at_2.5s"}
    if not ok:
        why = (f"sim bench did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("sim", f"{why}; stderr: {err.strip()}", **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def _serve_paged_main() -> int:
    """Bounded-subprocess wrapper for --serve-paged (same wedge-proof
    discipline as the matmul path: the parent never imports jax)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__), "--serve-paged-worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="serve_paged")
    skw = {"metric": "serve_paged_capacity_ratio",
           "unit": "x_concurrent_slots_at_fixed_hbm"}
    if not ok:
        why = (f"serve bench did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("serve_paged", f"{why}; stderr: {err.strip()}", **skw)
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}",
                 **skw)


def main() -> int:
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    # Persistent compilation cache for the probe + worker children (JAX
    # reads these env vars natively): a re-run after a wedge retry — or
    # right after capture_artifacts warmed the same 8192^3 matmul — skips
    # the ~30 s compile instead of spending its bounded budget on it.
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")

    # Stage 1 — backend init probe: is the chip (or any backend) reachable?
    ok, rc, out, err = _run_with_retry(
        [sys.executable, "-c", _PROBE_SRC], PROBE_TIMEOUT_S,
        retry_on_timeout=True, attempts=PROBE_ATTEMPTS,
        stage="backend_init")
    if not ok:
        why = (f"backend init did not return within {PROBE_TIMEOUT_S}s "
               f"(x{PROBE_ATTEMPTS} attempts) — device tunnel wedged?"
               if rc is None else f"probe exited rc={rc}")
        return _fail("backend_init", f"{why}; stderr: {err.strip()}")

    # Stage 2 — the measurement, bounded; retried only on fast failure.
    ok, rc, out, err = _run_with_retry(
        [sys.executable, os.path.abspath(__file__), "--worker"],
        MEASURE_TIMEOUT_S, retry_on_timeout=False, stage="measure")
    if not ok:
        why = (f"measurement did not finish within {MEASURE_TIMEOUT_S}s"
               if rc is None else f"worker exited rc={rc}")
        return _fail("measure", f"{why}; stderr: {err.strip()}")

    # Re-emit the worker's metric line (last parseable metric dict wins).
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            _emit(rec)
            return 0
    return _fail("parse", f"worker emitted no metric line; stdout: {out!r}")


if __name__ == "__main__":
    if "--worker" in sys.argv[1:]:
        sys.exit(_worker())
    if "--serve-paged-worker" in sys.argv[1:]:
        sys.exit(_serve_paged_worker())
    if "--serve-paged" in sys.argv[1:]:
        sys.exit(_serve_paged_main())
    if "--serve-spec-worker" in sys.argv[1:]:
        sys.exit(_serve_spec_worker())
    if "--serve-spec" in sys.argv[1:]:
        sys.exit(_serve_spec_main())
    if "--serve-attn-worker" in sys.argv[1:]:
        sys.exit(_serve_attn_worker())
    if "--serve-attn" in sys.argv[1:]:
        sys.exit(_serve_attn_main())
    if "--serve-tp-worker" in sys.argv[1:]:
        sys.exit(_serve_tp_worker())
    if "--serve-tp" in sys.argv[1:]:
        sys.exit(_serve_tp_main())
    if "--serve-obs-worker" in sys.argv[1:]:
        sys.exit(_serve_obs_worker())
    if "--serve-obs" in sys.argv[1:]:
        sys.exit(_serve_obs_main())
    if "--serve-tier-worker" in sys.argv[1:]:
        sys.exit(_serve_tier_worker())
    if "--serve-tier" in sys.argv[1:]:
        sys.exit(_serve_tier_main())
    if "--serve-router-worker" in sys.argv[1:]:
        sys.exit(_serve_router_worker())
    if "--serve-router" in sys.argv[1:]:
        sys.exit(_serve_router_main())
    if "--serve-disagg-worker" in sys.argv[1:]:
        sys.exit(_serve_disagg_worker())
    if "--serve-disagg" in sys.argv[1:]:
        sys.exit(_serve_disagg_main())
    if "--serve-autoscale-worker" in sys.argv[1:]:
        sys.exit(_serve_autoscale_worker())
    if "--serve-autoscale" in sys.argv[1:]:
        sys.exit(_serve_autoscale_main())
    if "--serve-canary-worker" in sys.argv[1:]:
        sys.exit(_serve_canary_worker())
    if "--serve-canary" in sys.argv[1:]:
        sys.exit(_serve_canary_main())
    if "--obs-pipeline-worker" in sys.argv[1:]:
        sys.exit(_obs_pipeline_worker())
    if "--obs-pipeline" in sys.argv[1:]:
        sys.exit(_obs_pipeline_main())
    if "--serve-qos-worker" in sys.argv[1:]:
        sys.exit(_serve_qos_worker())
    if "--serve-qos" in sys.argv[1:]:
        sys.exit(_serve_qos_main())
    if "--train-obs-worker" in sys.argv[1:]:
        sys.exit(_train_obs_worker())
    if "--train-obs" in sys.argv[1:]:
        sys.exit(_train_obs_main())
    if "--trace-obs-worker" in sys.argv[1:]:
        sys.exit(_trace_obs_worker())
    if "--trace-obs" in sys.argv[1:]:
        sys.exit(_trace_obs_main())
    if "--node-obs-worker" in sys.argv[1:]:
        sys.exit(_node_obs_worker())
    if "--node-obs" in sys.argv[1:]:
        sys.exit(_node_obs_main())
    if "--sim-worker" in sys.argv[1:]:
        sys.exit(_sim_worker())
    if "--sim" in sys.argv[1:]:
        sys.exit(_sim_main())
    sys.exit(main())
