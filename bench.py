"""Headline benchmark for the driver: bf16 matmul TFLOP/s per chip.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline (BASELINE.md): the reference publishes no numbers, so the target is
BASELINE.json's north star — >=50% MFU on v5e => 98.5 bf16 TFLOP/s per chip.
``vs_baseline`` is achieved/98.5 (so 1.0 == the 50%-MFU target; 2.0 == peak).

On a multi-device backend this runs the pjit-sharded matmul over the full mesh
(per-chip TFLOP/s reported); on one device it runs the single-chip kernel. On
a CPU-only backend it still emits a (small, honest) measurement so the pipeline
never breaks.
"""

from __future__ import annotations

import json
import sys

BASELINE_TFLOPS = 98.5  # 50% MFU on v5e (197 bf16 peak) — BASELINE.md


def main() -> int:
    import jax

    from k3stpu.ops.matmul import measure_matmul, measure_pjit_matmul

    devices = jax.devices()
    on_accel = devices[0].platform != "cpu"
    dim = 8192 if on_accel else 512
    iters = 50 if on_accel else 5

    if len(devices) > 1:
        from k3stpu.parallel.mesh import make_mesh

        mesh = make_mesh(len(devices), model_parallelism=1,
                         axis_names=("data", "model"))
        res = measure_pjit_matmul(mesh, m=dim, n=dim, k=dim, iters=iters)
    else:
        res = measure_matmul(m=dim, n=dim, k=dim, iters=iters)

    print(json.dumps({
        "metric": "pjit_matmul_bf16_tflops_per_chip",
        "value": round(res.tflops, 2),
        "unit": "TFLOP/s/chip",
        "vs_baseline": round(res.tflops / BASELINE_TFLOPS, 4),
        "detail": res.to_dict(),
        "device_kind": getattr(devices[0], "device_kind", "unknown"),
        "n_devices": len(devices),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
