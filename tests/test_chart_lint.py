"""Structural lint of the rendered k3s-tpu chart (kubeval-lite).

Real `helm template` still can't execute in this environment (no helm
binary, no network, no Go toolchain to build one — see
docs/HELM_VALIDATION.md), so beyond the byte-goldens
(tests/test_chart.py) this suite validates what a cluster's admission
path would: every rendered document is well-formed YAML with the
Kubernetes object skeleton, names are DNS-1123, workload selectors
actually match their pod templates, container specs are complete, and
the values knobs land where the manifests consume them. These checks
run on BOTH value sets the goldens pin (default and core-8way), so a
template edit that renders syntactically-plausible-but-unschedulable
YAML fails here even when the goldens are regenerated alongside it.
"""

import re

import pytest
import yaml

from k3stpu.utils.helm_lite import render_chart
from tests.test_chart import CHART, CORE_8WAY_OVERRIDES

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_ENV_NAME = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")

WORKLOAD_KINDS = {"Deployment", "DaemonSet", "StatefulSet", "Job"}


def _docs(overrides=()):
    text = render_chart(CHART, overrides=dict(overrides))
    docs = [d for d in yaml.safe_load_all(text) if d is not None]
    assert docs, "chart rendered no documents"
    return docs


@pytest.fixture(scope="module", params=[
    (),  # chart defaults
    tuple(CORE_8WAY_OVERRIDES.items()),  # THE golden value set, imported
], ids=["default", "core-8way"])
def rendered(request):
    return _docs(request.param)


def test_every_doc_has_k8s_skeleton(rendered):
    for doc in rendered:
        assert set(doc) >= {"apiVersion", "kind", "metadata"}, doc.get(
            "kind", doc)
        name = doc["metadata"].get("name", "")
        assert name, f"unnamed {doc['kind']}"
        # RBAC names may contain ':'; every segment must be DNS-1123-ish.
        for seg in name.split(":"):
            assert _DNS1123.match(seg), f"bad name {name!r}"


def test_workload_selectors_match_pod_labels(rendered):
    for doc in rendered:
        if doc["kind"] not in WORKLOAD_KINDS:
            continue
        spec = doc["spec"]
        sel = spec.get("selector", {}).get("matchLabels", {})
        pod_labels = (spec.get("template", {}).get("metadata", {})
                      .get("labels", {}))
        assert sel, f"{doc['metadata']['name']}: empty selector"
        for k, v in sel.items():
            assert pod_labels.get(k) == v, (
                f"{doc['metadata']['name']}: selector {k}={v} does not "
                f"match pod labels {pod_labels} — the controller would "
                "reject or orphan its pods")


def test_containers_are_complete(rendered):
    for doc in rendered:
        if doc["kind"] not in WORKLOAD_KINDS:
            continue
        pod = doc["spec"]["template"]["spec"]
        assert pod.get("containers"), doc["metadata"]["name"]
        for c in pod["containers"]:
            assert _DNS1123.match(c["name"])
            assert c.get("image"), f"{c['name']}: no image"
            for env in c.get("env", ()):
                assert _ENV_NAME.match(env["name"]), env
                assert "value" in env or "valueFrom" in env, env
            for vm in c.get("volumeMounts", ()):
                vols = {v["name"] for v in pod.get("volumes", ())}
                assert vm["name"] in vols, (
                    f"{c['name']}: volumeMount {vm['name']} has no "
                    f"matching volume (have {sorted(vols)})")


def test_namespaced_objects_share_the_release_namespace(rendered):
    cluster_scoped = {"ClusterRole", "ClusterRoleBinding", "RuntimeClass",
                      "Namespace", "PriorityClass"}
    namespaces = {doc["metadata"].get("namespace")
                  for doc in rendered
                  if doc["kind"] not in cluster_scoped}
    assert len(namespaces) == 1, (
        f"namespaced objects disagree on namespace: {namespaces}")


def test_rbac_references_resolve(rendered):
    """Every RoleBinding/ClusterRoleBinding's roleRef and subjects point
    at objects this chart renders (the plugin must not depend on
    out-of-band RBAC)."""
    by_kind = {}
    for doc in rendered:
        by_kind.setdefault(doc["kind"], set()).add(doc["metadata"]["name"])
    for doc in rendered:
        if doc["kind"] not in ("RoleBinding", "ClusterRoleBinding"):
            continue
        ref = doc["roleRef"]
        assert ref["name"] in by_kind.get(ref["kind"], ()), (
            f"{doc['metadata']['name']}: roleRef {ref['kind']}/"
            f"{ref['name']} not rendered by this chart")
        for sub in doc.get("subjects", ()):
            if sub["kind"] == "ServiceAccount":
                assert sub["name"] in by_kind.get("ServiceAccount", ()), (
                    f"{doc['metadata']['name']}: subject SA {sub['name']} "
                    "not rendered")


def test_values_knobs_reach_the_manifests():
    """The reference's headline knob path (values.yaml:12-18 ->
    plugin config) must hold end-to-end through OUR chart: replicas and
    granularity land in the ConfigMap the plugin consumes."""
    docs = _docs((
        ("config.flags.granularity", "core"),
        ("config.sharing.timeSlicing.resources",
         "[{name: google.com/tpu, replicas: 6}]")))
    # Select by NAME, not render order: the chart ships two DaemonSets
    # and order is an accident of template filename sorting.
    by_name = {(d["kind"], d["metadata"]["name"]): d for d in docs}
    cm = by_name[("ConfigMap", "k3s-tpu-config")]
    # The embedded plugin config must carry the overridden knobs with
    # real YAML semantics (parse the embedded doc, don't substring it).
    cfg = yaml.safe_load(cm["data"]["config.yaml"])
    assert cfg["flags"]["granularity"] == "core"
    assert cfg["sharing"]["timeSlicing"]["resources"][0]["replicas"] == 6
    # And the device-plugin DaemonSet mounts that ConfigMap.
    ds = next(d for (k, n), d in by_name.items()
              if k == "DaemonSet" and "device-plugin" in n)
    vols = ds["spec"]["template"]["spec"].get("volumes", ())
    cm_names = {n for (k, n) in by_name if k == "ConfigMap"}
    assert any(v.get("configMap", {}).get("name") in cm_names
               for v in vols), (
        "DaemonSet does not mount the chart's ConfigMap — the sharing "
        "knobs would never reach the plugin binary")


def test_runtimeclass_is_referenced_or_standalone(rendered):
    """If the chart ships a RuntimeClass, workloads that need the TPU
    runtime must reference it by the rendered name."""
    rcs = [d for d in rendered if d["kind"] == "RuntimeClass"]
    if not rcs:
        pytest.skip("chart renders no RuntimeClass")
    names = {d["metadata"]["name"] for d in rcs}
    for doc in rendered:
        if doc["kind"] not in WORKLOAD_KINDS:
            continue
        rcn = doc["spec"]["template"]["spec"].get("runtimeClassName")
        if rcn is not None:
            assert rcn in names
