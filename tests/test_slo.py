"""SLO burn-rate engine (k3stpu/obs/slo.py): hand-computed fixtures.

The acceptance bar for this layer is that the burn-rate math is pinned
against hand-computed bucket fixtures for all four windows (5m/1h fast
pair, 6h/3d slow pair) — every expected value below is derived in the
comments, not from the code under test. The engine is deterministic by
design (explicit ``now`` everywhere), so these tests use a fixed epoch
and never touch the clock.
"""

import pytest

from k3stpu.obs.hist import Histogram
from k3stpu.obs.slo import (
    FAST_BURN_THRESHOLD,
    SLOW_BURN_THRESHOLD,
    WINDOWS,
    SloEngine,
    SloSpec,
    default_specs,
    merge_histograms,
)

NOW = 1_000_000.0


def _spec(**kw):
    kw.setdefault("target", 0.999)
    kw.setdefault("window_days", 30.0)
    return SloSpec("ttft", "k3stpu_request_ttft_seconds",
                   threshold_s=2.5, **kw)


# -- burn-rate math, all four windows ---------------------------------------


def test_burn_rates_all_four_windows_hand_computed():
    """Distinct per-segment traffic so every window's burn differs.

    Cumulative (t, good, total) snapshots; budget = 1 - 0.999 = 0.001:

      t = NOW-3d   good       0  total         0
      t = NOW-6h   good  899200  total   900000   (bad so far:  800)
      t = NOW-1h   good  989054  total   990000   (bad so far:  946)
      t = NOW-5m   good  998020  total   999000   (bad so far:  980)
      t = NOW      good  999000  total  1000000   (bad so far: 1000)

      5m window: delta vs the NOW-5m snap  -> bad  20 / 1000    = 0.02
                 burn = 0.02   / 0.001 = 20.0
      1h window: delta vs the NOW-1h snap  -> bad  54 / 10000   = 0.0054
                 burn = 0.0054 / 0.001 = 5.4
      6h window: delta vs the NOW-6h snap  -> bad 200 / 100000  = 0.002
                 burn = 0.002  / 0.001 = 2.0
      3d window: delta vs the NOW-3d snap  -> bad 1000 / 1e6    = 0.001
                 burn = 0.001  / 0.001 = 1.0
    """
    eng = SloEngine([_spec()])
    for dt, good, total in ((259200.0, 0, 0),
                            (21600.0, 899200, 900000),
                            (3600.0, 989054, 990000),
                            (300.0, 998020, 999000),
                            (0.0, 999000, 1000000)):
        eng.ingest_counts("ttft", good, total, NOW - dt)
    res = eng.evaluate(NOW)["ttft"]
    assert res["burn_rate"]["5m"] == pytest.approx(20.0)
    assert res["burn_rate"]["1h"] == pytest.approx(5.4)
    assert res["burn_rate"]["6h"] == pytest.approx(2.0)
    assert res["burn_rate"]["3d"] == pytest.approx(1.0)
    assert res["window_total"] == 1000000
    # Fast pair (5m AND 1h) is NOT paging here (1h under 14.4), but the
    # slow pair (6h AND 3d) is at/over 1x — exactly the "sustained
    # steady burn" ticket condition.
    assert not (res["burn_rate"]["5m"] > FAST_BURN_THRESHOLD
                and res["burn_rate"]["1h"] > FAST_BURN_THRESHOLD)
    assert (res["burn_rate"]["6h"] >= SLOW_BURN_THRESHOLD - 1e-9
            and res["burn_rate"]["3d"] >= SLOW_BURN_THRESHOLD - 1e-9)
    # Budget over the 30d window: series is only 3d old, so the delta
    # anchors at its oldest point -> bad_frac 0.001 = the whole budget.
    assert res["budget_remaining"] == pytest.approx(0.0)


def test_budget_remaining_partial_consumption():
    # 500 bad of 1e6 -> bad_frac 5e-4 -> consumed 0.5 of a 0.001 budget.
    eng = SloEngine([_spec()])
    eng.ingest_counts("ttft", 0, 0, NOW - 86400.0)
    eng.ingest_counts("ttft", 999500, 1000000, NOW)
    res = eng.evaluate(NOW)["ttft"]
    assert res["budget_remaining"] == pytest.approx(0.5)


def test_no_traffic_burns_nothing():
    eng = SloEngine([_spec()])
    res = eng.evaluate(NOW)["ttft"]
    assert all(res["burn_rate"][w] == 0.0 for w, _ in WINDOWS)
    assert res["budget_remaining"] == 1.0
    assert res["window_total"] == 0


def test_counter_reset_restarts_the_series():
    # A replica restart drops the cumulative counters; differencing
    # across it would invent negative traffic. The reset clears the
    # series, so the next evaluate sees a single-snapshot series (no
    # delta -> burn 0) instead of garbage.
    eng = SloEngine([_spec()])
    eng.ingest_counts("ttft", 100, 100, NOW - 600.0)
    eng.ingest_counts("ttft", 200, 200, NOW - 300.0)
    eng.ingest_counts("ttft", 10, 60, NOW)  # total went DOWN: reset
    res = eng.evaluate(NOW)["ttft"]
    assert all(res["burn_rate"][w] == 0.0 for w, _ in WINDOWS)


# -- bucket-conservative good counting --------------------------------------


def test_good_total_rounds_threshold_down_to_provable_bucket():
    spec = _spec()  # threshold 2.5 between bounds 2.0 and 4.0
    hist = {"bounds": [1.0, 2.0, 4.0], "cumulative": [5, 8, 9, 10],
            "sum": 20.0, "count": 10}
    # Largest bound <= 2.5 is 2.0 -> good = cum[1] = 8. The 9th request
    # (<= 4.0) MIGHT have met 2.5s, but is not provably good.
    assert spec.good_total(hist) == (8, 10)


def test_good_total_threshold_under_first_bound_is_none():
    spec = SloSpec("t", "m", threshold_s=0.5)
    hist = {"bounds": [1.0, 2.0], "cumulative": [1, 2, 3],
            "sum": 1.0, "count": 3}
    assert spec.good_total(hist) is None  # nothing provably good
    assert spec.good_total(None) is None  # family absent


def test_spec_validation():
    with pytest.raises(ValueError):
        SloSpec("t", "m", threshold_s=1.0, target=1.0)
    with pytest.raises(ValueError):
        SloSpec("t", "m", threshold_s=0.0)
    with pytest.raises(ValueError):
        SloSpec("t", "m", threshold_s=1.0, window_days=0.0)
    with pytest.raises(ValueError):
        SloEngine([_spec(), _spec()])  # duplicate names


# -- fleet merge + scrape-text ingest ---------------------------------------


def _ttft_hist():
    return Histogram("k3stpu_request_ttft_seconds", "test",
                     bounds=(1.0, 2.0, 4.0))


def test_merge_histograms_sums_and_drops_mismatched_bounds():
    a, b = _ttft_hist(), _ttft_hist()
    odd = Histogram("k3stpu_request_ttft_seconds", "test", bounds=(1.0,))
    for v in (0.5, 1.5):
        a.observe(v)
    b.observe(3.0)
    odd.observe(0.1)
    from k3stpu.obs.hist import parse_prometheus_histograms
    parsed = [parse_prometheus_histograms(h.render())
              for h in (a, b, odd)]
    m = merge_histograms(parsed, "k3stpu_request_ttft_seconds")
    assert m["bounds"] == [1.0, 2.0, 4.0]
    assert m["cumulative"] == [1, 2, 3, 3]  # odd replica dropped
    assert m["count"] == 3


def test_ingest_scrape_texts_end_to_end():
    # Two replicas serve 20 good requests (first snapshot), then one
    # serves 5 at 3.0 s (over the 2.5 s threshold). The trailing-5m
    # delta is those 5 requests, all bad: burn = (5/5) / 0.001 = 1000x
    # — well past the fast-burn page line.
    eng = SloEngine([_spec()])
    a, b = _ttft_hist(), _ttft_hist()
    for _ in range(10):
        a.observe(1.5)
        b.observe(1.5)
    eng.ingest([a.render(), b.render()], NOW - 300.0)
    for _ in range(5):
        a.observe(3.0)
    eng.ingest([a.render(), b.render()], NOW)
    res = eng.evaluate(NOW)["ttft"]
    assert res["burn_rate"]["5m"] == pytest.approx(1000.0)
    assert res["burn_rate"]["5m"] > FAST_BURN_THRESHOLD
    assert res["budget_remaining"] == 0.0
    assert res["window_total"] == 5


def test_ingest_skips_rounds_with_family_absent():
    eng = SloEngine([_spec()])
    eng.ingest(["# HELP x_total nope\n# TYPE x_total counter\n"
                "x_total 3\n"], NOW)
    assert eng._snaps["ttft"] == []


# -- exposition -------------------------------------------------------------


def test_render_prometheus_two_label_burn_series():
    eng = SloEngine([_spec()])
    eng.ingest_counts("ttft", 0, 0, NOW - 600.0)
    eng.ingest_counts("ttft", 999, 1000, NOW)
    eng.evaluate(NOW)
    text = eng.render_prometheus()
    assert "# TYPE k3stpu_slo_error_budget_remaining_ratio gauge" in text
    assert "# TYPE k3stpu_slo_burn_rate gauge" in text
    assert 'k3stpu_slo_error_budget_remaining_ratio{slo="ttft"}' in text
    for label, _ in WINDOWS:
        assert (f'k3stpu_slo_burn_rate{{slo="ttft",window="{label}"}}'
                in text)


def test_default_specs_mirror_chart_threshold():
    import os
    import re
    values = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "deploy", "charts", "k3s-tpu", "values.yaml")
    with open(values) as f:
        m = re.search(r"ttftP99SloSeconds:\s*([\d.]+)", f.read())
    assert m, "chart lost its TTFT threshold value"
    (spec,) = default_specs()
    assert spec.threshold_s == float(m.group(1))
    assert spec.metric == "k3stpu_request_ttft_seconds"
