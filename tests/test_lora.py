"""LoRA fine-tuning (k3stpu/models/lora.py).

Invariants: a fresh LoRA model computes exactly its base (B is zero);
frozen-base training moves ONLY the adapters; merging folds the learned
delta into plain Dense trees that the base config serves unchanged.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from k3stpu.models.lora import (
    lora_label_tree,
    lora_optimizer,
    merge_lora_params,
)
from k3stpu.models.transformer import transformer_lm_tiny


def _base_and_lora(rank=4):
    base = transformer_lm_tiny(max_seq_len=32)
    lora = type(base)(dataclasses.replace(base.config, lora_rank=rank))
    bvars = base.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                      train=False)
    lvars = lora.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                      train=False)

    # Graft the base kernels into the LoRA tree (same module paths).
    def graft(lt, bt):
        if isinstance(lt, dict):
            out = {}
            for k, v in lt.items():
                out[k] = v if k in ("lora_a", "lora_b") else graft(
                    v, bt[k])
            return out
        return bt

    lparams = graft(lvars["params"], bvars["params"])
    return base, bvars["params"], lora, lparams


def test_fresh_lora_equals_base():
    base, bparams, lora, lparams = _base_and_lora()
    toks = jax.random.randint(jax.random.key(1), (2, 16), 0,
                              base.config.vocab_size)
    ref = base.apply({"params": bparams}, toks, train=False)
    out = lora.apply({"params": lparams}, toks, train=False)
    assert jnp.allclose(out, ref, atol=1e-4), (
        float(jnp.max(jnp.abs(out - ref))))


def test_frozen_base_training_moves_only_adapters():
    _, _, lora, lparams = _base_and_lora()
    tx = lora_optimizer(optax.sgd(0.5))
    state = tx.init(lparams)
    toks = jax.random.randint(jax.random.key(2), (2, 16), 0,
                              lora.config.vocab_size)
    labels = jax.random.randint(jax.random.key(3), (2, 16), 0,
                                lora.config.vocab_size)

    def loss(p):
        logits = lora.apply({"params": p}, toks, train=False)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, labels[..., None], -1))

    l0 = float(loss(lparams))
    p = lparams
    for _ in range(3):
        grads = jax.grad(loss)(p)
        updates, state = tx.update(grads, state, p)
        p = optax.apply_updates(p, updates)
    l1 = float(loss(p))
    assert l1 < l0, f"LoRA training did not reduce loss ({l0} -> {l1})"

    labels_tree = lora_label_tree(lparams)
    flat0 = jax.tree_util.tree_flatten_with_path(lparams)[0]
    flat1 = jax.tree_util.tree_flatten_with_path(p)[0]
    lbls = jax.tree_util.tree_flatten_with_path(labels_tree)[0]
    moved_adapters = frozen_moved = 0
    for (path, v0), (_, v1), (_, lab) in zip(flat0, flat1, lbls):
        changed = not np.array_equal(np.asarray(v0), np.asarray(v1))
        if lab == "train":
            moved_adapters += changed
        else:
            frozen_moved += changed
    assert frozen_moved == 0, "a frozen base leaf moved"
    assert moved_adapters > 0, "no adapter moved"


def test_merge_serves_through_base_config():
    base, _, lora, lparams = _base_and_lora()
    # Train-free but non-trivial delta: poke lora_b away from zero.
    lparams = jax.tree_util.tree_map_with_path(
        lambda pth, x: (x + 0.01 if getattr(pth[-1], "key", "") == "lora_b"
                        else x), lparams)
    toks = jax.random.randint(jax.random.key(4), (2, 16), 0,
                              base.config.vocab_size)
    ref = lora.apply({"params": lparams}, toks, train=False)

    merged = merge_lora_params(lparams)
    flat_m = jax.tree_util.tree_flatten_with_path(merged)[0]
    base_init = base.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                          train=False)["params"]
    flat_b = jax.tree_util.tree_flatten_with_path(base_init)[0]
    assert [(p, v.shape) for p, v in flat_m] == \
           [(p, v.shape) for p, v in flat_b], "merged tree != base tree"

    out = base.apply({"params": merged}, toks, train=False)
    # bf16 path difference: the LoRA model rounds x@A@B separately, the
    # merged kernel rounds once — O(1e-1) absolute on O(1) logits.
    assert jnp.allclose(out, ref, atol=1e-1), (
        float(jnp.max(jnp.abs(out - ref))))


def test_quant_and_lora_are_exclusive():
    base = transformer_lm_tiny(max_seq_len=32)
    bad = type(base)(dataclasses.replace(base.config, lora_rank=4,
                                         quant="int8"))
    with pytest.raises(ValueError, match="merge"):
        bad.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                 train=False)


def test_pretrain_finetune_serve_loop(tmp_path):
    """The full workflow: base pretrain -> LoRA fine-tune warm-started
    from it (--init-from) -> serve the LoRA checkpoint, whose adapters
    the server detects and MERGES (not silently drops)."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)

    def run(extra):
        out = subprocess.run(
            [sys.executable, "-m", "k3stpu.parallel.train_job",
             "--steps", "2", "--ckpt-every", "2", *extra],
            env=env, capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, out.stderr[-2000:]
        return [json.loads(l) for l in out.stdout.splitlines()]

    base_dir, lora_dir = str(tmp_path / "base"), str(tmp_path / "lora")
    run(["--ckpt-dir", base_dir])
    events = run(["--ckpt-dir", lora_dir, "--lora-rank", "4",
                  "--init-from", base_dir])
    assert any(e["event"] == "init_from" for e in events)

    from k3stpu.serve.server import InferenceServer

    server = InferenceServer(model_name="transformer-tiny", seq_len=64,
                             batch_window_ms=0.0, shard_devices=1,
                             ckpt_dir=lora_dir)
    try:
        assert server.loaded_step == 2
        # Served tree is the BASE structure (adapters folded in).
        flat = jax.tree_util.tree_flatten_with_path(
            server._variables["params"])[0]
        leaf_names = {getattr(p[-1], "key", "") for p, _ in flat}
        assert "lora_a" not in leaf_names
        out = server.predict(np.zeros((1, 64), np.int32))
        assert np.all(np.isfinite(out))
    finally:
        server.close()
