"""Workload manifests: structural parity with the reference's YAML surface.

The reference ships three manifests (nvidia-smi.yaml, jellyfin.yaml, plus the
Helm values); ours must carry the same load-bearing fields with the TPU
resource/runtime names (SURVEY.md §2a #2-#4, §3.3-§3.5).
"""

import glob
import os

import yaml

MANIFEST_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "deploy", "manifests",
)


def load_all(name):
    with open(os.path.join(MANIFEST_DIR, name)) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def by_kind(docs, kind):
    return [d for d in docs if d.get("kind") == kind]


def test_all_manifests_parse():
    files = glob.glob(os.path.join(MANIFEST_DIR, "*.yaml"))
    assert files, "no manifests found"
    for path in files:
        with open(path) as f:
            docs = [d for d in yaml.safe_load_all(f) if d]
        assert docs, f"{path} contains no documents"
        for doc in docs:
            assert "kind" in doc and "apiVersion" in doc, path


def test_runtimeclass():
    (rc,) = load_all("runtimeclass-tpu.yaml")
    assert rc["kind"] == "RuntimeClass"
    assert rc["metadata"]["name"] == "tpu"
    assert rc["handler"] == "tpu"


def test_probe_pod_parity():
    # Parity with reference nvidia-smi.yaml:1-16.
    (pod,) = load_all("tpu-probe.yaml")
    assert pod["kind"] == "Pod"
    spec = pod["spec"]
    assert spec["runtimeClassName"] == "tpu"           # nvidia-smi.yaml:8
    assert spec["restartPolicy"] == "Never"            # nvidia-smi.yaml:9
    (ctr,) = spec["containers"]
    assert ctr["resources"]["limits"]["google.com/tpu"] == "1"  # :14-16
    assert ctr["command"][0] == "python"
    assert "k3stpu.probe" in ctr["command"]


def test_inference_deployment_parity():
    # Parity with reference jellyfin.yaml:1-43.
    docs = load_all("tpu-inference.yaml")
    (dep,) = by_kind(docs, "Deployment")
    spec = dep["spec"]
    assert spec["replicas"] == 1                        # jellyfin.yaml:10
    assert spec["progressDeadlineSeconds"] == 600       # jellyfin.yaml:11
    assert spec["revisionHistoryLimit"] == 0            # jellyfin.yaml:12
    assert spec["strategy"]["type"] == "Recreate"       # jellyfin.yaml:13-14
    pod = spec["template"]["spec"]
    assert pod["runtimeClassName"] == "tpu"             # jellyfin.yaml:23
    (ctr,) = pod["containers"]
    assert ctr["resources"]["limits"]["google.com/tpu"] == "1"  # :27-29

    (svc,) = by_kind(docs, "Service")
    (port,) = svc["spec"]["ports"]
    assert port["port"] == 8096                         # jellyfin.yaml:40-42
    assert svc["spec"]["selector"] == {"app": "tpu-inference"}
    assert spec["selector"]["matchLabels"] == {"app": "tpu-inference"}


def test_inference_pod_scrape_annotations():
    # The serving pod advertises its /metrics endpoint the standard way,
    # and the port annotation must agree with the Service port.
    docs = load_all("tpu-inference.yaml")
    (dep,) = by_kind(docs, "Deployment")
    ann = dep["spec"]["template"]["metadata"]["annotations"]
    assert ann["prometheus.io/scrape"] == "true"
    assert ann["prometheus.io/path"] == "/metrics"
    (svc,) = by_kind(docs, "Service")
    (port,) = svc["spec"]["ports"]
    assert ann["prometheus.io/port"] == str(port["port"])


def test_pjit_job_rendezvous_wiring():
    # SURVEY.md §3.5: indexed pods + headless Service rendezvous.
    docs = load_all("tpu-pjit-job.yaml")
    (svc,) = by_kind(docs, "Service")
    assert svc["spec"]["clusterIP"] == "None"           # headless
    svc_name = svc["metadata"]["name"]

    (job,) = by_kind(docs, "Job")
    spec = job["spec"]
    assert spec["completionMode"] == "Indexed"
    assert spec["completions"] == spec["parallelism"]
    pod = spec["template"]["spec"]
    assert pod["subdomain"] == svc_name                 # stable per-pod DNS
    assert pod["runtimeClassName"] == "tpu"
    assert svc["spec"]["selector"] == spec["template"]["metadata"]["labels"]

    (ctr,) = pod["containers"]
    env = {e["name"]: e.get("value") for e in ctr["env"]}
    assert env["K3STPU_NUM_PROCESSES"] == str(spec["completions"])
    assert env["K3STPU_COORDINATOR_SERVICE"] == svc_name
    (svc_port,) = svc["spec"]["ports"]
    assert env["K3STPU_COORDINATOR_PORT"] == str(svc_port["port"])
    assert "k3stpu.parallel.launch" in ctr["command"]
    # Multi-chip pod (values.yaml:15 analogue): whole host's chips.
    assert int(ctr["resources"]["limits"]["google.com/tpu"]) >= 1
    # Rendezvous teardown gets more than the 30s kubelet default.
    assert pod["terminationGracePeriodSeconds"] >= 60


def test_train_job_preemption_budget():
    """SIGTERM -> bounded emergency checkpoint -> exit: the pod's grace
    period must exceed the save bound (plus headroom) or kubelet SIGKILLs
    mid-save and the restart recomputes up to --ckpt-every steps."""
    docs = load_all("tpu-train-job.yaml")
    (job,) = by_kind(docs, "Job")
    spec = job["spec"]
    # Restarts ARE the recovery mechanism for a preemptible training Job.
    assert spec["backoffLimit"] >= 1
    pod = spec["template"]["spec"]
    grace = pod["terminationGracePeriodSeconds"]
    (ctr,) = pod["containers"]
    env = {e["name"]: e.get("value") for e in ctr["env"]}
    bound = float(env["K3STPU_PREEMPT_SAVE_BOUND_S"])
    assert grace >= bound + 15, (
        f"terminationGracePeriodSeconds={grace} must exceed the emergency-"
        f"save bound {bound}s with headroom for drain + log flush")
    # Long-running Job on a finite PVC: retention GC must be on.
    cmd = ctr["command"]
    assert "--keep-last" in cmd and int(cmd[cmd.index("--keep-last") + 1]) >= 2


def test_train_job_ignores_clean_preemption_exits():
    """A preemption exit (PREEMPTED_EXIT_CODE) means the pod checkpointed
    and left on purpose. The Job must retry it WITHOUT spending
    backoffLimit, or a flapping spot pool exhausts the budget with clean
    departures and the run dies restartable-but-unrestarted."""
    from k3stpu.parallel.train_job import PREEMPTED_EXIT_CODE

    docs = load_all("tpu-train-job.yaml")
    (job,) = by_kind(docs, "Job")
    spec = job["spec"]
    rules = spec["podFailurePolicy"]["rules"]
    ignored = [
        r for r in rules
        if r["action"] == "Ignore"
        and PREEMPTED_EXIT_CODE in r["onExitCodes"]["values"]
    ]
    (rule,) = ignored
    # The rule must name the training container explicitly: a sidecar
    # exiting 42 is not a preemption.
    (ctr,) = spec["template"]["spec"]["containers"]
    assert rule["onExitCodes"]["containerName"] == ctr["name"]
    assert rule["onExitCodes"]["operator"] == "In"


def test_train_job_scrape_and_telemetry_wiring():
    # Process 0 serves /metrics on --metrics-port (obs/train.py); the pod
    # annotations must advertise exactly that port, and it must not
    # collide with the rendezvous coordinator port. No Service port here:
    # only rank 0 listens, so scraping goes straight to the pod.
    docs = load_all("tpu-train-job.yaml")
    (job,) = by_kind(docs, "Job")
    ann = job["spec"]["template"]["metadata"]["annotations"]
    assert ann["prometheus.io/scrape"] == "true"
    assert ann["prometheus.io/path"] == "/metrics"
    pod = job["spec"]["template"]["spec"]
    (ctr,) = pod["containers"]
    cmd = ctr["command"]
    metrics_port = cmd[cmd.index("--metrics-port") + 1]
    assert ann["prometheus.io/port"] == metrics_port
    env = {e["name"]: e.get("value") for e in ctr["env"]}
    assert metrics_port != env["K3STPU_COORDINATOR_PORT"]
    # Telemetry drop file (utils/telemetry.py): every rank feeds its
    # busy-fraction to host tpu-info via the shared /run/k3stpu mount.
    mounts = {m["name"]: m["mountPath"] for m in ctr["volumeMounts"]}
    assert mounts["k3stpu-metrics"] == "/run/k3stpu"
    vols = {v["name"]: v for v in pod["volumes"]}
    assert vols["k3stpu-metrics"]["hostPath"]["path"] == "/run/k3stpu"
