"""bench.py must print exactly one JSON line with the driver's schema —
in every outcome: success, wedged backend (bounded + structured error),
or killed parent (no orphan left holding the chip claim)."""

import json
import os
import signal
import subprocess
import sys
import time

import bench

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_json_line():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ""  # drop the axon sitecustomize (forces TPU tunnel)
    env.pop("XLA_FLAGS", None)  # single CPU device -> single-chip path
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600,
    )
    assert out.returncode == 0, out.stderr
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"bench.py must print exactly one line, got: {lines}"
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
    assert rec["value"] > 0


def test_probe_cpu():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ""
    out = subprocess.run(
        [sys.executable, "-m", "k3stpu.probe", "--m", "256", "--iters", "2"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600,
    )
    assert out.returncode == 0, out.stderr
    assert "DEVICES_JSON" in out.stdout
    assert "BENCH_JSON" in out.stdout


def test_run_bounded_kills_on_timeout():
    from k3stpu.utils.subproc import run_bounded

    t0 = time.monotonic()
    rc, _, _ = run_bounded(
        [sys.executable, "-c", "import time; time.sleep(60)"], 1)
    assert rc is None
    assert time.monotonic() - t0 < 10


def test_no_retry_on_timeout_when_disabled():
    t0 = time.monotonic()
    ok, rc, _, _ = bench._run_with_retry(
        [sys.executable, "-c", "import time; time.sleep(60)"], 1,
        retry_on_timeout=False)
    assert not ok and rc is None
    # a single attempt: well under timeout + RETRY_WAIT_S + timeout
    assert time.monotonic() - t0 < 1 + bench.RETRY_WAIT_S


def test_retry_recovers_fast_failure(tmp_path):
    # rc=1 on the first run, rc=0 on the second — retry must recover it.
    marker = tmp_path / "once"
    prog = (f"import pathlib, sys\nm = pathlib.Path({str(marker)!r})\n"
            "if m.exists():\n    sys.exit(0)\nm.touch()\nsys.exit(1)")
    ok, rc, _, _ = bench._run_with_retry(
        [sys.executable, "-c", prog], 30, retry_on_timeout=False)
    assert ok and rc == 0


def test_wedged_probe_yields_structured_error_line(monkeypatch):
    """A probe that never returns must degrade to ONE parseable error
    line with stage/detail — never a traceback or a hang."""
    monkeypatch.setattr(bench, "_PROBE_SRC", "import time; time.sleep(60)")
    monkeypatch.setattr(bench, "PROBE_TIMEOUT_S", 1)
    monkeypatch.setattr(bench, "RETRY_WAIT_S", 0)
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench.main()
    assert rc == 0
    lines = [l for l in buf.getvalue().strip().splitlines() if l.strip()]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["stage"] == "backend_init"
    assert rec["value"] == 0.0 and "error" in rec and "detail" in rec


def test_probe_knobs_come_from_env():
    """K3STPU_BENCH_PROBE_TIMEOUT_S / _ATTEMPTS tune the flaky first
    tunnel contact without editing bench.py (read at import time)."""
    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu",
               K3STPU_BENCH_PROBE_TIMEOUT_S="7",
               K3STPU_BENCH_PROBE_ATTEMPTS="5")
    out = subprocess.run(
        [sys.executable, "-c",
         "import bench; print(bench.PROBE_TIMEOUT_S, bench.PROBE_ATTEMPTS)"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=60)
    assert out.returncode == 0, out.stderr
    assert out.stdout.split() == ["7", "5"]
    # attempts floor: a zero/negative override must not disable the probe
    env["K3STPU_BENCH_PROBE_ATTEMPTS"] = "0"
    out = subprocess.run(
        [sys.executable, "-c", "import bench; print(bench.PROBE_ATTEMPTS)"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=60)
    assert out.stdout.split() == ["1"]


def test_failure_line_carries_per_stage_wall_times(monkeypatch):
    """The error line must say where the time went: stage_s records each
    stage's cumulative wall time (all attempts) for triage."""
    monkeypatch.setattr(bench, "_PROBE_SRC", "import time; time.sleep(60)")
    monkeypatch.setattr(bench, "PROBE_TIMEOUT_S", 1)
    monkeypatch.setattr(bench, "PROBE_ATTEMPTS", 2)
    monkeypatch.setattr(bench, "RETRY_WAIT_S", 0)
    monkeypatch.setattr(bench, "_stage_s", {})
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert bench.main() == 0
    (line,) = [l for l in buf.getvalue().strip().splitlines() if l.strip()]
    rec = json.loads(line)
    assert rec["stage"] == "backend_init"
    assert "x2 attempts" in rec["detail"]
    # Two 1s-timeout attempts: cumulative stage time ~2s, rounded to 2dp.
    assert rec["stage_s"]["backend_init"] >= 1.5


def test_sigterm_parent_does_not_orphan_child():
    """Kill bench mid-probe (as an outer `timeout` would): the probe
    child — which on TPU would hold the chip claim — must die with it."""
    prog = (
        "import sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import bench\n"
        "bench._PROBE_SRC = 'import time; time.sleep(120)'\n"
        "bench.PROBE_TIMEOUT_S = 100\n"
        "sys.exit(bench.main())\n")
    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", prog], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True)
    try:
        time.sleep(3)  # let it spawn the probe child
        children = _pgrep_children(proc.pid)
        assert children, "probe child never started"
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=15)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and any(map(_alive, children)):
            time.sleep(0.5)
        survivors = [pid for pid in children if _alive(pid)]
    finally:
        for pid in _pgrep_children(proc.pid):
            _kill_quiet(pid)
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
    for pid in survivors:
        _kill_quiet(pid)
    assert not survivors, f"orphaned probe children: {survivors}"


def _pgrep_children(ppid):
    out = subprocess.run(["pgrep", "-P", str(ppid)],
                         capture_output=True, text=True)
    return [int(p) for p in out.stdout.split()]


def _alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _kill_quiet(pid):
    try:
        os.kill(pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
