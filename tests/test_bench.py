"""bench.py must print exactly one JSON line with the driver's schema."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_json_line():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ""  # drop the axon sitecustomize (forces TPU tunnel)
    env.pop("XLA_FLAGS", None)  # single CPU device -> single-chip path
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600,
    )
    assert out.returncode == 0, out.stderr
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"bench.py must print exactly one line, got: {lines}"
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec
    assert rec["value"] > 0


def test_probe_cpu():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ""
    out = subprocess.run(
        [sys.executable, "-m", "k3stpu.probe", "--m", "256", "--iters", "2"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600,
    )
    assert out.returncode == 0, out.stderr
    assert "DEVICES_JSON" in out.stdout
    assert "BENCH_JSON" in out.stdout
