"""Disaggregated prefill/decode serving (docs/DISAGG.md).

What is pinned here, in order:

1. The engine decomposition is behavior-free: ``k3stpu.serve.engine``
   still exports the full public surface (the shim over the scheduler /
   kv-manager / runner mixins), so every existing import site keeps
   working.
2. The KV handoff is BIT-EXACT: a chain exported by a prefill-role
   engine and imported by a decode-role engine yields token-identical
   greedy output to a monolithic run — on plain prompts, ragged
   batches, int8 KV pools, and under speculative decode. The mechanism
   makes this structural: ``import_chain`` installs the chain as an
   exact prompt-cache entry, so admission takes the same pcache-hit
   path the monolithic engine takes for a repeated prompt.
3. Every transfer failure (torn payload, checksum mismatch, chaos
   ``kv_transfer`` on either leg, dark prefill peer) degrades to a
   cold prefill with the SAME output, counted in
   ``transfer_fallbacks``, allocator invariants intact, loop alive —
   capacity loss, never correctness loss (docs/RESILIENCE.md).
4. The HTTP layer composes: a prefill-role server's ``/v1/prefill``
   feeds a decode-role server's pre-admission prefetch, one hop or
   two (the router's X-K3STPU-Prefill-Endpoint header).
"""

import json
import os
import subprocess
import sys
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.chaos import FaultInjector, InjectedFault
from k3stpu.models.transformer import transformer_lm_tiny
from k3stpu.serve import engine as engine_mod
from k3stpu.serve.engine import EngineOverloaded, GenerateEngine, _PageAllocator
from k3stpu.serve.kv_manager import KVManagerMixin
from k3stpu.serve.runner import ModelRunnerMixin
from k3stpu.serve.scheduler import SchedulerMixin
from k3stpu.serve.tiering import TierCorrupt, decode_entry, encode_entry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def mp():
    model = transformer_lm_tiny(max_seq_len=64)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                           train=False)
    return model, variables["params"]


def _engine(model, params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("seed", 0)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 24)
    kw.setdefault("prompt_cache", 4)
    return GenerateEngine(model, params, **kw)


def _assert_page_invariants(engine):
    """Idle-engine allocator accounting, checked exactly (the proof
    from tests/test_paged.py / test_tiering.py): every page's refcount
    equals its appearances across live slot chains plus prompt-cache
    pins — a failed import must never strand a pin or leak a page."""
    alloc = engine._alloc
    expect = {}
    for chain in engine._chains:
        for p in chain:
            expect[p] = expect.get(p, 0) + 1
    for entry in engine._pcache.values():
        for p in entry[0]:
            expect[p] = expect.get(p, 0) + 1
    for p in range(1, alloc.num_pages):
        assert alloc.refcount(p) == expect.get(p, 0), (
            f"page {p}: rc={alloc.refcount(p)} but "
            f"{expect.get(p, 0)} live references")
    assert alloc.free == alloc.total - sum(1 for v in expect.values()
                                           if v > 0)


# --- 1. the decomposition shim ------------------------------------------


def test_engine_module_is_the_compatibility_shim():
    """Every pre-decomposition import site spells
    ``k3stpu.serve.engine.X`` — the shim must keep that surface:
    GenerateEngine composes the three mixins, and the names the tests,
    server, and bench reach for still resolve from the old module."""
    assert issubclass(GenerateEngine, SchedulerMixin)
    assert issubclass(GenerateEngine, KVManagerMixin)
    assert issubclass(GenerateEngine, ModelRunnerMixin)
    for name in ("GenerateEngine", "EngineOverloaded", "_PageAllocator"):
        assert getattr(engine_mod, name) is not None
    assert EngineOverloaded is not None and _PageAllocator is not None
    # The disagg surface lives on the KV-manager layer and is reachable
    # through the composed class.
    for meth in ("export_chain", "import_chain", "note_transfer_fallback"):
        assert callable(getattr(GenerateEngine, meth))


# --- 2. bit-exactness of the handoff ------------------------------------


def test_export_import_roundtrip_bit_exact(mp):
    model, params = mp
    src, dst, mono = (_engine(model, params) for _ in range(3))
    try:
        p = [5, 6, 7, 8, 9, 10, 11, 12, 13]
        data = src.export_chain(p)
        assert isinstance(data, bytes) and len(data) > 4
        assert dst.import_chain(data)
        want = mono.submit([p], max_new_tokens=6)
        assert dst.submit([p], max_new_tokens=6) == want
        s = dst.stats()
        # The admission consumed the imported entry as an exact hit —
        # the decode replica never ran this prompt's prefill.
        assert s["kv_imports"] == 1 and s["pcache_hits"] == 1
        assert s["transfer_fallbacks"] == 0
        assert src.stats()["kv_exports"] == 1
        assert src.stats()["kv_transfer_bytes"] == len(data)
        # A repeated export reuses the staged entry (prefill replica's
        # steady state): same bytes, no second prefill.
        assert src.export_chain(p) == data
        _assert_page_invariants(src)
        _assert_page_invariants(dst)
    finally:
        for e in (src, dst, mono):
            e.close()


def test_disagg_ragged_batch_bit_exact(mp):
    """Imported chains of different lengths admitted as concurrent
    single-prompt requests — the decode loop interleaves them into one
    ragged decode batch (the disagg serving shape: the HTTP prefetch is
    per-request) — must decode token-identically to the monolithic
    engine, each admission an exact hit on its imported entry."""
    model, params = mp
    src, dst, mono = (_engine(model, params, slots=4) for _ in range(3))
    try:
        p1 = [5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15]
        p2 = [30, 31, 32]
        for p in (p1, p2):
            assert dst.import_chain(src.export_chain(p))
        want = {id(p1): mono.submit([p1], max_new_tokens=5),
                id(p2): mono.submit([p2], max_new_tokens=5)}
        got = {}
        threads = [threading.Thread(
            target=lambda p=p: got.__setitem__(
                id(p), dst.submit([p], max_new_tokens=5)))
            for p in (p1, p2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert got == want
        assert dst.stats()["pcache_hits"] == 2
        _assert_page_invariants(dst)
    finally:
        for e in (src, dst, mono):
            e.close()


def test_disagg_int8_pool_bit_exact():
    """The wire format carries whatever leaves the pool holds — int8
    pages and their scale planes round-trip bit-exactly too."""
    model = transformer_lm_tiny(max_seq_len=64, kv_cache_dtype="int8")
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    src, dst, mono = (_engine(model, params) for _ in range(3))
    try:
        p = [7, 8, 9, 10, 11, 12, 13]
        assert dst.import_chain(src.export_chain(p))
        want = mono.submit([p], max_new_tokens=6)
        assert dst.submit([p], max_new_tokens=6) == want
        assert dst.stats()["pcache_hits"] == 1
    finally:
        for e in (src, dst, mono):
            e.close()


def test_disagg_speculative_bit_exact(mp):
    """A speculative decode replica fed an imported chain must emit the
    monolithic speculative engine's exact tokens — the handoff hands
    over the same logits the draft/verify loop would have seen."""
    model, params = mp
    src = _engine(model, params)
    dst = _engine(model, params, slots=4, speculate=True)
    mono = _engine(model, params, slots=4, speculate=True)
    try:
        p = [5, 6, 7, 8, 9, 10, 11]
        assert dst.import_chain(src.export_chain(p))
        want = mono.submit([p], max_new_tokens=6)
        assert dst.submit([p], max_new_tokens=6) == want
        assert dst.stats()["pcache_hits"] == 1
    finally:
        for e in (src, dst, mono):
            e.close()


# --- 3. failure matrix: every torn transfer is a cold prefill -----------


def test_corrupt_transfer_degrades_to_cold_prefill(mp):
    model, params = mp
    src, dst, mono = (_engine(model, params) for _ in range(3))
    try:
        p = [5, 6, 7, 8, 9, 10, 11, 12, 13]
        data = src.export_chain(p)
        # Bit rot past the checksum prefix and a torn (truncated) copy:
        # both fail closed, counted, nothing installed.
        rotten = data[:4] + bytes(b ^ 0xFF for b in data[4:12]) + data[12:]
        assert dst.import_chain(rotten) is False
        assert dst.import_chain(data[:10]) is False
        s = dst.stats()
        assert s["transfer_fallbacks"] == 2 and s["kv_imports"] == 0
        assert len(dst._pcache) == 0
        _assert_page_invariants(dst)
        # The caller's contract: just submit — cold prefill, same tokens.
        want = mono.submit([p], max_new_tokens=6)
        assert dst.submit([p], max_new_tokens=6) == want
        assert dst.stats()["pcache_hits"] == 0
        # The wire layer itself names the failure when decoded directly.
        with pytest.raises(TierCorrupt):
            decode_entry(rotten)
    finally:
        for e in (src, dst, mono):
            e.close()


def test_chaos_kv_transfer_import_leg(mp):
    """Fault matrix row (docs/RESILIENCE.md): chaos ``kv_transfer`` on
    the import leg — request completes via cold prefill with exact
    output, ``transfer_fallbacks`` counted, no live-row corruption,
    loop alive for the next transfer."""
    model, params = mp
    inj = FaultInjector()
    src = _engine(model, params)
    dst = _engine(model, params, chaos=inj)
    mono = _engine(model, params)
    try:
        p = [5, 6, 7, 8, 9, 10, 11]
        data = src.export_chain(p)
        inj.arm("kv_transfer", times=1)
        assert dst.import_chain(data) is False
        assert inj.fired("kv_transfer") == 1
        s = dst.stats()
        assert s["transfer_fallbacks"] == 1 and s["kv_imports"] == 0
        want = mono.submit([p], max_new_tokens=6)
        assert dst.submit([p], max_new_tokens=6) == want
        _assert_page_invariants(dst)
        # Disarmed, the same bytes install fine — the loop survived.
        assert dst.import_chain(data)
        assert dst.stats()["kv_imports"] == 1
    finally:
        for e in (src, dst, mono):
            e.close()


def test_chaos_kv_transfer_export_leg(mp):
    """The export leg fails LOUDLY (the HTTP layer turns it into a
    non-200 so the decode peer falls back), and the prefill engine
    keeps serving afterwards."""
    model, params = mp
    inj = FaultInjector()
    src = _engine(model, params, chaos=inj)
    mono = _engine(model, params)
    try:
        p = [5, 6, 7, 8, 9]
        inj.arm("kv_transfer", times=1)
        with pytest.raises(InjectedFault):
            src.export_chain(p)
        assert src.stats()["kv_exports"] == 0
        _assert_page_invariants(src)
        # Loop alive: the engine still prefills, exports, and decodes.
        assert src.submit([p], max_new_tokens=4) \
            == mono.submit([p], max_new_tokens=4)
        assert isinstance(src.export_chain(p), bytes)
    finally:
        src.close()
        mono.close()


def test_import_guards_unpaged_and_oversized(mp):
    model, params = mp
    unpaged = GenerateEngine(model, params, slots=2, seed=0)
    paged = _engine(model, params)
    try:
        with pytest.raises(ValueError, match="paged"):
            unpaged.export_chain([1, 2, 3])
        with pytest.raises(ValueError, match="paged"):
            unpaged.import_chain(b"xxxx")
        with pytest.raises(ValueError):
            paged.export_chain([])
        with pytest.raises(ValueError):
            paged.export_chain(list(range(999)))  # exceeds max_seq
        # An oversized LENGTH smuggled inside a valid checksum still
        # fails closed at import (the malformed-payload guard).
        key = (0, tuple(range(70)))
        data = encode_entry(key, 70, {}, {})
        assert paged.import_chain(data) is False
        assert paged.stats()["transfer_fallbacks"] == 1
    finally:
        unpaged.close()
        paged.close()


# --- 4. the HTTP layer: /v1/prefill -> prefetch -> exact hit ------------


def _http_server(**kw):
    from http.server import ThreadingHTTPServer

    from k3stpu.serve.server import InferenceServer, make_app

    kw.setdefault("model_name", "transformer-tiny")
    kw.setdefault("seq_len", 128)
    kw.setdefault("batch_window_ms", 0.0)
    kw.setdefault("continuous_batching", True)
    kw.setdefault("decode_block", 2)
    kw.setdefault("prompt_cache", 8)
    kw.setdefault("kv_page_size", 16)
    kw.setdefault("kv_pages", 32)
    kw.setdefault("shard_devices", None)
    srv = InferenceServer(**kw)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(srv))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return srv, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _post_generate(url, prompt, n, headers=None):
    req = urllib.request.Request(
        url + "/v1/generate",
        data=json.dumps({"prompt_tokens": [prompt],
                         "max_new_tokens": n}).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())["tokens"][0]


def test_http_prefill_decode_handoff_bit_exact():
    """Full two-replica path: decode-role server prefetches from its
    --prefill-upstream peer's /v1/prefill, admission is an exact hit,
    output token-identical to a monolithic server; the router's
    per-request header overrides the static upstream."""
    pre, pre_httpd, pre_url = _http_server(instance="t-pre",
                                           role="prefill")
    dec, dec_httpd, dec_url = _http_server(instance="t-dec",
                                           role="decode",
                                           prefill_upstream=pre_url)
    mono, mono_httpd, mono_url = _http_server(instance="t-mono")
    try:
        rng = np.random.default_rng(7)
        p = rng.integers(1, 1000, size=(40,)).tolist()
        want = _post_generate(mono_url, p, 6)
        assert _post_generate(dec_url, p, 6) == want
        assert pre._engine.stats()["kv_exports"] == 1
        ds = dec._engine.stats()
        assert ds["kv_imports"] == 1 and ds["pcache_hits"] == 1
        assert ds["transfer_fallbacks"] == 0
        # Header-routed variant (the router's two-hop placement).
        p2 = p[::-1]
        want2 = _post_generate(mono_url, p2, 4)
        got2 = _post_generate(dec_url, p2, 4,
                              headers={"X-K3STPU-Prefill-Endpoint":
                                       pre_url})
        assert got2 == want2
        assert dec._engine.stats()["kv_imports"] == 2
        # Role is visible where operators look for it.
        with urllib.request.urlopen(pre_url + "/healthz", timeout=30) as r:
            assert json.loads(r.read())["role"] == "prefill"
    finally:
        for httpd in (pre_httpd, dec_httpd, mono_httpd):
            httpd.shutdown()
        for s in (pre, dec, mono):
            s.close()


def test_http_dark_prefill_peer_degrades_to_cold():
    """A decode replica whose prefill peer is down serves EXACT output
    via its own cold prefill — availability survives, the fallback is
    counted (the autoscaler/operator signal that capacity, not
    correctness, is degraded)."""
    dec, dec_httpd, dec_url = _http_server(
        instance="t-dark", role="decode",
        prefill_upstream="http://127.0.0.1:9")  # nothing listens here
    mono, mono_httpd, mono_url = _http_server(instance="t-mono2")
    try:
        dec._prefill_timeout_s = 2.0
        rng = np.random.default_rng(11)
        p = rng.integers(1, 1000, size=(24,)).tolist()
        want = _post_generate(mono_url, p, 5)
        assert _post_generate(dec_url, p, 5) == want
        ds = dec._engine.stats()
        assert ds["transfer_fallbacks"] == 1 and ds["kv_imports"] == 0
    finally:
        dec_httpd.shutdown()
        mono_httpd.shutdown()
        dec.close()
        mono.close()


def test_server_role_validation():
    from k3stpu.serve.server import InferenceServer

    with pytest.raises(ValueError, match="role"):
        InferenceServer(model_name="transformer-tiny", role="hybrid")
    # Roles require the paged-engine unit the handoff stages through.
    with pytest.raises(ValueError, match="continuous-batching"):
        InferenceServer(model_name="transformer-tiny", role="prefill")
    with pytest.raises(ValueError, match="prefill-upstream"):
        InferenceServer(model_name="transformer-tiny", seq_len=128,
                        continuous_batching=True, kv_page_size=16,
                        prompt_cache=8, role="prefill",
                        prefill_upstream="http://x:1")


# --- 5. the bench gate ---------------------------------------------------


@pytest.mark.slow
def test_serve_disagg_bench_gates():
    """bench.py --serve-disagg: one JSON line; disagg short-class p99
    TPOT <= 0.5x monolithic under mixed traffic (vs_baseline <= 1.0)
    and the 512-token KV handoff <= 1/3 of the cold prefill it saves."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ""
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--serve-disagg"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert out.returncode == 0, out.stderr
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"must print exactly one line, got: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "serve_disagg_short_tpot_ratio"
    assert rec["vs_baseline"] <= 1.0, rec
    d = rec["detail"]
    assert d["tpot_gate_passed"] and d["transfer_gate_passed"], d
    assert d["transfer_fallbacks"] == 0, d
