"""Speculative decoding in the continuous-batching engine + int8 paged KV
(k3stpu/serve/engine.py `speculate=True`, k3stpu/serve/speculative.py
NgramDrafter, models/transformer.py int8 paged pools).

The correctness bar is the same BIT-EXACTNESS contract test_paged.py
holds the paged pool to: an engine with `speculate=True` must emit
exactly the tokens the plain engine (and solo `generate()`) emits —
greedy, across ragged batches, every prompt-cache path, eos early
release, and near the max_seq headroom gate. Speculation may only ever
change HOW MANY dispatches produce those tokens, never which tokens.
Each exactness test also asserts `spec_accepted > 0` (or the gate's
`spec_dispatches == 0`) so a speculative path that silently never
engages can't pass vacuously.

The int8-paged-KV half: per-page absmax scales must make the paged
int8 pool compute the same attention as the dense int8 cache, drift
against the fp pool must stay inside the documented bound
(docs/SPECULATIVE.md), and a fixed HBM budget must buy >= 2x the pages
vs fp32 — checked against the engine's measured per-page bytes, not
just the planning formula. CPU-JAX stand-in per SURVEY.md §4.

Engine economy: each GenerateEngine compiles its own jitted programs
(bound methods, self static), and the full suite already runs near the
single-process XLA:CPU compile-state horizon run_suite.sh documents —
so the exactness tests SHARE one module-scoped engine pair instead of
building fresh engines per test. The shared pair makes two tests
order-sensitive (noted inline): the sampled-gate test must see equal
dispatch histories on both engines, so it runs before any greedy
speculation desyncs the sampling-key folds.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.models.generate import (
    generate,
    init_cache,
    paged_model,
    set_cache_index,
)
from k3stpu.models.quant import kv_page_bytes, kv_pages_for_budget
from k3stpu.models.transformer import transformer_lm_tiny
from k3stpu.serve.engine import GenerateEngine
from k3stpu.serve.programs import decode_core
from k3stpu.serve.speculative import NgramDrafter


@pytest.fixture(scope="module")
def mp():
    model = transformer_lm_tiny(max_seq_len=64)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                           train=False)
    yield model, variables["params"]
    # Drop this module's compiled executables once it finishes: the
    # single-process full suite already runs near the XLA:CPU
    # compile-state horizon run_suite.sh documents, and the ~10 engines
    # this module builds are enough headroom to push a LATER module's
    # compile over it (observed as a segfault inside the compilation-
    # cache read in test_transformer). The persistent disk cache
    # (tests/conftest.py) keeps any re-warm cheap.
    jax.clear_caches()


@pytest.fixture(scope="module")
def pair(mp):
    """ONE plain paged engine and ONE speculative paged engine with
    identical scheduling parameters, shared by every exactness test
    (compile economy — see the module docstring). Same seed => the
    sampling-key folds match while dispatch histories match."""
    model, params = mp
    plain = GenerateEngine(model, params, seed=0, page_size=8, slots=4)
    spec = GenerateEngine(model, params, seed=0, page_size=8, slots=4,
                          speculate=True)
    yield plain, spec
    plain.close()
    spec.close()


def _solo(model, params, prompt, budget):
    out = generate(model, params,
                   jnp.asarray(np.array([prompt], np.int32)),
                   jnp.array([len(prompt)], jnp.int32), budget,
                   temperature=0.0)
    return np.asarray(out)[0].tolist()


def _assert_page_invariants(engine):
    # Same exact-accounting check as test_paged._assert_page_invariants
    # (duplicated: test modules aren't importable from each other).
    alloc = engine._alloc
    expect = {}
    for chain in engine._chains:
        for p in chain:
            expect[p] = expect.get(p, 0) + 1
    for entry in engine._pcache.values():
        for p in entry[0]:
            expect[p] = expect.get(p, 0) + 1
    for p in range(1, alloc.num_pages):
        assert alloc.refcount(p) == expect.get(p, 0), (
            f"page {p}: rc={alloc.refcount(p)} but "
            f"{expect.get(p, 0)} live references")
    assert alloc.free == alloc.total - sum(1 for v in expect.values()
                                           if v > 0)


# A prompt whose suffix recurs — the n-gram drafter proposes on these,
# so speculation actually engages (asserted, never assumed).
def _rep(a, b, reps=8):
    return [a, b] * reps


# --- NgramDrafter units (pure host, no jax) -----------------------------


def test_drafter_validation():
    with pytest.raises(ValueError):
        NgramDrafter(max_ngram=2, min_ngram=3)
    with pytest.raises(ValueError):
        NgramDrafter(min_ngram=0)
    with pytest.raises(ValueError):
        NgramDrafter(max_ngram=3, window=3)   # window < max_ngram + 1


def test_drafter_proposes_repeating_continuation():
    d = NgramDrafter()
    # suffix [1, 2] recurred; its earlier continuation is [3, 1, 2, 3...]
    hist = [1, 2, 3, 1, 2, 3, 1, 2]
    assert d.propose(hist, 3) == [3, 1, 2]
    assert d.propose(hist, 1) == [3]


def test_drafter_prefers_full_depth_continuation():
    """A run of one repeated token matches right at the end with almost
    no continuation room; an earlier occurrence with the full depth of
    continuation must win over that nearer partial match."""
    d = NgramDrafter(max_ngram=2, min_ngram=2)
    hist = [7, 7, 7, 7, 7]
    # suffix [7,7] at i=0 has depth-3 continuation [7,7,7]; the i=2
    # match only offers [7]. Full depth preferred.
    assert d.propose(hist, 3) == [7, 7, 7]


def test_drafter_latest_full_match_wins():
    d = NgramDrafter(max_ngram=2, min_ngram=2)
    #       [5,6]->9 ....... [5,6]->4 ....... [5,6]?
    hist = [5, 6, 9, 1, 1, 5, 6, 4, 1, 1, 5, 6]
    assert d.propose(hist, 1) == [4], "latest earlier occurrence wins"


def test_drafter_min_ngram_fallback():
    d = NgramDrafter(max_ngram=3, min_ngram=2)
    # No 3-gram recurs, but the 2-gram suffix [1, 2] does.
    hist = [1, 2, 8, 9, 1, 2]
    assert d.propose(hist, 1) == [8]


def test_drafter_no_match_and_zero_depth():
    d = NgramDrafter()
    assert d.propose([1, 2, 3, 4, 5], 4) == []      # nothing recurs
    assert d.propose([1, 2, 3, 1, 2], 0) == []      # no depth asked
    assert d.propose([], 4) == []


def test_drafter_window_bounds_the_scan():
    d = NgramDrafter(max_ngram=2, min_ngram=2, window=8)
    # The only recurrence of the suffix lies outside the last 8 tokens.
    hist = [5, 6, 7] + [1, 2, 3, 4] * 3
    assert hist[-8:].count(5) == 0
    assert d.propose(hist, 2) == [1, 2]             # in-window match
    hist2 = [5, 6, 9] + list(range(10, 19)) + [5, 6]
    assert d.propose(hist2, 1) == []                # match aged out


# --- constructor contract ----------------------------------------------


def test_speculate_requires_paged_cache(mp):
    model, params = mp
    with pytest.raises(ValueError, match="page_size"):
        GenerateEngine(model, params, speculate=True)
    with pytest.raises(ValueError, match="spec_gamma"):
        GenerateEngine(model, params, page_size=8, speculate=True,
                       spec_gamma=0)


# --- bit-exactness: speculative == plain == solo generate() -------------
# (shared `pair` fixture: tests below run in file order by design)


def test_spec_sampled_requests_take_plain_path(pair):
    """Speculative verify is greedy-only; sampled traffic must take the
    plain path and stay bit-identical to the plain engine. MUST run
    before any greedy test on the shared pair: the comparison needs
    equal dispatch histories (the sampling key folds on the dispatch
    counter, which greedy speculation advances differently)."""
    plain, spec = pair
    for kw in ({"temperature": 0.9, "top_k": 20},
               {"temperature": 1.0, "top_p": 0.9}):
        want = plain.submit([_rep(9, 10), [4, 5]], max_new_tokens=8,
                            **kw)
        assert spec.submit([_rep(9, 10), [4, 5]], max_new_tokens=8,
                           **kw) == want
    assert spec.stats()["spec_dispatches"] == 0, (
        "greedy-only gate must keep sampled batches off the "
        "speculative path")


def test_spec_matches_plain_greedy_repetitive(mp, pair):
    model, params = mp
    plain, spec = pair
    cases = [
        [_rep(5, 9)],
        [_rep(3, 4, reps=6), _rep(11, 12, reps=9)],    # ragged batch
        [_rep(7, 7, reps=5), [40] * 12, _rep(2, 8)],   # 3 rows
    ]
    for prompts in cases:
        want = plain.submit(prompts, max_new_tokens=8)
        assert spec.submit(prompts, max_new_tokens=8) == want
        # plain itself is pinned to solo generate() — anchor the
        # chain so a shared bug in both engines can't hide.
        for w, p in zip(want, prompts):
            assert w == _solo(model, params, p, 8)
    s = spec.stats()
    assert s["spec_dispatches"] > 0 and s["spec_accepted"] > 0, (
        "speculation never engaged — exactness checked nothing")
    assert s["spec_fallbacks"] == 0
    assert plain.stats()["spec_dispatches"] == 0
    # The perf claim at its weakest useful form: strictly fewer verify
    # dispatches than tokens they emitted (accepted-tokens/dispatch>1).
    assert s["spec_emitted"] > s["spec_dispatches"]
    assert s["spec_tokens_per_dispatch"] > 1.0
    assert 0.0 < s["spec_accept_rate"] <= 1.0
    _assert_page_invariants(spec)


def test_spec_eos_early_release_exact(mp, pair):
    """A row finishing on eos mid-speculation must release exactly like
    the plain engine: same (eos-padded) output, pages back to the pool,
    ragged budgets across the batch."""
    model, params = mp
    plain, spec = pair
    prompt = _rep(5, 9)
    sol = _solo(model, params, prompt, 10)
    eos = sol[4]                        # force a mid-generation stop
    want = plain.submit([prompt], max_new_tokens=10, eos_id=eos)
    assert spec.submit([prompt], max_new_tokens=10, eos_id=eos) == want
    # Ragged budgets: one row stops on eos while its sibling runs.
    free0 = spec.stats()["pages_free"]
    accepted0 = spec.stats()["spec_accepted"]
    want = plain.submit([prompt, _rep(11, 12)], max_new_tokens=9,
                        eos_id=eos)
    assert spec.submit([prompt, _rep(11, 12)], max_new_tokens=9,
                       eos_id=eos) == want
    assert spec.stats()["pages_free"] == free0, (
        "early-released rows must return their pages")
    assert spec.stats()["spec_accepted"] > accepted0
    _assert_page_invariants(spec)


def test_spec_max_seq_headroom_gate_exact(mp, pair):
    """Rows whose verify chunk would cross max_seq must fall back to
    plain decode for those dispatches — a static W-wide write past the
    last page would clamp into the row's own tail and corrupt the same
    dispatch's attention. Output must run exact right up to a full
    cache."""
    model, params = mp
    plain, spec = pair
    prompt = _rep(5, 9, reps=15) + [5]  # 31 toks (width bucket 32)
    budget = 64 - 32                    # fill the cache to the brim:
    #                                     final index 31 + 32 = 63,
    #                                     so late dispatches trip the
    #                                     idx + W > max_seq gate
    accepted0 = spec.stats()["spec_accepted"]
    want = plain.submit([prompt], max_new_tokens=budget)
    assert spec.submit([prompt], max_new_tokens=budget) == want
    assert want[0] == _solo(model, params, prompt, budget)
    assert spec.stats()["spec_accepted"] > accepted0, (
        "gate must not disable speculation")


def test_spec_matches_plain_prompt_cache_paths(mp):
    """Miss, exact hit, and prefix hit (COW tail page) stay bit-exact
    under speculation AND take the same cache path (counters compared,
    not just tokens). Own engine pair: the shared one has no prompt
    cache."""
    model, params = mp
    plain = GenerateEngine(model, params, seed=0, page_size=8, slots=4,
                           prompt_cache=4)
    spec = GenerateEngine(model, params, seed=0, page_size=8, slots=4,
                          prompt_cache=4, speculate=True)
    try:
        prompt = _rep(5, 6, reps=5) + [5]      # 11 toks: partial tail
        # miss -> insert
        want = plain.submit([prompt], max_new_tokens=6)
        assert spec.submit([prompt], max_new_tokens=6) == want
        # exact hit: same prompt again
        want = plain.submit([prompt], max_new_tokens=6)
        assert spec.submit([prompt], max_new_tokens=6) == want
        # prefix hit: cached prompt + a repetitive tail (COW on the
        # shared partial page, then speculative extends past it)
        ext = prompt + [6, 5, 6]
        want = plain.submit([ext], max_new_tokens=6)
        assert spec.submit([ext], max_new_tokens=6) == want
        ps, ss = plain.stats(), spec.stats()
        for k in ("pcache_hits", "pcache_prefix_hits", "pcache_misses"):
            assert ss[k] == ps[k], (k, ss[k], ps[k])
        assert ss["pcache_hits"] >= 1 and ss["pcache_prefix_hits"] >= 1
        assert ss["spec_accepted"] > 0
        _assert_page_invariants(spec)
    finally:
        plain.close()
        spec.close()


def test_spec_zero_steady_state_recompiles(mp):
    """The verify program takes a static (slots, gamma+1) chunk, so
    after one warmup pass steady-state speculative traffic — different
    tokens, depths, acceptance patterns, cache paths — must hit the jit
    cache every time. Own engine: the count must start from this
    engine's warmup."""
    model, params = mp

    def jit_cache_total():
        return sum(f._cache_size() for f in vars(GenerateEngine).values()
                   if hasattr(f, "_cache_size"))

    engine = GenerateEngine(model, params, slots=4, seed=0,
                            prompt_cache=4, page_size=8, speculate=True)
    try:
        def traffic(a, b):
            p = _rep(a, b, reps=5)
            engine.submit([p], max_new_tokens=6)
            engine.submit([p], max_new_tokens=6)              # exact hit
            engine.submit([p + [a, b, a]], max_new_tokens=6)  # prefix hit
            engine.submit([[a, b], _rep(b, a, reps=4)],
                          max_new_tokens=5)                   # ragged

        traffic(5, 9)                    # warmup: compiles everything,
        #                                  including the verify program
        assert engine.stats()["spec_dispatches"] > 0
        before = jit_cache_total()
        for a, b in ((60, 61), (120, 121), (180, 181)):
            traffic(a, b)
        assert jit_cache_total() == before, (
            "steady-state speculative traffic recompiled a program")
        _assert_page_invariants(engine)
    finally:
        engine.close()


# --- int8 paged KV ------------------------------------------------------


def _int8_variant(model):
    return type(model)(dataclasses.replace(model.config,
                                           kv_cache_dtype="int8"))


def test_spec_int8_paged_matches_dense_int8(mp):
    """Same storage dtype, paged-with-per-page-scales vs dense: token
    streams must be identical — the paged int8 layout (int8 value pages
    + fp32 scale pages) may not change the computed attention. Float
    params drop in unchanged (cache dtype is storage-only)."""
    model, params = mp
    qmodel = _int8_variant(model)
    dense = GenerateEngine(qmodel, params, slots=4, seed=0)
    spec = GenerateEngine(qmodel, params, slots=4, seed=0, page_size=8,
                          speculate=True)
    try:
        for prompts in ([_rep(5, 9)],
                        [_rep(3, 4, reps=6), _rep(11, 12, reps=9)]):
            want = dense.submit(prompts, max_new_tokens=8)
            assert spec.submit(prompts, max_new_tokens=8) == want
        assert spec.stats()["spec_accepted"] > 0
        _assert_page_invariants(spec)
    finally:
        dense.close()
        spec.close()


def _paged_decode_logits(model, params, prompt, *, page_size=8):
    """Last-step logits of `prompt` fed token-by-token through the
    model's PAGED decode path (the engine's storage layout, without the
    engine): one row, block table over pages 1..n_bt, index advanced
    explicitly like the engine's host mirror."""
    cfg = getattr(model.config, "base", model.config)
    n_bt = cfg.max_seq_len // page_size
    pmod = paged_model(model, num_pages=1 + n_bt, page_size=page_size)
    cache = init_cache(pmod, 1)
    bt = jnp.asarray(np.arange(1, 1 + n_bt, dtype=np.int32)[None, :])
    logits = None
    for i, t in enumerate(prompt):
        cache = set_cache_index(cache, jnp.full((1,), i, jnp.int32))
        cache, logits = decode_core(pmod, params, cache,
                                    jnp.asarray([t], jnp.int32),
                                    block_tables=bt)
    return np.asarray(logits, np.float32)[0]


def test_int8_paged_drift_bound_vs_fp_pool(mp):
    """The documented drift guarantee (docs/SPECULATIVE.md): per-page
    absmax int8 storage keeps decode logits within a bounded relative
    error of the fp paged pool — same bound test_quant.py holds the
    dense int8 cache to, here asserted against the PAGED layout whose
    scales live in separate fp32 pages."""
    model, params = mp
    prompt = [3, 7, 1, 9, 4, 2, 8, 6, 5, 1, 7, 3]
    lf = _paged_decode_logits(model, params, prompt)
    lq = _paged_decode_logits(_int8_variant(model), params, prompt)
    err = float(np.max(np.abs(lf - lq)))
    span = float(np.max(np.abs(lf))) + 1e-6
    assert err / span < 0.15, f"paged int8 drift {err/span:.3f} vs fp"
    # And the per-page scales are faithful to the DENSE int8 cache: the
    # paged layout quantizes per (token, kv-head) exactly like dense,
    # so the two int8 paths must agree far tighter than the fp bound.
    qmodel = _int8_variant(model)
    dq_cache = init_cache(qmodel, 1)
    dq = None
    for i, t in enumerate(prompt):
        dq_cache = set_cache_index(dq_cache, jnp.full((1,), i, jnp.int32))
        dq_cache, dq = decode_core(qmodel, params, dq_cache,
                                   jnp.asarray([t], jnp.int32))
    dq = np.asarray(dq, np.float32)[0]
    assert float(np.max(np.abs(dq - lq))) / span < 0.02


def test_int8_doubles_pages_at_fixed_byte_budget(mp):
    """Same HBM budget, same model: kv_cache_dtype='int8' must buy
    >= 2x the pages of an fp32 pool (4x at large head_dim; 3.2x at this
    model's head_dim 16), the planning formula must equal the engine's
    MEASURED per-page bytes, and the pool gauges must reflect the
    bigger pool."""
    model, params = mp
    ps = 16
    cfg32 = dataclasses.replace(model.config, dtype=jnp.float32)
    cfg8 = dataclasses.replace(model.config, kv_cache_dtype="int8")
    budget = 40 * kv_page_bytes(cfg32, ps)          # fixed byte budget
    n32 = kv_pages_for_budget(budget, cfg32, ps)
    n8 = kv_pages_for_budget(budget, cfg8, ps)
    assert n32 == 40
    assert n8 >= 2 * n32, (n8, n32)
    # Gauges: an int8 engine built at that budget reports the larger
    # pool, its measured per-page bytes equal the planning formula
    # (float engines asserted in test_paged's tier via _page_bytes),
    # and the pool stays inside the budget. Construction only — the
    # int8 pool's correctness under traffic is the exactness test
    # above, and engine programs compile per instance (run_suite.sh
    # compile-state horizon).
    eng = GenerateEngine(_int8_variant(model), params, slots=2,
                         page_size=ps, num_pages=n8, speculate=True)
    try:
        s = eng.stats()
        assert s["pages_total"] == n8 - 1           # sink excluded
        assert s["pages_free"] == n8 - 1
        assert eng._page_bytes == kv_page_bytes(cfg8, ps)
        assert eng._page_bytes * n8 <= budget
    finally:
        eng.close()
    fpe = GenerateEngine(model, params, slots=2, page_size=ps)
    try:
        assert fpe._page_bytes == kv_page_bytes(model.config, ps)
    finally:
        fpe.close()
