"""tpu-info CLI: the nvidia-smi parity tool against the fake host tree."""

import json
import os
import subprocess
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUILD_DIR = os.path.join(REPO, "native", "build")
BIN = os.path.join(BUILD_DIR, "tpu-info")


@pytest.fixture(scope="session")
def info_bin():
    subprocess.run(["cmake", "-S", os.path.join(REPO, "native"), "-B",
                    BUILD_DIR], check=True, capture_output=True)
    subprocess.run(["cmake", "--build", BUILD_DIR, "--target", "tpu-info"],
                   check=True, capture_output=True)
    return BIN


def test_json_inventory(info_bin, fake_host_root):
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    assert out.returncode == 0
    doc = json.loads(out.stdout)
    assert doc["chip_count"] == 4
    assert doc["topology"] == "2x2"
    assert doc["libtpu"] == "/usr/lib/libtpu.so"
    gens = {c["generation"] for c in doc["chips"]}
    assert gens == {"tpu-v5e"}
    assert doc["chips"][0]["dev_paths"] == ["/dev/accel0"]


def test_human_table(info_bin, fake_host_root):
    out = subprocess.run([info_bin, "--host-root", str(fake_host_root)],
                         capture_output=True, text=True)
    assert out.returncode == 0
    assert "chips: 4" in out.stdout
    assert "tpu-v5e" in out.stdout
    assert "/dev/accel0" in out.stdout


def test_exit_code_no_chips(info_bin, tmp_path):
    out = subprocess.run([info_bin, "--host-root", str(tmp_path)],
                         capture_output=True, text=True)
    assert out.returncode == 1  # nvidia-smi-style: nonzero when no devices


def test_usage_error(info_bin):
    out = subprocess.run([info_bin, "--bogus"], capture_output=True, text=True)
    assert out.returncode == 2


def test_live_columns_na_without_sources(info_bin, fake_host_root):
    # No sysfs attrs, no drop file: used/util are "n/a" but the capacity
    # column still shows the generation's HBM size (v5e = 16 GiB).
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    doc = json.loads(out.stdout)
    for c in doc["chips"]:
        assert c["mem_used_bytes"] == -1
        assert c["duty_cycle_pct"] == -1
        assert c["mem_total_bytes"] == 16 * 1024**3
    human = subprocess.run([info_bin, "--host-root", str(fake_host_root)],
                           capture_output=True, text=True).stdout
    assert "UTIL" in human and "MEMORY" in human
    assert "n/a / 16384MiB" in human


def test_live_columns_from_sysfs_attrs(info_bin, fake_host_root):
    # Driver-exposed per-chip attributes are authoritative when present.
    pci = fake_host_root / "sys" / "bus" / "pci" / "devices" / "0000:00:04.0"
    (pci / "tpu_mem_used_bytes").write_text(f"{512 * 1024**2}\n")
    (pci / "tpu_mem_total_bytes").write_text(f"{16 * 1024**3}\n")
    (pci / "tpu_duty_cycle_pct").write_text("37\n")
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    chip0 = json.loads(out.stdout)["chips"][0]
    assert chip0["mem_used_bytes"] == 512 * 1024**2
    assert chip0["duty_cycle_pct"] == 37
    human = subprocess.run([info_bin, "--host-root", str(fake_host_root)],
                           capture_output=True, text=True).stdout
    assert "512MiB / 16384MiB" in human
    assert "37%" in human


def test_live_columns_from_metrics_drop_file(info_bin, fake_host_root):
    # Workload-exported drop file (k3stpu/utils/telemetry.py) fills chips
    # that have no sysfs attrs, matched by device index.
    run_dir = fake_host_root / "run" / "k3stpu"
    run_dir.mkdir(parents=True)
    (run_dir / "metrics.json").write_text(json.dumps({
        "ts": int(time.time()),  # fresh: stale drops are ignored
        "devices": [
            {"index": 1, "bytes_in_use": 1024**3,
             "bytes_limit": 16 * 1024**3, "duty_cycle_pct": 83},
        ],
    }))
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    chips = json.loads(out.stdout)["chips"]
    assert chips[1]["mem_used_bytes"] == 1024**3
    assert chips[1]["duty_cycle_pct"] == 83
    assert chips[0]["mem_used_bytes"] == -1  # untouched


def test_malformed_drop_file_ignored(info_bin, fake_host_root):
    run_dir = fake_host_root / "run" / "k3stpu"
    run_dir.mkdir(parents=True)
    (run_dir / "metrics.json").write_text("{not json")
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    assert out.returncode == 0
    assert json.loads(out.stdout)["chips"][0]["mem_used_bytes"] == -1


def test_telemetry_writer_roundtrip(info_bin, fake_host_root):
    # The python exporter's file is exactly what the C++ reader consumes.
    from k3stpu.utils.telemetry import write_metrics

    run_dir = fake_host_root / "run" / "k3stpu"
    payload = write_metrics(str(run_dir / "metrics.json"), duty_cycle_pct=12)
    assert payload["devices"], "no local jax devices"
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    chips = json.loads(out.stdout)["chips"]
    # CPU backend reports bytes_in_use on some builds and -1 on others;
    # duty cycle must round-trip verbatim for matching indices.
    by_idx = {d["index"]: d for d in payload["devices"]}
    for c in chips:
        if c["index"] in by_idx and by_idx[c["index"]]["duty_cycle_pct"] >= 0:
            assert c["duty_cycle_pct"] == 12


def _empty_stats_dev(real):
    """Fake device: real identity (so device_set membership works) but
    empty PJRT memory_stats — the relayed-backend shape that forces the
    live-arrays fallback."""

    class EmptyStatsDev:
        id = real.id
        device_kind = "TPU v5 lite"

        def memory_stats(self):
            return {}

        def __eq__(self, other):
            return other == real or other is self

        def __hash__(self):
            return hash(real)

    return EmptyStatsDev()


def test_telemetry_live_arrays_fallback(monkeypatch):
    """When PJRT memory_stats() is empty (the relayed backend returns {}),
    bytes_in_use falls back to summing this process's live jax arrays on
    the device — an honest lower bound instead of eternal n/a — and the
    source field says which accounting the reader is looking at. The real
    collect_device_metrics runs against a patched device whose
    memory_stats is empty, so the fallback expression under test IS the
    implementation's."""
    import jax
    import jax.numpy as jnp

    from k3stpu.utils import telemetry

    big = jnp.ones((1024, 1024), jnp.float32)  # 4 MiB, forced live
    big.block_until_ready()
    real = jax.local_devices()[0]
    monkeypatch.setattr(jax, "local_devices",
                        lambda *a, **k: [_empty_stats_dev(real)])
    payload = telemetry.collect_device_metrics(duty_cycle_pct=7)
    d0 = payload["devices"][0]
    assert d0["source"] == "live_arrays"
    assert d0["bytes_in_use"] >= big.nbytes
    assert d0["bytes_limit"] == 16 * 1024**3
    assert d0["duty_cycle_pct"] == 7


def test_telemetry_sharded_array_counts_per_device_share(monkeypatch):
    """A sharded array charges each device its own shard's bytes through
    the REAL collect_device_metrics fallback — not its full global size
    n_devices times over (a replicated array, by the same per-shard
    accounting, correctly charges its full size per device)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from k3stpu.parallel.mesh import make_mesh
    from k3stpu.utils import telemetry

    n = len(jax.devices())
    if n < 2:
        import pytest
        pytest.skip("needs the multi-device CPU mesh")
    real = jax.local_devices()[0]
    monkeypatch.setattr(jax, "local_devices",
                        lambda *a, **k: [_empty_stats_dev(real)])
    before = telemetry.collect_device_metrics()["devices"][0]
    mesh = make_mesh(n, model_parallelism=1, axis_names=("data", "model"))
    arr = jax.device_put(jnp.zeros((n * 512, 512), jnp.float32),
                         NamedSharding(mesh, P(("data",), None)))
    arr.block_until_ready()
    after = telemetry.collect_device_metrics()["devices"][0]
    assert (after["bytes_in_use"] - before["bytes_in_use"]
            == arr.nbytes // n)


def test_hbm_limit_respects_mem_fraction(monkeypatch):
    from k3stpu.utils import telemetry

    class Dev:
        device_kind = "TPU v5 lite"

    monkeypatch.setenv("TPU_MEM_FRACTION", "0.25")
    assert telemetry._hbm_limit_for(Dev()) == 4 * 1024**3
    monkeypatch.delenv("TPU_MEM_FRACTION")
    assert telemetry._hbm_limit_for(Dev()) == 16 * 1024**3


def test_estimated_memory_renders_tilde(info_bin, fake_host_root):
    """A drop file whose source is client-side accounting
    (source=live_arrays) renders MEMORY with a '~' prefix and sets
    mem_estimated in JSON — the reader must be able to tell an honest
    lower bound from allocator truth (PJRT stats render unmarked)."""
    run_dir = fake_host_root / "run" / "k3stpu"
    run_dir.mkdir(parents=True)
    (run_dir / "metrics.json").write_text(json.dumps({
        "ts": int(time.time()),
        "devices": [
            {"index": 0, "bytes_in_use": 512 * 1024**2,
             "bytes_limit": 16 * 1024**3, "duty_cycle_pct": 40,
             "source": "live_arrays"},
            {"index": 1, "bytes_in_use": 256 * 1024**2,
             "bytes_limit": 16 * 1024**3, "duty_cycle_pct": 10,
             "source": "pjrt"},
        ],
    }))
    doc = json.loads(subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True).stdout)
    assert doc["chips"][0]["mem_estimated"] is True
    assert doc["chips"][1]["mem_estimated"] is False
    human = subprocess.run([info_bin, "--host-root", str(fake_host_root)],
                           capture_output=True, text=True).stdout
    assert "~512MiB / 16384MiB" in human
    assert "256MiB / 16384MiB" in human
    assert "~256MiB" not in human


def test_stale_drop_file_ignored(info_bin, fake_host_root):
    # A snapshot from an exited workload must not render as live data.
    run_dir = fake_host_root / "run" / "k3stpu"
    run_dir.mkdir(parents=True)
    (run_dir / "metrics.json").write_text(json.dumps({
        "ts": int(time.time()) - 3600,
        "devices": [{"index": 0, "bytes_in_use": 1024**3,
                     "bytes_limit": 16 * 1024**3, "duty_cycle_pct": 83}],
    }))
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    chip0 = json.loads(out.stdout)["chips"][0]
    assert chip0["mem_used_bytes"] == -1
    assert chip0["duty_cycle_pct"] == -1


def test_float_ts_and_values_accepted(info_bin, fake_host_root):
    # External drop-file writers emit time.time() floats (Python json turns
    # computed numbers into doubles); every numeric field must still parse.
    run_dir = fake_host_root / "run" / "k3stpu"
    run_dir.mkdir(parents=True)
    (run_dir / "metrics.json").write_text(json.dumps({
        "ts": time.time() + 0.5,
        "devices": [{"index": 0.0, "bytes_in_use": 2.0 * 1024**3,
                     "bytes_limit": 16.0 * 1024**3, "duty_cycle_pct": 42.0}],
    }))
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    chip0 = json.loads(out.stdout)["chips"][0]
    assert chip0["mem_used_bytes"] == 2 * 1024**3
    assert chip0["duty_cycle_pct"] == 42


def test_watch_mode_redraws(info_bin, fake_host_root):
    # --watch N redraws until killed (the `watch nvidia-smi` idiom).
    proc = subprocess.Popen(
        [info_bin, "--watch", "1", "--host-root", str(fake_host_root)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    time.sleep(2.5)
    proc.terminate()
    out, _ = proc.communicate(timeout=30)
    assert out.count("chips: 4") >= 2, "expected at least two redraws"


def test_watch_rejects_bad_interval(info_bin):
    out = subprocess.run([BIN, "--watch", "0"], capture_output=True,
                         text=True)
    assert out.returncode == 2
