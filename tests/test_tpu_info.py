"""tpu-info CLI: the nvidia-smi parity tool against the fake host tree."""

import json
import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUILD_DIR = os.path.join(REPO, "native", "build")
BIN = os.path.join(BUILD_DIR, "tpu-info")


@pytest.fixture(scope="session")
def info_bin():
    subprocess.run(["cmake", "-S", os.path.join(REPO, "native"), "-B",
                    BUILD_DIR], check=True, capture_output=True)
    subprocess.run(["cmake", "--build", BUILD_DIR, "--target", "tpu-info"],
                   check=True, capture_output=True)
    return BIN


def test_json_inventory(info_bin, fake_host_root):
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    assert out.returncode == 0
    doc = json.loads(out.stdout)
    assert doc["chip_count"] == 4
    assert doc["topology"] == "2x2"
    assert doc["libtpu"] == "/usr/lib/libtpu.so"
    gens = {c["generation"] for c in doc["chips"]}
    assert gens == {"tpu-v5e"}
    assert doc["chips"][0]["dev_paths"] == ["/dev/accel0"]


def test_human_table(info_bin, fake_host_root):
    out = subprocess.run([info_bin, "--host-root", str(fake_host_root)],
                         capture_output=True, text=True)
    assert out.returncode == 0
    assert "chips: 4" in out.stdout
    assert "tpu-v5e" in out.stdout
    assert "/dev/accel0" in out.stdout


def test_exit_code_no_chips(info_bin, tmp_path):
    out = subprocess.run([info_bin, "--host-root", str(tmp_path)],
                         capture_output=True, text=True)
    assert out.returncode == 1  # nvidia-smi-style: nonzero when no devices


def test_usage_error(info_bin):
    out = subprocess.run([info_bin, "--bogus"], capture_output=True, text=True)
    assert out.returncode == 2
