"""tpu-info CLI: the nvidia-smi parity tool against the fake host tree."""

import json
import os
import subprocess
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUILD_DIR = os.path.join(REPO, "native", "build")
BIN = os.path.join(BUILD_DIR, "tpu-info")


@pytest.fixture(scope="session")
def info_bin():
    subprocess.run(["cmake", "-S", os.path.join(REPO, "native"), "-B",
                    BUILD_DIR], check=True, capture_output=True)
    subprocess.run(["cmake", "--build", BUILD_DIR, "--target", "tpu-info"],
                   check=True, capture_output=True)
    return BIN


def test_json_inventory(info_bin, fake_host_root):
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    assert out.returncode == 0
    doc = json.loads(out.stdout)
    assert doc["chip_count"] == 4
    assert doc["topology"] == "2x2"
    assert doc["libtpu"] == "/usr/lib/libtpu.so"
    gens = {c["generation"] for c in doc["chips"]}
    assert gens == {"tpu-v5e"}
    assert doc["chips"][0]["dev_paths"] == ["/dev/accel0"]


def test_human_table(info_bin, fake_host_root):
    out = subprocess.run([info_bin, "--host-root", str(fake_host_root)],
                         capture_output=True, text=True)
    assert out.returncode == 0
    assert "chips: 4" in out.stdout
    assert "tpu-v5e" in out.stdout
    assert "/dev/accel0" in out.stdout


def test_exit_code_no_chips(info_bin, tmp_path):
    out = subprocess.run([info_bin, "--host-root", str(tmp_path)],
                         capture_output=True, text=True)
    assert out.returncode == 1  # nvidia-smi-style: nonzero when no devices


def test_usage_error(info_bin):
    out = subprocess.run([info_bin, "--bogus"], capture_output=True, text=True)
    assert out.returncode == 2


def test_live_columns_na_without_sources(info_bin, fake_host_root):
    # No sysfs attrs, no drop file: used/util are "n/a" but the capacity
    # column still shows the generation's HBM size (v5e = 16 GiB).
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    doc = json.loads(out.stdout)
    for c in doc["chips"]:
        assert c["mem_used_bytes"] == -1
        assert c["duty_cycle_pct"] == -1
        assert c["mem_total_bytes"] == 16 * 1024**3
    human = subprocess.run([info_bin, "--host-root", str(fake_host_root)],
                           capture_output=True, text=True).stdout
    assert "UTIL" in human and "MEMORY" in human
    assert "n/a / 16384MiB" in human


def test_live_columns_from_sysfs_attrs(info_bin, fake_host_root):
    # Driver-exposed per-chip attributes are authoritative when present.
    pci = fake_host_root / "sys" / "bus" / "pci" / "devices" / "0000:00:04.0"
    (pci / "tpu_mem_used_bytes").write_text(f"{512 * 1024**2}\n")
    (pci / "tpu_mem_total_bytes").write_text(f"{16 * 1024**3}\n")
    (pci / "tpu_duty_cycle_pct").write_text("37\n")
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    chip0 = json.loads(out.stdout)["chips"][0]
    assert chip0["mem_used_bytes"] == 512 * 1024**2
    assert chip0["duty_cycle_pct"] == 37
    human = subprocess.run([info_bin, "--host-root", str(fake_host_root)],
                           capture_output=True, text=True).stdout
    assert "512MiB / 16384MiB" in human
    assert "37%" in human


def test_live_columns_from_metrics_drop_file(info_bin, fake_host_root):
    # Workload-exported drop file (k3stpu/utils/telemetry.py) fills chips
    # that have no sysfs attrs, matched by device index.
    run_dir = fake_host_root / "run" / "k3stpu"
    run_dir.mkdir(parents=True)
    (run_dir / "metrics.json").write_text(json.dumps({
        "ts": int(time.time()),  # fresh: stale drops are ignored
        "devices": [
            {"index": 1, "bytes_in_use": 1024**3,
             "bytes_limit": 16 * 1024**3, "duty_cycle_pct": 83},
        ],
    }))
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    chips = json.loads(out.stdout)["chips"]
    assert chips[1]["mem_used_bytes"] == 1024**3
    assert chips[1]["duty_cycle_pct"] == 83
    assert chips[0]["mem_used_bytes"] == -1  # untouched


def test_malformed_drop_file_ignored(info_bin, fake_host_root):
    run_dir = fake_host_root / "run" / "k3stpu"
    run_dir.mkdir(parents=True)
    (run_dir / "metrics.json").write_text("{not json")
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    assert out.returncode == 0
    assert json.loads(out.stdout)["chips"][0]["mem_used_bytes"] == -1


def test_telemetry_writer_roundtrip(info_bin, fake_host_root):
    # The python exporter's file is exactly what the C++ reader consumes.
    from k3stpu.utils.telemetry import write_metrics

    run_dir = fake_host_root / "run" / "k3stpu"
    payload = write_metrics(str(run_dir / "metrics.json"), duty_cycle_pct=12)
    assert payload["devices"], "no local jax devices"
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    chips = json.loads(out.stdout)["chips"]
    # CPU backend reports bytes_in_use on some builds and -1 on others;
    # duty cycle must round-trip verbatim for matching indices.
    by_idx = {d["index"]: d for d in payload["devices"]}
    for c in chips:
        if c["index"] in by_idx and by_idx[c["index"]]["duty_cycle_pct"] >= 0:
            assert c["duty_cycle_pct"] == 12


def test_stale_drop_file_ignored(info_bin, fake_host_root):
    # A snapshot from an exited workload must not render as live data.
    run_dir = fake_host_root / "run" / "k3stpu"
    run_dir.mkdir(parents=True)
    (run_dir / "metrics.json").write_text(json.dumps({
        "ts": int(time.time()) - 3600,
        "devices": [{"index": 0, "bytes_in_use": 1024**3,
                     "bytes_limit": 16 * 1024**3, "duty_cycle_pct": 83}],
    }))
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    chip0 = json.loads(out.stdout)["chips"][0]
    assert chip0["mem_used_bytes"] == -1
    assert chip0["duty_cycle_pct"] == -1


def test_float_ts_and_values_accepted(info_bin, fake_host_root):
    # External drop-file writers emit time.time() floats (Python json turns
    # computed numbers into doubles); every numeric field must still parse.
    run_dir = fake_host_root / "run" / "k3stpu"
    run_dir.mkdir(parents=True)
    (run_dir / "metrics.json").write_text(json.dumps({
        "ts": time.time() + 0.5,
        "devices": [{"index": 0.0, "bytes_in_use": 2.0 * 1024**3,
                     "bytes_limit": 16.0 * 1024**3, "duty_cycle_pct": 42.0}],
    }))
    out = subprocess.run(
        [info_bin, "--json", "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    chip0 = json.loads(out.stdout)["chips"][0]
    assert chip0["mem_used_bytes"] == 2 * 1024**3
    assert chip0["duty_cycle_pct"] == 42


def test_watch_mode_redraws(info_bin, fake_host_root):
    # --watch N redraws until killed (the `watch nvidia-smi` idiom).
    proc = subprocess.Popen(
        [info_bin, "--watch", "1", "--host-root", str(fake_host_root)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    time.sleep(2.5)
    proc.terminate()
    out, _ = proc.communicate(timeout=30)
    assert out.count("chips: 4") >= 2, "expected at least two redraws"


def test_watch_rejects_bad_interval(info_bin):
    out = subprocess.run([BIN, "--watch", "0"], capture_output=True,
                         text=True)
    assert out.returncode == 2
