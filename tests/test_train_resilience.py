"""Preemption-tolerant training (ISSUE 4): SIGTERM -> emergency checkpoint
-> exact-step resume, corrupt-checkpoint quarantine/fallback, --keep-last
retention, and bounded rendezvous retries.

The SIGTERM scenario drives a REAL train-job subprocess (signals must hit a
real process boundary); everything else runs train_job.main() in-process on
the conftest CPU mesh, with faults armed through K3STPU_CHAOS exactly the
way a pod would arm them. docs/RESILIENCE.md is the prose version of the
fault matrix this file executes.
"""

import getpass
import json
import os
import pathlib
import shutil
import signal
import subprocess
import sys
import threading

import pytest

from k3stpu.chaos import FaultInjector, InjectedFault
from k3stpu.parallel import train_job
from k3stpu.parallel.distributed import (
    Rendezvous,
    RendezvousError,
    connect_with_retries,
)
from k3stpu.utils import checkpoint as ckpt

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


def _events(text):
    """Parse the JSON event lines, skipping noise (e.g. 'CHAOS ARMED')."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            out.append(json.loads(line))
    return out


def _run_inproc(capsys, argv, expect_rc=0):
    rc = train_job.main(argv)
    assert rc == expect_rc
    return _events(capsys.readouterr().out)


BASE = ["--model", "tiny", "--batch", "8", "--seq", "32"]


def _steps_of(events):
    return [e["step"] for e in events if e["event"] == "step"]


def _corrupt_largest_file(step_dir):
    """Flip a byte in the step's largest file (size unchanged -> the
    manifest's sha256 is the only thing that can catch it)."""
    victim = max((p for p in pathlib.Path(step_dir).rglob("*")
                  if p.is_file()), key=lambda p: p.stat().st_size)
    data = bytearray(victim.read_bytes())
    data[len(data) // 2] ^= 0xFF
    victim.write_bytes(bytes(data))
    return victim


# --- corrupt checkpoint: quarantine + fall back ---------------------------


def test_corrupt_checkpoint_quarantined_and_previous_step_wins(
        tmp_path, capsys):
    cdir = tmp_path / "ckpt"
    _run_inproc(capsys, BASE + ["--steps", "4", "--ckpt-dir", str(cdir),
                                "--ckpt-every", "2"])
    assert ckpt.finalized_steps(cdir) == [2, 4]
    _corrupt_largest_file(cdir / "4")

    events = _run_inproc(capsys, BASE + ["--steps", "6", "--ckpt-dir",
                                         str(cdir), "--ckpt-every", "2"])
    (q,) = [e for e in events if e["event"] == "ckpt_quarantined"]
    assert q["step"] == 4
    assert "checksum mismatch" in q["reason"]
    (resume,) = [e for e in events if e["event"] == "resume"]
    assert resume["step"] == 2
    assert resume["verify"].startswith("verified")
    # The bad step recomputes: training continues 3..6, not 5..6.
    assert _steps_of(events) == [3, 4, 5, 6]
    # Evidence preserved: step dir AND its manifest moved, never deleted.
    assert (cdir / "quarantine" / "4").is_dir()
    assert (cdir / "quarantine" / "4.manifest.json").is_file()
    # The rerun re-saved a healthy step 4 (and 6) with fresh manifests.
    assert ckpt.latest_step(cdir) == 6
    assert ckpt.verify_step(cdir, 4)[0]


def test_restore_failure_quarantines_and_falls_back(
        tmp_path, capsys, monkeypatch):
    """A checkpoint that passes its manifest but fails to RESTORE (bitrot
    orbax can see but sha256 cannot — here an injected ckpt_restore fault)
    must also quarantine and fall back, not crash-loop."""
    cdir = tmp_path / "ckpt"
    _run_inproc(capsys, BASE + ["--steps", "4", "--ckpt-dir", str(cdir),
                                "--ckpt-every", "2"])
    monkeypatch.setenv("K3STPU_CHAOS",
                       "ckpt_restore:times=1:exc=unreadable checkpoint")
    events = _run_inproc(capsys, BASE + ["--steps", "6", "--ckpt-dir",
                                         str(cdir), "--ckpt-every", "2"])
    (q,) = [e for e in events if e["event"] == "ckpt_quarantined"]
    assert q["step"] == 4
    assert "restore failed" in q["reason"]
    (resume,) = [e for e in events if e["event"] == "resume"]
    assert resume["step"] == 2
    assert _steps_of(events) == [3, 4, 5, 6]


def test_repeated_restore_failures_exit_nonzero_with_tree_intact(
        tmp_path, capsys, monkeypatch):
    """TWO independent checkpoints failing to RESTORE (after passing
    integrity) is environmental (device OOM, PVC hiccup), not bitrot:
    the boot must exit nonzero with the remaining tree intact — so the
    Job restart retries — instead of cascade-quarantining every step and
    silently starting from step 0."""
    cdir = tmp_path / "ckpt"
    _run_inproc(capsys, BASE + ["--steps", "4", "--ckpt-dir", str(cdir),
                                "--ckpt-every", "2"])
    monkeypatch.setenv("K3STPU_CHAOS",
                       "ckpt_restore:times=2:exc=device tunnel wedged")
    with pytest.raises(RuntimeError, match="likely environmental"):
        train_job.main(BASE + ["--steps", "6", "--ckpt-dir", str(cdir),
                               "--ckpt-every", "2"])
    events = _events(capsys.readouterr().out)
    # Only the first failure got the benefit of the doubt; step 2 is
    # still on disk for the restart to retry.
    assert [e["step"] for e in events
            if e["event"] == "ckpt_quarantined"] == [4]
    assert ckpt.finalized_steps(cdir) == [2]


def test_quarantine_cap_stops_a_corruption_cascade(tmp_path, capsys):
    """A boot that keeps finding bad steps stops quarantining at the cap
    and exits nonzero rather than consuming the whole checkpoint tree."""
    cdir = tmp_path / "ckpt"
    _run_inproc(capsys, BASE + ["--steps", "8", "--ckpt-dir", str(cdir),
                                "--ckpt-every", "2"])
    assert ckpt.finalized_steps(cdir) == [2, 4, 6, 8]
    for step in (4, 6, 8):
        _corrupt_largest_file(cdir / str(step))
    with pytest.raises(RuntimeError, match="quarantine cap"):
        train_job.main(BASE + ["--steps", "10", "--ckpt-dir", str(cdir),
                               "--ckpt-every", "2"])
    events = _events(capsys.readouterr().out)
    assert [e["step"] for e in events
            if e["event"] == "ckpt_quarantined"] == [8, 6]
    # Steps 2 and 4 survive on disk (4 corrupt but preserved as-is), the
    # quarantined evidence too.
    assert ckpt.finalized_steps(cdir) == [2, 4]
    assert (cdir / "quarantine" / "8").is_dir()
    assert (cdir / "quarantine" / "6").is_dir()


# --- retention GC + partial-save debris -----------------------------------


def test_keep_last_retention_spares_partials(tmp_path, capsys):
    cdir = tmp_path / "ckpt"
    debris = cdir / "3.orbax-checkpoint-tmp-123"
    debris.mkdir(parents=True)
    (debris / "shard").write_text("half-written")

    events = _run_inproc(capsys, BASE + [
        "--steps", "8", "--ckpt-dir", str(cdir), "--ckpt-every", "2",
        "--keep-last", "2"])
    # Boot saw only unfinalized debris: said so, started fresh.
    (skip,) = [e for e in events if e["event"] == "resume_skipped_partial"]
    assert skip["partial"] == ["3.orbax-checkpoint-tmp-123"]
    assert not any(e["event"] == "resume" for e in events)
    # Retention: exactly the newest two finalized steps survive, manifests
    # in lockstep, and the GC events account for every deletion.
    assert ckpt.finalized_steps(cdir) == [6, 8]
    assert sorted((cdir / "manifests").glob("*.json")) == [
        cdir / "manifests" / "6.json", cdir / "manifests" / "8.json"]
    deleted = [s for e in events if e["event"] == "ckpt_gc"
               for s in e["deleted"]]
    assert deleted == [2, 4]
    # The partial is never retention's business.
    assert debris.is_dir()


# --- crash mid-step: async save still lands, restart resumes --------------


def test_crash_mid_step_resumes_from_periodic_checkpoint(
        tmp_path, capsys, monkeypatch):
    cdir = tmp_path / "ckpt"
    # Steps 1..4 complete (async save at 2 and 4); the 5th step body raises.
    monkeypatch.setenv("K3STPU_CHAOS", "train_step:skip=4:times=1")
    with pytest.raises(InjectedFault):
        train_job.main(BASE + ["--steps", "8", "--ckpt-dir", str(cdir),
                               "--ckpt-every", "2"])
    events = _events(capsys.readouterr().out)
    assert _steps_of(events) == [1, 2, 3, 4]
    # The finally-drain landed the in-flight step-4 save AND its manifest.
    assert ckpt.latest_step(cdir) == 4
    assert ckpt.verify_step(cdir, 4)[0]

    monkeypatch.delenv("K3STPU_CHAOS")
    events = _run_inproc(capsys, BASE + ["--steps", "6", "--ckpt-dir",
                                         str(cdir), "--ckpt-every", "2"])
    (resume,) = [e for e in events if e["event"] == "resume"]
    assert resume["step"] == 4
    assert _steps_of(events) == [5, 6]


# --- bounded rendezvous (unit: fake connect, fake sleep) ------------------

_RDV = Rendezvous(coordinator_address="tpu-train-0.tpu-train:8476",
                  num_processes=2, process_id=1)


def test_rdv_retries_with_capped_exponential_backoff(capsys):
    sleeps, calls = [], {"n": 0}

    def connect():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("coordinator DNS not ready")

    connect_with_retries(connect, _RDV, timeout_s=5.0, attempts=5,
                         backoff_s=2.0, backoff_cap_s=30.0,
                         _sleep=sleeps.append)
    assert calls["n"] == 3
    assert sleeps == [2.0, 4.0]  # exponential: 2, 4
    events = _events(capsys.readouterr().out)
    kinds = [e["event"] for e in events]
    assert kinds == ["rdv_attempt", "rdv_retry", "rdv_attempt",
                     "rdv_retry", "rdv_attempt", "rdv_ok"]
    attempts = [e["attempt"] for e in events if e["event"] == "rdv_attempt"]
    assert attempts == [1, 2, 3]
    assert events[0]["coordinator"] == "tpu-train-0.tpu-train:8476"
    assert [e["backoff_s"] for e in events if e["event"] == "rdv_retry"] \
        == [2.0, 4.0]


def test_rdv_exhaustion_raises_diagnosable_error(capsys):
    sleeps = []

    def connect():
        raise TimeoutError("deadline exceeded")

    with pytest.raises(RendezvousError) as ei:
        connect_with_retries(connect, _RDV, timeout_s=9.0, attempts=3,
                             backoff_s=1.0, backoff_cap_s=2.0,
                             _sleep=sleeps.append)
    # Fail FAST and diagnosable: coordinator, budget, and every failure.
    msg = str(ei.value)
    assert "tpu-train-0.tpu-train:8476" in msg
    assert "3 attempts" in msg and "TimeoutError" in msg
    assert sleeps == [1.0, 2.0]  # cap clamps the 3rd-would-be 4.0 -> none
    events = _events(capsys.readouterr().out)
    assert [e["event"] for e in events][-1] == "rdv_failed"
    assert events[-1]["backoff_s"] is None  # no retry after the last


def test_rdv_chaos_point_drives_the_retry_loop(capsys):
    chaos = FaultInjector()
    chaos.arm("rdv_connect", times=2)
    connected = {"n": 0}
    connect_with_retries(
        lambda: connected.update(n=connected["n"] + 1), _RDV,
        timeout_s=1.0, attempts=4, backoff_s=0.0, backoff_cap_s=0.0,
        chaos=chaos, _sleep=lambda s: None)
    assert chaos.fired("rdv_connect") == 2
    assert connected["n"] == 1  # real connect ran once, on attempt 3
    events = _events(capsys.readouterr().out)
    assert events[-1] == {"event": "rdv_ok", "attempt": 3,
                          "elapsed_s": events[-1]["elapsed_s"]}


def test_rdv_env_knobs_parse_with_fallback(monkeypatch):
    from k3stpu.parallel.distributed import _env_float, _env_int

    monkeypatch.setenv("K3STPU_RDV_TIMEOUT_S", "bogus")
    assert _env_float("K3STPU_RDV_TIMEOUT_S", 7.5) == 7.5
    monkeypatch.setenv("K3STPU_RDV_TIMEOUT_S", "3")
    assert _env_float("K3STPU_RDV_TIMEOUT_S", 7.5) == 3.0
    # Int knobs degrade the same way — a typo'd K3STPU_RDV_ATTEMPTS must
    # not crash the job before rendezvous even starts.
    monkeypatch.setenv("K3STPU_RDV_ATTEMPTS", "four")
    assert _env_int("K3STPU_RDV_ATTEMPTS", 4) == 4
    monkeypatch.setenv("K3STPU_RDV_ATTEMPTS", "6")
    assert _env_int("K3STPU_RDV_ATTEMPTS", 4) == 6


def test_malformed_preempt_bound_env_does_not_crash(
        tmp_path, capsys, monkeypatch):
    """The save bound is parsed ONCE at startup with a fallback: a
    malformed K3STPU_PREEMPT_SAVE_BOUND_S must never surface as a
    ValueError in the SIGTERM path (which would skip the emergency
    checkpoint and the 'preempted' event entirely)."""
    monkeypatch.setenv("K3STPU_PREEMPT_SAVE_BOUND_S", "ninety")
    cdir = tmp_path / "ckpt"
    events = _run_inproc(capsys, BASE + ["--steps", "2", "--ckpt-dir",
                                         str(cdir), "--ckpt-every", "2"])
    assert _steps_of(events) == [1, 2]


# --- SIGTERM mid-training: real subprocess, real signal -------------------


def _train_env(**extra):
    env = dict(os.environ)
    # REPLACE PYTHONPATH (test_chaos.py idiom: drop the dev box's
    # sitecustomize, which would re-register the TPU tunnel) and run one
    # CPU device — the fastest cold start for a subprocess train job.
    env["PYTHONPATH"] = str(REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("K3STPU_CHAOS", None)
    try:
        user = getpass.getuser()
    except (KeyError, OSError):
        user = str(os.getuid())
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.environ.get(
        "K3STPU_TEST_CACHE", f"/tmp/k3stpu-test-compile-cache-{user}"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    env.update({k: str(v) for k, v in extra.items()})
    return env


TRAIN_CMD = [sys.executable, "-m", "k3stpu.parallel.train_job",
             "--model", "tiny", "--batch", "4", "--seq", "16"]


def _run_train(args, env, timeout=240):
    proc = subprocess.run(TRAIN_CMD + args, env=env, text=True,
                          stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, timeout=timeout)
    return proc.returncode, _events(proc.stdout), proc.stdout


def test_sigterm_emergency_checkpoint_then_exact_resume(tmp_path):
    cdir = tmp_path / "ckpt"
    # Pace steps (~0.25s each) so SIGTERM reliably lands mid-run;
    # --ckpt-every 400 means the ONLY checkpoint can be the emergency one.
    env = _train_env(K3STPU_CHAOS="train_step:stall_s=0.25:times=1000",
                     K3STPU_PREEMPT_SAVE_BOUND_S="60")
    proc = subprocess.Popen(
        TRAIN_CMD + ["--steps", "500", "--ckpt-dir", str(cdir),
                     "--ckpt-every", "400"],
        env=env, text=True, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    reaper = threading.Timer(300, proc.kill)
    reaper.start()
    events, signalled = [], False
    try:
        for line in proc.stdout:
            line = line.strip()
            if not line.startswith("{"):
                continue
            ev = json.loads(line)
            events.append(ev)
            if (not signalled and ev.get("event") == "step"
                    and ev["step"] >= 3):
                proc.send_signal(signal.SIGTERM)  # mid-stall of next step
                signalled = True
        rc = proc.wait(timeout=120)
    finally:
        reaper.cancel()
        if proc.poll() is None:
            proc.kill()

    assert rc == train_job.PREEMPTED_EXIT_CODE, events
    (pre,) = [e for e in events if e["event"] == "preempted"]
    last_step = _steps_of(events)[-1]
    assert pre["step"] == last_step
    assert pre["signal"] == "SIGTERM"
    assert pre["emergency_ckpt"] is True
    assert pre["save_error"] is None
    assert pre["save_s"] <= pre["save_bound_s"]
    # The emergency save is blocking: finalized + manifest before exit.
    (saved,) = [e for e in events if e["event"] == "checkpoint"]
    assert saved == {"event": "checkpoint", "step": last_step,
                     "async": False}
    assert ckpt.latest_step(cdir) == last_step
    assert ckpt.verify_step(cdir, last_step)[0]

    # Resume continues at EXACTLY the preempted step — twice, from
    # identical copies: bitwise-equal loss curves prove the emergency
    # checkpoint fully determines the continuation (no lost state).
    cdir_b = tmp_path / "ckpt_b"
    shutil.copytree(cdir, cdir_b)
    env = _train_env()
    rerun_losses = []
    for d in (cdir, cdir_b):
        rc, ev, out = _run_train(
            ["--steps", str(last_step + 2), "--ckpt-dir", str(d),
             "--ckpt-every", "400"], env)
        assert rc == 0, out[-2000:]
        (resume,) = [e for e in ev if e["event"] == "resume"]
        assert resume["step"] == last_step
        assert _steps_of(ev) == [last_step + 1, last_step + 2]
        rerun_losses.append([e["loss"] for e in ev
                             if e["event"] == "step"])
    # Bitwise-equal twins: both restores of the same emergency checkpoint
    # produce the same losses — the resumed state IS the checkpoint, not a
    # reinit. (No loss-LEVEL check: a handful of tiny-model steps moves
    # the loss less than batch-to-batch noise, and the resumed run's data
    # stream is reseeded from the resume step by design.)
    assert rerun_losses[0] == rerun_losses[1]


# --- flaky rendezvous: two real processes, injected first-attempt flake ---


@pytest.mark.slow
def test_two_process_rendezvous_survives_injected_flake(tmp_path):
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def env_for(rank):
        env = _train_env(K3STPU_NUM_PROCESSES=2,
                         K3STPU_COORDINATOR=f"127.0.0.1:{port}",
                         K3STPU_PROCESS_ID=rank,
                         K3STPU_RDV_TIMEOUT_S=120,
                         K3STPU_RDV_ATTEMPTS=4,
                         K3STPU_RDV_BACKOFF_S=0.5)
        if rank == 1:
            # Rank 1's first attempt fails (stands in for coordinator
            # DNS not yet resolvable); the retry loop must recover it.
            env["K3STPU_CHAOS"] = "rdv_connect:times=1"
        return env

    cmd = [sys.executable, "-m", "k3stpu.parallel.launch",
           "--skip-matmul", "--skip-allreduce"]
    procs = [subprocess.Popen(cmd, env=env_for(r), text=True,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
             for r in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    ev1 = _events(outs[1])
    kinds = [e["event"] for e in ev1]
    assert "rdv_retry" in kinds  # the flake actually fired
    (ok,) = [e for e in ev1 if e["event"] == "rdv_ok"]
    assert ok["attempt"] == 2
    (rdv,) = [e for e in ev1 if e["event"] == "rendezvous"]
    assert rdv["global_devices"] == 2
