"""MoE transformer: routing invariants, aux loss, EP-sharded training."""

import jax
import jax.numpy as jnp
import numpy as np

from k3stpu.models.moe import MoeMlp, moe_lm_tiny
from k3stpu.parallel.mesh import make_mesh
from k3stpu.parallel.train import (
    make_train_bundle,
    run_synthetic_steps,
    synth_token_batch,
)


def test_forward_shape_and_dtype():
    model = moe_lm_tiny()
    tokens = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.key(0), tokens)
    logits = model.apply({"params": variables["params"]}, tokens)
    assert logits.shape == (2, 16, model.config.base.vocab_size)
    assert logits.dtype == jnp.float32


def test_moe_blocks_alternate():
    model = moe_lm_tiny()
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
    params = variables["params"]
    # every_n_blocks=2 with 2 layers: block0 dense, block1 MoE.
    assert "mlp_in" in params["block0"] and "moe" not in params["block0"]
    assert "moe" in params["block1"] and "mlp_in" not in params["block1"]
    w_in = params["block1"]["moe"]["w_in"]
    cfg = model.config
    assert w_in.shape == (cfg.num_experts, cfg.base.d_model, cfg.base.d_ff)


def test_router_sows_aux_loss():
    model = moe_lm_tiny()
    tokens = jnp.zeros((1, 16), jnp.int32)
    variables = model.init(jax.random.key(0), tokens)
    _, mut = model.apply({"params": variables["params"]}, tokens,
                         mutable=["losses"])
    leaves = jax.tree.leaves(mut["losses"])
    assert leaves, "router aux loss not sowed"
    total = sum(float(jnp.sum(l)) for l in leaves)
    # Switch-style balance loss is ~coef (0.01) when balanced; bounded by
    # coef * E when fully collapsed. Must be positive and finite.
    assert 0 < total < 1.0


def test_route_top_k_invariants():
    """Capacity routing: load <= capacity, unique slots, top-k dispatch."""
    from k3stpu.models.moe import route_top_k

    t, e, cap, k = 64, 4, 6, 2  # cap << t/e so overflow definitely happens
    probs = jax.nn.softmax(
        jax.random.normal(jax.random.key(2), (t, e)) * 3.0, axis=-1)
    dispatch, combine = route_top_k(probs, top_k=k, capacity=cap)
    d = np.asarray(dispatch)

    # Per-expert load never exceeds capacity.
    load = d.sum(axis=(0, 2))
    assert (load <= cap).all(), load
    # With cap*e=24 slots for 128 dispatches, overflow occurred (drops).
    assert d.sum() < t * k
    # Every (expert, slot) is claimed by at most one token.
    assert (d.sum(axis=0) <= 1.0 + 1e-6).all()
    # Each token dispatches at most top_k times, to distinct experts.
    assert (d.sum(axis=(1, 2)) <= k + 1e-6).all()
    assert (d.sum(axis=2) <= 1.0 + 1e-6).all()
    # combine carries the token's own gate probability on dispatched slots.
    picked = d * np.asarray(probs)[:, :, None]
    np.testing.assert_allclose(np.asarray(combine), picked, atol=1e-6)


def test_route_top_k_no_overflow_when_capacity_ample():
    from k3stpu.models.moe import route_top_k

    t, e = 32, 4
    probs = jax.nn.softmax(
        jax.random.normal(jax.random.key(4), (t, e)), axis=-1)
    dispatch, _ = route_top_k(probs, top_k=1, capacity=t)
    # Nothing can overflow with capacity == t: every token is dispatched.
    assert float(np.asarray(dispatch).sum()) == t


def test_moe_trains_on_mesh_with_ep_sharding():
    import optax

    mesh = make_mesh(8, model_parallelism=2)
    model = moe_lm_tiny()
    bundle = make_train_bundle(
        model, mesh, example_input=jnp.zeros((1, 32), jnp.int32),
        optimizer=optax.adamw(3e-4))

    # Expert-major params shard over 'model' (expert parallelism).
    w_in = bundle.params["block1"]["moe"]["w_in"]
    shard_shapes = {s.data.shape for s in w_in.addressable_shards}
    e, d, f = w_in.shape
    assert shard_shapes == {(e // 2, d, f)}

    vocab = model.config.base.vocab_size
    losses = [run_synthetic_steps(
        bundle, lambda k: synth_token_batch(k, 8, 32, vocab))
        for _ in range(3)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] <= losses[0] + 1.0


def test_generation_works_with_moe():
    """KV-cache decode runs through MoE blocks too (shared Attention)."""
    from k3stpu.models.generate import generate

    model = moe_lm_tiny(max_seq_len=64)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, 512)
    out = generate(model, params, prompt, jnp.array([8], jnp.int32), 4)
    assert out.shape == (1, 4)
    assert int(out.max()) < 512


def test_router_z_loss_sown_and_scales():
    model = moe_lm_tiny(max_seq_len=32)
    toks = jax.random.randint(jax.random.key(9), (2, 16), 0,
                              model.config.base.vocab_size)
    variables = model.init(jax.random.key(0), toks, train=True)
    _, mut = model.apply(variables, toks, train=True, mutable=["losses"])
    flat = jax.tree_util.tree_flatten_with_path(mut["losses"])[0]
    names = {getattr(p[-2], "key", "") for p, _ in flat}
    assert "router_z" in names and "router_balance" in names
    z_vals = [float(v.sum()) for p, v in flat
              if getattr(p[-2], "key", "") == "router_z"]
    assert all(v >= 0 for v in z_vals) and any(v > 0 for v in z_vals)
