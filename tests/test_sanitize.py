"""ASan/UBSan build of the native components (SURVEY.md §5: the C++
runtime shim runs under sanitizers in CI — the cluster layer has no data
races to hunt, so memory/UB discipline on the native path is the analogue).

Builds native/ with -DK3STPU_SANITIZE=ON into a separate build tree and
drives the spec-rewrite and chip-inventory paths; any ASan/UBSan report
makes the binary exit non-zero (abort_on_error) and fails the test.
"""

import json
import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUILD_DIR = os.path.join(REPO, "native", "build-asan")

ASAN_ENV = {
    **os.environ,
    "ASAN_OPTIONS": "abort_on_error=1:detect_leaks=1",
    "UBSAN_OPTIONS": "halt_on_error=1",
}


@pytest.fixture(scope="session")
def asan_bins():
    subprocess.run(
        ["cmake", "-S", os.path.join(REPO, "native"), "-B", BUILD_DIR,
         "-DK3STPU_SANITIZE=ON"],
        check=True, capture_output=True)
    subprocess.run(["cmake", "--build", BUILD_DIR, "-j", "4"],
                   check=True, capture_output=True)
    return BUILD_DIR


def test_spec_patch_under_sanitizers(asan_bins, fake_host_root, tmp_path):
    bundle = tmp_path / "bundle"
    bundle.mkdir()
    spec = {
        "ociVersion": "1.0.2",
        "process": {"args": ["python"], "env": ["PATH=/usr/bin"]},
        "root": {"path": "rootfs"},
        "mounts": [{"destination": "/proc", "type": "proc",
                    "source": "proc"}],
        "linux": {"namespaces": [{"type": "pid"}]},
    }
    (bundle / "config.json").write_text(json.dumps(spec))

    out = subprocess.run(
        [os.path.join(asan_bins, "tpu-container-runtime"), "patch",
         "--bundle", str(bundle), "--dry-run",
         "--host-root", str(fake_host_root), "--always"],
        capture_output=True, text=True, env=ASAN_ENV)
    assert out.returncode == 0, out.stderr
    patched = json.loads(out.stdout)
    assert any("libtpu" in m.get("source", "")
               for m in patched.get("mounts", [])), patched["mounts"]
    assert "AddressSanitizer" not in out.stderr
    assert "runtime error" not in out.stderr


def test_tpu_info_under_sanitizers(asan_bins, fake_host_root):
    out = subprocess.run(
        [os.path.join(asan_bins, "tpu-info"), "--json",
         "--host-root", str(fake_host_root)],
        capture_output=True, text=True, env=ASAN_ENV)
    assert out.returncode == 0, out.stderr
    info = json.loads(out.stdout)
    assert len(info["chips"]) == 4
    assert "AddressSanitizer" not in out.stderr


def test_malformed_spec_is_rejected_cleanly(asan_bins, tmp_path):
    """Truncated/garbage JSON must fail with an error, not a crash."""
    bundle = tmp_path / "bundle"
    bundle.mkdir()
    (bundle / "config.json").write_text('{"process": {"args": [')
    out = subprocess.run(
        [os.path.join(asan_bins, "tpu-container-runtime"), "patch",
         "--bundle", str(bundle), "--dry-run"],
        capture_output=True, text=True, env=ASAN_ENV)
    assert out.returncode != 0
    assert "AddressSanitizer" not in out.stderr
    assert "Segmentation" not in out.stderr
