"""Ring attention (context parallelism) vs full attention, on the 8-device
virtual CPU mesh (conftest) — exactness check for the online-softmax ring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.ops.attention import reference_attention
from k3stpu.parallel.context import (
    context_parallel_attention,
    make_context_mesh,
    ring_attention,
)

try:
    from jax import shard_map
except ImportError:
    # Older jax spells it jax.experimental.shard_map; the pre-vma
    # replication check stays off — these programs are vma-typed.
    from jax.experimental.shard_map import shard_map as _esm

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
        return _esm(f, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, check_rep=check_vma)


def _qkv(b=2, s=256, h=4, d=32, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_full(causal):
    mesh = make_context_mesh(8)
    q, k, v = _qkv()
    out = context_parallel_attention(mesh, q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ring_on_subset_of_devices():
    mesh = make_context_mesh(4)
    q, k, v = _qkv(s=128, seed=3)
    out = context_parallel_attention(mesh, q, k, v)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ring_bf16():
    mesh = make_context_mesh(8)
    q, k, v = _qkv(seed=1, dtype=jnp.bfloat16)
    out = context_parallel_attention(mesh, q, k, v)
    ref = reference_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)


def test_ring_output_stays_sharded():
    mesh = make_context_mesh(8)
    q, k, v = _qkv()
    out = context_parallel_attention(mesh, q, k, v)
    # The output must remain sequence-sharded (no hidden all-gather).
    ns = out.sharding
    assert ns.spec == jax.sharding.PartitionSpec(None, "seq", None, None)


def test_ring_attention_differentiable():
    """Gradients flow through ppermute + fori_loop (training viability)."""
    from functools import partial

    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_context_mesh(4)
    q, k, v = _qkv(b=1, s=64, h=2, d=16, seed=5)
    spec = P(None, "seq", None, None)
    sh = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))

    ring = shard_map(partial(ring_attention, axis_name="seq"),
                     mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_matches_full(causal):
    # Flash-per-shard ring (interpret-mode kernels on CPU) must be exact
    # against full attention, like the einsum ring.
    mesh = make_context_mesh(8)
    q, k, v = _qkv(seed=3)
    out = context_parallel_attention(mesh, q, k, v, causal=causal,
                                     impl="flash", interpret=True)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_flash_bf16_and_sharded_output():
    mesh = make_context_mesh(4)
    q, k, v = _qkv(b=1, s=128, h=2, d=32, seed=9, dtype=jnp.bfloat16)
    out = context_parallel_attention(mesh, q, k, v, impl="flash",
                                     interpret=True)
    assert out.dtype == jnp.bfloat16
    assert out.sharding.spec == jax.sharding.PartitionSpec(
        None, "seq", None, None)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)


def test_ring_flash_non_divisible_shard_length():
    # 8 devices x s=384 -> s_local=48; default 512 blocks must round down
    # to a divisor instead of raising.
    mesh = make_context_mesh(8)
    q, k, v = _qkv(s=384, seed=11)
    out = context_parallel_attention(mesh, q, k, v, impl="flash",
                                     interpret=True)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_gradients_match_reference(causal):
    """The custom-VJP ring backward (Pallas kernels per shard, rotating
    dk/dv accumulators) must produce exact grads vs full attention."""
    from functools import partial

    from jax.sharding import NamedSharding, PartitionSpec as P

    from k3stpu.parallel.context import ring_flash_attention

    mesh = make_context_mesh(4)
    q, k, v = _qkv(b=1, s=128, h=2, d=16, seed=6)
    spec = P(None, "seq", None, None)
    sh = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))

    ring = shard_map(
        partial(ring_flash_attention, axis_name="seq", causal=causal,
                interpret=True),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   atol=1e-4, rtol=1e-4)


def test_zigzag_layout_roundtrip():
    from k3stpu.parallel.context import zigzag_from_local, zigzag_to_local

    x = jnp.arange(2 * 32 * 3 * 4, dtype=jnp.float32).reshape(2, 32, 3, 4)
    for n in (2, 4, 8):
        z = zigzag_to_local(x, n)
        np.testing.assert_array_equal(np.asarray(zigzag_from_local(z, n)),
                                      np.asarray(x))


def test_zigzag_matches_full_causal():
    mesh = make_context_mesh(8)
    q, k, v = _qkv(s=128, h=2, seed=13)  # 16 chunks of 8; exactness only
    out = context_parallel_attention(mesh, q, k, v, impl="zigzag",
                                     interpret=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_zigzag_gradients_match_reference():
    mesh = make_context_mesh(4)
    q, k, v = _qkv(b=1, s=128, h=2, d=16, seed=14)

    def loss_zz(q, k, v):
        return jnp.sum(context_parallel_attention(
            mesh, q, k, v, impl="zigzag", interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_zz = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_zz, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   atol=1e-4, rtol=1e-4)


def test_zigzag_rejects_non_causal():
    mesh = make_context_mesh(4)
    q, k, v = _qkv(b=1, s=64, h=2, d=16)
    with pytest.raises(ValueError, match="causal"):
        context_parallel_attention(mesh, q, k, v, causal=False,
                                   impl="zigzag", interpret=True)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_full(causal):
    # 4-device axis, 4 heads -> 1 head per device after the all-to-all.
    mesh = make_context_mesh(4)
    q, k, v = _qkv(s=128, seed=17)
    out = context_parallel_attention(mesh, q, k, v, causal=causal,
                                     impl="ulysses", interpret=True)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_gradients_match_reference():
    from functools import partial

    from jax.sharding import NamedSharding, PartitionSpec as P

    from k3stpu.parallel.context import ulysses_attention

    mesh = make_context_mesh(2)
    q, k, v = _qkv(b=1, s=64, h=2, d=16, seed=18)
    spec = P(None, "seq", None, None)
    sh = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))

    ul = shard_map(partial(ulysses_attention, axis_name="seq",
                           interpret=True),
                   mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
                   check_vma=False)
    g_ul = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(ul(q, k, v) ** 2),
        argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(reference_attention(q, k, v) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for gu, gf in zip(g_ul, g_ref):
        np.testing.assert_allclose(np.asarray(gu), np.asarray(gf),
                                   atol=1e-4, rtol=1e-4)


def test_ulysses_rejects_indivisible_heads():
    mesh = make_context_mesh(8)
    q, k, v = _qkv(s=64, h=4)  # 4 heads, 8-way axis
    with pytest.raises(ValueError, match="divide"):
        context_parallel_attention(mesh, q, k, v, impl="ulysses",
                                   interpret=True)
