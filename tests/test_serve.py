"""Inference server: HTTP surface, batching/padding, error paths."""

import json
import threading
import urllib.request
from http.server import ThreadingHTTPServer

import numpy as np
import pytest

from k3stpu.serve.server import InferenceServer, make_app


@pytest.fixture(scope="module")
def http_server():
    server = InferenceServer(model_name="resnet18-tiny", num_classes=10,
                             image_size=32)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(server))
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()


def get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, json.loads(r.read())


def post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_healthz(http_server):
    status, body = get(http_server + "/healthz")
    assert status == 200 and body["ok"]
    assert body["devices"]


def test_model_card(http_server):
    status, body = get(http_server + "/v1/models")
    assert status == 200
    assert body["model"] == "resnet18-tiny"
    assert body["input_shape"] == [32, 32, 3]
    assert body["batch_sizes"] == [1, 8, 32]


def test_predict_batches_and_pads(http_server):
    # Batch of 3 -> padded to 8 internally, 3 results back.
    images = np.random.rand(3, 32, 32, 3).astype(np.float32)
    status, body = post(http_server + "/v1/predict",
                        {"inputs": images.tolist()})
    assert status == 200, body
    assert len(body["top5"]) == 3
    assert len(body["top5"][0]) == 5
    assert body["logits_shape"] == [3, 10]


def test_predict_wrong_shape_400(http_server):
    status, body = post(http_server + "/v1/predict",
                        {"inputs": [[1.0, 2.0]]})
    assert status == 400
    assert "expected input shape" in body["error"]


def test_predict_missing_key_400(http_server):
    status, body = post(http_server + "/v1/predict", {"nope": 1})
    assert status == 400


def test_predict_oversized_batch_400(http_server):
    images = np.zeros((33, 32, 32, 3), np.float32)
    status, body = post(http_server + "/v1/predict",
                        {"inputs": images.tolist()})
    assert status == 400
    assert "exceeds max" in body["error"]


def test_lm_server_predict():
    server = InferenceServer(model_name="transformer-tiny", seq_len=16)
    tokens = np.zeros((2, 16), np.int32)
    logits = server.predict(tokens)
    assert logits.shape == (2, 16, 512)
    card = server.model_card()
    assert card["stats"]["examples"] == 2

@pytest.fixture(scope="module")
def lm_server():
    server = InferenceServer(model_name="transformer-tiny", seq_len=64)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(server))
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()


def test_generate_endpoint(lm_server):
    status, body = post(lm_server + "/v1/generate",
                        {"prompt_tokens": [[1, 2, 3], [4, 5, 6, 7, 8]],
                         "max_new_tokens": 6})
    assert status == 200, body
    toks = body["tokens"]
    assert len(toks) == 2 and all(len(t) == 6 for t in toks)
    assert all(0 <= t < 512 for row in toks for t in row)


def test_generate_greedy_deterministic(lm_server):
    req = {"prompt_tokens": [[9, 8, 7, 6]], "max_new_tokens": 5}
    _, a = post(lm_server + "/v1/generate", req)
    _, b = post(lm_server + "/v1/generate", req)
    assert a["tokens"] == b["tokens"]


def test_generate_rejects_non_lm(http_server):
    status, body = post(http_server + "/v1/generate",
                        {"prompt_tokens": [[1, 2]]})
    assert status == 400
    assert "not a generative LM" in body["error"]


def test_generate_rejects_empty_prompt(lm_server):
    status, body = post(lm_server + "/v1/generate", {"prompt_tokens": [[]]})
    assert status == 400


def test_generate_rejects_too_long_prompt(lm_server):
    status, body = post(lm_server + "/v1/generate",
                        {"prompt_tokens": [list(range(65))]})
    assert status == 400
    assert "exceeds" in body["error"]


def test_generate_rejects_cache_overflow(lm_server):
    status, body = post(lm_server + "/v1/generate",
                        {"prompt_tokens": [list(range(1, 40))],
                         "max_new_tokens": 32})
    assert status == 400
    assert "KV cache" in body["error"]


# --- Micro-batching ---------------------------------------------------------

def test_concurrent_requests_coalesce():
    # 6 concurrent batch-1 requests within one window must land in far
    # fewer device dispatches (ideally 1) and all get correct slices back.
    server = InferenceServer(model_name="transformer-tiny", seq_len=16,
                             batch_window_ms=200.0)
    server.warmup(batch_sizes=(1, 8))
    tokens = np.arange(6 * 16, dtype=np.int32).reshape(6, 16) % 50
    single = [server.predict(tokens[i:i + 1]) for i in range(6)]
    stats0 = server.model_card()["stats"]
    d0, e0 = stats0["dispatches"], stats0["examples"]

    results: dict[int, np.ndarray] = {}
    lock = threading.Lock()

    def call(i):
        out = server.predict(tokens[i:i + 1])
        with lock:
            results[i] = out

    threads = [threading.Thread(target=call, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    card = server.model_card()
    assert len(results) == 6
    for i in range(6):  # same rows as the sequential singles
        np.testing.assert_allclose(results[i], single[i], rtol=2e-5,
                                   atol=2e-5)
    dispatches = card["stats"]["dispatches"] - d0
    assert dispatches <= 3, f"6 concurrent requests took {dispatches} dispatches"
    assert card["stats"]["examples"] - e0 == 6
    assert card["throughput"]["examples_per_s"] > 0


def test_batcher_carries_overflow():
    # A request that would overflow max_batch is carried whole, never split.
    from k3stpu.serve.server import MicroBatcher

    calls = []

    def run(batch, n_requests):
        calls.append((len(batch), n_requests))
        return batch

    mb = MicroBatcher(run, window_s=0.05, max_batch=4)
    outs = {}

    def submit(i, rows):
        outs[i] = mb.submit(np.full((rows, 2), i, np.float32))

    threads = [threading.Thread(target=submit, args=(0, 3)),
               threading.Thread(target=submit, args=(1, 3))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert sorted(len(v) for v in outs.values()) == [3, 3]
    for i, out in outs.items():
        assert (out == i).all()
    assert sorted(c[0] for c in calls) == [3, 3]  # two whole dispatches


def test_batcher_failure_propagates_to_all():
    from k3stpu.serve.server import MicroBatcher

    def run(batch, n_requests):
        raise RuntimeError("device exploded")

    mb = MicroBatcher(run, window_s=0.02, max_batch=8)
    errs = []

    def submit():
        try:
            mb.submit(np.zeros((1, 2), np.float32))
        except RuntimeError as e:
            errs.append(str(e))

    threads = [threading.Thread(target=submit) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert errs == ["device exploded"] * 3
    # The dispatcher loop must survive a failed batch.
    out = None
    def ok_run(batch, n_requests):
        return batch
    mb2 = MicroBatcher(ok_run, window_s=0.01, max_batch=8)
    out = mb2.submit(np.ones((2, 2), np.float32))
    assert out.shape == (2, 2)


def test_batcher_mixed_shapes_dispatch_separately():
    """A /v1/score width bucket (e.g. (n, 8)) landing in the same window
    as a full-width /v1/predict must not fail the batch: the batcher
    groups by trailing shape — one dispatch per shape, correct slices
    back to every caller."""
    from k3stpu.serve.server import MicroBatcher

    calls = []

    def run(batch, n_requests):
        calls.append(batch.shape)
        return batch

    mb = MicroBatcher(run, window_s=0.25, max_batch=8)
    outs = {}

    def submit(key, arr):
        outs[key] = mb.submit(arr)

    arrs = {"wide": np.full((2, 16), 1, np.float32),
            "narrow": np.full((3, 8), 2, np.float32),
            "narrow2": np.full((1, 8), 3, np.float32)}
    threads = [threading.Thread(target=submit, args=(k, v))
               for k, v in arrs.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    mb.close()
    for k, arr in arrs.items():
        np.testing.assert_array_equal(outs[k], arr)
    # Same-shape requests still coalesce: at most one dispatch per shape
    # (narrow + narrow2 may share one if they landed in the same window).
    assert len(calls) <= 3
    assert all(s[1] in (8, 16) for s in calls)


def test_window_zero_disables_coalescing():
    server = InferenceServer(model_name="transformer-tiny", seq_len=16,
                             batch_window_ms=0.0)
    assert server._batcher is None
    out = server.predict(np.zeros((2, 16), np.int32))
    assert out.shape[0] == 2
    assert server.model_card()["stats"]["dispatches"] == 1


def test_batcher_close_stops_dispatcher():
    import time as _time

    from k3stpu.serve.server import MicroBatcher

    mb = MicroBatcher(lambda b, n: b, window_s=0.01, max_batch=8)
    assert mb.submit(np.ones((1, 2), np.float32)).shape == (1, 2)
    mb.close()
    mb._thread.join(timeout=5)  # drains the sentinel and exits
    assert not mb._thread.is_alive()
    with pytest.raises(RuntimeError, match="closed"):
        mb.submit(np.ones((1, 2), np.float32))


# --- Tensor-parallel serving (multi-chip pods) -------------------------------

def test_sharded_serving_matches_single_device():
    """shard_devices=2: weights split over the 'model' axis, logits match
    the unsharded server bit-for-bit shapes and numerically."""
    import jax

    single = InferenceServer(model_name="transformer-tiny", seq_len=16,
                             batch_window_ms=0.0, shard_devices=1)
    sharded = InferenceServer(model_name="transformer-tiny", seq_len=16,
                              batch_window_ms=0.0, shard_devices=2)
    assert sharded._mesh is not None
    assert dict(sharded._mesh.shape)["model"] == 2
    # At least one weight actually landed split over 'model'.
    specs = {str(s.spec) for leaf in
             jax.tree.leaves(sharded._variables["params"])
             if (s := getattr(leaf, "sharding", None)) is not None}
    assert any("model" in spec for spec in specs)

    tokens = np.arange(2 * 16, dtype=np.int32).reshape(2, 16) % 50
    np.testing.assert_allclose(
        np.asarray(single.predict(tokens)),
        np.asarray(sharded.predict(tokens)), rtol=2e-5, atol=2e-5)
    assert sharded.model_card()["sharding"] == {"data": 1, "model": 2}


def test_sharded_serving_resnet():
    server = InferenceServer(model_name="resnet18-tiny", num_classes=10,
                             image_size=32, batch_window_ms=0.0,
                             shard_devices=2)
    out = server.predict(np.random.rand(2, 32, 32, 3).astype(np.float32))
    assert out.shape == (2, 10)
    assert np.isfinite(out).all()


def test_moe_serving_predict_and_generate():
    """The MoE family serves through the same endpoints: predict logits and
    KV-cache generation (router sow is a no-op outside training)."""
    server = InferenceServer(model_name="moe-tiny", seq_len=32,
                             batch_window_ms=0.0)
    tokens = np.arange(2 * 32, dtype=np.int32).reshape(2, 32) % 500
    logits = server.predict(tokens)
    assert logits.shape == (2, 32, 512)
    assert np.isfinite(logits).all()
    out = server.generate_tokens([[1, 2, 3]], max_new_tokens=4)
    assert len(out) == 1 and len(out[0]) == 4


def test_serve_from_train_checkpoint(tmp_path):
    """train -> checkpoint -> serve: the server boots the TRAINED weights
    (logits differ from fresh init and match the trained params)."""
    import jax
    import jax.numpy as jnp
    import optax

    from k3stpu.models.transformer import transformer_lm_tiny
    from k3stpu.parallel.mesh import make_mesh
    from k3stpu.parallel.train import (
        make_train_bundle, run_synthetic_steps, synth_token_batch)
    from k3stpu.utils import checkpoint as ckpt

    model = transformer_lm_tiny(max_seq_len=16)
    mesh = make_mesh(1, model_parallelism=1)
    bundle = make_train_bundle(
        model, mesh, example_input=jnp.zeros((1, 16), jnp.int32),
        optimizer=optax.adamw(3e-3))
    run_synthetic_steps(bundle, lambda k: synth_token_batch(k, 4, 16, 512),
                        n_steps=3)
    ckpt.save_bundle(tmp_path, 3, bundle)

    fresh = InferenceServer(model_name="transformer-tiny", seq_len=16,
                            batch_window_ms=0.0)
    served = InferenceServer(model_name="transformer-tiny", seq_len=16,
                             batch_window_ms=0.0, ckpt_dir=str(tmp_path))
    assert served.loaded_step == 3
    assert served.model_card()["checkpoint_step"] == 3

    # The served weights ARE the trained ones — exact at the param level
    # (compared on host: the two trees live on different device layouts).
    diffs = jax.tree.map(
        lambda a, b: float(np.max(np.abs(
            np.asarray(a, np.float32) - np.asarray(b, np.float32)))),
        served._variables["params"], bundle.params)
    assert max(jax.tree.leaves(diffs)) == 0.0

    tokens = np.arange(16, dtype=np.int32)[None] % 500
    out_served = served.predict(tokens)
    assert not np.allclose(out_served, fresh.predict(tokens), atol=1e-3)
    # bf16 tolerance: the jitted serving program and the eager apply fuse
    # differently, so logits agree only to bf16 rounding.
    direct = model.apply({"params": bundle.params}, jnp.asarray(tokens))
    np.testing.assert_allclose(out_served, np.asarray(direct),
                               rtol=0.05, atol=0.06)


def test_serve_rejects_missing_checkpoint(tmp_path):
    with pytest.raises(ValueError, match="no finalized checkpoint"):
        InferenceServer(model_name="transformer-tiny", seq_len=16,
                        ckpt_dir=str(tmp_path))


def test_serve_rejects_wrong_architecture_checkpoint(tmp_path):
    """A checkpoint from a different config must fail AT BOOT (shape check
    in the merge), not at first request."""
    import jax
    import jax.numpy as jnp

    from k3stpu.models.transformer import transformer_lm_tiny
    from k3stpu.utils import checkpoint as ckpt

    other = transformer_lm_tiny(max_seq_len=16, d_ff=64)  # narrower MLP
    vs = other.init(jax.random.key(0), jnp.zeros((1, 16), jnp.int32))
    ckpt.save_train_state(tmp_path, 1, {"params": vs["params"],
                                        "batch_stats": {}, "opt_state": {}})
    with pytest.raises(ValueError, match="architecture|shape"):
        InferenceServer(model_name="transformer-tiny", seq_len=16,
                        ckpt_dir=str(tmp_path))


def test_prometheus_metrics_endpoint():
    import urllib.request

    from k3stpu.serve.server import InferenceServer, make_app
    from http.server import ThreadingHTTPServer
    import threading as _th

    server = InferenceServer(model_name="transformer-tiny", seq_len=16,
                             batch_window_ms=0.0, shard_devices=1)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(server))
    _th.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        server.predict(np.zeros((2, 16), np.int32))
        url = f"http://127.0.0.1:{httpd.server_address[1]}/metrics"
        with urllib.request.urlopen(url, timeout=60) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            body = r.read().decode()
        assert "k3stpu_predict_examples_total 2" in body
        assert "# TYPE k3stpu_predict_requests_total counter" in body
        assert "k3stpu_generate_tokens_total 0" in body
    finally:
        httpd.shutdown()
        server.close()


def test_score_tokens_matches_model_logprobs():
    import jax
    import jax.numpy as jnp

    from k3stpu.serve.server import InferenceServer

    server = InferenceServer(model_name="transformer-tiny", seq_len=16,
                             batch_window_ms=0.0, shard_devices=1)
    try:
        seqs = [[5, 6, 7, 8], [9, 10]]
        got = server.score_tokens(seqs)
        assert [len(r) for r in got] == [3, 1]
        # Oracle: direct model logprobs for row 0.
        block = np.zeros((1, 8), np.int32)
        block[0, :4] = seqs[0]
        logits = server.model.apply(server._variables,
                                    jnp.asarray(block), train=False)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        for i, tok in enumerate(seqs[0][1:]):
            # bf16 jit-vs-eager fusion differences land ~1e-2 in log space.
            assert abs(float(logp[0, i, tok]) - got[0][i]) < 5e-2
        # Every logprob is a valid log-probability.
        assert all(v <= 0.0 for r in got for v in r)
    finally:
        server.close()


def test_score_endpoint_http():
    import json as _json
    import threading as _th
    import urllib.request

    from http.server import ThreadingHTTPServer

    from k3stpu.serve.server import InferenceServer, make_app

    server = InferenceServer(model_name="transformer-tiny", seq_len=16,
                             batch_window_ms=0.0, shard_devices=1)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(server))
    _th.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}/v1/score"
        req = urllib.request.Request(
            url, data=_json.dumps({"tokens": [[3, 4, 5]]}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            body = _json.loads(r.read())
        assert len(body["logprobs"][0]) == 2
        assert body["nll"][0] > 0
    finally:
        httpd.shutdown()
        server.close()


def test_sigterm_drains_and_exits_cleanly():
    """The serving pod's Recreate-strategy restart path: SIGTERM stops
    accepting, in-flight work finishes, and the process exits 0 with the
    drain log — not a mid-batch kill."""
    import os
    import signal
    import socket
    import subprocess
    import sys
    import time as _time
    import urllib.request

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # Deliberately REPLACE PYTHONPATH (don't join the parent's): the dev
    # box injects a sitecustomize there that force-registers the TPU
    # tunnel platform, which JAX_PLATFORMS=cpu does not override — the
    # child would hang on a wedged tunnel instead of starting on CPU.
    env["PYTHONPATH"] = repo_root
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "k3stpu.serve.server", "--model",
         "transformer-tiny", "--seq-len", "16", "--port", str(port),
         "--no-warmup"],
        env=env, text=True, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    try:
        deadline = _time.time() + 120
        while True:
            if proc.poll() is not None:  # crashed at startup: show why
                out, _ = proc.communicate()
                raise AssertionError(
                    f"server exited rc={proc.returncode}: {out[-2000:]}")
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=5):
                    break
            except Exception:
                assert _time.time() < deadline, "server never came up"
                _time.sleep(0.3)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out[-2000:]
    assert "draining" in out and "drained; bye" in out
