"""k3stpu.utils.env: the one tolerant env-knob parser (ISSUE 8 satellite).

Every K3STPU_* numeric knob is read on a path that must never die in a
ValueError (SIGTERM handlers, rendezvous retries, elastic heartbeats), so
the contract is: unset OR malformed -> default, never an exception.
"""

import pytest

from k3stpu.utils.env import env_flag, env_float, env_int


def test_env_float_unset_returns_default(monkeypatch):
    monkeypatch.delenv("K3STPU_T_FLOAT", raising=False)
    assert env_float("K3STPU_T_FLOAT", 2.5) == 2.5


def test_env_float_parses(monkeypatch):
    monkeypatch.setenv("K3STPU_T_FLOAT", "0.25")
    assert env_float("K3STPU_T_FLOAT", 2.5) == 0.25


def test_env_float_malformed_returns_default(monkeypatch):
    monkeypatch.setenv("K3STPU_T_FLOAT", "ninety")
    assert env_float("K3STPU_T_FLOAT", 2.5) == 2.5


def test_env_int_unset_and_parse(monkeypatch):
    monkeypatch.delenv("K3STPU_T_INT", raising=False)
    assert env_int("K3STPU_T_INT", 7) == 7
    monkeypatch.setenv("K3STPU_T_INT", "42")
    assert env_int("K3STPU_T_INT", 7) == 42


@pytest.mark.parametrize("bad", ["", "x", "1.5", " 3 3"])
def test_env_int_malformed_returns_default(monkeypatch, bad):
    # "1.5" is the important case: int("1.5") raises, and a knob
    # documented as an int must not half-accept floats.
    monkeypatch.setenv("K3STPU_T_INT", bad)
    assert env_int("K3STPU_T_INT", 7) == 7


@pytest.mark.parametrize("val,expect", [
    ("1", True), ("true", True), ("TRUE", True), ("yes", True),
    ("on", True), ("0", False), ("false", False), ("no", False),
    ("off", False), ("", False),
])
def test_env_flag_spellings(monkeypatch, val, expect):
    monkeypatch.setenv("K3STPU_T_FLAG", val)
    assert env_flag("K3STPU_T_FLAG") is expect


def test_env_flag_unset_and_unknown_use_default(monkeypatch):
    monkeypatch.delenv("K3STPU_T_FLAG", raising=False)
    assert env_flag("K3STPU_T_FLAG") is False
    assert env_flag("K3STPU_T_FLAG", True) is True
    monkeypatch.setenv("K3STPU_T_FLAG", "maybe")
    assert env_flag("K3STPU_T_FLAG", True) is True


def test_distributed_reexports_stay_importable():
    # Pre-existing callers import the underscore names from
    # distributed.py (tests/test_train_resilience.py does); the
    # consolidation must keep that surface alive.
    from k3stpu.parallel.distributed import _env_float, _env_int

    assert _env_float is env_float
    assert _env_int is env_int
