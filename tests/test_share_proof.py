"""Chip-sharing proof harness on the CPU stand-in backend.

The real artifact runs against the chip (k3stpu/share_proof.py docstring);
here the same parent/children machinery runs with the CPU backend so CI
verifies: env construction matches the plugin's Allocate, children really
execute concurrently, windows overlap, and the JSON oracle is well-formed.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_share_proof_concurrent_cpu():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU tunnel in children
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (REPO, env.get("PYTHONPATH")) if p)
    out = subprocess.run(
        [sys.executable, "-m", "k3stpu.share_proof",
         "--replicas", "2", "--dim", "256", "--timeout", "120"],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    line = next(l for l in out.stdout.splitlines()
                if l.startswith("SHARE_JSON "))
    rec = json.loads(line[len("SHARE_JSON "):])
    assert rec["mode"] == "concurrent"
    assert rec["ok"] is True
    assert rec["overlap_s"] > 0
    assert rec["env"]["TPU_MEM_FRACTION"] == "0.5000"
    assert rec["env"]["TPU_ALLOW_MULTIPLE_LIBTPU_PROCESSES"] == "1"
    assert len(rec["children"]) == 2
    for c in rec["children"]:
        assert c["ok"] and abs(c["checksum_per_elem"] - 1.0) < 0.05
