"""Flash-attention kernel vs the einsum oracle (Pallas interpret mode on CPU
— SURVEY.md §4's no-hardware test tier)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.ops.attention import flash_attention, reference_attention


def _qkv(b=2, s=256, h=4, d=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                          interpret=True)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_multiple_k_blocks_per_q_block():
    # block_q != block_k exercises the diagonal-crossing tiles.
    q, k, v = _qkv(s=512)
    out = flash_attention(q, k, v, block_q=256, block_k=64, interpret=True)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_bf16_tolerance():
    q, k, v = _qkv(dtype=jnp.bfloat16, seed=1)
    out = flash_attention(q, k, v, interpret=True)
    ref = reference_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=3e-2, rtol=3e-2)


def test_blocks_larger_than_seq_are_clamped():
    q, k, v = _qkv(s=128)
    out = flash_attention(q, k, v, block_q=512, block_k=512, interpret=True)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_indivisible_seq_raises():
    q, k, v = _qkv(s=192)
    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, k, v, block_q=128, block_k=128, interpret=True)


def test_gradients_match_reference():
    q, k, v = _qkv(b=1, s=128, h=2, d=32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=2e-4, rtol=2e-4)
