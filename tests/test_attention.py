"""Flash-attention kernel vs the einsum oracle (Pallas interpret mode on CPU
— SURVEY.md §4's no-hardware test tier)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.ops.attention import flash_attention, reference_attention


def _qkv(b=2, s=256, h=4, d=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                          interpret=True)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_multiple_k_blocks_per_q_block():
    # block_q != block_k exercises the diagonal-crossing tiles.
    q, k, v = _qkv(s=512)
    out = flash_attention(q, k, v, block_q=256, block_k=64, interpret=True)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_bf16_tolerance():
    q, k, v = _qkv(dtype=jnp.bfloat16, seed=1)
    out = flash_attention(q, k, v, interpret=True)
    ref = reference_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=3e-2, rtol=3e-2)


def test_blocks_larger_than_seq_are_clamped():
    q, k, v = _qkv(s=128)
    out = flash_attention(q, k, v, block_q=512, block_k=512, interpret=True)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_indivisible_seq_raises():
    q, k, v = _qkv(s=192)
    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, k, v, block_q=128, block_k=128, interpret=True)


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_reference(causal):
    q, k, v = _qkv(b=1, s=128, h=2, d=32)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=causal, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_multiblock(causal):
    # Several q AND k tiles so the backward's two accumulation sweeps (and
    # the causal tile-skip on both grids) are actually exercised.
    q, k, v = _qkv(b=1, s=256, h=2, d=32, seed=3)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=64,
                                       block_k=64, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("s_q,s_kv", [(128, 256), (256, 128)])
def test_causal_cross_length_matches_reference(s_q, s_kv):
    """End-aligned causal semantics must agree between kernel fwd, kernel
    bwd, and the einsum oracle when s_q != s_kv (the KV-prefix case; when
    s_q > s_kv the top rows are fully masked and must stay zero/nan-free)."""
    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (1, s_q, 2, 32), jnp.float32)
    k = jax.random.normal(ks[1], (1, s_kv, 2, 32), jnp.float32)
    v = jax.random.normal(ks[2], (1, s_kv, 2, 32), jnp.float32)

    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=64,
                                       block_k=64, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        assert np.all(np.isfinite(np.asarray(gf)))
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=2e-4, rtol=2e-4)


def test_fully_masked_rows_inside_live_tile_are_zero():
    """s_q > s_kv with the offset NOT a multiple of block_q: rows 0..31 of
    tile (0, 0) are fully masked but the tile is live — exp(s - m) with
    every s at the finite _NEG_INF must not turn into uniform weights."""
    ks = jax.random.split(jax.random.key(11), 3)
    q = jax.random.normal(ks[0], (1, 128, 2, 32), jnp.float32)
    k = jax.random.normal(ks[1], (1, 96, 2, 32), jnp.float32)
    v = jax.random.normal(ks[2], (1, 96, 2, 32), jnp.float32)

    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=32,
                          interpret=True)
    ref = reference_attention(q, k, v, causal=True)
    # Rows 0..31 see no keys (row r attends to cols <= r - 32): exact zero.
    np.testing.assert_array_equal(np.asarray(out[:, :32]), 0.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    g = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, causal=True, block_q=64, block_k=32,
        interpret=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(
        reference_attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        assert np.all(np.isfinite(np.asarray(a)))
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_gradients_bf16():
    q, k, v = _qkv(b=1, s=128, h=2, d=32, dtype=jnp.bfloat16, seed=5)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, interpret=True).astype(jnp.float32)
            ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(
            reference_attention(q, k, v).astype(jnp.float32) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        assert gf.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(gf, np.float32), np.asarray(gr, np.float32),
            atol=6e-2, rtol=6e-2)


def test_bench_bwd_chain_keeps_all_grad_kernels():
    """The fwd+bwd bench step must keep dq, dk AND dv live: a dq-only chain
    lets XLA dead-code-eliminate the dK/dV kernel and the 'backward' number
    measures a fraction of the backward (caught on-chip in round 2)."""
    import jax
    import jax.numpy as jnp

    from k3stpu.ops.attention import reference_attention

    # Mirror attn_bench's bwd_step shape with the einsum impl (kernel-free,
    # so the HLO dot count is a clean proxy; flash uses the same chaining).
    def bwd_step(q, k, v):
        dq, dk, dv = jax.grad(
            lambda q, k, v: jnp.sum(
                reference_attention(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        g = (dq.astype(jnp.float32)
             + 1e-3 * (dk.astype(jnp.float32) + dv.astype(jnp.float32)))
        rms = jnp.sqrt(jnp.mean(g * g) + 1e-12)
        return (g / rms).astype(q.dtype), k, v

    def bwd_step_dq_only(q, k, v):
        dq, _, _ = jax.grad(
            lambda q, k, v: jnp.sum(
                reference_attention(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        return dq, k, v

    shape = (1, 64, 2, 16)
    q = jnp.zeros(shape, jnp.bfloat16)

    def n_dots(fn):
        hlo = jax.jit(fn).lower(q, q, q).compile().as_text()
        return hlo.count(" dot(") + hlo.count(" dot.")

    full, partial = n_dots(bwd_step), n_dots(bwd_step_dq_only)
    assert full > partial, (
        f"chained bwd step compiled to {full} dots vs dq-only {partial}: "
        "dk/dv work is being dead-code-eliminated from the benchmark")


@pytest.mark.parametrize("kv_heads", [1, 2])
@pytest.mark.parametrize("causal", [True, False])
def test_flash_gqa_matches_reference(kv_heads, causal):
    """GQA/MQA: fewer kv heads read in place (no materialized repeat) must
    match the head-repeated einsum oracle, forward and gradients."""
    b, s, h, d = 2, 256, 4, 32
    ks = jax.random.split(jax.random.key(21), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, kv_heads, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, kv_heads, d), jnp.float32)

    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                          interpret=True)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    def loss(f):
        return jax.grad(
            lambda q, k, v: jnp.sum(
                f(q, k, v).astype(jnp.float32) ** 2), argnums=(0, 1, 2))

    flash_fn = lambda q, k, v: flash_attention(
        q, k, v, causal=causal, block_q=128, block_k=128, interpret=True)
    ref_fn = lambda q, k, v: reference_attention(q, k, v, causal=causal)
    for gf, gr in zip(loss(flash_fn)(q, k, v), loss(ref_fn)(q, k, v)):
        assert gf.shape == gr.shape  # dk/dv come back kv-head-shaped
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("window", [32, 128])
def test_flash_sliding_window_matches_reference(window):
    """Sliding-window causal attention: fwd and grads vs the banded einsum
    oracle; out-of-band tiles contribute nothing."""
    b, s, h, d = 1, 256, 2, 32
    ks = jax.random.split(jax.random.key(31), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.float32) for kk in ks)

    flash_fn = lambda q, k, v: flash_attention(
        q, k, v, causal=True, window=window, block_q=64, block_k=64,
        interpret=True)
    ref_fn = lambda q, k, v: reference_attention(q, k, v, causal=True,
                                                 window=window)
    np.testing.assert_allclose(np.asarray(flash_fn(q, k, v)),
                               np.asarray(ref_fn(q, k, v)),
                               atol=2e-5, rtol=2e-5)

    def grads(f):
        return jax.grad(
            lambda q, k, v: jnp.sum(f(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))(q, k, v)

    for gf, gr in zip(grads(flash_fn), grads(ref_fn)):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=1e-4, rtol=1e-4)


def test_flash_window_requires_causal():
    q = jnp.zeros((1, 64, 2, 16), jnp.float32)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, q, q, causal=False, window=16, interpret=True)


def test_flash_under_pjit_mesh_matches_oracle():
    """custom_partitioning: the kernel runs per-shard under a (data, model)
    mesh with q/k/v split on batch x heads — no replication fallback, same
    numbers as the einsum oracle (fwd AND grads)."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    b, s, h, d = 4, 256, 4, 64
    ks = jax.random.split(jax.random.key(11), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.bfloat16)
               for kk in ks)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                ("data", "model"))
    xs = NamedSharding(mesh, P("data", None, "model", None))

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v).astype(jnp.float32) ** 2) / (b * s * h * d)

    flash = lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=128, block_k=128, interpret=True)
    oracle = lambda q, k, v: reference_attention(q, k, v, causal=True)

    qs, ks_, vs = (jax.device_put(x, xs) for x in (q, k, v))
    out = jax.jit(flash, in_shardings=(xs, xs, xs))(qs, ks_, vs)
    ref = oracle(q, k, v)
    assert float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                 - ref.astype(jnp.float32)))) < 2e-2

    gf = jax.jit(jax.grad(loss(flash), argnums=(0, 1, 2)),
                 in_shardings=(xs, xs, xs))(qs, ks_, vs)
    go = jax.grad(loss(oracle), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, go):
        assert float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                     - b_.astype(jnp.float32)))) < 2e-2


def test_cp_flash_check_on_mesh():
    """The probe's context-parallel oracle (k3stpu/probe.py:cp_flash_check)
    on the 8-device CPU mesh: ring flash, zigzag, and Ulysses all agree
    with the einsum oracle through the real shard_map programs."""
    from k3stpu.probe import cp_flash_check

    out = cp_flash_check(interpret=True, seq=256, batch=2, heads=8,
                         head_dim=32)
    assert out["ok"], out
    assert out["mesh"] == "seq:8"


def test_spmd_flash_check_on_mesh():
    """The probe's SPMD oracle (k3stpu/probe.py:spmd_flash_check): flash
    fwd+grad THROUGH the custom_partitioning rule on the 8-device CPU mesh
    agrees with the direct kernel call. This is the CI stand-in for the
    on-chip SPMD_ATTN_JSON line the probe captures on hardware."""
    from k3stpu.probe import spmd_flash_check

    out = spmd_flash_check(interpret=True, seq=128, batch=8, heads=2,
                           head_dim=32)
    assert out["ok"], out
    assert out["mesh"].startswith("data:")


def test_flash_has_no_layout_transposes():
    """Every flash path consumes (B, S, H, D) directly — zero layout
    transposes (each one was a full O(S d) HBM round-trip plus a fused
    op through the relay): the no-lse inference primal AND the training
    forward+backward (natural-layout residuals). A regression
    reintroducing a fold shows up as a transpose primitive."""
    q = k = v = jnp.zeros((2, 256, 4, 128), jnp.bfloat16)
    jaxpr = jax.make_jaxpr(lambda q, k, v: flash_attention(
        q, k, v, causal=True, interpret=True))(q, k, v)
    assert "transpose" not in str(jaxpr)
    gj = jax.make_jaxpr(jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, causal=True, interpret=True).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2)))(q, k, v)
    assert "transpose" not in str(gj)


@pytest.mark.parametrize("kv_heads", [1, 2])
def test_flash_gqa_with_sliding_window(kv_heads):
    """GQA/MQA composed with a sliding window — the grouped kv index map
    and the window's live/mask clamps interact in the BSHD forward, so
    cover them together, fwd and grads."""
    ks = jax.random.split(jax.random.key(23), 3)
    q = jax.random.normal(ks[0], (1, 256, 4, 32), jnp.float32)
    k = jax.random.normal(ks[1], (1, 256, kv_heads, 32), jnp.float32)
    v = jax.random.normal(ks[2], (1, 256, kv_heads, 32), jnp.float32)

    out = flash_attention(q, k, v, causal=True, window=96, block_q=64,
                          block_k=64, interpret=True)
    ref = reference_attention(q, k, v, causal=True, window=96)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    g = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, causal=True, window=96, block_q=64, block_k=64,
        interpret=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(
        reference_attention(q, k, v, causal=True, window=96) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("kv_heads", [1, 2])
def test_fwd_lse_bwd_shard_gqa_matches_oracle(kv_heads):
    """The ring-attention building blocks (fwd_lse + bwd_shard) under
    GQA/MQA: a single-shard 'ring' must reproduce the oracle's forward
    AND gradients — the grouped kv index maps and the per-q-head dK/dV
    fold run in both pallas calls."""
    from k3stpu.ops.attention import (flash_attention_bwd_shard,
                                      flash_attention_fwd_lse)
    ks = jax.random.split(jax.random.key(31), 4)
    q = jax.random.normal(ks[0], (1, 256, 4, 32), jnp.float32)
    k = jax.random.normal(ks[1], (1, 256, kv_heads, 32), jnp.float32)
    v = jax.random.normal(ks[2], (1, 256, kv_heads, 32), jnp.float32)
    g = jax.random.normal(ks[3], (1, 256, 4, 32), jnp.float32)

    out, lse = flash_attention_fwd_lse(q, k, v, causal=True, block_q=64,
                                       block_k=64, interpret=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    dq, dk, dv = flash_attention_bwd_shard(
        q, k, v, out, lse, g, causal=True, block_q=64, block_k=64,
        interpret=True)
    gr = jax.grad(lambda q, k, v: jnp.sum(
        reference_attention(q, k, v, causal=True) * g),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip((dq, dk, dv), gr):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)
