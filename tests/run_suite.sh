#!/bin/bash
# Canonical full-suite gate, in TWO pytest processes.
#
# Why not one: a single process compiles hundreds of XLA:CPU programs,
# and after ~300 tests the in-process LLVM/JIT state has segfaulted
# mid-compile three separate times (always in backend_compile or the
# cache write, always past the 80% mark) — with every affected test
# passing in any smaller combination. Two processes halve the
# accumulated state; the persistent compile cache (tests/conftest.py)
# makes warm re-runs near compile-free, shrinking the window further.
# The round-3 judge independently ran the suite in two halves for the
# same reason.
#
# Usage: tests/run_suite.sh [extra pytest args...]
set -u
cd "$(dirname "$0")/.." || exit 2
export PYTHONPATH=
export JAX_PLATFORMS=cpu
case "${XLA_FLAGS:-}" in
  *xla_force_host_platform_device_count*) ;;
  *) export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8";;
esac

# Split point chosen to balance wall time (model/parallel files are the
# heavy half) and to keep each process well under the observed failure
# horizon.
HALF_A=$(ls tests/test_[a-o]*.py)
HALF_B=$(ls tests/test_[p-z]*.py)

python -m pytest $HALF_A -q "$@"; rc_a=$?
python -m pytest $HALF_B -q "$@"; rc_b=$?
echo "run_suite: half A rc=$rc_a, half B rc=$rc_b"
# rc 5 = NO_TESTS_COLLECTED: a -k filter whose matches all live in the
# other half must not fail the gate.
ok() { [ "$1" -eq 0 ] || [ "$1" -eq 5 ]; }
ok "$rc_a" && ok "$rc_b"
