#!/bin/bash
# Canonical full-suite gate, in TWO pytest processes.
#
# Why not one: a single process compiles hundreds of XLA:CPU programs,
# and after ~300 tests the in-process LLVM/JIT state has segfaulted
# mid-compile three separate times (always in backend_compile or the
# cache write, always past the 80% mark) — with every affected test
# passing in any smaller combination. Two processes halve the
# accumulated state; the persistent compile cache (tests/conftest.py)
# makes warm re-runs near compile-free, shrinking the window further.
# The round-3 judge independently ran the suite in two halves for the
# same reason.
#
# Usage: tests/run_suite.sh [--smoke] [extra pytest args...]
#
#   --smoke  Per-commit gate (~2 min warm): the full cluster layer
#            (chart, lint, manifests, plugin config, chips, discovery,
#            container runtime, device plugin — none of it compiles XLA
#            programs beyond the runtime shim's cmake build) plus the
#            two driver-critical JAX files (bench JSON contract, graft
#            entry + 8-device dryrun). The full two-process suite stays
#            the round gate; smoke exists so intermediate commits keep a
#            fast green signal as the suite's wall time grows. Paged-KV
#            exactness, the serving observability layer (histograms,
#            request traces, /debug endpoints), distributed tracing
#            (traceparent propagation, exemplars, trace_merge), the
#            chaos/containment suite (fault injection + recovery
#            invariants), and the training-resilience suite (SIGTERM
#            checkpointing, quarantine, retention, bounded rendezvous),
#            the fleet tier (node exporter, health labeling, tpu_top),
#            and the elastic-membership suite (env-knob parsing, ledger
#            liveness, rank-loss detection -> re-rendezvous -> resume),
#            and the speculative-decoding suite (drafter units,
#            exactness vs the plain engine, int8-paged-KV
#            drift/capacity), and the KV-tiering suite (host-store
#            units, swap round-trip exactness, pin hygiene, tier_swap
#            fault degradation), and the correctness-watchdog suite
#            (canary known-answer probes + SLO burn-rate math), and
#            the QoS suite (priority classes, predictive admission,
#            loss-free preemption bit-exactness), and the fleet
#            digital-twin suite (deterministic simulation identity/
#            byte-stability + the cool-down oscillation regression
#            pair) ride
#            along minus their @slow soak/bench tests (the full suite
#            runs those).
set -u
cd "$(dirname "$0")/.." || exit 2
export PYTHONPATH=
export JAX_PLATFORMS=cpu
case "${XLA_FLAGS:-}" in
  *xla_force_host_platform_device_count*) ;;
  *) export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8";;
esac

# The smoke set, as an array so the registry guard below can check it.
SMOKE=(
  tests/test_chart.py tests/test_chart_lint.py tests/test_manifests.py
  tests/test_plugin_config.py tests/test_chips.py tests/test_discovery.py
  tests/test_container_runtime.py tests/test_device_plugin.py
  tests/test_e2e_assets.py
  tests/test_bench.py tests/test_graft_entry.py
  tests/test_paged.py tests/test_paged_attention.py
  tests/test_obs.py tests/test_trace.py
  tests/test_chaos.py tests/test_train_resilience.py
  tests/test_train_obs.py tests/test_metrics_lint.py
  tests/test_node_obs.py
  tests/test_env.py tests/test_elastic.py
  tests/test_spec_engine.py
  tests/test_tiering.py
  tests/test_router.py
  tests/test_autoscaler.py
  tests/test_disagg.py
  tests/test_tp_serve.py
  tests/test_slo.py
  tests/test_canary.py
  tests/test_qos.py
  tests/test_sim.py
  tests/test_tsdb.py
)

# Full-suite-only files: every test file must be EITHER in SMOKE or
# listed here with a reason — a new test_*.py that is in neither fails
# the gate, so coverage can't silently rot out of the per-commit
# signal. "Heavy" means XLA compiles or long soaks that would blow the
# ~2 min smoke budget.
FULL_ONLY=(
  tests/test_attention.py        # heavy: XLA kernel compiles
  tests/test_attn_roofline.py    # heavy: roofline sweep
  tests/test_checkpoint.py       # heavy: orbax round-trips
  tests/test_context.py          # heavy: long-context compiles
  tests/test_data.py             # covered transitively by train tests
  tests/test_distributed.py      # heavy: multi-process rendezvous
  tests/test_engine.py           # heavy: engine loop compiles
  tests/test_generate.py         # heavy: decode-path compiles
  tests/test_integration.py      # heavy: end-to-end train+serve
  tests/test_lora.py             # heavy: adapter training
  tests/test_moe.py              # heavy: MoE compiles
  tests/test_multi_lora.py       # heavy: multi-adapter serving
  tests/test_parallel.py         # heavy: 8-device mesh programs
  tests/test_pipeline.py         # heavy: pipeline-parallel compiles
  tests/test_prompt_cache.py     # heavy: prefill compiles
  tests/test_properties.py       # heavy: hypothesis sweeps
  tests/test_quant.py            # heavy: quantized compiles
  tests/test_resnet.py           # heavy: conv compiles
  tests/test_sanitize.py         # covered by serve smoke surface
  tests/test_serve.py            # heavy: server + model compiles
  tests/test_share_proof.py      # heavy: sharing-proof compiles
  tests/test_speculative.py      # heavy: draft+target compiles
  tests/test_stream.py           # heavy: SSE + engine compiles
  tests/test_tpu_info.py         # fleet tier, no fast assertions left out
  tests/test_train_job.py        # heavy: train-loop compiles
  tests/test_transformer.py      # heavy: model compiles
)

# Registry guard: refuse to run if any test file is unregistered.
# (Runs for BOTH smoke and full invocations — the full suite globs
# everything anyway, but the guard is about keeping the smoke registry
# an explicit, reviewed decision rather than an omission.)
for f in tests/test_*.py; do
  registered=no
  for s in "${SMOKE[@]}" "${FULL_ONLY[@]}"; do
    [ "$s" = "$f" ] && registered=yes && break
  done
  if [ "$registered" = no ]; then
    echo "run_suite: $f is neither in SMOKE nor FULL_ONLY — register it" >&2
    exit 2
  fi
done

# Wedge forensics: if any single test exceeds this, pytest's builtin
# faulthandler dumps EVERY thread's stack before the outer timeout kills
# the process silently. The BENCH_r03..r05 wedges (device-tunnel hangs
# with zero diagnostics) are exactly the failure this pays for; the
# chaos suite (stalls, loop death) makes an accidental hang likelier.
FAULTHANDLER="-o faulthandler_timeout=${FAULTHANDLER_TIMEOUT:-600}"

if [ "${1:-}" = "--smoke" ]; then
  shift
  exec python -m pytest -q $FAULTHANDLER "${SMOKE[@]}" -m "not slow" "$@"
fi

# Split point chosen to balance wall time (model/parallel files are the
# heavy half) and to keep each process well under the observed failure
# horizon.
HALF_A=(tests/test_[a-o]*.py)
HALF_B=(tests/test_[p-z]*.py)
# An empty glob would hand pytest NO paths and it would collect all of
# tests/ — the single-process run this script exists to avoid.
[ -e "${HALF_A[0]}" ] || { echo "run_suite: half A glob empty"; exit 2; }
[ -e "${HALF_B[0]}" ] || { echo "run_suite: half B glob empty"; exit 2; }

python -m pytest "${HALF_A[@]}" -q $FAULTHANDLER "$@"; rc_a=$?
python -m pytest "${HALF_B[@]}" -q $FAULTHANDLER "$@"; rc_b=$?
echo "run_suite: half A rc=$rc_a, half B rc=$rc_b"
# rc 5 = NO_TESTS_COLLECTED is fine for ONE half (a -k filter whose
# matches live in the other half) — but both halves collecting nothing
# means a typo'd filter, and a gate must not pass green on zero tests.
if [ "$rc_a" -eq 5 ] && [ "$rc_b" -eq 5 ]; then
  echo "run_suite: no tests collected in either half"; exit 5
fi
ok() { [ "$1" -eq 0 ] || [ "$1" -eq 5 ]; }
ok "$rc_a" && ok "$rc_b"
