"""Input pipeline: memmap corpus sampling + async device prefetch."""

import numpy as np
import pytest

from k3stpu.data import DevicePrefetcher, TokenCorpus, synthetic_corpus
from k3stpu.data.corpus import write_token_file


@pytest.fixture()
def corpus(tmp_path):
    path = synthetic_corpus(tmp_path / "toks.bin", vocab_size=512,
                            n_tokens=4096, seed=3)
    return TokenCorpus(path, vocab_size=512)


def test_corpus_shapes_and_shift(corpus):
    rng = np.random.default_rng(0)
    inputs, labels = corpus.sample_batch(rng, batch=4, seq=32)
    assert inputs.shape == labels.shape == (4, 32)
    assert inputs.dtype == labels.dtype == np.int32
    # labels are inputs shifted by one within the same crop
    np.testing.assert_array_equal(inputs[:, 1:], labels[:, :-1])
    assert inputs.max() < 512 and inputs.min() >= 0


def test_corpus_crops_come_from_file(tmp_path):
    toks = np.arange(100) % 64
    path = write_token_file(tmp_path / "t.bin", toks, vocab_size=64)
    c = TokenCorpus(path, vocab_size=64)
    inputs, labels = c.sample_batch(np.random.default_rng(1), 2, 8)
    for row_in, row_lab in zip(inputs, labels):
        # Contiguity: each crop is consecutive mod-64 ramp values.
        np.testing.assert_array_equal((row_in[1:] - row_in[:-1]) % 64,
                                      np.ones(7, np.int32))
        np.testing.assert_array_equal(row_lab[:-1], row_in[1:])


def test_batches_deterministic_resume(corpus):
    a = corpus.batches(batch=2, seq=16, seed=7)
    first_five = [next(a) for _ in range(5)]
    b = corpus.batches(batch=2, seq=16, seed=7, start_step=3)
    for expect, got in zip(first_five[3:], [next(b), next(b)]):
        np.testing.assert_array_equal(expect[0], got[0])
        np.testing.assert_array_equal(expect[1], got[1])


def test_write_rejects_out_of_range(tmp_path):
    with pytest.raises(ValueError, match="outside"):
        write_token_file(tmp_path / "bad.bin", [0, 5, 700], vocab_size=512)


def test_prefetcher_preserves_order_and_values(corpus):
    batches = [corpus.sample_batch(np.random.default_rng(i), 2, 8)
               for i in range(6)]
    with DevicePrefetcher(iter(batches), depth=2) as pf:
        out = list(pf)
    assert len(out) == 6
    for (ei, el), (gi, gl) in zip(batches, out):
        np.testing.assert_array_equal(ei, np.asarray(gi))
        np.testing.assert_array_equal(el, np.asarray(gl))


def test_prefetcher_propagates_source_error():
    def bad_iter():
        yield (np.zeros((1, 2), np.int32), np.zeros((1, 2), np.int32))
        raise RuntimeError("corpus disappeared")

    pf = DevicePrefetcher(bad_iter())
    next(pf)
    with pytest.raises(RuntimeError, match="corpus disappeared"):
        next(pf)


def test_prefetcher_close_unblocks_producer(corpus):
    # An unconsumed infinite stream must not hang close().
    pf = DevicePrefetcher(corpus.batches(2, 8, seed=1), depth=1)
    next(pf)
    pf.close()
    pf._thread.join(timeout=5)
    assert not pf._thread.is_alive()


def test_train_job_with_corpus(tmp_path):
    """End to end: train_job consumes a corpus file through the prefetcher,
    checkpoints, and resumes with the same data order."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    data = synthetic_corpus(tmp_path / "corpus.bin", vocab_size=512,
                            n_tokens=1 << 14)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)

    def run(steps):
        out = subprocess.run(
            [sys.executable, "-m", "k3stpu.parallel.train_job",
             "--steps", str(steps), "--ckpt-dir", str(tmp_path / "ck"),
             "--ckpt-every", "2", "--data", str(data)],
            env=env, capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, out.stderr[-2000:]
        return [json.loads(l) for l in out.stdout.splitlines()]

    first = run(4)
    assert any(e["event"] == "data" for e in first)
    assert sum(e["event"] == "step" for e in first) == 4
    second = run(6)
    assert any(e["event"] == "resume" and e["step"] == 4 for e in second)
    assert sum(e["event"] == "step" for e in second) == 2


def test_corpus_rejects_dtype_mismatch(tmp_path):
    # A file written with the wrong dtype must fail loudly at open (the
    # head scan sees out-of-vocab values), not train on garbage.
    np.full(100, 70000, dtype=np.int64).tofile(tmp_path / "x.bin")
    with pytest.raises(ValueError, match="vocab"):
        TokenCorpus(tmp_path / "x.bin", vocab_size=512)
    # Non-whole-token file sizes are rejected outright.
    (tmp_path / "odd.bin").write_bytes(b"\x01\x02\x03")
    with pytest.raises(ValueError, match="whole number"):
        TokenCorpus(tmp_path / "odd.bin", vocab_size=512)


def test_prefetcher_stops_after_error():
    # "log and continue" consumers must get StopIteration after the error,
    # never a forever-blocking get().
    def bad_iter():
        raise RuntimeError("boom")
        yield  # noqa: unreachable — makes this a generator

    pf = DevicePrefetcher(bad_iter())
    with pytest.raises(RuntimeError, match="boom"):
        next(pf)
    with pytest.raises(StopIteration):
        next(pf)


def test_write_rejects_empty_and_float(tmp_path):
    with pytest.raises(ValueError, match="empty"):
        write_token_file(tmp_path / "e.bin", [], vocab_size=512)
    with pytest.raises(ValueError, match="integers"):
        write_token_file(tmp_path / "f.bin", np.array([0.9, 1.7]),
                         vocab_size=512)


def test_corpus_split_windows_are_disjoint(tmp_path):
    from k3stpu.data.corpus import TokenCorpus, write_token_file

    toks = np.arange(1000) % 97  # recognizable values
    path = write_token_file(tmp_path / "c.bin", toks, vocab_size=128)
    train = TokenCorpus(path, 128, split="train", holdout_fraction=0.1)
    ev = TokenCorpus(path, 128, split="eval", holdout_fraction=0.1)
    assert len(train) + len(ev) == 1000
    assert len(ev) == 100
    # The eval window is exactly the tail: its tokens continue where the
    # train window stops.
    assert np.array_equal(np.asarray(ev.tokens),
                          np.asarray(toks[900:]).astype(ev.tokens.dtype))
    with pytest.raises(ValueError, match="split"):
        TokenCorpus(path, 128, split="test")


def test_train_job_eval_loop(tmp_path):
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    data = synthetic_corpus(tmp_path / "c.bin", vocab_size=512,
                            n_tokens=1 << 14)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)
    out = subprocess.run(
        [sys.executable, "-m", "k3stpu.parallel.train_job",
         "--steps", "4", "--data", str(data), "--eval-every", "2",
         "--eval-batches", "2"],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    events = [json.loads(l) for l in out.stdout.splitlines()]
    assert next(e for e in events
                if e["event"] == "data")["split"] == "train"
    evals = [e for e in events if e["event"] == "eval"]
    assert [e["step"] for e in evals] == [2, 4]
    assert all(e["ppl"] > 0 for e in evals)


def test_sharded_corpus_directory(tmp_path):
    """A directory of shard files reads as one logical stream: crops can
    cross shard boundaries, splits window the concatenation, and the
    content round-trips exactly."""
    d = tmp_path / "shards"
    d.mkdir()
    all_toks = np.arange(300) % 97
    write_token_file(d / "shard-0000.bin", all_toks[:100], vocab_size=128)
    write_token_file(d / "shard-0001.bin", all_toks[100:250], vocab_size=128)
    write_token_file(d / "shard-0002.bin", all_toks[250:], vocab_size=128)

    c = TokenCorpus(d, 128)
    assert len(c) == 300
    # Exact content, including across both boundaries.
    assert np.array_equal(c.tokens[90:110],
                          all_toks[90:110].astype(c.tokens[0:1].dtype))
    assert np.array_equal(c.tokens[0:300], all_toks.astype(np.uint16))

    rng = np.random.default_rng(0)
    x, y = c.sample_batch(rng, batch=8, seq=32)
    assert x.shape == (8, 32)
    np.testing.assert_array_equal(x[:, 1:], y[:, :-1])

    ev = TokenCorpus(d, 128, split="eval", holdout_fraction=0.1)
    tr = TokenCorpus(d, 128, split="train", holdout_fraction=0.1)
    assert len(ev) == 30 and len(tr) == 270
    assert np.array_equal(ev.tokens[0:30], all_toks[270:].astype(np.uint16))


def test_sharded_corpus_rejects_empty_dir(tmp_path):
    d = tmp_path / "empty"
    d.mkdir()
    with pytest.raises(ValueError, match="no token shards"):
        TokenCorpus(d, 128)


def test_sharded_corpus_ignores_stray_files(tmp_path):
    """Manifests/READMEs beside the shards (what real tokenizer pipelines
    emit) must not enter the token stream — even when their byte size
    happens to divide the dtype width."""
    d = tmp_path / "shards"
    d.mkdir()
    all_toks = np.arange(200) % 97
    write_token_file(d / "shard-0000.bin", all_toks[:100], vocab_size=128)
    write_token_file(d / "shard-0001.bin", all_toks[100:], vocab_size=128)
    # 4 bytes: divides uint16 width, would silently prepend garbage tokens
    # (sorted first) without the suffix filter.
    (d / "MANIFEST.json").write_bytes(b'{"n"')
    (d / "README.md").write_text("tokenizer output")

    c = TokenCorpus(d, 128)
    assert len(c) == 200
    assert np.array_equal(c.tokens[0:200], all_toks.astype(np.uint16))

    with pytest.raises(ValueError, match="no token shards"):
        only_stray = tmp_path / "stray"
        only_stray.mkdir()
        (only_stray / "README.md").write_text("x")
        TokenCorpus(only_stray, 128)


# --- elastic re-sharding (ISSUE 8): world-size-invariant global order -----


def test_batch_row_span_partitions_exactly():
    from k3stpu.parallel.sharding import batch_row_span

    for world in (1, 2, 3, 4, 6, 12):
        spans = [batch_row_span(12, r, world) for r in range(world)]
        # Contiguous, ordered, and an exact partition of [0, 12).
        assert spans[0][0] == 0 and spans[-1][1] == 12
        for (lo_a, hi_a), (lo_b, _) in zip(spans, spans[1:]):
            assert hi_a == lo_b > lo_a


def test_batch_row_span_rejects_bad_shapes():
    from k3stpu.parallel.sharding import batch_row_span

    with pytest.raises(ValueError, match="not divisible"):
        batch_row_span(12, 0, 5)
    with pytest.raises(ValueError, match="outside"):
        batch_row_span(12, 4, 4)
    with pytest.raises(ValueError, match="< 1"):
        batch_row_span(12, 0, 0)


def test_rank_slices_reassemble_the_global_batch(corpus):
    """Every rank draws the same (seed, step)-keyed global rows and keeps
    its contiguous block: stacking the per-rank slices must reproduce the
    world-size-1 stream bit for bit."""
    for world in (2, 3, 4):
        whole = corpus.batches(batch=12, seq=16, seed=9)
        parts = [corpus.batches(batch=12, seq=16, seed=9, rank=r,
                                world_size=world) for r in range(world)]
        for _ in range(4):
            inputs, labels = next(whole)
            got = [next(p) for p in parts]
            np.testing.assert_array_equal(
                inputs, np.concatenate([g[0] for g in got]))
            np.testing.assert_array_equal(
                labels, np.concatenate([g[1] for g in got]))


def test_reshard_mid_stream_no_dup_no_gap(corpus):
    """The elastic resync scenario: world 4 trains steps 0-2, rank 3
    dies, the survivors re-shard to world 3 and resume at step 3 from
    the checkpoint. The union of rows trained per step must equal the
    global batch at EVERY step — nothing double-trained, nothing
    skipped, before or after the membership change."""
    batch, seq, seed = 12, 16, 11
    reference = corpus.batches(batch, seq, seed=seed)
    ref_steps = [next(reference) for _ in range(6)]

    trained = []  # per step: list of (inputs, labels) rank slices
    gen0 = [corpus.batches(batch, seq, seed=seed, rank=r, world_size=4)
            for r in range(4)]
    for _ in range(3):
        trained.append([next(s) for s in gen0])
    gen1 = [corpus.batches(batch, seq, seed=seed, start_step=3, rank=r,
                           world_size=3) for r in range(3)]
    for _ in range(3):
        trained.append([next(s) for s in gen1])

    for step, slices in enumerate(trained):
        np.testing.assert_array_equal(
            ref_steps[step][0], np.concatenate([s[0] for s in slices]),
            err_msg=f"step {step}")
        np.testing.assert_array_equal(
            ref_steps[step][1], np.concatenate([s[1] for s in slices]),
            err_msg=f"step {step}")
