"""Fleet tier: node exporter drop-file merge, health verdict, the
health->label feedback into the labeler, and the tpu_top sweep.

The load-bearing test is the ISSUE's acceptance E2E: two per-process
drops + a fake sysfs render merged per-chip gauges; aging one drop past
staleness flips k3stpu_node_tpu_health AND makes the labeler dry-run
emit google.com/tpu.healthy "false"; freshening it emits the
null-delete patch.
"""

import json
import os
import time
import urllib.request

import pytest

from k3stpu.discovery import labeler
from k3stpu.obs.hist import LabeledGauge
from k3stpu.obs import node_exporter
from k3stpu.obs.node_exporter import (
    HEALTH_STATES,
    NodeCollector,
    gc_stale_drops,
    health_verdict,
    merge_devices,
    read_drop_files,
    start_node_exporter_server,
)

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def _write_drop(dirpath, name, ts, devices):
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, name)
    with open(path, "w") as f:
        json.dump({"ts": ts, "devices": devices}, f)
    return path


def _dev(index, used=2**30, limit=16 * 2**30, duty=50):
    return {"index": index, "bytes_in_use": used, "bytes_limit": limit,
            "duty_cycle_pct": duty, "source": "pjrt"}


# ---------------------------------------------------------------- drops


def test_read_drop_files_merges_per_process(tmp_path):
    now = 1000.0
    _write_drop(tmp_path, "metrics-pod-a-7.json", 990, [_dev(0), _dev(1)])
    _write_drop(tmp_path, "metrics-pod-b-7.json", 995, [_dev(2), _dev(3)])
    drops, errors = read_drop_files(str(tmp_path), now=now)
    assert errors == 0
    assert [d["file"] for d in drops] == [
        "metrics-pod-a-7.json", "metrics-pod-b-7.json"]
    assert drops[0]["age_s"] == pytest.approx(10.0)
    merged = merge_devices(drops)
    assert sorted(merged) == [0, 1, 2, 3]
    assert merged[2]["_file"] == "metrics-pod-b-7.json"


def test_merge_freshest_report_wins_on_overlap(tmp_path):
    _write_drop(tmp_path, "metrics-old-1.json", 900, [_dev(0, used=111)])
    _write_drop(tmp_path, "metrics-new-2.json", 950, [_dev(0, used=222)])
    drops, _ = read_drop_files(str(tmp_path), now=1000.0)
    merged = merge_devices(drops)
    assert merged[0]["bytes_in_use"] == 222


def test_malformed_drop_counts_as_parse_error(tmp_path):
    _write_drop(tmp_path, "metrics-ok-1.json", 990, [_dev(0)])
    (tmp_path / "metrics-bad-2.json").write_text("{not json")
    (tmp_path / "metrics-nots-3.json").write_text('{"devices": []}')
    drops, errors = read_drop_files(str(tmp_path), now=1000.0)
    assert errors == 2
    assert [d["file"] for d in drops] == ["metrics-ok-1.json"]


def test_legacy_single_file_is_compat_read_only(tmp_path):
    # Old writers only: metrics.json is read when nothing newer exists…
    _write_drop(tmp_path, "metrics.json", 990, [_dev(0, used=42)])
    drops, _ = read_drop_files(str(tmp_path), now=1000.0)
    assert [d["file"] for d in drops] == ["metrics.json"]
    # …and skipped once a per-process file appears (the default writer
    # MIRRORS into metrics.json — counting both would double-count).
    _write_drop(tmp_path, "metrics-pod-1.json", 995, [_dev(0, used=99)])
    drops, _ = read_drop_files(str(tmp_path), now=1000.0)
    assert [d["file"] for d in drops] == ["metrics-pod-1.json"]
    assert merge_devices(drops)[0]["bytes_in_use"] == 99


def test_gc_removes_old_per_process_but_never_legacy(tmp_path):
    old = _write_drop(tmp_path, "metrics-dead-1.json", 0, [_dev(0)])
    fresh = _write_drop(tmp_path, "metrics-live-2.json", 0, [_dev(1)])
    legacy = _write_drop(tmp_path, "metrics.json", 0, [_dev(0)])
    past = time.time() - 10_000
    os.utime(old, (past, past))
    os.utime(legacy, (past, past))
    removed = gc_stale_drops(str(tmp_path), gc_after_s=900)
    assert removed == 1
    assert not os.path.exists(old)
    assert os.path.exists(fresh)
    assert os.path.exists(legacy)  # old writers rewrite it in place


def test_write_metrics_default_is_per_process_plus_legacy_mirror(
        tmp_path, monkeypatch):
    from k3stpu.utils import telemetry

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv(telemetry.DROP_DIR_ENV, str(tmp_path))
    monkeypatch.delenv(telemetry.DROP_ENV, raising=False)
    payload = telemetry.write_metrics()
    own = telemetry.process_drop_path()
    assert os.path.dirname(own) == str(tmp_path)
    assert os.path.basename(own).startswith("metrics-")
    assert os.path.basename(own).endswith(f"-{os.getpid()}.json")
    with open(own) as f:
        assert json.load(f) == payload
    with open(tmp_path / "metrics.json") as f:  # the C++ tpu-info read
        assert json.load(f) == payload
    # An explicit path writes ONLY that file.
    explicit = tmp_path / "sub" / "only.json"
    telemetry.write_metrics(str(explicit))
    assert explicit.exists()
    assert not (tmp_path / "sub" / "metrics.json").exists()


# -------------------------------------------------------------- verdict


def test_health_verdict_transitions():
    fresh = {"file": "metrics-a-1.json", "ts": 990, "age_s": 10.0,
             "devices": [_dev(0)]}
    stale = dict(fresh, file="metrics-b-2.json", age_s=500.0)
    assert health_verdict(4, 0, [fresh], 120)[0] == "healthy"
    # No drops at all is healthy-IDLE, not stale.
    assert health_verdict(4, 0, [], 120)[0] == "healthy"
    assert health_verdict(4, 0, [fresh, stale], 120)[0] == "stale-telemetry"
    assert health_verdict(4, 8, [fresh], 120)[0] == "missing-chips"
    # 0 expected chips trusts the inventory — never missing.
    assert health_verdict(0, 0, [], 120)[0] == "healthy"


def test_health_verdict_wedged_is_fresh_drop_with_no_device_data():
    empty = {"file": "metrics-w-1.json", "ts": 990, "age_s": 10.0,
             "devices": []}
    sentinel = dict(empty, devices=[_dev(0, used=-1, duty=-1),
                                    _dev(1, used=-1, duty=-1)])
    assert health_verdict(4, 0, [empty], 120)[0] == "wedged"
    assert health_verdict(4, 0, [sentinel], 120)[0] == "wedged"
    # A STALE wedge signal is just stale telemetry (the process that
    # wrote it may be long gone)…
    old_wedge = dict(empty, age_s=500.0)
    assert health_verdict(4, 0, [old_wedge], 120)[0] == "stale-telemetry"
    # …and wedged outranks missing-chips outranks stale.
    stale = {"file": "metrics-s-2.json", "ts": 1, "age_s": 500.0,
             "devices": [_dev(2)]}
    assert health_verdict(2, 8, [empty, stale], 120)[0] == "wedged"
    assert health_verdict(2, 8, [stale], 120)[0] == "missing-chips"


def test_labeled_gauge_clear_drops_series():
    g = LabeledGauge("k3stpu_test_g", "help", "chip")
    g.set("0", 1.5)
    g.set("1", 2)
    assert 'k3stpu_test_g{chip="0"} 1.5' in g.render()
    g.clear()
    assert g.get("0") is None
    assert "{" not in g.render()  # only HELP/TYPE left


# ------------------------------------------------------------ collector


def test_collector_merges_drops_with_sysfs(fake_host_root, tmp_path):
    drops = tmp_path / "drops"
    now = time.time()
    _write_drop(drops, "metrics-serve-1.json", now - 5,
                [_dev(0, used=3 * 2**30), _dev(1, used=2**30)])
    _write_drop(drops, "metrics-train-2.json", now - 9,
                [_dev(2, used=4 * 2**30, duty=80), _dev(3, used=2**30)])
    coll = NodeCollector(drop_dir=str(drops),
                         host_root_path=str(fake_host_root),
                         expected_chips=4)
    text = coll.render()
    assert "k3stpu_node_chips 4" in text
    assert "k3stpu_node_chips_expected 4" in text
    assert 'k3stpu_node_chip_hbm_used_bytes{chip="0"} 3221225472' in text
    assert 'k3stpu_node_chip_hbm_used_bytes{chip="2"} 4294967296' in text
    assert 'k3stpu_node_chip_duty_cycle_pct{chip="2"} 80' in text
    assert 'k3stpu_node_drop_file_stale{file="metrics-serve-1.json"} 0' \
        in text
    assert "k3stpu_node_drop_files 2" in text
    assert "k3stpu_node_tpu_health 0" in text
    assert 'k3stpu_node_tpu_health_state{state="healthy"} 1' in text


def test_collector_no_expected_chips_reports_inventory(fake_host_root,
                                                       tmp_path):
    coll = NodeCollector(drop_dir=str(tmp_path / "none"),
                         host_root_path=str(fake_host_root))
    text = coll.render()
    # Empty drop dir: healthy-idle, and expected falls back to sysfs.
    assert "k3stpu_node_chips_expected 4" in text
    assert "k3stpu_node_tpu_health 0" in text
    assert "k3stpu_node_drop_files 0" in text


def test_collector_gcd_series_disappear(fake_host_root, tmp_path):
    drops = tmp_path / "drops"
    now = time.time()
    dead = _write_drop(drops, "metrics-dead-1.json", now,
                       [_dev(0, used=7)])
    coll = NodeCollector(drop_dir=str(drops),
                         host_root_path=str(fake_host_root),
                         gc_after_s=900)
    assert 'chip="0"' in coll.render()
    past = now - 10_000
    os.utime(dead, (past, past))
    text = coll.render()
    assert 'chip="0"' not in text  # clear()+rebuild, not a frozen series
    assert "k3stpu_node_drop_files_gc_total 1" in text


def test_http_metrics_and_healthz(fake_host_root, tmp_path):
    drops = tmp_path / "drops"
    _write_drop(drops, "metrics-a-1.json", time.time(), [_dev(0)])
    coll = NodeCollector(drop_dir=str(drops),
                         host_root_path=str(fake_host_root))
    httpd = start_node_exporter_server(coll, port=0, host="127.0.0.1")
    try:
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            body = r.read().decode()
        assert r.status == 200
        assert "k3stpu_node_tpu_health 0" in body
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            doc = json.loads(r.read())
        # /healthz is a REPORT (always 200) — the verdict is the body.
        assert doc == {"state": "healthy", "code": 0, "reason": ""}
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_main_once_prints_exposition(fake_host_root, tmp_path, capsys):
    rc = node_exporter.main([
        "--once", "--host-root", str(fake_host_root),
        "--drop-dir", str(tmp_path / "drops"), "--expected-chips", "8"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "k3stpu_node_chips 4" in out
    assert "k3stpu_node_tpu_health 2" in out  # missing-chips: 4 < 8


# ----------------------------------------- acceptance E2E (ISSUE 6)


def _dry_run_labels(fake_host_root, drops, capsys):
    rc = labeler.main([
        "--once", "--dry-run", "--health",
        "--host-root", str(fake_host_root), "--drop-dir", str(drops)])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("LABELS_JSON ")]
    assert lines, "labeler emitted no LABELS_JSON"
    return json.loads(lines[-1].split(" ", 1)[1])


def test_fleet_e2e_stale_flips_health_and_label(fake_host_root, tmp_path,
                                                capsys):
    drops = tmp_path / "drops"
    now = time.time()
    _write_drop(drops, "metrics-serve-1.json", now,
                [_dev(0), _dev(1)])
    _write_drop(drops, "metrics-train-2.json", now,
                [_dev(2), _dev(3)])
    coll = NodeCollector(drop_dir=str(drops),
                         host_root_path=str(fake_host_root),
                         expected_chips=4, stale_after_s=120)

    # Phase 1: both drops fresh -> merged per-chip gauges, healthy,
    # and the labeler dry-run carries NO health labels (null-delete).
    text = coll.render()
    for chip in range(4):
        assert f'k3stpu_node_chip_hbm_used_bytes{{chip="{chip}"}}' in text
    assert "k3stpu_node_tpu_health 0" in text
    labels = _dry_run_labels(fake_host_root, drops, capsys)
    assert labels["google.com/tpu.present"] == "true"
    assert labels["google.com/tpu.healthy"] is None
    assert labels["google.com/tpu.health.state"] is None

    # Phase 2: age one drop past staleness -> health flips to
    # stale-telemetry and the label goes "false".
    _write_drop(drops, "metrics-train-2.json", now - 1000,
                [_dev(2), _dev(3)])
    text = coll.render()
    assert ("k3stpu_node_tpu_health "
            + str(HEALTH_STATES.index("stale-telemetry"))) in text
    assert 'k3stpu_node_drop_file_stale{file="metrics-train-2.json"} 1' \
        in text
    labels = _dry_run_labels(fake_host_root, drops, capsys)
    assert labels["google.com/tpu.healthy"] == "false"
    assert labels["google.com/tpu.health.state"] == "stale-telemetry"

    # Phase 3: the process reports again -> recovery null-deletes.
    _write_drop(drops, "metrics-train-2.json", time.time(),
                [_dev(2), _dev(3)])
    assert "k3stpu_node_tpu_health 0" in coll.render()
    labels = _dry_run_labels(fake_host_root, drops, capsys)
    assert labels["google.com/tpu.healthy"] is None
    assert labels["google.com/tpu.health.state"] is None


# -------------------------------------------------------------- tpu_top


def _load_tpu_top():
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "tpu_top.py")
    spec = importlib.util.spec_from_file_location("tpu_top", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_tpu_top_parses_rendered_exposition(fake_host_root, tmp_path):
    top = _load_tpu_top()
    drops = tmp_path / "drops"
    _write_drop(drops, "metrics-a-1.json", time.time(),
                [_dev(0, used=2**30, duty=75)])
    coll = NodeCollector(drop_dir=str(drops),
                         host_root_path=str(fake_host_root),
                         expected_chips=4)
    fams = top.parse_families(coll.render())
    row = top.node_row("http://node-a:8478", fams)
    assert row["node"] == "node-a:8478"
    assert row["health"] == "healthy"
    assert row["chips"] == 4 and row["expected"] == 4
    assert row["devices"] == [
        {"chip": "0", "used": 2**30, "limit": 16 * 2**30, "duty": 75}]
    table = top.render_table([row])
    assert "node-a:8478" in table and "healthy" in table
    assert "chip 0" in table and "1.0/16.0 GiB" in table


def test_tpu_top_sweep_live_and_unreachable(fake_host_root, tmp_path):
    top = _load_tpu_top()
    drops = tmp_path / "drops"
    _write_drop(drops, "metrics-a-1.json", time.time(), [_dev(0)])
    coll = NodeCollector(drop_dir=str(drops),
                         host_root_path=str(fake_host_root))
    httpd = start_node_exporter_server(coll, port=0, host="127.0.0.1")
    try:
        live = f"http://127.0.0.1:{httpd.server_address[1]}"
        # Port 1: reserved/unassigned — connection refused immediately.
        rows = top.sweep([live, "http://127.0.0.1:1"], timeout=2.0)
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert rows[0]["health"] == "healthy"
    assert rows[1]["health"] == "unreachable"
    table = top.render_table(rows)
    assert "unreachable" in table
