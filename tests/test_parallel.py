"""Sharded training over the 8-virtual-device CPU mesh: the real pjit path
(dp gradients + tp kernels), no TPU needed."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.models.resnet import ResNet, BasicBlock
from k3stpu.parallel.mesh import make_mesh, mesh_shape_for
from k3stpu.parallel.train import (
    make_train_bundle,
    run_synthetic_steps,
    synth_image_batch,
)


def test_make_mesh_shape():
    mesh = make_mesh(8, model_parallelism=2)
    assert dict(mesh.shape) == {"data": 4, "model": 2}
    assert mesh_shape_for(16) == (4, 4)
    assert mesh_shape_for(8) == (4, 2)


def test_make_mesh_too_many():
    with pytest.raises(ValueError):
        make_mesh(1024)


def test_sharded_train_step_runs_and_shards():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    mesh = make_mesh(8, model_parallelism=2)
    model = ResNet(stage_sizes=(1, 1), block=BasicBlock, num_classes=16,
                   num_filters=16)
    image_shape = (16, 16, 3)
    bundle = make_train_bundle(
        model, mesh, example_input=jnp.zeros((1, *image_shape), jnp.float32))

    # Parameters with a feature axis must actually be sharded over 'model'.
    head_kernel = bundle.params["head"]["kernel"]
    assert len(head_kernel.sharding.device_set) == 8
    shard_shapes = {s.data.shape for s in head_kernel.addressable_shards}
    assert shard_shapes == {(head_kernel.shape[0], head_kernel.shape[1] // 2)}

    losses = [
        run_synthetic_steps(
            bundle, lambda k: synth_image_batch(k, 8, image_shape, 16))
        for _ in range(3)
    ]
    assert all(np.isfinite(l) for l in losses)
    # SGD on repeated synthetic batches should not diverge to inf/nan.
    assert losses[-1] == losses[-1]


def test_batch_divisibility_validated():
    mesh = make_mesh(8, model_parallelism=2)
    model = ResNet(stage_sizes=(1,), block=BasicBlock, num_classes=4,
                   num_filters=8)
    bundle = make_train_bundle(
        model, mesh, example_input=jnp.zeros((1, 8, 8, 3), jnp.float32))
    with pytest.raises(ValueError, match="not divisible"):
        bundle.run(jnp.zeros((6, 8, 8, 3)), jnp.zeros((6,), jnp.int32))


def test_grad_accumulation_updates_every_k():
    """optax.MultiSteps through the sharded bundle: grads accumulate for
    k micro-steps, params move only on the k-th."""
    import optax

    from k3stpu.models.transformer import transformer_lm_tiny
    from k3stpu.parallel.mesh import make_mesh
    from k3stpu.parallel.train import make_train_bundle, synth_token_batch

    model = transformer_lm_tiny()
    mesh = make_mesh(4, model_parallelism=2)
    tx = optax.MultiSteps(optax.sgd(0.1), every_k_schedule=2)
    bundle = make_train_bundle(
        model, mesh, example_input=jnp.zeros((1, 16), jnp.int32),
        optimizer=tx)
    p0 = jax.tree.map(lambda x: np.asarray(x).copy(), bundle.params)
    x, y = synth_token_batch(jax.random.key(0), 4, 16,
                             model.config.vocab_size)
    bundle.run(x, y)
    p1 = jax.tree.map(lambda x: np.asarray(x), bundle.params)
    same = all(np.array_equal(a, b) for a, b in zip(
        jax.tree.leaves(p0), jax.tree.leaves(p1)))
    assert same, "params must not move on an accumulation micro-step"
    bundle.run(x, y)
    p2 = jax.tree.map(lambda x: np.asarray(x), bundle.params)
    moved = any(not np.array_equal(a, b) for a, b in zip(
        jax.tree.leaves(p0), jax.tree.leaves(p2)))
    assert moved, "params must move on the k-th micro-step"


def test_train_job_grad_accum_and_cosine_cli(tmp_path):
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)
    out = subprocess.run(
        [sys.executable, "-m", "k3stpu.parallel.train_job",
         "--steps", "4", "--grad-accum", "2", "--lr-schedule", "cosine",
         "--warmup-steps", "1"],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    events = [json.loads(l) for l in out.stdout.splitlines()]
    assert sum(e["event"] == "step" for e in events) == 4
