"""Sharded training over the 8-virtual-device CPU mesh: the real pjit path
(dp gradients + tp kernels), no TPU needed."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.models.resnet import ResNet, BasicBlock
from k3stpu.parallel.mesh import make_mesh, mesh_shape_for
from k3stpu.parallel.train import (
    make_train_bundle,
    run_synthetic_steps,
    synth_image_batch,
)


def test_make_mesh_shape():
    mesh = make_mesh(8, model_parallelism=2)
    assert dict(mesh.shape) == {"data": 4, "model": 2}
    assert mesh_shape_for(16) == (4, 4)
    assert mesh_shape_for(8) == (4, 2)


def test_make_mesh_too_many():
    with pytest.raises(ValueError):
        make_mesh(1024)


def test_sharded_train_step_runs_and_shards():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    mesh = make_mesh(8, model_parallelism=2)
    model = ResNet(stage_sizes=(1, 1), block=BasicBlock, num_classes=16,
                   num_filters=16)
    image_shape = (16, 16, 3)
    bundle = make_train_bundle(
        model, mesh, example_input=jnp.zeros((1, *image_shape), jnp.float32))

    # Parameters with a feature axis must actually be sharded over 'model'.
    head_kernel = bundle.params["head"]["kernel"]
    assert len(head_kernel.sharding.device_set) == 8
    shard_shapes = {s.data.shape for s in head_kernel.addressable_shards}
    assert shard_shapes == {(head_kernel.shape[0], head_kernel.shape[1] // 2)}

    losses = [
        run_synthetic_steps(
            bundle, lambda k: synth_image_batch(k, 8, image_shape, 16))
        for _ in range(3)
    ]
    assert all(np.isfinite(l) for l in losses)
    # SGD on repeated synthetic batches should not diverge to inf/nan.
    assert losses[-1] == losses[-1]


def test_batch_divisibility_validated():
    mesh = make_mesh(8, model_parallelism=2)
    model = ResNet(stage_sizes=(1,), block=BasicBlock, num_classes=4,
                   num_filters=8)
    bundle = make_train_bundle(
        model, mesh, example_input=jnp.zeros((1, 8, 8, 3), jnp.float32))
    with pytest.raises(ValueError, match="not divisible"):
        bundle.run(jnp.zeros((6, 8, 8, 3)), jnp.zeros((6,), jnp.int32))
