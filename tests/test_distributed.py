"""Rendezvous derivation + collective measurement on the virtual CPU mesh."""

import json

import jax
import pytest

from k3stpu.parallel.distributed import Rendezvous, rendezvous_from_env
from k3stpu.parallel.mesh import make_mesh


def test_indexed_job_derivation():
    # Exactly the env an Indexed Job pod sees (tpu-pjit-job.yaml).
    rdv = rendezvous_from_env(
        env={
            "K3STPU_NUM_PROCESSES": "2",
            "K3STPU_COORDINATOR_SERVICE": "tpu-pjit",
            "K3STPU_COORDINATOR_PORT": "8476",
            "JOB_COMPLETION_INDEX": "1",
        },
        hostname="tpu-pjit-1",
    )
    assert rdv == Rendezvous("tpu-pjit-0.tpu-pjit:8476", 2, 1)
    assert rdv.is_distributed


def test_hostname_fallback_without_index_env():
    rdv = rendezvous_from_env(
        env={"K3STPU_NUM_PROCESSES": "4",
             "K3STPU_COORDINATOR_SERVICE": "tpu-pjit"},
        hostname="tpu-pjit-3",
    )
    assert rdv.process_id == 3
    assert rdv.coordinator_address == "tpu-pjit-0.tpu-pjit:8476"


def test_explicit_overrides_win():
    rdv = rendezvous_from_env(
        env={
            "K3STPU_NUM_PROCESSES": "8",
            "K3STPU_PROCESS_ID": "5",
            "K3STPU_COORDINATOR": "coord.example:9999",
            "JOB_COMPLETION_INDEX": "1",
        },
        hostname="whatever-1",
    )
    assert rdv == Rendezvous("coord.example:9999", 8, 5)


def test_single_process_fallback():
    rdv = rendezvous_from_env(env={}, hostname="laptop")
    assert rdv.num_processes == 1
    assert rdv.process_id == 0
    assert not rdv.is_distributed


def test_psum_allreduce_measurement():
    from k3stpu.ops.collectives import measure_psum_allreduce

    mesh = make_mesh(8, model_parallelism=2)
    res = measure_psum_allreduce(mesh, mbytes=0.5, iters=2, trials=1)
    assert res.n_devices == 8
    assert res.algo_gbps > 0
    assert res.bus_gbps == pytest.approx(res.algo_gbps * 2 * 7 / 8)


def test_launch_main_single_process(capsys, monkeypatch):
    # The Job entry point end-to-end on the virtual mesh (1 process).
    monkeypatch.delenv("K3STPU_NUM_PROCESSES", raising=False)
    from k3stpu.parallel import launch

    rc = launch.main(["--m", "256", "--iters", "2", "--mbytes", "0.25"])
    assert rc == 0
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    events = {l["event"]: l for l in lines}
    assert events["rendezvous"]["num_processes"] == 1
    assert events["rendezvous"]["global_devices"] == len(jax.devices())
    assert events["pjit_matmul"]["seconds"] > 0
    assert events["psum_allreduce"]["bus_gbps"] > 0


def test_two_process_rendezvous_and_psum(tmp_path):
    """The north-star Job path actually executes: two real processes with
    fake Indexed-Job env rendezvous via jax.distributed.initialize on a
    localhost coordinator, form the GLOBAL 2-device mesh, and a psum sums
    both processes' shards (SURVEY.md §3.5; tpu-pjit-job.yaml env)."""
    import os
    import socket
    import subprocess
    import sys

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "rdv_worker.py")
    with socket.socket() as s:  # free localhost port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    procs = []
    for i in range(2):
        env = dict(os.environ)
        # No axon/TPU tunnel in the children; 1 CPU device per process.
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        repo_root = os.path.dirname(os.path.dirname(worker))
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (repo_root, env.get("PYTHONPATH")) if p)
        # The Indexed-Job pod environment (deploy/manifests/tpu-pjit-job.yaml):
        # pod hostname <job>-<index>, kubelet-set JOB_COMPLETION_INDEX, and a
        # coordinator address (in-cluster it comes from the headless Service;
        # here the explicit-override leg pins it to localhost).
        env["HOSTNAME"] = f"tpu-pjit-{i}"
        env["JOB_COMPLETION_INDEX"] = str(i)
        env["K3STPU_NUM_PROCESSES"] = "2"
        env["K3STPU_COORDINATOR"] = f"127.0.0.1:{port}"
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))

    results = {}
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            assert p.returncode == 0, f"worker failed rc={p.returncode}: {err[-2000:]}"
            rec = json.loads(out.strip().splitlines()[-1])
            results[rec["process_id"]] = rec
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    assert set(results) == {0, 1}
    for rec in results.values():
        assert rec["num_processes"] == 2
        assert rec["jax_process_count"] == 2
        assert rec["global_devices"] == 4   # 2 processes x 2 local devices
        assert rec["local_devices"] == 2
        assert rec["psum_total"] == rec["expected_total"] == 10.0
        # model axis confined to one process's devices (ICI not DCN)
        assert rec["hybrid_mesh_ok"] is True
