"""Rendezvous derivation + collective measurement on the virtual CPU mesh."""

import json

import jax
import pytest

from k3stpu.parallel.distributed import Rendezvous, rendezvous_from_env
from k3stpu.parallel.mesh import make_mesh


def test_indexed_job_derivation():
    # Exactly the env an Indexed Job pod sees (tpu-pjit-job.yaml).
    rdv = rendezvous_from_env(
        env={
            "K3STPU_NUM_PROCESSES": "2",
            "K3STPU_COORDINATOR_SERVICE": "tpu-pjit",
            "K3STPU_COORDINATOR_PORT": "8476",
            "JOB_COMPLETION_INDEX": "1",
        },
        hostname="tpu-pjit-1",
    )
    assert rdv == Rendezvous("tpu-pjit-0.tpu-pjit:8476", 2, 1)
    assert rdv.is_distributed


def test_hostname_fallback_without_index_env():
    rdv = rendezvous_from_env(
        env={"K3STPU_NUM_PROCESSES": "4",
             "K3STPU_COORDINATOR_SERVICE": "tpu-pjit"},
        hostname="tpu-pjit-3",
    )
    assert rdv.process_id == 3
    assert rdv.coordinator_address == "tpu-pjit-0.tpu-pjit:8476"


def test_explicit_overrides_win():
    rdv = rendezvous_from_env(
        env={
            "K3STPU_NUM_PROCESSES": "8",
            "K3STPU_PROCESS_ID": "5",
            "K3STPU_COORDINATOR": "coord.example:9999",
            "JOB_COMPLETION_INDEX": "1",
        },
        hostname="whatever-1",
    )
    assert rdv == Rendezvous("coord.example:9999", 8, 5)


def test_single_process_fallback():
    rdv = rendezvous_from_env(env={}, hostname="laptop")
    assert rdv.num_processes == 1
    assert rdv.process_id == 0
    assert not rdv.is_distributed


def test_psum_allreduce_measurement():
    from k3stpu.ops.collectives import measure_psum_allreduce

    mesh = make_mesh(8, model_parallelism=2)
    res = measure_psum_allreduce(mesh, mbytes=0.5, iters=2, trials=1)
    assert res.n_devices == 8
    assert res.algo_gbps > 0
    assert res.bus_gbps == pytest.approx(res.algo_gbps * 2 * 7 / 8)


def test_launch_main_single_process(capsys, monkeypatch):
    # The Job entry point end-to-end on the virtual mesh (1 process).
    monkeypatch.delenv("K3STPU_NUM_PROCESSES", raising=False)
    from k3stpu.parallel import launch

    rc = launch.main(["--m", "256", "--iters", "2", "--mbytes", "0.25"])
    assert rc == 0
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    events = {l["event"]: l for l in lines}
    assert events["rendezvous"]["num_processes"] == 1
    assert events["rendezvous"]["global_devices"] == len(jax.devices())
    assert events["pjit_matmul"]["seconds"] > 0
    assert events["psum_allreduce"]["bus_gbps"] > 0


def _mp_env(i, port, n_local_devices):
    """The Indexed-Job pod environment (tpu-pjit-job.yaml) for a local
    2-process rehearsal: CPU backend, no axon tunnel, localhost
    coordinator pinned via the explicit-override leg."""
    import os

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count="
                        f"{n_local_devices}")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p)
    env["HOSTNAME"] = f"tpu-pjit-{i}"
    env["JOB_COMPLETION_INDEX"] = str(i)
    env["K3STPU_NUM_PROCESSES"] = "2"
    env["K3STPU_COORDINATOR"] = f"127.0.0.1:{port}"
    return env


def test_two_process_train_job_loss_parity():
    """The north-star train Job (BASELINE config 5's closest executable
    stand-in): train_job itself runs 2 processes x 4 devices each — dp
    over a DCN-like process boundary — and its per-step losses match both
    across the two processes AND a single-process 8-device run of the
    same config. Gradient psum over the process boundary therefore
    computes exactly what one host computes."""
    import os
    import socket
    import subprocess
    import sys

    args = ["-m", "k3stpu.parallel.train_job", "--steps", "3",
            "--model", "tiny", "--batch", "8", "--seq", "32"]

    def step_losses(out):
        recs = [json.loads(l) for l in out.splitlines()
                if l.startswith('{"event": "step"')]
        return [r["loss"] for r in recs]

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = [subprocess.Popen([sys.executable, *args],
                              env=_mp_env(i, port, 4), text=True,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE)
             for i in range(2)]
    losses = {}
    try:
        for i, p in enumerate(procs):
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"rank {i} rc={p.returncode}: {err[-2000:]}"
            losses[i] = step_losses(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert len(losses[0]) == 3
    assert losses[0] == losses[1], "ranks disagree on the loss sequence"

    env1 = _mp_env(0, 0, 8)
    for k in ("HOSTNAME", "JOB_COMPLETION_INDEX", "K3STPU_NUM_PROCESSES",
              "K3STPU_COORDINATOR"):
        env1.pop(k, None)
    single = subprocess.run([sys.executable, *args], env=env1, text=True,
                            capture_output=True, timeout=300)
    assert single.returncode == 0, single.stderr[-2000:]
    assert step_losses(single.stdout) == losses[0], (
        "2-process dp loss differs from single-process")


def test_two_process_rendezvous_and_psum(tmp_path):
    """The north-star Job path actually executes: two real processes with
    fake Indexed-Job env rendezvous via jax.distributed.initialize on a
    localhost coordinator, form the GLOBAL 2-device mesh, and a psum sums
    both processes' shards (SURVEY.md §3.5; tpu-pjit-job.yaml env)."""
    import os
    import socket
    import subprocess
    import sys

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "rdv_worker.py")
    with socket.socket() as s:  # free localhost port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    # Same fake pod env as the train rehearsal (the worker pins its own
    # 2-device count in-process, overriding _mp_env's XLA_FLAGS).
    procs = [subprocess.Popen(
        [sys.executable, worker], env=_mp_env(i, port, 2), text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for i in range(2)]

    results = {}
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            assert p.returncode == 0, f"worker failed rc={p.returncode}: {err[-2000:]}"
            rec = json.loads(out.strip().splitlines()[-1])
            results[rec["process_id"]] = rec
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    assert set(results) == {0, 1}
    for rec in results.values():
        assert rec["num_processes"] == 2
        assert rec["jax_process_count"] == 2
        assert rec["global_devices"] == 4   # 2 processes x 2 local devices
        assert rec["local_devices"] == 2
        assert rec["psum_total"] == rec["expected_total"] == 10.0
        # model axis confined to one process's devices (ICI not DCN)
        assert rec["hybrid_mesh_ok"] is True
