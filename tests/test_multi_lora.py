"""Multi-LoRA serving: N fine-tunes of one base, routed per request.

Three exactness bars:
- model math: the row-routed delta path must agree with independently
  FOLDING each adapter into the kernels (merge_lora_params) to bf16
  tolerance — two different float paths computing the same function;
- routing: the engine/server output for adapter k must be EXACTLY
  ``generate()`` with ``adapter_ids = k`` (same model, so bit-equal);
- isolation: adapter id 0 is exactly the base model, and requests on
  different adapters interleaved in one slot batch stay exact.
CPU-JAX stand-in per SURVEY.md §4.
"""

import threading
import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.models.generate import generate
from k3stpu.models.lora import (
    build_multi_lora_params,
    merge_lora_params,
)
from k3stpu.models.transformer import transformer_lm_tiny
from k3stpu.serve.engine import GenerateEngine

SEQ = 32
RANK = 4


def _adapter_tree(seed: int, scale: float = 0.3) -> dict:
    """A rank-RANK single-adapter LoRA tree with deterministic nonzero
    deltas (as if trained) — lora_b must be nonzero or the adapter IS
    the base. ``scale`` sets the delta magnitude: 0.3 makes adapters
    visibly diverge from the base (routing tests); a small scale keeps
    greedy chains clear of sub-ulp argmax ties (TP-equality tests)."""
    lmodel = transformer_lm_tiny(max_seq_len=SEQ, lora_rank=RANK)
    lvars = lmodel.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                        train=False)

    def perturb(path, x):
        if getattr(path[-1], "key", None) in ("lora_a", "lora_b"):
            # crc32, not hash(): str hashing is PYTHONHASHSEED-salted, and
            # per-process adapter weights would make the tolerance-based
            # fold-oracle comparison unreproducible.
            k = jax.random.fold_in(jax.random.key(seed),
                                   zlib.crc32(str(path).encode()))
            return scale * jax.random.normal(k, x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map_with_path(perturb, lvars["params"])


def _multi_lora_setup(n_adapters=2):
    base = transformer_lm_tiny(max_seq_len=SEQ)
    bvars = base.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                      train=False)
    adapters = [_adapter_tree(seed) for seed in range(1, n_adapters + 1)]
    ml = transformer_lm_tiny(max_seq_len=SEQ, lora_rank=RANK,
                             multi_lora=n_adapters + 1)
    params = build_multi_lora_params(bvars["params"], adapters)
    return base, bvars["params"], adapters, ml, params


def _solo(model, params, prompt, budget, aid=None):
    kw = ({} if aid is None
          else {"adapter_ids": jnp.array([aid], jnp.int32)})
    out = generate(model, params,
                   jnp.asarray(np.array([prompt], np.int32)),
                   jnp.array([len(prompt)], jnp.int32), budget,
                   temperature=0.0, **kw)
    return np.asarray(out)[0].tolist()


def test_row_routed_delta_matches_folded_adapter():
    """Per-row delta vs merge_lora_params fold: same FUNCTION, two float
    paths. Compared in fp32 compute — in bf16 the synthetic deltas
    (deliberately large so adapters visibly diverge) amplify rounding
    through layernorm/gelu and the comparison would measure precision,
    not logic."""
    _, bparams, adapters, _, mlparams = _multi_lora_setup()
    base32 = transformer_lm_tiny(max_seq_len=SEQ, dtype=jnp.float32)
    ml32 = transformer_lm_tiny(max_seq_len=SEQ, dtype=jnp.float32,
                               lora_rank=RANK,
                               multi_lora=len(adapters) + 1)
    toks = jnp.asarray(np.arange(24).reshape(2, 12) % 500)

    def graft_base(ad, b):
        # The fold oracle uses the SAME base the stacks were built on
        # (structures differ: only the adapter tree has lora leaves).
        return {k: (graft_base(v, b[k]) if isinstance(v, dict)
                    else (v if k in ("lora_a", "lora_b") else b[k]))
                for k, v in ad.items()}

    for i, ad in enumerate(adapters):
        folded = graft_base(ad, bparams)
        want = base32.apply({"params": merge_lora_params(folded)}, toks,
                            train=False)
        got = ml32.apply({"params": mlparams}, toks, train=False,
                         adapter_ids=jnp.full((2,), i + 1, jnp.int32))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


def test_adapter_zero_is_exactly_base():
    base, bparams, _, ml, mlparams = _multi_lora_setup()
    toks = jnp.asarray(np.arange(16).reshape(2, 8) % 500)
    want = base.apply({"params": bparams}, toks, train=False)
    got = ml.apply({"params": mlparams}, toks, train=False,
                   adapter_ids=jnp.zeros((2,), jnp.int32))
    # BIT-exact (the documented guarantee): slot 0's lora_b is zero, so
    # the delta is exactly 0.0 and y + 0.0 is bitwise y.
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_mixed_rows_match_single_adapter_rows():
    """One batch, three rows on three different adapters == each row run
    alone under its adapter (bit-level: same program, gathered weights)."""
    _, _, _, ml, mlparams = _multi_lora_setup()
    toks = jnp.asarray(np.arange(30).reshape(3, 10) % 500)
    mixed = ml.apply({"params": mlparams}, toks, train=False,
                     adapter_ids=jnp.array([0, 1, 2], jnp.int32))
    for r in range(3):
        solo = ml.apply({"params": mlparams}, toks[r:r + 1], train=False,
                        adapter_ids=jnp.array([r], jnp.int32))
        np.testing.assert_allclose(np.asarray(mixed[r:r + 1]),
                                   np.asarray(solo), atol=1e-5)


@pytest.fixture(scope="module")
def ml_engine():
    _, _, _, ml, mlparams = _multi_lora_setup()
    engine = GenerateEngine(ml, mlparams, slots=4, decode_block=3,
                            prompt_cache=4)
    yield ml, mlparams, engine
    engine.close()


def test_engine_routes_adapters_exactly(ml_engine):
    ml, mlparams, engine = ml_engine
    prompt = [5, 6, 7]
    outs = {}
    for aid in (0, 1, 2):
        outs[aid] = engine.submit([prompt], max_new_tokens=6,
                                  adapter_id=aid)
        assert outs[aid] == [_solo(ml, mlparams, prompt, 6, aid)]
    assert len({tuple(outs[a][0]) for a in outs}) >= 2, \
        "adapters must actually change the continuation"


def test_engine_interleaves_mixed_adapters(ml_engine):
    ml, mlparams, engine = ml_engine
    res = {}

    def run(aid):
        res[aid] = engine.submit([[10 + aid, 11, 12]], max_new_tokens=8,
                                 adapter_id=aid)

    threads = [threading.Thread(target=run, args=(a,)) for a in (0, 1, 2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for aid in (0, 1, 2):
        assert res[aid] == [_solo(ml, mlparams, [10 + aid, 11, 12], 8,
                                  aid)], f"adapter {aid}"


def test_prompt_cache_is_adapter_namespaced(ml_engine):
    ml, mlparams, engine = ml_engine
    prompt = [30, 31, 32]
    h0 = engine.stats()["pcache_hits"]
    r1 = engine.submit([prompt], max_new_tokens=4, adapter_id=1)
    r2 = engine.submit([prompt], max_new_tokens=4, adapter_id=2)
    assert engine.stats()["pcache_hits"] == h0, "cross-adapter hit!"
    assert r1 == [_solo(ml, mlparams, prompt, 4, 1)]
    assert r2 == [_solo(ml, mlparams, prompt, 4, 2)]
    assert engine.submit([prompt], max_new_tokens=4, adapter_id=1) == r1
    assert engine.stats()["pcache_hits"] == h0 + 1  # same-adapter hit


def test_engine_rejects_bad_adapter_ids(ml_engine):
    _, _, engine = ml_engine
    with pytest.raises(ValueError, match="adapter_id"):
        engine.submit([[1, 2]], max_new_tokens=2, adapter_id=3)
    model, params = (transformer_lm_tiny(max_seq_len=SEQ),)[0], None
    # engine without adapter stacks rejects nonzero ids
    bvars = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                       train=False)
    plain = GenerateEngine(model, bvars["params"], slots=2)
    try:
        with pytest.raises(ValueError, match="multi_lora is off"):
            plain.submit([[1, 2]], max_new_tokens=2, adapter_id=1)
    finally:
        plain.close()


# --- server boot + HTTP routing ----------------------------------------


@pytest.fixture(scope="module")
def adapter_server(tmp_path_factory):
    """Server booted with two fabricated adapter checkpoints."""
    from k3stpu.serve.server import InferenceServer
    from k3stpu.utils import checkpoint as ckpt

    root = tmp_path_factory.mktemp("adapters")
    dirs = {}
    for name, seed in (("alice", 1), ("bob", 2)):
        d = root / name
        ckpt.save_train_state(d, 1, {"params": _adapter_tree(seed)})
        dirs[name] = str(d)
    server = InferenceServer(
        model_name="transformer-tiny", seq_len=SEQ, batch_window_ms=0.0,
        continuous_batching=True, engine_slots=4, shard_devices=1,
        lora_adapters=f"alice={dirs['alice']},bob={dirs['bob']}")
    yield server
    server.close()


def test_server_loads_and_routes_adapters(adapter_server):
    server = adapter_server
    assert server.model_card()["adapters"] == ["base", "alice", "bob"]
    prompt = [[3, 4, 5]]
    outs = {name: server.generate_tokens(prompt, max_new_tokens=6,
                                         adapter=name)
            for name in (None, "alice", "bob")}
    # Routing exactness: each == generate() under that adapter slot.
    for aid, name in ((0, None), (1, "alice"), (2, "bob")):
        want = [_solo(server.model, server._variables["params"],
                      prompt[0], 6, aid)]
        assert outs[name] == want, f"adapter {name}"
    assert outs["alice"] != outs[None] or outs["bob"] != outs[None]


def test_server_rejects_unknown_adapter(adapter_server):
    with pytest.raises(ValueError, match="unknown adapter"):
        adapter_server.generate_tokens([[1, 2]], max_new_tokens=2,
                                       adapter="carol")


def test_http_adapter_routing_and_stream(adapter_server):
    import json
    import urllib.request
    from http.server import ThreadingHTTPServer

    from k3stpu.serve.server import make_app

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(adapter_server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        def post(body):
            req = urllib.request.Request(
                url + "/v1/generate", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=120) as r:
                    if r.headers.get("Content-Type") == "text/event-stream":
                        return r.status, [json.loads(l[6:]) for l in r
                                          if l.startswith(b"data: ")]
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        body = {"prompt_tokens": [[7, 8, 9]], "max_new_tokens": 5}
        _, base = post(body)
        _, alice = post(dict(body, adapter="alice"))
        st, frames = post(dict(body, adapter="alice", stream=True))
        assert st == 200
        assert frames[-1]["tokens"] == alice["tokens"]
        code, err = post(dict(body, adapter="carol"))
        assert code == 400 and "unknown adapter" in err["error"]
    finally:
        httpd.shutdown()


def test_sharded_multi_lora_matches_single_device(tmp_path):
    """Tensor-parallel multi-LoRA through the ENGINE: the 2-device
    sharded server (lora_b stacks split on their output axis, lora_a
    replicated — parallel/sharding.py; engine KV cache head-sharded on
    the same mesh) must produce the single-device outputs for every
    adapter.

    2 devices, deliberately: wider TP reorders bf16 reductions by about
    one ulp (measured 0.03 on these logits), and a greedy chain whose
    top-1/top-2 gap dips under that noise flips a token and diverges —
    numerics, not routing (the first-token argmax stays equal at 4-way
    and the base/alice chains match end-to-end there)."""
    from k3stpu.serve.server import InferenceServer
    from k3stpu.utils import checkpoint as ckpt

    for name, seed, scale in (("alice", 1, 0.3), ("bob", 2, 0.3),
                              ("carol", 3, 0.1)):
        ckpt.save_train_state(tmp_path / name, 1,
                              {"params": _adapter_tree(seed, scale)})
    spec = (f"alice={tmp_path}/alice,bob={tmp_path}/bob,"
            f"carol={tmp_path}/carol")
    kw = dict(model_name="transformer-tiny", seq_len=SEQ,
              batch_window_ms=0.0, continuous_batching=True,
              engine_slots=2, lora_adapters=spec)
    single = InferenceServer(shard_devices=1, **kw)
    sharded = InferenceServer(shard_devices=2, **kw)
    try:
        # Base chain: stable under the reordering (no adapter delta), so
        # the full greedy chain must match token for token.
        want = single.generate_tokens([[3, 4, 5]], max_new_tokens=6)
        assert sharded.generate_tokens([[3, 4, 5]], max_new_tokens=6) \
            == want
        # Adapter chains: the synthetic deltas are deliberately large,
        # so a greedy chain may hit a sub-ulp top-2 tie and legitimately
        # fork after a few tokens (the docstring numerics). The sharding
        # invariants that CAN'T legitimately drift: logits agree to ~one
        # bf16 ulp and the first generated token matches.
        toks = jnp.asarray(np.array([[3, 4, 5]], np.int32))
        for aid, adapter in ((1, "alice"), (2, "bob")):
            ids = jnp.full((1,), aid, jnp.int32)
            l1 = np.asarray(single.model.apply(
                {"params": single._variables["params"]}, toks,
                train=False, adapter_ids=ids))
            l2 = np.asarray(sharded.model.apply(
                {"params": sharded._variables["params"]}, toks,
                train=False, adapter_ids=ids))
            # atol: one bf16 ulp at the largest logit magnitudes here
            # (ulp(8) = 0.0625) — anything beyond that is a real
            # sharding defect, not reduction reordering.
            np.testing.assert_allclose(l2, l1, rtol=0.02, atol=0.08,
                                       err_msg=f"adapter {adapter}")
            s_tok = single.generate_tokens([[3, 4, 5]], max_new_tokens=1,
                                           adapter=adapter)
            d_tok = sharded.generate_tokens([[3, 4, 5]], max_new_tokens=1,
                                            adapter=adapter)
            assert s_tok == d_tok, f"adapter {adapter} first token"
        # carol's SMALL deltas keep the greedy chain clear of sub-ulp
        # ties, so her full chain exercises adapter-routed DECODE steps
        # reading back the head-sharded cache — and must match exactly
        # (and differ from the base, or the adapter did nothing).
        want = single.generate_tokens([[3, 4, 5]], max_new_tokens=6,
                                      adapter="carol")
        assert sharded.generate_tokens([[3, 4, 5]], max_new_tokens=6,
                                       adapter="carol") == want
        assert want != single.generate_tokens([[3, 4, 5]],
                                              max_new_tokens=6)
    finally:
        single.close()
        sharded.close()


def test_moe_multi_lora_serving(tmp_path):
    """MoE family: adapters ride the attention/dense-block projections
    (expert banks stay base). Engine-routed per-adapter output must be
    exact against generate() on the served model, and adapters must
    actually diverge."""
    from k3stpu.models.moe import moe_lm_tiny
    from k3stpu.serve.server import InferenceServer
    from k3stpu.utils import checkpoint as ckpt

    # Fabricate MoE LoRA checkpoints: lora_rank nests under base.
    import dataclasses

    base_moe = moe_lm_tiny(max_seq_len=SEQ)
    lmodel = type(base_moe)(dataclasses.replace(
        base_moe.config,
        base=dataclasses.replace(base_moe.config.base, lora_rank=RANK)))
    lvars = lmodel.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                        train=False)

    def perturb(seed):
        def f(path, x):
            if getattr(path[-1], "key", None) in ("lora_a", "lora_b"):
                k = jax.random.fold_in(jax.random.key(seed),
                                       zlib.crc32(str(path).encode()))
                return 0.3 * jax.random.normal(k, x.shape, x.dtype)
            return x
        return jax.tree_util.tree_map_with_path(f, lvars["params"])

    for name, seed in (("alice", 1), ("bob", 2)):
        ckpt.save_train_state(tmp_path / name, 1,
                              {"params": perturb(seed)})
    server = InferenceServer(
        model_name="moe-tiny", seq_len=SEQ, batch_window_ms=0.0,
        continuous_batching=True, engine_slots=2, shard_devices=1,
        lora_adapters=f"alice={tmp_path}/alice,bob={tmp_path}/bob")
    try:
        assert server.model_card()["adapters"] == ["base", "alice", "bob"]
        outs = {}
        for aid, name in ((0, None), (1, "alice"), (2, "bob")):
            outs[name] = server.generate_tokens([[3, 4, 5]],
                                                max_new_tokens=6,
                                                adapter=name)
            want = [_solo(server.model, server._variables["params"],
                          [3, 4, 5], 6, aid)]
            assert outs[name] == want, f"adapter {name}"
        assert len({tuple(o[0]) for o in outs.values()}) >= 2
    finally:
        server.close()
    # The NON-engine path too: multi_lora nests under MoeConfig.base,
    # and a top-level config read returned None here — the server
    # accepted adapter requests and silently answered with the BASE
    # model's tokens (caught in review; this pins the fix).
    plain = InferenceServer(
        model_name="moe-tiny", seq_len=SEQ, batch_window_ms=0.0,
        shard_devices=1,
        lora_adapters=f"alice={tmp_path}/alice,bob={tmp_path}/bob")
    try:
        base_out = plain.generate_tokens([[3, 4, 5]], max_new_tokens=6)
        alice_out = plain.generate_tokens([[3, 4, 5]], max_new_tokens=6,
                                          adapter="alice")
        assert alice_out == outs["alice"]  # same as the engine route
        assert alice_out != base_out, \
            "adapter request served base tokens (multi_lora read off " \
            "the wrong config level?)"
    finally:
        plain.close()


def test_server_mixed_rank_adapters_rejected(tmp_path):
    from k3stpu.serve.server import InferenceServer
    from k3stpu.utils import checkpoint as ckpt

    lm8 = transformer_lm_tiny(max_seq_len=SEQ, lora_rank=8)
    v8 = lm8.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                  train=False)
    ckpt.save_train_state(tmp_path / "a", 1,
                          {"params": _adapter_tree(1)})
    ckpt.save_train_state(tmp_path / "b", 1, {"params": v8["params"]})
    with pytest.raises(ValueError, match="rank"):
        InferenceServer(model_name="transformer-tiny", seq_len=SEQ,
                        batch_window_ms=0.0, shard_devices=1,
                        lora_adapters=f"a={tmp_path}/a,b={tmp_path}/b")
