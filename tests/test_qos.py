"""SLO-aware QoS (docs/QOS.md): priority classes, predictive admission
control, and tier-backed loss-free preemption.

The acceptance centerpiece: a batch request preempted mid-generation —
page chain parked in the host tier, request requeued, resumed — must
finish with output TOKEN-IDENTICAL to a never-preempted solo run,
including across a COW-shared prefix and the int8 KV pool. The
predictive gate must reject with a finite Retry-After under backlog,
fail OPEN when its estimator breaks, and the classless engine's
scheduling and /metrics exposition must stay byte-identical to the
pre-QoS build.
"""

import math
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.chaos import FaultInjector
from k3stpu.models.generate import generate
from k3stpu.models.transformer import transformer_lm_tiny
from k3stpu.obs import ServeObs
from k3stpu.obs.slo import predict_ttft, qos_specs
from k3stpu.serve.engine import (
    QOS_CLASSES,
    AdmissionRejected,
    GenerateEngine,
)
from k3stpu.serve.scheduler import QOS_INTERACTIVE_SHARE
from k3stpu.serve.server import InferenceServer
from k3stpu.serve.tiering import HostPageStore

QOS_FAMILIES = (
    "k3stpu_serve_class_queue_depth",
    "k3stpu_serve_preemptions_total",
    "k3stpu_serve_admission_rejected_total",
    "k3stpu_serve_preempt_park_seconds",
)


@pytest.fixture(scope="module")
def mp():
    model = transformer_lm_tiny(max_seq_len=64)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                           train=False)
    return model, variables["params"]


def _solo(model, params, prompt, budget):
    out = generate(model, params,
                   jnp.asarray(np.array([prompt], np.int32)),
                   jnp.array([len(prompt)], jnp.int32), budget,
                   temperature=0.0)
    return np.asarray(out)[0].tolist()


def _qos_engine(model, params, *, tier_mb=64, chaos=None, obs=None, **kw):
    """A qos=True paged+tiered engine, slots=1 by default so ONE batch
    request owns the only decode row and an interactive arrival has no
    choice but the preemption path — the race-free way to force a
    park on every scheduler tick ordering."""
    kw.setdefault("slots", 1)
    kw.setdefault("prompt_cache", 4)
    kw.setdefault("page_size", 8)
    store = HostPageStore(tier_mb * (1 << 20))
    eng = GenerateEngine(model, params, seed=0, qos=True, tier=store,
                         chaos=chaos, obs=obs, **kw)
    return eng, store


def _assert_page_invariants(engine):
    """Idle-engine allocator accounting, checked exactly (the same
    proof as tests/test_paged.py / tests/test_tiering.py): every
    page's refcount equals its appearances across live slot chains
    plus prompt-cache pins — a leaked page or stranded pin after
    preemption traffic fails here."""
    alloc = engine._alloc
    expect = {}
    for chain in engine._chains:
        for p in chain:
            expect[p] = expect.get(p, 0) + 1
    for entry in engine._pcache.values():
        for p in entry[0]:
            expect[p] = expect.get(p, 0) + 1
    for p in range(1, alloc.num_pages):
        assert alloc.refcount(p) == expect.get(p, 0), (
            f"page {p}: rc={alloc.refcount(p)} but "
            f"{expect.get(p, 0)} live references")
    assert alloc.free == alloc.total - sum(1 for v in expect.values()
                                           if v > 0)


def _preempt_scenario(engine, batch_prompt, batch_budget, inter_prompt,
                      inter_budget, min_tokens=2):
    """Run the preemption race deterministically: a batch request
    holding the lone slot, polled until it has decoded ``min_tokens``
    (so the park carries real mid-generation state), then an
    interactive submit that must displace it. Returns
    (batch_result_or_exc, interactive_result_or_exc)."""
    out = {}

    def run_batch():
        try:
            out["batch"] = engine.submit(
                [batch_prompt], max_new_tokens=batch_budget,
                priority="batch")
        except Exception as e:  # noqa: BLE001 — surfaced to the test
            out["batch"] = e

    t = threading.Thread(target=run_batch)
    t.start()
    deadline = time.time() + 60.0
    while time.time() < deadline:
        o = engine._owner[0]
        if (o is not None and engine._active[0]
                and getattr(o, "priority", None) == "batch"
                and len(engine._collected[0]) >= min_tokens):
            break
        time.sleep(0.002)
    else:
        t.join(5.0)
        raise AssertionError("batch request never reached mid-generation")
    try:
        inter = engine.submit([inter_prompt],
                              max_new_tokens=inter_budget,
                              priority="interactive")
    except Exception as e:  # noqa: BLE001
        inter = e
    t.join(60.0)
    assert not t.is_alive(), "batch request never completed"
    return out["batch"], inter


# --- loss-free preemption: bit-exactness ---------------------------------


def test_preempted_batch_output_identical_to_unpreempted_twin(mp):
    model, params = mp
    engine, store = _qos_engine(model, params)
    try:
        bp = [5, 6, 7, 8, 9, 10, 11, 12]
        ip = [20, 21, 22, 23]
        batch, inter = _preempt_scenario(engine, bp, 24, ip, 4)
        assert batch == [_solo(model, params, bp, 24)]
        assert inter == [_solo(model, params, ip, 4)]
        s = engine.stats()
        assert s["preemptions"] >= 1, "the preemption never fired"
        assert s["preempt_fallbacks"] == 0
        # The park went THROUGH the tier and the resume prefix-hit it.
        assert s["tier_swap_outs"] >= 1 or store.stats()["tier_entries"] >= 0
        assert s["tier_hits"] >= 1
        _assert_page_invariants(engine)
    finally:
        engine.close()


def test_preempted_batch_exact_across_cow_shared_prefix(mp):
    """The victim's chain COW-shares pinned prompt-cache pages with an
    earlier request: the park gathers the shared prefix, the requeue
    decrefs only the victim's references, and both the co-resident
    entry and the resumed continuation stay exact."""
    model, params = mp
    engine, store = _qos_engine(model, params)
    try:
        base = [5, 6, 7, 8, 9, 10, 11, 12, 13]
        warm = engine.submit([base], max_new_tokens=4)
        assert warm == [_solo(model, params, base, 4)]
        bp = base + warm[0] + [30, 31]
        ip = [40, 41, 42]
        batch, inter = _preempt_scenario(engine, bp, 20, ip, 4)
        assert batch == [_solo(model, params, bp, 20)]
        assert inter == [_solo(model, params, ip, 4)]
        s = engine.stats()
        assert s["preemptions"] >= 1
        assert s["preempt_fallbacks"] == 0
        _assert_page_invariants(engine)
    finally:
        engine.close()


def test_preempted_batch_exact_on_int8_pool(mp):
    """int8 pools park value pages AND their fp32 absmax scale planes;
    a park that dropped or reordered either leaf would resume garbage
    — the twin compare is against the solo int8 run."""
    model = transformer_lm_tiny(max_seq_len=64, kv_cache_dtype="int8")
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    engine, store = _qos_engine(model, params)
    try:
        bp = [3, 4, 5, 6, 7, 8, 9]
        ip = [15, 16, 17]
        batch, inter = _preempt_scenario(engine, bp, 20, ip, 4)
        assert batch == [_solo(model, params, bp, 20)]
        assert inter == [_solo(model, params, ip, 4)]
        assert engine.stats()["preemptions"] >= 1
        _assert_page_invariants(engine)
    finally:
        engine.close()


# --- predictive admission control ----------------------------------------


def test_predictive_rejection_fires_with_finite_retry_after(mp):
    """Once the obs TTFT histogram has history, an interactive SLO set
    below any achievable latency must reject the NEXT submit at the
    door with AdmissionRejected and a finite Retry-After in the
    [1, 30] s clamp — and count it per class."""
    model, params = mp
    obs = ServeObs()
    engine, _ = _qos_engine(model, params, obs=obs,
                            interactive_ttft_slo_s=1e-4)
    try:
        # No latency history yet: the gate has no basis and admits.
        out = engine.submit([[5, 6, 7, 8]], max_new_tokens=3)
        assert out == [_solo(model, params, [5, 6, 7, 8], 3)]
        assert obs.ttft.count >= 1
        with pytest.raises(AdmissionRejected) as ei:
            engine.submit([[5, 6, 7, 9]], max_new_tokens=3)
        ra = ei.value.retry_after_s
        assert math.isfinite(ra) and 1.0 <= ra <= 30.0
        s = engine.stats()
        assert s["admission_rejected"] == 1
        assert s["predict_fallbacks"] == 0
        text = obs.render_prometheus()
        assert ('k3stpu_serve_admission_rejected_total'
                '{class="interactive"} 1') in text
    finally:
        engine.close()


def test_predict_ttft_is_monotone_in_load():
    # No history => no basis to reject (0.0 admits everything).
    assert predict_ttft(0.0, 10, 1000, 4, 64) == 0.0
    # Empty queue: the forecast IS the p50.
    assert predict_ttft(0.5, 0, 0, 4, 64) == 0.5
    # One wave per slot doubles it; backlog converts through the
    # chunk budget into serialized admission ticks.
    assert predict_ttft(0.5, 4, 0, 4, 64) == pytest.approx(1.0)
    assert predict_ttft(0.5, 0, 128, 4, 64) == pytest.approx(
        0.5 * (1.0 + (128 / 64) / 4))
    # Monotone: more depth or backlog never lowers the forecast.
    base = predict_ttft(0.5, 2, 64, 4, 64)
    assert predict_ttft(0.5, 3, 64, 4, 64) >= base
    assert predict_ttft(0.5, 2, 128, 4, 64) >= base


def test_qos_specs_share_the_organic_ttft_family():
    inter, batch = qos_specs(interactive_threshold_s=1.5,
                             batch_threshold_s=20.0, window_days=7.0)
    assert inter.name == "ttft-interactive"
    assert batch.name == "ttft-batch"
    # Both read the SAME organic family at their own threshold —
    # no per-class histograms in the exposition.
    assert inter.metric == batch.metric == "k3stpu_request_ttft_seconds"
    assert inter.threshold_s == 1.5 and batch.threshold_s == 20.0
    assert inter.target > batch.target
    assert inter.window_days == batch.window_days == 7.0


# --- class-ordered admission walk ----------------------------------------


def test_admission_walk_orders_interactive_first_and_splits_budget(mp):
    model, params = mp
    engine, _ = _qos_engine(model, params, chunk_prefill=16)
    engine.close()  # stop the loop; the walk is a pure pending read

    class R:
        def __init__(self, priority):
            self.priority = priority

    i1, i2, b1, b2 = R("interactive"), R("interactive"), R("batch"), R("batch")
    engine._pending = [b1, i1, b2, i2]
    walk, budget = engine._admission_walk()
    # Interactive first, FIFO within each class.
    assert walk == [i1, i2, b1, b2]
    assert budget == {"interactive": QOS_INTERACTIVE_SHARE * 16.0,
                      "batch": (1.0 - QOS_INTERACTIVE_SHARE) * 16.0}
    # Work-conserving: an empty class donates its share.
    engine._pending = [b1, b2]
    _, budget = engine._admission_walk()
    assert budget["batch"] == 16.0
    engine._pending = [i1]
    _, budget = engine._admission_walk()
    assert budget["interactive"] == 16.0
    # A classless engine's walk is the pre-QoS arrival order, no budget.
    engine.qos = False
    engine._pending = [b1, i1]
    walk, budget = engine._admission_walk()
    assert walk == [b1, i1] and budget is None


def test_bad_priority_rejected_at_submit(mp):
    model, params = mp
    engine, _ = _qos_engine(model, params)
    try:
        with pytest.raises(ValueError, match="priority"):
            engine.submit([[1, 2, 3]], max_new_tokens=2,
                          priority="best-effort")
    finally:
        engine.close()


def test_deadline_ms_maps_onto_engine_timeout():
    f = InferenceServer._deadline_timeout
    assert f(None) == 600.0
    assert f(2500) == 2.5
    assert f(250.0) == 0.25
    # Capped at the default watchdog window: a huge client deadline
    # must not extend how long a wedged request can hold a waiter.
    assert f(10**9) == 600.0
    for bad in (0, -5, float("nan"), float("inf") * -1):
        with pytest.raises(ValueError, match="deadline_ms"):
            f(bad)


# --- exposition stability -------------------------------------------------


def test_classless_exposition_carries_no_qos_families(mp):
    """The four QoS families are constructed on every ServeObs (so the
    metrics lint scans them) but rendered ONLY once a qos=True engine
    arms them — a classless server's /metrics must stay byte-identical
    to the pre-QoS exposition."""
    model, params = mp
    obs = ServeObs()
    engine = GenerateEngine(model, params, seed=0, slots=2,
                            page_size=8, prompt_cache=2, obs=obs)
    try:
        out = engine.submit([[4, 5, 6, 7]], max_new_tokens=3)
        assert out == [_solo(model, params, [4, 5, 6, 7], 3)]
        text = obs.render_prometheus()
        for fam in QOS_FAMILIES:
            assert fam not in text
    finally:
        engine.close()


def test_qos_exposition_renders_per_class_families(mp):
    model, params = mp
    obs = ServeObs()
    engine, _ = _qos_engine(model, params, obs=obs, slots=2)
    try:
        engine.submit([[4, 5, 6, 7]], max_new_tokens=2)
        engine.submit([[8, 9, 10]], max_new_tokens=2, priority="batch")
        text = obs.render_prometheus()
        for cls in QOS_CLASSES:
            assert (f'k3stpu_serve_class_queue_depth{{class="{cls}"}}'
                    in text)
        assert "k3stpu_serve_preemptions_total" in text
        assert "k3stpu_serve_preempt_park_seconds_bucket" in text
        # Zero-armed counters render (a scrape can tell "no rejections
        # yet" from "family missing").
        assert ('k3stpu_serve_admission_rejected_total'
                '{class="interactive"} 0') in text
    finally:
        engine.close()
