"""Test env: force the CPU backend with 8 virtual devices BEFORE jax imports,
so every sharding/mesh test runs the real pjit path without TPU hardware
(SURVEY.md §4 — CPU-JAX stand-in, fake backends)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize (TPU tunnel) force-registers its platform ahead of
# env vars, so pin the CPU backend via jax.config before any backend init.
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import pytest


@pytest.fixture()
def fake_host_root(tmp_path):
    """A fabricated host filesystem with 4 TPU v5e chips: sysfs PCI entries
    (vendor 0x1ae0) + /dev/accel* nodes (files stand in for device nodes)."""
    for i in range(4):
        bdf = tmp_path / "sys" / "bus" / "pci" / "devices" / f"0000:00:0{4 + i}.0"
        bdf.mkdir(parents=True)
        (bdf / "vendor").write_text("0x1ae0\n")
        (bdf / "device").write_text("0x0062\n")
        (bdf / "numa_node").write_text(f"{i // 2}\n")
    # A non-TPU PCI device that must be ignored.
    other = tmp_path / "sys" / "bus" / "pci" / "devices" / "0000:00:01.0"
    other.mkdir(parents=True)
    (other / "vendor").write_text("0x8086\n")
    (other / "device").write_text("0x1237\n")

    dev = tmp_path / "dev"
    dev.mkdir()
    for i in range(4):
        (dev / f"accel{i}").write_text("")
    libdir = tmp_path / "usr" / "lib"
    libdir.mkdir(parents=True)
    (libdir / "libtpu.so").write_text("")
    return tmp_path
