"""Test env: force the CPU backend with 8 virtual devices BEFORE jax imports,
so every sharding/mesh test runs the real pjit path without TPU hardware
(SURVEY.md §4 — CPU-JAX stand-in, fake backends)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize (TPU tunnel) force-registers its platform ahead of
# env vars, so pin the CPU backend via jax.config before any backend init.
import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # older jax: the XLA_FLAGS fallback above already forces 8

# Persistent compilation cache for the suite: the full run compiles
# hundreds of programs, and XLA:CPU's concurrent LLVM codegen (an engine
# loop thread compiling while the test's main thread compiles) has
# segfaulted under that volume — twice, both times mid-compile at ~80%.
# Cache hits skip codegen entirely on re-runs, cutting both wall time
# and the window for that race to essentially zero after one warm run.
try:
    import getpass

    _user = getpass.getuser()
except (KeyError, OSError):  # scrubbed env + uid without a passwd entry
    _user = str(os.getuid())
_cache_dir = os.environ.get(
    "K3STPU_TEST_CACHE", f"/tmp/k3stpu-test-compile-cache-{_user}")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
# No eviction policy in jax for this cache: prune stale entries at
# session start so weeks of iteration can't fill a tmpfs-backed /tmp.
# Staleness = max(atime, mtime): cache HITS read without rewriting, so
# mtime alone would evict the oldest, most-reused entries first.
import time as _time

try:
    _cutoff = _time.time() - 14 * 86400
    with os.scandir(_cache_dir) as it:
        for _e in it:
            _st = _e.stat()
            if _e.is_file() and max(_st.st_atime, _st.st_mtime) < _cutoff:
                os.unlink(_e.path)
except OSError:
    pass  # first run (no dir yet) or shared-dir permissions

import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import contextlib
import signal
import subprocess
import time

import pytest

NATIVE_BUILD_DIR = REPO_ROOT / "native" / "build"


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long soaks/benches — deselected in run_suite.sh --smoke "
        "via -m 'not slow', run by the full suite")


@pytest.fixture(scope="session")
def native_build():
    """Build all native binaries once per session; returns the build dir."""
    subprocess.run(
        ["cmake", "-S", str(REPO_ROOT / "native"), "-B",
         str(NATIVE_BUILD_DIR)], check=True, capture_output=True)
    subprocess.run(["cmake", "--build", str(NATIVE_BUILD_DIR)],
                   check=True, capture_output=True)
    return NATIVE_BUILD_DIR


def wait_for_socket(path, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path):
            return
        time.sleep(0.02)
    raise TimeoutError(f"socket {path} never appeared")


@contextlib.contextmanager
def plugin_channel_for(build_dir, host_root, plugin_dir, *extra_argv,
                       expect_clean_exit=True):
    """Run the device plugin over host_root and yield a grpc channel to its
    socket; SIGTERM + reap on exit. The single home for this boilerplate —
    unit, tray, core-granularity, and integration tiers all enter here."""
    import grpc

    plugin_dir.mkdir(exist_ok=True)
    proc = subprocess.Popen(
        [str(build_dir / "tpu-device-plugin"), "--no-register",
         "--plugin-dir", str(plugin_dir), "--host-root", str(host_root),
         *extra_argv],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    sock = plugin_dir / "k3stpu.sock"
    try:
        wait_for_socket(str(sock))
        channel = grpc.insecure_channel(f"unix://{sock}")
        yield channel, proc
        channel.close()
        if expect_clean_exit:
            early = proc.poll()
            assert early is None, (
                f"plugin died during test rc={early} "
                f"stderr={proc.stderr.read()[-2000:]}")
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()


@pytest.fixture()
def fake_host_root(tmp_path):
    """A fabricated host filesystem with 4 TPU v5e chips: sysfs PCI entries
    (vendor 0x1ae0) + /dev/accel* nodes (files stand in for device nodes)."""
    for i in range(4):
        bdf = tmp_path / "sys" / "bus" / "pci" / "devices" / f"0000:00:0{4 + i}.0"
        bdf.mkdir(parents=True)
        (bdf / "vendor").write_text("0x1ae0\n")
        (bdf / "device").write_text("0x0062\n")
        (bdf / "numa_node").write_text(f"{i // 2}\n")
    # A non-TPU PCI device that must be ignored.
    other = tmp_path / "sys" / "bus" / "pci" / "devices" / "0000:00:01.0"
    other.mkdir(parents=True)
    (other / "vendor").write_text("0x8086\n")
    (other / "device").write_text("0x1237\n")

    dev = tmp_path / "dev"
    dev.mkdir()
    for i in range(4):
        (dev / f"accel{i}").write_text("")
    libdir = tmp_path / "usr" / "lib"
    libdir.mkdir(parents=True)
    (libdir / "libtpu.so").write_text("")
    return tmp_path
