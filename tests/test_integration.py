"""Integration tier (SURVEY.md §4): device plugin and runtime shim TOGETHER.

The reference's end-to-end check is scheduling a pod and reading the device
table from its logs (reference README.md:128-156) — kubelet merges the
plugin's Allocate response into the container, then the accelerator runtime
patches the OCI spec. Unit tiers cover each half; this tier proves the two
halves COMPOSE: the spec a pod actually gets after (1) kubelet applies
Allocate's env/devices/mounts and (2) containerd's RuntimeClass invokes the
shim, has no duplicate devices, no duplicate mounts, and exactly one value
for every TPU_* env var — the plugin's.
"""

import json
import os
import subprocess

import pytest

import dp_proto as pb
from conftest import plugin_channel_for

IDENT = dict(request_serializer=lambda x: x,
             response_deserializer=lambda x: x)


@pytest.fixture()
def plugin_channel(native_build, fake_host_root, tmp_path):
    with plugin_channel_for(native_build, fake_host_root,
                            tmp_path / "kubelet", "--replicas", "4",
                            "--scan-seconds", "60") as (ch, _):
        yield ch


def kubelet_apply(alloc: dict, fake_host_root) -> dict:
    """What kubelet+containerd do with an Allocate response before the
    runtime ever runs: env merged into the container process, DeviceSpecs
    into linux.devices (+ cgroup allow rules), Mounts into mounts."""
    spec = {
        "ociVersion": "1.0.2",
        "process": {
            "args": ["python", "-m", "k3stpu.probe"],
            "env": ["PATH=/usr/bin",
                    "POD_NAME=probe"] +
                   [f"{k}={v}" for k, v in sorted(alloc["envs"].items())],
        },
        "root": {"path": "rootfs"},
        "mounts": [
            {"destination": "/proc", "type": "proc", "source": "proc"},
        ],
        "linux": {"namespaces": [{"type": "pid"}],
                  "devices": [], "resources": {"devices": []}},
        "annotations": dict(alloc.get("annotations", {})),
    }
    for d in alloc["devices"]:
        spec["linux"]["devices"].append({
            "path": d["container_path"], "type": "c",
            "major": 0, "minor": 0, "fileMode": 0o666, "uid": 0, "gid": 0,
        })
        spec["linux"]["resources"]["devices"].append({
            "allow": True, "type": "c", "major": 0, "minor": 0,
            "access": d["permissions"],
        })
    for m in alloc["mounts"]:
        spec["mounts"].append({
            "destination": m["container_path"], "type": "bind",
            "source": str(fake_host_root) + m["host_path"],
            "options": ["rbind", "ro" if m["read_only"] else "rw"],
        })
    return spec


def run_shim(build_dir, spec, fake_host_root, tmp_path):
    bundle = tmp_path / "bundle"
    bundle.mkdir(exist_ok=True)
    (bundle / "config.json").write_text(json.dumps(spec))
    out = subprocess.run(
        [str(build_dir / "tpu-container-runtime"), "patch",
         "--bundle", str(bundle), "--dry-run",
         "--host-root", str(fake_host_root)],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    return json.loads(out.stdout)


def test_allocate_then_shim_compose(plugin_channel, native_build,
                                    fake_host_root, tmp_path):
    # 1. kubelet Allocate: two shared replicas collapsing to chips 1,2.
    call = plugin_channel.unary_unary(
        "/v1beta1.DevicePlugin/Allocate", **IDENT)
    resp = call(pb.allocate_request(["tpu-1-0", "tpu-1-2", "tpu-2-0"]),
                timeout=5)
    [alloc] = pb.parse_allocate_response(resp)
    assert alloc["envs"]["TPU_VISIBLE_CHIPS"] == "1,2"

    # 2. kubelet/containerd apply it, 3. the RuntimeClass shim re-patches.
    spec = kubelet_apply(alloc, fake_host_root)
    patched = run_shim(native_build, spec, fake_host_root, tmp_path)

    # Env: every TPU_* var appears EXACTLY once, with the plugin's value —
    # the shim must fill gaps (TPU_LIBRARY_PATH), never duplicate/override.
    env = patched["process"]["env"]
    tpu_env = {}
    for e in env:
        k, _, v = e.partition("=")
        if k.startswith("TPU_"):
            assert k not in tpu_env, f"duplicate env {k}: {env}"
            tpu_env[k] = v
    assert tpu_env["TPU_VISIBLE_CHIPS"] == "1,2"
    assert tpu_env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,2"
    assert tpu_env["TPU_PROCESS_BOUNDS"] == "1,1,1"
    assert tpu_env["TPU_ACCELERATOR_TYPE"] == "tpu-v5e-2"
    # Plugin-only (sharing) and shim-only (library path) halves both land.
    assert tpu_env["TPU_MEM_FRACTION"].startswith("0.25")
    assert tpu_env["TPU_ALLOW_MULTIPLE_LIBTPU_PROCESSES"] == "1"
    assert tpu_env["TPU_LIBRARY_PATH"] == "/lib/libtpu.so"

    # Devices: exactly the allocated chips' nodes, each once, allow-listed.
    dev_paths = [d["path"] for d in patched["linux"]["devices"]]
    assert sorted(dev_paths) == ["/dev/accel1", "/dev/accel2"]
    allow = patched["linux"]["resources"]["devices"]
    assert len(allow) == 2 and all(r["allow"] for r in allow)

    # Mounts: libtpu bound exactly once (kubelet's copy wins, shim skips).
    libtpu = [m for m in patched["mounts"]
              if m["destination"] == "/lib/libtpu.so"]
    assert len(libtpu) == 1
    assert libtpu[0]["source"].endswith("/usr/lib/libtpu.so")

    # Allocation annotation survives the shim untouched.
    assert patched["annotations"]["tpu.google.com/chips"] == "1,2"


def test_shim_alone_still_injects_for_manual_pods(fake_host_root, tmp_path,
                                                  native_build):
    """A pod bypassing the plugin (annotation opt-in, no Allocate env) must
    still get devices + libtpu from the shim alone — the reference's
    'runtime copies everything needed' behavior (README.md:164)."""
    spec = {
        "ociVersion": "1.0.2",
        "process": {"args": ["python"], "env": ["PATH=/usr/bin"]},
        "root": {"path": "rootfs"},
        "annotations": {"tpu.google.com/inject": "true"},
    }
    patched = run_shim(native_build, spec, fake_host_root, tmp_path)
    env = {e.partition("=")[0]: e.partition("=")[2]
           for e in patched["process"]["env"]}
    assert env["TPU_VISIBLE_CHIPS"] == "0,1,2,3"
    assert len(patched["linux"]["devices"]) == 4
    assert any(m["destination"] == "/lib/libtpu.so"
               for m in patched["mounts"])
