"""Elastic data-parallel training (ISSUE 8): survive a membership change
without losing the world.

Unit tests exercise the building blocks in-process (config, the file
heartbeat ledger, the generation-numbered socket barrier — in threads,
with a simulated coordinator death). The integration tests drive REAL
train-job subprocesses on a shared checkpoint/ledger tree and hard-kill
a rank mid-run via the ``rank_loss``/``coordinator_loss`` chaos points
(``os._exit`` — no SIGTERM drain, no goodbye: a kubelet-evicted pod).
The survivors must detect the loss by heartbeat staleness, re-rendezvous
at generation+1, restore the last finalized checkpoint, and continue —
in-process, with a loss curve equal to an uninterrupted twin's.

CPU groups run UNWIRED (local-replica): every rank computes the full
global batch on its local mesh, so the trajectories are lockstep and the
twin comparison is exact up to float noise. docs/RESILIENCE.md describes
the wired (TPU) variant of the same protocol.
"""

import getpass
import json
import os
import pathlib
import re
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from k3stpu.data.corpus import synthetic_corpus
from k3stpu.parallel import distributed as dist
from k3stpu.utils import checkpoint as ckpt

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


def _events(text):
    out = []
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            out.append(json.loads(line))
    return out


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# --- config ---------------------------------------------------------------


def test_elastic_config_off_by_default(monkeypatch):
    monkeypatch.delenv("K3STPU_ELASTIC", raising=False)
    assert dist.elastic_config_from_env(ledger_root="/x") is None


def test_elastic_config_from_env_knobs(monkeypatch, tmp_path):
    monkeypatch.setenv("K3STPU_ELASTIC", "1")
    monkeypatch.setenv("K3STPU_ADVERTISE_ADDRESS", "10.0.0.5:9000")
    monkeypatch.setenv("K3STPU_ELASTIC_MIN_WORLD", "2")
    monkeypatch.setenv("K3STPU_ELASTIC_LOSS_TIMEOUT_S", "3.5")
    monkeypatch.delenv("K3STPU_ELASTIC_LEDGER_DIR", raising=False)
    cfg = dist.elastic_config_from_env(ledger_root=str(tmp_path))
    assert cfg.advertise_host == "10.0.0.5"
    assert cfg.advertise_port == 9000
    assert cfg.min_world == 2
    assert cfg.loss_timeout_s == 3.5
    assert cfg.ledger_dir == os.path.join(str(tmp_path), "membership")


def test_elastic_config_needs_a_ledger_home(monkeypatch):
    monkeypatch.setenv("K3STPU_ELASTIC", "1")
    monkeypatch.delenv("K3STPU_ELASTIC_LEDGER_DIR", raising=False)
    with pytest.raises(ValueError, match="ledger"):
        dist.elastic_config_from_env(ledger_root=None)


# --- membership ledger ----------------------------------------------------


def test_ledger_heartbeat_liveness_and_loss(tmp_path):
    led = dist.MembershipLedger(str(tmp_path / "m"))
    led.write_heartbeat(0, "a:1")
    led.write_heartbeat(1, "b:1")
    assert led.alive(5.0) == {0, 1}
    assert led.lost({0, 1, 2}, 5.0) == {2}  # never wrote: lost
    # Staleness IS liveness: age rank 1's file past the timeout, exactly
    # what a SIGKILL'd rank looks like (it just stops touching it).
    old = time.time() - 60
    os.utime(os.path.join(led.directory, "rank-1.json"), (old, old))
    assert led.alive(5.0) == {0}
    assert led.lost({0, 1}, 5.0) == {1}


def test_ledger_heartbeat_thread_keeps_file_fresh(tmp_path):
    led = dist.MembershipLedger(str(tmp_path / "m"))
    led.start_heartbeat(0, "a:1", interval_s=0.05)
    try:
        time.sleep(0.3)
        assert led.alive(0.2) == {0}
        rec = led.read()[0]
        assert rec["address"] == "a:1"
    finally:
        led.stop()


def test_group_dense_rank_and_primary():
    g = dist.ElasticGroup(generation=3, ranks=(1, 3), rank=0,
                          coordinator_address="x:1")
    assert g.world_size == 2
    assert g.is_primary  # dense rank 0, even though ORIGINAL rank is 1
    h = dist.ElasticGroup(generation=3, ranks=(1, 3), rank=1,
                          coordinator_address="x:1")
    assert not h.is_primary


def test_ledger_group_manifest_roundtrip(tmp_path):
    """The persisted group manifest is the rejoin map: a recreated pod
    reads latest_group() to learn which generation the run is at."""
    led = dist.MembershipLedger(str(tmp_path / "m"))
    assert led.latest_group() is None  # cold ledger: first boot
    led.write_group(dist.ElasticGroup(generation=0, ranks=(0, 1), rank=0,
                                      coordinator_address="a:1"))
    led.write_group(dist.ElasticGroup(generation=2, ranks=(0,), rank=0,
                                      coordinator_address="a:1"))
    rec = led.latest_group()
    assert rec["generation"] == 2
    assert rec["ranks"] == [0] and rec["world_size"] == 1
    # A torn write (crash mid-manifest) must be skipped, not fatal.
    with open(os.path.join(led.directory, "group-00000007.json"), "w") as f:
        f.write('{"generation": 7, "ran')
    assert led.latest_group()["generation"] == 2
    # Clean exits take their heartbeat with them.
    led.write_heartbeat(3, "c:1")
    led.remove(3)
    assert 3 not in led.read()
    led.remove(3)  # idempotent


def test_membership_delta_lost_gained_reborn(tmp_path):
    led = dist.MembershipLedger(str(tmp_path / "m"))
    # Group (0, 1) finalized at generation 1. Rank 0 heartbeats at the
    # group's generation (healthy member); rank 1's heartbeat is stale
    # (dead); rank 2 is a fresh non-member (a joiner).
    led.write_heartbeat(0, "a:1", generation=1)
    led.write_heartbeat(1, "b:1", generation=1)
    led.write_heartbeat(2, "c:1", generation=0)
    old = time.time() - 60
    os.utime(os.path.join(led.directory, "rank-1.json"), (old, old))
    lost, gained = dist.membership_delta(led, (0, 1), 1, timeout_s=5.0)
    assert lost == {1} and gained == {2}
    # Reborn: rank 0's file is now FRESH but carries generation 0 — a
    # recreated pod heartbeating under a member's rank. The process the
    # group wired is gone (lost) AND a new one wants in (gained).
    led.write_heartbeat(0, "a:1", generation=0)
    lost, gained = dist.membership_delta(led, (0, 1), 1, timeout_s=5.0)
    assert 0 in lost and 0 in gained
    assert lost == {0, 1} and gained == {0, 2}


# --- socket barrier: formation and coordinator takeover, in threads -------


def _cfg(tmp_path, port, **kw):
    defaults = dict(min_world=1, max_world=0, settle_s=0.2,
                    heartbeat_s=0.1, loss_timeout_s=0.5,
                    advertise_address=f"127.0.0.1:{port}",
                    ledger_dir=str(tmp_path / "membership"))
    defaults.update(kw)
    return dist.ElasticConfig(**defaults)


def test_generation0_formation_then_survivor_takeover(tmp_path):
    base = _free_port()
    ports = {r: base + 50 * r for r in range(3)}
    cfgs = {r: _cfg(tmp_path, ports[r]) for r in range(3)}
    ledger = dist.MembershipLedger(str(tmp_path / "membership"))
    for r in range(3):
        ledger.write_heartbeat(r, cfgs[r].advertise_address)

    def join(rank, generation, results, expected):
        try:
            results[rank] = dist.elastic_rendezvous(
                cfgs[rank], dist.MembershipLedger(ledger.directory),
                rank, generation, expected=expected, timeout_s=10.0,
                attempts=2, backoff_s=0.1, emit=lambda *a, **k: None)
        except Exception as e:  # noqa: BLE001 — surfaced by assertions
            results[rank] = e

    # Generation 0: the full expected roster arrives; rank 0 coordinates.
    results = {}
    threads = [threading.Thread(target=join, args=(r, 0, results, range(3)))
               for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for r in range(3):
        g = results[r]
        assert isinstance(g, dist.ElasticGroup), g
        assert g.ranks == (0, 1, 2)
        assert g.rank == r
        assert g.coordinator_address == cfgs[0].advertise_address
    assert results[0].is_primary and not results[1].is_primary

    # Rank 0 "dies": its heartbeat goes stale. Generation 1 among the
    # survivors — the next-lowest ORIGINAL rank (1) must take over as
    # coordinator AND become the new primary (dense rank 0).
    old = time.time() - 60
    os.utime(os.path.join(ledger.directory, "rank-0.json"), (old, old))
    results = {}
    threads = [threading.Thread(target=join, args=(r, 1, results, None))
               for r in (1, 2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for r in (1, 2):
        g = results[r]
        assert isinstance(g, dist.ElasticGroup), g
        assert g.generation == 1
        assert g.ranks == (1, 2)
        assert g.coordinator_address == cfgs[1].advertise_address
    assert results[1].rank == 0 and results[1].is_primary
    assert results[2].rank == 1 and not results[2].is_primary


def test_coordinator_abdicates_to_alive_lower_rank(tmp_path):
    """Split-brain guard: a rank that self-elected off a ledger view
    that predated a lower rank's first heartbeat must abdicate (and
    retry as a member) the moment that heartbeat appears — otherwise
    both coordinators wait out the full timeout and the world forms as
    two solo groups."""
    cfg = _cfg(tmp_path, _free_port())
    ledger = dist.MembershipLedger(cfg.ledger_dir)
    ledger.write_heartbeat(0, "127.0.0.1:1")  # rank 0 is alive
    ledger.write_heartbeat(1, cfg.advertise_address)
    with pytest.raises(dist.RendezvousError, match="abdicating"):
        dist._run_coordinator(cfg, 1, 0, {0, 1}, ledger, timeout_s=5.0)


def test_rendezvous_below_min_world_raises(tmp_path):
    cfg = _cfg(tmp_path, _free_port(), min_world=2, settle_s=0.05)
    ledger = dist.MembershipLedger(cfg.ledger_dir)
    ledger.write_heartbeat(0, cfg.advertise_address)
    with pytest.raises(dist.RendezvousError, match="min_world"):
        dist.elastic_rendezvous(cfg, ledger, 0, 0, expected=None,
                                timeout_s=1.0, attempts=1, backoff_s=0.05,
                                emit=lambda *a, **k: None)


def test_pinned_roster_never_finalizes_partial(tmp_path):
    """Boot pins the full Indexed-Job roster: with staggered pod
    scheduling (image pulls routinely exceed settle_s) the first rank up
    must NOT finalize a singleton gen-0 group that latecomers can never
    join — it waits for everyone or raises."""
    cfg = _cfg(tmp_path, _free_port(), settle_s=0.05)
    ledger = dist.MembershipLedger(cfg.ledger_dir)
    ledger.write_heartbeat(0, cfg.advertise_address)  # rank 1 not up yet
    with pytest.raises(dist.RendezvousError, match="timed out"):
        dist._run_coordinator(cfg, 0, 0, {0, 1}, ledger, timeout_s=0.8)


def test_open_roster_waits_for_alive_late_member(tmp_path):
    """Resync rosters are open, but the settle break still waits for
    every ledger-alive rank: a member whose hello is slower than
    settle_s joins the group instead of being locked out."""
    base = _free_port()
    cfgs = {r: _cfg(tmp_path, base + 50 * r) for r in range(2)}
    ledger = dist.MembershipLedger(str(tmp_path / "membership"))
    # Both ranks run the heartbeat daemon (as train_job does): rank 1 is
    # ALIVE the whole time, just slow to say hello — 3x the settle
    # window. Without the daemons either side's one-shot heartbeat would
    # go stale and the other would correctly treat it as dead.
    daemons = [dist.MembershipLedger(ledger.directory) for _ in range(2)]
    for r in range(2):
        daemons[r].start_heartbeat(r, cfgs[r].advertise_address,
                                   interval_s=0.1)
    results = {}

    def join(rank, delay):
        time.sleep(delay)
        try:
            results[rank] = dist.elastic_rendezvous(
                cfgs[rank], dist.MembershipLedger(ledger.directory),
                rank, 1, expected=None, timeout_s=10.0, attempts=2,
                backoff_s=0.1, emit=lambda *a, **k: None)
        except Exception as e:  # noqa: BLE001 — surfaced by assertions
            results[rank] = e

    threads = [threading.Thread(target=join, args=(0, 0.0)),
               threading.Thread(target=join, args=(1, 0.6))]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        for d in daemons:
            d.stop()
    for r in range(2):
        g = results[r]
        assert isinstance(g, dist.ElasticGroup), g
        assert g.ranks == (0, 1) and g.generation == 1


# --- integration: real subprocesses, real kills ---------------------------


TRAIN_CMD = [sys.executable, "-m", "k3stpu.parallel.train_job",
             "--model", "tiny", "--batch", "8", "--seq", "32"]


def _sub_env(**extra):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("K3STPU_CHAOS", None)
    try:
        user = getpass.getuser()
    except (KeyError, OSError):
        user = str(os.getuid())
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.environ.get(
        "K3STPU_TEST_CACHE", f"/tmp/k3stpu-test-compile-cache-{user}"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _elastic_env(rank, port, **extra):
    # Tight elastic knobs so loss detection fits a test budget: 0.2s
    # heartbeats, a 1s loss timeout, and a short settle window.
    knobs = dict(
        K3STPU_NUM_PROCESSES=2, K3STPU_PROCESS_ID=rank,
        K3STPU_COORDINATOR="127.0.0.1:29400",  # unused by the barrier
        K3STPU_ELASTIC=1, K3STPU_ADVERTISE_ADDRESS=f"127.0.0.1:{port}",
        K3STPU_ELASTIC_SETTLE_S=0.3, K3STPU_ELASTIC_HEARTBEAT_S=0.2,
        K3STPU_ELASTIC_LOSS_TIMEOUT_S=1.0, K3STPU_ELASTIC_MIN_WORLD=1,
        K3STPU_RDV_TIMEOUT_S=60)
    knobs.update(extra)
    return _sub_env(**knobs)


def _scrape(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as r:
        return r.read().decode()


def _metric(text, name):
    m = re.search(rf"^{name} ([0-9.eE+-]+)$", text, re.M)
    return float(m.group(1)) if m else None


def _stream_until_done(proc, scrape_port=None, scrape_gen0=False):
    """Read a rank's stdout to completion; optionally scrape /metrics at
    the first gen-0 'step' event (the emitting rank's own server is
    guaranteed up by then) and right after 'elastic_resync' (the resync
    handler starts/keeps the server before emitting). Returns
    (rc, events, scrapes)."""
    events, scrapes = [], {}
    reaper = threading.Timer(420, proc.kill)
    reaper.start()
    try:
        for line in proc.stdout:
            line = line.strip()
            if not line.startswith("{"):
                continue
            ev = json.loads(line)
            events.append(ev)
            if scrape_port is None:
                continue
            if (scrape_gen0 and ev["event"] == "step"
                    and "gen0" not in scrapes):
                scrapes["gen0"] = _scrape(scrape_port)
            elif ev["event"] == "elastic_resync":
                scrapes["resync"] = _scrape(scrape_port)
        rc = proc.wait(timeout=60)
    finally:
        reaper.cancel()
        if proc.poll() is None:
            proc.kill()
    return rc, events, scrapes


def _losses_by_step(events):
    """step -> loss, keeping the LAST occurrence (post-resync retrain of
    a step overwrites the pre-loss-detection one)."""
    return {e["step"]: e["loss"] for e in events if e["event"] == "step"}


def test_rank_loss_resync_resume_and_twin_equivalence(tmp_path):
    """The tentpole acceptance: SIGKILL-style death of rank 1 mid-run ->
    rank 0 detects by heartbeat staleness, re-rendezvouses at world 1,
    restores the last finalized checkpoint, continues to completion with
    losses equal to an uninterrupted single-process twin — and the
    /metrics world-size gauge tracks 2 -> 1."""
    corpus = tmp_path / "corpus.bin"
    synthetic_corpus(corpus, vocab_size=256, n_tokens=1 << 15)
    cdir = tmp_path / "ckpt"
    mport = _free_port()
    base = _free_port()
    args = ["--steps", "60", "--ckpt-every", "5", "--ckpt-dir", str(cdir),
            "--data", str(corpus), "--data-seed", "7"]
    # Rank 0 paced at ~50ms/step so the ~1.5s detection latency lands
    # well before step 60; rank 1 rushes to step 5 and hard-exits.
    p0 = subprocess.Popen(
        TRAIN_CMD + args + ["--metrics-port", str(mport)],
        env=_elastic_env(0, base,
                         K3STPU_CHAOS="train_step:stall_s=0.05:times=1000"),
        text=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    p1 = subprocess.Popen(
        TRAIN_CMD + args,
        env=_elastic_env(1, base + 500,
                         K3STPU_CHAOS="rank_loss:skip=5:times=1"),
        text=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out1, _ = p1.communicate(timeout=300)
    rc0, ev0, scrapes = _stream_until_done(p0, scrape_port=mport,
                                           scrape_gen0=True)

    # Rank 1 died hard, mid-run, on purpose.
    assert p1.returncode == 1, out1[-2000:]
    (exit_ev,) = [e for e in _events(out1) if e["event"] == "chaos_rank_exit"]
    assert exit_ev["rank"] == 1 and exit_ev["generation"] == 0
    assert rc0 == 0, ev0[-10:]

    # Rank 0: detection -> generation-1 resync -> checkpoint resume.
    (lost_ev,) = [e for e in ev0 if e["event"] == "elastic_membership_lost"]
    assert lost_ev["lost"] == [1] and lost_ev["generation"] == 0
    (rs,) = [e for e in ev0 if e["event"] == "elastic_resync"]
    assert rs["generation"] == 1
    assert rs["world_size"] == 1 and rs["ranks"] == [0]
    assert rs["lost"] == [1]
    assert rs["recovery_s"] > 0
    (resume,) = [e for e in ev0 if e["event"] == "resume"]
    assert resume["step"] == rs["resume_step"] > 0
    assert rs["resume_step"] in ckpt.finalized_steps(cdir)
    # The run completed: every step up to 60 trained (post-resync for
    # the tail), and the goodput ledger billed the resync to 'recovery'.
    assert max(_losses_by_step(ev0)) == 60
    (good,) = [e for e in ev0 if e["event"] == "goodput"]
    assert good["seconds"]["recovery"] > 0

    # Checkpoint manifests carry the world size that wrote them.
    assert ckpt.manifest_world_size(cdir, rs["resume_step"]) == 2
    assert ckpt.manifest_world_size(cdir, 60) == 1

    # /metrics tracked the membership change on the live gauge.
    assert _metric(scrapes["gen0"], "k3stpu_train_world_size") == 2.0
    assert _metric(scrapes["resync"], "k3stpu_train_world_size") == 1.0
    assert _metric(scrapes["resync"],
                   "k3stpu_train_elastic_resyncs_total") == 1.0
    assert _metric(scrapes["resync"],
                   "k3stpu_train_elastic_lost_ranks_total") == 1.0

    # Twin equivalence: an uninterrupted single-process run of the same
    # corpus/seed/batch produces the same loss at every step — the
    # membership change changed WHO computed, never WHAT was trained.
    twin = subprocess.run(
        TRAIN_CMD + ["--steps", "60", "--data", str(corpus),
                     "--data-seed", "7"],
        env=_sub_env(), text=True, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, timeout=300)
    assert twin.returncode == 0, twin.stdout[-2000:]
    twin_losses = _losses_by_step(_events(twin.stdout))
    mine = _losses_by_step(ev0)
    assert set(twin_losses) == set(mine)
    for step, loss in twin_losses.items():
        assert mine[step] == pytest.approx(loss, rel=1e-4, abs=1e-4), step


def test_replacement_boot_joins_at_ledger_generation(tmp_path):
    """A recreated pod must NOT assume generation 0: it reads the
    ledger's persisted group manifest and boots one generation past it
    with an open roster. Here the manifest says the run is at gen 3, so
    the replacement forms (and trains at) generation 4."""
    ldir = tmp_path / "membership"
    dist.MembershipLedger(str(ldir)).write_group(
        dist.ElasticGroup(generation=3, ranks=(0,), rank=0,
                          coordinator_address="127.0.0.1:1"))
    proc = subprocess.run(
        TRAIN_CMD + ["--steps", "3"],
        env=_elastic_env(0, _free_port(),
                         K3STPU_ELASTIC_LEDGER_DIR=str(ldir)),
        text=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=300)
    assert proc.returncode == 0, proc.stdout[-2000:]
    events = _events(proc.stdout)
    (start,) = [e for e in events if e["event"] == "train_start"]
    assert start["elastic"] and start["generation"] == 4
    assert start["world_size"] == 1
    # No --ckpt-dir: the boot warned, loudly, that a resync would reset
    # the weights.
    assert any(e["event"] == "elastic_without_checkpoint" for e in events)


def test_unjoinable_replacement_exits_preempted_code(tmp_path):
    """A replacement that cannot re-form a group (here: min_world unmet,
    nobody else alive) must exit with the podFailurePolicy-ignored code
    instead of burning the Job's backoffLimit toward whole-Job death —
    and take its heartbeat with it so it cannot poison a later
    coordinator election."""
    ldir = tmp_path / "membership"
    dist.MembershipLedger(str(ldir)).write_group(
        dist.ElasticGroup(generation=1, ranks=(0,), rank=0,
                          coordinator_address="127.0.0.1:1"))
    proc = subprocess.run(
        TRAIN_CMD + ["--steps", "3"],
        env=_elastic_env(1, _free_port(),
                         K3STPU_ELASTIC_LEDGER_DIR=str(ldir),
                         K3STPU_ELASTIC_MIN_WORLD=2,
                         K3STPU_RDV_TIMEOUT_S=1, K3STPU_RDV_ATTEMPTS=1),
        text=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=300)
    assert proc.returncode == 42, proc.stdout[-2000:]
    events = _events(proc.stdout)
    (fail,) = [e for e in events if e["event"] == "elastic_rejoin_failed"]
    assert fail["generation"] == 2  # manifest gen 1 -> tried to join at 2
    assert not any(e["event"] == "train_start" for e in events)
    assert not os.path.exists(ldir / "rank-1.json")  # heartbeat removed


@pytest.mark.slow
def test_recreated_rank_rejoins_and_world_regrows(tmp_path):
    """The full Indexed-Job story: rank 1 dies hard, rank 0 resyncs to
    world 1 and keeps training; the Job controller recreates index 1,
    which boots at the ledger's generation; rank 0 detects the joiner
    and re-rendezvouses, the world regrows to 2, and the replacement
    resumes from the shared checkpoint tree — losses still equal an
    uninterrupted twin's."""
    corpus = tmp_path / "corpus.bin"
    synthetic_corpus(corpus, vocab_size=256, n_tokens=1 << 15)
    cdir = tmp_path / "ckpt"
    base = _free_port()
    args = ["--steps", "80", "--ckpt-every", "5", "--ckpt-dir", str(cdir),
            "--data", str(corpus), "--data-seed", "7"]
    # Rank 0 paced at ~0.3s/step: the kill at step 5, the ~1.5s loss
    # detection, AND the replacement's full process boot (~10s of jax
    # import + compile) all land well before step 80.
    p0 = subprocess.Popen(
        TRAIN_CMD + args,
        env=_elastic_env(0, base,
                         K3STPU_CHAOS="train_step:stall_s=0.3:times=1000"),
        text=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    p1 = subprocess.Popen(
        TRAIN_CMD + args,
        env=_elastic_env(1, base + 500,
                         K3STPU_CHAOS="rank_loss:skip=5:times=1"),
        text=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    p1.communicate(timeout=300)
    assert p1.returncode == 1
    # Let rank 0 notice the death and finish its shrink-to-1 resync, so
    # the replacement's manifest read sees the post-loss generation.
    time.sleep(3.0)
    p1b = subprocess.Popen(
        TRAIN_CMD + args,
        env=_elastic_env(1, base + 500),
        text=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out1b, _ = p1b.communicate(timeout=420)
    rc0, ev0, _ = _stream_until_done(p0)
    assert rc0 == 0, ev0[-10:]
    assert p1b.returncode == 0, out1b[-2000:]
    ev1b = _events(out1b)

    # The replacement did not boot at generation 0 — it joined where the
    # ledger said the run was, and resumed from the checkpoint tree.
    (start1b,) = [e for e in ev1b if e["event"] == "train_start"]
    assert start1b["generation"] >= 1
    (resume1b,) = [e for e in ev1b if e["event"] == "resume"]
    assert resume1b["step"] > 0

    # Rank 0 shrank to world 1, then REGREW to 2 when the joiner showed
    # up (and may shrink again when the unpaced replacement finishes
    # first and departs cleanly).
    resyncs = [e for e in ev0 if e["event"] == "elastic_resync"]
    assert resyncs[0]["world_size"] == 1 and resyncs[0]["ranks"] == [0]
    assert any(r["world_size"] == 2 and r["ranks"] == [0, 1]
               for r in resyncs)
    gained = [e for e in ev0 if e["event"] == "elastic_membership_lost"
              and e.get("gained")]
    assert any(g["gained"] == [1] for g in gained)

    # Twin equivalence survives the whole shrink/regrow dance: the
    # membership changed twice (or thrice), the data order never did.
    twin = subprocess.run(
        TRAIN_CMD + ["--steps", "80", "--data", str(corpus),
                     "--data-seed", "7"],
        env=_sub_env(), text=True, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, timeout=300)
    assert twin.returncode == 0, twin.stdout[-2000:]
    twin_losses = _losses_by_step(_events(twin.stdout))
    mine = _losses_by_step(ev0)
    assert max(mine) == 80
    assert set(twin_losses) == set(mine)
    for step, loss in twin_losses.items():
        assert mine[step] == pytest.approx(loss, rel=1e-4, abs=1e-4), step


@pytest.mark.slow
def test_coordinator_loss_takeover_soak(tmp_path):
    """Kill the COORDINATOR (rank 0, also the primary): rank 1 must take
    over coordination, inherit primary duties (checkpoint manifests, the
    /metrics port), and finish the run alone."""
    corpus = tmp_path / "corpus.bin"
    synthetic_corpus(corpus, vocab_size=256, n_tokens=1 << 15)
    cdir = tmp_path / "ckpt"
    mport = _free_port()
    base = _free_port()
    args = ["--steps", "100", "--ckpt-every", "5", "--ckpt-dir", str(cdir),
            "--data", str(corpus), "--data-seed", "7",
            "--metrics-port", str(mport)]
    pace = "train_step:stall_s=0.05:times=1000"
    p0 = subprocess.Popen(
        TRAIN_CMD + args,
        env=_elastic_env(0, base,
                         K3STPU_CHAOS=pace + ";coordinator_loss:skip=8:times=1"),
        text=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    p1 = subprocess.Popen(
        TRAIN_CMD + args,
        env=_elastic_env(1, base + 500, K3STPU_CHAOS=pace),
        text=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out0, _ = p0.communicate(timeout=420)
    rc1, ev1, scrapes = _stream_until_done(p1, scrape_port=mport)

    assert p0.returncode == 1, out0[-2000:]
    assert any(e["event"] == "chaos_rank_exit" for e in _events(out0))
    assert rc1 == 0, ev1[-10:]
    (rs,) = [e for e in ev1 if e["event"] == "elastic_resync"]
    assert rs["ranks"] == [1] and rs["world_size"] == 1
    assert max(_losses_by_step(ev1)) == 100
    # Primary duty moved: rank 1 wrote the post-takeover manifests and
    # now answers on the metrics port rank 0 took to its grave.
    assert ckpt.manifest_world_size(cdir, 100) == 1
    assert _metric(scrapes["resync"], "k3stpu_train_world_size") == 1.0


@pytest.mark.slow
def test_elastic_recovery_beats_full_restart(tmp_path):
    """The point of the whole subsystem: an in-process resync costs
    recovery_s (goodput 'recovery' bucket); the PR-4 alternative — exit
    nonzero, Job restart, reimport jax, recompile, restore — costs the
    full process boot. Measure both against the same checkpoint tree."""
    corpus = tmp_path / "corpus.bin"
    synthetic_corpus(corpus, vocab_size=256, n_tokens=1 << 15)
    cdir = tmp_path / "ckpt"
    base = _free_port()
    args = ["--steps", "60", "--ckpt-every", "5", "--ckpt-dir", str(cdir),
            "--data", str(corpus), "--data-seed", "7"]
    p0 = subprocess.Popen(
        TRAIN_CMD + args,
        env=_elastic_env(0, base,
                         K3STPU_CHAOS="train_step:stall_s=0.05:times=1000"),
        text=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    p1 = subprocess.Popen(
        TRAIN_CMD + args,
        env=_elastic_env(1, base + 500,
                         K3STPU_CHAOS="rank_loss:skip=5:times=1"),
        text=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    p1.communicate(timeout=300)
    rc0, ev0, _ = _stream_until_done(p0)
    assert rc0 == 0
    (rs,) = [e for e in ev0 if e["event"] == "elastic_resync"]

    # Full-restart arm: a fresh non-elastic process resuming the same
    # tree; its recovery cost is spawn -> first post-resume step.
    t0 = time.monotonic()
    proc = subprocess.Popen(
        TRAIN_CMD + ["--steps", "62", "--ckpt-every", "400",
                     "--ckpt-dir", str(cdir), "--data", str(corpus),
                     "--data-seed", "7"],
        env=_sub_env(), text=True, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    restart_s = None
    try:
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("{") and json.loads(line)["event"] == "step":
                restart_s = time.monotonic() - t0
                break
    finally:
        proc.kill()
        proc.wait(timeout=60)
    assert restart_s is not None
    # "Measurably lower": an in-process resync skips interpreter boot,
    # jax import and XLA warmup, so even with generous slack it must be
    # well under the restart path.
    assert rs["recovery_s"] < restart_s / 2, (rs["recovery_s"], restart_s)
