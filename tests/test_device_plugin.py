"""C++ TPU device plugin driven by a Python grpcio fake kubelet.

Interop test of the whole native stack — hand-rolled HTTP/2 + HPACK +
protobuf against the reference gRPC implementation — per SURVEY.md §4
("a fake kubelet ... to test Register/ListAndWatch/Allocate without K8s").
"""

import os
import queue
import signal
import subprocess
import time

import grpc
import pytest

import dp_proto as pb
from conftest import plugin_channel_for, wait_for_socket

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

IDENT = dict(request_serializer=lambda x: x,
             response_deserializer=lambda x: x)


@pytest.fixture(scope="session")
def plugin_bin(native_build):
    return str(native_build / "tpu-device-plugin")


@pytest.fixture()
def plugin(native_build, fake_host_root, tmp_path, request):
    """Plugin with 4 fake v5e chips x 4 replicas, no kubelet registration."""
    kills_plugin = "sigterm" in request.node.name
    plugin_dir = tmp_path / "kubelet"
    with plugin_channel_for(native_build, fake_host_root, plugin_dir,
                            "--replicas", "4", "--scan-seconds", "1",
                            expect_clean_exit=not kills_plugin) as (ch, proc):
        yield ch, proc, plugin_dir


def test_dump_inventory(plugin_bin, fake_host_root):
    out = subprocess.run(
        [plugin_bin, "--dump", "--replicas", "4", "--host-root",
         str(fake_host_root)],
        capture_output=True, text=True)
    assert out.returncode == 0
    import json
    inv = json.loads(out.stdout)
    assert inv["chip_count"] == 4
    assert inv["schedulable"] == 16
    assert inv["topology"] == "2x2"
    assert inv["chips"][0]["generation"] == "tpu-v5e"


def test_get_options(plugin):
    channel, _, _ = plugin
    call = channel.unary_unary(
        "/v1beta1.DevicePlugin/GetDevicePluginOptions", **IDENT)
    resp = call(pb.empty(), timeout=5)
    assert bool(pb.first(resp, 2, 0))  # get_preferred_allocation_available
    assert not bool(pb.first(resp, 1, 0))  # pre_start_required


def test_list_and_watch_advertises_replicas(plugin):
    channel, _, _ = plugin
    stream = channel.unary_stream(
        "/v1beta1.DevicePlugin/ListAndWatch", **IDENT)(pb.empty())
    first = next(iter(stream))
    devices = pb.parse_devices(first)
    # 4 chips x 4 replicas, parity with values.yaml:18 (1 GPU -> 4).
    assert len(devices) == 16
    ids = {d["id"] for d in devices}
    assert "tpu-0-0" in ids and "tpu-3-3" in ids
    assert all(d["health"] == "Healthy" for d in devices)
    by_chip0 = [d for d in devices if d["id"].startswith("tpu-0-")]
    assert all(d["numa"] == 0 for d in by_chip0)
    by_chip3 = [d for d in devices if d["id"].startswith("tpu-3-")]
    assert all(d["numa"] == 1 for d in by_chip3)
    stream.cancel()


def test_allocate_two_chips(plugin):
    channel, _, _ = plugin
    call = channel.unary_unary("/v1beta1.DevicePlugin/Allocate", **IDENT)
    resp = call(pb.allocate_request(["tpu-1-0", "tpu-2-1"]), timeout=5)
    [alloc] = pb.parse_allocate_response(resp)
    assert alloc["envs"]["TPU_VISIBLE_CHIPS"] == "1,2"
    assert alloc["envs"]["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,2"
    assert alloc["envs"]["TPU_ACCELERATOR_TYPE"] == "tpu-v5e-2"
    # 4-way sharing -> per-pod HBM cap present.
    assert alloc["envs"]["TPU_MEM_FRACTION"].startswith("0.25")
    dev_paths = [d["container_path"] for d in alloc["devices"]]
    assert dev_paths == ["/dev/accel1", "/dev/accel2"]
    assert all(d["permissions"] == "rwm" for d in alloc["devices"])
    [mount] = alloc["mounts"]
    assert mount["container_path"] == "/lib/libtpu.so"
    assert mount["read_only"]
    assert alloc["annotations"]["tpu.google.com/chips"] == "1,2"


def test_allocate_same_chip_replicas_collapse(plugin):
    channel, _, _ = plugin
    call = channel.unary_unary("/v1beta1.DevicePlugin/Allocate", **IDENT)
    resp = call(pb.allocate_request(["tpu-2-0", "tpu-2-3"]), timeout=5)
    [alloc] = pb.parse_allocate_response(resp)
    assert alloc["envs"]["TPU_VISIBLE_CHIPS"] == "2"
    assert [d["container_path"] for d in alloc["devices"]] == ["/dev/accel2"]


def test_allocate_unknown_chip_fails(plugin):
    channel, _, _ = plugin
    call = channel.unary_unary("/v1beta1.DevicePlugin/Allocate", **IDENT)
    with pytest.raises(grpc.RpcError) as err:
        call(pb.allocate_request(["tpu-9-0"]), timeout=5)
    assert err.value.code() == grpc.StatusCode.NOT_FOUND


def test_preferred_allocation_contiguous(plugin):
    channel, _, _ = plugin
    available = [f"tpu-{c}-{r}" for c in (0, 1, 3) for r in range(4)]
    call = channel.unary_unary(
        "/v1beta1.DevicePlugin/GetPreferredAllocation", **IDENT)
    resp = call(pb.preferred_request(available, 8), timeout=5)
    [chosen] = pb.parse_preferred_response(resp)
    assert len(chosen) == 8
    chips = {int(d.split("-")[1]) for d in chosen}
    # On the 2x2 tray, chips 0,1 form a 1x2 sub-mesh covering 8 ids; chip 3
    # at (1,1) would widen the rectangle and must be avoided.
    assert chips == {0, 1}


def make_tray_root(tmp_path, n_chips, coords=None):
    """Fake host fs with an n-chip v5e tray; optional per-chip tpu_coords
    sysfs attributes (the driver-exposed ground truth)."""
    for i in range(n_chips):
        bdf = (tmp_path / "sys" / "bus" / "pci" / "devices"
               / f"0000:00:{4 + i:02x}.0")
        bdf.mkdir(parents=True)
        (bdf / "vendor").write_text("0x1ae0\n")
        (bdf / "device").write_text("0x0062\n")
        (bdf / "numa_node").write_text(f"{i * 2 // n_chips}\n")
        if coords is not None:
            (bdf / "tpu_coords").write_text("%d,%d\n" % coords[i])
    dev = tmp_path / "dev"
    dev.mkdir()
    for i in range(n_chips):
        (dev / f"accel{i}").write_text("")
    return tmp_path


@pytest.fixture()
def tray8_plugin(native_build, tmp_path, request):
    """Plugin over an 8-chip 2x4 tray (row-major coords), 2 replicas."""
    coords = getattr(request, "param", None)
    root = make_tray_root(tmp_path / "root", 8, coords)
    with plugin_channel_for(native_build, root, tmp_path / "kubelet",
                            "--replicas", "2", "--scan-seconds", "60"
                            ) as (ch, _):
        yield ch


def _preferred(channel, available, size, must=()):
    call = channel.unary_unary(
        "/v1beta1.DevicePlugin/GetPreferredAllocation", **IDENT)
    resp = call(pb.preferred_request(list(available), size, list(must)),
                timeout=5)
    [chosen] = pb.parse_preferred_response(resp)
    return chosen


def test_preferred_prefers_submesh_over_contiguous_indices(tray8_plugin):
    """2x4 tray: chips 3 (3,0) and 4 (0,1) are index-contiguous but share
    no ICI link; chips 4,5 form a real 1x2 sub-mesh and must win."""
    available = [f"tpu-{c}-0" for c in (3, 4, 5)]
    chosen = _preferred(tray8_plugin, available, 2)
    assert {int(d.split("-")[1]) for d in chosen} == {4, 5}


def test_preferred_picks_2x2_rectangle_from_noncontiguous(tray8_plugin):
    """Available {0,1,4,5} (non-contiguous indices) is a perfect 2x2
    sub-mesh; {2,6} would stretch the rectangle and must be avoided."""
    available = [f"tpu-{c}-0" for c in (0, 1, 2, 4, 5, 6)]
    chosen = _preferred(tray8_plugin, available, 4)
    assert {int(d.split("-")[1]) for d in chosen} == {0, 1, 4, 5}


def test_preferred_square_beats_row(tray8_plugin):
    """For 4 chips with both a 1x4 row and a 2x2 square free, the square
    wins (equal area, smaller perimeter — more ICI bisection links)."""
    available = [f"tpu-{c}-0" for c in (0, 1, 2, 3, 4, 5)]
    chosen = _preferred(tray8_plugin, available, 4)
    chips = {int(d.split("-")[1]) for d in chosen}
    assert chips == {0, 1, 4, 5}


def test_preferred_counts_replicas_within_rectangle(tray8_plugin):
    """8 replica-ids on the 2x2 {0,1,4,5} (2 replicas each x 4 chips)
    satisfy size=8 without leaving the rectangle."""
    available = [f"tpu-{c}-{r}" for c in (0, 1, 3, 4, 5, 7)
                 for r in range(2)]
    chosen = _preferred(tray8_plugin, available, 8)
    assert {int(d.split("-")[1]) for d in chosen} == {0, 1, 4, 5}


def test_preferred_must_include_anchors_rectangle(tray8_plugin):
    """A pinned chip at (3,0) must pull its companion to an adjacent chip
    ((2,0) or (3,1)), not to a compact island at the origin."""
    available = [f"tpu-{c}-0" for c in range(8)]
    chosen = _preferred(tray8_plugin, available, 2, must=["tpu-3-0"])
    chips = {int(d.split("-")[1]) for d in chosen}
    assert "tpu-3-0" in chosen and len(chips) == 2
    assert chips - {3} <= {2, 7}, chips


@pytest.fixture()
def core_plugin(native_build, tmp_path):
    """Plugin in per-TensorCore granularity over 2 v5p chips (2 cores
    each), replicas=1 — the reference's MIG-analogue spatial split."""
    root = make_tray_root(tmp_path / "root", 2)
    for bdf in (root / "sys" / "bus" / "pci" / "devices").iterdir():
        if (bdf / "vendor").read_text().strip() == "0x1ae0":
            (bdf / "device").write_text("0x0063\n")  # v5p: 2 TensorCores
    with plugin_channel_for(native_build, root, tmp_path / "kubelet",
                            "--replicas", "1", "--granularity", "core",
                            "--scan-seconds", "60") as (ch, _):
        yield ch


def test_core_granularity_doubles_schedulable_units(core_plugin):
    stream = core_plugin.unary_stream(
        "/v1beta1.DevicePlugin/ListAndWatch", **IDENT)(pb.empty())
    devices = pb.parse_devices(next(iter(stream)))
    # 2 chips x 2 TensorCores x 1 replica.
    assert {d["id"] for d in devices} == {
        "tpu-0-c0-0", "tpu-0-c1-0", "tpu-1-c0-0", "tpu-1-c1-0"}
    stream.cancel()


def test_core_granularity_allocate_single_core(core_plugin):
    call = core_plugin.unary_unary(
        "/v1beta1.DevicePlugin/Allocate", **IDENT)
    [alloc] = pb.parse_allocate_response(
        call(pb.allocate_request(["tpu-1-c1-0"]), timeout=5))
    assert alloc["envs"]["TPU_VISIBLE_CHIPS"] == "1"
    assert alloc["envs"]["TPU_VISIBLE_TENSORCORES"] == "1:1"
    # Half a 2-core chip -> half its HBM, shared-process mode on.
    assert alloc["envs"]["TPU_MEM_FRACTION"].startswith("0.5")
    assert alloc["envs"]["TPU_ALLOW_MULTIPLE_LIBTPU_PROCESSES"] == "1"
    assert [d["container_path"] for d in alloc["devices"]] == ["/dev/accel1"]


def test_core_granularity_whole_chip_is_exclusive(core_plugin):
    """Both cores of a chip in one pod = the whole chip: no HBM cap, no
    shared-process mode."""
    call = core_plugin.unary_unary(
        "/v1beta1.DevicePlugin/Allocate", **IDENT)
    [alloc] = pb.parse_allocate_response(
        call(pb.allocate_request(["tpu-0-c0-0", "tpu-0-c1-0"]), timeout=5))
    assert alloc["envs"]["TPU_VISIBLE_CHIPS"] == "0"
    assert alloc["envs"]["TPU_VISIBLE_TENSORCORES"] == "0:0,0:1"
    assert "TPU_MEM_FRACTION" not in alloc["envs"]
    assert "TPU_ALLOW_MULTIPLE_LIBTPU_PROCESSES" not in alloc["envs"]


def test_core_granularity_preferred_allocation(core_plugin):
    """Rectangle search still groups per-core ids by chip: prefer both
    cores of one chip over cores spread across two chips."""
    available = ["tpu-0-c1-0", "tpu-1-c0-0", "tpu-1-c1-0"]
    chosen = _preferred(core_plugin, available, 2)
    assert set(chosen) == {"tpu-1-c0-0", "tpu-1-c1-0"}


@pytest.mark.parametrize(
    "tray8_plugin",
    # Driver-exposed coords override the row-major default: snake layout,
    # second row reversed — chip 4 sits at (3,1) under chip 3 (3,0).
    [[(0, 0), (1, 0), (2, 0), (3, 0), (3, 1), (2, 1), (1, 1), (0, 1)]],
    indirect=True)
def test_preferred_uses_sysfs_coords_when_present(tray8_plugin):
    """With snake-order tpu_coords, index neighbors 3,4 ARE mesh neighbors
    ((3,0)/(3,1)) while 4,5 are still adjacent; 3,4 must now win over the
    lexically-earlier-but-wider {3,5} or index pairs like {5,6}."""
    available = [f"tpu-{c}-0" for c in (3, 4, 6)]
    chosen = _preferred(tray8_plugin, available, 2)
    assert {int(d.split("-")[1]) for d in chosen} == {3, 4}


def test_health_flips_on_device_loss(plugin, fake_host_root):
    channel, _, _ = plugin
    stream = channel.unary_stream(
        "/v1beta1.DevicePlugin/ListAndWatch", **IDENT)(pb.empty())
    updates = queue.Queue()

    def consume():
        try:
            for msg in stream:
                updates.put(pb.parse_devices(msg))
        except grpc.RpcError:
            pass  # stream.cancel() at test end

    import threading
    t = threading.Thread(target=consume, daemon=True)
    t.start()
    initial = updates.get(timeout=5)
    assert all(d["health"] == "Healthy" for d in initial)

    # Simulate chip loss: drop the last accel node; rescan (1s) must stream
    # an update. The plugin pairs chips to nodes by index, so chip 3 loses
    # its device and goes Unhealthy (SURVEY.md §5 failure detection).
    os.unlink(fake_host_root / "dev" / "accel3")
    after = updates.get(timeout=10)
    unhealthy = {d["id"] for d in after if d["health"] == "Unhealthy"}
    assert unhealthy == {f"tpu-3-{r}" for r in range(4)}
    stream.cancel()


def test_sigterm_shutdown_with_open_stream(plugin):
    """SIGTERM while kubelet's ListAndWatch is connected must exit promptly
    (the DaemonSet would otherwise be SIGKILLed every rollout)."""
    channel, proc, _ = plugin
    stream = channel.unary_stream(
        "/v1beta1.DevicePlugin/ListAndWatch", **IDENT)(pb.empty())
    first = next(iter(stream))
    assert pb.parse_devices(first)
    proc.send_signal(signal.SIGTERM)
    assert proc.wait(timeout=5) == 0


def test_kubelet_reconnect(plugin):
    """A second ListAndWatch after dropping the first (kubelet restart) must
    get a fresh device list; the dropped stream must not strand the plugin."""
    channel, _, plugin_dir = plugin
    stream = channel.unary_stream(
        "/v1beta1.DevicePlugin/ListAndWatch", **IDENT)(pb.empty())
    next(iter(stream))
    channel.close()  # kubelet dies

    sock = plugin_dir / "k3stpu.sock"
    fresh = grpc.insecure_channel(f"unix://{sock}")
    try:
        stream2 = fresh.unary_stream(
            "/v1beta1.DevicePlugin/ListAndWatch", **IDENT)(pb.empty())
        devices = pb.parse_devices(next(iter(stream2)))
        assert len(devices) == 16
        stream2.cancel()
    finally:
        fresh.close()


def fake_kubelet(plugin_dir, received):
    """grpcio server speaking the kubelet Registration protocol."""
    from concurrent import futures

    class Registration(grpc.GenericRpcHandler):
        def service(self, handler_call_details):
            if handler_call_details.method == "/v1beta1.Registration/Register":
                def handler(request, context):
                    received.put(pb.parse_register_request(request))
                    return b""
                return grpc.unary_unary_rpc_method_handler(
                    handler, request_deserializer=lambda x: x,
                    response_serializer=lambda x: x)
            return None

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    server.add_generic_rpc_handlers((Registration(),))
    server.add_insecure_port(f"unix://{plugin_dir}/kubelet.sock")
    server.start()
    return server


def test_register_against_fake_kubelet(plugin_bin, fake_host_root, tmp_path):
    """The plugin's hand-rolled gRPC *client* must interop with a real grpc
    server (the fake kubelet), mirroring SURVEY.md §3.2's Register step."""
    plugin_dir = tmp_path / "kubelet"
    plugin_dir.mkdir()
    received = queue.Queue()
    server = fake_kubelet(plugin_dir, received)
    try:
        proc = subprocess.Popen(
            [plugin_bin, "--replicas", "2", "--plugin-dir", str(plugin_dir),
             "--host-root", str(fake_host_root)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            reg = received.get(timeout=10)
            assert reg == {
                "version": "v1beta1",
                "endpoint": "k3stpu.sock",
                "resource_name": "google.com/tpu",
                "preferred_alloc": True,
            }
        finally:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=5)
    finally:
        server.stop(None)


def test_reregisters_after_kubelet_restart(plugin_bin, fake_host_root,
                                           tmp_path):
    """Kubelet restart wipes the device-plugins dir; the plugin must notice
    its socket vanished, rebind, and Register again (the reference NVIDIA
    plugin does the same; without it google.com/tpu drops to 0 forever)."""
    plugin_dir = tmp_path / "kubelet"
    plugin_dir.mkdir()
    received = queue.Queue()
    server = fake_kubelet(plugin_dir, received)
    proc = subprocess.Popen(
        [plugin_bin, "--replicas", "2", "--plugin-dir", str(plugin_dir),
         "--host-root", str(fake_host_root)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        received.get(timeout=10)  # initial registration
        # Simulate kubelet restart: delete the plugin's socket.
        os.unlink(plugin_dir / "k3stpu.sock")
        reg2 = received.get(timeout=10)
        assert reg2["resource_name"] == "google.com/tpu"
        wait_for_socket(str(plugin_dir / "k3stpu.sock"))
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
        server.stop(None)
