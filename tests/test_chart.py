"""Helm chart: render with helm_lite and verify the control-plane objects.

Parity targets: the nvdp chart + NFD install the reference drives at
README.md:97-126, with the values schema of reference values.yaml:1-18.
"""

import os

import pytest
import yaml

from k3stpu.plugin_config import argv_for, parse_config
from k3stpu.utils.helm_lite import render_chart

CHART = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "deploy", "charts", "k3s-tpu",
)


def render(overrides=None, namespace="tpu-system"):
    text = render_chart(CHART, namespace=namespace, overrides=overrides)
    docs = [d for d in yaml.safe_load_all(text) if d]
    return {(d["kind"], d["metadata"]["name"]): d for d in docs}


def test_default_render_objects():
    objs = render()
    kinds = {k for k, _ in objs}
    assert kinds == {"RuntimeClass", "ConfigMap", "DaemonSet",
                     "ServiceAccount", "ClusterRole", "ClusterRoleBinding"}
    assert ("DaemonSet", "k3s-tpu-device-plugin") in objs
    assert ("DaemonSet", "k3s-tpu-feature-discovery") in objs


def test_runtimeclass_and_namespace():
    objs = render(namespace="custom-ns")
    rc = objs[("RuntimeClass", "tpu")]
    assert rc["handler"] == "tpu"
    cm = objs[("ConfigMap", "k3s-tpu-config")]
    assert cm["metadata"]["namespace"] == "custom-ns"


def test_config_roundtrip_to_plugin_flags():
    # The ConfigMap payload must parse back through plugin_config into the
    # flags the C++ binary takes — 4-way sharing by default (reference
    # values.yaml:18).
    objs = render()
    cm = objs[("ConfigMap", "k3s-tpu-config")]
    settings = parse_config(cm["data"]["config.yaml"])
    assert settings["resource"] == "google.com/tpu"
    assert settings["replicas"] == 4
    assert settings["fail_multi"] is False
    argv = argv_for(settings, "/usr/local/bin/tpu-device-plugin")
    assert argv == ["/usr/local/bin/tpu-device-plugin",
                    "--resource", "google.com/tpu", "--replicas", "4"]


def test_device_plugin_daemonset_wiring():
    objs = render()
    ds = objs[("DaemonSet", "k3s-tpu-device-plugin")]
    pod = ds["spec"]["template"]["spec"]
    # Label-gated like the reference's NFD-dependent plugin (README.md:99).
    assert pod["nodeSelector"] == {"google.com/tpu.present": "true"}
    (ctr,) = pod["containers"]
    cmd = ctr["command"]
    assert "k3stpu.plugin_config" in cmd
    assert "/usr/local/bin/tpu-device-plugin" in cmd
    mounts = {m["name"]: m for m in ctr["volumeMounts"]}
    assert mounts["device-plugins"]["mountPath"] == "/var/lib/kubelet/device-plugins"
    assert mounts["host-sys"]["readOnly"] and mounts["host-dev"]["readOnly"]
    vols = {v["name"]: v for v in ds["spec"]["template"]["spec"]["volumes"]}
    assert vols["config"]["configMap"]["name"] == "k3s-tpu-config"


def test_tfd_disable_and_rbac():
    # tfd.enabled mirrors gfd.enabled (reference values.yaml:1-2).
    objs = render(overrides={"tfd.enabled": "false"})
    assert ("DaemonSet", "k3s-tpu-feature-discovery") not in objs
    assert all(k != "ClusterRole" for k, _ in objs)

    objs = render()
    tfd = objs[("DaemonSet", "k3s-tpu-feature-discovery")]
    pod = tfd["spec"]["template"]["spec"]
    assert pod["serviceAccountName"] == "k3s-tpu-feature-discovery"
    role = objs[("ClusterRole", "k3s-tpu-feature-discovery")]
    (rule,) = role["rules"]
    assert set(rule["verbs"]) == {"get", "patch"}
    assert rule["resources"] == ["nodes"]
    (ctr,) = pod["containers"]
    env = {e["name"] for e in ctr["env"]}
    assert "NODE_NAME" in env


def test_replicas_override():
    objs = render(overrides={
        "config.sharing.timeSlicing.resources": '[{"name": "google.com/tpu", "replicas": 2}]',
    })
    cm = objs[("ConfigMap", "k3s-tpu-config")]
    assert parse_config(cm["data"]["config.yaml"])["replicas"] == 2


def test_bad_configs_fail_loudly():
    with pytest.raises(ValueError, match="version"):
        parse_config("version: v2\n")
    with pytest.raises(ValueError, match="replicas"):
        parse_config(
            "version: v1\nsharing:\n  timeSlicing:\n    resources:\n"
            "      - name: google.com/tpu\n        replicas: 0\n")
    with pytest.raises(ValueError, match="renameByDefault"):
        parse_config(
            "version: v1\nsharing:\n  timeSlicing:\n    renameByDefault: true\n"
            "    resources:\n      - name: google.com/tpu\n        replicas: 2\n")
