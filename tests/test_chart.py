"""Helm chart: render with helm_lite and verify the control-plane objects.

Parity targets: the nvdp chart + NFD install the reference drives at
README.md:97-126, with the values schema of reference values.yaml:1-18.
"""

import os

import pytest
import yaml

from k3stpu.plugin_config import argv_for, parse_config
from k3stpu.utils.helm_lite import render_chart

CHART = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "deploy", "charts", "k3s-tpu",
)


def render(overrides=None, namespace="tpu-system"):
    text = render_chart(CHART, namespace=namespace, overrides=overrides)
    docs = [d for d in yaml.safe_load_all(text) if d]
    return {(d["kind"], d["metadata"]["name"]): d for d in docs}


def test_default_render_objects():
    objs = render()
    kinds = {k for k, _ in objs}
    assert kinds == {"RuntimeClass", "ConfigMap", "DaemonSet",
                     "ServiceAccount", "ClusterRole", "ClusterRoleBinding"}
    assert ("DaemonSet", "k3s-tpu-device-plugin") in objs
    assert ("DaemonSet", "k3s-tpu-feature-discovery") in objs


def test_inference_disabled_by_default():
    # The chart installs infrastructure; the serving workload is opt-in,
    # and the default golden renderings must stay byte-stable.
    objs = render()
    assert ("Deployment", "tpu-inference") not in objs
    assert ("Service", "tpu-inference") not in objs


def test_inference_enabled_carries_scrape_annotations():
    objs = render({"inference.enabled": "true"}, namespace="serve-ns")
    dep = objs[("Deployment", "tpu-inference")]
    assert dep["metadata"]["namespace"] == "serve-ns"
    ann = dep["spec"]["template"]["metadata"]["annotations"]
    assert ann["prometheus.io/scrape"] == "true"
    assert ann["prometheus.io/path"] == "/metrics"
    svc = objs[("Service", "tpu-inference")]
    (port,) = svc["spec"]["ports"]
    # The scrape port must agree with the Service port, values-driven.
    assert ann["prometheus.io/port"] == str(port["port"]) == "8096"
    pod = dep["spec"]["template"]["spec"]
    assert pod["runtimeClassName"] == "tpu"
    (ctr,) = pod["containers"]
    assert ctr["resources"]["limits"]["google.com/tpu"] == "1"
    assert ctr["readinessProbe"]["httpGet"]["port"] == port["port"]


def test_inference_probes_and_drain_wiring():
    # Containment wiring (docs/RESILIENCE.md): readiness -> /healthz (the
    # breaker/drain hook), liveness -> /livez (breaker-blind), and the
    # SIGTERM grace period strictly above the server's drain deadline so
    # the kubelet never SIGKILLs mid-drain.
    objs = render({"inference.enabled": "true"})
    pod = objs[("Deployment", "tpu-inference")]["spec"]["template"]["spec"]
    (ctr,) = pod["containers"]
    assert ctr["readinessProbe"]["httpGet"]["path"] == "/healthz"
    assert ctr["livenessProbe"]["httpGet"]["path"] == "/livez"
    assert ctr["livenessProbe"]["httpGet"]["port"] == 8096
    cmd = ctr["command"]
    drain_s = float(cmd[cmd.index("--drain-deadline-s") + 1])
    assert pod["terminationGracePeriodSeconds"] > drain_s


def test_inference_speculate_flags_travel_together():
    # docs/SPECULATIVE.md: --speculate is only valid on the paged
    # continuous-batching engine, so the chart emits the three flags as
    # one unit (the server validates the dependency at boot) — and none
    # of them leak into the default render.
    base = render({"inference.enabled": "true"})
    cmd = base[("Deployment", "tpu-inference")]["spec"]["template"][
        "spec"]["containers"][0]["command"]
    for flag in ("--speculate", "--continuous-batching", "--kv-page-size"):
        assert flag not in cmd
    objs = render({"inference.enabled": "true",
                   "inference.speculate": "true"})
    cmd = objs[("Deployment", "tpu-inference")]["spec"]["template"][
        "spec"]["containers"][0]["command"]
    assert "--speculate" in cmd and "--continuous-batching" in cmd
    assert cmd[cmd.index("--kv-page-size") + 1] == "64"


def test_qos_disabled_by_default():
    # QoS is opt-in like every serving feature: no --qos flags (and no
    # engine flags they would drag in) leak into a plain inference
    # render, and the per-class burn-rate alerts stay out of the rules
    # ConfigMap — default renders stay byte-stable.
    objs = render({"inference.enabled": "true", "rules.enabled": "true"})
    cmd = objs[("Deployment", "tpu-inference")]["spec"]["template"][
        "spec"]["containers"][0]["command"]
    for flag in ("--qos", "--qos-classes", "--interactive-ttft-slo-ms",
                 "--continuous-batching"):
        assert flag not in cmd
    alerts = yaml.safe_load(objs[("ConfigMap", "k3s-tpu-rules")][
        "data"]["k3s-tpu-alerts.rules.yaml"])
    names = {r["alert"] for g in alerts["groups"] for r in g["rules"]}
    assert "K3sTpuInteractiveTtftBudgetFastBurn" not in names
    assert "K3sTpuBatchTtftBudgetSlowBurn" not in names


def test_qos_enabled_wiring():
    # docs/QOS.md: inference.qos.* renders the server's QoS unit — the
    # class flags plus the paged-engine flags QoS requires (the server
    # validates --qos needs --continuous-batching at boot) — and the
    # same switch grows the per-class burn-rate alert pair, with the
    # interactive SLO value reaching the page alert's description.
    objs = render({"inference.enabled": "true",
                   "inference.qos.enabled": "true",
                   "inference.qos.interactiveTtftSloMs": "1800",
                   "rules.enabled": "true"})
    cmd = objs[("Deployment", "tpu-inference")]["spec"]["template"][
        "spec"]["containers"][0]["command"]
    assert "--qos" in cmd and "--continuous-batching" in cmd
    assert cmd[cmd.index("--qos-classes") + 1] == "interactive,batch"
    assert cmd[cmd.index("--interactive-ttft-slo-ms") + 1] == "1800"
    assert cmd[cmd.index("--kv-page-size") + 1] == "64"
    alerts = yaml.safe_load(objs[("ConfigMap", "k3s-tpu-rules")][
        "data"]["k3s-tpu-alerts.rules.yaml"])
    rules = {r["alert"]: r for g in alerts["groups"] for r in g["rules"]}
    fast = rules["K3sTpuInteractiveTtftBudgetFastBurn"]
    assert 'slo="ttft-interactive",window="5m"} > 14.4' in fast["expr"]
    assert 'window="1h"' in fast["expr"]
    assert fast["labels"]["severity"] == "page"
    assert "1800" in fast["annotations"]["description"]
    slow = rules["K3sTpuBatchTtftBudgetSlowBurn"]
    assert 'slo="ttft-batch",window="6h"} > 1' in slow["expr"]
    assert slow["labels"]["severity"] == "ticket"


def test_router_disabled_by_default():
    # Same opt-in rule as the workloads: the scale-out tier is explicit,
    # and the default golden rendering stays byte-stable.
    objs = render()
    assert ("Deployment", "tpu-router") not in objs
    assert ("Service", "tpu-router") not in objs


def test_router_enabled_wiring():
    objs = render({"router.enabled": "true"}, namespace="route-ns")
    dep = objs[("Deployment", "tpu-router")]
    assert dep["metadata"]["namespace"] == "route-ns"
    ann = dep["spec"]["template"]["metadata"]["annotations"]
    assert ann["prometheus.io/scrape"] == "true"
    assert ann["prometheus.io/path"] == "/metrics"
    svc = objs[("Service", "tpu-router")]
    (port,) = svc["spec"]["ports"]
    assert ann["prometheus.io/port"] == str(port["port"]) == "8095"
    pod = dep["spec"]["template"]["spec"]
    (ctr,) = pod["containers"]
    cmd = ctr["command"]
    # Replica discovery defaults to the inference Service's in-namespace
    # DNS name on the inference port — the values the two components
    # must agree on.
    assert cmd[cmd.index("--replicas") + 1] == "http://tpu-inference:8096"
    assert cmd[cmd.index("--policy") + 1] == "affinity"
    # Probe split mirrors the server: readiness can-route (/healthz),
    # liveness process-up (/livez) — a sick FLEET must not restart the
    # router.
    assert ctr["readinessProbe"]["httpGet"]["path"] == "/healthz"
    assert ctr["livenessProbe"]["httpGet"]["path"] == "/livez"
    assert ctr["readinessProbe"]["httpGet"]["port"] == port["port"]
    # SIGTERM pairing, same invariant as inference.
    drain_s = float(cmd[cmd.index("--drain-deadline-s") + 1])
    assert pod["terminationGracePeriodSeconds"] > drain_s
    # Stateless and deviceless: no TPU resource, no runtimeClass, and
    # rolling updates allowed (no Recreate pin).
    assert "resources" not in ctr
    assert "runtimeClassName" not in pod
    assert dep["spec"].get("strategy") is None


def test_disagg_disabled_by_default():
    # Disaggregated serving is opt-in like every workload, and neither
    # the role Deployments nor the router's --prefill-replicas flag may
    # leak into default renders (byte-stable goldens).
    objs = render()
    for name in ("tpu-prefill", "tpu-decode"):
        assert ("Deployment", name) not in objs
        assert ("Service", name) not in objs
    objs = render({"router.enabled": "true"})
    cmd = objs[("Deployment", "tpu-router")]["spec"]["template"][
        "spec"]["containers"][0]["command"]
    assert "--prefill-replicas" not in cmd


def test_disagg_enabled_wiring():
    # docs/DISAGG.md: two role-flagged Deployments, each carrying the
    # paged-engine unit the KV handoff stages through, the decode side
    # pointed at the prefill Service for headerless requests, and the
    # router handing out per-request prefill peers.
    objs = render({"inference.disagg.enabled": "true",
                   "inference.disagg.prefillReplicas": "2",
                   "inference.disagg.decodeReplicas": "3",
                   "router.enabled": "true",
                   "router.replicaUrls": "http://tpu-decode:8096"})
    for name, role, replicas in (("tpu-prefill", "prefill", 2),
                                 ("tpu-decode", "decode", 3)):
        dep = objs[("Deployment", name)]
        assert dep["spec"]["replicas"] == replicas
        pod = dep["spec"]["template"]["spec"]
        (ctr,) = pod["containers"]
        cmd = ctr["command"]
        assert cmd[cmd.index("--role") + 1] == role
        # The handoff's engine-level requirements travel as one unit.
        assert "--continuous-batching" in cmd
        assert cmd[cmd.index("--kv-page-size") + 1] == "64"
        assert int(cmd[cmd.index("--prompt-cache") + 1]) > 0
        # Device-holding replicas: Recreate pin + TPU limit, like the
        # monolithic inference Deployment.
        assert dep["spec"]["strategy"]["type"] == "Recreate"
        assert ctr["resources"]["limits"]["google.com/tpu"] == "1"
        assert ctr["readinessProbe"]["httpGet"]["path"] == "/healthz"
        svc = objs[("Service", name)]
        (port,) = svc["spec"]["ports"]
        assert port["port"] == 8096
    dec_cmd = objs[("Deployment", "tpu-decode")]["spec"]["template"][
        "spec"]["containers"][0]["command"]
    assert dec_cmd[dec_cmd.index("--prefill-upstream") + 1] \
        == "http://tpu-prefill:8096"
    pre_cmd = objs[("Deployment", "tpu-prefill")]["spec"]["template"][
        "spec"]["containers"][0]["command"]
    assert "--prefill-upstream" not in pre_cmd
    router_cmd = objs[("Deployment", "tpu-router")]["spec"]["template"][
        "spec"]["containers"][0]["command"]
    assert router_cmd[router_cmd.index("--prefill-replicas") + 1] \
        == "http://tpu-prefill:8096"
    assert router_cmd[router_cmd.index("--replicas") + 1] \
        == "http://tpu-decode:8096"


def test_disagg_missing_values_fail_loudly():
    # A half-specified disagg block must be a render-time error, not a
    # Deployment with an empty replicas field.
    with pytest.raises(ValueError, match="undefined reference"):
        render({"inference.disagg.enabled": "true",
                "inference.disagg.prefillReplicas": "null"})


def test_train_disabled_by_default():
    # Same opt-in rule as inference: the chart installs infrastructure,
    # workloads are explicit, and the default golden stays byte-stable.
    objs = render()
    assert ("Job", "tpu-train") not in objs
    assert ("Service", "tpu-train") not in objs
    assert ("PersistentVolumeClaim", "tpu-train-ckpt") not in objs


def test_train_enabled_scrape_and_preemption_wiring():
    objs = render({"train.enabled": "true"}, namespace="train-ns")
    job = objs[("Job", "tpu-train")]
    assert job["metadata"]["namespace"] == "train-ns"
    spec = job["spec"]
    assert spec["completionMode"] == "Indexed"
    assert spec["completions"] == spec["parallelism"] == 2
    ann = spec["template"]["metadata"]["annotations"]
    assert ann["prometheus.io/scrape"] == "true"
    assert ann["prometheus.io/path"] == "/metrics"
    pod = spec["template"]["spec"]
    assert pod["runtimeClassName"] == "tpu"
    (ctr,) = pod["containers"]
    cmd = ctr["command"]
    # The scrape annotation must agree with the port train_job actually
    # serves on, values-driven, and stay off the coordinator port.
    assert ann["prometheus.io/port"] == cmd[cmd.index("--metrics-port") + 1] == "8477"
    env = {e["name"]: e.get("value") for e in ctr["env"]}
    assert env["K3STPU_COORDINATOR_PORT"] == "8476" != ann["prometheus.io/port"]
    # Preemption budget ordering, same invariant as the raw manifest.
    grace = pod["terminationGracePeriodSeconds"]
    assert grace >= float(env["K3STPU_PREEMPT_SAVE_BOUND_S"]) + 15
    # Headless coordinator Service + RWX checkpoint PVC come along.
    svc = objs[("Service", "tpu-train")]
    assert svc["spec"]["clusterIP"] == "None"           # headless
    (port,) = svc["spec"]["ports"]
    assert str(port["port"]) == env["K3STPU_COORDINATOR_PORT"]
    pvc = objs[("PersistentVolumeClaim", "tpu-train-ckpt")]
    assert pvc["spec"]["accessModes"] == ["ReadWriteMany"]
    mounts = {m["name"]: m["mountPath"] for m in ctr["volumeMounts"]}
    assert mounts["k3stpu-metrics"] == "/run/k3stpu"


def test_node_exporter_disabled_by_default():
    # Default render must stay byte-stable: no exporter DaemonSet, no
    # rules ConfigMap, and the tfd labeler runs WITHOUT --health.
    objs = render()
    assert ("DaemonSet", "k3s-tpu-node-exporter") not in objs
    assert ("ConfigMap", "k3s-tpu-rules") not in objs
    tfd = objs[("DaemonSet", "k3s-tpu-feature-discovery")]
    (ctr,) = tfd["spec"]["template"]["spec"]["containers"]
    assert "--health" not in ctr["command"]


def test_node_exporter_enabled_wiring():
    objs = render({"nodeExporter.enabled": "true"}, namespace="fleet-ns")
    ds = objs[("DaemonSet", "k3s-tpu-node-exporter")]
    assert ds["metadata"]["namespace"] == "fleet-ns"
    tmpl = ds["spec"]["template"]
    ann = tmpl["metadata"]["annotations"]
    assert ann["prometheus.io/scrape"] == "true"
    assert ann["prometheus.io/path"] == "/metrics"
    pod = tmpl["spec"]
    # Exporter only lands where discovery found chips.
    assert pod["nodeSelector"] == {"google.com/tpu.present": "true"}
    (ctr,) = pod["containers"]
    cmd = ctr["command"]
    # Scrape annotation, containerPort, hostPort (tpu_top's sweep
    # surface) and the --port flag must all agree, values-driven.
    (port,) = ctr["ports"]
    assert (ann["prometheus.io/port"] == cmd[cmd.index("--port") + 1]
            == str(port["containerPort"]) == str(port["hostPort"])
            == "8478")
    # Drop dir rw (the exporter GCs), host sysfs/dev ro under /host.
    mounts = {m["name"]: m for m in ctr["volumeMounts"]}
    assert mounts["k3stpu-metrics"]["mountPath"] == "/run/k3stpu"
    assert not mounts["k3stpu-metrics"].get("readOnly")
    assert mounts["host-sys"]["readOnly"] and mounts["host-dev"]["readOnly"]
    assert cmd[cmd.index("--host-root") + 1] == "/host"
    vols = {v["name"]: v for v in pod["volumes"]}
    assert vols["k3stpu-metrics"]["hostPath"]["type"] == "DirectoryOrCreate"
    # And the tfd labeler switches on health labeling with a READ-ONLY
    # view of the same drop dir, thresholds shared with the exporter.
    tfd = objs[("DaemonSet", "k3s-tpu-feature-discovery")]
    (tctr,) = tfd["spec"]["template"]["spec"]["containers"]
    tcmd = tctr["command"]
    assert "--health" in tcmd
    assert tcmd[tcmd.index("--drop-dir") + 1] == "/host/run/k3stpu"
    assert (tcmd[tcmd.index("--stale-after-s") + 1]
            == cmd[cmd.index("--stale-after-s") + 1] == "120")
    tmounts = {m["name"]: m for m in tctr["volumeMounts"]}
    assert tmounts["k3stpu-metrics"]["readOnly"]


def test_rules_configmap_thresholds_reach_exprs():
    objs = render({"rules.enabled": "true",
                   "rules.ttftP99SloSeconds": "1.5",
                   "rules.goodputFractionMin": "0.9"})
    cm = objs[("ConfigMap", "k3s-tpu-rules")]
    recording = yaml.safe_load(cm["data"]["k3s-tpu-slo.rules.yaml"])
    alerts = yaml.safe_load(cm["data"]["k3s-tpu-alerts.rules.yaml"])
    recorded = {r["record"] for g in recording["groups"]
                for r in g["rules"]}
    assert "k3stpu:request_ttft_seconds:p99" in recorded
    rules = [r for g in alerts["groups"] for r in g["rules"]]
    exprs = {r["alert"]: r["expr"] for r in rules}
    # The static TtftSloBreach threshold rule is gone, replaced by the
    # multi-window burn-rate pair over the canary pod's SLO engine.
    assert "K3sTpuTtftSloBreach" not in exprs
    fast = exprs["K3sTpuTtftBudgetFastBurn"]
    assert 'k3stpu_slo_burn_rate{slo="ttft",window="5m"} > 14.4' in fast
    assert 'window="1h"' in fast  # both windows must confirm
    slow = exprs["K3sTpuTtftBudgetSlowBurn"]
    assert 'window="6h"' in slow and 'window="3d"' in slow
    # The values-driven threshold still reaches the operator (via the
    # description — the expr consumes it through the canary's
    # --slo-ttft-threshold-s flag, not inline).
    descs = {r["alert"]: r["annotations"]["description"] for r in rules}
    assert "1.5" in descs["K3sTpuTtftBudgetFastBurn"]
    assert exprs["K3sTpuCanaryFailing"] == "k3stpu_canary_fleet_ok == 0"
    assert ("k3stpu_canary_mismatch_total"
            in exprs["K3sTpuCanaryTokenMismatch"])
    assert "< 0.9" in exprs["K3sTpuGoodputLow"]
    # Alerts on recorded series reference them by the recorded name.
    assert "k3stpu:node_tpu_health:max" in exprs["K3sTpuNodeUnhealthy"]


def test_canary_disabled_by_default():
    objs = render()
    assert ("Deployment", "tpu-canary") not in objs


def test_canary_deployment_wiring():
    objs = render({"canary.enabled": "true",
                   "rules.ttftP99SloSeconds": "1.5",
                   "canary.skipSessionProbe": "true"})
    dep = objs[("Deployment", "tpu-canary")]
    tmpl = dep["spec"]["template"]
    (ctr,) = tmpl["spec"]["containers"]
    cmd = ctr["command"]
    assert cmd[:3] == ["python", "-m", "k3stpu.canary"]
    assert cmd[cmd.index("--router") + 1] == "http://tpu-router:8095"
    # The SLO threshold single-sources from rules.ttftP99SloSeconds —
    # the burn-rate alerts and the engine computing them can't drift.
    assert cmd[cmd.index("--slo-ttft-threshold-s") + 1] == "1.5"
    # Probe toggles are skip-phrased (helm_lite `if` takes bare refs
    # only); session skipped here, stream probe stays on.
    assert "--no-probe-session" in cmd
    assert "--no-probe-stream" not in cmd
    # Scrape annotation, liveness and the --metrics-port flag agree.
    ann = tmpl["metadata"]["annotations"]
    assert (ann["prometheus.io/port"]
            == cmd[cmd.index("--metrics-port") + 1] == "8093")
    assert ctr["livenessProbe"]["httpGet"]["path"] == "/healthz"
    assert ctr["livenessProbe"]["httpGet"]["port"] == 8093
    # One replica, no RBAC: the canary is a pure HTTP client.
    assert dep["spec"]["replicas"] == 1
    assert "serviceAccountName" not in tmpl["spec"]


def test_runtimeclass_and_namespace():
    objs = render(namespace="custom-ns")
    rc = objs[("RuntimeClass", "tpu")]
    assert rc["handler"] == "tpu"
    cm = objs[("ConfigMap", "k3s-tpu-config")]
    assert cm["metadata"]["namespace"] == "custom-ns"


def test_config_roundtrip_to_plugin_flags():
    # The ConfigMap payload must parse back through plugin_config into the
    # flags the C++ binary takes — 4-way sharing by default (reference
    # values.yaml:18).
    objs = render()
    cm = objs[("ConfigMap", "k3s-tpu-config")]
    settings = parse_config(cm["data"]["config.yaml"])
    assert settings["resource"] == "google.com/tpu"
    assert settings["replicas"] == 4
    assert settings["fail_multi"] is False
    argv = argv_for(settings, "/usr/local/bin/tpu-device-plugin")
    assert argv == ["/usr/local/bin/tpu-device-plugin",
                    "--resource", "google.com/tpu", "--replicas", "4"]


def test_device_plugin_daemonset_wiring():
    objs = render()
    ds = objs[("DaemonSet", "k3s-tpu-device-plugin")]
    pod = ds["spec"]["template"]["spec"]
    # Label-gated like the reference's NFD-dependent plugin (README.md:99).
    assert pod["nodeSelector"] == {"google.com/tpu.present": "true"}
    (ctr,) = pod["containers"]
    cmd = ctr["command"]
    assert "k3stpu.plugin_config" in cmd
    assert "/usr/local/bin/tpu-device-plugin" in cmd
    mounts = {m["name"]: m for m in ctr["volumeMounts"]}
    assert mounts["device-plugins"]["mountPath"] == "/var/lib/kubelet/device-plugins"
    assert mounts["host-sys"]["readOnly"] and mounts["host-dev"]["readOnly"]
    vols = {v["name"]: v for v in ds["spec"]["template"]["spec"]["volumes"]}
    assert vols["config"]["configMap"]["name"] == "k3s-tpu-config"


def test_tfd_disable_and_rbac():
    # tfd.enabled mirrors gfd.enabled (reference values.yaml:1-2).
    objs = render(overrides={"tfd.enabled": "false"})
    assert ("DaemonSet", "k3s-tpu-feature-discovery") not in objs
    assert all(k != "ClusterRole" for k, _ in objs)

    objs = render()
    tfd = objs[("DaemonSet", "k3s-tpu-feature-discovery")]
    pod = tfd["spec"]["template"]["spec"]
    assert pod["serviceAccountName"] == "k3s-tpu-feature-discovery"
    role = objs[("ClusterRole", "k3s-tpu-feature-discovery")]
    (rule,) = role["rules"]
    assert set(rule["verbs"]) == {"get", "patch"}
    assert rule["resources"] == ["nodes"]
    (ctr,) = pod["containers"]
    env = {e["name"] for e in ctr["env"]}
    assert "NODE_NAME" in env


def test_replicas_override():
    objs = render(overrides={
        "config.sharing.timeSlicing.resources": '[{"name": "google.com/tpu", "replicas": 2}]',
    })
    cm = objs[("ConfigMap", "k3s-tpu-config")]
    assert parse_config(cm["data"]["config.yaml"])["replicas"] == 2


def test_bad_configs_fail_loudly():
    with pytest.raises(ValueError, match="version"):
        parse_config("version: v2\n")
    with pytest.raises(ValueError, match="replicas"):
        parse_config(
            "version: v1\nsharing:\n  timeSlicing:\n    resources:\n"
            "      - name: google.com/tpu\n        replicas: 0\n")
    with pytest.raises(ValueError, match="renameByDefault"):
        parse_config(
            "version: v1\nsharing:\n  timeSlicing:\n    renameByDefault: true\n"
            "    resources:\n      - name: google.com/tpu\n        replicas: 2\n")


# --- Golden renderings -----------------------------------------------------
#
# tests/golden/chart/*.yaml are full chart renderings checked in for review
# and diffed byte-for-byte here, so any template/values change shows up as a
# readable golden diff (the reference's whole method is reviewable rendered
# artifacts — its values.yaml IS a checked-in rendering input, reference
# README.md:101-116). Regenerate after an intentional chart change with:
#     REGEN_CHART_GOLDENS=1 python -m pytest tests/test_chart.py -q
# When a real `helm` binary is on PATH the same goldens are also checked
# against `helm template` output object-for-object, so helm_lite's template
# subset (k3stpu/utils/helm_lite.py:10-18) can never silently diverge from
# what an operator's actual helm install would apply.

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "golden", "chart")
CORE_8WAY_OVERRIDES = {
    "config.flags.granularity": "core",
    "config.sharing.timeSlicing.resources":
        "[{name: google.com/tpu, replicas: 8}]",
}


def _golden_case(name):
    return {
        "default.yaml": {},
        "core-8way.yaml": CORE_8WAY_OVERRIDES,
        # The opt-in serving workload, probes + drain wiring included —
        # inference is off in the default golden, so this is the only
        # reviewable rendering of the Deployment/Service pair.
        "inference.yaml": {"inference.enabled": "true"},
        # Likewise for the opt-in training workload: the only reviewable
        # rendering of the Service/PVC/Job triple with scrape annotations.
        "train.yaml": {"train.enabled": "true"},
        # Scale-out tier (docs/ROUTER.md): the router Deployment/Service
        # pair in front of the enabled inference fleet — rendered
        # together since the router's default replica discovery names
        # the inference Service.
        "router.yaml": {"router.enabled": "true",
                        "inference.enabled": "true"},
        # Fleet observability tier: node-exporter DaemonSet + SLO rules
        # ConfigMap + the tfd health-labeling wiring they switch on —
        # all off in the default golden, which stays byte-unchanged.
        "node-obs.yaml": {"nodeExporter.enabled": "true",
                          "rules.enabled": "true"},
        # Fleet autoscaler (docs/AUTOSCALING.md): SA + scale-subresource
        # Role/Binding + controller Deployment, rendered with the
        # router and inference components it scales and drains through.
        "autoscaler.yaml": {"autoscaler.enabled": "true",
                            "router.enabled": "true",
                            "inference.enabled": "true"},
        # Disaggregated prefill/decode serving (docs/DISAGG.md): the
        # two role-flagged Deployments behind the router, with the
        # router's replica pool pointed at the decode Service (decode
        # replicas take generate traffic; prefill peers are per-request
        # header hints).
        "disagg.yaml": {"inference.disagg.enabled": "true",
                        "router.enabled": "true",
                        "router.replicaUrls": "http://tpu-decode:8096"},
        # Correctness watchdog (docs/OBSERVABILITY.md "Correctness &
        # SLOs"): the canary Deployment probing the routed fleet, plus
        # the rules ConfigMap whose burn-rate/canary alerts consume
        # the families it exports.
        "canary.yaml": {"canary.enabled": "true",
                        "router.enabled": "true",
                        "inference.enabled": "true",
                        "rules.enabled": "true"},
        # SLO-aware QoS (docs/QOS.md): the inference Deployment with
        # priority classes + predictive admission + preemption on, and
        # the rules ConfigMap growing the per-class burn-rate alert
        # pair the same values switch on.
        "qos.yaml": {"inference.enabled": "true",
                     "inference.qos.enabled": "true",
                     "rules.enabled": "true"},
        # Embedded metrics pipeline (docs/OBSERVABILITY.md "Executing
        # the rules"): the collector Deployment/Service with the rules
        # ConfigMap mounted — the same rule files a real Prometheus
        # would load, executed by the in-cluster engine.
        "collector.yaml": {"collector.enabled": "true",
                           "router.enabled": "true",
                           "inference.enabled": "true",
                           "rules.enabled": "true"},
    }[name]


GOLDEN_NAMES = ["default.yaml", "core-8way.yaml", "inference.yaml",
                "train.yaml", "node-obs.yaml", "router.yaml",
                "autoscaler.yaml", "disagg.yaml", "canary.yaml",
                "qos.yaml", "collector.yaml"]


@pytest.mark.parametrize("name", GOLDEN_NAMES)
def test_golden_rendering(name):
    from k3stpu.utils.helm_lite import render_chart

    path = os.path.join(GOLDEN_DIR, name)
    text = render_chart(CHART, overrides=_golden_case(name))
    if os.environ.get("REGEN_CHART_GOLDENS"):
        with open(path, "w") as f:
            f.write(text)
    with open(path) as f:
        golden = f.read()
    assert golden.strip(), f"golden {name} is empty"
    assert text == golden, (
        f"chart rendering drifted from golden {name}; if intentional, "
        "rerun with REGEN_CHART_GOLDENS=1")


def test_core_8way_golden_semantics():
    # The golden must actually encode the core-granularity 8-way policy,
    # not just render: round-trip through the plugin-config parser.
    with open(os.path.join(GOLDEN_DIR, "core-8way.yaml")) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    cm = next(d for d in docs if d["kind"] == "ConfigMap")
    settings = parse_config(cm["data"]["config.yaml"])
    assert settings["replicas"] == 8
    assert settings["granularity"] == "core"


@pytest.mark.parametrize("name", GOLDEN_NAMES)
def test_golden_matches_real_helm(name):
    """Object-for-object equality between the golden and `helm template`.

    Skips without a helm binary (none in CI); on an operator box with helm
    this is the chart-fidelity check: helm_lite's subset renderer and real
    helm must produce the same Kubernetes objects from the same chart.
    """
    import shutil
    import subprocess

    helm = shutil.which("helm")
    if helm is None:
        pytest.skip("no helm binary on PATH")
    cmd = [helm, "template", "k3s-tpu", CHART, "--namespace", "tpu-system"]
    for dotted, v in _golden_case(name).items():
        # helm needs list-index syntax for the resources list; derive the
        # entries from the override value so new cases can't drift.
        if dotted.endswith(".resources"):
            for i, res in enumerate(yaml.safe_load(v)):
                for key, val in res.items():
                    cmd += ["--set", f"{dotted}[{i}].{key}={val}"]
        else:
            cmd += ["--set", f"{dotted}={v}"]
    out = subprocess.run(cmd, check=True, capture_output=True,
                         text=True).stdout

    def objects(text):
        return {(d["kind"], d["metadata"]["name"]): d
                for d in yaml.safe_load_all(text) if d}

    with open(os.path.join(GOLDEN_DIR, name)) as f:
        golden_objs = objects(f.read())
    helm_objs = objects(out)
    assert helm_objs == golden_objs


@pytest.mark.parametrize("snippet", [
    "{{- range .Values.items }}\nx: 1\n{{- end }}",
    "{{ include \"k3s-tpu.labels\" . }}",
    "{{- with .Values.nodeSelector }}\nnodeSelector: {{ . }}\n{{- end }}",
    "{{ define \"helper\" }}x{{ end }}",
    "{{ template \"helper\" }}",
    "{{ block \"b\" . }}{{ end }}",
    "{{- if .Values.missing }}\na: 1\n{{- else }}\nb: 2\n{{- end }}",
    "{{- if not .Values.a }}\nx: 1\n{{- end }}",
    "{{- if eq .Values.a .Values.b }}\nx: 1\n{{- end }}",
    "{{- if or .Values.a }}\nx: 1\n{{- end }}",
    "{{- if and .Values.a true }}\nx: 1\n{{- end }}",
    "{{- if or .Values.a (not .Values.b) }}\nx: 1\n{{- end }}",
    "x: {{ .Values.n | default 3 }}",
])
def test_renderer_rejects_constructs_outside_subset(snippet):
    """helm-lite must HARD-FAIL on any Go-template construct it does not
    implement — block keywords (range/with/include/template/define/
    block/else), if conditions beyond bare-.Ref or/and forms (not/eq/
    literal operands/nested calls), and unknown pipeline functions
    (default/printf/...) — instead of silently mis-rendering: a skipped
    {{ else }} would drop the else-body, an unparsed if condition would
    _lookup nothing and render the branch empty, and a skipped
    {{ range }}'s {{ end }} would corrupt the if-stack. The
    guard fires even when the construct sits inside a disabled
    {{ if }} branch: subset membership must not depend on which values
    are set today."""
    from k3stpu.utils.helm_lite import render_template
    with pytest.raises(ValueError, match="unsupported"):
        render_template(snippet, {"Values": {}})
    # Same construct nested in a branch the current values DISABLE:
    wrapped = "{{- if .Values.off }}\n" + snippet + "\n{{- end }}"
    with pytest.raises(ValueError, match="unsupported"):
        render_template(wrapped, {"Values": {"off": False}})


def test_renderer_rejects_inline_unsupported_constructs():
    from k3stpu.utils.helm_lite import render_template
    with pytest.raises(ValueError, match="unsupported template construct"):
        render_template("name: {{ include \"x\" . }}-suffix",
                        {"Values": {}})


@pytest.mark.parametrize("a,b,or_body,and_body", [
    (True, True, True, True),
    (True, False, True, False),
    (False, True, True, False),
    (False, False, False, False),
])
def test_renderer_flat_or_and_if(a, b, or_body, and_body):
    """The flat boolean if-forms the inference template uses for shared
    paged-engine flags: `if or .A .B` emits when either ref is truthy,
    `if and .A .B` only when both are. Missing refs count as falsy,
    matching the single-ref `if` semantics."""
    from k3stpu.utils.helm_lite import render_template
    tpl = ("{{- if or .Values.a .Values.b }}\nboth: or\n{{- end }}\n"
           "{{- if and .Values.a .Values.b }}\nboth: and\n{{- end }}\n"
           "tail: 1")
    out = render_template(tpl, {"Values": {"a": a, "b": b}})
    assert ("both: or" in out) == or_body
    assert ("both: and" in out) == and_body
    assert "tail: 1" in out
