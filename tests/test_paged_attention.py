"""Ragged paged-attention Pallas kernel (k3stpu/ops/paged_attention.py).

Two correctness bars. The KERNEL bar is parity with the XLA-gather
reference oracle: fp32 pools agree to float rounding (the online
softmax reorders reductions, so "bit-exact" is the wrong spec — the
assert is a tight allclose), int8/bf16 agree within the quantization
drift already accepted elsewhere. The ENGINE bar is the one the ISSUE
pins: greedy fp32 token streams through GenerateEngine must be
IDENTICAL between attn_backend="xla-gather" and "pallas-paged" — same
prompts, same pages, same tokens — across ragged batches, COW shared
prefixes, and page-boundary positions. CPU-JAX interpreter mode per
SURVEY.md §4.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.ops.paged_attention import (
    paged_attention,
    paged_attention_reference,
    paged_decode_bytes,
)

jax.config.update("jax_platform_name", "cpu")


def _inputs(batch, t, q_heads, kv_heads, head_dim, max_seq, ps, lengths,
            dtype=jnp.float32, int8=False, seed=0, shared_rows=None):
    """Random pools + identity block tables (page 0 reserved as sink).
    ``shared_rows=(a, b)`` makes row b's table alias row a's pages — the
    engine's COW zero-copy prefix-sharing layout."""
    rng = np.random.default_rng(seed)
    n_bt = max_seq // ps
    num_pages = 1 + batch * n_bt
    q = jnp.asarray(rng.standard_normal(
        (batch, t, q_heads, head_dim)), dtype)
    bt = 1 + np.arange(batch * n_bt, dtype=np.int32).reshape(batch, n_bt)
    if shared_rows is not None:
        a, b = shared_rows
        bt[b] = bt[a]
    kw = {}
    if int8:
        kp = jnp.asarray(rng.integers(
            -127, 128, (num_pages, ps, kv_heads, head_dim)), jnp.int8)
        vp = jnp.asarray(rng.integers(
            -127, 128, (num_pages, ps, kv_heads, head_dim)), jnp.int8)
        kw["k_scale_pages"] = jnp.asarray(rng.uniform(
            0.005, 0.03, (num_pages, ps, kv_heads)), jnp.float32)
        kw["v_scale_pages"] = jnp.asarray(rng.uniform(
            0.005, 0.03, (num_pages, ps, kv_heads)), jnp.float32)
    else:
        kp = jnp.asarray(rng.standard_normal(
            (num_pages, ps, kv_heads, head_dim)), dtype)
        vp = jnp.asarray(rng.standard_normal(
            (num_pages, ps, kv_heads, head_dim)), dtype)
    lens = jnp.asarray(np.asarray(lengths, np.int32))
    return q, kp, vp, jnp.asarray(bt), lens, kw


def _agree(q, kp, vp, bt, lens, kw, atol):
    got = paged_attention(q, kp, vp, bt, lens, interpret=True, **kw)
    want = paged_attention_reference(q, kp, vp, bt, lens, **kw)
    assert got.shape == want.shape and got.dtype == want.dtype
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                - want.astype(jnp.float32))))
    assert err < atol, f"kernel vs reference drift {err} >= {atol}"


def test_fp32_ragged_batches():
    for lengths in ([1, 5, 8, 32], [3, 3, 3, 3], [32, 1, 17, 9]):
        q, kp, vp, bt, lens, kw = _inputs(
            4, 1, 4, 4, 32, 32, 8, lengths, seed=1)
        _agree(q, kp, vp, bt, lens, kw, 1e-5)


def test_fp32_page_boundaries():
    # Every length within +-1 of a page edge, plus the exact edges and
    # the full chain — the off-by-one surface of the in-kernel walk.
    ps = 8
    q, kp, vp, bt, lens, kw = _inputs(
        6, 1, 4, 4, 32, 32, ps, [ps - 1, ps, ps + 1, 2 * ps, 31, 32],
        seed=2)
    _agree(q, kp, vp, bt, lens, kw, 1e-5)


def test_fp32_grouped_query_heads():
    q, kp, vp, bt, lens, kw = _inputs(
        3, 1, 8, 2, 32, 32, 8, [5, 16, 29], seed=3)
    _agree(q, kp, vp, bt, lens, kw, 1e-5)


def test_fp32_multi_token_query_width():
    # T=5 is the speculative verify width (gamma+1); each query token j
    # must see exactly lengths - T + j + 1 keys.
    q, kp, vp, bt, lens, kw = _inputs(
        3, 5, 4, 4, 32, 64, 8, [7, 30, 64], seed=4)
    _agree(q, kp, vp, bt, lens, kw, 1e-5)


def test_fp32_cow_shared_prefix_pages():
    # Rows 0 and 2 alias the SAME physical pages (the prompt cache's
    # zero-copy sharing); identical q rows must produce identical
    # outputs, and both must match the reference.
    q, kp, vp, bt, lens, kw = _inputs(
        3, 1, 4, 4, 32, 32, 8, [17, 9, 17], seed=5, shared_rows=(0, 2))
    q = q.at[2].set(q[0])
    _agree(q, kp, vp, bt, lens, kw, 1e-5)
    out = paged_attention(q, kp, vp, bt, lens, interpret=True, **kw)
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(out[2]))


def test_int8_pages_bounded_drift():
    q, kp, vp, bt, lens, kw = _inputs(
        3, 2, 4, 4, 32, 32, 8, [5, 20, 32], int8=True, seed=6)
    _agree(q, kp, vp, bt, lens, kw, 1e-4)


def test_sharded_head_slice_walk_parity():
    """Tensor-parallel pool walk (engine tp_shards=N): each shard's
    kernel sees only ITS heads' slice of the page pool (axis 2) and its
    matching query-head group, but the same block tables and lengths.
    Running the kernel on a head-slice must equal the reference on the
    same slice — per-head independence is what makes the head-axis
    shard legal, so this is the sharded walk's parity oracle. GQA
    shape: 8 query heads over 4 kv heads, split 2 ways."""
    q, kp, vp, bt, lens, kw = _inputs(
        3, 1, 8, 4, 32, 32, 8, [5, 17, 31], seed=11)
    full = paged_attention_reference(q, kp, vp, bt, lens, **kw)
    group = 8 // 4  # query heads per kv head
    for shard, (k0, k1) in enumerate(((0, 2), (2, 4))):
        q_s = q[:, :, k0 * group:k1 * group]
        kp_s, vp_s = kp[:, :, k0:k1], vp[:, :, k0:k1]
        _agree(q_s, kp_s, vp_s, bt, lens, kw, 1e-5)
        # And the slice IS the full result's head range — nothing
        # about the walk couples heads across the shard boundary.
        got = paged_attention(q_s, kp_s, vp_s, bt, lens, interpret=True,
                              **kw)
        err = float(jnp.max(jnp.abs(
            got - full[:, :, k0 * group:k1 * group])))
        assert err < 1e-5, f"shard {shard} diverged from full walk: {err}"


def test_sharded_head_slice_walk_parity_int8():
    """Same oracle over an int8 pool: the scale planes slice on the
    same head axis, so a shard dequantizes exactly its own heads."""
    q, kp, vp, bt, lens, kw = _inputs(
        2, 1, 4, 4, 32, 32, 8, [9, 26], int8=True, seed=12)
    for k0, k1 in ((0, 2), (2, 4)):
        kw_s = {"k_scale_pages": kw["k_scale_pages"][:, :, k0:k1],
                "v_scale_pages": kw["v_scale_pages"][:, :, k0:k1]}
        _agree(q[:, :, k0:k1], kp[:, :, k0:k1], vp[:, :, k0:k1],
               bt, lens, kw_s, 1e-4)


def test_bf16_pools_bounded_drift():
    # bf16 pools: the kernel accumulates fp32 where the gather path
    # rounds probs through bf16, so drift is bounded, not bit-tight.
    q, kp, vp, bt, lens, kw = _inputs(
        3, 1, 4, 4, 32, 32, 8, [5, 20, 32], dtype=jnp.bfloat16, seed=7)
    _agree(q, kp, vp, bt, lens, kw, 5e-2)


def test_kernel_rejects_bad_shapes():
    q, kp, vp, bt, lens, kw = _inputs(3, 1, 4, 4, 32, 32, 8, [5, 9, 2])
    with pytest.raises(ValueError, match="multiple of kv heads"):
        paged_attention(q[:, :, :3], kp, vp, bt, lens, interpret=True)
    with pytest.raises(ValueError, match="scale"):
        paged_attention(q, kp.astype(jnp.int8), vp.astype(jnp.int8),
                        bt, lens, interpret=True)


def test_decode_bytes_model():
    bb = paged_decode_bytes(4, [8, 64, 128, 200], 256, 8, 64, 16)
    # The gather pays full width regardless of fill; the walk pays live
    # pages only — the ratio is the whole point of the kernel.
    assert bb["bytes_ratio"] > 1.0
    assert bb["live_tokens"] < bb["full_tokens"]
    full = paged_decode_bytes(4, [256] * 4, 256, 8, 64, 16)
    assert full["bytes_ratio"] == pytest.approx(2.0)  # 4 passes vs 2


# --- engine-level token identity (the ISSUE's acceptance bar) -----------


@pytest.fixture(scope="module")
def fp32_mp():
    from k3stpu.models.transformer import transformer_lm_tiny

    model = transformer_lm_tiny(max_seq_len=64, dtype=jnp.float32)
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, 8), jnp.int32), train=False)
    return model, variables["params"]


def _engine_tokens(model, params, backend, cases, **kw):
    from k3stpu.serve.engine import GenerateEngine

    eng = GenerateEngine(model, params, seed=0, slots=4, page_size=8,
                         attn_backend=backend, **kw)
    try:
        outs = [eng.submit(p, max_new_tokens=8) for p in cases]
        assert eng.stats()["attn_backend"] == backend
        return outs
    finally:
        eng.close()


def test_engine_greedy_token_identity(fp32_mp):
    model, params = fp32_mp
    cases = [
        [[5, 6, 7]],
        [[3, 4], [9, 10, 11, 12, 13]],                # ragged batch
        [list(range(1, 20)), [40], [7, 8, 9]],        # 3 ragged rows
        [[7, 8, 9, 10, 11, 12, 13, 14]],              # page-aligned prompt
    ]
    want = _engine_tokens(model, params, "xla-gather", cases)
    got = _engine_tokens(model, params, "pallas-paged", cases)
    assert got == want


def test_engine_token_identity_shared_prefix(fp32_mp):
    # The prompt cache's zero-copy COW page sharing under the kernel:
    # a repeat prompt and an extending prompt both pin the ancestor's
    # pages read-only into the new row's table.
    model, params = fp32_mp
    prefix = list(range(3, 14))
    cases = [[prefix], [prefix], [prefix + [50, 51]]]
    want = _engine_tokens(model, params, "xla-gather", cases,
                          prompt_cache=4)
    got = _engine_tokens(model, params, "pallas-paged", cases,
                        prompt_cache=4)
    assert got == want


def test_engine_token_identity_speculative(fp32_mp):
    # Speculative decoding's batch-wide verify extend runs the kernel at
    # query width gamma+1 — the T>1 ragged path through the engine.
    model, params = fp32_mp
    prompt = [3, 4, 5, 3, 4, 5, 3, 4]      # repetitive: drafter engages
    cases = [[prompt], [[9, 2, 9, 2, 9, 2]]]
    want = _engine_tokens(model, params, "xla-gather", cases,
                          speculate=True, spec_gamma=3)
    got = _engine_tokens(model, params, "pallas-paged", cases,
                         speculate=True, spec_gamma=3)
    assert got == want


def test_engine_validation_and_exposure():
    from k3stpu.serve.engine import GenerateEngine
    from k3stpu.models.transformer import transformer_lm_tiny

    model = transformer_lm_tiny(max_seq_len=64)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    with pytest.raises(ValueError, match="requires page_size"):
        GenerateEngine(model, params, attn_backend="pallas-paged")
    with pytest.raises(ValueError, match="not in"):
        GenerateEngine(model, params, page_size=8,
                       attn_backend="flash-paged")


def test_obs_backend_label_and_mfu_gauge():
    from k3stpu.obs import ServeObs

    obs = ServeObs(attn_backend="pallas-paged")
    obs.on_decode_dispatch(0.004, mfu=0.31)
    text = obs.render_prometheus()
    assert ('k3stpu_serve_decode_dispatch_seconds_bucket'
            '{le="0.005",backend="pallas-paged"}') in text
    assert 'k3stpu_serve_decode_dispatch_seconds_count'\
           '{backend="pallas-paged"} 1' in text
    assert "k3stpu_serve_decode_mfu 0.31" in text
    # None MFU (CPU stand-in) leaves the gauge where it was.
    obs.on_decode_dispatch(0.004, mfu=None)
    assert "k3stpu_serve_decode_mfu 0.31" in obs.render_prometheus()
    obs.reset()
    assert "k3stpu_serve_decode_mfu 0" in obs.render_prometheus()
