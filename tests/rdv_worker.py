"""Worker subprocess for the two-process rendezvous integration test.

Launched twice by tests/test_distributed.py with fake Indexed-Job env
(HOSTNAME=<job>-<i>, JOB_COMPLETION_INDEX=<i>, localhost coordinator) — the
exact environment deploy/manifests/tpu-pjit-job.yaml gives its pods. Joins
the JAX process group via k3stpu.parallel.distributed.initialize, forms the
GLOBAL mesh, runs a psum over it, and prints one JSON result line.
"""

import json
import os
import sys

# 2 local devices per process. Set the XLA_FLAGS lever BEFORE jax loads:
# on jax builds predating jax_num_cpu_devices it is the only one.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # older jax: the XLA_FLAGS fallback above already forces 2

import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from k3stpu.parallel.distributed import initialize, rendezvous_from_env  # noqa: E402


def main() -> int:
    rdv = rendezvous_from_env()
    initialize(rdv)

    import jax.numpy as jnp
    try:
        from jax import shard_map
    except ImportError:
        # Older jax spells it jax.experimental.shard_map; the pre-vma
        # replication check stays off — this program is vma-typed.
        from jax.experimental.shard_map import shard_map as _esm

        def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
            return _esm(f, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=check_vma)

    devices = jax.devices()  # GLOBAL list after initialize
    mesh = Mesh(np.array(devices), ("d",))
    n = len(devices)

    # Hybrid (data, model) mesh: the 'model' axis must stay within one
    # process's local devices (ICI), 'data' spans processes (DCN).
    from k3stpu.parallel.mesh import make_hybrid_mesh

    hybrid = make_hybrid_mesh(model_parallelism=2)
    hybrid_ok = (dict(hybrid.shape) == {"data": n // 2, "model": 2}
                 and all(len({d.process_index for d in row}) == 1
                         for row in hybrid.devices))

    # Global (n,) array, device i holding value i + 1; psum must see every
    # process's shard — the number cannot come out right from one process.
    sharding = NamedSharding(mesh, P("d"))
    x = jax.make_array_from_callback(
        (n,), sharding, lambda idx: np.arange(1, n + 1, dtype=np.float32)[idx])

    allreduce = jax.jit(shard_map(
        lambda v: jax.lax.psum(v, "d"), mesh=mesh,
        in_specs=P("d"), out_specs=P()))
    total = float(np.asarray(
        jax.device_get(allreduce(x).addressable_data(0)))[0])

    print(json.dumps({
        "process_id": rdv.process_id,
        "num_processes": rdv.num_processes,
        "coordinator": rdv.coordinator_address,
        "jax_process_count": jax.process_count(),
        "global_devices": n,
        "local_devices": len(jax.local_devices()),
        "psum_total": total,
        "expected_total": float(n * (n + 1) / 2),
        "hybrid_mesh_ok": bool(hybrid_ok),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
