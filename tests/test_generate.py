"""KV-cache generation vs the naive full-forward loop (exactness oracle)."""

import jax
import jax.numpy as jnp
import numpy as np

from k3stpu.models.generate import generate, init_cache
from k3stpu.models.transformer import transformer_lm_tiny


def _model_and_params(seed=0, max_seq_len=64):
    model = transformer_lm_tiny(max_seq_len=max_seq_len)
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.key(seed), tokens)["params"]
    return model, params


def _naive_greedy(model, params, prompt, n_new):
    """Re-run the full forward for every generated token — the oracle."""
    toks = prompt
    out = []
    for _ in range(n_new):
        logits = model.apply({"params": params}, toks)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_greedy_matches_naive_loop():
    model, params = _model_and_params()
    prompt = jax.random.randint(jax.random.key(3), (2, 12), 0,
                                model.config.vocab_size)
    lens = jnp.full((2,), 12, jnp.int32)
    fast = generate(model, params, prompt, lens, 8)
    slow = _naive_greedy(model, params, prompt, 8)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


def test_ragged_prompt_first_token():
    """A right-padded shorter row must sample its first token from its own
    last real position, identical to running it unpadded."""
    model, params = _model_and_params(seed=1)
    short = jax.random.randint(jax.random.key(5), (1, 6), 0,
                               model.config.vocab_size)
    # Pad with the last real token (the serving convention).
    padded = jnp.concatenate(
        [short, jnp.broadcast_to(short[:, -1:], (1, 4))], axis=1)
    out_padded = generate(model, params, padded,
                          jnp.array([6], jnp.int32), 1)
    out_exact = generate(model, params, short,
                         jnp.array([6], jnp.int32), 1)
    np.testing.assert_array_equal(np.asarray(out_padded),
                                  np.asarray(out_exact))


def test_eos_latches():
    model, params = _model_and_params(seed=2)
    prompt = jax.random.randint(jax.random.key(7), (1, 4), 0,
                                model.config.vocab_size)
    lens = jnp.array([4], jnp.int32)
    # Find what greedy emits first, then declare THAT the eos token: every
    # later position must repeat it.
    first = int(generate(model, params, prompt, lens, 1)[0, 0])
    out = generate(model, params, prompt, lens, 6, eos_id=first)
    assert np.asarray(out).tolist() == [[first] * 6]


def test_sampling_is_reproducible_and_varied():
    model, params = _model_and_params(seed=4)
    prompt = jax.random.randint(jax.random.key(9), (1, 8), 0,
                                model.config.vocab_size)
    lens = jnp.array([8], jnp.int32)
    a = generate(model, params, prompt, lens, 16, rng=jax.random.key(0),
                 temperature=1.0, top_k=50)
    b = generate(model, params, prompt, lens, 16, rng=jax.random.key(0),
                 temperature=1.0, top_k=50)
    c = generate(model, params, prompt, lens, 16, rng=jax.random.key(1),
                 temperature=1.0, top_k=50)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    assert np.asarray(a).max() < model.config.vocab_size


def test_init_cache_shapes():
    model, _ = _model_and_params()
    cache = init_cache(model, batch=3)
    cfg = model.config
    key0 = cache["block0"]["attn"]["key"]
    assert key0.shape == (3, cfg.max_seq_len, cfg.n_heads,
                          cfg.d_model // cfg.n_heads)
    # Per-row write indices (ragged prompts / continuous batching).
    idx = cache["block0"]["attn"]["index"]
    assert idx.shape == (3,) and int(idx.sum()) == 0


def test_ragged_batch_matches_solo_generation():
    """Per-row cache indices make ragged batches EXACT: each row's greedy
    continuation equals generating that prompt alone (no pad K/V leaks
    into any visible window)."""
    model, params = _model_and_params()
    prompts = [[5, 6, 7], [9, 10, 11, 12, 13, 14, 15, 16]]
    width = 8
    block = np.zeros((2, width), np.int32)
    for i, p in enumerate(prompts):
        block[i, :len(p)] = p          # zero-padded — pads must not matter
    lens = jnp.array([len(p) for p in prompts], jnp.int32)

    batched = generate(model, params, jnp.asarray(block), lens, 6,
                       temperature=0.0)
    for i, p in enumerate(prompts):
        solo = generate(model, params,
                        jnp.asarray(np.array([p], np.int32)),
                        jnp.array([len(p)], jnp.int32), 6, temperature=0.0)
        assert jnp.array_equal(batched[i], solo[0]), (
            f"row {i}: ragged-batch continuation diverged from solo")


def test_top_p_mask_keeps_nucleus():
    from k3stpu.models.generate import top_p_mask

    logits = jnp.log(jnp.array([[0.5, 0.3, 0.15, 0.05]]))
    # p=0.6: top-1 has 0.5 < 0.6 so the second (0.3) is still needed.
    cut = top_p_mask(logits, 0.6)
    assert bool(jnp.isfinite(cut[0, 0])) and bool(cut[0, 1] > -1e29)
    assert bool(cut[0, 2] < -1e29) and bool(cut[0, 3] < -1e29)
    # p tiny: only the argmax survives.
    cut1 = top_p_mask(logits, 0.01)
    assert bool(cut1[0, 0] > -1e29)
    assert bool(jnp.all(cut1[0, 1:] < -1e29))
    # p=1.0 keeps everything.
    assert bool(jnp.all(top_p_mask(logits, 1.0) > -1e29))
    # Per-row p.
    two = jnp.concatenate([logits, logits])
    cut2 = top_p_mask(two, jnp.array([0.01, 1.0]))
    assert bool(jnp.all(cut2[1] > -1e29)) and bool(
        jnp.all(cut2[0, 1:] < -1e29))


def test_generate_top_p_valid_tokens():
    model, params = _model_and_params()
    prompts = jnp.array([[5, 6, 7, 8]], jnp.int32)
    out = generate(model, params, prompts, jnp.array([4], jnp.int32), 8,
                   rng=jax.random.key(1), temperature=1.0, top_p=0.9)
    assert out.shape == (1, 8)
    assert bool(jnp.all((out >= 0) & (out < model.config.vocab_size)))
