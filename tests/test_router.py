"""Router tier (k3stpu/router, docs/ROUTER.md): routing determinism,
session affinity, health-driven membership, failover, and the trace /
replica-identity invariants across the extra hop.

Replicas here are scriptable in-thread HTTP stand-ins, not model
servers — the router is deliberately model-blind, so these tests stay
jax-free and SMOKE-fast. The contract they script (healthz/livez,
X-K3STPU-Replica, SSE framing, 503 + Retry-After) is the one
server.py's handler actually speaks, asserted by its own suite.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from k3stpu.obs import parse_traceparent
from k3stpu.router import (
    REPLICA_HEADER,
    FleetUnavailable,
    HashRing,
    Router,
    RouterObs,
    make_router_app,
)

# --- scriptable replica ----------------------------------------------------


class _ReplicaState:
    def __init__(self, name):
        self.name = name
        self.healthy = True          # /healthz answer
        self.refuse = False          # raise pre-response (connection dies)
        self.answer_503 = False      # answer 503 + Retry-After
        self.die_mid_stream = False  # SSE: stop after the first frame
        self.lock = threading.Lock()
        self.requests = []           # (path, body, traceparent) per POST
        self.sessions_released = []

    def served(self):
        with self.lock:
            return len(self.requests)


def _make_replica(state: _ReplicaState):
    class H(BaseHTTPRequestHandler):
        def _send(self, code, doc, extra=None):
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.send_header(REPLICA_HEADER, state.name)
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                if state.healthy:
                    self._send(200, {"ok": True})
                else:
                    self._send(503, {"ok": False},
                               extra={"Retry-After": "1"})
            elif self.path == "/v1/models":
                self._send(200, {"model": "scripted"})
            else:
                self._send(404, {"error": self.path})

        def do_POST(self):
            raw = self.rfile.read(
                int(self.headers.get("Content-Length", "0")))
            body = json.loads(raw) if raw else {}
            with state.lock:
                state.requests.append(
                    (self.path, body, self.headers.get("traceparent")))
            if state.refuse:
                # Kill the connection before any response bytes: the
                # failover-safe shape.
                self.connection.close()
                return
            if state.answer_503:
                self._send(503, {"error": "overloaded"},
                           extra={"Retry-After": "1"})
                return
            if self.path == "/v1/session/release":
                with state.lock:
                    state.sessions_released.append(body.get("session"))
                self._send(200, {"released": True})
                return
            if body.get("stream"):
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header(REPLICA_HEADER, state.name)
                self.end_headers()
                self.wfile.write(b"data: " + json.dumps(
                    {"tokens": [[1]], "done": False}).encode() + b"\n\n")
                self.wfile.flush()
                if state.die_mid_stream:
                    # RST, not FIN: a crashing process aborts its
                    # sockets — a clean close would read as normal EOF
                    # on an EOF-delimited stream.
                    import socket
                    import struct
                    self.connection.setsockopt(
                        socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
                    self.connection.close()
                    return
                self.wfile.write(b"data: " + json.dumps(
                    {"tokens": [[1, 2]], "done": True,
                     "served_by": state.name}).encode() + b"\n\n")
                self.wfile.flush()
                return
            self._send(200, {"ok": True, "served_by": state.name,
                             "echo_traceparent":
                                 self.headers.get("traceparent")})

        def log_message(self, *args):
            pass

    return H


@pytest.fixture
def fleet():
    """Two scripted replicas plus a router in front, all in-thread.
    Yields (router_url, router, [state_a, state_b], poke)."""
    states, httpds, urls = [], [], []
    for name in ("rep-a", "rep-b"):
        st = _ReplicaState(name)
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), _make_replica(st))
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        states.append(st)
        httpds.append(httpd)
        urls.append(f"http://127.0.0.1:{httpd.server_address[1]}")
    router = Router(urls, health_period_s=0.1, health_timeout_s=1.0,
                    proxy_timeout_s=10.0, instance="test-router")
    rhttpd = ThreadingHTTPServer(("127.0.0.1", 0), make_router_app(router))
    threading.Thread(target=rhttpd.serve_forever, daemon=True).start()
    try:
        yield (f"http://127.0.0.1:{rhttpd.server_address[1]}", router,
               states, urls)
    finally:
        router.close()
        rhttpd.shutdown()
        for h in httpds:
            h.shutdown()


def _post(url, path, doc, headers=None, timeout=30):
    req = urllib.request.Request(
        url + path, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    return urllib.request.urlopen(req, timeout=timeout)


def _until(cond, timeout=10.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return False


# --- ring determinism ------------------------------------------------------


def test_ring_lookup_is_process_stable():
    # Two independently built rings over the same members agree on every
    # key — the property that lets N router pods converge on one map
    # (sha256 positions, never the process-seeded builtin hash).
    a, b = HashRing(), HashRing()
    for node in ("r1", "r2", "r3"):
        a.add(node)
        b.add(node)
    for i in range(500):
        key = f"key-{i}"
        assert a.lookup(key) == b.lookup(key)


def test_ring_bounded_movement_on_remove_and_add():
    ring = HashRing()
    nodes = ["r1", "r2", "r3", "r4"]
    for n in nodes:
        ring.add(n)
    keys = [f"key-{i}" for i in range(2000)]
    before = {k: ring.lookup(k) for k in keys}
    # Every node owns a meaningful share (vnodes smooth the spread).
    share = {n: sum(1 for v in before.values() if v == n) for n in nodes}
    assert min(share.values()) > len(keys) / len(nodes) / 2, share

    ring.remove("r2")
    after = {k: ring.lookup(k) for k in keys}
    # The Karger property: ONLY keys that lived on the removed node move.
    moved = [k for k in keys if before[k] != after[k]]
    assert all(before[k] == "r2" for k in moved)
    assert all(after[k] != "r2" for k in keys)

    # Readmission restores the exact original map — eject/readmit round
    # trips are lossless, so a flapping replica can't permanently scramble
    # prefix affinity.
    ring.add("r2")
    assert {k: ring.lookup(k) for k in keys} == before


def test_ring_failover_walk_starts_at_owner_and_covers_all():
    ring = HashRing()
    for n in ("r1", "r2", "r3"):
        ring.add(n)
    for i in range(50):
        walk = list(ring.iter_nodes(f"key-{i}"))
        assert walk[0] == ring.lookup(f"key-{i}")
        assert sorted(walk) == ["r1", "r2", "r3"]  # distinct, complete


def test_prefix_key_uses_token_head_and_raw_fallback():
    body = {"prompt_tokens": [[7] * 40], "max_new_tokens": 4}
    k1 = Router.prefix_key(body, b"", prefix_tokens=16)
    # Same head, different tail -> same key (the shared-system-prompt
    # span sticks); different head -> different key.
    body2 = {"prompt_tokens": [[7] * 16 + [9] * 24]}
    assert Router.prefix_key(body2, b"", 16) == k1
    body3 = {"prompt_tokens": [[8] * 40]}
    assert Router.prefix_key(body3, b"", 16) != k1
    # Opaque bodies still route deterministically by raw head.
    assert (Router.prefix_key(None, b"blob-head", 16)
            == Router.prefix_key(None, b"blob-head", 16))


# --- routing policy + pins (Router unit level) -----------------------------


def test_session_pin_set_on_commit_and_survives_eject_readmit():
    router = Router(["http://a", "http://b"])
    raw = json.dumps({"session": "s1",
                      "prompt_tokens": [[1, 2, 3]]}).encode()
    body = json.loads(raw)
    cands, reason, session = router.route(body, raw)
    assert session == "s1" and reason == "prefix"  # first turn: placed
    router.commit_route(session, cands[0])
    pinned = router.pinned_replica("s1")
    assert pinned == cands[0]

    # Pinned turn: pinned replica leads, reason says so.
    cands2, reason2, _ = router.route(body, raw)
    assert cands2[0] == pinned and reason2 == "session"

    # Eject the pinned replica: the turn rebalances, but the PIN is kept
    # (no traffic landed elsewhere — the chain still lives there).
    router.eject(pinned, "test")
    cands3, reason3, _ = router.route(body, raw)
    assert reason3 == "rebalance" and pinned not in cands3
    assert router.pinned_replica("s1") == pinned

    # Readmit with no traffic in between: stickiness fully restored.
    router.readmit(pinned)
    cands4, reason4, _ = router.route(body, raw)
    assert cands4[0] == pinned and reason4 == "session"

    # A turn actually SERVED elsewhere moves the pin (freshest chain).
    router.eject(pinned, "test")
    cands5, _, _ = router.route(body, raw)
    router.commit_route("s1", cands5[0])
    assert router.pinned_replica("s1") == cands5[0] != pinned


def test_route_raises_when_no_replica_is_healthy():
    router = Router(["http://a", "http://b"])
    router.eject("http://a", "t")
    router.eject("http://b", "t")
    with pytest.raises(FleetUnavailable):
        router.route({"prompt_tokens": [[1]]}, b"{}")


def test_random_policy_round_robins_and_sessionless_affinity_sticks():
    router = Router(["http://a", "http://b"], policy="random")
    firsts = {router.route(None, b"same-body")[0][0] for _ in range(4)}
    assert firsts == {"http://a", "http://b"}  # spread, no affinity
    sticky = Router(["http://a", "http://b"])
    firsts = {sticky.route(None, b"same-body")[0][0] for _ in range(4)}
    assert len(firsts) == 1  # prefix affinity: same body, same replica


def test_inflight_admission_bounds():
    router = Router(["http://a"], max_inflight=2)
    assert router.acquire("http://a")
    assert router.acquire("http://a")
    assert not router.acquire("http://a")  # at cap
    router.release("http://a")
    assert router.acquire("http://a")


# --- HTTP end-to-end -------------------------------------------------------


def test_traceparent_passthrough_router_to_replica_to_response(fleet):
    url, _router, states, _urls = fleet
    inbound = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    with _post(url, "/v1/generate", {"prompt_tokens": [[1, 2, 3]]},
               headers={"traceparent": inbound}) as r:
        doc = json.loads(r.read())
        echoed = r.headers.get("traceparent")
    # The replica received the CLIENT's traceparent verbatim (the router
    # forwards, never re-mints an existing trace)...
    assert doc["echo_traceparent"] == inbound
    # ...and the router's response echo carries the same trace id with a
    # router span.
    tid, _sid = parse_traceparent(echoed)
    assert tid == "ab" * 16
    # Replica identity passes through.
    assert r.headers.get(REPLICA_HEADER) in {"rep-a", "rep-b"}


def test_router_mints_trace_when_absent_and_echoes_own_503(fleet):
    url, router, states, _urls = fleet
    with _post(url, "/v1/generate", {"prompt_tokens": [[5, 5]]}) as r:
        doc = json.loads(r.read())
    upstream_tp = doc["echo_traceparent"]
    assert parse_traceparent(upstream_tp) is not None  # minted, valid
    # The router's own 503 (whole fleet down) still echoes a trace id
    # and speaks the retryable shape.
    router.eject(_urls[0], "t")
    router.eject(_urls[1], "t")
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(url, "/v1/generate", {"prompt_tokens": [[5]]})
    assert ei.value.code == 503
    assert ei.value.headers.get("Retry-After")
    assert parse_traceparent(ei.value.headers.get("traceparent"))


def test_sticky_session_over_http_and_release_drops_pin(fleet):
    url, router, states, urls = fleet
    body = {"prompt_tokens": [[3, 1, 4, 1, 5]], "session": "chat-1"}
    served = []
    for _ in range(4):
        with _post(url, "/v1/generate", body) as r:
            served.append(json.loads(r.read())["served_by"])
    assert len(set(served)) == 1  # every turn on the pinned replica
    pinned_url = router.pinned_replica("chat-1")
    assert pinned_url is not None

    with _post(url, "/v1/session/release", {"session": "chat-1"}) as r:
        assert json.loads(r.read())["released"] is True
    assert router.pinned_replica("chat-1") is None
    # The release reached exactly the replica that held the chain.
    pinned_state = states[urls.index(pinned_url)]
    assert pinned_state.sessions_released == ["chat-1"]


def test_failover_on_dead_replica_and_readmit_after_recovery(fleet):
    """The chaos acceptance shape: replica dies under load -> router
    ejects it and fails over in-flight work; the fleet keeps serving;
    the replica is readmitted once /healthz recovers."""
    url, router, states, urls = fleet
    body = {"prompt_tokens": [[2, 7, 1, 8]], "session": "s-fo"}
    with _post(url, "/v1/generate", body) as r:
        first = json.loads(r.read())["served_by"]
    victim = states[0] if first == "rep-a" else states[1]
    victim_url = urls[states.index(victim)]

    # Kill it: connections die pre-response AND /healthz goes dark.
    victim.refuse = True
    victim.healthy = False
    with _post(url, "/v1/generate", body) as r:
        doc = json.loads(r.read())
    assert doc["served_by"] != victim.name  # failed over, same request
    # The failover target now holds the freshest chain: pin moved.
    assert router.pinned_replica("s-fo") != victim_url
    assert not any(rep["healthy"] for rep in router.state()["replicas"]
                   if rep["url"] == victim_url)
    # Fleet keeps serving while degraded.
    with _post(url, "/v1/generate", body) as r:
        assert json.loads(r.read())["served_by"] != victim.name

    # Recovery: health poller readmits without operator action.
    victim.refuse = False
    victim.healthy = True
    router.start_health_poller()
    try:
        assert _until(lambda: all(
            rep["healthy"] for rep in router.state()["replicas"]))
    finally:
        router.stop_health_poller()


def test_chaos_route_proxy_injects_failover(fleet):
    url, router, states, _urls = fleet
    from k3stpu.chaos import FaultInjector

    chaos = FaultInjector()
    chaos.arm("route_proxy", times=1)
    router._chaos = chaos
    with _post(url, "/v1/generate", {"prompt_tokens": [[9, 9]]}) as r:
        doc = json.loads(r.read())
    assert chaos.fired("route_proxy") == 1
    # The injected first-attempt death failed over to a live replica;
    # the first candidate was ejected on the way.
    assert doc["ok"]
    assert sum(1 for rep in router.state()["replicas"]
               if not rep["healthy"]) == 1


def test_sse_stream_relays_through_router(fleet):
    url, _router, _states, _urls = fleet
    frames = []
    with _post(url, "/v1/generate",
               {"prompt_tokens": [[6, 6]], "stream": True}) as r:
        assert "text/event-stream" in r.headers.get("Content-Type")
        assert r.headers.get(REPLICA_HEADER) in {"rep-a", "rep-b"}
        for line in r:
            if line.startswith(b"data: "):
                frames.append(json.loads(line[6:]))
    assert frames[-1]["done"] is True
    assert frames[-1]["served_by"] == r.headers.get(REPLICA_HEADER)


def test_sse_mid_stream_death_becomes_error_frame(fleet):
    url, router, states, _urls = fleet
    for st in states:
        st.die_mid_stream = True
    frames = []
    with _post(url, "/v1/generate",
               {"prompt_tokens": [[4, 2]], "stream": True}) as r:
        for line in r:
            if line.startswith(b"data: "):
                frames.append(json.loads(line[6:]))
    # Headers were sent before the death, so no failover: the client
    # gets the frames that made it plus a terminal error frame (which
    # loadgen's stream consumer already treats as a failed request).
    assert any("error" in f for f in frames)
    assert not frames[-1].get("done")


def test_upstream_503_fails_over_before_shedding(fleet):
    url, router, states, _urls = fleet
    states[0].answer_503 = True
    states[1].answer_503 = True
    # Both replicas shed -> the router forwards the last 503 with
    # Retry-After (the client's backoff discipline still works).
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(url, "/v1/generate", {"prompt_tokens": [[1, 1]]})
    assert ei.value.code == 503
    assert ei.value.headers.get("Retry-After")
    # One replica recovering is enough: the 503 from the first attempt
    # fails over to the healthy one and the client sees a 200.
    states[1].answer_503 = False
    with _post(url, "/v1/generate", {"prompt_tokens": [[1, 1]]}) as r:
        assert json.loads(r.read())["ok"]
    # Both replicas were tried while both shed (failover, not instant
    # give-up).
    assert states[0].served() >= 1 and states[1].served() >= 1


def test_saturated_fleet_sheds_503_with_retry_after(fleet):
    url, router, _states, _urls = fleet
    router.max_inflight = 0  # every acquire refuses: fully saturated
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(url, "/v1/generate", {"prompt_tokens": [[1]]})
    assert ei.value.code == 503
    assert ei.value.headers.get("Retry-After")
    body = json.loads(ei.value.read())
    assert "in-flight" in body["error"]


def test_healthz_metrics_and_debug_surfaces(fleet):
    url, router, _states, urls = fleet
    with urllib.request.urlopen(url + "/healthz", timeout=10) as r:
        assert json.loads(r.read())["replicas_healthy"] == 2
    with urllib.request.urlopen(url + "/livez", timeout=10) as r:
        assert json.loads(r.read())["ok"]
    _post(url, "/v1/generate", {"prompt_tokens": [[1, 2]]}).read()
    with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
        text = r.read().decode()
    assert "k3stpu_router_requests_total" in text
    assert 'k3stpu_build_info{component="router"' in text
    assert 'instance="test-router"' in text
    with urllib.request.urlopen(url + "/debug/router", timeout=10) as r:
        state = json.loads(r.read())
    assert {rep["url"] for rep in state["replicas"]} == set(urls)
    # GET /v1/* fans in to a replica (loadgen's model-card fetch).
    with urllib.request.urlopen(url + "/v1/models", timeout=10) as r:
        assert json.loads(r.read())["model"] == "scripted"
    # Fleet-down readiness: /healthz 503s (Service pulls the router),
    # /livez stays 200 (no restart for a sick FLEET).
    for u in urls:
        router.eject(u, "t")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(url + "/healthz", timeout=10)
    assert ei.value.code == 503
    with urllib.request.urlopen(url + "/livez", timeout=10) as r:
        assert r.status == 200


def test_router_obs_families_render_clean():
    obs = RouterObs(instance="unit")
    obs.on_route("session")
    obs.on_proxy("http://a", 0.002)
    obs.on_failover("http://a")
    obs.on_eject("http://a")
    obs.on_reject()
    obs.on_membership(2)
    obs.on_pins(3)
    text = obs.render_prometheus()
    for family in ("k3stpu_router_requests_total",
                   "k3stpu_router_failovers_total",
                   "k3stpu_router_ejections_total",
                   "k3stpu_router_routing_decisions_total",
                   "k3stpu_router_rejected_total",
                   "k3stpu_router_proxy_overhead_seconds",
                   "k3stpu_router_replicas_healthy",
                   "k3stpu_router_sessions_pinned"):
        assert family in text, family
    assert 'reason="session"' in text
    om = obs.render_openmetrics()
    assert om.endswith("# EOF\n")
