"""Training observability (ISSUE 5): the TrainObs funnel, goodput
accounting, the /metrics + /debug/trace surfaces, and telemetry duty
cycle.

Unit tests drive the goodput accountant with a fake clock (the bucket
invariants must hold exactly, not within timing slop) and the emit()
funnel in-process; the integration test drives a REAL train_job
subprocess with --metrics-port, scrapes it mid-run, preempts it with
SIGTERM, and checks the terminal goodput line's buckets sum to its
wall-clock within 2% — the PR's acceptance criterion, verbatim.
"""

import getpass
import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from k3stpu.obs.hist import parse_prometheus_histograms
from k3stpu.obs.train import (
    GOODPUT_BUCKETS,
    GoodputAccountant,
    TrainObs,
    start_metrics_server,
    start_telemetry_thread,
)

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def tick(self, s):
        self.t += s


# --- goodput accountant ---------------------------------------------------


def test_goodput_starts_in_init_and_buckets_are_exclusive():
    clk = FakeClock()
    acc = GoodputAccountant(clock=clk)
    assert acc.bucket == "init"
    clk.tick(2.0)
    acc.enter("rendezvous")
    clk.tick(3.0)
    acc.enter("productive")
    clk.tick(5.0)
    totals = acc.totals()
    # Every second lands in exactly one bucket; untouched buckets are 0.
    assert totals["init"] == pytest.approx(2.0)
    assert totals["rendezvous"] == pytest.approx(3.0)
    assert totals["productive"] == pytest.approx(5.0)
    for b in set(GOODPUT_BUCKETS) - {"init", "rendezvous", "productive"}:
        assert totals[b] == 0.0
    assert sum(totals.values()) == pytest.approx(acc.elapsed())


def test_goodput_sum_equals_elapsed_at_every_read():
    clk = FakeClock()
    acc = GoodputAccountant(clock=clk)
    for i, b in enumerate(GOODPUT_BUCKETS):
        acc.enter(b)
        clk.tick(0.1 * (i + 1))
        # Mid-bucket reads charge the open bucket up to now.
        assert sum(acc.totals().values()) == pytest.approx(acc.elapsed())


def test_goodput_enter_returns_previous_bucket():
    clk = FakeClock()
    acc = GoodputAccountant(clock=clk)
    assert acc.enter("productive") == "init"
    assert acc.enter("checkpoint") == "productive"
    assert acc.enter("productive") == "checkpoint"


def test_goodput_rejects_unknown_bucket():
    with pytest.raises(ValueError, match="unknown goodput bucket"):
        GoodputAccountant(clock=FakeClock()).enter("coffee")


def test_goodput_fraction():
    clk = FakeClock()
    acc = GoodputAccountant(clock=clk)
    clk.tick(1.0)          # init
    acc.enter("productive")
    clk.tick(3.0)
    assert acc.fraction() == pytest.approx(0.75)
    assert acc.fraction("init") == pytest.approx(0.25)


def test_phase_nesting_restores_outer_bucket():
    clk = FakeClock()
    obs = TrainObs(clock=clk)
    obs.goodput.enter("productive")
    clk.tick(1.0)
    with obs.phase("preempted-drain"):
        clk.tick(2.0)
        with obs.phase("checkpoint", hist=obs.ckpt_save):
            clk.tick(4.0)
        clk.tick(0.5)
    totals = obs.goodput.totals()
    assert obs.goodput.bucket == "productive"
    assert totals["productive"] == pytest.approx(1.0)
    assert totals["preempted-drain"] == pytest.approx(2.5)
    assert totals["checkpoint"] == pytest.approx(4.0)
    assert obs.ckpt_save.count == 1


# --- the emit funnel ------------------------------------------------------


def test_emit_prints_exact_json_line_and_updates_metrics(capsys):
    obs = TrainObs()
    obs.emit("step", step=3, loss=1.25, step_s=0.5, tokens_per_s=100.0,
             tflops_per_chip=0.1, mfu=None)
    line = capsys.readouterr().out.strip()
    # The stdout contract: the line IS the dict, event first, fields in
    # call order — byte-identical to the pre-funnel print sites.
    assert line == ('{"event": "step", "step": 3, "loss": 1.25, '
                    '"step_s": 0.5, "tokens_per_s": 100.0, '
                    '"tflops_per_chip": 0.1, "mfu": null}')
    assert obs.steps.value == 1
    assert obs.step_s.count == 1


def test_emit_event_metric_dispatch():
    obs = TrainObs()
    obs.emit("rdv_ok", attempt=2, elapsed_s=0.25)
    obs.emit("rdv_retry", attempt=1, elapsed_s=0.1, error="x", backoff_s=1)
    obs.emit("ckpt_quarantined", step=4, reason="bad", quarantined_to="q")
    obs.emit("ckpt_gc", deleted=[2, 4, 6], keep_last=1)
    obs.emit("preempted", step=9, signal="SIGTERM", emergency_ckpt=True)
    assert obs.rdv_attempt.count == 2          # ok + retry both observed
    assert obs.rdv_retries.value == 1
    assert obs.quarantines.value == 1
    assert obs.gc_deleted.value == 3
    assert obs.preemptions.value == 1


def test_emit_disabled_still_prints_but_records_nothing(capsys):
    obs = TrainObs(enabled=False)
    obs.emit("step", step=1, step_s=0.5)
    obs.emit("preempted", step=1)
    assert len(capsys.readouterr().out.strip().splitlines()) == 2
    assert obs.steps.value == 0
    assert obs.preemptions.value == 0
    with obs.phase("eval"):   # no-op scope, no bucket switch
        pass
    assert obs.goodput.bucket == "init"


def test_probe_recompiles_counts_cache_growth():
    obs = TrainObs()
    obs.probe_recompiles(1)   # first compile IS a miss
    obs.probe_recompiles(1)
    obs.probe_recompiles(1)
    assert obs.recompiles.value == 1
    obs.probe_recompiles(3)   # two more misses (e.g. shape drift)
    assert obs.recompiles.value == 3
    obs.probe_recompiles(None)  # probe unavailable: no-op
    assert obs.recompiles.value == 3


# --- exposition + quantile round-trip -------------------------------------


def test_render_prometheus_parses_and_quantiles_round_trip():
    clk = FakeClock()
    obs = TrainObs(clock=clk)
    obs.goodput.enter("productive")
    clk.tick(8.0)
    obs.goodput.enter("checkpoint")
    clk.tick(2.0)
    for v in (0.01, 0.02, 0.03, 0.04):
        obs.step_s.observe(v)
    obs.steps.inc(4)
    text = obs.render_prometheus()
    # Goodput: one series per bucket, values matching the accountant.
    assert 'k3stpu_train_goodput_seconds_total{bucket="productive"} 8'\
        in text
    assert 'k3stpu_train_goodput_seconds_total{bucket="checkpoint"} 2'\
        in text
    assert "k3stpu_train_goodput_fraction 0.8" in text
    assert "k3stpu_train_steps_total 4" in text
    hists = parse_prometheus_histograms(text)
    st = hists["k3stpu_train_step_seconds"]
    assert st["count"] == 4
    assert st["sum"] == pytest.approx(0.1)
    # Quantile from the parsed exposition agrees with the live object.
    from k3stpu.obs.hist import quantile_from_buckets

    q_parsed = quantile_from_buckets(st["bounds"], st["cumulative"],
                                     st["count"], 0.5)
    assert q_parsed == pytest.approx(obs.step_s.quantile(0.5))


def test_exposition_lines_are_well_formed():
    import re

    obs = TrainObs()
    obs.step_s.observe(0.01)
    name_re = re.compile(r"^[a-z_:][a-z0-9_:]*(\{[^}]*\})?$")
    for line in obs.render_prometheus().splitlines():
        if not line or line.startswith("#"):
            continue
        key, _val = line.rsplit(None, 1)
        assert name_re.match(key), line
        float(_val)  # every sample value parses as a number


def test_chrome_trace_spans_by_kind():
    obs = TrainObs()
    with obs.span("step", step=1):
        pass
    with obs.phase("eval", kind="eval", step=1):
        pass
    with obs.span("step", step=2):
        pass
    trace = obs.chrome_trace()
    spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert [s["name"] for s in spans] == ["step", "eval", "step"]
    # One pseudo-thread per kind: both step spans share a tid, eval gets
    # its own.
    tids = {s["name"]: s["tid"] for s in spans}
    assert tids["step"] != tids["eval"]
    assert all(s["dur"] >= 0 for s in spans)


# --- HTTP surface ---------------------------------------------------------


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.headers.get("Content-Type"), r.read().decode()


def test_metrics_server_serves_metrics_and_trace():
    obs = TrainObs()
    obs.step_s.observe(0.02)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    httpd = start_metrics_server(obs, port, host="127.0.0.1")
    try:
        status, ctype, body = _get(port, "/metrics")
        assert status == 200 and ctype == "text/plain; version=0.0.4"
        assert "k3stpu_train_step_seconds_count 1" in body
        assert parse_prometheus_histograms(body)
        status, ctype, body = _get(port, "/debug/trace")
        assert status == 200 and ctype == "application/json"
        assert "traceEvents" in json.loads(body)
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(port, "/nope")
        assert e.value.code == 404
    finally:
        httpd.shutdown()


# --- telemetry duty cycle -------------------------------------------------


def test_write_metrics_clamps_duty_cycle(tmp_path):
    from k3stpu.utils.telemetry import write_metrics

    path = str(tmp_path / "m.json")
    for supplied, expected in ((150, 100), (37, 37), (0, 0), (-5, -1)):
        payload = write_metrics(path=path, duty_cycle_pct=supplied)
        assert all(d["duty_cycle_pct"] == expected
                   for d in payload["devices"])


def test_telemetry_thread_writes_busy_fraction(tmp_path):
    path = str(tmp_path / "drop.json")
    obs = TrainObs()
    obs._busy_s = 0.0
    tel = start_telemetry_thread(obs, interval=0.1, path=path)
    try:
        obs._busy_s += 0.05  # 50% busy over the 0.1s window
        deadline = time.monotonic() + 10.0
        while not os.path.exists(path):
            assert time.monotonic() < deadline, "drop file never appeared"
            time.sleep(0.02)
    finally:
        tel.stop_event.set()
        tel.join(timeout=5)
    data = json.loads(pathlib.Path(path).read_text())
    assert data["devices"]
    for d in data["devices"]:
        assert 0 <= d["duty_cycle_pct"] <= 100


# --- integration: live subprocess scrape + goodput acceptance -------------


def _train_env(**extra):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("K3STPU_CHAOS", None)
    try:
        user = getpass.getuser()
    except (KeyError, OSError):
        user = str(os.getuid())
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.environ.get(
        "K3STPU_TEST_CACHE", f"/tmp/k3stpu-test-compile-cache-{user}"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    env.update({k: str(v) for k, v in extra.items()})
    return env


def test_live_train_job_scrape_and_goodput_acceptance(tmp_path):
    """The acceptance criterion end to end: scrape a REAL train_job
    mid-run (exposition parses, goodput + step quantiles present),
    preempt it, and check the terminal goodput line's buckets are
    exclusive and sum to the job's elapsed wall-clock within 2%. Also
    checks the telemetry drop file carries a non-negative duty cycle."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    drop = tmp_path / "telemetry.json"
    env = _train_env(
        # Slow steps so the run is comfortably alive while we scrape.
        K3STPU_CHAOS="train_step:stall_s=0.2:times=1000",
        K3STPU_TELEMETRY_DROP=str(drop),
        K3STPU_TELEMETRY_INTERVAL_S="0.2",
    )
    cdir = tmp_path / "ckpt"
    proc = subprocess.Popen(
        [sys.executable, "-m", "k3stpu.parallel.train_job",
         "--model", "tiny", "--batch", "4", "--seq", "16",
         "--steps", "500", "--ckpt-dir", str(cdir), "--ckpt-every", "3",
         "--metrics-port", str(port)],
        env=env, cwd=REPO_ROOT, stdout=subprocess.PIPE, text=True)
    try:
        seen_steps = 0
        for line in proc.stdout:
            if not line.startswith("{"):
                continue
            if json.loads(line)["event"] == "step":
                seen_steps += 1
                if seen_steps >= 5:
                    break
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            body = r.read().decode()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/trace", timeout=10) as r:
            trace = json.load(r)
        proc.send_signal(signal.SIGTERM)
        rest = proc.stdout.read()
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    # Preemption behavior unchanged by the obs layer.
    assert rc == 42

    # Live scrape: parses, and carries the acceptance families.
    assert "k3stpu_train_goodput_seconds_total" in body
    hists = parse_prometheus_histograms(body)
    st = hists["k3stpu_train_step_seconds"]
    assert st["count"] >= 5
    from k3stpu.obs.hist import quantile_from_buckets

    p50 = quantile_from_buckets(st["bounds"], st["cumulative"],
                                st["count"], 0.5)
    assert p50 is not None and p50 > 0
    assert any(e.get("name") == "step"
               for e in trace["traceEvents"] if e.get("ph") == "X")

    # Terminal goodput line: every bucket present exactly once, sum
    # matches the job's own elapsed wall-clock within 2%.
    events = [json.loads(ln) for ln in rest.splitlines()
              if ln.startswith("{")]
    (goodput,) = [e for e in events if e["event"] == "goodput"]
    assert sorted(goodput["seconds"]) == sorted(GOODPUT_BUCKETS)
    total = sum(goodput["seconds"].values())
    assert total == pytest.approx(goodput["elapsed_s"],
                                  rel=0.02, abs=0.05)
    # A preempted run spent real time draining and checkpointing.
    assert (goodput["seconds"]["preempted-drain"] > 0
            or goodput["seconds"]["checkpoint"] > 0)
    assert goodput["seconds"]["productive"] > 0
    (pre,) = [e for e in events if e["event"] == "preempted"]
    assert pre["emergency_ckpt"] is True

    # Telemetry drop file: written, with a clamped non-negative duty.
    assert drop.exists(), "telemetry drop file never written"
    data = json.loads(drop.read_text())
    for d in data["devices"]:
        assert 0 <= d["duty_cycle_pct"] <= 100


# --- elastic resync accounting (ISSUE 8) ----------------------------------


def test_begin_resync_inside_phase_keeps_recovery_bucket(capsys):
    """The goodput fix: a membership change detected while a
    checkpoint/eval phase is open must close that bucket and charge the
    rest of the window to 'recovery' — the unwinding phase scope must
    NOT blindly re-enter its captured previous bucket (which would bill
    the whole resync to 'productive' and break sum==elapsed honesty)."""
    clk = FakeClock()
    obs = TrainObs(clock=clk)
    obs.goodput.enter("productive")
    clk.tick(5.0)
    with obs.phase("checkpoint"):
        clk.tick(2.0)
        obs.begin_resync()
        clk.tick(1.0)
    assert obs.goodput.bucket == "recovery"  # phase exit did not restore
    clk.tick(4.0)
    totals = obs.goodput.totals()
    assert totals["productive"] == 5.0
    assert totals["checkpoint"] == 2.0
    assert totals["recovery"] == 5.0
    assert sum(totals.values()) == pytest.approx(obs.goodput.elapsed())
    # The NEXT phase (fresh epoch) restores normally again.
    with obs.phase("eval"):
        clk.tick(1.0)
    assert obs.goodput.bucket == "recovery"
    obs.goodput.enter("productive")
    with obs.phase("checkpoint"):
        clk.tick(1.0)
    assert obs.goodput.bucket == "productive"
    capsys.readouterr()


def test_elastic_resync_event_updates_counters_and_world_gauge(capsys):
    obs = TrainObs()
    obs.emit("train_start", model="tiny", num_processes=4)
    assert obs.world_size.value == 4.0
    obs.emit("elastic_resync", generation=1, world_size=3, ranks=[0, 1, 3],
             lost=[2], resume_step=10, recovery_s=0.2)
    assert obs.elastic_resyncs.value == 1
    assert obs.elastic_lost.value == 1
    assert obs.world_size.value == 3.0
    text = obs.render_prometheus()
    assert "k3stpu_train_world_size 3" in text
    assert "k3stpu_train_elastic_resyncs_total 1" in text
    capsys.readouterr()
