"""Continuous-batching generation engine (k3stpu/serve/engine.py).

The correctness bar: a request interleaved with strangers in the slot
batch must produce EXACTLY the tokens it would get alone (per-row cache
indices make that well-defined); the scheduling bar: a request submitted
mid-decode of another must join without waiting for it to finish.
CPU-JAX stand-in per SURVEY.md §4.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.models.generate import generate
from k3stpu.models.transformer import transformer_lm_tiny
from k3stpu.serve.engine import GenerateEngine


def _model_and_params(max_seq_len=64):
    model = transformer_lm_tiny(max_seq_len=max_seq_len)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                           train=False)
    return model, variables["params"]


def _solo(model, params, prompt, budget):
    out = generate(model, params,
                   jnp.asarray(np.array([prompt], np.int32)),
                   jnp.array([len(prompt)], jnp.int32), budget,
                   temperature=0.0)
    return np.asarray(out)[0].tolist()


@pytest.fixture(scope="module")
def engine_setup():
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=4)
    yield model, params, engine
    engine.close()


def test_single_request_matches_generate(engine_setup):
    model, params, engine = engine_setup
    prompt = [5, 6, 7]
    got = engine.submit([prompt], max_new_tokens=6)
    assert got == [_solo(model, params, prompt, 6)]


def test_multi_prompt_request(engine_setup):
    model, params, engine = engine_setup
    prompts = [[3, 4], [9, 10, 11, 12, 13]]
    got = engine.submit(prompts, max_new_tokens=5)
    for g, p in zip(got, prompts):
        assert g == _solo(model, params, p, 5)


def test_concurrent_requests_interleave_and_match_solo(engine_setup):
    """The continuous-batching property: a second request joins while the
    first is mid-decode (strictly overlapping windows), and both emit
    exactly their solo-greedy tokens."""
    model, params, engine = engine_setup
    p1, p2 = [5, 6, 7, 8], [20, 21]
    # Warm every compiled program first so jit time can't skew the
    # interleaving-order assertions below.
    engine.submit([p1], max_new_tokens=2)
    engine.submit([p2], max_new_tokens=2)

    done_a = {}
    budget_a = 48

    def run_a():
        out = engine.submit([p1], max_new_tokens=budget_a)[0]
        done_a["tokens"], done_a["t"] = out, time.time()

    steps0 = engine.stats()["steps"]
    ta = threading.Thread(target=run_a)
    ta.start()
    # Wait until a is demonstrably mid-decode, then submit b from here.
    deadline = time.time() + 60
    while engine.stats()["steps"] < steps0 + 3:
        assert time.time() < deadline, "request a never started decoding"
        time.sleep(0.005)
    got_b = engine.submit([p2], max_new_tokens=4)[0]
    t_b_done = time.time()
    ta.join(120)

    assert done_a["tokens"] == _solo(model, params, p1, budget_a)
    assert got_b == _solo(model, params, p2, 4)
    # b was submitted while a decoded and returned before a finished ->
    # it joined a's in-flight batch rather than queueing behind it.
    assert t_b_done < done_a["t"], (
        "short request waited for the long one: no interleaving happened")
    st = engine.stats()
    assert st["tokens"] > 0 and st["steps"] > 0


def test_eos_stops_a_slot_early(engine_setup):
    model, params, engine = engine_setup
    prompt = [5, 6, 7]
    solo = _solo(model, params, prompt, 8)
    eos = solo[2]  # force an early stop at the 3rd generated token
    got = engine.submit([prompt], max_new_tokens=8, eos_id=eos)[0]
    assert got[:3] == solo[:3]
    assert all(t == eos for t in got[3:]), "eos must repeat once emitted"


def test_max_pending_sheds_load_and_recovers():
    """Bounded admission: with max_pending in-flight requests, the next
    submit raises EngineOverloaded immediately (no queueing, no
    timeout-wait); tokens release on every exit path, so the engine
    serves normally once load drains."""
    from k3stpu.serve.engine import EngineOverloaded

    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=2, max_pending=2)
    try:
        engine.submit([[1, 2]], max_new_tokens=2)  # warm
        real = engine._decode_step

        def slow_step(*args, **kwargs):
            time.sleep(0.02)
            return real(*args, **kwargs)

        engine._decode_step = slow_step
        started = threading.Barrier(3)
        results = {}

        def hold(i):
            started.wait()
            results[i] = engine.submit([[5 + i, 6]], max_new_tokens=30)

        holders = [threading.Thread(target=hold, args=(i,))
                   for i in range(2)]
        for t in holders:
            t.start()
        started.wait()
        time.sleep(0.2)  # both in flight (decoding slowly)
        t0 = time.time()
        with pytest.raises(EngineOverloaded):
            engine.submit([[9, 9]], max_new_tokens=2)
        assert time.time() - t0 < 1.0, "overload must reject, not queue"
        # A streaming attempt sheds too — and its token releases.
        it = engine.submit_stream([[9, 9]], max_new_tokens=2)
        with pytest.raises(EngineOverloaded):
            next(it)
        for t in holders:
            t.join(timeout=120)
        engine._decode_step = real
        # Both holders must have SUCCEEDED (a spurious rejection at the
        # bound would die silently in its thread otherwise).
        assert len(results) == 2 and all(len(r) == 1 for r in
                                         results.values())
        assert engine._inflight == 0
        got = engine.submit([[5, 6, 7]], max_new_tokens=4)
        assert got == [_solo(model, params, [5, 6, 7], 4)]
    finally:
        engine.close()


def test_engine_on_tensor_parallel_mesh_matches_single_device():
    """Continuous batching over a 2-device 'model' mesh: params sharded
    by parallel/sharding.py, the engine's KV cache head-sharded on the
    same mesh — greedy output, the prompt cache, and streaming must all
    match the single-device engine exactly (2 devices: see the TP
    numerics note in tests/test_multi_lora.py)."""
    from k3stpu.parallel.mesh import make_mesh
    from k3stpu.parallel.sharding import shard_params

    model, params = _model_and_params()
    mesh = make_mesh(2, model_parallelism=2)
    sharded, _ = shard_params(params, mesh)
    solo_eng = GenerateEngine(model, params, slots=4, decode_block=3,
                              prompt_cache=2)
    tp_eng = GenerateEngine(model, sharded, slots=4, decode_block=3,
                            prompt_cache=2, mesh=mesh)
    try:
        prompt = [5, 6, 7]
        want = solo_eng.submit([prompt], max_new_tokens=8)
        assert tp_eng.submit([prompt], max_new_tokens=8) == want
        # Prompt-cache hit on the sharded engine stays exact.
        assert tp_eng.submit([prompt], max_new_tokens=8) == want
        assert tp_eng.stats()["pcache_hits"] == 1
        # Streaming over the mesh: deltas concatenate to the final.
        rows: "dict[int, list[int]]" = {}
        final = None
        for ev in tp_eng.submit_stream([prompt], max_new_tokens=8):
            if ev["done"]:
                final = ev["tokens"]
            else:
                for r, toks in ev["rows"].items():
                    rows.setdefault(r, []).extend(toks)
        assert final == want and rows[0] == want[0]
    finally:
        solo_eng.close()
        tp_eng.close()


def test_early_finished_row_not_reused_until_request_completes():
    """A row that hits eos while its sibling row keeps decoding must NOT
    be handed to a queued request: its owner/collected state feeds the
    eventual _maybe_complete, and a stranger scattered into the slot
    would surface ITS tokens in the finished request's result (and crash
    the loop thread when whichever finishes second completes against
    clobbered bookkeeping — the soak caught exactly this)."""
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=2)
    try:
        pa, pb = [5, 6, 7], [9, 10, 11, 12, 13]
        solo_a = _solo(model, params, pa, 16)
        eos = solo_a[0]  # row A finishes on its very first token
        solo_b = np.asarray(generate(
            model, params, jnp.asarray(np.array([pb], np.int32)),
            jnp.array([len(pb)], jnp.int32), 16, temperature=0.0,
            eos_id=eos))[0].tolist()
        # Precondition for the scenario: row B must outlive row A by a
        # few steps (deterministic: fixed init seed).
        assert eos not in solo_b[:4], "pick prompts where B runs longer"

        results = {}

        def run_ab():
            results["ab"] = engine.submit([pa, pb], max_new_tokens=16,
                                          eos_id=eos)

        t = threading.Thread(target=run_ab)
        t.start()
        time.sleep(0.3)  # row A long finished; row B still decoding
        # Queued single-prompt request: with both slots owned by the
        # in-flight request it must WAIT, not steal A's finished slot.
        results["c"] = engine.submit([[20, 21]], max_new_tokens=4)
        t.join(timeout=120)
        assert results["ab"][0] == [eos] * 16
        assert results["ab"][1] == solo_b
        assert results["c"] == [_solo(model, params, [20, 21], 4)]
    finally:
        engine.close()


def test_more_requests_than_slots_queue(engine_setup):
    model, params, engine = engine_setup
    prompts = [[i + 1, i + 2] for i in range(6)]  # 6 requests, 4 slots
    results = [None] * 6

    def run(i):
        results[i] = engine.submit([prompts[i]], max_new_tokens=4)[0]

    threads = [threading.Thread(target=run, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(180)
    for i, p in enumerate(prompts):
        assert results[i] == _solo(model, params, p, 4), f"request {i}"


def test_submit_validation(engine_setup):
    _, _, engine = engine_setup
    with pytest.raises(ValueError, match="prompts"):
        engine.submit([], max_new_tokens=4)
    with pytest.raises(ValueError, match="non-empty"):
        engine.submit([[]], max_new_tokens=4)
    with pytest.raises(ValueError, match="exceeds"):
        engine.submit([[1] * 60], max_new_tokens=30)


def test_closed_engine_rejects():
    model, params = _model_and_params(max_seq_len=32)
    engine = GenerateEngine(model, params, slots=2)
    engine.close()
    with pytest.raises(RuntimeError, match="closed"):
        engine.submit([[1, 2]], max_new_tokens=2)


def test_server_continuous_batching_route():
    from k3stpu.serve.server import InferenceServer

    server = InferenceServer(model_name="transformer-tiny", seq_len=32,
                             batch_window_ms=0.0, continuous_batching=True,
                             engine_slots=4, shard_devices=1)
    try:
        toks = server.generate_tokens([[3, 4, 5]], max_new_tokens=4)
        assert len(toks) == 1 and len(toks[0]) == 4
        card = server.model_card()
        assert card["engine"]["tokens"] >= 4
        # The engine route must agree with the batch route (same greedy
        # semantics) for the same prompt.
        plain = InferenceServer(model_name="transformer-tiny", seq_len=32,
                                batch_window_ms=0.0, shard_devices=1)
        try:
            assert plain.generate_tokens([[3, 4, 5]],
                                         max_new_tokens=4) == toks
        finally:
            plain.close()
    finally:
        server.close()


def test_server_continuous_batching_rejects_non_lm():
    from k3stpu.serve.server import InferenceServer

    with pytest.raises(ValueError, match="continuous-batching"):
        InferenceServer(model_name="resnet18-tiny", image_size=32,
                        continuous_batching=True)


def test_server_chunks_wide_requests_through_engine():
    from k3stpu.serve.server import InferenceServer

    server = InferenceServer(model_name="transformer-tiny", seq_len=32,
                             batch_window_ms=0.0, continuous_batching=True,
                             engine_slots=2, shard_devices=1)
    try:
        prompts = [[i + 1, i + 2] for i in range(5)]  # 5 rows, 2 slots
        toks = server.generate_tokens(prompts, max_new_tokens=3)
        assert len(toks) == 5
        plain = InferenceServer(model_name="transformer-tiny", seq_len=32,
                                batch_window_ms=0.0, shard_devices=1)
        try:
            assert plain.generate_tokens(prompts, max_new_tokens=3) == toks
        finally:
            plain.close()
    finally:
        server.close()


def test_engine_composes_with_quant_and_int8_kv():
    """The engine must schedule the quantized model + int8 cache exactly
    like the float one schedules the float model (same code path the
    server wires with --quant/--kv-cache-dtype/--continuous-batching)."""
    from k3stpu.serve.server import InferenceServer

    server = InferenceServer(model_name="transformer-tiny", seq_len=32,
                             batch_window_ms=0.0, quant="int8",
                             kv_cache_dtype="int8",
                             continuous_batching=True, engine_slots=2,
                             shard_devices=1)
    try:
        toks = server.generate_tokens([[3, 4, 5], [7, 8]],
                                      max_new_tokens=4)
        assert len(toks) == 2 and all(len(t) == 4 for t in toks)
        # Same quantized model WITHOUT the engine must emit the same
        # greedy tokens — scheduling must not change sampling.
        plain = InferenceServer(model_name="transformer-tiny", seq_len=32,
                                batch_window_ms=0.0, quant="int8",
                                kv_cache_dtype="int8", shard_devices=1)
        try:
            assert plain.generate_tokens([[3, 4, 5], [7, 8]],
                                         max_new_tokens=4) == toks
        finally:
            plain.close()
    finally:
        server.close()


def test_chunked_prefill_admission_exact():
    """chunk_prefill=8 with prompts longer than one chunk (ragged lengths
    crossing chunk boundaries): outputs still equal solo generation, and
    chunked admission actually ran."""
    model, params = _model_and_params(max_seq_len=64)
    engine = GenerateEngine(model, params, slots=4, chunk_prefill=8)
    try:
        prompts = [list(range(1, 20)),          # 19 tokens: 3 chunks
                   list(range(30, 41))]         # 11 tokens: 2 chunks
        got = engine.submit(prompts, max_new_tokens=5)
        for g, p in zip(got, prompts):
            assert g == _solo(model, params, p, 5), p
        assert engine.stats()["adm_chunks"] >= 2
    finally:
        engine.close()


def test_chunked_admission_interleaves_with_decode():
    """A long-prompt admission must not freeze an in-flight generation:
    the active request keeps emitting decode steps between chunks."""
    model, params = _model_and_params(max_seq_len=64)
    engine = GenerateEngine(model, params, slots=4, chunk_prefill=8)
    try:
        # Warm the compiled programs.
        engine.submit([[1, 2]], max_new_tokens=2)
        engine.submit([list(range(1, 20))], max_new_tokens=2)

        long_prompt = list(range(1, 25))
        results = {}
        t = threading.Thread(target=lambda: results.update(
            a=engine.submit([[5, 6, 7]], max_new_tokens=30)[0]))
        t.start()
        deadline = time.time() + 60
        while engine.stats()["steps"] < 3:
            assert time.time() < deadline
            time.sleep(0.005)
        got = engine.submit([long_prompt], max_new_tokens=4)[0]
        t.join(120)
        assert got == _solo(model, params, long_prompt, 4)
        assert results["a"] == _solo(model, params, [5, 6, 7], 30)
    finally:
        engine.close()


def test_short_request_admits_during_chunked_prefill():
    """No head-of-line blocking: a short prompt admits (and can finish)
    while a long prompt's chunked admission is still in flight."""
    model, params = _model_and_params(max_seq_len=64)
    engine = GenerateEngine(model, params, slots=4, chunk_prefill=8)
    try:
        engine.submit([[1, 2]], max_new_tokens=2)  # warm programs
        engine.submit([list(range(1, 25))], max_new_tokens=2)
        long_prompt = list(range(1, 33))
        results = {}
        t = threading.Thread(target=lambda: results.update(
            long=engine.submit([long_prompt], max_new_tokens=20)[0]))
        t.start()
        time.sleep(0.01)  # let the chunked admission start
        short = engine.submit([[5, 6]], max_new_tokens=2)[0]
        t.join(120)
        assert short == _solo(model, params, [5, 6], 2)
        assert results["long"] == _solo(model, params, long_prompt, 20)
    finally:
        engine.close()


def test_bad_chunk_prefill_rejected():
    model, params = _model_and_params(max_seq_len=32)
    with pytest.raises(ValueError, match="chunk_prefill"):
        GenerateEngine(model, params, slots=2, chunk_prefill=0)


def test_engine_mixed_sampling_params_concurrently():
    """Heterogeneous requests share the one decode program: a greedy
    request stays exact while a sampled request runs in the same batch."""
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=4)
    try:
        engine.submit([[1, 2]], max_new_tokens=2)  # warm
        results = {}

        def run_sampled():
            try:
                results["sampled"] = engine.submit(
                    [[9, 10, 11]], max_new_tokens=24, temperature=1.0,
                    top_k=8)[0]
            except Exception as e:  # noqa: BLE001 — surface in the assert
                results["error"] = e

        t = threading.Thread(target=run_sampled)
        t.start()
        greedy = engine.submit([[5, 6, 7]], max_new_tokens=6)[0]
        t.join(120)
        assert greedy == _solo(model, params, [5, 6, 7], 6)
        assert "error" not in results, results.get("error")
        s = results["sampled"]
        assert len(s) == 24
        assert all(0 <= tok < model.config.vocab_size for tok in s)
    finally:
        engine.close()


def test_engine_moe_model():
    from k3stpu.models.moe import moe_lm_tiny

    model = moe_lm_tiny(max_seq_len=32)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                           train=False)
    engine = GenerateEngine(model, variables["params"], slots=2)
    try:
        got = engine.submit([[3, 4, 5]], max_new_tokens=4)[0]
        assert got == _solo(model, variables["params"], [3, 4, 5], 4)
    finally:
        engine.close()


def test_chunked_prefill_with_int8_kv_cache():
    import dataclasses

    base = transformer_lm_tiny(max_seq_len=64)
    variables = base.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                          train=False)
    qmodel = type(base)(dataclasses.replace(base.config,
                                            kv_cache_dtype="int8"))
    engine = GenerateEngine(qmodel, variables["params"], slots=2,
                            chunk_prefill=8)
    plain = GenerateEngine(qmodel, variables["params"], slots=2)
    try:
        prompt = list(range(1, 22))
        a = engine.submit([prompt], max_new_tokens=5)[0]
        b = plain.submit([prompt], max_new_tokens=5)[0]
        assert a == b, "chunked admission must not change int8-KV decode"
    finally:
        engine.close()
        plain.close()


def test_submit_samples_shared_prefix():
    """One prefill, n rows: greedy samples are all the solo continuation;
    sampled rows are valid and (statistically) diverge."""
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=4)
    try:
        sol = _solo(model, params, [5, 6, 7], 6)
        greedy = engine.submit_samples([5, 6, 7], 3, max_new_tokens=6,
                                       temperature=0.0)
        assert greedy == [sol, sol, sol]
        sampled = engine.submit_samples([5, 6, 7], 4, max_new_tokens=16,
                                        temperature=1.0)
        assert len(sampled) == 4
        assert all(len(s) == 16 for s in sampled)
        assert all(0 <= t < model.config.vocab_size
                   for s in sampled for t in s)
        assert len({tuple(s) for s in sampled}) > 1, (
            "independent sampling noise should diverge the rows")
    finally:
        engine.close()


def test_submit_samples_chunked_prefill():
    model, params = _model_and_params(max_seq_len=64)
    engine = GenerateEngine(model, params, slots=4, chunk_prefill=8)
    try:
        prompt = list(range(1, 20))
        sol = _solo(model, params, prompt, 4)
        greedy = engine.submit_samples(prompt, 2, max_new_tokens=4,
                                       temperature=0.0)
        assert greedy == [sol, sol]
    finally:
        engine.close()


def test_server_num_samples_routes():
    from k3stpu.serve.server import InferenceServer

    eng = InferenceServer(model_name="transformer-tiny", seq_len=32,
                          batch_window_ms=0.0, continuous_batching=True,
                          engine_slots=4, shard_devices=1)
    plain = InferenceServer(model_name="transformer-tiny", seq_len=32,
                            batch_window_ms=0.0, shard_devices=1)
    try:
        for server in (eng, plain):
            out = server.generate_tokens([[3, 4, 5]], max_new_tokens=4,
                                         temperature=1.0, num_samples=3)
            assert len(out) == 3 and all(len(r) == 4 for r in out)
        import pytest as _pt
        with _pt.raises(ValueError, match="num_samples"):
            eng.generate_tokens([[1, 2], [3, 4]], max_new_tokens=2,
                                num_samples=2)
    finally:
        eng.close()
        plain.close()


def test_decode_failure_fails_requests_and_engine_recovers(monkeypatch):
    """A device-side decode failure must fail every in-flight request
    cleanly (no hang, no stuck slots) and leave the engine serviceable."""
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=2)
    try:
        engine.submit([[1, 2]], max_new_tokens=2)  # warm + sanity

        real = engine._decode_step
        calls = {"n": 0}

        def boom(*args, **kwargs):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected decode failure")
            return real(*args, **kwargs)

        monkeypatch.setattr(engine, "_decode_step", boom)
        with pytest.raises(RuntimeError, match="injected"):
            engine.submit([[5, 6, 7]], max_new_tokens=8)
        # Slots freed, loop alive: the next request succeeds.
        got = engine.submit([[5, 6, 7]], max_new_tokens=4)
        assert got == [_solo(model, params, [5, 6, 7], 4)]
    finally:
        engine.close()


def test_engine_soak_randomized_failures(monkeypatch):
    """Soak under chaos (SURVEY.md §4's designed pyramid, VERDICT r3 #9):
    concurrent clients mix submit/submit_samples with random budgets,
    sampling params, tiny random deadlines, and chunked-prefill prompts
    while injected decode faults fire every ~13th dispatch. Invariants at
    the end: no slot leak (_free_slots back to full), no reserved rows,
    no stuck client (every call returned or raised), and the engine still
    serves exact greedy output."""
    import random

    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=4, chunk_prefill=8,
                            decode_block=3)
    try:
        engine.submit([[1, 2]], max_new_tokens=2)  # warm the programs

        real = engine._decode_block_step
        calls = {"n": 0}

        def flaky(*args, **kwargs):
            calls["n"] += 1
            if calls["n"] % 13 == 0:
                raise RuntimeError("injected decode fault")
            return real(*args, **kwargs)

        monkeypatch.setattr(engine, "_decode_block_step", flaky)

        outcomes = {"done": 0, "failed": 0, "timeout": 0}
        lock = threading.Lock()
        stop = time.time() + 20.0

        def client(seed):
            rng = random.Random(seed)
            while time.time() < stop:
                budget = rng.randint(1, 12)
                try:
                    if rng.random() < 0.25:
                        engine.submit_samples(
                            [rng.randint(1, 40)], rng.randint(1, 3),
                            max_new_tokens=budget, temperature=1.0,
                            top_k=rng.choice([None, 8]),
                            timeout_s=rng.choice([0.02, 5.0, 30.0]))
                    else:
                        n_prompts = rng.randint(1, 2)
                        prompts = [
                            [rng.randint(1, 40)
                             for _ in range(rng.randint(1, 20))]
                            for _ in range(n_prompts)]
                        engine.submit(
                            prompts, max_new_tokens=budget,
                            temperature=rng.choice([0.0, 0.8]),
                            top_p=rng.choice([None, 0.9]),
                            eos_id=rng.choice([None, 3]),
                            timeout_s=rng.choice([0.02, 5.0, 30.0]))
                    key = "done"
                except TimeoutError:
                    key = "timeout"
                except RuntimeError:
                    key = "failed"
                with lock:
                    outcomes[key] += 1

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "stuck client"
        assert outcomes["done"] > 0, outcomes
        assert outcomes["failed"] > 0, f"no fault ever fired: {outcomes}"

        # Drain: every slot frees once in-flight work settles.
        deadline = time.time() + 30
        while len(engine._free_slots()) != engine.slots:
            assert time.time() < deadline, (
                f"slot leak: {engine._free_slots()} free of "
                f"{engine.slots}; active={engine._active}, "
                f"reserved={engine._reserved}")
            time.sleep(0.05)
        assert not engine._reserved.any()
        assert engine._adm is None

        monkeypatch.setattr(engine, "_decode_block_step", real)
        got = engine.submit([[5, 6, 7]], max_new_tokens=4)
        assert got == [_solo(model, params, [5, 6, 7], 4)]
    finally:
        engine.close()


def test_expired_request_frees_slots():
    """A request whose client stopped waiting is evicted mid-decode: its
    slots free up and the engine keeps serving."""
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=2)
    try:
        engine.submit([[1, 2]], max_new_tokens=2)  # warm
        # Deterministic expiry: an idle box decodes 48 tiny-model tokens
        # inside the timeout, so slow each dispatch explicitly — the
        # scenario under test is "client gave up mid-decode", not a race
        # against machine speed.
        real = engine._decode_step

        def slow_step(*args, **kwargs):
            time.sleep(0.02)
            return real(*args, **kwargs)

        engine._decode_step = slow_step
        with pytest.raises(TimeoutError):
            # Tiny timeout: the client gives up while decode is running.
            engine.submit([[5, 6, 7]], max_new_tokens=48, timeout_s=0.05)
        deadline = time.time() + 30
        while engine._active.any():
            assert time.time() < deadline, "expired slots never freed"
            time.sleep(0.05)
        got = engine.submit([[5, 6, 7]], max_new_tokens=4)
        assert got == [_solo(model, params, [5, 6, 7], 4)]
    finally:
        engine.close()


def test_expired_chunked_admission_aborts():
    """A request whose client gave up mid-chunked-prefill must not run
    its remaining chunks + full decode budget: the deadline check covers
    the in-flight admission, clears the reserved rows, and the engine
    keeps serving."""
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=2, chunk_prefill=4)
    try:
        engine.submit([[1, 2]], max_new_tokens=2)  # warm all programs
        with pytest.raises(TimeoutError):
            # 32-token prompt = 8 chunks; the client gives up immediately.
            engine.submit([list(range(1, 33))], max_new_tokens=24,
                          timeout_s=0.01)
        deadline = time.time() + 30
        while engine._adm is not None or engine._reserved.any():
            assert time.time() < deadline, "expired admission never cleared"
            time.sleep(0.05)
        got = engine.submit([[5, 6, 7]], max_new_tokens=4)
        assert got == [_solo(model, params, [5, 6, 7], 4)]
    finally:
        engine.close()


def test_decode_block_matches_generate():
    """decode_block=4 (multi-token dispatch) must stay EXACTLY pinned to
    generate(): greedy K-step scan == K greedy steps, budgets that aren't
    multiples of K discard the surplus, eos mid-block truncates."""
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=4, decode_block=4)
    try:
        for budget in (1, 3, 4, 6, 11):
            got = engine.submit([[5, 6, 7]], max_new_tokens=budget)
            assert got == [_solo(model, params, [5, 6, 7], budget)], budget
        # Multi-prompt ragged batch through the block path.
        prompts = [[3, 4], [9, 10, 11, 12, 13]]
        got = engine.submit(prompts, max_new_tokens=7)
        for g, p in zip(got, prompts):
            assert g == _solo(model, params, p, 7)
    finally:
        engine.close()


def test_decode_block_concurrent_interleave():
    """Concurrent requests through the K-block path each match their solo
    output (slot interleaving must not leak across rows within a block)."""
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=4, decode_block=3)
    try:
        prompts = [[5, 6], [7, 8, 9], [10], [11, 12, 13, 14]]
        outs: dict[int, list] = {}

        def call(i):
            outs[i] = engine.submit([prompts[i]], max_new_tokens=9)[0]

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert len(outs) == 4
        for i, p in enumerate(prompts):
            assert outs[i] == _solo(model, params, p, 9), i
    finally:
        engine.close()


def test_decode_block_eos_and_expiry():
    """eos stopping and deadline expiry still work at block granularity."""
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=2, decode_block=4)
    try:
        ref = _solo(model, params, [5, 6, 7], 10)
        eos = ref[4]  # force a mid-generation eos
        got = engine.submit([[5, 6, 7]], max_new_tokens=10, eos_id=eos)[0]
        cut = ref.index(eos)
        assert got[:cut + 1] == ref[:cut + 1]
        assert all(t == eos for t in got[cut:])  # eos-extended tail
        assert engine.decode_block == 4
    finally:
        engine.close()


def test_bad_decode_block_rejected():
    model, params = _model_and_params()
    with pytest.raises(ValueError, match="decode_block"):
        GenerateEngine(model, params, decode_block=0)


def test_engine_top_p_sampling():
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=2)
    try:
        out = engine.submit([[5, 6, 7]], max_new_tokens=12,
                            temperature=1.0, top_p=0.9)[0]
        assert len(out) == 12
        assert all(0 <= t < model.config.vocab_size for t in out)
        # top_p must not perturb greedy (temperature 0 short-circuits).
        g = engine.submit([[5, 6, 7]], max_new_tokens=4, top_p=0.5)[0]
        assert g == _solo(model, params, [5, 6, 7], 4)
    finally:
        engine.close()
