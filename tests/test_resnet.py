"""ResNet correctness: shapes, parameter count, train-mode batch stats."""

import jax
import jax.numpy as jnp

from k3stpu.models.resnet import resnet18, resnet50


def n_params(tree):
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def test_resnet18_forward_shape():
    model = resnet18(num_classes=10)
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32


def test_resnet50_param_count():
    # Canonical ImageNet ResNet-50: 25,557,032 parameters (weights only).
    model = resnet50(num_classes=1000)
    x = jnp.zeros((1, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x, train=False)
    count = n_params(variables["params"])
    assert count == 25_557_032, count


def test_batch_stats_update():
    model = resnet18(num_classes=10)
    x = jax.random.normal(jax.random.key(1), (4, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x, train=True)
    _, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    before = variables["batch_stats"]["bn_stem"]["mean"]
    after = mutated["batch_stats"]["bn_stem"]["mean"]
    assert not jnp.allclose(before, after)
