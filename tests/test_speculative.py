"""Speculative decoding (k3stpu/serve/speculative.py).

THE invariant: greedy speculative output equals the target model's own
greedy continuation exactly, for ANY draft — a good draft only changes
how many rounds it takes. Verified with an unrelated random draft (worst
case) and with the target as its own draft (best case: acceptance 1.0).
"""

import jax
import jax.numpy as jnp
import numpy as np

from k3stpu.models.generate import generate
from k3stpu.models.transformer import transformer_lm_tiny
from k3stpu.serve.speculative import speculative_generate


def _lm(seed, **overrides):
    model = transformer_lm_tiny(**overrides)
    variables = model.init(jax.random.key(seed),
                           jnp.zeros((1, 8), jnp.int32), train=False)
    return model, variables["params"]


def _greedy(model, params, block, lens, budget):
    out = generate(model, params, jnp.asarray(block), jnp.asarray(lens),
                   budget, temperature=0.0)
    return np.asarray(out)


def test_speculative_matches_target_greedy_with_unrelated_draft():
    target, tparams = _lm(0, max_seq_len=64)
    draft, dparams = _lm(99, max_seq_len=64, n_layers=1, d_model=32,
                         n_heads=2, d_ff=64)
    block = np.zeros((2, 8), np.int32)
    block[0, :3] = [5, 6, 7]
    block[1, :8] = [9, 10, 11, 12, 13, 14, 15, 16]
    lens = np.array([3, 8], np.int32)

    out, stats = speculative_generate(target, tparams, draft, dparams,
                                      block, lens, 12, gamma=3)
    ref = _greedy(target, tparams, block, lens, 12)
    assert np.array_equal(out, ref), (out.tolist(), ref.tolist())
    assert stats["rounds"] >= 1
    assert 0.0 <= stats["acceptance_rate"] <= 1.0


def test_speculative_self_draft_accepts_everything():
    target, tparams = _lm(1, max_seq_len=64)
    block = np.zeros((1, 8), np.int32)
    block[0, :4] = [3, 4, 5, 6]
    lens = np.array([4], np.int32)

    out, stats = speculative_generate(target, tparams, target, tparams,
                                      block, lens, 10, gamma=4)
    ref = _greedy(target, tparams, block, lens, 10)
    assert np.array_equal(out, ref)
    # A perfect draft is always accepted: gamma proposals + the bonus
    # token per round.
    assert stats["acceptance_rate"] == 1.0
    assert stats["rounds"] <= -(-9 // 5)  # ceil((budget-1) / (gamma+1))


def test_speculative_bounds_validation():
    target, tparams = _lm(2, max_seq_len=16)
    draft, dparams = _lm(3, max_seq_len=16)
    block = np.zeros((1, 8), np.int32)
    block[0, :8] = np.arange(1, 9)
    import pytest

    with pytest.raises(ValueError, match="exceeds"):
        speculative_generate(target, tparams, draft, dparams, block,
                             np.array([8], np.int32), 8, gamma=4)
    with pytest.raises(ValueError, match="gamma"):
        speculative_generate(target, tparams, draft, dparams, block,
                             np.array([8], np.int32), 2, gamma=0)


def test_server_speculative_route_matches_plain():
    from k3stpu.serve.server import InferenceServer

    spec = InferenceServer(model_name="transformer-tiny", seq_len=64,
                           batch_window_ms=0.0, shard_devices=1,
                           draft_model="transformer-tiny", spec_gamma=3)
    plain = InferenceServer(model_name="transformer-tiny", seq_len=64,
                            batch_window_ms=0.0, shard_devices=1)
    try:
        prompts = [[5, 6, 7], [9, 10]]
        got = spec.generate_tokens(prompts, max_new_tokens=8)
        ref = plain.generate_tokens(prompts, max_new_tokens=8)
        assert got == ref
        card = spec.model_card()
        assert card["speculative"]["requests"] == 1
        assert card["speculative"]["acceptance_rate"] is not None
        # Sampled requests must still work (plain-path fallback).
        sampled = spec.generate_tokens(prompts, max_new_tokens=4,
                                       temperature=1.0)
        assert len(sampled) == 2
    finally:
        spec.close()
        plain.close()


def test_server_spec_eos_latch():
    from k3stpu.serve.server import InferenceServer

    spec = InferenceServer(model_name="transformer-tiny", seq_len=64,
                           batch_window_ms=0.0, shard_devices=1,
                           draft_model="transformer-tiny", spec_gamma=3)
    plain = InferenceServer(model_name="transformer-tiny", seq_len=64,
                            batch_window_ms=0.0, shard_devices=1)
    try:
        ref = plain.generate_tokens([[5, 6, 7]], max_new_tokens=8)[0]
        eos = ref[2]
        assert (spec.generate_tokens([[5, 6, 7]], max_new_tokens=8,
                                     eos_id=eos)
                == plain.generate_tokens([[5, 6, 7]], max_new_tokens=8,
                                         eos_id=eos))
    finally:
        spec.close()
        plain.close()
