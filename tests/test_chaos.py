"""Chaos suite: fault injection + containment invariants (ISSUE 3).

Every test injects one named fault class through k3stpu.chaos and then
asserts the SAME recovery contract: the engine accepts and completes new
work, the page allocator's free count returns to its pre-fault baseline,
no client thread stays blocked past its deadline, and the containment
counters moved. docs/RESILIENCE.md is the prose version of this file.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.chaos import FaultInjector, InjectedFault
from k3stpu.serve.containment import (
    CircuitBreaker,
    CircuitOpen,
    EngineStalled,
)
from k3stpu.serve.engine import GenerateEngine


@pytest.fixture(scope="module")
def mp():
    from k3stpu.models.transformer import transformer_lm_tiny

    model = transformer_lm_tiny(max_seq_len=64)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                           train=False)
    return model, variables["params"]


def _engine(mp, **kw):
    model, params = mp
    kw.setdefault("slots", 4)
    return GenerateEngine(model, params, **kw)


def _submit_until_healthy(eng, deadline_s=30.0):
    """Retry-loop client: submits until the engine serves a request —
    the 'engine accepts new work again' half of the recovery contract.
    EngineStalled/CircuitOpen are exactly the retryable errors the
    containment layer promises, so retrying them IS the contract."""
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            return eng.submit([[7, 8, 9]], max_new_tokens=2, timeout_s=30.0)
        except (EngineStalled, CircuitOpen):
            assert time.monotonic() < deadline, \
                "engine never recovered within the deadline"
            time.sleep(0.25)


# --- fault class: raised backend error mid-decode -----------------------


def test_dispatch_error_crash_resets_paged_state(mp):
    chaos = FaultInjector()
    eng = _engine(mp, page_size=16, chaos=chaos)
    try:
        baseline = eng.stats()["pages_free"]
        eng.submit([[1, 2, 3]], max_new_tokens=4)  # healthy warm pass
        assert eng.stats()["pages_free"] == baseline
        chaos.arm("decode_dispatch", exc=InjectedFault("injected XLA error"))
        with pytest.raises(InjectedFault):
            eng.submit([[4, 5, 6]], max_new_tokens=4, timeout_s=30.0)
        assert chaos.fired("decode_dispatch") == 1
        # Recovery invariants: verified-empty pool, fresh work completes.
        out = eng.submit([[7, 8, 9]], max_new_tokens=4, timeout_s=30.0)
        assert len(out) == 1 and len(out[0]) == 4
        s = eng.stats()
        assert s["pages_free"] == baseline
        assert s["loop_crashes"] == 1
    finally:
        eng.close()


def test_dispatch_error_fails_every_inflight_request_cleanly(mp):
    """Two concurrent requests share the crash: both submitters get the
    error (not a hang), and both slots come back."""
    chaos = FaultInjector()
    eng = _engine(mp, page_size=16, chaos=chaos)
    try:
        baseline = eng.stats()["pages_free"]
        eng.submit([[1, 2]], max_new_tokens=2)  # warm compiles first
        chaos.arm("decode_dispatch", exc=InjectedFault("boom"), skip=0)
        results = []

        def client(tok):
            try:
                eng.submit([[tok, tok + 1]], max_new_tokens=8, timeout_s=30.0)
                results.append("ok")
            except InjectedFault:
                results.append("fault")
            except Exception as e:  # noqa: BLE001
                results.append(repr(e))

        threads = [threading.Thread(target=client, args=(10 + i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "client thread stuck past deadline"
        # At least one rode the crashed dispatch; any sibling that was
        # still queued is served by the recovered loop.
        assert "fault" in results, results
        assert all(r in ("ok", "fault") for r in results), results
        assert eng.stats()["pages_free"] == baseline
        _submit_until_healthy(eng)
    finally:
        eng.close()


# --- fault class: page-pool exhaustion ----------------------------------


def test_page_pool_exhaustion_contained(mp):
    chaos = FaultInjector()
    eng = _engine(mp, page_size=16, chaos=chaos)
    try:
        baseline = eng.stats()["pages_free"]
        chaos.arm("page_alloc",
                  exc=RuntimeError("chaos: page pool exhausted"))
        with pytest.raises(RuntimeError, match="exhausted"):
            eng.submit([[1, 2, 3]], max_new_tokens=4, timeout_s=30.0)
        assert eng.stats()["pages_free"] == baseline
        out = eng.submit([[1, 2, 3]], max_new_tokens=4, timeout_s=30.0)
        assert len(out[0]) == 4
        assert eng.stats()["pages_free"] == baseline
    finally:
        eng.close()


# --- fault class: speculative verify dispatch failure -------------------


def test_spec_verify_fault_falls_back_to_plain_decode(mp):
    """A verify dispatch that raises must degrade that batch to plain
    decode — counted in ``spec_fallbacks`` — with the OUTPUT still
    bit-exact and the loop alive; speculation is an optimization and a
    failing optimization may never cost correctness or availability."""
    chaos = FaultInjector()
    eng = _engine(mp, page_size=16, speculate=True, chaos=chaos)
    try:
        prompt = [5, 9] * 8                 # repetitive: drafter engages
        # Warm pass doubles as the reference: greedy output is
        # deterministic, so the post-fault submit must reproduce it
        # (and test_spec_engine.py pins it to the plain engine).
        want = eng.submit([prompt], max_new_tokens=8, timeout_s=30.0)
        assert eng.stats()["spec_dispatches"] > 0, (
            "speculation never engaged — the fault below would not be "
            "exercised")
        chaos.arm("spec_verify", exc=InjectedFault("injected verify error"))
        out = eng.submit([prompt], max_new_tokens=8, timeout_s=30.0)
        assert out == want, "fallback batch must stay bit-exact"
        assert chaos.fired("spec_verify") == 1
        s = eng.stats()
        assert s["spec_fallbacks"] == 1
        assert s["loop_crashes"] == 0, (
            "a verify fault must be contained, not crash the loop")
        assert eng.loop_alive()
        # Speculation resumes once the fault is spent.
        eng.submit([prompt], max_new_tokens=8, timeout_s=30.0)
        assert eng.stats()["spec_dispatches"] > s["spec_dispatches"]
    finally:
        eng.close()


# --- fault class: QoS preemption park / predictive admission ------------


def test_preempt_park_fault_leaves_victim_running_rejects_trigger(mp):
    """A park that dies mid-swap (page gather / tier put) must abort
    BEFORE any victim state is torn down: the batch victim keeps its
    slot and finishes bit-exactly, the interactive trigger is rejected
    honestly (503-shaped AdmissionRejected with a Retry-After), and
    the allocator comes back to baseline — a failed park is a capacity
    miss, never a lost or corrupted request (docs/QOS.md)."""
    from k3stpu.models.generate import generate
    from k3stpu.serve.engine import AdmissionRejected
    from k3stpu.serve.tiering import HostPageStore

    model, params = mp
    chaos = FaultInjector()
    eng = GenerateEngine(model, params, seed=0, slots=1, page_size=8,
                         prompt_cache=2, qos=True,
                         tier=HostPageStore(64 << 20), chaos=chaos)
    try:
        bp = [5, 6, 7, 8, 9, 10, 11, 12]
        want = np.asarray(generate(
            model, params, jnp.asarray(np.array([bp], np.int32)),
            jnp.array([len(bp)], jnp.int32), 20,
            temperature=0.0))[0].tolist()
        chaos.arm("preempt_park", exc=InjectedFault("park died mid-swap"))
        out = {}

        def run_batch():
            out["batch"] = eng.submit([bp], max_new_tokens=20,
                                      priority="batch", timeout_s=60.0)

        t = threading.Thread(target=run_batch)
        t.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            o = eng._owner[0]
            if (o is not None and eng._active[0]
                    and len(eng._collected[0]) >= 2):
                break
            time.sleep(0.002)
        with pytest.raises(AdmissionRejected) as ei:
            eng.submit([[20, 21, 22]], max_new_tokens=4, timeout_s=60.0)
        assert ei.value.retry_after_s >= 1.0
        t.join(60)
        assert not t.is_alive(), "victim thread stuck"
        assert chaos.fired("preempt_park") == 1
        assert out["batch"] == [want], (
            "the victim's output changed — the failed park tore state")
        s = eng.stats()
        assert s["preempt_fallbacks"] == 1
        assert s["preemptions"] == 0
        # Allocator invariants hold exactly: every page's refcount is
        # its live chain + prompt-cache-pin references, free agrees.
        alloc, expect = eng._alloc, {}
        for chain in eng._chains:
            for p in chain:
                expect[p] = expect.get(p, 0) + 1
        for entry in eng._pcache.values():
            for p in entry[0]:
                expect[p] = expect.get(p, 0) + 1
        for p in range(1, alloc.num_pages):
            assert alloc.refcount(p) == expect.get(p, 0)
        assert alloc.free == alloc.total - sum(
            1 for v in expect.values() if v > 0)
        # Fresh work still completes: nothing is wedged or poisoned.
        eng.submit([[1, 2, 3]], max_new_tokens=2, timeout_s=30.0)
    finally:
        eng.close()


def test_admission_predict_fault_fails_open(mp):
    """A broken TTFT estimator must degrade the predictive gate to the
    pre-QoS FIFO admission (fail OPEN, ``predict_fallbacks`` counted)
    — never to rejecting live traffic on a bad forecast."""
    from k3stpu.obs import ServeObs
    from k3stpu.serve.engine import AdmissionRejected
    from k3stpu.serve.tiering import HostPageStore

    model, params = mp
    chaos = FaultInjector()
    obs = ServeObs()
    eng = GenerateEngine(model, params, seed=0, slots=2, page_size=8,
                         prompt_cache=2, qos=True,
                         tier=HostPageStore(64 << 20),
                         chaos=chaos, obs=obs,
                         interactive_ttft_slo_s=1e-4)
    try:
        eng.submit([[5, 6, 7, 8]], max_new_tokens=2)  # seeds the p50
        # Positive control: with the estimator healthy, the impossible
        # SLO rejects at the door.
        with pytest.raises(AdmissionRejected):
            eng.submit([[5, 6, 7, 9]], max_new_tokens=2)
        chaos.arm("admission_predict",
                  exc=InjectedFault("estimator down"))
        out = eng.submit([[5, 6, 8, 9]], max_new_tokens=2,
                         timeout_s=30.0)
        assert len(out[0]) == 2, "fail-open admission must still serve"
        assert chaos.fired("admission_predict") == 1
        assert eng.stats()["predict_fallbacks"] == 1
    finally:
        eng.close()


# --- fault class: loop-thread death -------------------------------------


def test_loop_thread_death_revived_by_watchdog(mp):
    chaos = FaultInjector()
    eng = _engine(mp, chaos=chaos, watchdog_s=5.0)
    try:
        eng.submit([[1, 2]], max_new_tokens=2)  # warm
        chaos.arm("engine_loop", exc=InjectedFault("injected loop death"))
        # The idle loop ticks every <=0.2s, so the fault kills it almost
        # immediately; the watchdog polls ~1s and revives it.
        deadline = time.monotonic() + 20
        while (eng.stats()["loop_restarts"] == 0
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert eng.stats()["loop_restarts"] == 1
        assert chaos.fired("engine_loop") == 1
        out = eng.submit([[3, 4]], max_new_tokens=2, timeout_s=30.0)
        assert len(out[0]) == 2
        assert eng.loop_alive()
    finally:
        eng.close()


# --- fault class: stalled dispatch (watchdog) ----------------------------


def test_watchdog_fails_stalled_clients_with_retryable_error(mp):
    chaos = FaultInjector()
    breaker = CircuitBreaker(threshold=3, cooldown_s=0.5)
    # Warm the persistent compile cache with a throwaway engine first, so
    # the watchdog engine's own compiles stay far below watchdog_s (a
    # compile IS a dispatch stall as far as the heartbeat can tell).
    warm = _engine(mp)
    warm.submit([[1, 2]], max_new_tokens=4)
    warm.close()
    eng = _engine(mp, chaos=chaos, watchdog_s=2.0, breaker=breaker)
    try:
        eng.submit([[1, 2]], max_new_tokens=4)  # cache-hit compiles
        chaos.arm("decode_dispatch", stall_s=6.0)
        t0 = time.monotonic()
        with pytest.raises(EngineStalled):
            eng.submit([[3, 4]], max_new_tokens=4, timeout_s=60.0)
        elapsed = time.monotonic() - t0
        # The whole point: the client fails in ~watchdog_s, NOT after
        # riding out the stall (6s) or its own timeout (60s).
        assert elapsed < 5.5, elapsed
        s = eng.stats()
        assert s["watchdog_trips"] >= 1
        # The stall also tripped the breaker -> /healthz would be 503.
        assert breaker.state() in ("open", "half_open")
        _submit_until_healthy(eng)
        assert breaker.state() == "closed"
    finally:
        eng.close()


# --- fault class: client disconnect mid-stream ---------------------------


def test_client_disconnect_mid_stream_frees_pages(mp):
    eng = _engine(mp, page_size=16)
    try:
        baseline = eng.stats()["pages_free"]
        events = eng.submit_stream([[1, 2, 3]], max_new_tokens=32,
                                   timeout_s=30.0)
        first = next(events)
        assert not first["done"]
        events.close()  # the client went away mid-stream
        deadline = time.monotonic() + 10
        while (eng.stats()["pages_free"] != baseline
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert eng.stats()["pages_free"] == baseline, "page leak"
        out = eng.submit([[1, 2, 3]], max_new_tokens=4, timeout_s=30.0)
        assert len(out[0]) == 4
        assert eng.stats()["pages_free"] == baseline
    finally:
        eng.close()


# --- deadlines ----------------------------------------------------------


def test_deadline_expiry_is_counted(mp):
    eng = _engine(mp)
    try:
        with pytest.raises(TimeoutError):
            eng.submit([[1, 2]], max_new_tokens=2, timeout_s=0.0)
        deadline = time.monotonic() + 10
        while (eng.stats()["deadline_expired"] == 0
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert eng.stats()["deadline_expired"] >= 1
        out = eng.submit([[1, 2]], max_new_tokens=2, timeout_s=30.0)
        assert len(out[0]) == 2
    finally:
        eng.close()


# --- circuit breaker (engine level) --------------------------------------


def test_breaker_opens_after_repeated_failures_and_half_open_recovers(mp):
    chaos = FaultInjector()
    breaker = CircuitBreaker(threshold=2, cooldown_s=0.4)
    eng = _engine(mp, chaos=chaos, breaker=breaker)
    try:
        eng.submit([[1, 2]], max_new_tokens=2)  # healthy: stays closed
        assert breaker.state() == "closed"
        chaos.arm("decode_dispatch", times=2, exc=InjectedFault("boom"))
        for _ in range(2):
            with pytest.raises(InjectedFault):
                eng.submit([[3, 4]], max_new_tokens=4, timeout_s=30.0)
        assert breaker.state() == "open"
        with pytest.raises(CircuitOpen):
            eng.submit([[5, 6]], max_new_tokens=2, timeout_s=30.0)
        assert eng.stats()["breaker_rejected"] >= 1
        time.sleep(0.5)  # cooldown -> the next submit is the probe
        out = eng.submit([[5, 6]], max_new_tokens=2, timeout_s=30.0)
        assert len(out[0]) == 2
        assert breaker.state() == "closed"
        assert eng.stats()["breaker_trips"] >= 1
    finally:
        eng.close()


# --- stats/obs consistency across faults ---------------------------------


def test_stats_and_obs_stay_consistent_after_faults(mp):
    from k3stpu.obs import ServeObs

    chaos = FaultInjector()
    obs = ServeObs()
    eng = _engine(mp, page_size=16, chaos=chaos, obs=obs)
    try:
        eng.submit([[1, 2]], max_new_tokens=2)
        chaos.arm("decode_dispatch", exc=InjectedFault("boom"))
        with pytest.raises(InjectedFault):
            eng.submit([[3, 4]], max_new_tokens=4, timeout_s=30.0)
        eng.submit([[5, 6]], max_new_tokens=2, timeout_s=30.0)
        # The obs surface still renders (no wedged trace state) and the
        # engine's own counters reflect exactly one crash.
        text = obs.render_prometheus()
        assert "k3stpu_request_ttft_seconds" in text
        s = eng.stats()
        assert s["loop_crashes"] == 1
        assert s["requests"] >= 2
        assert s["pages_free"] == s["pages_total"]
    finally:
        eng.close()


# --- MicroBatcher loop death (satellite fix) -----------------------------


def test_microbatcher_loop_death_fails_waiters_immediately():
    from k3stpu.serve.server import MicroBatcher

    mb = MicroBatcher(lambda batch, n: batch, window_s=0.01)
    try:
        ones = np.ones((1, 2), np.float32)
        assert np.array_equal(mb.submit(ones), ones)
        # An item the dispatcher cannot even gather kills the loop thread
        # OUTSIDE its per-group try (the bug: submit then re-waited 30s
        # on a thread that no longer exists).
        mb._q.put({"bad": True})
        deadline = time.monotonic() + 5
        while mb._thread.is_alive() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not mb._thread.is_alive()
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="died"):
            mb.submit(ones)
        assert time.monotonic() - t0 < 5.0, "waiter not failed promptly"
    finally:
        mb.close()


def test_microbatcher_death_propagates_to_already_blocked_waiter():
    from k3stpu.serve.server import MicroBatcher

    started = threading.Event()

    def run(batch, n):
        started.set()
        time.sleep(0.2)
        raise KeyboardInterrupt("dispatcher dies mid-batch")

    mb = MicroBatcher(run, window_s=0.01)
    try:
        errors = []

        def client():
            try:
                mb.submit(np.ones((1, 2), np.float32))
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        t = threading.Thread(target=client)
        t.start()
        assert started.wait(timeout=5)
        t.join(timeout=10)
        assert not t.is_alive(), "client thread stuck on dead dispatcher"
        assert errors and "died" in str(errors[0])
    finally:
        mb.close()


# --- loadgen 503 retry (satellite) ---------------------------------------


class _FlakyHandler(BaseHTTPRequestHandler):
    """Replies 503 + Retry-After for the first `fails_left` POSTs, then
    200 forever."""
    state = {"fails_left": 0, "seen": 0}

    def do_POST(self):
        self.rfile.read(int(self.headers.get("Content-Length", "0")))
        self.state["seen"] += 1
        if self.state["fails_left"] > 0:
            self.state["fails_left"] -= 1
            body = json.dumps({"error": "overloaded"}).encode()
            self.send_response(503)
            self.send_header("Retry-After", "0.01")
        else:
            body = json.dumps({"ok": True}).encode()
            self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


def _flaky_server(fails):
    _FlakyHandler.state["fails_left"] = fails
    _FlakyHandler.state["seen"] = 0
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _FlakyHandler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def test_loadgen_retries_503_honoring_retry_after():
    from k3stpu.serve.loadgen import _client_loop

    httpd, url = _flaky_server(fails=2)
    try:
        stop = threading.Event()
        latencies, errors = [], []
        retry_stats = {"retries": 0, "gave_up": 0}
        lock = threading.Lock()
        t = threading.Thread(
            target=_client_loop,
            args=(url, b"{}", stop, latencies, lock, errors),
            kwargs={"retry_stats": retry_stats, "seed": 0}, daemon=True)
        t.start()
        deadline = time.monotonic() + 20
        while not latencies and time.monotonic() < deadline:
            time.sleep(0.02)
        stop.set()
        t.join(timeout=10)
        assert latencies, f"no success; errors={errors}"
        assert retry_stats["retries"] >= 2
        assert retry_stats["gave_up"] == 0
        assert not errors, errors
    finally:
        httpd.shutdown()


def test_loadgen_gives_up_after_capped_retries(monkeypatch):
    from k3stpu.serve import loadgen

    monkeypatch.setattr(loadgen, "_MAX_RETRIES_503", 2)
    monkeypatch.setattr(loadgen, "_BACKOFF_CAP_S", 0.05)
    httpd, url = _flaky_server(fails=10 ** 6)  # always 503
    try:
        stop = threading.Event()
        latencies, errors = [], []
        retry_stats = {"retries": 0, "gave_up": 0}
        lock = threading.Lock()
        t = threading.Thread(
            target=loadgen._client_loop,
            args=(url, b"{}", stop, latencies, lock, errors),
            kwargs={"retry_stats": retry_stats, "seed": 1}, daemon=True)
        t.start()
        deadline = time.monotonic() + 20
        while retry_stats["gave_up"] == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        stop.set()
        t.join(timeout=10)
        assert retry_stats["gave_up"] >= 1
        assert retry_stats["retries"] >= 2
        assert not latencies
    finally:
        httpd.shutdown()


# --- HTTP integration: breaker flips /healthz (acceptance criterion) -----


def _get(url, timeout=30):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _post(url, body, timeout=120, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def test_http_breaker_flips_healthz_and_recovers():
    """End-to-end acceptance path: repeated injected backend failures ->
    /v1/generate 500s -> breaker opens -> /healthz 503 (K8s pulls the
    pod) + admission 503 with Retry-After -> cooldown -> half-open probe
    through the HTTP surface closes the breaker -> /healthz 200."""
    from k3stpu.serve.server import InferenceServer, make_app

    chaos = FaultInjector()
    server = InferenceServer(
        model_name="transformer-tiny", seq_len=64,
        continuous_batching=True, breaker_threshold=2,
        breaker_cooldown_s=0.6, watchdog_s=120.0, chaos=chaos)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    gen = {"prompt_tokens": [[1, 2, 3]], "max_new_tokens": 2}
    try:
        code, _, _ = _post(url + "/v1/generate", gen)  # warm; closed
        assert code == 200
        assert _get(url + "/healthz")[0] == 200

        chaos.arm("decode_dispatch", times=2,
                  exc=InjectedFault("injected backend failure"))
        for _ in range(2):
            code, _, body = _post(url + "/v1/generate", gen)
            # Crash-only containment: the backend failure surfaces as a
            # JSON 500, never a hung connection.
            assert code == 500, body
        assert chaos.fired("decode_dispatch") == 2

        code, _, body = _get(url + "/healthz")
        assert code == 503
        assert b"circuit breaker open" in body
        code, headers, _ = _post(url + "/v1/generate", gen)
        assert code == 503
        assert float(headers["Retry-After"]) >= 1
        # A rejected request still echoes its trace context — the
        # client's retry chain stays correlated across the 503s.
        tid = "ab" * 16
        code, headers, _ = _post(
            url + "/v1/generate", gen,
            headers={"traceparent": f"00-{tid}-{'cd' * 8}-01"})
        assert code == 503
        assert headers["traceparent"].split("-")[1] == tid
        # Liveness stays green: an open breaker must NOT crash-loop the
        # pod (restart would not fix a poisoned backend faster).
        assert _get(url + "/livez")[0] == 200
        metrics = _get(url + "/metrics")[2].decode()
        assert "k3stpu_breaker_state 2" in metrics
        assert "k3stpu_breaker_trips_total 1" in metrics

        time.sleep(0.7)  # cooldown -> half-open reads as READY
        assert _get(url + "/healthz")[0] == 200
        code, _, _ = _post(url + "/v1/generate", gen)  # the probe
        assert code == 200
        metrics = _get(url + "/metrics")[2].decode()
        assert "k3stpu_breaker_state 0" in metrics
    finally:
        httpd.shutdown()
        server.close()


# --- SIGTERM drain under chaos (satellite: graceful-drain coverage) ------


def test_sigterm_drain_finishes_inflight_rejects_new_exits_in_deadline():
    """SIGTERM lands while a streamed generate is mid-flight (an injected
    2.5s dispatch stall holds it open): the stream still finishes, new
    /v1 work and /healthz answer 503 during the drain, and the process
    exits 0 within --drain-deadline-s."""
    import os
    import signal
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # Deliberately REPLACE PYTHONPATH (see test_serve.py's SIGTERM test:
    # the dev box's sitecustomize would re-register the TPU tunnel).
    env["PYTHONPATH"] = repo_root
    env["JAX_PLATFORMS"] = "cpu"
    env["K3STPU_CHAOS"] = "decode_dispatch:stall_s=2.5:times=1"
    proc = subprocess.Popen(
        [sys.executable, "-m", "k3stpu.serve.server", "--model",
         "transformer-tiny", "--seq-len", "32", "--port", str(port),
         "--no-warmup", "--continuous-batching",
         "--drain-deadline-s", "20"],
        env=env, text=True, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    stream_result = {}
    try:
        deadline = time.time() + 120
        while True:
            if proc.poll() is not None:
                out, _ = proc.communicate()
                raise AssertionError(
                    f"server exited rc={proc.returncode}: {out[-2000:]}")
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=5):
                    break
            except Exception:
                assert time.time() < deadline, "server never came up"
                time.sleep(0.3)

        def stream_client():
            body = json.dumps({"prompt_tokens": [[1, 2, 3]],
                               "max_new_tokens": 4,
                               "stream": True}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/generate", data=body,
                headers={"Content-Type": "application/json"})
            try:
                last = None
                with urllib.request.urlopen(req, timeout=180) as r:
                    for line in r:
                        if line.startswith(b"data: "):
                            last = json.loads(line[6:])
                stream_result["last"] = last
            except Exception as e:  # noqa: BLE001
                stream_result["error"] = repr(e)

        t = threading.Thread(target=stream_client, daemon=True)
        t.start()
        # Give the request time to enter the server (the injected stall
        # then holds its first decode dispatch open ~2.5s; on a cold
        # compile the window is even wider — either way it is in flight).
        time.sleep(1.0)
        proc.send_signal(signal.SIGTERM)
        time.sleep(0.3)  # let the drain flag land
        # New work is rejected while the stream drains... (and the
        # drain-503 still echoes the caller's trace id, so a retrying
        # client correlates the rejection with its request)
        drain_tid = "ef" * 16
        code, headers, body = _post(
            f"http://127.0.0.1:{port}/v1/generate",
            {"prompt_tokens": [[4, 5]], "max_new_tokens": 2},
            timeout=30,
            headers={"traceparent": f"00-{drain_tid}-{'12' * 8}-01"})
        assert code == 503, body
        assert headers["traceparent"].split("-")[1] == drain_tid
        # ...and readiness drops so the endpoint leaves the Service.
        assert _get(f"http://127.0.0.1:{port}/healthz")[0] == 503
        t.join(timeout=120)
        assert not t.is_alive(), "stream client stuck through drain"
        out, _ = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out[-2000:]
    assert "draining" in out and "drained; bye" in out
    # The in-flight stream finished cleanly mid-drain.
    assert stream_result.get("last", {}).get("done") is True, stream_result
