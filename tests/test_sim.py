"""Fleet digital twin (ISSUE 19): deterministic discrete-event sim that
drives the REAL policy code.

The tests here pin three contracts:

1. IDENTITY — the sim runs the same function/class objects the live
   fleet runs (not lookalikes): ``SchedulerMixin._admission_walk``,
   ``AdmissionRejected``, ``predict_ttft``/``admission_retry_after``,
   ``Router``/``HashRing``, ``DecisionPolicy``, ``SloEngine``.
2. DETERMINISM — same (scenario, seed) → byte-identical report, both
   through the API and through ``python -m k3stpu.sim --json``.
3. BEHAVIOR — the cooldowns-disabled regression reproduces autoscaler
   oscillation while shipped defaults pass the same trace; the fault
   matrix covers every chaos point; wedged telemetry holds scale-down
   via the scrape-coverage veto; and (slow) the 1000-replica acceptance
   soak meets the interactive TTFT SLO with zero lost requests.
"""

import dataclasses
import json
import random

import pytest

from k3stpu import chaos
from k3stpu.autoscaler.controller import DecisionPolicy
from k3stpu.autoscaler.signals import FleetSignals, ReplicaSample
from k3stpu.sim import calibrate, faults, report, scenarios, traces
from k3stpu.sim.clock import EventQueue, VirtualClock
from k3stpu.sim.fleet import (
    DEFAULT_DOWN_WINDOW_S,
    DEFAULT_UP_WINDOW_S,
    FleetSim,
)
from k3stpu.sim.replica import SimReplica, real_policy


def _mini_fleet(**overrides) -> FleetSim:
    """A tiny wired (not run) fleet for structural assertions."""
    sc = scenarios.get_scenario("smoke")
    sc = dataclasses.replace(sc, replicas_start=3, **overrides)
    return FleetSim(sc, seed=0, trace=[], costs=calibrate.CostModel())


# --- identity: the twin runs the real code ------------------------------


def test_admission_walk_is_the_real_scheduler_method():
    from k3stpu.serve.scheduler import AdmissionRejected, SchedulerMixin
    _mini_fleet()  # first SimReplica init binds the class attribute
    assert SimReplica._admission_walk is SchedulerMixin._admission_walk
    assert real_policy()["AdmissionRejected"] is AdmissionRejected


def test_router_policy_and_slo_objects_are_real():
    from k3stpu.router.ring import HashRing
    from k3stpu.router.router import Router
    import k3stpu.obs.slo as slo
    import k3stpu.sim.replica as sim_replica
    fleet = _mini_fleet()
    assert type(fleet.router) is Router
    assert type(fleet.router._ring) is HashRing
    assert type(fleet.policy) is DecisionPolicy
    assert sim_replica.predict_ttft is slo.predict_ttft
    assert sim_replica.admission_retry_after is slo.admission_retry_after
    assert type(fleet.slo_engine) is slo.SloEngine


def test_sim_replica_exposition_parses_via_real_parser():
    fleet = _mini_fleet()
    r = next(iter(fleet.replicas.values()))
    r.h_ttft.observe(0.3)
    r.h_wait.observe(0.05)
    s = r.sample(0.0)
    assert s.ok
    assert s.pages_total == r.pages_total
    assert s.pages_free == r.pages_free
    assert s.ttft_p50_s is not None and s.ttft_p50_s > 0.0
    # A wedged replica scrapes exactly like a dead endpoint.
    r.wedged_until = 10.0
    assert not r.sample(5.0).ok
    assert r.sample(15.0).ok


# --- the fault matrix covers every chaos point --------------------------


def test_fault_matrix_covers_every_known_chaos_point():
    missing = set(chaos.KNOWN_POINTS) - set(faults.SIM_FAULT_EFFECTS)
    assert not missing, (
        f"chaos points with no simulated blast radius: {sorted(missing)} "
        f"— teach k3stpu/sim/faults.py their containment contract")


def test_full_matrix_schedule_is_deterministic_and_complete():
    urls = [f"http://sim-{i:05d}" for i in range(4)]
    a = faults.full_matrix_schedule(random.Random(7), urls, 10.0, 90.0)
    b = faults.full_matrix_schedule(random.Random(7), urls, 10.0, 90.0)
    assert a == b
    assert {e.kind for e in a} == set(faults.SIM_FAULT_EFFECTS)
    assert all(10.0 <= e.t < 90.0 for e in a)


# --- chaos scripted form (point@n:K) ------------------------------------


def test_chaos_scripted_form_fires_on_exactly_the_kth_hit():
    inj = chaos.FaultInjector.from_env("page_alloc@n:3")
    inj.fire("page_alloc")
    inj.fire("page_alloc")
    with pytest.raises(chaos.InjectedFault):
        inj.fire("page_alloc")
    inj.fire("page_alloc")  # once, then never again
    assert inj.fired("page_alloc") == 1


def test_chaos_scripted_form_rejects_conflicts():
    with pytest.raises(ValueError):
        chaos.FaultInjector.from_env("page_alloc@n:2:times=3")
    with pytest.raises(ValueError):
        chaos.FaultInjector.from_env("page_alloc@n")
    with pytest.raises(ValueError):
        chaos.FaultInjector.from_env("page_alloc@n:0")


# --- determinism: same seed, byte-identical report ----------------------


def test_same_seed_byte_identical_report():
    runs = []
    for _ in range(2):
        fleet = scenarios.run_scenario("smoke", seed=11, max_requests=120)
        runs.append(report.canonical_json(report.build_report(fleet)))
    assert runs[0] == runs[1]
    other = scenarios.run_scenario("smoke", seed=12, max_requests=120)
    assert report.canonical_json(report.build_report(other)) != runs[0]


def test_cli_writes_byte_identical_json(tmp_path):
    from k3stpu.sim.__main__ import main
    outs = []
    for name in ("a.json", "b.json"):
        path = tmp_path / name
        rc = main(["--scenario", "smoke", "--seed", "5",
                   "--requests", "100", "--json", str(path)])
        assert rc == 0
        outs.append(path.read_bytes())
    assert outs[0] == outs[1]
    doc = json.loads(outs[0])
    assert doc["schema"] == "k3stpu-sim-report-v1"
    assert doc["requests"]["total"] == 100


# --- the virtual clock is monotone and seq-deterministic ----------------


def test_event_queue_orders_ties_by_schedule_order():
    clock = VirtualClock()
    q = EventQueue(clock)
    seen = []
    q.schedule(1.0, lambda t: seen.append("a"))
    q.schedule(1.0, lambda t: seen.append("b"))
    q.schedule(0.5, lambda t: seen.append("c"))
    q.run_until(2.0)
    assert seen == ["c", "a", "b"]
    with pytest.raises(ValueError):
        clock.advance_to(0.1)


def test_event_queue_run_all_detects_reschedule_leak():
    q = EventQueue(VirtualClock())

    def forever(t):
        q.schedule(t + 1.0, forever)

    q.schedule(0.0, forever)
    with pytest.raises(RuntimeError, match="self-rescheduling"):
        q.run_all(50.0)


# --- trace schema: loadgen --record-arrivals round-trips ----------------


def test_arrival_recorder_roundtrips_into_sim_trace(tmp_path):
    from k3stpu.serve.loadgen import ArrivalRecorder
    rec = ArrivalRecorder()
    payloads = [
        {"prompt_tokens": [[1] * 40], "max_new_tokens": 8,
         "session": "s-1", "priority": "interactive"},
        {"prompt_tokens": [[2] * 90], "max_new_tokens": 16,
         "priority": "batch"},
    ]
    for i, p in enumerate(payloads):
        rec.note(100.0 + i * 0.25, json.dumps(p).encode())
    path = tmp_path / "arrivals.json"
    assert rec.dump(str(path)) == 2
    reqs = traces.load_trace(str(path))
    assert [r["t"] for r in reqs] == [0.0, 0.25]
    assert reqs[0]["prompt_tokens"] == 40
    assert reqs[0]["session"] == "s-1"
    assert reqs[1]["priority"] == "batch"
    # Replayed traces get the degenerate per-shape prefix backfill.
    assert reqs[0]["prefix_id"] == 40 % 1009
    assert reqs[0]["prefix_len"] == 16


def test_generated_traces_are_seed_stable():
    prof = traces.diurnal_profile(60.0, 2.0, 6.0)
    a = traces.generate(random.Random(3), duration_s=60.0, profile=prof)
    b = traces.generate(random.Random(3), duration_s=60.0, profile=prof)
    assert a == b and len(a) > 0


# --- wedged telemetry: the scrape-coverage veto holds scale-down --------


def test_wedged_telemetry_vetoes_scale_down():
    fleet = _mini_fleet()
    wedged = next(iter(fleet.replicas.values()))
    wedged.wedged_until = 100.0
    sig = fleet._collect(50.0)
    assert sig.scraped == len(fleet.members) - 1
    desired, reasons = fleet.policy.decide(sig, len(fleet.members), 50.0)
    assert desired == len(fleet.members)
    assert any("coverage" in r for r in reasons)


# --- the oscillation regression pair ------------------------------------


def test_cooldowns_disabled_reproduces_oscillation():
    fleet = scenarios.run_scenario("regress-cooldown-off", seed=0)
    osc = fleet.oscillations()
    assert osc, "cooldowns-off run failed to reproduce flapping"
    flips = {o["flip"] for o in osc}
    assert "down->up" in flips or "up->down" in flips
    for o in osc:
        assert o["gap_s"] < o["window_s"]


def test_shipped_cooldowns_pass_the_same_trace():
    fleet = scenarios.run_scenario("regress-cooldown", seed=0)
    assert fleet.oscillations() == []
    assert fleet.counters["lost"] == 0
    assert fleet.scale_log, "scenario never actuated — not a regression"


# --- property: DecisionPolicy never flips inside the windows ------------


@pytest.mark.parametrize("seed", [0, 1, 2, 7, 11, 23])
def test_policy_never_flips_direction_within_cooldown_window(seed):
    """Randomized signal sequences (including the bursty alternation
    the adversarial sweep used to break the per-direction cool-down):
    after ANY actuation, the opposite direction must stay vetoed for
    that direction's full window."""
    rng = random.Random(seed)
    policy = DecisionPolicy(min_replicas=1, max_replicas=10)
    current = rng.randrange(1, 11)
    t = 0.0
    last = None  # (t, direction)
    for _ in range(400):
        t += rng.uniform(0.5, 7.0)
        hot = rng.random() < 0.5
        sample = ReplicaSample(
            "r", ok=True,
            queue_depth=rng.uniform(5.0, 50.0) if hot
            else rng.uniform(0.0, 0.4),
            pages_free=80, pages_total=100,
            queue_wait_p50_s=0.0, ttft_p50_s=0.0)
        desired, _reasons = policy.decide(
            FleetSignals([sample]), current, t)
        if desired == current:
            continue
        direction = "up" if desired > current else "down"
        if last is not None and last[1] != direction:
            window = (policy.scale_up_cooldown_s if direction == "up"
                      else policy.scale_down_cooldown_s)
            assert t - last[0] >= window, (
                f"flip {last[1]}->{direction} after {t - last[0]:.1f}s "
                f"inside the {window:.0f}s window (seed {seed})")
        policy.note_scaled(direction, t)
        last = (t, direction)
        current = desired


# --- faulted mid-size run: containment holds ----------------------------


def test_fault_matrix_run_applies_all_faults_and_loses_nothing():
    sc = scenarios.get_scenario("diurnal")
    sc = dataclasses.replace(sc, duration_s=150.0, max_requests=900,
                             replicas_start=6,
                             profile=traces.diurnal_profile(150.0, 3.0,
                                                            10.0))
    fleet = scenarios.build_run(sc, seed=4)
    fleet.run()
    rep = report.build_report(fleet)
    assert rep["faults"]["scheduled"] == len(faults.SIM_FAULT_EFFECTS)
    assert rep["faults"]["applied"] == rep["faults"]["scheduled"]
    assert fleet.counters["lost"] == 0
    assert fleet.counters["crashes"] >= 3  # rank/coordinator/replica
    done = (fleet.counters["completed"] + fleet.counters["aborted"])
    assert done == fleet.counters["total"]


# --- the acceptance soak (slow) -----------------------------------------


@pytest.mark.slow
def test_thousand_replica_diurnal_meets_slo_with_zero_loss():
    """ISSUE 19 acceptance: a 1000-replica diurnal-ramp scenario with
    the full chaos fault matrix, REAL DecisionPolicy/Ring/admission (by
    identity — asserted above), meets the interactive TTFT SLO with
    zero lost requests on shipped policy defaults. 30k requests here
    keeps the suite bounded; ``bench.py --sim`` runs the full 100k."""
    from k3stpu.serve.scheduler import SchedulerMixin
    fleet = scenarios.run_scenario("diurnal-1000", seed=0,
                                   max_requests=30_000)
    assert SimReplica._admission_walk is SchedulerMixin._admission_walk
    rep = report.build_report(fleet)
    assert rep["requests"]["lost"] == 0
    assert rep["faults"]["applied"] == rep["faults"]["scheduled"] > 0
    att = rep["latency"]["interactive"]["attainment"]
    assert att is not None and att >= 0.999, rep["latency"]
    assert rep["autoscaler"]["oscillations"] == []
    assert (DEFAULT_UP_WINDOW_S, DEFAULT_DOWN_WINDOW_S) == (15.0, 60.0)
