"""tpu-container-runtime: OCI spec rewriting + runc passthrough.

Spec-diff unit tests (SURVEY.md §7 step 1) against the fake host tree — no
TPU, no containerd. The binary is built on demand from native/.
"""

import json
import os
import stat
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUILD_DIR = os.path.join(REPO, "native", "build")
BIN = os.path.join(BUILD_DIR, "tpu-container-runtime")


@pytest.fixture(scope="session")
def runtime_bin():
    subprocess.run(
        ["cmake", "-S", os.path.join(REPO, "native"), "-B", BUILD_DIR],
        check=True, capture_output=True,
    )
    subprocess.run(
        ["cmake", "--build", BUILD_DIR], check=True, capture_output=True
    )
    return BIN


def base_spec(env=()):
    return {
        "ociVersion": "1.0.2",
        "process": {
            "args": ["python", "-m", "k3stpu.probe"],
            "env": ["PATH=/usr/bin"] + list(env),
        },
        "root": {"path": "rootfs"},
        "mounts": [
            {"destination": "/proc", "type": "proc", "source": "proc"},
        ],
        "linux": {"namespaces": [{"type": "pid"}]},
    }


def run_patch(runtime_bin, bundle, *extra):
    out = subprocess.run(
        [runtime_bin, "patch", "--bundle", str(bundle), "--dry-run", *extra],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    return json.loads(out.stdout), out.stderr


def write_bundle(tmp_path, spec, name="bundle"):
    bundle = tmp_path / name
    bundle.mkdir(exist_ok=True)
    (bundle / "config.json").write_text(json.dumps(spec))
    return bundle


def test_injects_devices_mounts_env(runtime_bin, fake_host_root, tmp_path):
    bundle = write_bundle(tmp_path, base_spec(env=["TPU_VISIBLE_CHIPS=all"]))
    patched, log = run_patch(
        runtime_bin, bundle, "--host-root", str(fake_host_root)
    )
    env = patched["process"]["env"]
    assert "TPU_VISIBLE_CHIPS=all" in env
    assert "TPU_CHIPS_PER_PROCESS_BOUNDS=1,1,4" in env
    assert "TPU_LIBRARY_PATH=/lib/libtpu.so" in env
    assert any(e.startswith("TPU_ACCELERATOR_TYPE=tpu-v5e-4") for e in env)

    dev_paths = [d["path"] for d in patched["linux"]["devices"]]
    assert dev_paths == [f"/dev/accel{i}" for i in range(4)]
    allows = patched["linux"]["resources"]["devices"]
    assert all(rule["allow"] and rule["access"] == "rwm" for rule in allows)

    libtpu_mounts = [
        m for m in patched["mounts"] if m["destination"] == "/lib/libtpu.so"
    ]
    assert len(libtpu_mounts) == 1
    assert libtpu_mounts[0]["source"].endswith("/usr/lib/libtpu.so")
    assert "ro" in libtpu_mounts[0]["options"]
    assert "injected=1" in log


def test_visible_chips_subset(runtime_bin, fake_host_root, tmp_path):
    bundle = write_bundle(tmp_path, base_spec(env=["TPU_VISIBLE_CHIPS=1,3"]))
    patched, _ = run_patch(
        runtime_bin, bundle, "--host-root", str(fake_host_root)
    )
    dev_paths = [d["path"] for d in patched["linux"]["devices"]]
    assert dev_paths == ["/dev/accel1", "/dev/accel3"]
    assert "TPU_CHIPS_PER_PROCESS_BOUNDS=1,1,2" in patched["process"]["env"]


def test_no_request_no_injection(runtime_bin, fake_host_root, tmp_path):
    bundle = write_bundle(tmp_path, base_spec())
    patched, log = run_patch(
        runtime_bin, bundle, "--host-root", str(fake_host_root)
    )
    assert "devices" not in patched.get("linux", {})
    assert patched["process"]["env"] == ["PATH=/usr/bin"]
    assert "injected=0" in log


def test_annotation_triggers_injection(runtime_bin, fake_host_root, tmp_path):
    spec = base_spec()
    spec["annotations"] = {"tpu.google.com/inject": "true"}
    bundle = write_bundle(tmp_path, spec)
    patched, _ = run_patch(
        runtime_bin, bundle, "--host-root", str(fake_host_root)
    )
    assert len(patched["linux"]["devices"]) == 4


def test_idempotent(runtime_bin, fake_host_root, tmp_path):
    bundle = write_bundle(tmp_path, base_spec(env=["TPU_VISIBLE_CHIPS=all"]))
    first, _ = run_patch(runtime_bin, bundle, "--host-root", str(fake_host_root))
    (bundle / "config.json").write_text(json.dumps(first))
    second, _ = run_patch(
        runtime_bin, bundle, "--host-root", str(fake_host_root)
    )
    assert first == second


def test_create_patches_and_execs_runc(runtime_bin, fake_host_root, tmp_path):
    """End-to-end shape of the containerd call: `create --bundle X id` must
    rewrite config.json in place and exec the real runtime with argv intact."""
    bundle = write_bundle(tmp_path, base_spec(env=["TPU_VISIBLE_CHIPS=0"]))
    argv_log = tmp_path / "runc-argv"
    fake_runc = tmp_path / "fake-runc"
    fake_runc.write_text(f'#!/bin/sh\necho "$@" > {argv_log}\nexit 0\n')
    fake_runc.chmod(fake_runc.stat().st_mode | stat.S_IEXEC)

    env = dict(os.environ)
    env["TPU_CONTAINER_RUNTIME_RUNC"] = str(fake_runc)
    env["K3STPU_HOST_ROOT"] = str(fake_host_root)
    out = subprocess.run(
        [runtime_bin, "--log", "/dev/null", "create", "--bundle", str(bundle),
         "probe-pod-1"],
        capture_output=True, text=True, env=env,
    )
    assert out.returncode == 0, out.stderr
    assert argv_log.read_text().split() == [
        "--log", "/dev/null", "create", "--bundle", str(bundle), "probe-pod-1",
    ]
    patched = json.loads((bundle / "config.json").read_text())
    assert [d["path"] for d in patched["linux"]["devices"]] == ["/dev/accel0"]


def test_non_create_passthrough(runtime_bin, tmp_path):
    """`state`/`delete`/... must not touch any spec, just exec runc."""
    argv_log = tmp_path / "runc-argv"
    fake_runc = tmp_path / "fake-runc"
    fake_runc.write_text(f'#!/bin/sh\necho "$@" > {argv_log}\nexit 3\n')
    fake_runc.chmod(fake_runc.stat().st_mode | stat.S_IEXEC)
    env = dict(os.environ)
    env["TPU_CONTAINER_RUNTIME_RUNC"] = str(fake_runc)
    out = subprocess.run(
        [runtime_bin, "state", "some-container"],
        capture_output=True, text=True, env=env,
    )
    assert out.returncode == 3  # fake runc's exit code propagates via exec
    assert argv_log.read_text().split() == ["state", "some-container"]


def test_malformed_spec_does_not_block_container(runtime_bin, tmp_path):
    """A broken config.json must not wedge non-TPU pods: log + exec runc."""
    bundle = tmp_path / "bundle"
    bundle.mkdir()
    (bundle / "config.json").write_text("{not json")
    fake_runc = tmp_path / "fake-runc"
    fake_runc.write_text("#!/bin/sh\nexit 0\n")
    fake_runc.chmod(fake_runc.stat().st_mode | stat.S_IEXEC)
    env = dict(os.environ)
    env["TPU_CONTAINER_RUNTIME_RUNC"] = str(fake_runc)
    out = subprocess.run(
        [runtime_bin, "create", "--bundle", str(bundle), "c1"],
        capture_output=True, text=True, env=env,
    )
    assert out.returncode == 0
    assert "patch skipped" in out.stderr
