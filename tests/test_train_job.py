"""train_job entry point: runs steps, checkpoints, and resumes (CPU mesh)."""

import json

from k3stpu.parallel import train_job


def _run(capsys, argv):
    rc = train_job.main(argv)
    assert rc == 0
    return [json.loads(line) for line in
            capsys.readouterr().out.strip().splitlines()]


def test_train_then_resume(tmp_path, capsys):
    ckpt = str(tmp_path / "ckpt")
    base = ["--model", "tiny", "--steps", "4", "--ckpt-dir", ckpt,
            "--ckpt-every", "2", "--batch", "8", "--seq", "32"]

    events = _run(capsys, base)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "train_start"
    assert kinds.count("step") == 4
    assert "checkpoint" in kinds
    losses = [e["loss"] for e in events if e["event"] == "step"]
    assert losses[-1] < losses[0]  # it actually optimizes

    # Second invocation resumes at step 4 and only runs the remaining 2.
    events = _run(capsys, ["--model", "tiny", "--steps", "6",
                           "--ckpt-dir", ckpt, "--ckpt-every", "2",
                           "--batch", "8", "--seq", "32"])
    kinds = [e["event"] for e in events]
    (resume,) = [e for e in events if e["event"] == "resume"]
    assert resume["step"] == 4
    assert resume["verify"].startswith("verified")  # manifest checked
    assert kinds.count("step") == 2
    steps = [e["step"] for e in events if e["event"] == "step"]
    assert steps == [5, 6]


def test_train_without_ckpt_dir(capsys):
    events = _run(capsys, ["--model", "tiny", "--steps", "2",
                           "--batch", "8", "--seq", "32"])
    kinds = [e["event"] for e in events]
    assert kinds.count("step") == 2
    assert "checkpoint" not in kinds
