"""Pipeline parallelism vs sequential block application (exactness), on the
8-device virtual CPU mesh. The pipeline is exact — microbatching plus the
ring handoff must reproduce the unstaged forward bit-for-bit (fp32)."""

import numpy as np

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from k3stpu.models.transformer import Block, transformer_lm_tiny
from k3stpu.parallel.pipeline import (
    pipeline_forward,
    place_stacked_params,
    stack_block_params,
    unstack_block_params,
)

# float32 compute: the gradient-exactness test needs tolerances far below
# bf16 rounding noise (~8e-3), and the pipeline is meant to be numerically
# exact, not just close, so all comparisons here run in fp32.
CFG = transformer_lm_tiny(n_layers=4, max_seq_len=32,
                          dtype=jnp.float32).config


def _block_apply(block_params, h):
    return Block(CFG).apply({"params": block_params}, h)


def _make_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("pipe",))


def _blocks_and_input(seed=0, batch=8, seq=16):
    rng = jax.random.key(seed)
    x = jax.random.normal(rng, (batch, seq, CFG.d_model), jnp.float32)
    block_params = []
    for i in range(CFG.n_layers):
        p = Block(CFG).init(jax.random.key(100 + i), x)["params"]
        block_params.append(p)
    return block_params, x


def _sequential(block_params, x):
    h = x
    for p in block_params:
        h = _block_apply(p, h)
    return h


@pytest.mark.parametrize("stages,micro", [(2, 4), (4, 8)])
def test_pipeline_matches_sequential(stages, micro):
    mesh = _make_mesh(stages)
    block_params, x = _blocks_and_input()
    stacked = place_stacked_params(
        stack_block_params(block_params, stages), mesh)
    out = pipeline_forward(mesh, _block_apply, stacked, x, micro)
    ref = _sequential(block_params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_stack_roundtrip():
    block_params, _ = _blocks_and_input()
    stacked = stack_block_params(block_params, 2)
    back = unstack_block_params(stacked, 2, 2)
    for orig, rt in zip(block_params, back):
        for a, b in zip(jax.tree.leaves(orig), jax.tree.leaves(rt)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipeline_is_differentiable():
    """Grads through the scan+ppermute pipeline == grads of the plain
    stack (training through pp is viable)."""
    mesh = _make_mesh(2)
    block_params, x = _blocks_and_input(batch=4)
    stacked = place_stacked_params(stack_block_params(block_params, 2), mesh)

    def loss_pipe(stacked, x):
        return jnp.sum(pipeline_forward(mesh, _block_apply, stacked, x, 4) ** 2)

    def loss_seq(params_list, x):
        return jnp.sum(_sequential(params_list, x) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked, x)
    g_seq = jax.grad(loss_seq)(block_params, x)
    g_pipe_list = unstack_block_params(g_pipe, 2, 2)
    for gp, gs in zip(g_pipe_list, g_seq):
        for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-4, rtol=3e-4)


def test_bad_microbatch_count_raises():
    mesh = _make_mesh(2)
    block_params, x = _blocks_and_input()
    stacked = place_stacked_params(stack_block_params(block_params, 2), mesh)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_forward(mesh, _block_apply, stacked, x, 3)


def test_bad_stage_count_raises():
    block_params, _ = _blocks_and_input()
    with pytest.raises(ValueError, match="not divisible"):
        stack_block_params(block_params, 3)
