"""Node labeling: pure label math + the dry-run CLI surface."""

import json
import os
import subprocess
import sys

from k3stpu.discovery.labeler import labels_for_inventory
from k3stpu.utils.chips import enumerate_chips

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_labels_for_v5e_pod(fake_host_root):
    inv = enumerate_chips(root=str(fake_host_root))
    labels = labels_for_inventory(inv)
    assert labels == {
        "google.com/tpu.present": "true",
        "google.com/tpu.count": "4",
        "google.com/tpu.generation": "tpu-v5e",
        "google.com/tpu.topology": "2x2",
        "feature.node.kubernetes.io/pci-1ae0.present": "true",
    }


def test_labels_no_tpu(tmp_path):
    labels = labels_for_inventory(enumerate_chips(root=str(tmp_path)))
    assert labels["google.com/tpu.present"] == "false"
    assert labels["feature.node.kubernetes.io/pci-1ae0.present"] == "false"
    # Null values delete stale labels via strategic-merge-patch.
    assert labels["google.com/tpu.count"] is None
    assert labels["google.com/tpu.topology"] is None


def test_labeler_cli_dry_run(fake_host_root):
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    out = subprocess.run(
        [sys.executable, "-m", "k3stpu.discovery.labeler", "--once",
         "--dry-run", "--host-root", str(fake_host_root)],
        capture_output=True, text=True, cwd=REPO, timeout=60, env=env)
    assert out.returncode == 0, out.stderr
    line = [l for l in out.stdout.splitlines() if l.startswith("LABELS_JSON")]
    labels = json.loads(line[0].split(" ", 1)[1])
    assert labels["google.com/tpu.topology"] == "2x2"
