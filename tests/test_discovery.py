"""Node labeling: pure label math + the dry-run CLI surface."""

import json
import os
import subprocess
import sys

from k3stpu.discovery import labeler
from k3stpu.discovery.labeler import health_labels, labels_for_inventory
from k3stpu.utils.chips import enumerate_chips

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_labels_for_v5e_pod(fake_host_root):
    inv = enumerate_chips(root=str(fake_host_root))
    labels = labels_for_inventory(inv)
    assert labels == {
        "google.com/tpu.present": "true",
        "google.com/tpu.count": "4",
        "google.com/tpu.generation": "tpu-v5e",
        "google.com/tpu.topology": "2x2",
        "feature.node.kubernetes.io/pci-1ae0.present": "true",
    }


def test_labels_no_tpu(tmp_path):
    labels = labels_for_inventory(enumerate_chips(root=str(tmp_path)))
    assert labels["google.com/tpu.present"] == "false"
    assert labels["feature.node.kubernetes.io/pci-1ae0.present"] == "false"
    # Null values delete stale labels via strategic-merge-patch.
    assert labels["google.com/tpu.count"] is None
    assert labels["google.com/tpu.topology"] is None


def test_health_labels_pure():
    assert health_labels("stale-telemetry") == {
        "google.com/tpu.healthy": "false",
        "google.com/tpu.health.state": "stale-telemetry",
    }
    assert health_labels("wedged")["google.com/tpu.healthy"] == "false"
    # Recovery: null values -> strategic-merge label DELETES, so a
    # healthy node carries no health labels at all.
    assert health_labels("healthy") == {
        "google.com/tpu.healthy": None,
        "google.com/tpu.health.state": None,
    }


def _health_dry_run(fake_host_root, drops, capsys):
    rc = labeler.main([
        "--once", "--dry-run", "--health",
        "--host-root", str(fake_host_root), "--drop-dir", str(drops)])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("LABELS_JSON ")]
    return json.loads(lines[-1].split(" ", 1)[1])


def test_labeler_health_transition_patch_shapes(fake_host_root, tmp_path,
                                                capsys):
    """healthy -> unhealthy -> recovered: the dry-run patch pins "false"
    while degraded and null-deletes both keys on recovery, with the
    inventory labels untouched throughout."""
    import time

    drops = tmp_path / "drops"
    drops.mkdir()

    def write(ts):
        with open(drops / "metrics-pod-1.json", "w") as f:
            json.dump({"ts": ts, "devices": [
                {"index": 0, "bytes_in_use": 1, "bytes_limit": 2,
                 "duty_cycle_pct": 10}]}, f)

    write(time.time())
    labels = _health_dry_run(fake_host_root, drops, capsys)
    assert labels["google.com/tpu.healthy"] is None
    assert labels["google.com/tpu.health.state"] is None

    write(time.time() - 10_000)  # telemetry goes stale
    labels = _health_dry_run(fake_host_root, drops, capsys)
    assert labels["google.com/tpu.healthy"] == "false"
    assert labels["google.com/tpu.health.state"] == "stale-telemetry"
    assert labels["google.com/tpu.present"] == "true"  # inventory intact
    assert labels["google.com/tpu.count"] == "4"

    write(time.time())  # recovered
    labels = _health_dry_run(fake_host_root, drops, capsys)
    assert labels["google.com/tpu.healthy"] is None
    assert labels["google.com/tpu.health.state"] is None


def test_labeler_without_health_flag_has_no_health_keys(fake_host_root,
                                                        capsys):
    rc = labeler.main(["--once", "--dry-run",
                       "--host-root", str(fake_host_root)])
    assert rc == 0
    line = [l for l in capsys.readouterr().out.splitlines()
            if l.startswith("LABELS_JSON ")][0]
    labels = json.loads(line.split(" ", 1)[1])
    assert "google.com/tpu.healthy" not in labels


def test_labeler_cli_dry_run(fake_host_root):
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    out = subprocess.run(
        [sys.executable, "-m", "k3stpu.discovery.labeler", "--once",
         "--dry-run", "--host-root", str(fake_host_root)],
        capture_output=True, text=True, cwd=REPO, timeout=60, env=env)
    assert out.returncode == 0, out.stderr
    line = [l for l in out.stdout.splitlines() if l.startswith("LABELS_JSON")]
    labels = json.loads(line[0].split(" ", 1)[1])
    assert labels["google.com/tpu.topology"] == "2x2"
