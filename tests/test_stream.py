"""Streaming generation: engine submit_stream + the SSE /v1/generate route.

The correctness bar mirrors the engine's: streamed deltas, concatenated
per row, must be a prefix of EXACTLY the tokens the same request returns
non-streaming (which is itself pinned to ``generate()``). The latency
bar: the first event per request carries one token per row straight off
the prefill logits — time-to-first-token must not wait for the full
decode budget. CPU-JAX stand-in per SURVEY.md §4.
"""

import json
import threading
import time
import urllib.request
from http.server import ThreadingHTTPServer

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.models.generate import generate
from k3stpu.models.transformer import transformer_lm_tiny
from k3stpu.serve.engine import GenerateEngine
from k3stpu.serve.server import InferenceServer, make_app


def _model_and_params(max_seq_len=64):
    model = transformer_lm_tiny(max_seq_len=max_seq_len)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                           train=False)
    return model, variables["params"]


def _solo(model, params, prompt, budget):
    out = generate(model, params,
                   jnp.asarray(np.array([prompt], np.int32)),
                   jnp.array([len(prompt)], jnp.int32), budget,
                   temperature=0.0)
    return np.asarray(out)[0].tolist()


@pytest.fixture(scope="module")
def stream_engine():
    model, params = _model_and_params()
    # decode_block > 1: deltas arrive in blocks, the shape streaming must
    # handle (and the default serving configuration).
    engine = GenerateEngine(model, params, slots=4, decode_block=3)
    yield model, params, engine
    engine.close()


def _drain(events):
    """Consume a stream; return (per-row concatenated deltas, final)."""
    rows: "dict[int, list[int]]" = {}
    final = None
    n_deltas = 0
    for ev in events:
        if ev["done"]:
            final = ev["tokens"]
        else:
            n_deltas += 1
            for r, toks in ev["rows"].items():
                rows.setdefault(int(r), []).extend(toks)
    assert final is not None, "stream ended without a done event"
    return rows, final, n_deltas


def test_stream_matches_submit_greedy(stream_engine):
    model, params, engine = stream_engine
    prompts = [[5, 6, 7], [9, 10, 11, 12]]
    rows, final, n_deltas = _drain(
        engine.submit_stream(prompts, max_new_tokens=7))
    assert final == [_solo(model, params, p, 7) for p in prompts]
    # Deltas are a prefix of the final (eos-extended) tokens; with no eos
    # hit they are the whole row.
    for r, streamed in rows.items():
        assert streamed == final[r][:len(streamed)]
        assert len(streamed) == 7  # no eos: everything streamed
    # First event from prefill + ceil(6/3) decode blocks = at least 3.
    assert n_deltas >= 3


def test_stream_first_event_is_prefill_token(stream_engine):
    model, params, engine = stream_engine
    it = engine.submit_stream([[3, 4]], max_new_tokens=6)
    first = next(it)
    assert first["done"] is False
    # TTFT semantics: exactly one token, before any decode dispatch.
    assert list(first["rows"].values()) == [[_solo(model, params,
                                                  [3, 4], 6)[0]]]
    _drain(it)  # let the request finish cleanly


def test_stream_eos_stops_deltas(stream_engine):
    model, params, engine = stream_engine
    prompt = [7, 8, 9]
    full = _solo(model, params, prompt, 8)
    eos = full[2]  # force an eos hit mid-budget (position 2 of 8)
    rows, final, _ = _drain(
        engine.submit_stream([prompt], max_new_tokens=8, eos_id=eos))
    # Streamed tokens stop at the eos token (inclusive); the final row is
    # eos-extended to the budget exactly like submit().
    assert rows[0] == full[:3]
    assert final[0] == full[:3] + [eos] * 5
    got = engine.submit([prompt], max_new_tokens=8, eos_id=eos)
    assert final == got


def test_stream_concurrent_with_plain_submit(stream_engine):
    model, params, engine = stream_engine
    results = {}

    def plain():
        results["plain"] = engine.submit([[20, 21]], max_new_tokens=9)

    t = threading.Thread(target=plain)
    t.start()
    rows, final, _ = _drain(
        engine.submit_stream([[30, 31, 32]], max_new_tokens=9))
    t.join(timeout=60)
    assert results["plain"] == [_solo(model, params, [20, 21], 9)]
    assert final == [_solo(model, params, [30, 31, 32], 9)]
    assert rows[0] == final[0]


def test_stream_validation_eager(stream_engine):
    _, _, engine = stream_engine
    with pytest.raises(ValueError):
        engine.submit_stream([], max_new_tokens=4)
    with pytest.raises(ValueError):
        engine.submit_stream([[1]] * (engine.slots + 1), max_new_tokens=4)


def test_stream_closed_engine_rejects():
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=2)
    engine.close()
    with pytest.raises(RuntimeError):
        engine.submit_stream([[1, 2]], max_new_tokens=4)


def test_stream_sampled_rows_complete(stream_engine):
    """Sampled (non-greedy) streaming: deltas must still concatenate to
    the final tokens (values are stochastic; structure is the bar)."""
    _, _, engine = stream_engine
    rows, final, _ = _drain(engine.submit_stream(
        [[2, 3, 4]], max_new_tokens=6, temperature=1.0, top_k=8))
    assert len(final) == 1 and len(final[0]) == 6
    assert rows[0] == final[0][:len(rows[0])]


def test_stream_abandoned_cancels_request():
    """Closing the stream iterator (what the server does on client
    disconnect) must cancel the in-flight request: its slots free within
    an expiry cycle instead of decoding the rest of the budget for
    nobody, and the engine keeps serving exactly."""
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=2)
    try:
        engine.submit([[1, 2]], max_new_tokens=2)  # warm the programs
        real = engine._decode_step

        def slow_step(*args, **kwargs):  # make the 40-token decode long
            time.sleep(0.02)
            return real(*args, **kwargs)

        engine._decode_step = slow_step
        it = engine.submit_stream([[5, 6, 7]], max_new_tokens=40)
        assert next(it)["done"] is False  # admitted and producing
        it.close()  # consumer walks away mid-stream
        deadline = time.time() + 30
        while len(engine._free_slots()) != engine.slots:
            assert time.time() < deadline, "abandoned stream never reaped"
            time.sleep(0.05)
        engine._decode_step = real
        got = engine.submit([[5, 6, 7]], max_new_tokens=4)
        assert got == [_solo(model, params, [5, 6, 7], 4)]
    finally:
        engine.close()


def test_soak_streaming_pcache_adapters_under_chaos(monkeypatch):
    """The round-4 surfaces under randomized chaos TOGETHER — streaming
    consumers that vanish mid-stream, repeat prompts riding the prompt
    cache, mixed adapters in one slot batch, tiny deadlines, injected
    decode faults. Invariants at the end: no slot/reserved-row leak, the
    cache respects its capacity, and the engine still serves exact
    greedy output per adapter."""
    import random

    # pytest's prepend import mode already has tests/ on sys.path.
    from test_multi_lora import _multi_lora_setup, _solo

    _, _, _, ml, mlparams = _multi_lora_setup()
    engine = GenerateEngine(ml, mlparams, slots=4, decode_block=3,
                            chunk_prefill=8, prompt_cache=3)
    try:
        engine.submit([[1, 2]], max_new_tokens=2)  # warm
        real = engine._decode_block_step
        calls = {"n": 0}

        def flaky(*args, **kwargs):
            calls["n"] += 1
            if calls["n"] % 17 == 0:
                raise RuntimeError("injected decode fault")
            return real(*args, **kwargs)

        monkeypatch.setattr(engine, "_decode_block_step", flaky)
        pool = [[5, 6, 7], [5, 6, 7, 8], [9, 10], list(range(1, 14))]
        stop = time.time() + 15.0

        def client(seed):
            rng = random.Random(seed)
            while time.time() < stop:
                prompt = rng.choice(pool)
                aid = rng.randrange(3)
                budget = rng.randint(1, 10)
                try:
                    if rng.random() < 0.4:
                        it = engine.submit_stream(
                            [prompt], max_new_tokens=budget,
                            adapter_id=aid,
                            timeout_s=rng.choice([0.05, 5.0, 30.0]))
                        if rng.random() < 0.4:
                            next(it, None)
                            it.close()  # consumer walks away
                        else:
                            for _ in it:
                                pass
                    else:
                        engine.submit(
                            [prompt], max_new_tokens=budget,
                            adapter_id=aid,
                            temperature=rng.choice([0.0, 0.8]),
                            timeout_s=rng.choice([0.05, 5.0, 30.0]))
                except (TimeoutError, RuntimeError, StopIteration):
                    pass  # chaos is the point; invariants checked below

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "stuck client"

        deadline = time.time() + 30
        while len(engine._free_slots()) != engine.slots:
            assert time.time() < deadline, (
                f"slot leak: {engine._free_slots()} free; "
                f"active={engine._active}, owner={engine._owner}")
            time.sleep(0.05)
        assert not engine._reserved.any(), "reserved-row leak"
        s = engine.stats()
        assert s["pcache_entries"] <= 3 and s["pcache_bytes"] > 0
        monkeypatch.setattr(engine, "_decode_block_step", real)
        for aid in (0, 1, 2):
            assert engine.submit([[5, 6, 7]], max_new_tokens=5,
                                 adapter_id=aid) \
                == [_solo(ml, mlparams, [5, 6, 7], 5, aid)], \
                f"post-soak exactness, adapter {aid}"
    finally:
        engine.close()


# --- HTTP/SSE route ----------------------------------------------------


@pytest.fixture(scope="module")
def engine_server():
    server = InferenceServer(model_name="transformer-tiny", seq_len=64,
                             batch_window_ms=0.0, continuous_batching=True,
                             engine_slots=4, decode_block=3,
                             shard_devices=1)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", server
    httpd.shutdown()
    server.close()


def _post_json(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post_sse(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    frames = []
    with urllib.request.urlopen(req, timeout=120) as r:
        assert r.headers.get("Content-Type") == "text/event-stream"
        for line in r:
            if line.startswith(b"data: "):
                frames.append(json.loads(line[6:]))
    return frames


def test_sse_route_matches_plain(engine_server):
    url, _ = engine_server
    body = {"prompt_tokens": [[1, 2, 3], [4, 5]], "max_new_tokens": 6}
    status, plain = _post_json(url + "/v1/generate", body)
    assert status == 200, plain
    frames = _post_sse(url + "/v1/generate", dict(body, stream=True))
    assert frames[-1]["done"] is True
    assert frames[-1]["tokens"] == plain["tokens"]
    assert len(frames) >= 3  # prefill event + >=1 block + done
    rows: "dict[int, list[int]]" = {}
    for f in frames[:-1]:
        assert f["done"] is False
        for r, toks in f["rows"].items():
            rows.setdefault(int(r), []).extend(toks)
    for r, streamed in rows.items():
        assert streamed == plain["tokens"][r][:len(streamed)]


def test_sse_bad_args_clean_400(engine_server):
    url, _ = engine_server
    status, body = _post_json(
        url + "/v1/generate",
        {"prompt_tokens": [[]], "max_new_tokens": 4, "stream": True})
    assert status == 400
    assert "error" in body


def test_sse_fallback_without_engine():
    """No engine: the stream degrades to one final event with the plain
    route's exact tokens (uniform client API either way)."""
    server = InferenceServer(model_name="transformer-tiny", seq_len=32,
                             batch_window_ms=0.0, shard_devices=1)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        body = {"prompt_tokens": [[3, 4, 5]], "max_new_tokens": 4}
        _, plain = _post_json(url + "/v1/generate", body)
        frames = _post_sse(url + "/v1/generate", dict(body, stream=True))
        assert len(frames) == 1
        assert frames[0] == {"done": True, "tokens": plain["tokens"]}
    finally:
        httpd.shutdown()
        server.close()


def test_http_503_when_engine_at_capacity():
    """--max-pending over HTTP: the overloaded generate route answers a
    retryable 503 (Retry-After) instead of queueing, for both the plain
    and streaming forms, and serves again after the load drains."""
    server = InferenceServer(model_name="transformer-tiny", seq_len=64,
                             batch_window_ms=0.0, continuous_batching=True,
                             engine_slots=2, max_pending=1,
                             shard_devices=1)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        server.generate_tokens([[1, 2]], max_new_tokens=2)  # warm
        eng = server._engine
        # The server's engine dispatches through the k>1 block path
        # (decode_block=4 default) — slow THAT one; _decode_step is the
        # k==1 path and never runs here, so patching it holds nothing.
        real = eng._decode_block_step

        def slow_step(*args, **kwargs):
            time.sleep(0.05)
            return real(*args, **kwargs)

        eng._decode_block_step = slow_step
        # Budget 48 x 50 ms per (4-token) dispatch ~ 600 ms of held
        # capacity — the probe requests below must land inside it even
        # on a loaded CI box.
        hold = threading.Thread(
            target=lambda: _post_json(
                url + "/v1/generate",
                {"prompt_tokens": [[5, 6]], "max_new_tokens": 48}))
        hold.start()
        deadline = time.time() + 10
        while not eng.at_capacity():
            assert time.time() < deadline, "holder never admitted"
            time.sleep(0.02)
        status, body = _post_json(
            url + "/v1/generate",
            {"prompt_tokens": [[7, 8]], "max_new_tokens": 2})
        assert status == 503 and "capacity" in body["error"]
        assert "k3stpu_engine_rejected_total 1" \
            in server.prometheus_metrics()
        st2, body2 = _post_json(
            url + "/v1/generate",
            {"prompt_tokens": [[7, 8]], "max_new_tokens": 2,
             "stream": True})
        assert st2 == 503 and "capacity" in body2["error"]
        hold.join(timeout=120)
        eng._decode_block_step = real
        status, body = _post_json(
            url + "/v1/generate",
            {"prompt_tokens": [[7, 8]], "max_new_tokens": 2})
        assert status == 200 and len(body["tokens"][0]) == 2
    finally:
        httpd.shutdown()
        server.close()


def test_stream_stats_counted(engine_server):
    url, server = engine_server
    before = server.model_card()["stats"]["gen_requests"]
    _post_sse(url + "/v1/generate",
              {"prompt_tokens": [[8, 9]], "max_new_tokens": 4,
               "stream": True})
    assert server.model_card()["stats"]["gen_requests"] == before + 1
