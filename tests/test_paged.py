"""Paged KV cache (k3stpu/serve/engine.py + models/transformer.py).

The correctness bar is BIT-EXACTNESS: an engine with a paged pool +
block tables must emit exactly the tokens the dense per-slot engine
emits — greedy, sampled (same seed), chunked prefill, and every prompt
cache path (miss / exact hit / prefix hit). The capacity win must come
from the allocator alone, never from numerics.

The safety bar is the allocator: random admit/finish/cancel storms may
never leak a page, double-free one, or alias one across slot chains
without a matching refcount; prompt-cache-pinned pages must survive
pool pressure while referenced. CPU-JAX stand-in per SURVEY.md §4.
"""

import json
import os
import random
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.models.generate import generate
from k3stpu.models.transformer import transformer_lm_tiny
from k3stpu.serve.engine import GenerateEngine, _PageAllocator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def mp():
    model = transformer_lm_tiny(max_seq_len=64)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                           train=False)
    return model, variables["params"]


def _solo(model, params, prompt, budget):
    out = generate(model, params,
                   jnp.asarray(np.array([prompt], np.int32)),
                   jnp.array([len(prompt)], jnp.int32), budget,
                   temperature=0.0)
    return np.asarray(out)[0].tolist()


def _pair(model, params, *, page_size=8, **kw):
    """A dense engine and a paged engine with identical scheduling
    parameters (same seed => identical sampling-key folds)."""
    dense = GenerateEngine(model, params, seed=0, **kw)
    paged = GenerateEngine(model, params, seed=0, page_size=page_size,
                           **kw)
    return dense, paged


def _assert_page_invariants(engine):
    """Idle-engine allocator accounting, checked exactly: every page's
    refcount equals its appearances across live slot chains plus the
    prompt-cache pins holding it. Equality is simultaneously the leak
    proof (rc>0 but unowned fails), the alias proof (a page in two
    chains without two refs fails), and the pin proof (a cached entry's
    pages count toward rc, so reclaim-while-referenced fails)."""
    alloc = engine._alloc
    expect = {}
    for chain in engine._chains:
        for p in chain:
            expect[p] = expect.get(p, 0) + 1
    for entry in engine._pcache.values():
        for p in entry[0]:
            expect[p] = expect.get(p, 0) + 1
    for p in range(1, alloc.num_pages):
        assert alloc.refcount(p) == expect.get(p, 0), (
            f"page {p}: rc={alloc.refcount(p)} but "
            f"{expect.get(p, 0)} live references")
    assert alloc.free == alloc.total - sum(1 for v in expect.values()
                                           if v > 0)
    pinned = {}
    for entry in engine._pcache.values():
        for p in entry[0]:
            pinned[p] = pinned.get(p, 0) + 1
    assert engine._pinned == pinned


# --- bit-exactness: paged == dense on every serving path ----------------


def test_paged_matches_dense_greedy(mp):
    model, params = mp
    dense, paged = _pair(model, params, slots=4)
    try:
        cases = [
            [[5, 6, 7]],
            [[3, 4], [9, 10, 11, 12, 13]],               # ragged batch
            [list(range(1, 20)), [40], [7, 8, 9]],        # 3 rows
        ]
        for prompts in cases:
            want = dense.submit(prompts, max_new_tokens=6)
            assert paged.submit(prompts, max_new_tokens=6) == want
            # dense itself is pinned to solo generate() — anchor the
            # chain so a shared bug in both engines can't hide.
            for w, p in zip(want, prompts):
                assert w == _solo(model, params, p, 6)
    finally:
        dense.close()
        paged.close()


def test_paged_matches_dense_sampled(mp):
    """Same seed, same fold sequence => sampled tokens must be
    IDENTICAL, not merely plausible."""
    model, params = mp
    dense, paged = _pair(model, params, slots=4)
    try:
        for kw in ({"temperature": 0.9, "top_k": 20},
                   {"temperature": 1.0, "top_p": 0.9},
                   {"temperature": 0.7, "top_k": 16, "top_p": 0.95}):
            want = dense.submit([[9, 10, 11], [4, 5]], max_new_tokens=8,
                                **kw)
            assert paged.submit([[9, 10, 11], [4, 5]], max_new_tokens=8,
                                **kw) == want
    finally:
        dense.close()
        paged.close()


def test_paged_matches_dense_chunked_prefill(mp):
    model, params = mp
    dense, paged = _pair(model, params, slots=4, chunk_prefill=8,
                         decode_block=3)
    try:
        cases = [
            [list(range(1, 20))],                 # 19 tokens: 3 chunks
            [list(range(30, 41)), [7, 8]],        # ragged across chunks
            [list(range(1, 24))],
        ]
        for prompts in cases:
            want = dense.submit(prompts, max_new_tokens=7)
            assert paged.submit(prompts, max_new_tokens=7) == want
        assert paged.stats()["adm_chunks"] >= 2
    finally:
        dense.close()
        paged.close()


def test_paged_matches_dense_prompt_cache_paths(mp):
    """Miss, exact hit, and prefix hit must all be bit-exact AND take
    the same cache path as dense (counters compared, not just tokens) —
    a paged engine silently downgrading hits to misses would pass a
    tokens-only check while giving up the zero-copy win."""
    model, params = mp
    dense, paged = _pair(model, params, slots=4, prompt_cache=4)
    try:
        prompt = [5, 6, 7, 8, 9, 10, 11, 12, 13]    # 9 toks: partial tail
        # miss -> insert
        want = dense.submit([prompt], max_new_tokens=6)
        assert paged.submit([prompt], max_new_tokens=6) == want
        # exact hit: same prompt again
        want = dense.submit([prompt], max_new_tokens=6)
        assert paged.submit([prompt], max_new_tokens=6) == want
        # prefix hit: cached prompt + a new tail
        ext = prompt + [20, 21, 22]
        want = dense.submit([ext], max_new_tokens=6)
        assert paged.submit([ext], max_new_tokens=6) == want
        ds, ps = dense.stats(), paged.stats()
        for k in ("pcache_hits", "pcache_prefix_hits", "pcache_misses"):
            assert ps[k] == ds[k], (k, ps[k], ds[k])
        assert ps["pcache_hits"] >= 1 and ps["pcache_prefix_hits"] >= 1
        assert ps["pcache_shared_pages"] >= 1, (
            "a prefix hit must actually share pages zero-copy")
        _assert_page_invariants(paged)
    finally:
        dense.close()
        paged.close()


def test_paged_matches_dense_submit_samples(mp):
    model, params = mp
    dense, paged = _pair(model, params, slots=4, prompt_cache=2)
    try:
        sol = _solo(model, params, [5, 6, 7], 6)
        # Mirror every request on BOTH engines: the sampling key folds
        # on the step counter, so an asymmetric history would desync
        # the fold sequence and void the bit-exactness comparison.
        for eng in (dense, paged):
            assert eng.submit_samples([5, 6, 7], 3, max_new_tokens=6,
                                      temperature=0.0) == [sol] * 3
        want = dense.submit_samples([9, 10, 11], 4, max_new_tokens=10,
                                    temperature=1.0, top_k=12)
        got = paged.submit_samples([9, 10, 11], 4, max_new_tokens=10,
                                   temperature=1.0, top_k=12)
        assert got == want
        _assert_page_invariants(paged)
    finally:
        dense.close()
        paged.close()


def test_paged_engine_on_mesh_matches_dense(mp):
    """Paged pool sharded on its kv-head axis over the 8-device CPU
    mesh (data=2 x model=4): greedy output and the prompt-cache hit
    must match the single-device dense engine exactly."""
    from k3stpu.parallel.mesh import make_mesh
    from k3stpu.parallel.sharding import shard_params

    model, params = mp
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device CPU backend")
    mesh = make_mesh(8, model_parallelism=4)
    sharded, _ = shard_params(params, mesh)
    dense = GenerateEngine(model, params, slots=4, seed=0, prompt_cache=2)
    paged = GenerateEngine(model, sharded, slots=4, seed=0, prompt_cache=2,
                           page_size=8, mesh=mesh)
    try:
        prompt = [5, 6, 7, 8, 9]
        want = dense.submit([prompt], max_new_tokens=8)
        assert paged.submit([prompt], max_new_tokens=8) == want
        # hit path over the mesh stays exact
        assert paged.submit([prompt], max_new_tokens=8) == want
        assert paged.stats()["pcache_hits"] == 1
    finally:
        dense.close()
        paged.close()


# --- static shapes: zero steady-state recompiles ------------------------


def _jit_cache_total():
    return sum(f._cache_size() for f in vars(GenerateEngine).values()
               if hasattr(f, "_cache_size"))


def test_zero_steady_state_recompiles(mp):
    """Page assignments ride in as TRACED arrays, so after one warmup
    pass over each program shape, further traffic — different tokens,
    different page layouts, cache hits, evictions — must hit the jit
    cache every time. Growth here is the paged design's failure mode
    (a shape leak recompiles per request and erases the win)."""
    model, params = mp
    engine = GenerateEngine(model, params, slots=4, seed=0,
                            prompt_cache=4, page_size=8)
    try:
        def traffic(base):
            # One structural pass: single row, ragged pair, fan-out,
            # exact hit, prefix hit — same SHAPES each round, different
            # token values and page placements.
            p = [base + i for i in range(9)]
            engine.submit([p], max_new_tokens=6)
            engine.submit([p], max_new_tokens=6)              # exact hit
            engine.submit([p + [base + 40, base + 41, base + 42]],
                          max_new_tokens=6)                    # prefix hit
            engine.submit([[base, base + 1],
                           [base + 2, base + 3, base + 4]],
                          max_new_tokens=5)
            engine.submit_samples([base + 7, base + 8], 3,
                                  max_new_tokens=6, temperature=0.9)

        traffic(5)                       # warmup: compiles everything
        before = _jit_cache_total()
        for base in (60, 120, 180):      # steady state: 3 more rounds
            traffic(base)
        assert _jit_cache_total() == before, (
            "steady-state traffic recompiled a paged program")
        _assert_page_invariants(engine)
    finally:
        engine.close()


# --- allocator safety ---------------------------------------------------


def test_allocator_random_storm():
    """Model-checked random alloc/incref/decref storm: the allocator's
    visible state (free count, per-page refcount) must track a shadow
    model exactly at every step; fresh pages are never aliased, the
    sink page is never handed out, and a full drain restores the pool."""
    rng = random.Random(0)
    alloc = _PageAllocator(48)
    shadow = {}                  # page -> expected refcount
    held = []                    # chains we owe a decref for

    for _ in range(3000):
        roll = rng.random()
        if roll < 0.45:
            n = rng.randint(1, 6)
            pages = alloc.alloc(n)
            if pages is None:
                assert n > alloc.free, "refused an alloc that fits"
            else:
                assert len(set(pages)) == n and 0 not in pages
                for p in pages:
                    assert shadow.get(p, 0) == 0, f"aliased page {p}"
                    shadow[p] = 1
                held.append(list(pages))
        elif roll < 0.70 and held:
            chain = rng.choice(held)
            alloc.incref(chain)
            for p in chain:
                shadow[p] += 1
            held.append(list(chain))
        elif held:
            chain = held.pop(rng.randrange(len(held)))
            alloc.decref(chain)
            for p in chain:
                shadow[p] -= 1
        live = sum(1 for v in shadow.values() if v > 0)
        assert alloc.free == alloc.total - live
        for p, v in shadow.items():
            assert alloc.refcount(p) == v

    for chain in held:
        alloc.decref(chain)
    assert alloc.free == alloc.total

    with pytest.raises(RuntimeError, match="double free"):
        alloc.decref([1])
    with pytest.raises(RuntimeError, match="incref on free"):
        alloc.incref([1])


def test_pinned_pages_survive_pool_pressure(mp):
    """Pool pressure may evict LRU prompt-cache entries, but a pinned
    page backing a SURVIVING entry must never be reclaimed — the proof
    is that a hit on the survivor still returns bit-exact tokens after
    the pressure (reclaimed-and-rewritten pages would corrupt it)."""
    model, params = mp
    # 11 usable pages, 2 slots: big requests must squeeze the pcache.
    engine = GenerateEngine(model, params, slots=2, seed=0,
                            prompt_cache=8, page_size=8, num_pages=12)
    try:
        keep = [5, 6, 7]
        want = engine.submit([keep], max_new_tokens=4)   # miss + pin
        engine.submit([[30, 31, 32]], max_new_tokens=4)  # second entry
        # Pressure: needs most of the pool; forces LRU eviction.
        engine.submit([list(range(40, 57))], max_new_tokens=8)
        for entry in engine._pcache.values():
            for p in entry[0]:
                assert engine._alloc.refcount(p) >= 1, (
                    "pinned page reclaimed while referenced")
        hits0 = engine.stats()["pcache_hits"]
        assert engine.submit([keep], max_new_tokens=4) == want
        assert engine.stats()["pcache_hits"] == hits0 + 1
        _assert_page_invariants(engine)
    finally:
        engine.close()


def test_oversized_request_rejected_not_deadlocked(mp):
    model, params = mp
    engine = GenerateEngine(model, params, slots=2, seed=0,
                            page_size=8, num_pages=5)  # 4 usable pages
    try:
        with pytest.raises(ValueError, match="pages"):
            engine.submit([list(range(1, 30))], max_new_tokens=20)
        # ...and the rejection leaked nothing.
        assert engine._alloc.free == engine._alloc.total
        got = engine.submit([[5, 6, 7]], max_new_tokens=4)
        assert got == [_solo(model, params, [5, 6, 7], 4)]
    finally:
        engine.close()


@pytest.mark.slow
def test_paged_engine_storm_soak(mp):
    """Randomized concurrent admit/finish/cancel storm on a TIGHT pool:
    mixed submit/submit_samples, random eos (early row finishes -> early
    page release), tiny random deadlines (mid-decode cancellation), and
    prompt-cache churn. Afterwards: every slot chain released, exact
    refcount accounting (no leak, no alias, pins intact), and the
    engine still serves exact greedy output."""
    model, params = mp
    engine = GenerateEngine(model, params, slots=4, seed=0,
                            prompt_cache=4, page_size=8, num_pages=25,
                            decode_block=2)
    try:
        engine.submit([[1, 2]], max_new_tokens=2)  # warm the programs
        outcomes = {"done": 0, "timeout": 0, "rejected": 0}
        lock = threading.Lock()
        stop = time.time() + 12.0

        def client(seed):
            rng = random.Random(seed)
            while time.time() < stop:
                budget = rng.randint(1, 10)
                try:
                    if rng.random() < 0.3:
                        engine.submit_samples(
                            [rng.randint(1, 40), rng.randint(1, 40)],
                            rng.randint(1, 3), max_new_tokens=budget,
                            temperature=1.0,
                            timeout_s=rng.choice([0.02, 5.0, 30.0]))
                    else:
                        prompts = [
                            [rng.randint(1, 40)
                             for _ in range(rng.randint(1, 14))]
                            for _ in range(rng.randint(1, 2))]
                        engine.submit(
                            prompts, max_new_tokens=budget,
                            temperature=rng.choice([0.0, 0.8]),
                            eos_id=rng.choice([None, 3]),
                            timeout_s=rng.choice([0.02, 5.0, 30.0]))
                    key = "done"
                except TimeoutError:
                    key = "timeout"
                except ValueError:
                    key = "rejected"   # oversized for the tight pool
                with lock:
                    outcomes[key] += 1

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not any(t.is_alive() for t in threads), "stuck client"
        assert outcomes["done"] > 0, outcomes

        deadline = time.time() + 30
        while len(engine._free_slots()) != engine.slots:
            assert time.time() < deadline, "slot leak after the storm"
            time.sleep(0.05)
        assert all(not c for c in engine._chains), (
            "slot chain survived its request")
        _assert_page_invariants(engine)
        got = engine.submit([[5, 6, 7]], max_new_tokens=4)
        assert got == [_solo(model, params, [5, 6, 7], 4)]
        _assert_page_invariants(engine)
    finally:
        engine.close()


# --- bench mode ---------------------------------------------------------


@pytest.mark.slow
def test_serve_paged_bench_capacity():
    """bench.py --serve-paged: one JSON line; >=2x concurrent slots at
    the fixed HBM budget with decode tokens/s within 10% of dense."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ""
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--serve-paged"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert out.returncode == 0, out.stderr
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"must print exactly one line, got: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "serve_paged_capacity_ratio"
    assert rec["value"] >= 2.0, rec
    assert rec["detail"]["decode_tps_ratio"] >= 0.9, rec["detail"]
