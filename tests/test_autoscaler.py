"""Autoscaler tier (k3stpu/autoscaler, docs/AUTOSCALING.md): signal
parsing, decision policy (hysteresis / cool-downs / bounds), membership
watchers, actuators, the scale_actuate chaos containment, and the
drain-before-kill protocol end to end.

Most of the file is jax-free: replicas are scripted exposition servers
and actuator fleets are stub processes, because the controller is
deliberately model-blind. The one real-server test
(test_drain_before_kill_restores_warm_on_survivor) runs two in-process
InferenceServers against a shared spill dir to prove the property the
whole subsystem exists for: a session released with spill=true during
a scale-down serves its next turn WARM on a surviving replica.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from k3stpu.autoscaler import (
    AutoscalerObs,
    Controller,
    DecisionPolicy,
    DryRunActuator,
    FleetSignals,
    KubernetesActuator,
    LocalProcessActuator,
    ReplicaSample,
    ScaleError,
    make_autoscaler_app,
    parse_replica_metrics,
    scrape,
)
from k3stpu.chaos import FaultInjector
from k3stpu.router import (
    EndpointsWatcher,
    FileWatcher,
    Router,
    endpoints_to_urls,
    make_router_app,
    parse_replicas_text,
)

# --- signal parsing --------------------------------------------------------


def _exposition(queue_depth=0.0, pages_free=-1.0, pages_total=0.0,
                ttft_bucket=None, wait_bucket=None):
    """A minimal but real v0.0.4 exposition. ``ttft_bucket`` /
    ``wait_bucket`` put all observations into ONE bucket upper bound so
    the expected p50 is knowable without re-deriving interpolation."""
    lines = [
        "# HELP k3stpu_engine_queue_depth q",
        "# TYPE k3stpu_engine_queue_depth gauge",
        f"k3stpu_engine_queue_depth {queue_depth}",
        "# HELP k3stpu_engine_pages_free f",
        "# TYPE k3stpu_engine_pages_free gauge",
        f"k3stpu_engine_pages_free {pages_free}",
        "# HELP k3stpu_pages_total t",
        "# TYPE k3stpu_pages_total gauge",
        f"k3stpu_pages_total {pages_total}",
    ]
    for name, bucket in (("k3stpu_request_ttft_seconds", ttft_bucket),
                         ("k3stpu_request_queue_wait_seconds",
                          wait_bucket)):
        if bucket is None:
            continue
        le, count = bucket
        lines += [
            f"# HELP {name} h",
            f"# TYPE {name} histogram",
            f'{name}_bucket{{le="{le}"}} {count}',
            f'{name}_bucket{{le="+Inf"}} {count}',
            f"{name}_sum {le * count}",
            f"{name}_count {count}",
        ]
    return "\n".join(lines) + "\n"


def test_parse_replica_metrics_gauges_and_histograms():
    text = _exposition(queue_depth=7.0, pages_free=20, pages_total=80,
                       ttft_bucket=(2.0, 10), wait_bucket=(0.5, 4))
    s = parse_replica_metrics("http://r0", text)
    assert s.ok
    assert s.queue_depth == 7.0
    assert s.pages_free_frac == pytest.approx(0.25)
    # All mass in the first finite bucket: p50 interpolates inside it.
    assert 0.0 < s.ttft_p50_s <= 2.0
    assert 0.0 < s.queue_wait_p50_s <= 0.5


def test_parse_replica_metrics_non_paged_and_missing_families():
    s = parse_replica_metrics("http://r0", _exposition())
    assert s.ok and s.pages_free_frac == -1.0
    assert s.queue_depth == 0.0 and s.ttft_p50_s == 0.0
    # Families absent entirely (an old build): still a usable sample.
    s2 = parse_replica_metrics("http://r0", "# nothing here\n")
    assert s2.ok and s2.queue_depth == 0.0


def test_parse_tp_per_shard_pages_free_takes_min():
    """A tensor-parallel replica exposes per-shard pool gauges
    (k3stpu_serve_tp_pages_free{shard="i"}); the parser must take the
    MIN across shards — the tightest pool gates admission, and summing
    would overstate the fleet's headroom N-fold."""
    text = _exposition(pages_free=40, pages_total=80) + "\n".join([
        "# HELP k3stpu_serve_tp_pages_free f",
        "# TYPE k3stpu_serve_tp_pages_free gauge",
        'k3stpu_serve_tp_pages_free{shard="0"} 24',
        'k3stpu_serve_tp_pages_free{shard="1"} 8',
    ]) + "\n"
    s = parse_replica_metrics("http://r0", text)
    assert s.pages_free == 8.0          # min, not 32 (sum) or 24
    assert s.pages_free_frac == pytest.approx(0.1)
    # Monolithic replica (no per-shard family): the unlabeled engine
    # gauge still rules.
    s2 = parse_replica_metrics("http://r0",
                               _exposition(pages_free=40, pages_total=80))
    assert s2.pages_free == 40.0
    # And the policy sees the tight shard: a fleet whose TP replica is
    # page-starved aggregates to the starved fraction even when the
    # unlabeled gauge looks healthy.
    fleet = FleetSignals([s, s2])
    assert fleet.pages_free_frac == pytest.approx(0.1)


def test_scrape_unreachable_is_ok_false_not_raise():
    s = scrape("http://127.0.0.1:1", timeout_s=0.2)
    assert not s.ok


def test_fleet_aggregation_worst_case_bias():
    fleet = FleetSignals([
        ReplicaSample("a", ok=True, queue_depth=6.0, pages_free=50,
                      pages_total=100, queue_wait_p50_s=0.1,
                      ttft_p50_s=0.2),
        ReplicaSample("b", ok=True, queue_depth=2.0, pages_free=5,
                      pages_total=100, queue_wait_p50_s=0.9,
                      ttft_p50_s=3.0),
        ReplicaSample("c", ok=False),       # unreachable: excluded
    ])
    assert fleet.scraped == 2
    assert fleet.total_queue_depth == 8.0
    assert fleet.queue_depth_per_replica == 4.0   # mean of the LIVE two
    assert fleet.pages_free_frac == pytest.approx(0.05)   # WORST
    assert fleet.queue_wait_p50_s == 0.9          # WORST
    assert fleet.ttft_p50_s == 3.0                # WORST
    empty = FleetSignals([])
    assert empty.scraped == 0 and empty.queue_depth_per_replica == 0.0
    assert empty.pages_free_frac == -1.0


# --- decision policy -------------------------------------------------------


def _pressure(queue=0.0, pages=-1.0, wait=0.0, ttft=0.0):
    return FleetSignals([ReplicaSample(
        "r", ok=True, queue_depth=queue,
        pages_free=pages, pages_total=100 if pages >= 0 else 0,
        queue_wait_p50_s=wait, ttft_p50_s=ttft)])


def test_policy_queue_depth_sizes_proportionally():
    p = DecisionPolicy(max_replicas=8, queue_high=4.0)
    desired, reasons = p.decide(_pressure(queue=20.0), 1, 0.0)
    # ceil(20 / 4) = 5 replicas, one proportional step.
    assert desired == 5 and any("queue_depth" in r for r in reasons)


def test_policy_hysteresis_band_holds_steady():
    p = DecisionPolicy(queue_high=4.0, queue_low=0.5)
    # Between low and high: no move in either direction.
    desired, reasons = p.decide(_pressure(queue=2.0), 2, 0.0)
    assert desired == 2 and reasons == []


def test_policy_each_signal_triggers_one_step_up():
    for kw in ({"pages": 5.0}, {"wait": 2.0}, {"ttft": 5.0}):
        p = DecisionPolicy(max_replicas=4)
        desired, reasons = p.decide(_pressure(**kw), 2, 0.0)
        assert desired == 3, kw
        assert reasons, kw


def test_policy_down_requires_every_signal_idle():
    p = DecisionPolicy()
    # Idle queue but TTFT above half its bar: hold, don't shrink.
    assert p.decide(_pressure(queue=0.1, ttft=1.5), 3, 0.0)[0] == 3
    # Everything idle: one step down.
    assert p.decide(_pressure(queue=0.1), 3, 0.0)[0] == 2


def test_policy_cooldowns_cross_direction_windows():
    """Each direction keeps its own window LENGTH, but both windows
    measure from the last actuation in EITHER direction — the sim's
    adversarial sweep showed per-direction stamps alone permit an
    up→down flip seconds after a scale-up (burst ends, fleet reads
    idle, the replica just added is handed straight back)."""
    p = DecisionPolicy(scale_up_cooldown_s=10.0,
                       scale_down_cooldown_s=100.0)
    p.note_scaled("up", t0 := 50.0)
    d, reasons = p.decide(_pressure(queue=50.0), 2, t0 + 5.0)
    assert d == 2 and any("cool-down" in r for r in reasons)
    # The up actuation arms the DOWN window too: no immediate give-back.
    d, reasons = p.decide(_pressure(queue=0.1), 2, t0 + 5.0)
    assert d == 2 and any("cool-down" in r for r in reasons)
    # Past the down window (measured from the up actuation): shrink ok.
    assert p.decide(_pressure(queue=0.1), 2, t0 + 101.0)[0] == 1
    p.note_scaled("down", t0 + 101.0)
    # A down actuation arms BOTH windows at their own lengths: growth
    # waits out the (short) up window, shrink the (long) down window.
    assert p.decide(_pressure(queue=50.0), 2, t0 + 106.0)[0] == 2
    assert p.decide(_pressure(queue=0.1), 1 + 1, t0 + 106.0)[0] == 2
    assert p.decide(_pressure(queue=50.0), 2, t0 + 112.0)[0] > 2


def test_policy_bounds_clamp_and_repair():
    p = DecisionPolicy(min_replicas=2, max_replicas=3)
    assert p.decide(_pressure(queue=100.0), 3, 0.0)[0] == 3  # at max
    assert p.decide(_pressure(queue=0.0), 2, 0.0)[0] == 2    # at min
    assert p.decide(_pressure(), 1, 0.0)[0] == 2             # below min
    assert p.decide(_pressure(), 5, 0.0)[0] == 3             # above max


def test_policy_down_vetoed_without_full_scrape_coverage():
    p = DecisionPolicy()
    # Zero coverage (router briefly unreachable, empty membership):
    # every signal zero-fills to "idle" — the fleet holds, never
    # shrinks on no information.
    desired, reasons = p.decide(FleetSignals([]), 3, 0.0)
    assert desired == 3 and any("coverage" in r for r in reasons)
    # Partial coverage: one unreachable replica also vetoes the
    # all-idle claim (its signals are unknown, not zero).
    part = FleetSignals([
        ReplicaSample("a", ok=True, queue_depth=0.0),
        ReplicaSample("b", ok=False),
    ])
    desired, reasons = p.decide(part, 2, 0.0)
    assert desired == 2 and any("coverage" in r for r in reasons)
    # Full coverage of the same idle fleet shrinks as before.
    assert p.decide(_pressure(queue=0.0), 2, 0.0)[0] == 1


def test_controller_holds_when_fleet_view_is_empty():
    """k8s mode with the router unreachable: replica_urls() is empty,
    so a loaded fleet would read as idle — the step must report held,
    not kill a replica with no drain possible."""
    act = _StubActuator([])
    act.n = 2
    ctl = Controller(act, DecisionPolicy())
    report = ctl.step(now=0.0)
    assert report["action"] == "held"
    assert act.calls == [] and act.n == 2


def test_policy_validates_configuration():
    with pytest.raises(ValueError):
        DecisionPolicy(min_replicas=0)
    with pytest.raises(ValueError):
        DecisionPolicy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        DecisionPolicy(queue_high=1.0, queue_low=1.0)


# --- membership watchers ---------------------------------------------------


def test_parse_replicas_text_lines_commas_comments():
    text = ("http://a:1, http://b:2/\n"
            "# a comment line\n"
            "http://c:3  # trailing comment\n\n")
    assert parse_replicas_text(text) == [
        "http://a:1", "http://b:2", "http://c:3"]


def test_endpoints_to_urls_ready_only_sorted_deduped():
    doc = {"subsets": [
        {"addresses": [{"ip": "10.0.0.2"}, {"ip": "10.0.0.1"}],
         "notReadyAddresses": [{"ip": "10.0.0.9"}],
         "ports": [{"port": 8096}]},
        {"addresses": [{"ip": "10.0.0.1"}], "ports": [{"port": 8096}]},
    ]}
    assert endpoints_to_urls(doc) == [
        "http://10.0.0.1:8096", "http://10.0.0.2:8096"]
    assert endpoints_to_urls(doc, port=9000)[0] == "http://10.0.0.1:9000"
    assert endpoints_to_urls({}) == []


def _quiet_router(urls, **kw):
    # Long health period: the poller thread never fires inside a test,
    # so scripted/absent replicas keep their optimistic boot health.
    return Router(urls, health_period_s=3600.0, instance="test-as", **kw)


def test_file_watcher_hot_reloads_membership(tmp_path):
    path = tmp_path / "replicas.txt"
    path.write_text("http://127.0.0.1:7001\n")
    router = _quiet_router([], allow_empty=True)
    try:
        w = FileWatcher(router, str(path), period_s=3600.0)
        assert w.poll_once() == (1, 0)
        assert router.replicas() == ["http://127.0.0.1:7001"]
        # Unchanged mtime: no re-read, no churn.
        assert w.poll_once() == (0, 0)
        # Atomic rewrite (the actuator's handshake): swap the fleet.
        tmp = tmp_path / "replicas.txt.tmp"
        tmp.write_text("http://127.0.0.1:7002,http://127.0.0.1:7003\n")
        os.replace(tmp, path)
        w._mtime = None  # force past same-second mtime granularity
        assert w.poll_once() == (2, 1)
        assert router.replicas() == ["http://127.0.0.1:7002",
                                     "http://127.0.0.1:7003"]
        # Empty file: torn-write guard keeps the fleet.
        path.write_text("")
        w._mtime = None
        assert w.poll_once() == (0, 0)
        assert len(router.replicas()) == 2
        # File gone: no information, keep membership.
        path.unlink()
        assert w.poll_once() == (0, 0)
    finally:
        router.close()


def test_endpoints_watcher_reconciles_with_stubbed_fetch():
    docs = [
        {"subsets": [{"addresses": [{"ip": "10.0.0.1"}],
                      "ports": [{"port": 8096}]}]},
        None,  # apiserver flake -> keep membership
        {"subsets": [{"addresses": [{"ip": "10.0.0.1"},
                                    {"ip": "10.0.0.2"}],
                      "ports": [{"port": 8096}]}]},
    ]

    def fetch_doc():
        doc = docs.pop(0)
        if doc is None:
            raise OSError("apiserver down")
        return doc

    router = _quiet_router([], allow_empty=True)
    try:
        w = EndpointsWatcher(router, "ns", "svc", fetch_doc=fetch_doc,
                             period_s=3600.0)
        assert w.poll_once() == (1, 0)
        assert w.poll_once() == (0, 0)      # flake: unchanged
        assert len(router.replicas()) == 1
        assert w.poll_once() == (1, 0)
        assert sorted(router.replicas()) == [
            "http://10.0.0.1:8096", "http://10.0.0.2:8096"]
    finally:
        router.close()


def test_router_drain_excludes_new_placement_keeps_pins():
    urls = ["http://127.0.0.1:7101", "http://127.0.0.1:7102"]
    router = _quiet_router(urls)
    try:
        # Pin a session somewhere, then drain that replica.
        cands, _, _ = router.route({"session": "s1"}, b"{}")
        pinned = cands[0]
        router.commit_route("s1", pinned)
        assert router.set_replica_drain(pinned, True)
        assert router.pinned_sessions(pinned) == ["s1"]
        other = [u for u in urls if u != pinned][0]
        # New sessions place on the un-drained replica only...
        for i in range(8):
            c, _, _ = router.route({"session": f"n{i}"}, b"{}")
            assert c[0] == other
        # ...while the existing pin still routes to the draining one.
        c, reason, _ = router.route({"session": "s1"}, b"{}")
        assert c[0] == pinned and reason == "session"
        # Undrain restores placement; unknown replicas are refused.
        assert router.set_replica_drain(pinned, False)
        assert not router.set_replica_drain("http://nope:1", True)
        state = router.state()
        assert {r["url"]: r["draining"] for r in state["replicas"]} == {
            urls[0]: False, urls[1]: False}
    finally:
        router.close()


# --- scripted-fleet controller loop ----------------------------------------


class _ScriptedReplica:
    """An HTTP stand-in replica: /metrics serves a settable exposition,
    /debug/drain a settable in-flight count."""

    def __init__(self):
        self.text = _exposition()
        self.active = 0
        handler = self._make()
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def _make(self):
        rep = self

        class H(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path == "/metrics":
                    body = rep.text.encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path == "/debug/drain":
                    body = json.dumps(
                        {"active_http_requests": rep.active}).encode()
                    ctype = "application/json"
                else:
                    body, ctype = b"{}", "application/json"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        return H

    def close(self):
        self.httpd.shutdown()


class _StubActuator:
    """In-memory fleet: current() tracks scale_to; urls() mirrors a
    scripted replica list."""

    def __init__(self, urls):
        self._urls = list(urls)
        self.n = len(urls)
        self.calls = []

    def current(self):
        return self.n

    def urls(self):
        return self._urls[:self.n]

    def scale_to(self, n, victims=None):
        self.calls.append((n, victims))
        self.n = n


def test_controller_scales_up_on_queue_pressure():
    rep = _ScriptedReplica()
    try:
        rep.text = _exposition(queue_depth=20.0)
        act = _StubActuator([rep.url])
        ctl = Controller(act, DecisionPolicy(max_replicas=4,
                                             queue_high=4.0))
        report = ctl.step(now=0.0)
        assert report["action"] == "up"
        assert act.calls == [(4, None)]
        assert ctl.obs.desired_replicas.value == 4.0
        # Same pressure immediately after: cool-down holds.
        rep2 = [rep.url] * 4  # urls() now returns 4 entries
        act._urls = rep2
        report2 = ctl.step(now=1.0)
        assert report2["action"] in ("held", "none")
        assert len(act.calls) == 1
    finally:
        rep.close()


def test_controller_scale_down_drains_victim_first():
    reps = [_ScriptedReplica(), _ScriptedReplica()]
    try:
        act = _StubActuator([r.url for r in reps])
        ctl = Controller(act, DecisionPolicy(min_replicas=1),
                         drain_deadline_s=2.0, drain_poll_s=0.05)
        report = ctl.step(now=1000.0)
        assert report["action"] == "down"
        (n, victims), = act.calls
        assert n == 1
        # No router: the victim is the last replica, still drain-polled.
        assert victims == [reps[-1].url]
        assert ctl.obs.drain_duration.count == 1
    finally:
        for r in reps:
            r.close()


def test_chaos_scale_actuate_backs_off_keeps_last_known_good():
    rep = _ScriptedReplica()
    try:
        rep.text = _exposition(queue_depth=50.0)
        act = _StubActuator([rep.url])
        chaos = FaultInjector()
        chaos.arm("scale_actuate", times=1)
        ctl = Controller(act, DecisionPolicy(max_replicas=4), chaos=chaos,
                         backoff_s=30.0)
        report = ctl.step(now=0.0)
        assert report["action"] == "actuate_failed"
        assert chaos.fired("scale_actuate") == 1
        assert act.calls == [] and act.n == 1   # last-known-good kept
        assert ctl.obs.actuate_failures.value == 1
        # Inside the back-off window: no actuation attempt at all.
        report2 = ctl.step(now=10.0)
        assert report2["action"] == "backoff"
        assert act.calls == []
        # Past the window the same decision goes through.
        report3 = ctl.step(now=40.0)
        assert report3["action"] == "up"
        assert act.n == 4
    finally:
        rep.close()


class _ScriptedRouterState:
    """Router HTTP stand-in for the drain protocol: a session pins to
    the victim only AFTER the drain mark lands (the snapshot-vs-mark
    race the controller must survive), and /v1/session/release drops
    the pin."""

    def __init__(self, victim):
        self.victim = victim
        self.drained = False
        self.pins = {}
        self.released = []
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), self._make())
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def _make(self):
        rt = self

        class H(BaseHTTPRequestHandler):
            def _send(self, doc):
                body = json.dumps(doc).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._send({"replicas": [{"url": rt.victim}],
                            "pins": dict(rt.pins)})

            def do_POST(self):
                doc = json.loads(self.rfile.read(
                    int(self.headers.get("Content-Length", "0"))))
                if self.path == "/v1/admin/drain":
                    rt.drained = True
                    rt.pins["late-session"] = rt.victim
                elif self.path == "/v1/session/release":
                    rt.released.append(doc["session"])
                    rt.pins.pop(doc["session"], None)
                self._send({"ok": True})

            def log_message(self, *a):
                pass

        return H

    def close(self):
        self.httpd.shutdown()


def test_drain_enumerates_pins_after_mark():
    """A session that pins to the victim between any pre-mark snapshot
    and the drain mark must still be released: pins are enumerated
    after the mark is acknowledged and re-fetched until none remain."""
    rep = _ScriptedReplica()
    rt = _ScriptedRouterState(rep.url)
    try:
        ctl = Controller(_StubActuator([rep.url]), DecisionPolicy(),
                         router_url=rt.url, drain_deadline_s=5.0,
                         drain_poll_s=0.05)
        ctl._drain_victim(rep.url)
        assert rt.drained
        assert rt.released == ["late-session"]
        assert rt.pins == {}
    finally:
        rt.close()
        rep.close()


def test_autoscaler_obs_families_and_app_render_clean():
    obs = AutoscalerObs(instance="t")
    obs.on_signals(1.5, 0.4, 0.1, 0.2, scraped=2)
    obs.on_decision(3, 2)
    obs.on_scale("up")
    obs.on_drain(0.25)
    text = obs.render_prometheus()
    for fam in ("k3stpu_autoscaler_desired_replicas",
                "k3stpu_autoscaler_current_replicas",
                "k3stpu_autoscaler_scale_events_total",
                "k3stpu_autoscaler_signal_queue_depth",
                "k3stpu_autoscaler_drain_seconds",
                "k3stpu_build_info"):
        assert fam in text, fam
    assert 'direction="up"' in text
    om = obs.render_openmetrics()
    assert om.endswith("# EOF\n")
    # The controller's own HTTP surface serves them.
    ctl = Controller(_StubActuator([]), DecisionPolicy(), obs=obs)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                make_autoscaler_app(ctl))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            assert b"k3stpu_autoscaler_desired_replicas" in r.read()
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            assert json.loads(r.read())["ok"] is True
    finally:
        httpd.shutdown()


# --- actuators -------------------------------------------------------------

# A stand-in replica process: answers 200 on every GET (healthz), so
# LocalProcessActuator's spawn/health-wait/kill machinery is testable
# without jax or a model.
_STUB_SERVER = """
import sys
from http.server import BaseHTTPRequestHandler, HTTPServer
class H(BaseHTTPRequestHandler):
    def do_GET(self):
        body = b'{"ok": true}'
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
    def log_message(self, *a):
        pass
HTTPServer(("127.0.0.1", int(sys.argv[1])), H).serve_forever()
"""


def _stub_spawn(index, port):
    return [sys.executable, "-c", _STUB_SERVER, str(port)]


def _free_port_base():
    # A base unlikely to collide across test runs; the actuator binds
    # base+index so keep a spread.
    import random
    return random.randint(20000, 40000)


def test_local_process_actuator_scale_up_down(tmp_path):
    rf = str(tmp_path / "replicas.txt")
    act = LocalProcessActuator(_stub_spawn, base_port=_free_port_base(),
                               replicas_file=rf, ready_timeout_s=30.0,
                               kill_timeout_s=5.0)
    try:
        assert act.current() == 0
        assert parse_replicas_text(open(rf).read()) == []
        act.scale_to(2)
        assert act.current() == 2
        urls = act.urls()
        assert parse_replicas_text(open(rf).read()) == urls
        for u in urls:  # health-waited: immediately reachable
            with urllib.request.urlopen(u + "/healthz", timeout=5) as r:
                assert r.status == 200
        # Victim-directed scale-down: the named replica dies, the
        # other survives on ITS port (index-stable URLs).
        act.scale_to(1, victims=[urls[1]])
        assert act.urls() == [urls[0]]
        assert parse_replicas_text(open(rf).read()) == [urls[0]]
        with urllib.request.urlopen(urls[0] + "/healthz", timeout=5):
            pass
        act.scale_to(0)
        assert act.current() == 0
    finally:
        act.close()


def test_local_process_actuator_middle_victim_keeps_ports(tmp_path):
    """Killing a non-tail victim (the controller's fewest-pins pick can
    legitimately be a first/middle replica) must not shift survivors'
    URLs: each process keeps its port for life, and the next scale-up
    reuses the freed port instead of colliding with a survivor."""
    rf = str(tmp_path / "replicas.txt")
    act = LocalProcessActuator(_stub_spawn, base_port=_free_port_base(),
                               replicas_file=rf, ready_timeout_s=30.0,
                               kill_timeout_s=5.0)
    try:
        act.scale_to(3)
        u0, u1, u2 = act.urls()
        act.scale_to(2, victims=[u1])
        assert act.urls() == [u0, u2]
        assert parse_replicas_text(open(rf).read()) == [u0, u2]
        for u in (u0, u2):  # survivors still serve on THEIR ports
            with urllib.request.urlopen(u + "/healthz", timeout=5) as r:
                assert r.status == 200
        act.scale_to(3)  # spawns on the freed middle port
        assert act.urls() == [u0, u1, u2]
        with urllib.request.urlopen(u1 + "/healthz", timeout=5) as r:
            assert r.status == 200
    finally:
        act.close()


def test_local_process_actuator_spawn_failure_is_scale_error(tmp_path):
    act = LocalProcessActuator(
        lambda i, p: [sys.executable, "-c", "import sys; sys.exit(3)"],
        base_port=_free_port_base(), ready_timeout_s=10.0)
    try:
        with pytest.raises(ScaleError, match="exited"):
            act.scale_to(1)
        assert act.current() == 0
    finally:
        act.close()


def test_kubernetes_actuator_scale_subresource_http(tmp_path):
    """GET/PATCH against a scripted apiserver: bearer token from the SA
    mount, merge-patch body shape, ScaleError on HTTP failure."""
    sa = tmp_path / "sa"
    sa.mkdir()
    (sa / "token").write_text("sekret-token\n")
    seen = {"replicas": 2, "patches": [], "auth": []}

    class API(BaseHTTPRequestHandler):
        def _ok(self, doc):
            body = json.dumps(doc).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            seen["auth"].append(self.headers.get("Authorization"))
            self._ok({"spec": {"replicas": seen["replicas"]}})

        def do_PATCH(self):
            raw = self.rfile.read(
                int(self.headers.get("Content-Length", "0")))
            seen["patches"].append((self.headers.get("Content-Type"),
                                    json.loads(raw)))
            seen["replicas"] = json.loads(raw)["spec"]["replicas"]
            self._ok({"spec": {"replicas": seen["replicas"]}})

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), API)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        act = KubernetesActuator(
            "ns", "tpu-inference", sa_dir=str(sa),
            api_base=f"http://127.0.0.1:{httpd.server_address[1]}")
        assert act.current() == 2
        assert seen["auth"][0] == "Bearer sekret-token"
        act.scale_to(5, victims=["ignored"])
        assert seen["patches"] == [("application/merge-patch+json",
                                    {"spec": {"replicas": 5}})]
        assert act.current() == 5
        assert act.urls() == []
    finally:
        httpd.shutdown()
        httpd.server_close()
    # Apiserver gone: every call is a contained ScaleError.
    with pytest.raises(ScaleError):
        act.current()


def test_dry_run_actuator_records_without_acting():
    inner = _StubActuator(["http://a"])
    dry = DryRunActuator(inner)
    dry.scale_to(5)
    assert dry.calls == [5]
    assert inner.n == 1 and inner.calls == []


# --- drain-before-kill, real servers ---------------------------------------


def _post(url, path, doc, timeout=120):
    req = urllib.request.Request(
        url + path, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def test_drain_before_kill_restores_warm_on_survivor(tmp_path):
    """The property the subsystem exists for: a session pinned to the
    scale-down victim, released with spill=true through the router,
    serves its NEXT turn warm (tier hit, no cold prefill) on the
    surviving replica — two real engines handing a chain across a
    shared spill dir."""
    from k3stpu.serve.server import InferenceServer, make_app

    tier_dir = str(tmp_path / "tier")
    servers, httpds, urls = [], [], []
    for _ in range(2):
        srv = InferenceServer(model_name="transformer-tiny", seq_len=64,
                              continuous_batching=True, kv_page_size=8,
                              prompt_cache=4, tier_host_mb=16,
                              tier_dir=tier_dir)
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(srv))
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        servers.append(srv)
        httpds.append(httpd)
        urls.append(f"http://127.0.0.1:{httpd.server_address[1]}")
    router = Router(urls, health_period_s=3600.0, instance="test-drain")
    rhttpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                 make_router_app(router))
    threading.Thread(target=rhttpd.serve_forever, daemon=True).start()
    rurl = f"http://127.0.0.1:{rhttpd.server_address[1]}"
    try:
        p1 = [5, 6, 7, 8, 9, 10, 11, 12]
        r1 = _post(rurl, "/v1/generate",
                   {"prompt_tokens": [p1], "max_new_tokens": 4,
                    "session": "s-drain"})
        reply = r1["tokens"][0]
        victim = router.state()["pins"]["s-drain"]
        vi = urls.index(victim)
        survivor_srv = servers[1 - vi]

        # The controller's drain protocol, over the real HTTP surface.
        assert _post(rurl, "/v1/admin/drain",
                     {"replica": victim})["draining"] is True
        assert _post(rurl, "/v1/session/release",
                     {"session": "s-drain", "spill": True})["released"]
        # The chain is parked on disk, pin is gone, victim is idle.
        assert [f for f in os.listdir(tier_dir) if f.endswith(".kv")]
        assert "s-drain" not in router.state()["pins"]
        # Poll like the controller does: the victim's in-flight count
        # for the forwarded release settles a beat after the router's
        # response (the handler's finally runs post-write).
        deadline = time.monotonic() + 10.0
        while True:
            drain = json.loads(urllib.request.urlopen(
                victim + "/debug/drain", timeout=10).read())
            if drain["active_http_requests"] == 0:
                break
            assert time.monotonic() < deadline, drain
            time.sleep(0.05)

        # Kill the victim (actuator's job); membership watcher's view.
        router.set_membership([urls[1 - vi]])
        httpds[vi].shutdown()
        servers[vi].close()

        # Next turn extends turn 1; it must land on the survivor and
        # restore WARM by adopting the victim's spill file.
        p2 = p1 + reply + [20, 21]
        r2 = _post(rurl, "/v1/generate",
                   {"prompt_tokens": [p2], "max_new_tokens": 4,
                    "session": "s-drain"})
        assert len(r2["tokens"][0]) == 4
        stats = survivor_srv._engine.stats()
        assert stats["tier_hits"] >= 1, stats
        assert stats["tier_swap_ins"] >= 1, stats
        assert stats["tier_fallbacks"] == 0, stats
        assert router.state()["pins"]["s-drain"] == urls[1 - vi]
    finally:
        router.close()
        rhttpd.shutdown()
        for h in httpds:
            h.shutdown()
        for s in servers:
            s.close()


# --- bench gate ------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_serve_autoscale_bench_gates():
    """bench.py --serve-autoscale-worker: one BENCH_JSON line; the
    fleet scales 1->2 and back under a ramp with zero failed requests,
    and the parked session's post-scale-down turn restores warm
    (<= 1/3 of the cold re-prefill, the PR-10 tier bound)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ""
    env.pop("XLA_FLAGS", None)
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(REPO, ".jax_cache"))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--serve-autoscale-worker"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900)
    assert out.returncode == 0, out.stderr[-3000:]
    lines = [l for l in out.stdout.splitlines()
             if l.startswith("BENCH_JSON ")]
    assert len(lines) == 1, out.stdout
    doc = json.loads(lines[0][len("BENCH_JSON "):])
    assert doc["metric"] == "serve_autoscale_warm_restore_ratio"
    d = doc["detail"]
    assert d["scale_gate_passed"], d
    assert d["zero_failed_gate_passed"], d
    assert d["warm_gate_passed"], d
