"""Property-based tests (hypothesis) for the pure invariants the stack
leans on.

These functions are small but load-bearing: the causal tile predicates
decide which kernel tiles skip masking/compute/DMA (a wrong predicate is
silent garbage attention), the width bucket is the contract between
server validation and engine admission, and top_p_mask is the sampling
cut every generate path shares. Example-based tests pin known cases;
these pin the ALGEBRA over the whole input space.
"""

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need hypothesis (present in the "
    "dev image; optional everywhere else — skip-when-absent like helm)")
from hypothesis import given, settings, strategies as st  # noqa: E402

# Deterministic, CI-sized: the default profile is plenty here because
# every property is O(block^2) numpy at most.
settings.register_profile("ci", max_examples=60, deadline=None)
settings.load_profile("ci")

blocks = st.sampled_from([8, 16, 32, 64, 128, 256])
small = st.integers(min_value=0, max_value=16)


# --- causal tile predicates (ops/attention.py) --------------------------

def _brute_mask(qi, ki, bq, bk, offset, window):
    """Element-level truth: live[r, c] for the (qi, ki) tile."""
    rows = qi * bq + np.arange(bq)[:, None] + offset
    cols = ki * bk + np.arange(bk)[None, :]
    live = rows >= cols
    if window is not None:
        live &= cols > rows - window
    return live


@given(qi=small, ki=small, bq=blocks, bk=blocks,
       offset=st.integers(min_value=-64, max_value=64),
       window=st.one_of(st.none(), st.integers(min_value=1, max_value=512)))
def test_tile_predicates_match_elementwise_truth(qi, ki, bq, bk, offset,
                                                 window):
    from k3stpu.ops.attention import (
        _causal_tile_live,
        _causal_tile_needs_mask,
    )

    truth = _brute_mask(qi, ki, bq, bk, offset, window)
    live = bool(_causal_tile_live(qi, ki, bq, bk, offset, window))
    needs = bool(_causal_tile_needs_mask(qi, ki, bq, bk, offset, window))

    # live is exact for the no-window upper-triangle side: a tile with
    # any live element MUST be marked live (skipping it would drop real
    # attention mass — the unforgivable direction).
    if truth.any():
        assert live, "live tile marked dead: real attention mass dropped"
    if window is None and not truth.any():
        assert not live, "dead tile marked live (pure waste)"
    # needs_mask must hold whenever a LIVE tile contains any masked
    # element — skipping the mask there corrupts the softmax.
    if live and not truth.all():
        assert needs, "partially-masked tile skipped masking"


@given(qi=small, ki=small, bq=blocks, bk=blocks,
       offset=st.integers(min_value=-64, max_value=64),
       window=st.one_of(st.none(), st.integers(min_value=1, max_value=512)))
def test_masked_tile_values_match_elementwise_truth(qi, ki, bq, bk,
                                                    offset, window):
    """_causal_tile_mask itself: kept entries pass through, masked ones
    land at the -inf sentinel — elementwise, against the brute mask."""
    import jax.numpy as jnp

    from k3stpu.ops.attention import _NEG_INF, _causal_tile_mask

    s = jnp.asarray(np.random.default_rng(0).standard_normal((bq, bk)),
                    jnp.float32)
    got = np.asarray(_causal_tile_mask(s, qi, ki, bq, bk, offset, window))
    truth = _brute_mask(qi, ki, bq, bk, offset, window)
    np.testing.assert_array_equal(got == np.asarray(s), truth)
    assert (got[~truth] == _NEG_INF).all()


# --- prompt width bucket (serve/programs.py) ----------------------------

@given(max_len=st.integers(min_value=1, max_value=1 << 14),
       max_seq=st.sampled_from([64, 128, 1024, 1 << 14]))
def test_prompt_width_bucket_contract(max_len, max_seq):
    from k3stpu.serve.programs import prompt_width_bucket

    w = prompt_width_bucket(max_len, max_seq)
    assert w & (w - 1) == 0, "bucket must be a power of two"
    assert w <= max_seq
    # The server/engine contract: a prompt fits its bucket unless the
    # cache itself is the binding constraint.
    assert w >= min(max_len, max_seq)
    # Monotone: longer prompts never get smaller buckets.
    assert prompt_width_bucket(max_len + 1, max_seq) >= w


# --- top-p nucleus mask (models/generate.py) ----------------------------

@given(
    logits=st.lists(
        st.floats(min_value=-20, max_value=20, allow_nan=False),
        min_size=2, max_size=64),
    p=st.floats(min_value=0.05, max_value=1.0),
)
def test_top_p_mask_keeps_smallest_sufficient_nucleus(logits, p):
    import jax.numpy as jnp

    from k3stpu.models.generate import top_p_mask

    row = jnp.asarray([logits], jnp.float32)
    out = np.asarray(top_p_mask(row, p))[0]
    kept = out > -1e29
    assert kept.any(), "top-p must always keep at least the argmax"
    assert kept[np.argmax(logits)], "argmax must survive any p"
    probs = np.exp(logits - np.max(logits))
    probs = probs / probs.sum()
    kept_mass = probs[kept].sum()
    # Kept set reaches the target mass...
    assert kept_mass >= min(p, 1.0) - 1e-4
    # ...and is minimal up to ties: dropping EVERY kept entry tied at
    # the minimum kept probability must dip below p (ties at the cut
    # boundary are all kept — a deliberate property of the threshold
    # formulation, and the right call: arbitrary tie-breaking would make
    # the nucleus depend on sort order).
    if kept.sum() > 1:
        weakest_p = np.min(probs[kept])
        tied_mass = probs[kept & np.isclose(probs, weakest_p, atol=1e-9)]
        assert kept_mass - tied_mass.sum() < p + 1e-4


# --- sharded corpus view (data/corpus.py) -------------------------------

@given(
    sizes=st.lists(st.integers(min_value=1, max_value=40), min_size=1,
                   max_size=6),
    data=st.data(),
)
def test_shard_view_slices_match_concatenation(sizes, data):
    from k3stpu.data.corpus import _ShardView

    rng = np.random.default_rng(7)
    shards = [rng.integers(0, 1000, size=n).astype(np.uint16)
              for n in sizes]
    cum = np.concatenate([[0], np.cumsum([len(s) for s in shards])])
    full = np.concatenate(shards)
    view = _ShardView(shards, cum, 0, int(cum[-1]))
    assert len(view) == len(full)

    a = data.draw(st.integers(min_value=0, max_value=len(full)))
    b = data.draw(st.integers(min_value=a, max_value=len(full)))
    np.testing.assert_array_equal(np.asarray(view[a:b]), full[a:b])
    # Sub-windows compose.
    if b > a:
        w = view.window(a, b)
        np.testing.assert_array_equal(np.asarray(w[0:b - a]), full[a:b])
