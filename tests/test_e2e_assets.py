"""Lint the real-cluster e2e assets (deploy/e2e/, tools/e2e_cluster.sh).

The e2e script itself can only run on a machine with docker+k3d
(docs/E2E_CLUSTER.md), but everything it applies to the cluster is
committed YAML that CAN be validated here — with the same kubeval-lite
discipline as the chart lint (tests/test_chart_lint.py): skeletons,
names, and — the drift-prone part — that the strategic-merge patches
only touch volumes the chart actually renders, so a chart refactor that
renames a volume fails CI instead of silently un-faking the e2e.
"""

import glob
import os
import re
import subprocess

import yaml

from k3stpu.utils.helm_lite import render_chart
from tests.test_chart import CHART

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
E2E_DIR = os.path.join(REPO, "deploy", "e2e")
SCRIPT = os.path.join(REPO, "tools", "e2e_cluster.sh")

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")


def _load(name):
    with open(os.path.join(E2E_DIR, name)) as f:
        return yaml.safe_load(f)


def _chart_daemonsets():
    docs = yaml.safe_load_all(render_chart(CHART, namespace="tpu-system"))
    return {d["metadata"]["name"]: d for d in docs
            if d and d["kind"] == "DaemonSet"}


def test_script_lints():
    subprocess.run(["bash", "-n", SCRIPT], check=True)
    assert os.access(SCRIPT, os.X_OK), "e2e script must be executable"


def test_all_e2e_yamls_parse():
    files = glob.glob(os.path.join(E2E_DIR, "*.yaml"))
    assert len(files) >= 3
    for path in files:
        with open(path) as f:
            assert yaml.safe_load(f) is not None, path


def test_probe_pod_skeleton_and_parity():
    doc = _load("e2e-probe.yaml")
    assert set(doc) >= {"apiVersion", "kind", "metadata", "spec"}
    assert doc["kind"] == "Pod"
    assert _DNS1123.match(doc["metadata"]["name"])
    spec = doc["spec"]
    # The stack-parity triple every probe in this repo shares
    # (deploy/manifests/tpu-probe.yaml, reference nvidia-smi.yaml:8-16):
    assert spec["runtimeClassName"] == "tpu"
    assert spec["restartPolicy"] == "Never"
    [c] = spec["containers"]
    assert c["resources"]["limits"]["google.com/tpu"] == "1"
    # e2e-specific: label-gated scheduling (the LIVE form of the
    # reference's commented selector) + local image only + the log
    # oracle the script greps for, exiting nonzero when injection is
    # missing so pod phase is the assertion.
    assert spec["nodeSelector"]["google.com/tpu.present"] == "true"
    assert c["imagePullPolicy"] == "Never"
    body = c["command"][-1]
    assert "E2E_PROBE_JSON" in body and "TPU_VISIBLE_CHIPS" in body
    assert "sys.exit" in body


def test_patches_touch_only_rendered_volumes():
    """Every volume a fakeroot patch overrides must exist (by name) in
    the chart-rendered DaemonSet it patches, and must repoint under
    /fake-tpu-root — the tree tools/e2e_cluster.sh seeds."""
    ds = _chart_daemonsets()
    for patch_name, ds_name in (
            ("plugin-fakeroot-patch.yaml", "k3s-tpu-device-plugin"),
            ("tfd-fakeroot-patch.yaml", "k3s-tpu-feature-discovery")):
        patch = _load(patch_name)
        patch_vols = patch["spec"]["template"]["spec"]["volumes"]
        rendered = {v["name"]: v for v in
                    ds[ds_name]["spec"]["template"]["spec"]["volumes"]}
        assert patch_vols, patch_name
        for v in patch_vols:
            assert v["name"] in rendered, (
                f"{patch_name}: volume {v['name']!r} not in the rendered "
                f"{ds_name} — chart and e2e patch have drifted")
            path = v["hostPath"]["path"]
            assert path.startswith("/fake-tpu-root/"), path
            # The repoint must mirror the real source's basename so the
            # container-side mount semantics stay identical.
            real = rendered[v["name"]]["hostPath"]["path"]
            assert path == "/fake-tpu-root" + real, (patch_name, path, real)


def test_script_references_exist():
    """Paths the script mounts/applies must exist in the repo, and its
    assertions must match what the assets emit."""
    with open(SCRIPT) as f:
        text = f.read()
    for rel in ("deploy/containerd/config-v3.toml.tmpl",
                "deploy/containerd/config.toml.tmpl",
                "deploy/charts/k3s-tpu",
                "deploy/e2e/tfd-fakeroot-patch.yaml",
                "deploy/e2e/plugin-fakeroot-patch.yaml",
                "deploy/e2e/e2e-probe.yaml",
                "docker/k3s-tpu.Dockerfile"):
        assert rel in text, f"script no longer uses {rel}?"
        assert os.path.exists(os.path.join(REPO, rel)), rel
    # the capacity assertion must agree with the chart's replicas knob
    values = yaml.safe_load(
        open(os.path.join(REPO, "deploy/charts/k3s-tpu/values.yaml")))
    replicas = values["config"]["sharing"]["timeSlicing"]["resources"][0][
        "replicas"]
    assert f"grep -qx {replicas}" in text, (
        "script's capacity assertion drifted from the chart default")
