"""Chip enumeration against the fake sysfs tree (SURVEY.md §4)."""

from k3stpu.utils import chips


def test_enumerate_fake_v5e(fake_host_root):
    inv = chips.enumerate_chips(root=str(fake_host_root))
    assert inv.count == 4
    assert inv.generation == "tpu-v5e"
    assert inv.topology() == "2x2"
    assert [c.index for c in inv.chips] == [0, 1, 2, 3]
    assert inv.chips[0].dev_paths == ("/dev/accel0",)
    assert inv.chips[3].dev_paths == ("/dev/accel3",)
    assert inv.chips[0].numa_node == 0
    assert inv.chips[3].numa_node == 1
    # The Intel device must not appear.
    assert all(c.vendor_id == "0x1ae0" for c in inv.chips)


def test_mixed_accel_vfio(tmp_path):
    """Chips beyond the accel nodes map onto vfio groups starting at 0."""
    for i in range(4):
        bdf = tmp_path / "sys" / "bus" / "pci" / "devices" / f"0000:00:0{4 + i}.0"
        bdf.mkdir(parents=True)
        (bdf / "vendor").write_text("0x1ae0\n")
        (bdf / "device").write_text("0x0062\n")
    dev = tmp_path / "dev"
    (dev / "vfio").mkdir(parents=True)
    for i in range(2):
        (dev / f"accel{i}").write_text("")
    for i in range(2):
        (dev / "vfio" / str(i)).write_text("")
    (dev / "vfio" / "vfio").write_text("")

    inv = chips.enumerate_chips(root=str(tmp_path))
    assert [c.dev_paths for c in inv.chips] == [
        ("/dev/accel0",),
        ("/dev/accel1",),
        ("/dev/vfio/0", "/dev/vfio/vfio"),
        ("/dev/vfio/1", "/dev/vfio/vfio"),
    ]


def test_enumerate_empty(tmp_path):
    inv = chips.enumerate_chips(root=str(tmp_path))
    assert inv.count == 0
    assert inv.generation == "none"
    assert inv.topology() == "0"


def test_libtpu_path(fake_host_root, tmp_path):
    assert chips.libtpu_path(root=str(fake_host_root)) == "/usr/lib/libtpu.so"


def test_host_root_env(fake_host_root, monkeypatch):
    monkeypatch.setenv(chips.HOST_ROOT_ENV, str(fake_host_root))
    assert chips.enumerate_chips().count == 4
