"""Prompt/prefix KV caching in the continuous-batching engine.

The correctness bar is absolute: a cache hit (exact or prefix) must
produce BIT-IDENTICAL tokens to the uncached path, which is itself
pinned to ``generate()``. The win being bought: an exact repeat skips
its prefill dispatch entirely; a prompt extending a cached one prefills
only the suffix (the chat / shared-system-prompt serving pattern).
CPU-JAX stand-in per SURVEY.md §4.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.models.generate import generate
from k3stpu.models.transformer import transformer_lm_tiny
from k3stpu.serve.engine import GenerateEngine


def _model_and_params(max_seq_len=64):
    model = transformer_lm_tiny(max_seq_len=max_seq_len)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                           train=False)
    return model, variables["params"]


def _solo(model, params, prompt, budget):
    out = generate(model, params,
                   jnp.asarray(np.array([prompt], np.int32)),
                   jnp.array([len(prompt)], jnp.int32), budget,
                   temperature=0.0)
    return np.asarray(out)[0].tolist()


@pytest.fixture(scope="module")
def cached_engine():
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=4, prompt_cache=4)
    yield model, params, engine
    engine.close()


def test_exact_hit_matches_and_skips_prefill(cached_engine):
    model, params, engine = cached_engine
    prompt = [11, 12, 13, 14]
    want = [_solo(model, params, prompt, 6)]
    assert engine.submit([prompt], max_new_tokens=6) == want
    s0 = engine.stats()
    assert s0["pcache_entries"] >= 1 and s0["pcache_bytes"] > 0
    # The repeat must hit (no new prefill) and stay bit-identical.
    assert engine.submit([prompt], max_new_tokens=6) == want
    s1 = engine.stats()
    assert s1["pcache_hits"] == s0["pcache_hits"] + 1
    assert s1["pcache_misses"] == s0["pcache_misses"]


def test_prefix_hit_extends_and_matches(cached_engine):
    model, params, engine = cached_engine
    base = [21, 22, 23]
    engine.submit([base], max_new_tokens=4)
    s0 = engine.stats()
    extended = base + [24, 25]
    got = engine.submit([extended], max_new_tokens=6)
    assert got == [_solo(model, params, extended, 6)]
    s1 = engine.stats()
    assert s1["pcache_prefix_hits"] == s0["pcache_prefix_hits"] + 1
    # The extension itself is now cached: an exact repeat hits.
    assert engine.submit([extended], max_new_tokens=6) == got
    assert engine.stats()["pcache_hits"] == s1["pcache_hits"] + 1


def test_cached_generation_not_corrupted_by_decodes(cached_engine):
    """The cached row must survive the decodes of the slot its copy ran
    in (jax immutability): generate twice with DIFFERENT budgets — if the
    first generation's decode steps had leaked into the cached row, the
    second's continuation would diverge."""
    model, params, engine = cached_engine
    prompt = [31, 32, 33, 34, 35]
    engine.submit([prompt], max_new_tokens=8)
    assert engine.submit([prompt], max_new_tokens=3) == \
        [_solo(model, params, prompt, 3)]


def test_samples_fan_out_from_cached_prompt(cached_engine):
    _, _, engine = cached_engine
    prompt = [41, 42, 43]
    engine.submit([prompt], max_new_tokens=4)
    s0 = engine.stats()
    rows = engine.submit_samples(prompt, 3, max_new_tokens=5,
                                 temperature=1.0, top_k=8)
    assert len(rows) == 3 and all(len(r) == 5 for r in rows)
    assert engine.stats()["pcache_hits"] == s0["pcache_hits"] + 1


def test_lru_eviction_capacity_one():
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=2, prompt_cache=1)
    try:
        p1, p2 = [1, 2, 3], [4, 5, 6]
        w1 = [_solo(model, params, p1, 4)]
        assert engine.submit([p1], max_new_tokens=4) == w1
        assert engine.submit([p2], max_new_tokens=4) == \
            [_solo(model, params, p2, 4)]  # evicts p1
        assert engine.submit([p1], max_new_tokens=4) == w1  # re-prefills
        s = engine.stats()
        assert s["pcache_entries"] == 1
        assert s["pcache_misses"] == 3 and s["pcache_hits"] == 0
    finally:
        engine.close()


def test_chunked_admission_inserts_and_exact_hit_skips_chunking():
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=2, chunk_prefill=8,
                            prompt_cache=2)
    try:
        prompt = list(range(1, 25))  # width 32 > chunk 8: chunked admission
        want = [_solo(model, params, prompt, 5)]
        assert engine.submit([prompt], max_new_tokens=5) == want
        s0 = engine.stats()
        assert s0["adm_chunks"] >= 2 and s0["pcache_entries"] == 1
        # Exact repeat: no chunked admission at all, identical tokens.
        assert engine.submit([prompt], max_new_tokens=5) == want
        s1 = engine.stats()
        assert s1["pcache_hits"] == s0["pcache_hits"] + 1
        assert s1["adm_chunks"] == s0["adm_chunks"]
        # Small suffix (pow2 bucket 2 <= chunk 8): prefix path allowed.
        ext = prompt + [30, 31]
        assert engine.submit([ext], max_new_tokens=4) == \
            [_solo(model, params, ext, 4)]
        assert engine.stats()["pcache_prefix_hits"] == \
            s1["pcache_prefix_hits"] + 1
    finally:
        engine.close()


def test_long_suffix_falls_back_to_chunked_path():
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=2, chunk_prefill=4,
                            prompt_cache=2)
    try:
        base = [1, 2, 3]
        engine.submit([base], max_new_tokens=3)
        s0 = engine.stats()
        # Suffix of 13 -> pow2 bucket 16 > chunk 4: stall bound says no
        # prefix reuse; the request runs the plain chunked admission and
        # must still be exact.
        ext = base + list(range(10, 23))
        assert engine.submit([ext], max_new_tokens=4) == \
            [_solo(model, params, ext, 4)]
        s1 = engine.stats()
        assert s1["pcache_prefix_hits"] == s0["pcache_prefix_hits"]
        assert s1["pcache_misses"] == s0["pcache_misses"] + 1
    finally:
        engine.close()


def test_cache_disabled_by_default():
    model, params = _model_and_params()
    engine = GenerateEngine(model, params, slots=2)
    try:
        engine.submit([[1, 2]], max_new_tokens=3)
        engine.submit([[1, 2]], max_new_tokens=3)
        s = engine.stats()
        assert s["pcache_entries"] == 0 and s["pcache_bytes"] == 0
        assert s["pcache_hits"] == 0 and s["pcache_misses"] == 0
    finally:
        engine.close()


def test_multi_prompt_requests_bypass_cache(cached_engine):
    model, params, engine = cached_engine
    prompts = [[51, 52], [53, 54, 55]]
    s0 = engine.stats()
    got = engine.submit(prompts, max_new_tokens=4)
    assert got == [_solo(model, params, p, 4) for p in prompts]
    s1 = engine.stats()
    assert s1["pcache_hits"] == s0["pcache_hits"]
    assert s1["pcache_misses"] == s0["pcache_misses"]


def test_stream_from_cached_prompt(cached_engine):
    """Streaming + cache hit: the first event still carries the first
    token and the final result stays pinned."""
    model, params, engine = cached_engine
    prompt = [61, 62, 63]
    want = [_solo(model, params, prompt, 5)]
    assert engine.submit([prompt], max_new_tokens=5) == want
    events = list(engine.submit_stream([prompt], max_new_tokens=5))
    assert events[-1] == {"done": True, "tokens": want}
    first = events[0]
    assert first["done"] is False
    assert first["rows"] == {0: [want[0][0]]}


def test_server_flag_and_prometheus_counters():
    """--prompt-cache wiring end-to-end: the server's engine caches, and
    the scrape surface exports the hit/miss/bytes series (only when the
    cache is enabled — a disabled cache must not emit dead series)."""
    from k3stpu.serve.server import InferenceServer

    server = InferenceServer(model_name="transformer-tiny", seq_len=32,
                             batch_window_ms=0.0, continuous_batching=True,
                             engine_slots=2, prompt_cache=2,
                             shard_devices=1)
    try:
        first = server.generate_tokens([[1, 2, 3]], max_new_tokens=3)
        assert server.generate_tokens([[1, 2, 3]], max_new_tokens=3) \
            == first
        text = server.prometheus_metrics()
        assert "k3stpu_pcache_hits_total 1" in text
        assert "k3stpu_pcache_misses_total 1" in text
        assert "k3stpu_pcache_bytes" in text
    finally:
        server.close()
    plain = InferenceServer(model_name="transformer-tiny", seq_len=32,
                            batch_window_ms=0.0, continuous_batching=True,
                            engine_slots=2, shard_devices=1)
    try:
        assert "k3stpu_pcache" not in plain.prometheus_metrics()
    finally:
        plain.close()


def test_reset_stats_preserves_pcache_bytes(cached_engine):
    _, _, engine = cached_engine
    assert engine.stats()["pcache_bytes"] > 0
    before = engine.stats()["pcache_bytes"]
    engine.reset_stats()
    s = engine.stats()
    assert s["pcache_bytes"] == before and s["pcache_hits"] == 0
