"""Checkpoint/resume round trips (orbax, sharded state on the 8-device CPU
mesh): save a trained bundle, restore into a fresh one, losses must agree."""

import jax
import jax.numpy as jnp
import numpy as np

from k3stpu.models.transformer import transformer_lm_tiny
from k3stpu.parallel.mesh import make_mesh
from k3stpu.parallel.train import (
    make_train_bundle,
    run_synthetic_steps,
    synth_token_batch,
)
from k3stpu.utils.checkpoint import (
    latest_step,
    restore_bundle,
    restore_train_state,
    save_bundle,
    save_train_state,
)


def test_roundtrip_pytree(tmp_path):
    state = {"w": jnp.arange(12.0).reshape(3, 4), "step": jnp.int32(7)}
    save_train_state(tmp_path, 3, state)
    out = restore_train_state(tmp_path, 3, state)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(state["w"]))
    assert int(out["step"]) == 7


def test_latest_step(tmp_path):
    assert latest_step(tmp_path / "missing") is None
    state = {"x": jnp.ones((2,))}
    save_train_state(tmp_path, 1, state)
    save_train_state(tmp_path, 10, state)
    assert latest_step(tmp_path) == 10


def test_bundle_resume_preserves_training(tmp_path):
    mesh = make_mesh(8, model_parallelism=2)
    model = transformer_lm_tiny()
    seq, vocab = 32, model.config.vocab_size
    mk = lambda k: synth_token_batch(k, 8, seq, vocab)

    bundle = make_train_bundle(model, mesh,
                               example_input=jnp.zeros((1, seq), jnp.int32))
    run_synthetic_steps(bundle, mk, n_steps=2)
    save_bundle(tmp_path, 2, bundle)

    # Fresh bundle (different init path state), restore, then the next step
    # must match a continuation of the original exactly.
    resumed = make_train_bundle(model, mesh,
                                example_input=jnp.zeros((1, seq), jnp.int32))
    restore_bundle(tmp_path, 2, resumed)

    loss_cont = run_synthetic_steps(bundle, mk, n_steps=1, seed=9)
    loss_resumed = run_synthetic_steps(resumed, mk, n_steps=1, seed=9)
    assert abs(loss_cont - loss_resumed) < 1e-6

    # Restored arrays keep their mesh shardings (no silent host gather).
    leaf = jax.tree.leaves(resumed.params)[0]
    assert leaf.sharding.mesh.shape == mesh.shape


def test_async_save_restore_roundtrip(tmp_path):
    """blocking=False saves commit in the background; wait_for_saves() makes
    them durable and latest_step sees only finalized steps."""
    import jax.numpy as jnp

    from k3stpu.utils import checkpoint as ckpt

    state = {"w": jnp.arange(8, dtype=jnp.float32), "n": jnp.ones(())}
    ckpt.save_train_state(tmp_path, 1, state, blocking=False)
    ckpt.save_train_state(tmp_path, 2, jax.tree.map(lambda x: x * 2, state),
                          blocking=False)  # drains save 1 first
    ckpt.wait_for_saves()
    assert ckpt.latest_step(tmp_path) == 2
    restored = ckpt.restore_train_state(tmp_path, 2, state)
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               2 * np.arange(8, dtype=np.float32))
