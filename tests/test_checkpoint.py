"""Checkpoint/resume round trips (orbax, sharded state on the 8-device CPU
mesh): save a trained bundle, restore into a fresh one, losses must agree.
Plus the integrity/retention layer (ISSUE 4): manifests, verify/quarantine,
keep-last GC, and a kill-mid-save subprocess proving partial saves are
never resumed from."""

import getpass
import json
import os
import pathlib
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.models.transformer import transformer_lm_tiny
from k3stpu.parallel.mesh import make_mesh
from k3stpu.parallel.train import (
    make_train_bundle,
    run_synthetic_steps,
    synth_token_batch,
)
from k3stpu.utils import checkpoint as ckpt
from k3stpu.utils.checkpoint import (
    latest_step,
    restore_bundle,
    restore_train_state,
    save_bundle,
    save_train_state,
)


def test_roundtrip_pytree(tmp_path):
    state = {"w": jnp.arange(12.0).reshape(3, 4), "step": jnp.int32(7)}
    save_train_state(tmp_path, 3, state)
    out = restore_train_state(tmp_path, 3, state)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(state["w"]))
    assert int(out["step"]) == 7


def test_latest_step(tmp_path):
    assert latest_step(tmp_path / "missing") is None
    state = {"x": jnp.ones((2,))}
    save_train_state(tmp_path, 1, state)
    save_train_state(tmp_path, 10, state)
    assert latest_step(tmp_path) == 10


def test_bundle_resume_preserves_training(tmp_path):
    mesh = make_mesh(8, model_parallelism=2)
    model = transformer_lm_tiny()
    seq, vocab = 32, model.config.vocab_size
    mk = lambda k: synth_token_batch(k, 8, seq, vocab)

    bundle = make_train_bundle(model, mesh,
                               example_input=jnp.zeros((1, seq), jnp.int32))
    run_synthetic_steps(bundle, mk, n_steps=2)
    save_bundle(tmp_path, 2, bundle)

    # Fresh bundle (different init path state), restore, then the next step
    # must match a continuation of the original exactly.
    resumed = make_train_bundle(model, mesh,
                                example_input=jnp.zeros((1, seq), jnp.int32))
    restore_bundle(tmp_path, 2, resumed)

    loss_cont = run_synthetic_steps(bundle, mk, n_steps=1, seed=9)
    loss_resumed = run_synthetic_steps(resumed, mk, n_steps=1, seed=9)
    assert abs(loss_cont - loss_resumed) < 1e-6

    # Restored arrays keep their mesh shardings (no silent host gather).
    leaf = jax.tree.leaves(resumed.params)[0]
    assert leaf.sharding.mesh.shape == mesh.shape


def test_async_save_restore_roundtrip(tmp_path):
    """blocking=False saves commit in the background; wait_for_saves() makes
    them durable and latest_step sees only finalized steps."""
    import jax.numpy as jnp

    from k3stpu.utils import checkpoint as ckpt

    state = {"w": jnp.arange(8, dtype=jnp.float32), "n": jnp.ones(())}
    ckpt.save_train_state(tmp_path, 1, state, blocking=False)
    ckpt.save_train_state(tmp_path, 2, jax.tree.map(lambda x: x * 2, state),
                          blocking=False)  # drains save 1 first
    ckpt.wait_for_saves()
    assert ckpt.latest_step(tmp_path) == 2
    restored = ckpt.restore_train_state(tmp_path, 2, state)
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               2 * np.arange(8, dtype=np.float32))
    # Manifests trail async saves by design (they must only describe
    # FINALIZED bytes); after the drain both steps have one.
    assert ckpt.verify_step(tmp_path, 1)[0]
    assert ckpt.verify_step(tmp_path, 2)[1].startswith("verified")


# --- integrity manifests (ISSUE 4) ---------------------------------------


def _save(tmp_path, step, scale=1.0):
    save_train_state(tmp_path, step,
                     {"w": scale * jnp.arange(16, dtype=jnp.float32)})


def test_manifest_catches_corruption(tmp_path):
    _save(tmp_path, 3)
    mpath = tmp_path / "manifests" / "3.json"
    assert mpath.is_file()
    manifest = json.loads(mpath.read_text())
    assert manifest["step"] == 3 and manifest["files"]
    ok, why = ckpt.verify_step(tmp_path, 3)
    assert ok and why.startswith("verified")

    # Flip one byte (size unchanged): only the sha256 can catch this.
    victim = max((p for p in (tmp_path / "3").rglob("*") if p.is_file()),
                 key=lambda p: p.stat().st_size)
    data = bytearray(victim.read_bytes())
    data[len(data) // 2] ^= 0xFF
    victim.write_bytes(bytes(data))
    ok, why = ckpt.verify_step(tmp_path, 3)
    assert not ok and "checksum mismatch" in why

    # Truncation is caught by the cheaper size check first.
    victim.write_bytes(bytes(data[:-1]))
    ok, why = ckpt.verify_step(tmp_path, 3)
    assert not ok and "size mismatch" in why

    victim.unlink()
    ok, why = ckpt.verify_step(tmp_path, 3)
    assert not ok and "missing file" in why


def test_manifestless_step_passes_verification(tmp_path):
    # Back-compat: a step saved by an older build (or whose process died
    # between commit and manifest) is still resumable.
    _save(tmp_path, 1)
    (tmp_path / "manifests" / "1.json").unlink()
    assert ckpt.verify_step(tmp_path, 1) == (True, "no-manifest")
    assert ckpt.verify_step(tmp_path, 99) == (False, "not a finalized step")


def test_quarantine_moves_step_and_manifest(tmp_path):
    _save(tmp_path, 1)
    _save(tmp_path, 2)
    dest = ckpt.quarantine_step(tmp_path, 2)
    assert dest == tmp_path / "quarantine" / "2"
    assert dest.is_dir()
    assert (tmp_path / "quarantine" / "2.manifest.json").is_file()
    assert not (tmp_path / "manifests" / "2.json").exists()
    assert latest_step(tmp_path) == 1
    # A recreated-then-requarantined step never clobbers the evidence.
    _save(tmp_path, 2)
    assert ckpt.quarantine_step(tmp_path, 2) == tmp_path / "quarantine" / "2-1"


def test_quarantine_tolerates_a_peer_winning_the_race(tmp_path):
    """Every process of a multi-host job walks the same fallback loop
    over the same RWX PVC: the loser of the quarantine race must treat
    'already gone' as done, not crash with FileNotFoundError."""
    _save(tmp_path, 1)
    _save(tmp_path, 2)
    ckpt.quarantine_step(tmp_path, 2)  # the winning peer
    dest = ckpt.quarantine_step(tmp_path, 2)  # the loser: no crash
    assert not dest.exists()
    assert (tmp_path / "quarantine" / "2").is_dir()
    assert latest_step(tmp_path) == 1


def test_manifest_rewrite_is_atomic_and_leaves_no_debris(tmp_path):
    """Concurrent manifest writers (two pods on one PVC) each go through
    a per-process tmp + atomic rename: re-writing an existing manifest
    publishes a complete file and leaves no tmp litter behind."""
    _save(tmp_path, 1)
    ckpt.write_manifest(tmp_path, 1)  # as a racing peer would
    assert [p.name for p in (tmp_path / "manifests").iterdir()] \
        == ["1.json"]
    ok, why = ckpt.verify_step(tmp_path, 1)
    assert ok and why.startswith("verified")


def test_gc_tolerates_a_peer_having_deleted_first(tmp_path):
    """A manifest (or step dir) a concurrent GC already removed is just
    less to delete — never an exception."""
    for step in (1, 2, 3):
        _save(tmp_path, step)
    (tmp_path / "manifests" / "1.json").unlink()  # peer got there first
    assert ckpt.gc_steps(tmp_path, 1) == [1, 2]
    assert ckpt.finalized_steps(tmp_path) == [3]


def test_gc_keeps_newest_and_spares_partials(tmp_path):
    for step in (1, 2, 3):
        _save(tmp_path, step, scale=float(step))
    debris = tmp_path / "5.orbax-checkpoint-tmp-7"
    debris.mkdir()
    (debris / "shard").write_text("half")
    _save(tmp_path, 4)
    ckpt.quarantine_step(tmp_path, 4)

    with pytest.raises(ValueError):
        ckpt.gc_steps(tmp_path, 0)
    assert ckpt.gc_steps(tmp_path, 1) == [1, 2]
    assert ckpt.finalized_steps(tmp_path) == [3]
    assert [p.name for p in sorted((tmp_path / "manifests").iterdir())] \
        == ["3.json"]
    # Partials and quarantined steps are evidence, not garbage.
    assert debris.is_dir()
    assert (tmp_path / "quarantine" / "4").is_dir()
    assert ckpt.partial_steps(tmp_path) == ["5.orbax-checkpoint-tmp-7"]
    assert ckpt.gc_steps(tmp_path, 1) == []  # idempotent


# --- kill mid-save: the partial step is never resumed from ---------------


def _train_env():
    env = dict(os.environ)
    # Replace PYTHONPATH (drop the dev box's sitecustomize TPU tunnel) and
    # run one CPU device; share the suite's persistent compile cache.
    repo = pathlib.Path(__file__).resolve().parent.parent
    env["PYTHONPATH"] = str(repo)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("K3STPU_CHAOS", None)
    try:
        user = getpass.getuser()
    except (KeyError, OSError):
        user = str(os.getuid())
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.environ.get(
        "K3STPU_TEST_CACHE", f"/tmp/k3stpu-test-compile-cache-{user}"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    return env


def test_sigkill_mid_save_skips_partial_and_resumes_previous(tmp_path):
    """SIGKILL lands while the step-4 save is held open by an injected
    stall (the step-2 save has committed, its manifest not yet written):
    boot must resume from step 2 — 'no-manifest' is resumable — and the
    planted orbax tmp debris is skipped, reported, and preserved."""
    cdir = tmp_path / "ckpt"
    env = _train_env()
    # skip=1 lets the step-2 save through; the step-4 save then stalls
    # 120s at the top of save_train_state — plenty of window for SIGKILL.
    env["K3STPU_CHAOS"] = "ckpt_save:skip=1:stall_s=120"
    cmd = [sys.executable, "-m", "k3stpu.parallel.train_job",
           "--model", "tiny", "--batch", "4", "--seq", "16",
           "--steps", "8", "--ckpt-dir", str(cdir), "--ckpt-every", "2"]
    proc = subprocess.Popen(cmd, env=env, text=True,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    import threading

    reaper = threading.Timer(240, proc.kill)  # backstop: no hung readline
    reaper.start()
    try:
        saw_step_4 = False
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("{"):
                ev = json.loads(line)
                if ev.get("event") == "step" and ev["step"] == 4:
                    saw_step_4 = True
                    break
        assert saw_step_4, "never reached step 4"
        # The save call after step 4 is now inside the injected stall;
        # give the ASYNC step-2 commit a moment to land, then SIGKILL —
        # the hard version of preemption (no grace period at all).
        time.sleep(2.0)
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        reaper.cancel()
        if proc.poll() is None:
            proc.kill()

    # Plant the debris an interrupted orbax rename leaves behind (the
    # injected stall fires before orbax touches disk, so the partial
    # layout is modelled explicitly — same shape latest_step must skip).
    # Two pieces: step 4's (the stalled save — the rerun will re-save
    # that step, superseding it) and step 3's (a step the rerun never
    # writes — nothing may ever delete it).
    debris4 = cdir / "4.orbax-checkpoint-tmp-0"
    debris4.mkdir()
    (debris4 / "shard").write_text("half-written")
    debris3 = cdir / "3.orbax-checkpoint-tmp-0"
    debris3.mkdir()
    (debris3 / "shard").write_text("half-written")

    assert ckpt.finalized_steps(cdir) == [2]
    assert ckpt.partial_steps(cdir) == ["3.orbax-checkpoint-tmp-0",
                                        "4.orbax-checkpoint-tmp-0"]
    # Step 2 committed but died before its manifest: still resumable.
    assert ckpt.verify_step(cdir, 2) == (True, "no-manifest")

    env.pop("K3STPU_CHAOS")
    out = subprocess.run(
        [sys.executable, "-m", "k3stpu.parallel.train_job",
         "--model", "tiny", "--batch", "4", "--seq", "16",
         "--steps", "4", "--ckpt-dir", str(cdir), "--ckpt-every", "2"],
        env=env, text=True, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, timeout=240)
    assert out.returncode == 0, out.stdout[-2000:]
    events = [json.loads(ln) for ln in out.stdout.splitlines()
              if ln.strip().startswith("{")]
    (resume,) = [e for e in events if e["event"] == "resume"]
    assert resume == {"event": "resume", "step": 2,
                      "verify": "no-manifest"}
    assert [e["step"] for e in events if e["event"] == "step"] == [3, 4]
    # Step 4's re-save supersedes its stale tmp dir (orbax's atomic-save
    # cleanup — the finalized step replaces the debris); step 3's debris
    # belongs to no save the rerun performed and must be untouched.
    assert ckpt.finalized_steps(cdir) == [2, 4]
    assert ckpt.verify_step(cdir, 4)[0]
    assert debris3.is_dir()  # unrelated evidence preserved
