"""plugin_config: v1 config schema -> native binary flags (chart launcher)."""

import subprocess
import sys

import pytest

from k3stpu.plugin_config import argv_for, parse_config

V1 = """\
version: v1
flags:
  granularity: chip
sharing:
  timeSlicing:
    renameByDefault: false
    failRequestsGreaterThanOne: false
    resources:
      - name: google.com/tpu
        replicas: 4
"""


def test_parse_default_schema():
    s = parse_config(V1)
    assert s == {"resource": "google.com/tpu", "replicas": 4,
                 "fail_multi": False, "granularity": "chip"}


def test_empty_config_is_exclusive():
    s = parse_config("version: v1\n")
    assert s["replicas"] == 1
    assert argv_for(s, "bin") == ["bin", "--resource", "google.com/tpu",
                                  "--replicas", "1"]


def test_core_granularity_accepted():
    s = parse_config(V1.replace("granularity: chip", "granularity: core"))
    assert s["granularity"] == "core"
    argv = argv_for(s, "bin")
    assert argv[argv.index("--granularity") + 1] == "core"


def test_chip_granularity_omits_flag():
    argv = argv_for(parse_config(V1), "bin")
    assert "--granularity" not in argv


def test_fail_requests_greater_than_one():
    s = parse_config(V1.replace("failRequestsGreaterThanOne: false",
                                "failRequestsGreaterThanOne: true"))
    assert s["fail_multi"] is True
    assert "--fail-multi" in argv_for(s, "bin")


def test_extra_flags_pass_through():
    s = parse_config(V1)
    argv = argv_for(s, "bin", ["--plugin-dir", "/tmp/dp"])
    assert argv[-2:] == ["--plugin-dir", "/tmp/dp"]


def test_cli_dry_run(tmp_path):
    cfg = tmp_path / "config.yaml"
    cfg.write_text(V1)
    out = subprocess.run(
        [sys.executable, "-m", "k3stpu.plugin_config", "--config", str(cfg),
         "--exec", "/usr/local/bin/tpu-device-plugin", "--dry-run",
         "--", "--scan-seconds", "30"],
        capture_output=True, text=True, check=True)
    assert out.stdout.split() == [
        "/usr/local/bin/tpu-device-plugin", "--resource", "google.com/tpu",
        "--replicas", "4", "--scan-seconds", "30"]


def test_unknown_granularity_rejected():
    with pytest.raises(ValueError, match="granularity"):
        parse_config("version: v1\nflags:\n  granularity: tensorcore\n")
