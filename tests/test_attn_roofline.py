"""The roofline model's accounting must agree with the bench's."""

import json
import subprocess
import sys

from k3stpu.ops.attn_bench import _attn_flops
from k3stpu.ops.attn_roofline import V5E, model


def test_flops_match_the_bench_accounting():
    # The model must credit exactly the flops the bench divides by —
    # otherwise the doc's MFU ceilings and the captured ATTN_JSON MFUs
    # are not comparable numbers.
    for s in (1024, 4096, 16384):
        r = model(seq=s, batch=8, heads=8, head_dim=128, causal=True)
        assert r.flops == _attn_flops(8, s, 8, 128, True, False)


def test_bound_transitions_and_monotonic_ceiling():
    # Short S: k/v restreaming is amortized over few q tiles -> HBM wall.
    assert model(seq=1024).bound_by == "hbm"
    # Long S with the log2-domain kernel: the three walls are a near-tie
    # (no unit more than 40% over the cheapest) — the headline claim the
    # doc makes about why the kernel design is balanced.
    r = model(seq=8192)
    units = (r.mxu_ms, r.vpu_ms, r.hbm_ms)
    assert max(units) / min(units) < 1.4, units
    # Ceiling MFU never exceeds 1 and the dispatch floor only hurts.
    for s in (1024, 4096, 8192):
        r = model(seq=s)
        assert 0 < r.ceiling_mfu <= 1.0
        assert r.measured_mfu_with_floor < r.ceiling_mfu


def test_kernel_time_is_max_of_units():
    r = model(seq=4096)
    assert r.kernel_ms == max(r.mxu_ms, r.vpu_ms, r.hbm_ms)


def test_cli_emits_roofline_json():
    out = subprocess.run(
        [sys.executable, "-m", "k3stpu.ops.attn_roofline",
         "--seqs", "2048"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    lines = [l for l in out.stdout.splitlines()
             if l.startswith("ROOFLINE_JSON ")]
    assert len(lines) == 1
    rec = json.loads(lines[0].split(" ", 1)[1])
    assert rec["chip"] == V5E["name"]
    assert rec["bound_by"] in ("mxu", "vpu", "hbm")
