"""Host KV page tier (k3stpu/serve/tiering.py + engine/server wiring).

The correctness bar is BIT-EXACTNESS: a session chain that round-trips
through the host tier (gather -> host RAM [-> disk spill] -> device_put
+ scatter into fresh pages) must make the engine emit exactly the
tokens a never-swapped engine emits — greedy, sampled (same seed),
int8 KV pools, and COW-shared prefixes with live co-resident entries.
The capacity win must come from moving idle bytes off-device, never
from numerics.

The safety bar is pin hygiene: swap storms may never leak a page or
strand a pin (free count returns to baseline), a failed swap-in (chaos
``tier_swap``, torn disk spill) must degrade to a cold prefill without
touching live rows, and the accounting the capacity planning trusts
(``stats()['pcache_bytes']``, ``engine._page_bytes``) must agree with
``models/quant.kv_page_bytes`` layout-for-layout. CPU-JAX stand-in per
SURVEY.md §4.
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.chaos import FaultInjector
from k3stpu.models.generate import generate
from k3stpu.models.quant import kv_page_bytes
from k3stpu.models.transformer import transformer_lm_tiny
from k3stpu.serve.engine import GenerateEngine
from k3stpu.serve.tiering import HostPageStore, TierCorrupt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def mp():
    model = transformer_lm_tiny(max_seq_len=64)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                           train=False)
    return model, variables["params"]


def _solo(model, params, prompt, budget):
    out = generate(model, params,
                   jnp.asarray(np.array([prompt], np.int32)),
                   jnp.array([len(prompt)], jnp.int32), budget,
                   temperature=0.0)
    return np.asarray(out)[0].tolist()


def _tier_pair(model, params, *, tier_mb=64, spill_dir=None,
               watermark=0, chaos=None, **kw):
    """A no-tier paged engine and a tiered paged engine with identical
    scheduling parameters (same seed => identical sampling-key folds).
    Mirror every submit on both: swap traffic must not perturb the fold
    sequence, so outputs stay comparable request-for-request."""
    kw.setdefault("slots", 4)
    kw.setdefault("prompt_cache", 4)
    kw.setdefault("page_size", 8)
    plain = GenerateEngine(model, params, seed=0, **kw)
    store = HostPageStore(tier_mb * (1 << 20), spill_dir=spill_dir)
    tiered = GenerateEngine(model, params, seed=0, tier=store,
                            tier_watermark=watermark, chaos=chaos, **kw)
    return plain, tiered, store


def _assert_page_invariants(engine):
    """Idle-engine allocator accounting, checked exactly (the same
    proof as tests/test_paged.py): every page's refcount equals its
    appearances across live slot chains plus prompt-cache pins. The
    tier holds HOST bytes only, so a correct swap-out changes nothing
    here — a stranded pin or leaked page after swap traffic fails."""
    alloc = engine._alloc
    expect = {}
    for chain in engine._chains:
        for p in chain:
            expect[p] = expect.get(p, 0) + 1
    for entry in engine._pcache.values():
        for p in entry[0]:
            expect[p] = expect.get(p, 0) + 1
    for p in range(1, alloc.num_pages):
        assert alloc.refcount(p) == expect.get(p, 0), (
            f"page {p}: rc={alloc.refcount(p)} but "
            f"{expect.get(p, 0)} live references")
    assert alloc.free == alloc.total - sum(1 for v in expect.values()
                                           if v > 0)
    pinned = {}
    for entry in engine._pcache.values():
        for p in entry[0]:
            pinned[p] = pinned.get(p, 0) + 1
    assert engine._pinned == pinned


# --- HostPageStore unit behavior ----------------------------------------


def _fake_chain(seed, n_pages=2):
    rng = np.random.default_rng(seed)
    return {
        "0/attn/key_pages": rng.standard_normal(
            (n_pages, 8, 2, 4)).astype(np.float32),
        "0/attn/value_pages": rng.standard_normal(
            (n_pages, 8, 2, 4)).astype(np.float32),
    }


def test_store_match_is_longest_prefix_per_adapter():
    store = HostPageStore(1 << 20)
    store.put((0, (1, 2)), 2, _fake_chain(0))
    store.put((0, (1, 2, 3)), 3, _fake_chain(1))
    store.put((1, (1, 2, 3, 4)), 4, _fake_chain(2))
    assert store.match(0, (1, 2, 3, 4, 5)) == (0, (1, 2, 3))
    assert store.match(0, (1, 2)) == (0, (1, 2))
    assert store.match(0, (9, 9, 9)) is None
    assert store.match(2, (1, 2, 3)) is None  # adapter namespaced


def test_store_capacity_evicts_last_use_first():
    one = sum(a.nbytes for a in _fake_chain(0).values())
    store = HostPageStore(int(one * 2.5))  # room for two entries
    store.put((0, (1,)), 1, _fake_chain(0))
    store.put((0, (2,)), 1, _fake_chain(1))
    store.load((0, (1,)))                 # refresh: (2,) is now LRU
    store.put((0, (3,)), 1, _fake_chain(2))
    assert store.keys() == [(0, (1,)), (0, (3,))], (
        "eviction must follow last-use order, not insertion order")
    assert store.stats()["tier_bytes"] <= store.capacity


def test_store_spill_roundtrip_and_unlink(tmp_path):
    one = sum(a.nbytes for a in _fake_chain(0).values())
    store = HostPageStore(int(one * 1.5), spill_dir=str(tmp_path))
    want = _fake_chain(7)
    store.put((0, (1,)), 1, want)
    store.put((0, (2,)), 1, _fake_chain(8))   # pushes (1,) to disk
    assert store.stats()["tier_spilled_bytes"] > 0
    assert len(list(tmp_path.iterdir())) == 1
    assert store.contains((0, (1,)))          # spilled, not gone
    length, pages, last = store.load((0, (1,)))
    assert length == 1 and last is None
    for name, arr in want.items():
        assert np.array_equal(pages[name], arr), name
    # load promoted it back; the spill file must not linger...
    spills = [p for p in tmp_path.iterdir() if p.suffix == ".kv"]
    # ...(the promote may have spilled the OTHER entry to make room).
    assert store.stats()["tier_entries"] == 2
    for p in spills:
        # the first spill this process wrote is tier-<pid>-1.kv
        assert not p.name.endswith("-1.kv"), \
            "consumed spill file not unlinked"


def test_store_torn_spill_fails_checksum(tmp_path):
    one = sum(a.nbytes for a in _fake_chain(0).values())
    store = HostPageStore(int(one * 1.2), spill_dir=str(tmp_path))
    store.put((0, (1,)), 1, _fake_chain(0))
    store.put((0, (2,)), 1, _fake_chain(1))
    (spill,) = list(tmp_path.iterdir())
    raw = spill.read_bytes()
    spill.write_bytes(raw[:len(raw) // 2])            # torn write
    with pytest.raises(TierCorrupt):
        store.load((0, (1,)))
    spill.write_bytes(b"xy")                          # truncated header
    with pytest.raises(TierCorrupt):
        store.load((0, (1,)))
    assert store.discard((0, (1,)))
    assert not store.contains((0, (1,)))


def test_park_spill_claimed_by_exactly_one_peer(tmp_path):
    """The drain handoff: spill(key) parks as an adoptable park-*.kv;
    a peer claims it by atomic rename so exactly ONE store adopts, and
    private eviction spills (tier-*) are never offered."""
    one = sum(a.nbytes for a in _fake_chain(0).values())
    owner = HostPageStore(int(one * 4), spill_dir=str(tmp_path))
    want = _fake_chain(3)
    owner.put((0, (1, 2)), 2, want)
    assert owner.spill((0, (1, 2)))
    assert all(p.name.startswith("park-") for p in tmp_path.iterdir())
    # An eviction spill rides the private tier-* namespace.
    owner.put((0, (9,)), 1, _fake_chain(4))
    owner.capacity = 1
    owner._evict_oldest_resident()
    assert any(p.name.startswith("tier-") for p in tmp_path.iterdir())

    a = HostPageStore(int(one * 4), spill_dir=str(tmp_path))
    b = HostPageStore(int(one * 4), spill_dir=str(tmp_path))
    got = a.adopt_orphans() + b.adopt_orphans()
    assert got == 1, "park file adopted once; tier file never offered"
    winner, loser = (a, b) if a.contains((0, (1, 2))) else (b, a)
    assert not loser.contains((0, (1, 2)))
    assert loser.match(0, (1, 2, 3)) is None
    assert winner.match(0, (1, 2, 3)) == (0, (1, 2))
    length, pages, last = winner.load((0, (1, 2)))
    assert length == 2
    for name, arr in want.items():
        assert np.array_equal(pages[name], arr), name
    # The owner never adopts its own files back; its eviction spill
    # still loads from the private namespace.
    assert owner.adopt_orphans() == 0
    owner.capacity = int(one * 4)
    owner.load((0, (9,)))


def test_spill_promotes_prior_eviction_spill_to_park(tmp_path):
    """release with spill=true on an entry ALREADY evicted to disk:
    the private tier-* file is renamed into the adoptable park-*
    namespace rather than rewritten."""
    one = sum(a.nbytes for a in _fake_chain(0).values())
    store = HostPageStore(int(one * 1.2), spill_dir=str(tmp_path))
    store.put((0, (1,)), 1, _fake_chain(0))
    store.put((0, (2,)), 1, _fake_chain(1))   # evicts (1,) to tier-*
    assert any(p.name.startswith("tier-") for p in tmp_path.iterdir())
    assert store.spill((0, (1,)))
    names = [p.name for p in tmp_path.iterdir()]
    assert any(n.startswith("park-") for n in names)
    assert store.spill((0, (1,)))             # idempotent: stays parked
    peer = HostPageStore(int(one * 4), spill_dir=str(tmp_path))
    assert peer.adopt_orphans() == 1
    assert peer.load((0, (1,)))[0] == 1


def test_match_adoption_gated_on_dir_mtime(tmp_path):
    """The tier probe pays one os.stat, not a listdir+parse, while the
    spill dir is quiet — and still adopts promptly when a peer parks."""
    time.sleep(0.06)  # let the fresh dir's mtime age past the gate
    store = HostPageStore(1 << 20, spill_dir=str(tmp_path))
    calls = []
    orig = store.adopt_orphans
    store.adopt_orphans = lambda: (calls.append(1), orig())[1]
    store.match(0, (1,))
    n0 = len(calls)
    assert n0 == 1, "first probe scans"
    store.match(0, (1,))
    store.match(0, (1,))
    assert len(calls) == n0, "quiet dir: stat-only probes"
    peer = HostPageStore(1 << 20, spill_dir=str(tmp_path))
    peer.put((0, (5, 6)), 2, _fake_chain(1))
    assert peer.spill((0, (5, 6)))
    assert store.match(0, (5, 6, 7)) == (0, (5, 6))
    assert len(calls) > n0, "dir change re-arms the scan"


# --- accounting: the bytes capacity planning trusts (satellite) ---------


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_page_bytes_matches_kv_page_bytes(kv_dtype):
    """The engine's measured per-page cost (summed from the live cache
    leaves by name) must equal the planning-side models/quant form for
    BOTH pool layouts — fp32 and int8+scale-planes — and
    stats()['pcache_bytes'] must be the exact sum of entry footprints
    computed from it. A drift here silently mis-sizes --tier-host-mb."""
    kw = {"max_seq_len": 64}
    if kv_dtype is not None:
        kw["kv_cache_dtype"] = kv_dtype
    model = transformer_lm_tiny(**kw)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    engine = GenerateEngine(model, params, slots=2, seed=0,
                            page_size=8, prompt_cache=4)
    try:
        assert engine._page_bytes == kv_page_bytes(model.config, 8)
        engine.submit([[5, 6, 7, 8, 9]], max_new_tokens=4)
        engine.submit([[20, 21, 22]], max_new_tokens=4)
        want = sum(entry[-1] for entry in engine._pcache.values())
        assert engine.stats()["pcache_bytes"] == want
        for entry in engine._pcache.values():
            page_part = len(entry[0]) * kv_page_bytes(model.config, 8)
            assert entry[-1] >= page_part
    finally:
        engine.close()


# --- bit-exactness: swapped == never-swapped on every path --------------


def test_session_restore_bit_exact_greedy(mp):
    model, params = mp
    plain, tiered, store = _tier_pair(model, params)
    try:
        p1 = [5, 6, 7, 8, 9, 10, 11, 12, 13]
        want1 = plain.submit([p1], max_new_tokens=6)
        got1 = tiered.submit([p1], max_new_tokens=6, session="s1")
        assert got1 == want1
        assert want1[0] == _solo(model, params, p1, 6)

        assert tiered.release_session("s1")
        assert tiered.stats()["tier_swap_outs"] == 1
        assert store.stats()["tier_entries"] == 1

        # Turn 2 extends turn 1's prompt + reply: the tier restore must
        # be byte-for-byte the plain engine's warm pcache path.
        p2 = p1 + got1[0] + [20, 21]
        want2 = plain.submit([p2], max_new_tokens=6)
        got2 = tiered.submit([p2], max_new_tokens=6, session="s1")
        assert got2 == want2
        assert want2[0] == _solo(model, params, p2, 6)
        ts = tiered.stats()
        assert ts["tier_hits"] == 1 and ts["tier_swap_ins"] == 1
        assert ts["tier_fallbacks"] == 0
        _assert_page_invariants(tiered)
    finally:
        plain.close()
        tiered.close()


def test_session_restore_bit_exact_sampled(mp):
    """Same seed, same fold sequence => sampled tokens after a tier
    round-trip must be IDENTICAL, not merely plausible — swap traffic
    must never bump the step counter the sampling keys fold on."""
    model, params = mp
    plain, tiered, store = _tier_pair(model, params)
    try:
        p1 = [9, 10, 11, 12]
        kw = {"temperature": 0.9, "top_k": 20}
        want1 = plain.submit([p1], max_new_tokens=6, **kw)
        got1 = tiered.submit([p1], max_new_tokens=6, session="s1", **kw)
        assert got1 == want1
        assert tiered.release_session("s1")
        p2 = p1 + got1[0] + [30]
        want2 = plain.submit([p2], max_new_tokens=8, **kw)
        got2 = tiered.submit([p2], max_new_tokens=8, session="s1", **kw)
        assert got2 == want2
        assert tiered.stats()["tier_swap_ins"] == 1
    finally:
        plain.close()
        tiered.close()


def test_session_restore_bit_exact_int8(mp):
    """The int8 pools carry fp32 absmax scale planes next to the int8
    values; a swap that dropped or reordered either leaf would decode
    garbage. Greedy output after a round-trip must match the no-tier
    int8 engine exactly."""
    model = transformer_lm_tiny(max_seq_len=64, kv_cache_dtype="int8")
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    plain, tiered, store = _tier_pair(model, params)
    try:
        p1 = [3, 4, 5, 6, 7, 8, 9]
        want1 = plain.submit([p1], max_new_tokens=5)
        got1 = tiered.submit([p1], max_new_tokens=5, session="q")
        assert got1 == want1
        assert tiered.release_session("q")
        p2 = p1 + got1[0] + [40]
        want2 = plain.submit([p2], max_new_tokens=5)
        assert tiered.submit([p2], max_new_tokens=5, session="q") == want2
        assert tiered.stats()["tier_swap_ins"] == 1
        _assert_page_invariants(tiered)
    finally:
        plain.close()
        tiered.close()


def test_cow_shared_prefix_survives_neighbor_release(mp):
    """Two sessions sharing a COW prefix: releasing one to the tier
    decrefs only ITS references — the co-resident entry keeps its pins,
    stays exact, and the released chain restores exact alongside it."""
    model, params = mp
    plain, tiered, store = _tier_pair(model, params)
    try:
        base = [5, 6, 7, 8, 9, 10, 11, 12, 13]
        r1p = plain.submit([base], max_new_tokens=4)
        r1t = tiered.submit([base], max_new_tokens=4, session="a")
        assert r1t == r1p
        # b branches off a's turn-1 transcript: its prompt extends a's
        # session key (base + reply[:-1]) so admission COW-shares a's
        # pinned pages and only copies the partial tail.
        ext = base + r1t[0] + [30, 31]
        r2p = plain.submit([ext], max_new_tokens=4)
        r2t = tiered.submit([ext], max_new_tokens=4, session="b")
        assert r2t == r2p
        assert tiered.stats()["pcache_prefix_hits"] >= 1

        assert tiered.release_session("a")  # shared pages: b still pins
        for entry in tiered._pcache.values():
            for p in entry[0]:
                assert tiered._alloc.refcount(p) >= 1, (
                    "neighbor release reclaimed a shared pinned page")

        # b continues exact on its still-resident chain...
        b2 = ext + r2t[0] + [60]
        assert (tiered.submit([b2], max_new_tokens=4, session="b")
                == plain.submit([b2], max_new_tokens=4))
        # ...and a restores exact from the tier.
        a2 = base + r1t[0] + [50]
        assert (tiered.submit([a2], max_new_tokens=4, session="a")
                == plain.submit([a2], max_new_tokens=4))
        assert a2[:len(base)] == b2[:len(base)] and a2 != b2
        assert tiered.stats()["tier_swap_ins"] == 1
        _assert_page_invariants(tiered)
    finally:
        plain.close()
        tiered.close()


def test_watermark_demotes_idle_entries_under_pressure(mp):
    """tier_watermark > 0: when the free list sits below it, the loop
    demotes LRU pcache entries to host instead of letting the next
    admission stall — and a demoted session still restores exact."""
    model, params = mp
    store = HostPageStore(64 << 20)
    engine = GenerateEngine(model, params, slots=2, seed=0,
                            prompt_cache=8, page_size=8, num_pages=12,
                            tier=store, tier_watermark=8)
    try:
        p1 = [5, 6, 7, 8, 9, 10, 11, 12, 13]
        got1 = engine.submit([p1], max_new_tokens=4, session="w")
        # Pressure: this request + the cached chain push free below the
        # watermark; the loop (which wakes on its 0.2 s drain timeout
        # even when idle) must gather idle entries to host.
        engine.submit([list(range(20, 33))], max_new_tokens=4)
        deadline = time.time() + 10
        while (engine.stats()["tier_swap_outs"] < 1
               and time.time() < deadline):
            time.sleep(0.05)
        s = engine.stats()
        assert s["tier_swap_outs"] >= 1, "watermark demotion never ran"
        assert s["host_tier_pages"] >= 1
        p2 = p1 + got1[0] + [40]
        assert engine.submit([p2], max_new_tokens=4, session="w") \
            == [_solo(model, params, p2, 4)]
        _assert_page_invariants(engine)
    finally:
        engine.close()


# --- lifecycle / API edges ----------------------------------------------


def test_release_session_semantics(mp):
    model, params = mp
    dense = GenerateEngine(model, params, slots=2, seed=0)
    plain, tiered, store = _tier_pair(model, params)
    try:
        assert dense.release_session("x") is False   # dense: no chains
        assert tiered.release_session("ghost") is False
        tiered.submit([[5, 6, 7]], max_new_tokens=4, session="s")
        assert tiered.release_session("s") is True
        assert tiered.release_session("s") is True   # idempotent: on host
        # no-tier paged engine: release still frees HBM (entry dropped).
        plain.submit([[5, 6, 7]], max_new_tokens=4, session="s")
        assert plain.release_session("s") is True
        assert plain.release_session("s") is False   # gone for good
        with pytest.raises(ValueError, match="one prompt"):
            tiered.submit([[1, 2], [3, 4]], max_new_tokens=2, session="s")
    finally:
        dense.close()
        plain.close()
        tiered.close()


def test_chaos_tier_swap_in_degrades_to_cold_prefill(mp):
    """An injected fault inside the swap-in dispatch must cost ONLY the
    restore: the request falls back to a cold prefill with bit-exact
    output, tier_fallbacks counts it, and the engine keeps serving."""
    model, params = mp
    inj = FaultInjector()
    plain, tiered, store = _tier_pair(model, params, chaos=inj)
    try:
        p1 = [5, 6, 7, 8, 9]
        got1 = tiered.submit([p1], max_new_tokens=4, session="c")
        assert got1 == plain.submit([p1], max_new_tokens=4)
        assert tiered.release_session("c")          # swap-out (clean)
        inj.arm("tier_swap", times=1)
        p2 = p1 + got1[0] + [20]
        want2 = plain.submit([p2], max_new_tokens=4)
        assert tiered.submit([p2], max_new_tokens=4, session="c") == want2
        assert inj.fired("tier_swap") == 1
        s = tiered.stats()
        assert s["tier_fallbacks"] == 1 and s["tier_swap_ins"] == 0
        # engine loop alive and exact afterwards
        assert tiered.submit([[7, 8, 9]], max_new_tokens=3) \
            == plain.submit([[7, 8, 9]], max_new_tokens=3)
        _assert_page_invariants(tiered)
    finally:
        plain.close()
        tiered.close()


def test_torn_disk_spill_degrades_to_cold_prefill(mp, tmp_path):
    """End-to-end fault matrix row: a spilled session whose file is
    corrupted on disk fails the checksum at swap-in and degrades to a
    cold prefill — exact output, fallback counted, loop alive."""
    model, params = mp
    plain, tiered, store = _tier_pair(model, params,
                                      spill_dir=str(tmp_path))
    try:
        p1 = [5, 6, 7, 8, 9]
        g1 = tiered.submit([p1], max_new_tokens=4, session="a")
        plain.submit([p1], max_new_tokens=4)
        p1b = [20, 21, 22, 23]
        tiered.submit([p1b], max_new_tokens=4, session="b")
        plain.submit([p1b], max_new_tokens=4)
        assert tiered.release_session("a")
        assert tiered.release_session("b")
        # Shrink capacity so a's entry (LRU) hits the disk tier.
        store.capacity = 1
        store._evict_oldest_resident()
        (spill,) = [p for p in tmp_path.iterdir() if p.suffix == ".kv"]
        raw = spill.read_bytes()
        spill.write_bytes(raw[:8] + b"\x00" * 8 + raw[16:])  # bit rot
        p2 = p1 + g1[0] + [40]
        want = plain.submit([p2], max_new_tokens=4)
        assert tiered.submit([p2], max_new_tokens=4, session="a") == want
        s = tiered.stats()
        assert s["tier_fallbacks"] >= 1
        assert not store.contains((0, tuple(p1 + g1[0][:-1])))
        _assert_page_invariants(tiered)
    finally:
        plain.close()
        tiered.close()


# --- pin hygiene under sustained swap traffic (satellite) ---------------


def test_swap_storm_free_count_returns_to_baseline(mp):
    """500+ swap events (release -> restore cycles across sessions):
    afterwards every page is back on the free list and the tier's
    byte accounting is still capacity-bounded. One stranded pin or
    leaked ref per cycle would compound into pool exhaustion in an
    afternoon of chat traffic — this is the leak-free proof."""
    model, params = mp
    store = HostPageStore(2 << 20)   # tight: forces tier eviction churn
    engine = GenerateEngine(model, params, slots=2, seed=0,
                            prompt_cache=4, page_size=8,
                            decode_block=1, tier=store)
    try:
        engine.submit([[1, 2, 3]], max_new_tokens=1)   # warm programs
        for i in range(170):
            p1 = [(i * 7 + j) % 400 + 1 for j in range(5)]
            r1 = engine.submit([p1], max_new_tokens=2,
                               session=f"s{i}")[0]
            assert engine.release_session(f"s{i}")     # swap-out #1
            p2 = p1 + r1 + [(i % 50) + 1]
            engine.submit([p2], max_new_tokens=2,
                          session=f"s{i}")             # swap-in
            assert engine.release_session(f"s{i}")     # swap-out #2
            if i % 40 == 0:
                _assert_page_invariants(engine)
        s = engine.stats()
        assert s["tier_swap_outs"] + s["tier_swap_ins"] >= 500, s
        # Free count returns to the working-set baseline: the ONLY pages
        # off the free list are the (<= prompt_cache) live LRU entries'
        # — 510+ swaps stranded nothing. A one-page leak per cycle would
        # show up here as 170 missing pages.
        live = set()
        for entry in engine._pcache.values():
            live.update(entry[0])
        assert engine._alloc.free == engine._alloc.total - len(live), (
            "swap storm leaked pages or stranded pins")
        assert len(engine._pcache) <= 4
        ts = store.stats()
        assert ts["tier_bytes"] <= store.capacity
        _assert_page_invariants(engine)
        # and the engine still serves exact output
        assert engine.submit([[5, 6, 7]], max_new_tokens=4) \
            == [_solo(model, params, [5, 6, 7], 4)]
    finally:
        engine.close()


# --- server surface ------------------------------------------------------


def test_server_session_api_and_tier_metrics():
    from k3stpu.serve.server import InferenceServer
    server = InferenceServer(model_name="transformer-tiny", seq_len=64,
                             continuous_batching=True, kv_page_size=8,
                             prompt_cache=4, tier_host_mb=16)
    try:
        p1 = [5, 6, 7, 8, 9]
        g1 = server.generate_tokens([p1], max_new_tokens=4, session="s1")
        assert server.release_session("s1") is True
        p2 = p1 + g1[0] + [20]
        server.generate_tokens([p2], max_new_tokens=4, session="s1")
        stats = server._engine.stats()
        assert stats["tier_swap_ins"] >= 1
        text = server._counter_exposition()
        for family in ("k3stpu_tier_entries", "k3stpu_tier_host_bytes",
                       "k3stpu_tier_spilled_bytes", "k3stpu_tier_sessions",
                       "k3stpu_tier_swap_ins_total",
                       "k3stpu_tier_swap_outs_total"):
            assert family in text, family
        with pytest.raises(ValueError):
            server.generate_tokens([p1, p1], max_new_tokens=2,
                                   session="s2")   # sessions are 1-row
        with pytest.raises(ValueError):
            server.release_session("")
    finally:
        server.close()


def test_server_rejects_tier_without_paged_engine():
    from k3stpu.serve.server import InferenceServer
    with pytest.raises(ValueError, match="tier-host-mb"):
        InferenceServer(model_name="transformer-tiny", seq_len=32,
                        tier_host_mb=16)
    with pytest.raises(ValueError, match="tier-dir"):
        InferenceServer(model_name="transformer-tiny", seq_len=32,
                        continuous_batching=True, kv_page_size=8,
                        prompt_cache=4, tier_dir="/tmp/nope")


# --- bench mode ---------------------------------------------------------


@pytest.mark.slow
def test_serve_tier_bench_gates():
    """bench.py --serve-tier: one JSON line; warm-turn restore latency
    <= 1/3 of cold re-prefill at a 512-token prompt (vs_baseline <= 1.0)
    and >= 8x restorable sessions at the fixed page pool."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ""
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--serve-tier"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert out.returncode == 0, out.stderr
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"must print exactly one line, got: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "serve_tier_warm_restore_ratio"
    assert rec["vs_baseline"] <= 1.0, rec
    d = rec["detail"]
    assert d["warm_gate_passed"] and d["capacity_gate_passed"], d
    assert d["session_capacity_x"] >= 8.0, d
