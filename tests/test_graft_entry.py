"""Driver contract: entry() traces; dryrun_multichip executes on 8 devices."""

import jax

import __graft_entry__ as ge


def test_entry_traces():
    fn, args = ge.entry()
    out = jax.eval_shape(fn, *args)
    assert out.shape == (8, 512, 32768)  # (batch, seq, vocab)


def test_dryrun_multichip_8():
    ge.dryrun_multichip(8)
