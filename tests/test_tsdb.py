"""Embedded metrics pipeline (k3stpu/obs/{tsdb,promql,collector}).

Evaluator semantics are pinned with hand-computed fixtures — rate()
under a counter reset, histogram_quantile() on labeled buckets,
``and ignoring()`` vector matching, ``for:`` state transitions — so a
future "optimization" of the window math shows up as a changed number,
not a silently different alert timeline. The chart contract is the
acceptance criterion: every rule the chart renders (default AND qos)
must parse and evaluate in the embedded engine, and a real 2-replica
routed fleet with silent corruption armed must drive
K3sTpuCanaryTokenMismatch to firing from scrape data alone.
"""

import json
import os
import sys
import threading
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

import pytest

from k3stpu.obs.promql import (
    PromQLError,
    Rule,
    RuleEngine,
    evaluate,
    load_rule_groups,
    metric_names,
    parse_duration,
    parse_expr,
    yaml_lite_load,
)
from k3stpu.obs.tsdb import TSDB, anchor_index, counter_increase

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

CHART = os.path.join(os.path.dirname(__file__), "..", "deploy",
                     "charts", "k3s-tpu")


def _store(samples):
    """TSDB from [(name, labels, value, t)]."""
    db = TSDB()
    for name, labels, value, t in samples:
        db.ingest_sample(name, labels, value, t)
    return db


def _eval(expr, db, now):
    return sorted(evaluate(parse_expr(expr), db, now),
                  key=lambda lv: sorted(lv[0].items()))


# --- TSDB -------------------------------------------------------------------


def test_instant_respects_lookback_and_staleness():
    db = _store([("m", {"i": "a"}, 1.0, 0.0),
                 ("m", {"i": "b"}, 2.0, 290.0)])
    assert _eval("m", db, 300.0) == [({"i": "a"}, 1.0),
                                     ({"i": "b"}, 2.0)]
    # a: 301s old > 300s lookback -> gone; b still inside.
    assert _eval("m", db, 301.0) == [({"i": "b"}, 2.0)]
    db.mark_stale("m", {"i": "b"}, 295.0)
    # stale-marked: b gone at once (a's sample is still in lookback).
    assert _eval("m", db, 296.0) == [({"i": "a"}, 1.0)]
    db.ingest_sample("m", {"i": "b"}, 3.0, 297.0)
    assert _eval("m", db, 298.0) == [({"i": "a"}, 1.0),
                                     ({"i": "b"}, 3.0)]  # un-staled


def test_target_staleness_on_scrape_and_on_target_down():
    db = TSDB()
    db.ingest_text("a 1\nb 2\n", 0.0, instance="x", target="t1")
    assert db.names() == ["a", "b"]
    # next scrape drops family b -> b stale-marked immediately.
    db.ingest_text("a 3\n", 10.0, instance="x", target="t1")
    assert _eval("b", db, 11.0) == []
    assert _eval("a", db, 11.0) == [({"instance": "x"}, 3.0)]
    db.mark_target_down("t1", 20.0)
    assert _eval("a", db, 21.0) == []  # unreachable target: all stale


def test_ring_buffer_caps_samples_per_series():
    db = TSDB(max_samples=4)
    for i in range(10):
        db.ingest_sample("m", {}, float(i), float(i))
    assert db.sample_count() == 4
    assert _eval("m", db, 10.0) == [({}, 9.0)]


def test_anchor_index_is_the_slo_delta_rule():
    from k3stpu.obs.slo import SloEngine
    # the unification is an identity, not a lookalike
    assert SloEngine._delta.__module__ == "k3stpu.obs.slo"
    assert anchor_index([0.0, 60.0, 120.0], 60.0) == 1  # at horizon
    assert anchor_index([0.0, 60.0, 120.0], 59.0) == 0
    assert anchor_index([100.0, 160.0], 50.0) == 0  # young series


# --- evaluator semantics (hand-computed fixtures) ---------------------------


def _counter_with_reset():
    # counter climbs to 60, resets (restart), climbs again:
    # pairwise increase = 60 + 10 + 60 = 130, NOT 70 - 0 = 70.
    return _store([("c", {"i": "a"}, 0.0, 0.0),
                   ("c", {"i": "a"}, 60.0, 60.0),
                   ("c", {"i": "a"}, 10.0, 120.0),
                   ("c", {"i": "a"}, 70.0, 180.0)])


def test_increase_is_reset_corrected():
    db = _counter_with_reset()
    assert _eval("increase(c[3m])", db, 180.0) == [({"i": "a"}, 130.0)]
    # window covering only the post-reset leg: anchor at t=120.
    assert _eval("increase(c[1m])", db, 180.0) == [({"i": "a"}, 60.0)]


def test_rate_is_increase_over_window():
    db = _counter_with_reset()
    ((_, v),) = _eval("rate(c[3m])", db, 180.0)
    assert v == pytest.approx(130.0 / 180.0)


def test_counter_increase_needs_two_points():
    assert counter_increase([(0.0, 5.0)], 60.0, 60.0) is None
    assert counter_increase([], 60.0, 60.0) is None


def test_histogram_quantile_on_labeled_buckets():
    db = _store([
        ("h_bucket", {"i": "a", "le": "0.1"}, 5.0, 0.0),
        ("h_bucket", {"i": "a", "le": "1"}, 9.0, 0.0),
        ("h_bucket", {"i": "a", "le": "+Inf"}, 10.0, 0.0),
        ("h_bucket", {"i": "b", "le": "0.1"}, 0.0, 0.0),
        ("h_bucket", {"i": "b", "le": "1"}, 10.0, 0.0),
        ("h_bucket", {"i": "b", "le": "+Inf"}, 10.0, 0.0),
    ])
    got = dict((lv[0]["i"], lv[1])
               for lv in _eval("histogram_quantile(0.5, h_bucket)",
                               db, 0.0))
    # a: rank 5 lands exactly on the 0.1 bucket's cumulative count.
    assert got["a"] == pytest.approx(0.1)
    # b: rank 5 is halfway through (0.1, 1] -> 0.1 + 0.9/2.
    assert got["b"] == pytest.approx(0.55)
    # q=0.3 interpolates inside a's first bucket: 3/5 of (0, 0.1].
    got = dict((lv[0]["i"], lv[1])
               for lv in _eval("histogram_quantile(0.3, h_bucket)",
                               db, 0.0))
    assert got["a"] == pytest.approx(0.06)


def test_and_ignoring_matches_on_remaining_labels():
    db = _store([
        ("b", {"slo": "x", "window": "5m"}, 20.0, 0.0),
        ("b", {"slo": "x", "window": "1h"}, 16.0, 0.0),
        ("b", {"slo": "y", "window": "5m"}, 20.0, 0.0),
        ("b", {"slo": "y", "window": "1h"}, 2.0, 0.0),
    ])
    expr = ('b{window="5m"} > 14.4 '
            'and ignoring(window) b{window="1h"} > 14.4')
    # only slo=x clears the bar on BOTH windows; the result keeps the
    # LEFT side's labels and value (Prometheus `and` semantics).
    assert _eval(expr, db, 0.0) == [
        ({"slo": "x", "window": "5m"}, 20.0)]


def test_aggregation_and_arithmetic():
    db = _store([("q", {"i": "a", "c": "int"}, 3.0, 0.0),
                 ("q", {"i": "b", "c": "int"}, 5.0, 0.0),
                 ("q", {"i": "a", "c": "bat"}, 7.0, 0.0)])
    assert _eval("sum by (c) (q)", db, 0.0) == [({"c": "bat"}, 7.0),
                                                ({"c": "int"}, 8.0)]
    assert _eval("sum(q) by (c)", db, 0.0) == [({"c": "bat"}, 7.0),
                                               ({"c": "int"}, 8.0)]
    assert _eval("max(q)", db, 0.0) == [({}, 7.0)]
    assert _eval("sum(q) / 3", db, 0.0) == [({}, 5.0)]
    assert _eval("q * 2 + 1", db, 0.0) == [
        ({"c": "bat", "i": "a"}, 15.0),
        ({"c": "int", "i": "a"}, 7.0),
        ({"c": "int", "i": "b"}, 11.0)]


def test_division_by_zero_drops_the_element():
    db = _store([("good", {"i": "a"}, 5.0, 0.0),
                 ("tot", {"i": "a"}, 10.0, 0.0),
                 ("good", {"i": "b"}, 0.0, 0.0),
                 ("tot", {"i": "b"}, 0.0, 0.0)])
    # 0/0 is silence (no traffic), not a paging NaN.
    assert _eval("good / tot", db, 0.0) == [({"i": "a"}, 0.5)]


def test_comparison_filters_do_not_booleanize():
    db = _store([("m", {"i": "a"}, 5.0, 0.0),
                 ("m", {"i": "b"}, 1.0, 0.0)])
    assert _eval("m > 2", db, 0.0) == [({"i": "a"}, 5.0)]
    assert _eval("m <= 1", db, 0.0) == [({"i": "b"}, 1.0)]
    assert _eval("m == 5", db, 0.0) == [({"i": "a"}, 5.0)]


# --- the subset boundary ----------------------------------------------------


@pytest.mark.parametrize("expr,tok", [
    ("a or b", "or"),
    ("a unless b", "unless"),
    ("sum without (x) (a)", "without"),
    ('a{x=~"y"}', "=~"),
    ('a{x!="y"}', "!="),
    ("a offset 5m", "offset"),
    ("rate(a[5m:1m])", "duration"),  # subqueries are out
    ("a[5m]", "range vector"),       # bare top-level range vector
    ("avg(a)", "avg"),            # outside the agg subset
    ("irate(a[1m])", "irate"),    # outside the func subset
    ("a and on(x) b", "on"),
    ("1 > 2", ">"),               # scalar-scalar comparison
])
def test_out_of_subset_rejected_with_offending_token(expr, tok):
    with pytest.raises(PromQLError) as ei:
        parse_expr(expr)
    assert tok in str(ei.value)


def test_metric_names_walks_the_whole_tree():
    node = parse_expr("sum by (i) (rate(a[5m])) / max(b) + c")
    assert metric_names(node) == {"a", "b", "c"}


def test_parse_duration():
    assert parse_duration("90s") == 90.0
    assert parse_duration("2m") == 120.0
    assert parse_duration("1h") == 3600.0
    with pytest.raises(PromQLError):
        parse_duration("5 parsecs")


# --- rule engine ------------------------------------------------------------

_RULES_YAML = """\
groups:
  - name: test.rules
    interval: 30s
    rules:
      - record: t:m:sum
        expr: sum(m)
      - alert: MHigh
        expr: m > 10
        for: 1m
        labels:
          severity: page
        annotations:
          summary: m too high
"""


def _alert_states(engine):
    return [(a["name"], a["state"]) for a in engine.alerts()]


def test_for_duration_pending_firing_resolved():
    db = TSDB()
    engine = RuleEngine(yaml_lite_load(_RULES_YAML)["groups"], db)
    db.ingest_sample("m", {"i": "a"}, 20.0, 0.0)
    engine.evaluate(0.0)
    assert _alert_states(engine) == [("MHigh", "pending")]
    db.ingest_sample("m", {"i": "a"}, 20.0, 30.0)
    engine.evaluate(30.0)
    assert _alert_states(engine) == [("MHigh", "pending")]  # 30 < 60
    db.ingest_sample("m", {"i": "a"}, 20.0, 60.0)
    engine.evaluate(60.0)
    assert _alert_states(engine) == [("MHigh", "firing")]
    (alert,) = engine.firing()
    assert alert["labels"]["severity"] == "page"
    assert alert["active_since"] == 0.0
    # expr goes false -> resolved (gone), ALERTS series stale at once.
    db.ingest_sample("m", {"i": "a"}, 1.0, 90.0)
    engine.evaluate(90.0)
    assert engine.alerts() == []
    assert db.instant("ALERTS", None, 90.0) == []


def test_alerts_series_tracks_state_transitions():
    db = TSDB()
    engine = RuleEngine(yaml_lite_load(_RULES_YAML)["groups"], db)
    db.ingest_sample("m", {"i": "a"}, 20.0, 0.0)
    engine.evaluate(0.0)
    ((labels, v),) = db.instant("ALERTS", None, 0.0)
    assert v == 1.0 and labels["alertstate"] == "pending"
    db.ingest_sample("m", {"i": "a"}, 20.0, 60.0)
    engine.evaluate(60.0)
    # the pending series was stale-marked when the alert promoted:
    # exactly one ALERTS series visible, and it says firing.
    ((labels, _),) = db.instant("ALERTS", None, 60.0)
    assert labels["alertstate"] == "firing"
    assert labels["alertname"] == "MHigh"


def test_recording_rule_feeds_later_rules_in_same_pass():
    text = _RULES_YAML.replace("expr: m > 10", "expr: t:m:sum > 10")
    db = TSDB()
    engine = RuleEngine(yaml_lite_load(text)["groups"], db)
    db.ingest_sample("m", {"i": "a"}, 7.0, 0.0)
    db.ingest_sample("m", {"i": "b"}, 7.0, 0.0)
    engine.evaluate(0.0)
    assert db.instant("t:m:sum", None, 0.0) == [({}, 14.0)]
    assert _alert_states(engine) == [("MHigh", "pending")]


def test_interval_default_and_rule_parse():
    (group,) = yaml_lite_load(_RULES_YAML)["groups"]
    engine = RuleEngine([group], TSDB())
    ((name, interval, rules),) = engine.groups
    assert (name, interval) == ("test.rules", 30.0)
    assert [r.is_alert for r in rules] == [False, True]
    assert rules[1].for_s == 60.0


# --- the shared-parser pin (satellite: one exposition reader) ---------------


def test_exposition_parser_is_shared_not_copied():
    import tpu_top

    from k3stpu.autoscaler import signals
    from k3stpu.obs.hist import parse_prometheus_samples
    assert signals.parse_samples is parse_prometheus_samples
    assert tpu_top.parse_families is parse_prometheus_samples
    # histogram lifting and the canary/node-exporter primitives ride
    # the same reader module (no second regex stack anywhere).
    from k3stpu.obs import hist
    assert hist.parse_prometheus_histograms.__module__ == hist.__name__


# --- the chart contract -----------------------------------------------------


def _rendered_groups(qos):
    yaml = pytest.importorskip("yaml")  # noqa: F841 (render needs it)
    from k3stpu.utils.helm_lite import render_chart
    overrides = {"rules.enabled": "true"}
    if qos:
        overrides.update({"inference.enabled": "true",
                          "inference.qos.enabled": "true"})
    return load_rule_groups(render_chart(CHART, overrides=overrides))


@pytest.mark.parametrize("qos", [False, True], ids=["default", "qos"])
def test_every_shipped_rule_parses_and_evaluates(qos):
    groups = _rendered_groups(qos)
    assert groups, "chart rendered no rule groups"
    rules = [Rule(r) for g in groups for r in g["rules"]]
    assert len(rules) >= (12 if qos else 10)
    # and the engine can run the full pass on an empty store: every
    # expr evaluates (to empty vectors) without touching the reject
    # paths — the lint gate and the runtime agree on the subset.
    engine = RuleEngine(groups, TSDB())
    assert engine.evaluate(0.0) == []
    names = {r.name for r in engine.rules}
    assert "K3sTpuCanaryTokenMismatch" in names
    if qos:
        assert "K3sTpuInteractiveTtftBudgetFastBurn" in names


# --- collector HTTP surface + tpu_top integration ---------------------------


def _serve(app):
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), app)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def test_query_api_and_tpu_top_collector_mode():
    import tpu_top

    from k3stpu.obs.collector import Collector, make_collector_app
    groups = yaml_lite_load(_RULES_YAML)["groups"]
    col = Collector(groups=groups)
    col.ingest("http://fake:1", 'm{instance="a"} 20\n', 0.0)
    col.eval_rules(0.0)
    col.ingest("http://fake:1", 'm{instance="a"} 20\n', 60.0)
    col.eval_rules(60.0)
    col.last_now = 60.0
    httpd, base = _serve(make_collector_app(col))
    try:
        got = tpu_top.collector_query(base, "sum(m)")
        assert got == [({}, 20.0)]
        alerts = tpu_top.collector_alerts(base)
        assert [(a["name"], a["state"]) for a in alerts] == [
            ("MHigh", "firing")]
        # out-of-subset query: 400 with the offending token, not a 500.
        try:
            urllib.request.urlopen(
                base + "/api/query?query=m%20or%20n", timeout=5.0)
            raise AssertionError("expected HTTP 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            doc = json.loads(e.read().decode())
            assert doc["status"] == "error" and "'or'" in doc["error"]
        # /metrics self-telemetry + the synthetic ALERTS family.
        with urllib.request.urlopen(base + "/metrics",
                                    timeout=5.0) as r:
            text = r.read().decode()
        assert "k3stpu_pipeline_rules 2" in text
        assert 'ALERTS{' in text and 'alertstate="firing"' in text
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_render_table_alert_column_and_footer():
    import tpu_top
    rows = [tpu_top.node_row("http://node-a:8478", None)]
    firing = [{"name": "MHigh", "state": "firing",
               "labels": {"severity": "page"}}]
    out = tpu_top.render_table(rows, alerts=firing)
    assert "ALERTS" in out and "FIRING: MHigh" in out
    # legacy direct-scrape rendering stays byte-compatible: no column.
    assert "ALERTS" not in tpu_top.render_table(rows)


# --- sim twin alert replay --------------------------------------------------


def _replay(name, seed=0):
    from k3stpu.sim.scenarios import build_run, get_scenario
    fleet = build_run(get_scenario(name), seed=seed)
    fleet.run()
    return fleet.alert_replay.timeline


def test_sim_replay_fires_on_overload_and_only_then():
    pytest.importorskip("yaml")
    timeline = _replay("alert-replay")
    states = [s for e in timeline for (n, s) in e["alerts"]
              if n == "K3sTpuInteractiveTtftBudgetFastBurn"]
    assert "firing" in states
    assert states.index("firing") > 0  # for: 2m held it pending first
    calm = _replay("alert-replay-calm")
    assert all(not e["alerts"] for e in calm), calm


def test_sim_replay_timeline_is_byte_identical_per_seed():
    pytest.importorskip("yaml")
    a = json.dumps(_replay("alert-replay", seed=7), sort_keys=True)
    b = json.dumps(_replay("alert-replay", seed=7), sort_keys=True)
    assert a == b


# --- e2e: corruption observed from scrape data alone ------------------------


def test_e2e_canary_mismatch_alert_fires_from_scrapes():
    """A real 2-replica routed fleet, one replica silently corrupting
    its output tokens; the collector learns of it ONLY by scraping
    /metrics over HTTP and must walk K3sTpuCanaryTokenMismatch through
    pending to firing on logical timestamps."""
    from test_canary import PROMPTS, _real_fleet

    from k3stpu.canary import Canary, CanaryObs
    from k3stpu.canary.__main__ import make_canary_app
    from k3stpu.obs.collector import Collector
    from k3stpu.obs.slo import SloEngine

    servers, httpds, urls, router, rhttpd, router_url, inj = \
        _real_fleet()
    chttpd = None
    try:
        can = Canary(router_url, prompts=PROMPTS, max_new_tokens=4,
                     timeout_s=60.0, obs=CanaryObs(instance="e2e"))
        chttpd, canary_url = _serve(make_canary_app(can, SloEngine([])))
        col = Collector(router_url=router_url, targets=[canary_url],
                        groups=_rendered_groups(qos=False))
        # discovery is live: router membership, not a static list.
        targets = col.discover_targets()
        assert canary_url in targets and all(u in targets for u in urls)

        can.record_golden()
        col.step(0.0)  # baseline: clean fleet, no alert
        assert col.engine.alerts() == []

        inj.arm("gen_corrupt", times=10_000)
        for _ in range(2):  # canary acceptance bar: TWO intervals
            can.probe_round()
            if can.obs.fleet_ok.value == 0.0:
                break
        assert inj.fired("gen_corrupt") > 0
        assert can.obs.mismatch.get("replica") >= 1

        # the mismatch series is born at this scrape (LabeledCounter
        # renders a path only once seen): one window point is no delta
        # yet — increase() needs two, exactly like Prometheus.
        col.step(30.0)
        def _mismatch_states():
            # one alert instance per mismatching probe path — how many
            # paths caught the corruption varies with routing, the
            # state machine must not.
            return {a["state"] for a in col.engine.alerts()
                    if a["name"] == "K3sTpuCanaryTokenMismatch"}
        assert _mismatch_states() == set()
        can.probe_round()  # corruption persists: counter still rising
        col.step(60.0)  # second point: increase[10m] > 0 -> pending
        assert _mismatch_states() == {"pending"}
        col.step(90.0)
        col.step(120.0)  # for: 1m elapsed since 60.0
        firing = [a["name"] for a in col.engine.firing()]
        assert "K3sTpuCanaryTokenMismatch" in firing
        # and the verdict is queryable where an operator would look.
        got = col.query('increase(k3stpu_canary_mismatch_total[10m])')
        assert any(v > 0 for _, v in got)
    finally:
        if chttpd is not None:
            chttpd.shutdown()
            chttpd.server_close()
        for h in [rhttpd] + httpds:
            h.shutdown()
            h.server_close()
