"""Hand-rolled protobuf wire helpers for the kubelet device-plugin v1beta1
messages (field numbers documented in native/tpu-device-plugin/
deviceplugin.proto). Used by the fake kubelet tests to talk to the C++ plugin
through grpcio with identity serializers — no protoc plugin needed."""

from __future__ import annotations


def put_varint(buf: bytearray, v: int) -> None:
    while v >= 0x80:
        buf.append((v & 0x7F) | 0x80)
        v >>= 7
    buf.append(v)


def put_tag(buf: bytearray, field: int, wire_type: int) -> None:
    put_varint(buf, (field << 3) | wire_type)


def put_bytes(buf: bytearray, field: int, data: bytes) -> None:
    put_tag(buf, field, 2)
    put_varint(buf, len(data))
    buf.extend(data)


def put_str(buf: bytearray, field: int, s: str) -> None:
    put_bytes(buf, field, s.encode())


def put_uint(buf: bytearray, field: int, v: int) -> None:
    put_tag(buf, field, 0)
    put_varint(buf, v)


def read_varint(data: bytes, pos: int) -> tuple[int, int]:
    v = shift = 0
    while True:
        b = data[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, pos
        shift += 7


def iter_fields(data: bytes):
    """Yields (field_number, wire_type, value); value is bytes for
    length-delimited fields and int for varints."""
    pos = 0
    while pos < len(data):
        tag, pos = read_varint(data, pos)
        field, wt = tag >> 3, tag & 0x7
        if wt == 0:
            v, pos = read_varint(data, pos)
            yield field, wt, v
        elif wt == 2:
            length, pos = read_varint(data, pos)
            yield field, wt, data[pos:pos + length]
            pos += length
        elif wt == 1:
            yield field, wt, data[pos:pos + 8]
            pos += 8
        elif wt == 5:
            yield field, wt, data[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")


def fields(data: bytes, field: int) -> list:
    return [v for f, _, v in iter_fields(data) if f == field]


def first(data: bytes, field: int, default=None):
    got = fields(data, field)
    return got[0] if got else default


def parse_map(entries: list[bytes]) -> dict[str, str]:
    out = {}
    for e in entries:
        key = first(e, 1, b"").decode()
        value = first(e, 2, b"").decode()
        out[key] = value
    return out


# ------------------------------------------------------- message builders

def empty() -> bytes:
    return b""


def allocate_request(*container_device_ids: list[str]) -> bytes:
    buf = bytearray()
    for ids in container_device_ids:
        creq = bytearray()
        for d in ids:
            put_str(creq, 1, d)
        put_bytes(buf, 1, bytes(creq))
    return bytes(buf)


def preferred_request(available: list[str], size: int,
                      must: list[str] = ()) -> bytes:
    creq = bytearray()
    for d in available:
        put_str(creq, 1, d)
    for d in must:
        put_str(creq, 2, d)
    put_uint(creq, 3, size)
    buf = bytearray()
    put_bytes(buf, 1, bytes(creq))
    return bytes(buf)


# ------------------------------------------------------- message parsers

def parse_devices(law_response: bytes) -> list[dict]:
    """ListAndWatchResponse -> [{id, health, numa}]"""
    out = []
    for dev in fields(law_response, 1):
        numa = None
        topo = first(dev, 3)
        if topo is not None:
            node = first(topo, 1)
            if node is not None:
                numa = first(node, 1, 0)
        out.append({
            "id": first(dev, 1, b"").decode(),
            "health": first(dev, 2, b"").decode(),
            "numa": numa,
        })
    return out


def parse_allocate_response(resp: bytes) -> list[dict]:
    """AllocateResponse -> [{envs, mounts, devices, annotations}]"""
    out = []
    for cresp in fields(resp, 1):
        mounts = [
            {
                "container_path": first(m, 1, b"").decode(),
                "host_path": first(m, 2, b"").decode(),
                "read_only": bool(first(m, 3, 0)),
            }
            for m in fields(cresp, 2)
        ]
        devices = [
            {
                "container_path": first(d, 1, b"").decode(),
                "host_path": first(d, 2, b"").decode(),
                "permissions": first(d, 3, b"").decode(),
            }
            for d in fields(cresp, 3)
        ]
        out.append({
            "envs": parse_map(fields(cresp, 1)),
            "mounts": mounts,
            "devices": devices,
            "annotations": parse_map(fields(cresp, 4)),
        })
    return out


def parse_preferred_response(resp: bytes) -> list[list[str]]:
    return [[d.decode() for d in fields(c, 1)] for c in fields(resp, 1)]


def parse_register_request(req: bytes) -> dict:
    opts = first(req, 4)
    return {
        "version": first(req, 1, b"").decode(),
        "endpoint": first(req, 2, b"").decode(),
        "resource_name": first(req, 3, b"").decode(),
        "preferred_alloc": bool(first(opts, 2, 0)) if opts else False,
    }
