"""Correctness canary (k3stpu/canary): known-answer probes + verdicts.

Unit tests drive the prober against scriptable fake fleets (stdlib
HTTP, no jax) to pin the verdict logic per path; the E2E test is the
acceptance criterion — two REAL replicas behind a real router, one
chaos-armed to corrupt its output tokens, and the canary must flag the
mismatch within two probe rounds while every pre-existing health and
latency signal on the bad replica stays nominal (the exact gap the
canary exists to close). The synthetic-exclusion tentpole is asserted
on the same fleet: canary traffic must leave the organic latency
histograms untouched.
"""

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from k3stpu.canary import (
    CANARY_HEADER,
    PRIORITY_HEADER,
    VERDICT_MISMATCH,
    VERDICT_OK,
    VERDICT_UNREACHABLE,
    Canary,
    CanaryObs,
)
from k3stpu.chaos import FaultInjector

# --- scriptable fake fleet -------------------------------------------------

# One prompt keeps fake answer tables (and the E2E compile count) small;
# the canary derives the two-turn golden key from it.
PROMPTS = ((1, 2),)
ANSWERS = {(1, 2): [7, 8], (1, 2, 7, 8): [9, 10]}


def _start_fake(answers, corrupt=False, bad_deltas=False):
    """A fake that plays router AND replica: /debug/router membership
    is scriptable via state["replicas"], /v1/generate answers from the
    canned table (optionally corrupted / with lying SSE deltas)."""
    state = {"answers": dict(answers), "replicas": [], "corrupt": corrupt,
             "bad_deltas": bad_deltas, "canary_headers": [],
             "priority_headers": [], "body_priorities": []}

    class _H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _json(self, code, doc):
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/debug/router":
                self._json(200, {"replicas": state["replicas"]})
            elif self.path == "/healthz":
                self._json(200, {"ok": True})
            else:
                self._json(404, {"error": self.path})

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            if self.path == "/v1/session/release":
                self._json(200, {"released": True})
                return
            state["canary_headers"].append(
                self.headers.get(CANARY_HEADER))
            state["priority_headers"].append(
                self.headers.get(PRIORITY_HEADER))
            state["body_priorities"].append(body.get("priority"))
            ans = list(state["answers"][tuple(body["prompt_tokens"][0])])
            if state["corrupt"]:
                ans = [t + 1 for t in ans]
            if not body.get("stream"):
                self._json(200, {"tokens": [ans]})
                return
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.end_headers()
            deltas = [[999]] if state["bad_deltas"] else [ans[:1], ans[1:]]
            for d in deltas:
                self.wfile.write(b"data: " + json.dumps(
                    {"done": False, "rows": {"0": d}}).encode() + b"\n\n")
            self.wfile.write(b"data: " + json.dumps(
                {"done": True, "tokens": [ans]}).encode() + b"\n\n")

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    state["replicas"] = [{"url": url, "healthy": True, "draining": False}]
    return httpd, url, state


def _canary(url, **kw):
    kw.setdefault("prompts", PROMPTS)
    kw.setdefault("max_new_tokens", 2)
    kw.setdefault("timeout_s", 5.0)
    kw.setdefault("obs", CanaryObs(instance="test-canary"))
    return Canary(url, **kw)


def _by_path(results):
    out = {}
    for r in results:
        out.setdefault(r.path, []).append(r)
    return out


# --- unit: verdicts per path ----------------------------------------------


def test_golden_then_clean_round_all_paths_ok():
    httpd, url, state = _start_fake(ANSWERS)
    try:
        can = _canary(url)
        assert can.record_golden() == 2  # prompt + two-turn golden
        assert can.obs.golden_prompts.value == 2.0
        results = can.probe_round()
    finally:
        httpd.shutdown()
        httpd.server_close()
    paths = _by_path(results)
    assert set(paths) == {"router", "replica", "session", "stream"}
    assert all(r.verdict == VERDICT_OK for r in results)
    assert can.obs.fleet_ok.value == 1.0
    assert can.obs.rounds.value == 1
    assert can.obs.replicas_probed.value == 1.0
    # Every probe (and the golden recording itself) carried the
    # synthetic marker — nothing the canary sends may look organic.
    assert state["canary_headers"] and all(
        h == "1" for h in state["canary_headers"])
    # Stream probe measured per-token latency.
    assert paths["stream"][0].ttft_s is not None


def test_probes_are_tagged_interactive_end_to_end():
    """Every canary request — golden recording and all probe paths —
    must carry the interactive priority in BOTH the router header and
    the engine-facing body field, or a QoS-enabled fleet under overload
    would shed/preempt/reject its own watchdog and the correctness
    signal would flap exactly when it matters most."""
    httpd, url, state = _start_fake(ANSWERS)
    try:
        can = _canary(url)
        can.record_golden()
        can.probe_round()
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert state["priority_headers"] and all(
        h == "interactive" for h in state["priority_headers"])
    assert state["body_priorities"] and all(
        p == "interactive" for p in state["body_priorities"])


def test_corrupt_replica_direct_probe_isolates_mismatch():
    router_httpd, router_url, router_state = _start_fake(ANSWERS)
    bad_httpd, bad_url, _ = _start_fake(ANSWERS, corrupt=True)
    try:
        can = _canary(router_url)
        can.record_golden()  # against the (correct) router fake
        # Membership now gains the corrupt replica: the routed paths
        # stay green (the fake router answers correctly itself), but
        # the direct replica probe must isolate the bad one.
        router_state["replicas"].append(
            {"url": bad_url, "healthy": True, "draining": False})
        results = can.probe_round()
    finally:
        for h in (router_httpd, bad_httpd):
            h.shutdown()
            h.server_close()
    paths = _by_path(results)
    assert paths["router"][0].verdict == VERDICT_OK
    verdicts = {r.detail.split(":")[0]: r.verdict
                for r in paths["replica"]}
    assert VERDICT_MISMATCH in verdicts.values()
    assert can.obs.mismatch.get("replica") == 1
    assert can.obs.fleet_ok.value == 0.0
    bad = [r for r in paths["replica"]
           if r.verdict == VERDICT_MISMATCH][0]
    assert "want" in bad.detail and bad_url in bad.detail


def test_dead_replica_counts_unreachable():
    httpd, url, state = _start_fake(ANSWERS)
    try:
        can = _canary(url)
        can.record_golden()
        state["replicas"].append(  # nothing listens on port 1
            {"url": "http://127.0.0.1:1", "healthy": True,
             "draining": False})
        can.probe_round()
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert can.obs.unreachable.get("replica") == 1
    assert can.obs.fleet_ok.value == 0.0


def test_discovery_failure_is_one_unreachable_replica_probe():
    can = _canary("http://127.0.0.1:1")  # no router at all
    with pytest.raises(OSError):
        can.record_golden()
    can.golden = {tuple(p): [0] for p in PROMPTS}  # force past boot
    can.golden[(1, 2, 0)] = [0]
    results = can.probe_round()
    paths = _by_path(results)
    assert paths["router"][0].verdict == VERDICT_UNREACHABLE
    assert any("discovery" in r.detail for r in paths["replica"])
    assert can.obs.fleet_ok.value == 0.0


def test_chaos_canary_probe_fails_probe_not_fleet():
    httpd, url, _ = _start_fake(ANSWERS)
    inj = FaultInjector()
    try:
        can = _canary(url, chaos=inj)
        can.record_golden()
        inj.arm("canary_probe", times=1)
        results = can.probe_round()
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert inj.fired("canary_probe") == 1
    paths = _by_path(results)
    # First probe in the round (router) eats the fault; the rest of
    # the round still runs and verifies the fleet is actually fine.
    assert paths["router"][0].verdict == VERDICT_UNREACHABLE
    assert all(r.verdict == VERDICT_OK for r in paths["replica"])
    assert can.obs.unreachable.get("router") == 1


def test_stream_deltas_must_prefix_final_frame():
    httpd, url, _ = _start_fake(ANSWERS, bad_deltas=True)
    try:
        can = _canary(url, probe_session=False)
        can.record_golden()
        results = can.probe_round()
    finally:
        httpd.shutdown()
        httpd.server_close()
    paths = _by_path(results)
    stream = paths["stream"][0]
    assert stream.verdict == VERDICT_MISMATCH
    assert "deltas diverge" in stream.detail
    assert can.obs.mismatch.get("stream") == 1


def test_probe_round_requires_goldens():
    can = _canary("http://127.0.0.1:1")
    with pytest.raises(RuntimeError):
        can.probe_round()


def test_canary_obs_exposition():
    obs = CanaryObs(instance="t")
    obs.on_probe("stream", VERDICT_OK, 0.5, ttft_s=0.1, tpot_s=0.05)
    obs.on_round(True, 2)
    text = obs.render_prometheus()
    for fam in ("k3stpu_canary_ok_total", "k3stpu_canary_fleet_ok",
                "k3stpu_canary_probe_seconds_bucket",
                "k3stpu_canary_last_ttft_seconds",
                "k3stpu_canary_replicas_probed", "k3stpu_build_info"):
        assert fam in text
    assert 'k3stpu_canary_ok_total{path="stream"} 1' in text
    assert "k3stpu_canary_fleet_ok 1" in text
    assert obs.render_openmetrics().endswith("# EOF\n")


# --- E2E acceptance: real fleet, silent corruption detected ----------------


def _real_fleet():
    """Two real transformer-tiny replicas behind a real router; the
    second replica carries a FaultInjector for gen_corrupt."""
    from k3stpu.router import Router, make_router_app
    from k3stpu.serve.server import InferenceServer, make_app

    inj = FaultInjector()
    servers, httpds, urls = [], [], []
    for instance, chaos in (("canary-good", None), ("canary-bad", inj)):
        srv = InferenceServer(
            model_name="transformer-tiny", seq_len=128,
            batch_window_ms=0.0, continuous_batching=True,
            decode_block=2, prompt_cache=8, kv_page_size=16,
            kv_pages=32, shard_devices=None, instance=instance,
            chaos=chaos)
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(srv))
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        servers.append(srv)
        httpds.append(httpd)
        urls.append(f"http://127.0.0.1:{httpd.server_address[1]}")
    router = Router(urls, health_period_s=5.0, health_timeout_s=2.0,
                    proxy_timeout_s=30.0, instance="canary-router")
    rhttpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                 make_router_app(router))
    threading.Thread(target=rhttpd.serve_forever, daemon=True).start()
    router_url = f"http://127.0.0.1:{rhttpd.server_address[1]}"
    return servers, httpds, urls, router, rhttpd, router_url, inj


def _get(url):
    with urllib.request.urlopen(url, timeout=10.0) as r:
        return r.read().decode()


def test_e2e_silent_corruption_detected_within_two_rounds():
    servers, httpds, urls, router, rhttpd, router_url, inj = _real_fleet()
    bad_url = urls[1]
    try:
        can = _canary(router_url, max_new_tokens=4, timeout_s=60.0)
        can.record_golden()
        first = can.probe_round()  # clean fleet: everything verifies
        assert all(r.verdict == VERDICT_OK for r in first), \
            [(r.path, r.detail) for r in first]
        assert can.obs.fleet_ok.value == 1.0

        # Arm silent corruption on the bad replica: every generate
        # completes normally (status 200, sane latency) but every
        # output token is perturbed — invisible to health/latency.
        inj.arm("gen_corrupt", times=10_000)
        flagged_round = None
        for i in range(2):  # acceptance bar: within TWO intervals
            results = can.probe_round()
            if any(r.verdict == VERDICT_MISMATCH for r in results):
                flagged_round = i + 1
                break
        assert flagged_round is not None
        assert inj.fired("gen_corrupt") > 0  # the fault actually fired
        assert can.obs.fleet_ok.value == 0.0
        assert can.obs.mismatch.get("replica") >= 1

        # The exact gap the canary closes: every PRE-EXISTING signal
        # on the corrupting replica still reads nominal.
        health = json.loads(_get(bad_url + "/healthz"))
        assert health["ok"] is True
        bad_metrics = _get(bad_url + "/metrics")
        from k3stpu.obs.hist import parse_prometheus_histograms
        for text in (bad_metrics, _get(urls[0] + "/metrics")):
            parsed = parse_prometheus_histograms(text)
            # Tentpole exclusion: ALL traffic so far is canary traffic,
            # and none of it may land in the organic latency
            # histograms the SLO engine and autoscaler consume.
            assert parsed["k3stpu_request_e2e_seconds"]["count"] == 0
            assert parsed["k3stpu_request_ttft_seconds"]["count"] == 0
        assert "k3stpu_engine_queue_depth 0" in bad_metrics
        import re
        m = re.search(r"k3stpu_serve_synthetic_requests_total (\d+)",
                      bad_metrics)
        assert m and int(m.group(1)) > 0

        # An ORGANIC request (no canary header) still lands in the
        # histograms — the exclusion is header-scoped, not global.
        req = urllib.request.Request(
            urls[0] + "/v1/generate", method="POST",
            data=json.dumps({"prompt_tokens": [[3, 1, 2]],
                             "max_new_tokens": 2,
                             "temperature": 0.0}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60.0) as r:
            assert r.status == 200
        parsed = parse_prometheus_histograms(_get(urls[0] + "/metrics"))
        assert parsed["k3stpu_request_e2e_seconds"]["count"] == 1
    finally:
        router.close()
        rhttpd.shutdown()
        rhttpd.server_close()
        for h in httpds:
            h.shutdown()
            h.server_close()
        for s in servers:
            s.close()
