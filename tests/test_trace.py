"""Distributed tracing: W3C trace-context propagation end to end.

The contract under test (ISSUE 7): one trace id, minted at the edge
(loadgen or the server itself), survives every hop — the traceparent
echo on the HTTP response, the engine's /debug/trace timeline, the
TTFT exemplar on the OpenMetrics scrape, and the client-side Chrome
trace — and tools/trace_merge.py can stitch those exports into a
single wall-clock-aligned Perfetto timeline. The default /metrics
exposition stays byte-identical to the pre-exemplar format.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

import pytest

from k3stpu.obs.trace import (
    TRACEPARENT_MAX_LEN,
    ReqTrace,
    TraceBuffer,
    format_traceparent,
    new_span_id,
    new_trace_id,
    parse_traceparent,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import trace_merge  # noqa: E402


# --- traceparent parse/format units --------------------------------------


def test_traceparent_roundtrip():
    tid, sid = new_trace_id(), new_span_id()
    assert len(tid) == 32 and len(sid) == 16
    header = format_traceparent(tid, sid)
    assert header == f"00-{tid}-{sid}-01"
    assert parse_traceparent(header) == (tid, sid)
    assert format_traceparent(tid, sid, sampled=False).endswith("-00")


def test_trace_ids_are_random():
    assert new_trace_id() != new_trace_id()
    assert new_span_id() != new_span_id()


@pytest.mark.parametrize("header", [
    "",
    None,
    123,
    "00-abc-def-01",                                   # short fields
    "00-" + "g" * 32 + "-" + "1" * 16 + "-01",         # non-hex
    "00-" + "A" * 32 + "-" + "1" * 16 + "-01",         # uppercase
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",         # all-zero trace
    "00-" + "1" * 32 + "-" + "0" * 16 + "-01",         # all-zero span
    "ff-" + "1" * 32 + "-" + "2" * 16 + "-01",         # version ff
    "00-" + "1" * 32 + "-" + "2" * 16 + "-01-extra",   # v00 extra field
    "00-" + "1" * 32 + "-" + "2" * 16 + "-0g",         # bad flags
    "00-" + "1" * 32 + "-" + "2" * 16,                 # missing flags
    "x" * (TRACEPARENT_MAX_LEN + 1),                   # oversized
    "00-" + "1" * 32 + "-" + "2" * 16 + "-01" + "-x" * 50,  # oversized v00
])
def test_traceparent_rejects_malformed(header):
    assert parse_traceparent(header) is None


def test_traceparent_accepts_future_version_with_extra_fields():
    tid, sid = "1" * 32, "2" * 16
    assert parse_traceparent(f"cc-{tid}-{sid}-01-future-stuff") \
        == (tid, sid)


# --- lazy minting + export identity --------------------------------------


def test_reqtrace_mints_lazily_and_keeps_edge_id():
    buf = TraceBuffer()
    tr = buf.start()
    assert tr._trace_id is None  # no urandom paid yet
    tid = tr.trace_id
    assert len(tid) == 32 and tr.trace_id == tid  # stable once minted

    edge = new_trace_id()
    tr2 = buf.start(trace_id=edge)
    assert tr2._trace_id == edge and tr2.trace_id == edge
    assert tr2.to_dict()["trace_id"] == edge


def test_chrome_trace_carries_identity_and_wall_anchor():
    buf = TraceBuffer(component="client")
    tid = new_trace_id()
    tr = buf.start(trace_id=tid)
    tr.t_admit = tr.event("admit")
    tr.t_first = tr.event("first")
    tr.finish("ok")
    doc = buf.chrome_trace()
    md = doc["metadata"]
    assert md["component"] == "client"
    assert abs(md["wall_t0_s"] - buf.wall_t0_s) < 1e-3
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("name") == "process_name"}
    assert names == {"k3stpu-client"}
    rows = [e for e in doc["traceEvents"] if e.get("name") == "thread_name"]
    assert any(e["args"].get("trace_id") == tid for e in rows)
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert spans and all(e["args"]["trace_id"] == tid for e in spans)


# --- exemplar rendering ---------------------------------------------------


def test_histogram_exemplar_on_buckets_only():
    from k3stpu.obs.hist import Histogram, format_exemplar

    h = Histogram("k3stpu_t_seconds", "T.", (0.1, 1.0))
    tid = new_trace_id()
    h.observe(0.05, trace_id=tid)
    h.observe(5.0)  # no trace id -> that bucket gets no exemplar
    om = h.render_openmetrics()
    ex_lines = [ln for ln in om.splitlines() if " # {" in ln]
    assert ex_lines and all("_bucket{" in ln for ln in ex_lines)
    assert all(f'trace_id="{tid}"' in ln for ln in ex_lines)
    # The default exposition never grows exemplar syntax.
    assert " # {" not in h.render()
    # Over the spec's 128-rune label cap the exemplar is dropped whole.
    assert format_exemplar("a" * 140, 1.0, 1.0) == ""


def test_serveobs_exemplars_only_for_edge_assigned_ids():
    from k3stpu.obs import ServeObs

    obs = ServeObs()
    edge = new_trace_id()
    tr = obs.start_trace(trace_id=edge)
    obs.on_first_token(tr, 0.01)
    untraced = obs.start_trace()  # no edge id -> no exemplar, no mint
    obs.on_first_token(untraced, 0.02)
    assert untraced._trace_id is None
    om = obs.render_openmetrics()
    assert om.count(f'trace_id="{edge}"') >= 1


# --- trace_merge ----------------------------------------------------------


def _assert_chrome_trace(doc):
    """The merged artifact must load as ONE valid Chrome trace."""
    assert isinstance(doc, dict)
    ev = doc["traceEvents"]
    assert isinstance(ev, list) and ev
    for e in ev:
        assert e["ph"] in ("M", "X", "i")
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert isinstance(e["name"], str)
        if e["ph"] != "M":
            assert isinstance(e["ts"], (int, float))
        if e["ph"] == "X":
            assert e["dur"] >= 0
    json.loads(json.dumps(doc))  # round-trips as a single document


def _train_export(rank, skew_s):
    buf = TraceBuffer(component="train")
    tr = buf.start(op="train_step")
    tr.t_admit = tr.event("step")
    tr.finish("ok")
    doc = buf.chrome_trace()
    doc["metadata"].update(rank=rank, pod=f"pod-{rank}",
                           wall_t0_s=doc["metadata"]["wall_t0_s"] + skew_s)
    return doc


def test_trace_merge_training_two_ranks(tmp_path):
    paths = []
    for rank in range(2):
        p = tmp_path / f"rank{rank}.json"
        p.write_text(json.dumps(_train_export(rank, skew_s=rank * 0.25)))
        paths.append(str(p))
    out = str(tmp_path / "merged.json")
    assert trace_merge.main(["-o", out] + paths) == 0

    merged = json.loads(open(out).read())
    _assert_chrome_trace(merged)
    assert merged["metadata"]["mode"] == "training"  # auto-sniffed
    # One process row per rank, named with the rank/pod identity.
    rows = {e["args"]["name"] for e in merged["traceEvents"]
            if e.get("name") == "process_name"}
    assert rows == {"train rank 0 (pod-0)", "train rank 1 (pod-1)"}
    # Rank 1's anchor skew moved its events +250ms on the shared clock.
    t = {pid: min(e["ts"] for e in merged["traceEvents"]
                  if e["pid"] == pid and e["ph"] != "M")
         for pid in (1, 2)}
    assert 200_000 < t[2] - t[1] < 10_000_000


def test_trace_merge_serving_joins_client_and_server(tmp_path):
    tid = new_trace_id()
    docs = []
    for component in ("client", "serve"):
        buf = TraceBuffer(component=component)
        tr = buf.start(trace_id=tid)
        tr.t_admit = tr.event("admit")
        tr.t_first = tr.event("first")
        tr.finish("ok")
        docs.append(buf.chrome_trace())
    paths = []
    for i, doc in enumerate(docs):
        p = tmp_path / f"src{i}.json"
        p.write_text(json.dumps(doc))
        paths.append(str(p))
    out = str(tmp_path / "merged.json")
    assert trace_merge.main(["-o", out] + paths) == 0

    merged = json.loads(open(out).read())
    _assert_chrome_trace(merged)
    assert merged["metadata"]["mode"] == "serving"
    assert merged["metadata"]["trace_rows"] == 1
    # Both processes' spans landed on the single per-trace-id row,
    # tagged with their source component.
    spans = [e for e in merged["traceEvents"] if e["ph"] == "X"]
    assert {e["tid"] for e in spans} == {1}
    assert {e["args"]["src"] for e in spans} == {"client", "serve"}
    rows = [e for e in merged["traceEvents"]
            if e.get("name") == "thread_name"]
    assert any(e["args"].get("trace_id") == tid for e in rows)


def test_trace_merge_rejects_non_trace_input(tmp_path, capsys):
    p = tmp_path / "bogus.json"
    p.write_text(json.dumps({"not": "a trace"}))
    assert trace_merge.main(
        ["-o", str(tmp_path / "out.json"), str(p)]) == 1
    assert "no traceEvents" in capsys.readouterr().err


# --- live server: the E2E contract ---------------------------------------


@pytest.fixture(scope="module")
def engine_server():
    from k3stpu.serve.server import InferenceServer, make_app

    server = InferenceServer(model_name="transformer-tiny", seq_len=64,
                             continuous_batching=True)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    server.close()


def _get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, dict(r.headers), r.read().decode()


def _post(url, payload, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


GEN = {"prompt_tokens": [[1, 2, 3]], "max_new_tokens": 3}


def test_e2e_one_trace_id_across_three_surfaces(engine_server):
    """The acceptance path: the id a client mints shows up in (1) the
    response echo, (2) the server's /debug/trace timeline, and (3) a
    TTFT exemplar on the OpenMetrics scrape."""
    tid, sid = new_trace_id(), new_span_id()
    code, headers, _ = _post(engine_server + "/v1/generate", GEN,
                             headers={"traceparent":
                                      format_traceparent(tid, sid)})
    assert code == 200

    # (1) echo: same trace id, a FRESH server-side span id.
    echo = parse_traceparent(headers["traceparent"])
    assert echo is not None and echo[0] == tid and echo[1] != sid

    # (2) the engine's timeline carries the edge id.
    _, _, body = _get(engine_server + "/debug/trace")
    trace = json.loads(body)
    ids = {e["args"].get("trace_id") for e in trace["traceEvents"]
           if e.get("name") == "thread_name"}
    assert tid in ids

    # (3) the TTFT exemplar on the negotiated OpenMetrics scrape.
    _, h, om = _get(engine_server + "/metrics",
                    headers={"Accept": "application/openmetrics-text"})
    assert h["Content-Type"].startswith("application/openmetrics-text")
    assert om.rstrip().endswith("# EOF")
    ttft_ex = [ln for ln in om.splitlines()
               if ln.startswith("k3stpu_request_ttft_seconds_bucket")
               and f'trace_id="{tid}"' in ln]
    assert ttft_ex, "TTFT exemplar with the edge trace id missing"


def test_server_mints_when_no_header(engine_server):
    code, headers, _ = _post(engine_server + "/v1/generate", GEN)
    assert code == 200
    echo = parse_traceparent(headers["traceparent"])
    assert echo is not None  # fresh, valid identity


@pytest.mark.parametrize("bad", [
    "garbage",
    "00-" + "Z" * 32 + "-" + "1" * 16 + "-01",
    "00-" + "0" * 32 + "-" + "0" * 16 + "-01",
    "y" * 300,  # oversized
])
def test_malformed_header_served_with_fresh_id(engine_server, bad):
    """A bad traceparent is IGNORED: the request is served, a fresh id
    is minted for the echo, and the raw header bytes never surface in
    the debug timeline (they never reached the engine)."""
    code, headers, _ = _post(engine_server + "/v1/generate", GEN,
                             headers={"traceparent": bad})
    assert code == 200
    echo = parse_traceparent(headers["traceparent"])
    assert echo is not None and echo[0] not in bad
    _, _, body = _get(engine_server + "/debug/trace")
    assert bad not in body


def test_default_metrics_format_unchanged(engine_server):
    """No Accept negotiation -> the pre-exemplar text format, byte
    compatible: v0.0.4 content type, no exemplar syntax, no EOF."""
    _post(engine_server + "/v1/generate", GEN,
          headers={"traceparent":
                   format_traceparent(new_trace_id(), new_span_id())})
    _, h, text = _get(engine_server + "/metrics")
    assert h["Content-Type"] == "text/plain; version=0.0.4"
    assert " # {" not in text
    assert "# EOF" not in text
    assert "k3stpu_build_info{" in text  # new gauge, old syntax


def test_loadgen_json_and_merged_timeline(engine_server, tmp_path):
    """loadgen --json / --trace-out against a live server, then the
    client trace merged with the live /debug/trace endpoint: every
    surviving request's trace id appears in all three artifacts and the
    merged file is one valid Chrome trace."""
    from k3stpu.serve import loadgen

    json_p = str(tmp_path / "load.json")
    trace_p = str(tmp_path / "client.json")
    rc = loadgen.main(["--url", engine_server, "--model",
                       "transformer-tiny", "--clients", "2",
                       "--seconds", "1.5", "--generate-tokens", "3",
                       "--json", json_p, "--trace-out", trace_p])
    assert rc == 0

    doc = json.loads(open(json_p).read())
    recs = doc["requests"]
    assert recs and doc["summary"]["requests"] > 0
    for r in recs:
        assert set(r["trace_id"]) <= set("0123456789abcdef")
        assert len(r["trace_id"]) == 32
        assert isinstance(r["ok"], bool) and r["attempts"] >= 1
    ok_ids = {r["trace_id"] for r in recs if r["ok"]}

    # The same ids are on the server's timeline...
    _, _, body = _get(engine_server + "/debug/trace")
    server_ids = {e["args"].get("trace_id")
                  for e in json.loads(body)["traceEvents"]
                  if e.get("name") == "thread_name"}
    # (the debug ring is bounded; every id the ring still holds from
    # this run must be a loadgen id, and at least one must survive)
    assert ok_ids & server_ids

    # ...and in the client-side Chrome trace.
    client = json.loads(open(trace_p).read())
    assert client["metadata"]["component"] == "client"
    client_ids = {e["args"].get("trace_id")
                  for e in client["traceEvents"]
                  if e.get("name") == "thread_name"}
    assert ok_ids <= client_ids

    # Merge the file with the LIVE endpoint: one valid Chrome trace,
    # client and server spans joined on per-trace rows.
    out = str(tmp_path / "merged.json")
    assert trace_merge.main(
        ["-o", out, trace_p, engine_server + "/debug/trace"]) == 0
    merged = json.loads(open(out).read())
    _assert_chrome_trace(merged)
    assert merged["metadata"]["mode"] == "serving"
    srcs = {e["args"]["src"] for e in merged["traceEvents"]
            if e["ph"] == "X"}
    assert srcs == {"client", "serve"}
