"""Weight-only int8 serving quantization (k3stpu/models/quant.py).

Covers the converter's tree mapping (float Dense kernels -> int8+scale at
the same module paths), numerical fidelity of the quantized forward
against the float model, KV-cache generation through the quant config, and
the serving integration (the reference validates its serving workload by
driving it and reading the output — reference README.md:128-160; same
method here, CPU stand-in per SURVEY.md §4).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.models.quant import (
    dequantize_kernel,
    param_bytes,
    quantize_kernel,
    quantize_lm_params,
)
from k3stpu.models.transformer import transformer_lm_tiny


def _float_model_and_params(**overrides):
    model = transformer_lm_tiny(**overrides)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                           train=False)
    return model, variables


def test_quantize_kernel_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    w8, scale = quantize_kernel(w)
    assert w8.dtype == jnp.int8 and scale.shape == (32,)
    back = dequantize_kernel(w8, scale)
    # Symmetric per-channel absmax: error <= scale/2 per element.
    assert float(jnp.max(jnp.abs(back - w) / scale[None, :])) <= 0.5 + 1e-6


def test_quantize_kernel_zero_column_safe():
    w = jnp.zeros((16, 4), jnp.float32)
    w8, scale = quantize_kernel(w)
    assert float(jnp.max(jnp.abs(dequantize_kernel(w8, scale)))) == 0.0


def test_quantized_tree_matches_quant_model_init():
    model, variables = _float_model_and_params()
    qparams = quantize_lm_params(variables["params"])
    qmodel = type(model)(dataclasses.replace(model.config, quant="int8"))
    qinit = qmodel.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                        train=False)
    flat_q = jax.tree_util.tree_flatten_with_path(qparams)[0]
    flat_i = jax.tree_util.tree_flatten_with_path(qinit["params"])[0]
    assert [(p, v.shape, v.dtype) for p, v in flat_q] == \
           [(p, v.shape, v.dtype) for p, v in flat_i]
    # Projections really are int8 now: the tree must be smaller.
    assert param_bytes(qparams) < param_bytes(variables["params"])


def test_quant_forward_tracks_float_logits():
    model, variables = _float_model_and_params()
    qmodel = type(model)(dataclasses.replace(model.config, quant="int8"))
    qparams = quantize_lm_params(variables["params"])
    tokens = jax.random.randint(jax.random.key(2), (2, 16), 0,
                                model.config.vocab_size)
    ref = model.apply(variables, tokens, train=False)
    out = qmodel.apply({"params": qparams}, tokens, train=False)
    assert out.shape == ref.shape and bool(jnp.all(jnp.isfinite(out)))
    # int8 weights perturb logits slightly; rank order must survive. A
    # tiny random-init model has near-uniform logits, so compare values
    # (tight) rather than argmax (meaninglessly noisy at init).
    err = float(jnp.max(jnp.abs(out - ref)))
    span = float(jnp.max(jnp.abs(ref))) + 1e-6
    assert err / span < 0.15, f"quant drift {err:.4f} vs span {span:.4f}"


def test_generate_runs_through_quant_config():
    from k3stpu.models.generate import generate

    model, variables = _float_model_and_params(max_seq_len=32)
    qmodel = type(model)(dataclasses.replace(model.config, quant="int8"))
    qparams = quantize_lm_params(variables["params"])
    prompts = jnp.array([[5, 6, 7, 8]], jnp.int32)
    out = generate(qmodel, qparams, prompts,
                   jnp.array([4], jnp.int32), 8,
                   rng=jax.random.key(0), temperature=0.0)
    assert out.shape == (1, 8)
    assert bool(jnp.all((out >= 0) & (out < model.config.vocab_size)))


def test_server_quant_predict_and_card():
    from k3stpu.serve.server import InferenceServer

    server = InferenceServer(model_name="transformer-tiny", seq_len=16,
                             batch_window_ms=0.0, quant="int8")
    try:
        out = server.predict(np.zeros((2, 16), np.int32))
        assert out.shape[0] == 2 and np.all(np.isfinite(out))
        card = server.model_card()
        assert card["quant"]["mode"] == "int8"
        assert card["quant"]["param_bytes"] < card["quant"]["float_param_bytes"]
    finally:
        server.close()


def test_server_quant_rejects_non_lm():
    from k3stpu.serve.server import InferenceServer

    with pytest.raises(ValueError, match="quant"):
        InferenceServer(model_name="resnet18-tiny", image_size=32,
                        quant="int8")


# --- int8 KV cache ---------------------------------------------------------


def test_kv_cache_int8_shapes_and_decode_fidelity():
    """Prefill+decode with an int8 cache tracks the float-cache output."""
    from k3stpu.models.generate import init_cache

    model, variables = _float_model_and_params(max_seq_len=32)
    qcfg = dataclasses.replace(model.config, kv_cache_dtype="int8")
    qmodel = type(model)(qcfg)

    prompt = jax.random.randint(jax.random.key(3), (2, 8), 0,
                                model.config.vocab_size)
    cache_f = init_cache(model, 2)
    cache_q = init_cache(qmodel, 2)
    k_leaf = cache_q["block0"]["attn"]["key"]
    assert k_leaf.dtype == jnp.int8
    assert cache_q["block0"]["attn"]["key_scale"].shape == k_leaf.shape[:3]

    params = variables["params"]  # same float params for both
    lf, mf = model.apply({"params": params, "cache": cache_f}, prompt,
                         mode="prefill", mutable=["cache"])
    lq, mq = qmodel.apply({"params": params, "cache": cache_q}, prompt,
                          mode="prefill", mutable=["cache"])
    # Prefill attention runs on the float k/v in both: logits match tightly.
    assert float(jnp.max(jnp.abs(lf - lq))) < 1e-3

    tok = jnp.full((2, 1), 7, jnp.int32)
    df, _ = model.apply({"params": params, "cache": mf["cache"]}, tok,
                        mode="decode", mutable=["cache"])
    dq, _ = qmodel.apply({"params": params, "cache": mq["cache"]}, tok,
                         mode="decode", mutable=["cache"])
    err = float(jnp.max(jnp.abs(df - dq)))
    span = float(jnp.max(jnp.abs(df))) + 1e-6
    assert err / span < 0.15, f"int8 KV drift {err:.4f} vs span {span:.4f}"


def test_kv_cache_int8_halves_cache_bytes():
    from k3stpu.models.generate import init_cache

    model, _ = _float_model_and_params(max_seq_len=32)
    qmodel = type(model)(dataclasses.replace(model.config,
                                             kv_cache_dtype="int8"))
    fbytes = param_bytes(init_cache(model, 2))
    qbytes = param_bytes(init_cache(qmodel, 2))
    # int8 tensors + small fp32 scale planes: comfortably under 3/4.
    assert qbytes < 0.75 * fbytes


def test_kv_cache_int8_generate_and_server():
    from k3stpu.serve.server import InferenceServer

    server = InferenceServer(model_name="transformer-tiny", seq_len=16,
                             batch_window_ms=0.0, quant="int8",
                             kv_cache_dtype="int8")
    try:
        toks = server.generate_tokens([[3, 4, 5]], max_new_tokens=4)
        assert len(toks) == 1 and len(toks[0]) == 4
        card = server.model_card()
        assert card["quant"]["kv_cache_dtype"] == "int8"
    finally:
        server.close()


def test_kv_cache_dtype_rejects_unknown():
    model, variables = _float_model_and_params()
    bad = type(model)(dataclasses.replace(model.config,
                                          kv_cache_dtype="fp8"))
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        bad.apply({"params": variables["params"]},
                  jnp.zeros((1, 4), jnp.int32), mode="prefill",
                  mutable=["cache"])


# --- MoE expert quantization ------------------------------------------------


def test_moe_quant_tree_and_forward():
    from k3stpu.models.moe import moe_lm_tiny

    model = moe_lm_tiny(max_seq_len=32)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                           train=False)
    qparams = quantize_lm_params(variables["params"])

    qcfg = dataclasses.replace(
        model.config,
        base=dataclasses.replace(model.config.base, quant="int8"))
    qmodel = type(model)(qcfg)
    qinit = qmodel.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                        train=False)
    flat_q = jax.tree_util.tree_flatten_with_path(qparams)[0]
    flat_i = jax.tree_util.tree_flatten_with_path(qinit["params"])[0]
    assert [(p, v.shape, v.dtype) for p, v in flat_q] == \
           [(p, v.shape, v.dtype) for p, v in flat_i]
    assert param_bytes(qparams) < param_bytes(variables["params"])

    tokens = jax.random.randint(jax.random.key(2), (2, 16), 0,
                                model.config.base.vocab_size)
    ref = model.apply(variables, tokens, train=False)
    out = qmodel.apply({"params": qparams}, tokens, train=False)
    # Routing decisions are fp32 and unquantized; expert outputs drift
    # only by int8 weight error.
    err = float(jnp.max(jnp.abs(out - ref)))
    span = float(jnp.max(jnp.abs(ref))) + 1e-6
    assert err / span < 0.15, f"moe quant drift {err:.4f} / {span:.4f}"


def test_server_moe_quant_generate():
    from k3stpu.serve.server import InferenceServer

    server = InferenceServer(model_name="moe-tiny", seq_len=16,
                             batch_window_ms=0.0, quant="int8",
                             shard_devices=1)
    try:
        toks = server.generate_tokens([[3, 4, 5]], max_new_tokens=4)
        assert len(toks) == 1 and len(toks[0]) == 4
        card = server.model_card()
        assert card["quant"]["param_bytes"] < card["quant"]["float_param_bytes"]
    finally:
        server.close()


# --- W8A8 dynamic activation quantization -----------------------------------


def test_dynamic_quant_forward_tracks_float():
    model, variables = _float_model_and_params()
    qmodel = type(model)(dataclasses.replace(model.config,
                                             quant="int8-dynamic"))
    qparams = quantize_lm_params(variables["params"])  # same tree as int8
    tokens = jax.random.randint(jax.random.key(5), (2, 16), 0,
                                model.config.vocab_size)
    ref = model.apply(variables, tokens, train=False)
    out = qmodel.apply({"params": qparams}, tokens, train=False)
    assert out.shape == ref.shape and bool(jnp.all(jnp.isfinite(out)))
    # W8A8 adds per-token activation error on top of weight error.
    err = float(jnp.max(jnp.abs(out - ref)))
    span = float(jnp.max(jnp.abs(ref))) + 1e-6
    assert err / span < 0.25, f"W8A8 drift {err:.4f} vs span {span:.4f}"


def test_server_dynamic_quant_generate():
    from k3stpu.serve.server import InferenceServer

    server = InferenceServer(model_name="transformer-tiny", seq_len=16,
                             batch_window_ms=0.0, quant="int8-dynamic",
                             shard_devices=1)
    try:
        toks = server.generate_tokens([[3, 4, 5]], max_new_tokens=4)
        assert len(toks) == 1 and len(toks[0]) == 4
        assert server.model_card()["quant"]["mode"] == "int8-dynamic"
    finally:
        server.close()
