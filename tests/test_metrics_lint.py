"""Metric-family hygiene gate: tools/metrics_lint.py runs in tier-1.

The exposition layer is hand-rolled, so naming/HELP discipline is only
as strong as this gate — a family added without a k3stpu_ prefix, HELP
text, or the right unit suffix fails here, not in a dashboard review.
The negative tests pin the lint's own rules so a refactor of the tool
can't silently stop checking them.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import metrics_lint  # noqa: E402


def test_repo_metric_families_are_clean():
    problems = metrics_lint.lint()
    assert problems == [], "\n".join(problems)


def test_scan_actually_finds_families():
    fams = (metrics_lint._families_from_obs()
            + metrics_lint._families_from_server())
    names = [n for n, _, _ in fams]
    # Spot-check one family per source: the two facades and the
    # server's hand-emitted counters all made it into the scan.
    assert "k3stpu_request_ttft_seconds" in names
    assert "k3stpu_train_goodput_seconds_total" in names
    assert "k3stpu_predict_requests_total" in names
    assert len(names) >= 20


_COLLECTORS = ("_families_from_obs", "_families_from_server",
               "_families_from_router", "_families_from_autoscaler",
               "_families_from_canary", "_families_from_slo",
               "_families_from_collector")


def _check(fams):
    """Run the rule engine over a synthetic family list."""
    real = {name: getattr(metrics_lint, name) for name in _COLLECTORS}
    metrics_lint._families_from_obs = lambda: fams
    for name in _COLLECTORS[1:]:
        setattr(metrics_lint, name, lambda: [])
    try:
        return metrics_lint.lint()
    finally:
        for name, fn in real.items():
            setattr(metrics_lint, name, fn)


def _pad(fams):
    """Top up a synthetic list past the collector-sanity floor with
    clean filler families."""
    filler = [(f"k3stpu_filler_{i}_total", "counter", "Filler.")
              for i in range(25)]
    return fams + filler


def test_lint_rejects_bad_families():
    bad = _pad([
        ("requests_total", "counter", "No prefix."),
        ("k3stpu_UPPER", "gauge", "Bad grammar."),
        ("k3stpu_things", "counter", "Counter without _total."),
        ("k3stpu_x_total", "counter", ""),
        ("k3stpu_lat_bucket", "histogram", "Reserved suffix."),
        ("k3stpu_seconds_spent", "gauge", "Unit not a suffix."),
    ])
    problems = "\n".join(_check(bad))
    assert "missing k3stpu_ prefix" in problems
    assert "invalid Prometheus name" in problems
    assert "must end in _total" in problems
    assert "empty # HELP" in problems
    assert "reserved suffix" in problems
    assert "not suffixed _seconds" in problems


def test_lint_accepts_unit_suffix_variants():
    ok = _pad([
        ("k3stpu_a_seconds", "histogram", "Plain unit suffix."),
        ("k3stpu_b_seconds_total", "counter", "Counter over seconds."),
        ("k3stpu_c_bytes", "gauge", "Byte gauge."),
        ("k3stpu_pages_total2_total", "counter", "No unit at all."),
    ])
    assert _check(ok) == []


def test_lint_fails_when_collectors_break():
    # An empty scan is a broken scan — the gate must not pass vacuously.
    assert any("collectors are broken" in p for p in _check([]))


def test_scan_finds_canary_and_slo_families():
    canary = [n for n, _, _ in metrics_lint._families_from_canary()]
    assert "k3stpu_canary_fleet_ok" in canary
    assert "k3stpu_canary_mismatch_total" in canary
    assert "k3stpu_canary_probe_seconds" in canary
    slo = [n for n, _, _ in metrics_lint._families_from_slo()]
    assert "k3stpu_slo_burn_rate" in slo
    assert "k3stpu_slo_error_budget_remaining_ratio" in slo
    # The burn-rate family's two-label shape is in the labeled scan
    # (it is hand-rendered, so only the LINT_LABELED declaration can
    # carry it).
    labeled = dict(metrics_lint._labeled_families())
    assert labeled["k3stpu_slo_burn_rate"] == ("slo", "window")


def test_every_build_info_stamps_the_single_sourced_version():
    """Satellite of the canary PR: k3stpu.__version__ is the ONE
    version that every component's k3stpu_build_info carries — a
    facade hand-rolling its own version string fails here, not in a
    fleet dashboard join."""
    import re

    from k3stpu import __version__
    from k3stpu.autoscaler.obs import AutoscalerObs
    from k3stpu.canary.obs import CanaryObs
    from k3stpu.obs import ServeObs
    from k3stpu.obs.train import TrainObs
    from k3stpu.router.obs import RouterObs

    facades = {"serve": ServeObs(), "train": TrainObs(),
               "router": RouterObs(instance="t"),
               "autoscaler": AutoscalerObs(instance="t"),
               "canary": CanaryObs(instance="t")}
    for component, obs in facades.items():
        text = obs.build_info.render()
        m = re.search(r'version="([^"]*)"', text)
        assert m, f"{component}: build_info lost its version label"
        assert m.group(1) == __version__, component
        assert f'component="{component}"' in text


def test_scan_finds_node_exporter_families():
    names = [n for n, _, _ in metrics_lint._families_from_node_exporter()]
    assert "k3stpu_node_tpu_health" in names
    assert "k3stpu_node_chip_hbm_used_bytes" in names
    assert "k3stpu_node_drop_parse_errors_total" in names
    assert len(names) >= 13


def test_repo_rules_are_clean():
    problems = metrics_lint.lint_rules()
    assert problems == [], "\n".join(problems)


def test_rules_lint_rejects_unknown_metric_and_bad_record_name():
    fams = [("k3stpu_real_seconds", "histogram", "Real."),
            ("k3stpu_up", "gauge", "Real gauge.")]
    groups = [{"name": "g", "rules": [
        # References a family that does not exist (a rename victim).
        {"alert": "A", "expr": "k3stpu_gone_total > 1"},
        # Histogram families are known via their _bucket series.
        {"record": "k3stpu:real:p99",
         "expr": "histogram_quantile(0.99, k3stpu_real_seconds_bucket)"},
        # Recording rules must use the colon convention.
        {"record": "k3stpu_flat", "expr": "k3stpu_up"},
        {"alert": "B", "expr": "   "},
        # A recorded rule's output IS a known metric for other rules.
        {"alert": "C", "expr": "k3stpu:real:p99 > 2"},
    ]}]
    problems = "\n".join(metrics_lint.lint_rules(fams=fams, groups=groups))
    assert "k3stpu_gone_total" in problems
    assert "level:metric:operation" in problems
    assert "empty expr" in problems
    assert "k3stpu_real_seconds_bucket" not in problems
    assert "'k3stpu:real:p99'" not in problems


def test_rules_lint_fails_on_empty_render():
    assert any("no rule groups" in p
               for p in metrics_lint.lint_rules(groups=[]))


def test_build_info_duplicate_is_exempt():
    # Three metric servers each declare k3stpu_build_info (distinct
    # component labels); the duplicate rule must not fire on it, but
    # must still fire on any other repeated name.
    fams = _pad([("k3stpu_build_info", "gauge", "Build info."),
                 ("k3stpu_build_info", "gauge", "Build info."),
                 ("k3stpu_twice_total", "counter", "Dup."),
                 ("k3stpu_twice_total", "counter", "Dup.")])
    problems = "\n".join(_check(fams))
    assert "k3stpu_build_info (gauge): duplicate" not in problems
    assert "k3stpu_twice_total (counter): duplicate" in problems


def test_repo_label_keys_are_bounded():
    problems = metrics_lint.lint_label_keys()
    assert problems == [], "\n".join(problems)


def test_label_key_lint_rejects_unbounded_key():
    problems = "\n".join(metrics_lint.lint_label_keys(
        [("k3stpu_ok", ("bucket",)),
         ("k3stpu_bad", ("trace_id",))]))
    assert "k3stpu_bad" in problems and "trace_id" in problems
    assert "k3stpu_ok" not in problems
    # And an empty scan fails loudly, same as the family lint.
    assert any("no labeled families" in p
               for p in metrics_lint.lint_label_keys([]))


def test_repo_openmetrics_exposition_is_clean():
    problems = metrics_lint.lint_openmetrics(
        metrics_lint._live_openmetrics())
    assert problems == [], "\n".join(problems)


def test_openmetrics_lint_rejects_violations():
    long_id = "a" * 140
    bad = (
        "# TYPE k3stpu_x_seconds histogram\n"
        'k3stpu_x_seconds_sum 1.0 # {trace_id="abcd"} 1.0 1.000\n'
        'k3stpu_x_seconds_bucket{le="+Inf"} 1 '
        f'# {{trace_id="{long_id}"}} 1.0 1.000\n'
    )  # also: no # EOF terminator
    problems = "\n".join(metrics_lint.lint_openmetrics(bad))
    assert "exemplar on a non-bucket/non-count sample line" in problems
    assert "runes" in problems
    assert "# EOF" in problems
    # The same content made well-formed passes.
    ok = (
        "# TYPE k3stpu_x_seconds histogram\n"
        'k3stpu_x_seconds_bucket{le="+Inf"} 1 '
        '# {trace_id="abcd"} 1.0 1.000\n'
        "k3stpu_x_seconds_sum 1.0\n"
        "k3stpu_x_seconds_count 1\n"
        "# EOF\n"
    )
    assert metrics_lint.lint_openmetrics(ok) == []


def test_cli_gate_runs_clean():
    import subprocess
    import sys as _sys

    out = subprocess.run(
        [_sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                       "tools", "metrics_lint.py")],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "clean" in out.stdout
