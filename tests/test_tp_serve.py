"""Tensor-parallel serving (engine ``tp_shards=``): exactness and
accounting.

The TP contract is the dense/paged contract one more time: sharding the
attention heads, MLP hidden, and KV page pool across a 'model' mesh
axis is an EXECUTION-LAYOUT change, not a numerical one — greedy decode
must be token-identical between ``tp_shards=1`` and ``tp_shards=2`` on
the same seed, across every serving mode that touches the pool (ragged
batches, COW shared-prefix prompt cache, int8 pools, speculative
decode). The accounting half pins what the layout buys: per-shard pool
bytes halve (stats + the models/quant byte model), the
``k3stpu_serve_tp_*`` families arm only on an explicit TP engine, and
the disagg wire format stays shard-count-agnostic (a 2-shard prefill
replica hands off to a 1-shard decode replica bit-exact —
docs/DISAGG.md "TP x disagg").

Runs on the conftest-forced 8-virtual-device CPU backend; anything
needing 2+ devices skips below that.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k3stpu.models.quant import kv_page_bytes
from k3stpu.models.transformer import transformer_lm_tiny
from k3stpu.obs import ServeObs
from k3stpu.parallel.mesh import make_mesh
from k3stpu.serve.engine import GenerateEngine

needs_2 = pytest.mark.skipif(len(jax.devices()) < 2,
                             reason="needs >= 2 devices for tp_shards=2")


@pytest.fixture(scope="module")
def mp():
    model = transformer_lm_tiny(max_seq_len=64)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                           train=False)
    return model, variables["params"]


def _engine(model, params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("seed", 0)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 33)
    return GenerateEngine(model, params, **kw)


def _pair(model, params, **kw):
    """A single-chip engine and a 2-shard engine with identical
    scheduling parameters (same seed => identical sampling-key
    folds)."""
    mono = _engine(model, params, **kw)
    tp = _engine(model, params, tp_shards=2, **kw)
    return mono, tp


RAGGED = [[5, 6, 7], [3, 4, 5, 6, 7, 8, 9, 10],
          list(range(1, 21)), [40, 41]]


# --- 1. token identity across serving modes -----------------------------


@needs_2
def test_tp_ragged_greedy_token_identical(mp):
    """The headline exactness gate: concurrent ragged greedy requests
    decode token-identically on the 2-shard engine."""
    model, params = mp
    mono, tp = _pair(model, params)
    try:
        want, got = {}, {}
        for eng, out in ((mono, want), (tp, got)):
            threads = [threading.Thread(
                target=lambda p=p, e=eng, o=out: o.__setitem__(
                    id(p), e.submit([p], max_new_tokens=12)))
                for p in RAGGED]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
        assert got == want and len(want) == len(RAGGED)
    finally:
        mono.close()
        tp.close()


@needs_2
def test_tp_cow_shared_prefix_token_identical(mp):
    """Prompt-cache COW path: an exact hit and a prefix-extend both
    walk shared pages — the sharded pool must serve them identically
    and count the same hits."""
    model, params = mp
    mono, tp = _pair(model, params, prompt_cache=4)
    try:
        base = [5, 6, 7, 8, 9, 10, 11, 12, 13]
        ext = base + [20, 21, 22]
        for eng in (mono, tp):
            eng.submit([base], max_new_tokens=4)  # seed the cache
        assert (tp.submit([base], max_new_tokens=6)
                == mono.submit([base], max_new_tokens=6))
        assert (tp.submit([ext], max_new_tokens=6)
                == mono.submit([ext], max_new_tokens=6))
        for eng in (mono, tp):
            s = eng.stats()
            assert s["pcache_hits"] >= 1
            assert s["pcache_prefix_hits"] >= 1
    finally:
        mono.close()
        tp.close()


@needs_2
def test_tp_int8_pool_token_identical():
    """int8 KV pools carry a per-(page, slot, head) scale plane — also
    head-axis sharded, so quantize/dequantize must round-trip the same
    values per shard."""
    model = transformer_lm_tiny(max_seq_len=64, kv_cache_dtype="int8")
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    mono, tp = _pair(model, params)
    try:
        for p in RAGGED:
            assert (tp.submit([p], max_new_tokens=8)
                    == mono.submit([p], max_new_tokens=8))
    finally:
        mono.close()
        tp.close()


@needs_2
def test_tp_speculative_token_identical(mp):
    """Speculative decode's verify-extend dispatch writes gamma+1
    positions per row per step — the widest pool-write path, so the
    sharded scatter gets no slack here."""
    model, params = mp
    mono, tp = _pair(model, params, speculate=True)
    try:
        for p in RAGGED:
            assert (tp.submit([p], max_new_tokens=8)
                    == mono.submit([p], max_new_tokens=8))
        # Acceptance accounting must agree too: same tokens => same
        # draft/verify outcomes.
        assert tp.stats()["spec_accepted"] == mono.stats()["spec_accepted"]
    finally:
        mono.close()
        tp.close()


# --- 2. the accounting the layout buys ----------------------------------


@needs_2
def test_tp_stats_and_per_shard_bytes(mp):
    """stats() carries the shard count and the per-shard pool bill —
    halved at 2 shards, in exact agreement with the models/quant byte
    model the HBM-sizing recipe uses (docs/ARCHITECTURE.md)."""
    model, params = mp
    mono, tp = _pair(model, params)
    try:
        sm, st = mono.stats(), tp.stats()
        assert sm["tp_shards"] == 1 and st["tp_shards"] == 2
        assert sm["page_bytes"] == st["page_bytes"]  # pool-wide bill
        assert st["page_bytes_per_shard"] * 2 == sm["page_bytes_per_shard"]
        cfg = model.config
        assert (kv_page_bytes(cfg, 8, tp_shards=2) * 2
                == kv_page_bytes(cfg, 8))
        # num_pages * per-page bytes == the pool's modeled bill.
        assert (kv_page_bytes(cfg, 8, tp_shards=2) * st["pages_total"]
                == st["page_bytes_per_shard"] * st["pages_total"])
    finally:
        mono.close()
        tp.close()


def test_kv_page_bytes_tp_validation():
    cfg = transformer_lm_tiny(max_seq_len=64).config
    with pytest.raises(ValueError):
        kv_page_bytes(cfg, 8, tp_shards=0)
    with pytest.raises(ValueError):
        kv_page_bytes(cfg, 8, tp_shards=3)  # 4 kv heads % 3 != 0


@needs_2
def test_tp_obs_families_arm_only_on_explicit_tp(mp):
    """The k3stpu_serve_tp_* families render on a tp_shards=2 engine
    (shard count, all-reduce probe samples, per-shard pages-free) and
    are ABSENT from a monolithic engine's exposition — including one
    handed a pre-built mesh, the server's multi-device auto-shard
    default, which must stay byte-stable."""
    model, params = mp
    obs_tp = ServeObs()
    tp = _engine(model, params, tp_shards=2, obs=obs_tp)
    try:
        tp.submit([[5, 6, 7]], max_new_tokens=4)
        text = obs_tp.render_prometheus()
        assert "k3stpu_serve_tp_shards 2" in text
        assert "k3stpu_serve_tp_allreduce_seconds_count" in text
        # Per-shard pool series, one per shard, sampled by the loop.
        assert 'k3stpu_serve_tp_pages_free{shard="0"}' in text
        assert 'k3stpu_serve_tp_pages_free{shard="1"}' in text
        free = tp.stats()["pages_free"]
        assert obs_tp.tp_pages_free.get("0") == float(free)
        assert obs_tp.tp_pages_free.get("1") == float(free)
    finally:
        tp.close()

    obs_mono = ServeObs()
    n = len(jax.devices())
    mesh = make_mesh(n, model_parallelism=n)
    mono = _engine(model, params, mesh=mesh, obs=obs_mono)
    try:
        mono.submit([[5, 6, 7]], max_new_tokens=4)
        assert "k3stpu_serve_tp" not in obs_mono.render_prometheus()
    finally:
        mono.close()


@needs_2
def test_tp_validation_errors(mp):
    model, params = mp
    with pytest.raises(ValueError):
        _engine(model, params, tp_shards=0)
    with pytest.raises(ValueError):  # 4 heads % 3 != 0
        _engine(model, params, tp_shards=3)
    with pytest.raises(ValueError):  # more shards than devices
        _engine(model, params, tp_shards=2 * len(jax.devices()))
    with pytest.raises(ValueError):  # mesh width disagrees with knob
        mesh = make_mesh(4, model_parallelism=4)
        _engine(model, params, mesh=mesh, tp_shards=2)


# --- 3. TP x disagg: shard-count-agnostic wire format -------------------


@needs_2
def test_tp_prefill_to_mono_decode_handoff_bit_exact(mp):
    """A 2-shard prefill replica exports, a 1-shard decode replica
    imports — and decodes token-identically to a monolithic engine
    that never saw a handoff. The wire carries full head-axis-concat
    arrays (_gather_pages assembles sharded leaves on device_get), so
    the exporter's tp_shards never leaks into the bytes."""
    model, params = mp
    src = _engine(model, params, tp_shards=2, prompt_cache=4)
    dst = _engine(model, params, prompt_cache=4)
    mono = _engine(model, params, prompt_cache=4)
    try:
        p = [5, 6, 7, 8, 9, 10, 11, 12, 13]
        data = src.export_chain(p)
        assert dst.import_chain(data)
        want = mono.submit([p], max_new_tokens=6)
        assert dst.submit([p], max_new_tokens=6) == want
        s = dst.stats()
        assert s["kv_imports"] == 1 and s["pcache_hits"] == 1
        assert s["transfer_fallbacks"] == 0
        # Shard-count-agnostic means SHAPE-agnostic: the 2-shard
        # export carries the same full head-axis arrays a 1-shard one
        # does (the pool values themselves may differ in float ULPs —
        # sharded reductions re-associate), so the serialized sizes
        # match and the 1-shard bytes restore interchangeably.
        assert len(mono.export_chain(p)) == len(data)
        assert dst.import_chain(mono.export_chain(p))
    finally:
        for e in (src, dst, mono):
            e.close()


@needs_2
def test_mono_prefill_to_tp_decode_handoff_bit_exact(mp):
    """The reverse direction: a 1-shard export restores into a 2-shard
    pool (the import scatter re-splits per the DESTINATION sharding)."""
    model, params = mp
    src = _engine(model, params, prompt_cache=4)
    dst = _engine(model, params, tp_shards=2, prompt_cache=4)
    mono = _engine(model, params, prompt_cache=4)
    try:
        p = [30, 31, 32, 33, 34, 35, 36]
        assert dst.import_chain(src.export_chain(p))
        assert (dst.submit([p], max_new_tokens=6)
                == mono.submit([p], max_new_tokens=6))
        assert dst.stats()["pcache_hits"] == 1
    finally:
        for e in (src, dst, mono):
            e.close()
