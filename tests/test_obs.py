"""Observability layer: histograms, request tracing, debug endpoints.

Unit tests cover the zero-dep histogram/trace primitives; the
integration tests stand up a real engine-backed server and assert the
acceptance criteria end to end — /debug/trace yields valid Chrome-trace
JSON with the enqueue→admit→first_token→complete chain per request,
and /metrics histogram counts match requests served. The exposition
lint test is the satellite: every metric family carries # HELP/# TYPE,
names match the Prometheus grammar, and _bucket/_sum/_count triples
are internally consistent.
"""

import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

import pytest

from k3stpu.obs import (
    MAX_EVENTS_PER_TRACE,
    Gauge,
    Histogram,
    ServeObs,
    TraceBuffer,
    parse_prometheus_histograms,
    quantile_from_buckets,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# -- histogram unit tests ---------------------------------------------------


def test_histogram_observe_and_snapshot():
    h = Histogram("t_seconds", "test", bounds=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    cum, total_sum, count = h.snapshot()
    assert count == 5
    assert cum == [1, 3, 4, 5]  # cumulative incl. +Inf
    assert abs(total_sum - 56.05) < 1e-9


def test_histogram_boundary_value_lands_in_its_bucket():
    # Prometheus buckets are le= (inclusive upper bound).
    h = Histogram("t_seconds", "test", bounds=(0.1, 1.0))
    h.observe(0.1)
    cum, _, _ = h.snapshot()
    assert cum[0] == 1


def test_histogram_render_parse_roundtrip():
    h = Histogram("t_seconds", "test", bounds=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    parsed = parse_prometheus_histograms(h.render())
    assert list(parsed) == ["t_seconds"]
    p = parsed["t_seconds"]
    assert p["bounds"] == [0.1, 1.0, 10.0]
    assert p["cumulative"] == [1, 2, 3, 4]
    assert p["count"] == 4
    assert abs(p["sum"] - 55.55) < 1e-9


def test_histogram_quantile_interpolates():
    h = Histogram("t_seconds", "test", bounds=(1.0, 2.0, 4.0))
    for _ in range(10):
        h.observe(1.5)  # all land in the (1, 2] bucket
    # Linear interpolation inside the winning bucket, PromQL-style:
    # p50 -> rank 5 of 10, all 10 in bucket 2 -> 1 + (2-1) * 5/10.
    assert abs(h.quantile(0.5) - 1.5) < 1e-9
    assert abs(h.quantile(1.0) - 2.0) < 1e-9


def test_quantile_from_buckets_edge_cases():
    assert quantile_from_buckets((1.0, 2.0), [0, 0, 0], 0, 0.5) is None
    # Everything in +Inf clamps to the highest finite bound.
    assert quantile_from_buckets((1.0, 2.0), [0, 0, 3], 3, 0.5) == 2.0


def test_quantile_single_bucket_interpolates_from_zero():
    # One finite bucket, all mass in it: p50 of rank 2-of-4 sits
    # halfway up the [0, 1.0) interpolation span.
    assert quantile_from_buckets((1.0,), [4, 4], 4, 0.5) == 0.5
    assert quantile_from_buckets((1.0,), [4, 4], 4, 1.0) == 1.0


def test_quantile_total_zero_is_none_even_with_bounds():
    # total <= 0 short-circuits before any bucket walk — a scrape of
    # a fresh histogram must read as "no data", not 0.0.
    assert quantile_from_buckets((0.1, 1.0, 10.0), [0, 0, 0, 0], 0,
                                 0.99) is None
    assert quantile_from_buckets((0.1,), [0, 0], -1, 0.5) is None


def test_parse_labeled_buckets_with_exemplars():
    # The OpenMetrics render carries constant labels AFTER le= and
    # exemplar suffixes on bucket lines; the parser must read the
    # sample value, not the exemplar's value or timestamp.
    h = Histogram("t_seconds", "test", bounds=(0.1, 1.0),
                  labels={"shard": "3"})
    h.observe(0.05, trace_id="a" * 32)
    h.observe(0.5, trace_id="b" * 32)
    h.observe(5.0, trace_id="c" * 32)
    text = h.render_openmetrics()
    assert ' # {trace_id="' in text  # exemplars actually rendered
    assert '_bucket{le="0.1",shard="3"}' in text
    parsed = parse_prometheus_histograms(text)
    p = parsed["t_seconds"]
    assert p["bounds"] == [0.1, 1.0]
    assert p["cumulative"] == [1, 2, 3]
    assert p["count"] == 3
    assert abs(p["sum"] - 5.55) < 1e-9


def test_histogram_reset_and_rejects_bad_bounds():
    h = Histogram("t_seconds", "test", bounds=(1.0, 2.0))
    h.observe(1.5)
    h.reset()
    assert h.count == 0
    with pytest.raises(ValueError, match="strictly increasing"):
        Histogram("bad", "test", bounds=(2.0, 1.0))


def test_gauge_renders_help_type_and_value():
    g = Gauge("t_gauge", "a gauge", value=3.0)
    text = g.render()
    assert "# HELP t_gauge a gauge" in text
    assert "# TYPE t_gauge gauge" in text
    assert text.endswith("t_gauge 3")


# -- trace unit tests -------------------------------------------------------


def test_trace_ring_is_bounded():
    buf = TraceBuffer(capacity=4)
    for _ in range(10):
        buf.start().finish("ok")
    timelines = buf.timelines()
    assert len(timelines) == 4
    assert [t["rid"] for t in timelines] == [6, 7, 8, 9]  # most recent kept
    assert len(buf.timelines(2)) == 2


def test_trace_event_cap_counts_drops():
    buf = TraceBuffer()
    tr = buf.start()
    for i in range(MAX_EVENTS_PER_TRACE + 50):
        tr.event("decode", {"i": i})
    tr.finish("ok")
    d = tr.to_dict()
    assert len(d["events"]) == MAX_EVENTS_PER_TRACE
    # Attempted: 1 enqueue (from start) + cap+50 decodes + 1 complete.
    assert d["dropped_events"] == 52


def test_trace_finish_is_idempotent():
    buf = TraceBuffer()
    tr = buf.start()
    tr.finish("ok")
    tr.finish("error", "late failure must not overwrite")
    assert tr.status == "ok" and tr.error is None
    assert len(buf.timelines()) == 1  # not double-retired


def test_serve_obs_lifecycle_and_chrome_trace():
    obs = ServeObs()
    tr = obs.start_trace(rows=1, prompt_len=4)
    obs.on_admit(tr, 0.01, slots=1)
    obs.on_first_token(tr, 0.02)
    obs.on_dispatch(n_active=1, queue_depth=0, pages_free=7)
    obs.on_complete(tr, 0.05, 0.001)
    assert obs.ttft.count == obs.e2e.count == obs.queue_wait.count == 1
    assert obs.pages_free.value == 7.0

    doc = obs.chrome_trace()
    assert doc["displayTimeUnit"] == "ms"
    spans = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert spans == {"queue_wait", "prefill", "decode"}
    instants = {e["name"] for e in doc["traceEvents"] if e["ph"] == "i"}
    assert {"enqueue", "admit", "first_token", "complete"} <= instants
    # Spans sit on the request's tid (rid+1); tid 0 is process metadata.
    assert all(e["tid"] == tr.rid + 1
               for e in doc["traceEvents"] if e["ph"] == "X")


def test_serve_obs_disabled_is_noop():
    obs = ServeObs(enabled=False)
    tr = obs.start_trace(rows=1)
    assert tr is None
    obs.on_admit(tr, 0.01)
    obs.on_first_token(tr, 0.02)
    obs.on_complete(tr, 0.05, 0.001)
    obs.on_fail(tr, "boom")
    assert obs.ttft.count == obs.e2e.count == 0


def test_serve_obs_failure_path():
    obs = ServeObs()
    tr = obs.start_trace(rows=1)
    obs.on_admit(tr, 0.0)
    obs.on_fail(tr, "ValueError('bad prompt')")
    (d,) = obs.timelines()
    assert d["status"] == "error" and "bad prompt" in d["error"]
    assert d["events"][-1]["name"] == "fail"


def test_obs_hot_path_is_cheap():
    # Absolute-budget guard (the comparative bench is the slow test):
    # a full request lifecycle is a handful of appends + bisects and
    # must stay far under a millisecond even on a loaded CI box.
    obs = ServeObs()
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        tr = obs.start_trace(rows=1, prompt_len=8)
        obs.on_admit(tr, 0.001)
        obs.on_first_token(tr, 0.002)
        obs.on_dispatch(4, 0, 16)
        tr.event("decode", {"k": 4})
        obs.on_complete(tr, 0.01, 0.0005)
    per_req_us = (time.perf_counter() - t0) / n * 1e6
    assert per_req_us < 500, f"lifecycle cost {per_req_us:.1f}us/request"


@pytest.mark.slow
def test_obs_overhead_within_budget_on_decode_bench():
    # The acceptance bar: tracing costs <=5% decode throughput on the
    # CPU microbench. Subprocess-isolated like all bench workers.
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"),
         "--serve-obs-worker"],
        capture_output=True, text=True, timeout=600, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["metric"] == "serve_obs_overhead_pct"
    assert payload["value"] <= payload["detail"]["budget_pct"], payload


# -- server integration -----------------------------------------------------


@pytest.fixture(scope="module")
def obs_server():
    from k3stpu.serve.server import InferenceServer, make_app

    server = InferenceServer(model_name="transformer-tiny", seq_len=64,
                             batch_window_ms=0.0, continuous_batching=True,
                             engine_slots=4, shard_devices=1,
                             prompt_cache=4)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(server))
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", server
    httpd.shutdown()
    server.close()


def get(url):
    try:
        with urllib.request.urlopen(url, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def get_text(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, r.read().decode()


def post(url, payload=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload or {}).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_trace_and_histograms_after_two_requests(obs_server):
    url, server = obs_server
    server.reset_stats()
    for prompt in ([3, 4, 5], [7, 8]):
        status, body = post(url + "/v1/generate",
                            {"prompt_tokens": [prompt], "max_new_tokens": 4})
        assert status == 200, body
        assert len(body["tokens"][0]) == 4

    # /debug/requests: both timelines, each with the full lifecycle in
    # timestamp order.
    status, body = get(url + "/debug/requests?n=10")
    assert status == 200
    done = [t for t in body["requests"] if t["status"] == "ok"]
    assert len(done) == 2
    for t in done:
        names = [e["name"] for e in t["events"]]
        for must in ("enqueue", "admit", "first_token", "complete"):
            assert must in names, (must, names)
        assert (names.index("enqueue") < names.index("admit")
                < names.index("first_token") < names.index("complete"))
        times = [e["t_ms"] for e in t["events"]]
        assert times == sorted(times)
        assert any(n.startswith("pcache_") for n in names)
        assert "decode" in names

    # /debug/trace: valid Chrome-trace JSON with the same chain per rid.
    status, doc = get(url + "/debug/trace")
    assert status == 200
    assert isinstance(doc["traceEvents"], list)
    by_rid = {}
    for e in doc["traceEvents"]:
        assert {"ph", "pid", "tid", "name"} <= set(e)
        if e["ph"] == "i":
            assert isinstance(e["ts"], (int, float))
            by_rid.setdefault(e["args"]["rid"], set()).add(e["name"])
    assert len(by_rid) == 2
    for names in by_rid.values():
        assert {"enqueue", "admit", "first_token", "complete"} <= names

    # /metrics: every request-latency histogram counted both requests.
    status, text = get_text(url + "/metrics")
    assert status == 200
    hists = parse_prometheus_histograms(text)
    for name in ("k3stpu_request_ttft_seconds",
                 "k3stpu_request_e2e_seconds",
                 "k3stpu_request_queue_wait_seconds"):
        assert hists[name]["count"] == 2, (name, hists[name])
    assert hists["k3stpu_engine_batch_occupancy"]["count"] >= 2
    # Loop-sampled gauges made it into the exposition.
    assert "k3stpu_engine_queue_depth" in text
    assert "k3stpu_engine_pages_free" in text


def test_metrics_exposition_lint(obs_server):
    """Satellite: every exported family has # HELP and # TYPE, names
    match the Prometheus grammar, histogram triples are consistent."""
    url, _ = obs_server
    _, text = get_text(url + "/metrics")
    helped, typed = set(), {}
    name_re = re.compile(r"[a-z_:][a-z0-9_:]*$")
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            helped.add(line.split()[2])
            continue
        if line.startswith("# TYPE "):
            typed[line.split()[2]] = line.split()[3]
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        key = line.split(None, 1)[0]
        name = key.split("{", 1)[0]
        assert name_re.match(name), f"bad metric name: {name}"
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            stem = name[: -len(suffix)] if name.endswith(suffix) else None
            if stem and typed.get(stem) == "histogram":
                base = stem
        assert base in helped, f"{base} has samples but no # HELP"
        assert base in typed, f"{base} has samples but no # TYPE"
    for name, h in parse_prometheus_histograms(text).items():
        assert typed.get(name) == "histogram"
        assert len(h["cumulative"]) == len(h["bounds"]) + 1, name
        assert h["cumulative"] == sorted(h["cumulative"]), \
            f"{name} buckets not cumulative"
        assert h["cumulative"][-1] == h["count"], \
            f"{name} +Inf bucket != _count"


def test_debug_requests_rejects_bad_n(obs_server):
    url, _ = obs_server
    status, body = get(url + "/debug/requests?n=zzz")
    assert status == 400
    assert "n" in body["error"]


def test_debug_profile_captures_artifact(obs_server):
    url, _ = obs_server
    status, body = post(url + "/debug/profile?seconds=0.2")
    assert status == 200, body
    assert os.path.isdir(body["artifact"])
    # start_trace writes the capture under plugins/profile/.
    assert any(files for _, _, files in os.walk(body["artifact"]))


def test_stream_requests_are_traced(obs_server):
    url, server = obs_server
    server.reset_stats()
    req = urllib.request.Request(
        url + "/v1/generate",
        data=json.dumps({"prompt_tokens": [[5, 6, 7]],
                         "max_new_tokens": 4, "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        assert r.status == 200
        r.read()  # drain the SSE body to completion
    deadline = time.time() + 10
    while time.time() < deadline:
        done = [t for t in server.debug_timelines()["requests"]
                if t["status"] == "ok"]
        if done:
            break
        time.sleep(0.05)
    assert done and done[-1].get("stream") is True
    names = [e["name"] for e in done[-1]["events"]]
    assert {"enqueue", "admit", "first_token", "complete"} <= set(names)
