"""Transformer LM: shapes, causality, param count, sharded LM training."""

import jax
import jax.numpy as jnp
import numpy as np

from k3stpu.models.transformer import (
    transformer_lm_small,
    transformer_lm_tiny,
)
from k3stpu.parallel.mesh import make_mesh
from k3stpu.parallel.train import (
    make_train_bundle,
    run_synthetic_steps,
    synth_token_batch,
)


def test_forward_shape():
    model = transformer_lm_tiny()
    tokens = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.key(0), tokens)
    logits = model.apply(variables, tokens)
    assert logits.shape == (2, 16, model.config.vocab_size)
    assert logits.dtype == jnp.float32


def test_causality():
    """Changing a future token must not change past logits."""
    model = transformer_lm_tiny()
    rng = jax.random.key(0)
    tokens = jax.random.randint(rng, (1, 12), 0, model.config.vocab_size)
    variables = model.init(jax.random.key(1), tokens)
    base = model.apply(variables, tokens)
    mutated = tokens.at[0, 8].set((tokens[0, 8] + 1) % model.config.vocab_size)
    out = model.apply(variables, mutated)
    np.testing.assert_allclose(base[0, :8], out[0, :8], rtol=2e-3, atol=2e-3)
    assert not np.allclose(base[0, 8:], out[0, 8:], rtol=1e-3, atol=1e-3)


def test_small_param_count():
    """GPT-2-small scale: 12 layers x 12 heads x 768 with tied embeddings."""
    model = transformer_lm_small()
    tokens = jnp.zeros((1, 8), jnp.int32)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.key(0), tokens))
    count = sum(np.prod(x.shape) for x in
                jax.tree_util.tree_leaves(variables["params"]))
    # 12 * 12 * d^2 ~ 85M transformer + 25M embed (32768 x 768).
    assert 100e6 < count < 120e6, count


def test_sharded_lm_train_step():
    import optax

    mesh = make_mesh(8, model_parallelism=2)
    model = transformer_lm_tiny()
    bundle = make_train_bundle(
        model, mesh, example_input=jnp.zeros((1, 32), jnp.int32),
        optimizer=optax.adamw(3e-4, b1=0.9, b2=0.95, weight_decay=0.1))

    qkv = bundle.params["block0"]["attn"]["qkv"]["kernel"]
    shard_shapes = {s.data.shape for s in qkv.addressable_shards}
    assert shard_shapes == {(qkv.shape[0], qkv.shape[1] // 2)}

    losses = [
        run_synthetic_steps(
            bundle,
            lambda k: synth_token_batch(k, 8, 32, model.config.vocab_size))
        for _ in range(3)
    ]
    assert all(np.isfinite(l) for l in losses)
    # Adam on random tokens: loss should move toward uniform ~log(V).
    assert losses[-1] <= losses[0] + 1.0

def test_flash_attn_impl_matches_einsum():
    """attn_impl='flash' (Pallas interpreter on CPU) == einsum logits."""
    flash = transformer_lm_tiny(attn_impl="flash", max_seq_len=256)
    einsum = transformer_lm_tiny(attn_impl="einsum", max_seq_len=256)
    # seq=256 hits the flash gate (s % DEFAULT_BLOCK == 0).
    tokens = jax.random.randint(jax.random.key(0), (1, 256), 0,
                                flash.config.vocab_size)
    variables = flash.init(jax.random.key(1), tokens)
    out_f = flash.apply(variables, tokens)
    out_e = einsum.apply(variables, tokens)
    # bf16 activations through 2 blocks: tiny elementwise wiggle on
    # near-zero logits is expected; gate on absolute error only.
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_e),
                               atol=8e-2, rtol=0)


def test_bad_attn_impl_raises():
    import pytest

    model = transformer_lm_tiny(attn_impl="falsh")
    tokens = jnp.zeros((1, 16), jnp.int32)
    with pytest.raises(ValueError, match="attn_impl"):
        model.init(jax.random.key(0), tokens)


def test_remat_same_forward_and_grads():
    """cfg.remat must change memory behavior only: identical params tree,
    identical logits, identical gradients (activation recomputation)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from k3stpu.models.transformer import transformer_lm_tiny

    plain = transformer_lm_tiny(dtype=jnp.float32)
    remat = transformer_lm_tiny(dtype=jnp.float32, remat=True)
    tokens = jnp.arange(2 * 32, dtype=jnp.int32).reshape(2, 32) % 512
    vs = plain.init(jax.random.key(0), tokens)
    assert (jax.tree.structure(remat.init(jax.random.key(0), tokens))
            == jax.tree.structure(vs))

    def loss(model, params):
        return jnp.mean(model.apply({"params": params}, tokens) ** 2)

    lp, gp = jax.value_and_grad(lambda p: loss(plain, p))(vs["params"])
    lr, gr = jax.value_and_grad(lambda p: loss(remat, p))(vs["params"])
    np.testing.assert_allclose(float(lp), float(lr), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_gqa_lm_trains_and_caches_small():
    """n_kv_heads < n_heads: forward + grads work and the decode cache holds
    kv_heads, not n_heads (the serving-memory win)."""
    import jax
    import jax.numpy as jnp

    from k3stpu.models.transformer import transformer_lm_tiny

    model = transformer_lm_tiny(n_kv_heads=2, dtype=jnp.float32)
    tokens = jnp.arange(2 * 16, dtype=jnp.int32).reshape(2, 16) % 512
    vs = model.init(jax.random.key(0), tokens)
    logits = model.apply(vs, tokens)
    assert logits.shape == (2, 16, 512)

    grads = jax.grad(lambda p: jnp.mean(
        model.apply({"params": p}, tokens) ** 2))(vs["params"])
    assert all(bool(jnp.all(jnp.isfinite(g)))
               for g in jax.tree.leaves(grads))

    # Prefill materializes the cache at kv_heads width.
    _, mut = model.apply(vs, tokens, mode="prefill", mutable=["cache"])
    ck = mut["cache"]["block0"]["attn"]["key"]
    assert ck.shape[2] == 2  # kv heads, not the 4 query heads


def test_gqa_generate_roundtrip():
    import jax
    import jax.numpy as jnp

    from k3stpu.models.generate import generate
    from k3stpu.models.transformer import transformer_lm_tiny

    model = transformer_lm_tiny(n_kv_heads=1, max_seq_len=32)
    prompts = jnp.array([[5, 6, 7, 7], [9, 9, 2, 2]], jnp.int32)
    vs = model.init(jax.random.key(0), prompts)
    out = generate(model, vs["params"], prompts,
                   jnp.array([4, 4], jnp.int32), 8)
    assert out.shape == (2, 8)
    assert bool(jnp.all((out >= 0) & (out < 512)))


def test_sliding_window_full_vs_decode_consistent():
    """A windowed model's incremental decode must reproduce the full
    forward's logits position by position (window masks agree)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from k3stpu.models.transformer import transformer_lm_tiny

    model = transformer_lm_tiny(sliding_window=8, max_seq_len=32,
                                dtype=jnp.float32)
    tokens = (jnp.arange(24, dtype=jnp.int32)[None] * 7) % 512
    vs = model.init(jax.random.key(0), tokens)
    full = model.apply(vs, tokens)  # (1, 24, V)

    # prefill the first 16, then decode the rest one token at a time,
    # checking EVERY decoded position against the full forward (catches
    # window off-by-ones at the prefill/decode seam, not just the end).
    _, state = model.apply(vs, tokens[:, :16], mode="prefill",
                           mutable=["cache"])
    for t in range(16, 24):
        logits, state = model.apply(
            {**vs, **state}, tokens[:, t:t + 1], mode="decode",
            mutable=["cache"])
        np.testing.assert_allclose(np.asarray(logits[0, 0]),
                                   np.asarray(full[0, t]),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg=f"decode position {t}")


def test_extend_mode_matches_prefill():
    """prefill(P) == prefill(P0) then extend(P - P0): same final logits
    and identical cache contents up to each row's index — the chunked
    prefill / speculative-verify building block."""
    model = transformer_lm_tiny(max_seq_len=32)
    vs = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)
    toks = jax.random.randint(jax.random.key(5), (2, 12), 0,
                              model.config.vocab_size)

    from k3stpu.models.generate import init_cache
    full_logits, full_mut = model.apply(
        {"params": vs["params"], "cache": init_cache(model, 2)}, toks,
        mode="prefill", mutable=["cache"])

    first, rest = toks[:, :8], toks[:, 8:]
    _, mut = model.apply(
        {"params": vs["params"], "cache": init_cache(model, 2)}, first,
        mode="prefill", mutable=["cache"])
    ext_logits, mut = model.apply(
        {"params": vs["params"], "cache": mut["cache"]}, rest,
        mode="extend", mutable=["cache"])

    assert jnp.allclose(ext_logits, full_logits[:, 8:], atol=2e-2), (
        float(jnp.max(jnp.abs(ext_logits - full_logits[:, 8:]))))
    idx = mut["cache"]["block0"]["attn"]["index"]
    assert jnp.array_equal(idx, jnp.array([12, 12]))
    k_full = full_mut["cache"]["block0"]["attn"]["key"][:, :12]
    k_ext = mut["cache"]["block0"]["attn"]["key"][:, :12]
    assert jnp.allclose(k_full.astype(jnp.float32),
                        k_ext.astype(jnp.float32), atol=2e-2)


def test_extend_rollback_is_free():
    """Dropping the cache index back hides the speculated slots: decoding
    after a rollback produces the same logits as if the rolled-back
    extension never happened."""
    model = transformer_lm_tiny(max_seq_len=32)
    vs = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)
    from k3stpu.models.generate import init_cache
    prompt = jax.random.randint(jax.random.key(6), (1, 8), 0,
                                model.config.vocab_size)
    _, mut = model.apply(
        {"params": vs["params"], "cache": init_cache(model, 1)}, prompt,
        mode="prefill", mutable=["cache"])
    clean = mut["cache"]

    # Speculate 4 junk tokens, then roll back by resetting the index.
    junk = jnp.full((1, 4), 3, jnp.int32)
    _, mut2 = model.apply({"params": vs["params"], "cache": clean}, junk,
                          mode="extend", mutable=["cache"])
    rolled = jax.tree.map(lambda x: x, mut2["cache"])
    rolled = jax.tree_util.tree_map_with_path(
        lambda p, x: (jnp.full_like(x, 8)
                      if p[-1].key == "index" else x), rolled)

    tok = jnp.array([[7]], jnp.int32)
    ref, _ = model.apply({"params": vs["params"], "cache": clean}, tok,
                         mode="decode", mutable=["cache"])
    got, _ = model.apply({"params": vs["params"], "cache": rolled}, tok,
                         mode="decode", mutable=["cache"])
    assert jnp.allclose(ref, got, atol=1e-5), (
        float(jnp.max(jnp.abs(ref - got))))
