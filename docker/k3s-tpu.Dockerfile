# Control-plane image: native C++ binaries + the k3stpu python package.
#
# Runs the device-plugin DaemonSet (python launcher exec'ing the C++ gRPC
# plugin) and the feature-discovery DaemonSet — the TPU equivalents of the
# nvdp plugin and NFD/GFD images the reference's Helm installs pull
# (reference README.md:97-126).
#
# Build: docker build -f docker/k3s-tpu.Dockerfile -t ghcr.io/k3s-tpu/k3s-tpu:latest .

FROM debian:bookworm-slim AS build
RUN apt-get update && apt-get install -y --no-install-recommends \
    g++ cmake ninja-build && rm -rf /var/lib/apt/lists/*
WORKDIR /src
COPY native /src/native
RUN cmake -S native -B native/build -G Ninja -DCMAKE_BUILD_TYPE=Release \
 && cmake --build native/build

FROM python:3.11-slim
RUN pip install --no-cache-dir pyyaml
COPY --from=build /src/native/build/tpu-device-plugin \
                  /src/native/build/tpu-container-runtime \
                  /usr/local/bin/
WORKDIR /app
COPY k3stpu /app/k3stpu
ENV PYTHONPATH=/app \
    PYTHONUNBUFFERED=1

# Default role: the device plugin behind its config launcher (the chart's
# DaemonSet passes the full command; see deploy/charts/k3s-tpu/templates).
CMD ["python", "-m", "k3stpu.plugin_config", \
     "--config", "/etc/k3s-tpu/config.yaml", \
     "--exec", "/usr/local/bin/tpu-device-plugin"]
