# Workload base image: python + jax[tpu] + the k3stpu package.
#
# The TPU analogue of the reference's CUDA base image
# (nvcr.io/nvidia/cuda:12.5.0-base-ubuntu22.04, reference nvidia-smi.yaml:12)
# AND of its demo workload image (jellyfin/jellyfin, jellyfin.yaml:26): one
# image serves the probe pod (`python -m k3stpu.probe`), the inference
# Deployment (`python -m k3stpu.serve.server`), and the multi-node Job
# (`python -m k3stpu.parallel.launch`) — the command in the pod spec picks
# the role.
#
# libtpu.so itself is bind-mounted at run time by tpu-container-runtime
# (RuntimeClass `tpu`), exactly as the reference's runtime injects the CUDA
# driver libs ("will automatically copy everything needed", reference
# README.md:164) — so this image stays hardware-agnostic and also runs on
# CPU (JAX_PLATFORMS=cpu) for CI.
#
# Build: docker build -f docker/jax-tpu.Dockerfile -t ghcr.io/k3s-tpu/jax-tpu:latest .

FROM python:3.11-slim

RUN pip install --no-cache-dir \
    "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    flax optax numpy pyyaml

WORKDIR /app
COPY k3stpu /app/k3stpu
ENV PYTHONPATH=/app \
    PYTHONUNBUFFERED=1

# Default role: the diagnostic probe (override `command:` in the pod spec).
CMD ["python", "-m", "k3stpu.probe"]
