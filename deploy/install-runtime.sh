#!/bin/sh
# Per-node install of the tpu-container-runtime OCI shim — the TPU analogue
# of the reference's nvidia-container-toolkit node step (reference
# README.md:57-69: add repo, apt-get install, reboot). Here there is no
# kernel driver to install (Cloud TPU VMs ship VFIO + libtpu — SURVEY.md §1
# L1), so the whole step is: place the binary, register the containerd
# handler, restart k3s.
#
# Usage: sudo ./install-runtime.sh [path/to/tpu-container-runtime]
set -eu

BIN="${1:-$(dirname "$0")/../native/build/tpu-container-runtime}"
K3S_AGENT_DIR=/var/lib/rancher/k3s/agent/etc/containerd
DEST=/usr/local/bin/tpu-container-runtime
TMPL_V3="$(dirname "$0")/containerd/config-v3.toml.tmpl"
TMPL_V2="$(dirname "$0")/containerd/config.toml.tmpl"

[ -x "$BIN" ] || { echo "runtime binary not found: $BIN (build native/ first)" >&2; exit 1; }

install -m 0755 "$BIN" "$DEST"
echo "installed $DEST"

mkdir -p "$K3S_AGENT_DIR"
# K3S >= 1.29 reads config-v3.toml.tmpl (containerd v3 config syntax);
# older K3S reads config.toml.tmpl (containerd 1.x `io.containerd.grpc.v1.cri`
# syntax). Each name gets the file written in the syntax that K3S
# generation's containerd understands; K3S only consumes the one it knows.
install -m 0644 "$TMPL_V3" "$K3S_AGENT_DIR/config-v3.toml.tmpl"
install -m 0644 "$TMPL_V2" "$K3S_AGENT_DIR/config.toml.tmpl"
echo "installed containerd template into $K3S_AGENT_DIR"

# Restart whichever K3S unit this node runs (server or agent).
if command -v systemctl >/dev/null 2>&1; then
    if systemctl is-active --quiet k3s-agent 2>/dev/null; then
        systemctl restart k3s-agent
        echo "restarted k3s-agent"
    elif systemctl is-active --quiet k3s 2>/dev/null; then
        systemctl restart k3s
        echo "restarted k3s"
    else
        echo "k3s service not detected — restart it manually to pick up the runtime" >&2
    fi
fi

echo "done. verify with: kubectl apply -f deploy/manifests/runtimeclass-tpu.yaml"
