"""Capture the round's on-TPU proof artifacts into artifacts/.

The reference proves its stack with logged oracles read out of pods
(reference README.md:128-156); this script is the one-command equivalent for
the repo's TPU claims, each stage a bounded subprocess (same wedge-proof
discipline as bench.py — a hung tunnel degrades to a structured error line,
never a hang):

  probe   — device table + matmul MFU + compiled-attention correctness line
            + flash-vs-einsum bench table   -> artifacts/attn_rNN.log
  share   — N-way chip-sharing proof        -> artifacts/share_rNN.log
  train   — train_job run, then a SECOND run that must log a resume line
                                            -> artifacts/train_rNN.log
  serve   — loadgen before/after micro-batching (window 0 vs 5 ms)
                                            -> artifacts/serve_rNN.log

Run: python tools/capture_artifacts.py [--round 3] [--stages probe,share,...]
Exit 0 if every requested stage produced its artifact, 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from k3stpu.utils.subproc import run_bounded  # noqa: E402 (needs REPO path)

PROBE_TIMEOUT_S = 120

# Persistent XLA compilation cache shared by EVERY stage (and pre-warmed by
# backend_reachable): tunnel windows are scarce — round 3 burned 87 s of a
# 35-minute window recompiling the train step on resume — so no stage may
# pay the same compile twice. JAX reads these env vars natively; a backend
# that can't serialize executables just ignores the cache (no harm).
CACHE_DIR = os.path.join(REPO, ".jax_cache")
_CACHE_ENV = {
    "JAX_COMPILATION_CACHE_DIR": CACHE_DIR,
    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0.5",
}

# Hard stop (unix epoch) the CAPTURE itself honors — set by
# auto_capture.sh from its own deadline. The watcher's start-margin
# alone can't stop a long stage (the tune sweep's worst case is ~45 min)
# from spilling past the round-end bench and contending for the chip:
# every subprocess bound is clamped to the remaining time, and stages
# that can't get a useful slice are skipped with a structured event.
_DEADLINE = float(os.environ.get("K3STPU_CAPTURE_DEADLINE", "0")) or None

_PROBE_SRC = ("import jax; ds = jax.devices(); "
              "print('PROBE_OK', ds[0].platform, len(ds))")


def _run_bounded(cmd, timeout_s, log_path=None, env=None):
    """Bounded group-killed run (k3stpu/utils/subproc) + combined-output log."""
    if _DEADLINE is not None:
        # Clamp to remaining-minus-margin so the child AND its
        # group-kill teardown finish before the deadline instant.
        remaining = _DEADLINE - time.time() - 60
        if remaining < 60:
            msg = (f"[capture] skipped (deadline in "
                   f"{remaining + 60:.0f}s): {' '.join(cmd)}\n")
            if log_path:
                with open(log_path, "a") as f:
                    f.write(msg)
            return None, msg
        timeout_s = min(timeout_s, int(remaining))
    env = dict(os.environ if env is None else env)
    for k, v in _CACHE_ENV.items():
        env.setdefault(k, v)
    rc, out, _ = run_bounded(cmd, timeout_s, env=env, cwd=REPO,
                             merge_streams=True)
    if rc is None:
        out += f"\n[capture] TIMEOUT after {timeout_s}s (process group killed)\n"
    if log_path:
        with open(log_path, "a") as f:
            f.write(f"$ {' '.join(cmd)}\n{out}\n[capture] rc={rc}\n\n")
    return rc, out


_PLATFORM = None  # set by backend_reachable(): "tpu" | "cpu" | ...


def backend_reachable() -> bool:
    global _PLATFORM
    for _ in range(2):
        rc, out = _run_bounded([sys.executable, "-c", _PROBE_SRC],
                               PROBE_TIMEOUT_S)
        if rc == 0 and "PROBE_OK" in out:
            # Parse defensively: merged streams can glue log bytes onto
            # the marker token, and a parse miss must degrade to an
            # unknown platform, never kill the capture run.
            toks = out.split()
            _PLATFORM = next(
                (toks[i + 1] for i, t in enumerate(toks[:-1])
                 if t.endswith("PROBE_OK")), None)
            return True
        time.sleep(5)
    return False


def _oracle_ok(out: str, marker: str) -> bool:
    """True iff `marker` appears AND its JSON payload says ok: a failing
    numeric oracle must not count as a captured proof."""
    for line in out.splitlines():
        if marker in line:
            try:
                payload = line.split(marker, 1)[1].strip()
                doc, _ = json.JSONDecoder().raw_decode(payload)
                return bool(doc.get("ok"))
            except (ValueError, json.JSONDecodeError):
                return False
    return False


def stage_probe(log):
    # No --iters override: the probe's default IS bench.py's (one shared
    # measurement core, ops/matmul.py) so the two numbers are comparable.
    rc, out = _run_bounded(
        [sys.executable, "-m", "k3stpu.probe", "--attn"],
        1800, log)
    # Line-anchored: "SPMD_ATTN_JSON"/"CP_ATTN_JSON" contain "ATTN_JSON"
    # as a substring, so a bare `in` check could pass with zero actual
    # per-shape bench lines.
    has_bench = re.search(r"^ATTN_JSON ", out, re.M) is not None
    return (rc == 0 and has_bench
            and all(_oracle_ok(out, m) for m in
                    ("ATTN_CHECK_JSON", "SPMD_ATTN_JSON", "CP_ATTN_JSON")))


def stage_share(log):
    # replicas=4 IS the reference headline (reference values.yaml:18) and
    # the chart default; each child also holds ~80% of its 25% HBM share
    # through the compute window (allocation-pressure evidence, since
    # memory_stats() is empty through the relay).
    rc, out = _run_bounded(
        [sys.executable, "-m", "k3stpu.share_proof", "--replicas", "4"],
        900, log)
    # rc 0 == concurrent PASS or documented sequential fallback; rc 1 means
    # neither worked — that log is a failure record, not a proof artifact.
    return rc == 0 and "SHARE_JSON" in out


def stage_train(log):
    import tempfile

    ckpt = tempfile.mkdtemp(prefix="k3stpu-train-")
    # On a real chip, the medium (~350M) flagship: big enough that the v5e
    # step is matmul-bound (~34 TFLOP at 16x1024), so the logged MFU
    # reflects the chip, not dispatch overheads the tiny configs measure.
    # On CPU (smoke runs of this harness), train_job's own tiny default —
    # 350M on CPU would just eat both 1800 s bounds.
    # --compilation-cache: the second run's resume recompile was 87 s of a
    # 35-minute round-3 window; with the persistent cache it is a reload.
    cfg = ["--ckpt-dir", ckpt, "--ckpt-every", "10",
           "--compilation-cache", CACHE_DIR]
    if _PLATFORM not in (None, "cpu"):
        cfg = ["--model", "medium", "--remat", *cfg]
    rc1, out1 = _run_bounded(
        [sys.executable, "-m", "k3stpu.parallel.train_job", "--steps", "20",
         *cfg], 1800, log)
    rc2, out2 = _run_bounded(
        [sys.executable, "-m", "k3stpu.parallel.train_job", "--steps", "30",
         *cfg], 1800, log)
    return (rc1 == 0 and rc2 == 0 and '"event": "resume"' in out2
            and '"event": "step"' in out2)


def stage_serve(log):
    # Build tpu-info FIRST: a from-scratch cmake build can take minutes,
    # and the live-columns render below must happen within 120 s of the
    # last serving run's telemetry drop.
    tpu_info_bin = _build_tpu_info(log)
    ok = True
    # Bounded incremental pre-warm: the serve stage's first loadgen hung
    # in warmup in BOTH r3 and r5 (TUNNEL_DIAGNOSIS.md — warmup is the
    # stage's compile-heavy phase and sat at wedge onset both times).
    # --warmup-only + the shared persistent cache make each attempt keep
    # every compile that finished, so a killed attempt still moves the
    # next one forward, and the loadgen warmups below become cache-hits.
    # BOTH model configs the loadgen runs use are pre-warmed (seq_len is
    # a model parameter — the 512-token prompt-cache pair compiles
    # different programs than the default-128 runs). Failures here are
    # recorded but not fatal — the loadgen runs remain the deliverable.
    for extra in ((), ("--seq-len", "512")):
        for _ in range(2):
            rc, _out = _run_bounded(
                [sys.executable, "-m", "k3stpu.serve.server", "--model",
                 "transformer", "--warmup-only", "--continuous-batching",
                 *extra], 600, log)
            if rc == 0:
                break
    # /v1/predict: coalescing window off vs on (the micro-batcher win).
    for window in ("0", "5"):
        rc, out = _run_bounded(
            [sys.executable, "-m", "k3stpu.serve.loadgen", "--model",
             "transformer", "--clients", "8", "--seconds", "15",
             "--batch-window-ms", window], 1800, log)
        ok = ok and rc == 0 and "LOADGEN_JSON" in out
    # /v1/generate: sequential requests vs the continuous-batching engine
    # (the decode-scheduling win), same concurrent-client load; the third
    # run rides the SSE route for the on-chip TTFT number (first token ~
    # prefill latency while the total stays the full decode).
    for extra in ((), ("--continuous-batching",),
                  ("--continuous-batching", "--stream")):
        rc, out = _run_bounded(
            [sys.executable, "-m", "k3stpu.serve.loadgen", "--model",
             "transformer", "--clients", "8", "--seconds", "20",
             "--generate-tokens", "64", *extra], 1800, log)
        ok = ok and rc == 0 and "LOADGEN_JSON" in out
    # Prompt-cache win: ONE fixed 256-token prompt (loadgen's generate
    # load always reuses its prompt), so with the cache on every request
    # after the first skips its prefill — the latency/ttft delta vs the
    # cache-off run is the committed prefill-skip number.
    for extra in ((), ("--prompt-cache", "4")):
        rc, out = _run_bounded(
            [sys.executable, "-m", "k3stpu.serve.loadgen", "--model",
             "transformer", "--seq-len", "512", "--rows", "256",
             "--clients", "4", "--seconds", "12", "--generate-tokens",
             "32", "--continuous-batching", "--stream", *extra],
            1800, log)
        ok = ok and rc == 0 and "LOADGEN_JSON" in out
    # tpu-info's live columns, fed by the telemetry the serving runs just
    # dropped — rendered IMMEDIATELY so the drop file is inside the
    # tool's 120 s freshness window.
    return _capture_tpu_info(log, tpu_info_bin) and ok


def _build_tpu_info(log) -> "str | None":
    build = os.path.join(REPO, "native", "build")
    for cmd in ((["cmake", "-S", os.path.join(REPO, "native"),
                  "-B", build]),
                (["cmake", "--build", build, "--target", "tpu-info"])):
        rc, _ = _run_bounded(cmd, 600, log)
        if rc != 0:
            return None
    return os.path.join(build, "tpu-info")


def _capture_tpu_info(log, tpu_info_bin) -> bool:
    """Render the host tpu-info table with LIVE MEMORY/UTIL columns.

    The MEMORY/UTIL values come from the real drop file the serving
    process just wrote (/run/k3stpu/metrics.json, utils/telemetry.py).
    The sysfs side uses a one-v5e fake host tree: the dev box reaches its
    chip through a relay, so there is no local TPU PCI device for the
    inventory scan — the tree is the same fixture the unit tests use, and
    the log says so. Parity target: the reference's live memory/util
    table (reference README.md:78-84)."""
    import shutil
    import tempfile

    if tpu_info_bin is None:
        return False
    root = tempfile.mkdtemp(prefix="k3stpu-info-root-")
    try:
        return _render_tpu_info(log, tpu_info_bin, root)
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _render_tpu_info(log, tpu_info_bin, root) -> bool:
    import shutil
    bdf = os.path.join(root, "sys", "bus", "pci", "devices",
                       "0000:00:04.0")
    os.makedirs(bdf)
    with open(os.path.join(bdf, "vendor"), "w") as f:
        f.write("0x1ae0\n")
    with open(os.path.join(bdf, "device"), "w") as f:
        f.write("0x0062\n")
    os.makedirs(os.path.join(root, "dev"))
    open(os.path.join(root, "dev", "accel0"), "w").close()
    drop_src = "/run/k3stpu/metrics.json"
    if os.path.exists(drop_src):
        os.makedirs(os.path.join(root, "run", "k3stpu"))
        shutil.copy(drop_src, os.path.join(root, "run", "k3stpu",
                                           "metrics.json"))
    with open(log, "a") as f:
        f.write("[capture] tpu-info host-root: fake 1-chip sysfs tree "
                "(no local TPU PCI device on a relay dev box); MEMORY/"
                "UTIL values are LIVE from the serving run's drop file "
                f"{drop_src}\n")
    ok = True
    rc, _ = _run_bounded([tpu_info_bin, "--host-root", root], 60, log)
    ok = ok and rc == 0
    rc, out = _run_bounded([tpu_info_bin, "--json",
                            "--host-root", root], 60, log)
    try:
        # The merged-stream log wraps the JSON ("$ cmd" header, rc
        # trailer): raw_decode from the first brace reads exactly the
        # object and ignores the trailer.
        doc, _ = json.JSONDecoder().raw_decode(out[out.index("{"):])
        populated = any(c.get("mem_used_bytes", -1) >= 0
                        and c.get("duty_cycle_pct", -1) >= 0
                        for c in doc.get("chips", []))
    except (ValueError, json.JSONDecodeError):
        populated = False
    with open(log, "a") as f:
        f.write(f"[capture] tpu-info live columns populated: "
                f"{populated}\n")
    return ok and rc == 0 and populated


def stage_tune(log):
    """Block-size sweep on the chip: the winner calibrates DEFAULT_BLOCK
    (ops/attention.py) — committed as an artifact so the choice is a
    measurement, not a guess. The full 16-combo fwd+bwd sweep is ~32
    cold compiles; if it blows its bound on a cold cache, salvage with
    the 3-point square fwd-only sweep (whose compiles the full attempt
    likely already cached) so the window still yields a calibration.

    Appended AFTER the sweep (the calibration is the deliverable; a
    wedge mid-stage must cost the extra, not the artifact): the
    per-iteration-overhead diagnostic the r5 probe demands
    (docs/ATTN_ROOFLINE.md round-5 section). probe_r05 fit ms/iter ~
    8 + 3.3*kernel_wall INSIDE a single-dispatch fori_loop — and the
    pure-XLA einsum path showed the same ~8 ms/iter pin at S=1024, so
    the overhead is not Pallas-specific. iters=10 vs 50 at S=1024
    decides: constant ms/iter = per-iteration overhead inside the
    compiled loop (a backend/relay property); dropping ~5x = a
    per-dispatch cost, meaning the r5 probe's small-S numbers are
    floor artifacts and the kernel is fine."""
    rc, out = _run_bounded(
        [sys.executable, "-m", "k3stpu.ops.attn_tune", "--seq", "4096",
         "--batch", "8"], 1800, log)
    ok = rc == 0 and "ATTN_TUNE_BEST" in out
    if not ok:
        rc, out = _run_bounded(
            [sys.executable, "-m", "k3stpu.ops.attn_tune", "--seq", "4096",
             "--batch", "8", "--fast", "--fwd-only"], 900, log)
        ok = rc == 0 and "ATTN_TUNE_BEST" in out
    if ok:
        # Diagnostic only when the deliverable landed (i.e. the backend
        # is answering): ~1 min warm each, 300 s bound so a mid-stage
        # wedge costs minutes, not the window. The matmul pair isolates
        # the backend: a small PURE-XLA chain showing the same flat
        # ms/iter at 10 vs 50 iters proves the overhead has nothing to
        # do with attention or Pallas at all.
        # Three points, not two: flat ms/iter across 10/50/200 = a cost
        # per LOOP ITERATION (would also explain the matmul headline's
        # ~2 ms/iter gap to its walls); ms/iter falling ~linearly with
        # iters = a per-DISPATCH cost the 10-iter probe under-amortized.
        for iters in ("10", "50", "200"):
            _run_bounded(
                [sys.executable, "-m", "k3stpu.ops.attn_bench", "--seq",
                 "1024", "--batch", "8", "--fwd-only", "--flash-only",
                 "--iters", iters], 300, log)
            # Same measurement core as the headline bench, via the probe
            # CLI (BENCH_JSON carries seconds+iters; ms/iter derives).
            _run_bounded(
                [sys.executable, "-m", "k3stpu.probe", "--m", "1024",
                 "--iters", iters], 300, log)
    return ok


STAGES = {"probe": stage_probe, "share": stage_share,
          "train": stage_train, "serve": stage_serve,
          "tune": stage_tune}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="capture on-TPU artifacts")
    ap.add_argument("--round", type=int, default=3)
    ap.add_argument("--stages", default="probe,share,train,serve")
    ap.add_argument("--skip-reachability", action="store_true")
    args = ap.parse_args(argv)

    os.makedirs(os.path.join(REPO, "artifacts"), exist_ok=True)
    if not args.skip_reachability and not backend_reachable():
        print(json.dumps({"event": "capture_abort",
                          "reason": "backend unreachable (tunnel wedged?)"}),
              flush=True)
        return 1

    results = {}
    for name in args.stages.split(","):
        if _DEADLINE is not None and time.time() > _DEADLINE - 120:
            # Not enough runway for a useful stage: leave its existing
            # artifact (if any) untouched rather than truncating it.
            print(json.dumps({"event": "stage_skipped", "stage": name,
                              "reason": "deadline"}), flush=True)
            results[name] = False
            continue
        log = os.path.join(REPO, "artifacts", f"{name}_r{args.round:02d}.log")
        open(log, "w").close()  # fresh file per capture
        t0 = time.time()
        ok = STAGES[name](log)
        results[name] = ok
        print(json.dumps({"event": "stage", "stage": name, "ok": ok,
                          "seconds": round(time.time() - t0, 1),
                          "log": os.path.relpath(log, REPO)}), flush=True)

    print(json.dumps({"event": "capture_done", "results": results}),
          flush=True)
    return 0 if all(results.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
