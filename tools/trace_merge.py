#!/usr/bin/env python
"""Merge N Chrome-trace exports into one wall-clock-aligned timeline.

Every k3stpu trace export (``TraceBuffer.chrome_trace()``,
``TrainObs.chrome_trace()``, loadgen's ``--trace-out``) stamps a
``metadata`` block with its identity (component, rank/pod for
training) and ``wall_t0_s`` — the wall-clock second its exported
``ts=0`` corresponds to. That anchor is what makes this tool possible:
each source's timestamps are shifted by its offset from the earliest
anchor, so spans from independent processes land where they actually
happened relative to each other, and the merged file still opens in
``ui.perfetto.dev`` as a single timeline.

Two merge keys, picked per ``--mode`` (default ``auto`` sniffs the
sources' metadata):

- ``training``: one Perfetto process row per SOURCE, named by its
  rank/pod identity — the "did rank 1's compile stall rank 0's
  all-reduce" view across a 2..N-rank job.
- ``serving``: one thread row per TRACE ID, client and server spans of
  the same request interleaved on it (each event tagged with its
  source component) — the "where did this request's latency actually
  go, edge or engine" view.

Sources are file paths or live ``http(s)://.../debug/trace`` URLs.

Run:
    python tools/trace_merge.py -o merged.json rank0.json rank1.json
    python tools/trace_merge.py -o merged.json \\
        client.json http://127.0.0.1:8000/debug/trace
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def load_source(src: str, timeout_s: float = 10.0) -> dict:
    """One Chrome-trace dict from a file path or live /debug/trace
    URL. Raises ValueError on anything that isn't a trace export."""
    if src.startswith(("http://", "https://")):
        with urllib.request.urlopen(src, timeout=timeout_s) as resp:
            doc = json.loads(resp.read().decode("utf-8"))
    else:
        with open(src) as f:
            doc = json.load(f)
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError(f"{src}: not a Chrome trace export "
                         f"(no traceEvents)")
    return doc


def _meta(doc: dict) -> dict:
    md = doc.get("metadata")
    return md if isinstance(md, dict) else {}


def _anchor(doc: dict) -> "float | None":
    t = _meta(doc).get("wall_t0_s")
    return float(t) if isinstance(t, (int, float)) else None


def _shifts_us(docs: "list[dict]") -> "list[float]":
    """Per-source µs offset onto the shared timeline. Sources without
    an anchor (foreign traces) stay unshifted at offset 0 — visibly
    wrong beats silently guessed."""
    anchors = [_anchor(d) for d in docs]
    known = [a for a in anchors if a is not None]
    base = min(known) if known else 0.0
    return [round((a - base) * 1e6, 1) if a is not None else 0.0
            for a in anchors]


def _source_label(doc: dict, src: str, idx: int) -> str:
    md = _meta(doc)
    component = md.get("component", f"src{idx}")
    if "rank" in md:
        label = f"{component} rank {md['rank']}"
        if md.get("pod"):
            label += f" ({md['pod']})"
        return label
    return f"{component} [{src}]"


def sniff_mode(docs: "list[dict]") -> str:
    """training iff every source identifies as a train export."""
    comps = [_meta(d).get("component") for d in docs]
    return "training" if comps and all(c == "train" for c in comps) \
        else "serving"


def merge_training(docs: "list[dict]", srcs: "list[str]") -> dict:
    """One process row per source, events time-shifted onto the shared
    wall clock; tids within a source are preserved."""
    shifts = _shifts_us(docs)
    ev = []
    for idx, (doc, src) in enumerate(zip(docs, srcs)):
        pid = idx + 1
        label = _source_label(doc, src, idx)
        ev.append({"ph": "M", "pid": pid, "tid": 0,
                   "name": "process_name", "args": {"name": label}})
        for e in doc["traceEvents"]:
            if e.get("ph") == "M" and e.get("name") == "process_name":
                continue  # replaced by the identity row above
            out = dict(e)
            out["pid"] = pid
            if "ts" in out:
                out["ts"] = round(out["ts"] + shifts[idx], 1)
            ev.append(out)
    return {"traceEvents": ev, "displayTimeUnit": "ms",
            "metadata": {"merged_from": srcs, "mode": "training"}}


def merge_serving(docs: "list[dict]", srcs: "list[str]") -> dict:
    """One thread row per trace id. Each source's tid->trace_id map
    comes from its own thread_name metadata rows (TraceBuffer stamps
    the id there); spans and instants follow their tid onto the shared
    per-trace row, tagged with the source component so client and
    server segments stay distinguishable."""
    shifts = _shifts_us(docs)
    rows: "dict[str, int]" = {}       # trace_id -> merged tid
    untraced_tid = 0                   # lazily allocated catch-all row
    ev = [{"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
           "args": {"name": "k3stpu merged (by trace id)"}}]

    def row_for(trace_id: "str | None") -> int:
        nonlocal untraced_tid
        if trace_id is None:
            if untraced_tid == 0:
                untraced_tid = len(rows) + 10_000  # past any trace row
                ev.append({"ph": "M", "pid": 1, "tid": untraced_tid,
                           "name": "thread_name",
                           "args": {"name": "(untraced)"}})
            return untraced_tid
        tid = rows.get(trace_id)
        if tid is None:
            tid = rows[trace_id] = len(rows) + 1
            ev.append({"ph": "M", "pid": 1, "tid": tid,
                       "name": "thread_name",
                       "args": {"name": trace_id,
                                "trace_id": trace_id}})
        return tid

    for idx, (doc, src) in enumerate(zip(docs, srcs)):
        component = _meta(doc).get("component", f"src{idx}")
        tid_to_trace: "dict[int, str]" = {}
        for e in doc["traceEvents"]:
            if (e.get("ph") == "M" and e.get("name") == "thread_name"
                    and isinstance(e.get("args"), dict)
                    and e["args"].get("trace_id")):
                tid_to_trace[e.get("tid")] = e["args"]["trace_id"]
        for e in doc["traceEvents"]:
            if e.get("ph") == "M":
                continue  # identity rows are re-emitted by row_for()
            trace_id = (e.get("args") or {}).get("trace_id") \
                or tid_to_trace.get(e.get("tid"))
            out = dict(e)
            out["pid"] = 1
            out["tid"] = row_for(trace_id)
            out["args"] = {**(e.get("args") or {}), "src": component}
            if "ts" in out:
                out["ts"] = round(out["ts"] + shifts[idx], 1)
            ev.append(out)
    return {"traceEvents": ev, "displayTimeUnit": "ms",
            "metadata": {"merged_from": srcs, "mode": "serving",
                         "trace_rows": len(rows)}}


def merge(docs: "list[dict]", srcs: "list[str]",
          mode: str = "auto") -> dict:
    if mode == "auto":
        mode = sniff_mode(docs)
    if mode == "training":
        return merge_training(docs, srcs)
    return merge_serving(docs, srcs)


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        description="Merge k3stpu Chrome-trace exports onto one "
                    "wall-clock-aligned Perfetto timeline.")
    ap.add_argument("sources", nargs="+",
                    help="trace files or live /debug/trace URLs")
    ap.add_argument("-o", "--out", required=True,
                    help="merged Chrome-trace JSON output path")
    ap.add_argument("--mode", choices=("auto", "serving", "training"),
                    default="auto",
                    help="merge key: per-rank rows (training) or "
                         "per-trace-id rows (serving); auto sniffs "
                         "the sources' metadata")
    args = ap.parse_args(argv)

    docs = []
    for src in args.sources:
        try:
            docs.append(load_source(src))
        except Exception as e:
            print(f"trace-merge: {src}: {e}", file=sys.stderr)
            return 1
    merged = merge(docs, args.sources, mode=args.mode)
    with open(args.out, "w") as f:
        json.dump(merged, f)
    mode = merged["metadata"]["mode"]
    print(f"trace-merge: {len(docs)} sources -> {args.out} "
          f"({mode}, {len(merged['traceEvents'])} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
