#!/bin/bash
# Poll the device tunnel; on the first healthy window, run the round's
# remaining artifact captures exactly once — then GIT-COMMIT whatever was
# captured so a later session death cannot lose the round's evidence
# (rounds 3-4 lost or nearly lost all hardware evidence to exactly that).
# Survives the shell that launched it (run with nohup/setsid). All chip
# work stays inside capture_artifacts.py's bounded, group-killed
# subprocesses.
#
#   nohup tools/auto_capture.sh 5 "probe,share,serve,tune,train" \
#       "$(( $(date +%s) + 36000 ))" > /tmp/auto_capture.log 2>&1 & disown
#
# Evidence-pipeline rules this script enforces:
#   - every poll result is appended to artifacts/tunnel_poll_rNN.jsonl
#     (committed with the captures — never only in /tmp);
#   - default stage order is probe-first/shortest-first so even a
#     5-minute window yields the headline matmul number;
#   - the healthy probe is bounded at 60 s (the 256^2 matmul compile is
#     in the persistent cache; a healthy tunnel answers in ~10 s) with
#     60 s spacing — a wedge is detected as "did not answer in 60 s",
#     and a false WEDGED on a slow-but-alive tunnel only costs one poll.
ROUND="${1:-5}"
STAGES="${2:-probe,share,serve,tune,train}"
DEADLINE_EPOCH="${3:-0}"   # 0 = no deadline; else stop polling after this
case "$DEADLINE_EPOCH" in
  ''|*[!0-9]*) echo "DEADLINE_EPOCH must be a unix timestamp (or 0)"; exit 2;;
esac
# K3STPU_REPO override exists for running a SNAPSHOT COPY of this script
# (editing the repo copy while a watcher executes it corrupts the running
# bash); the default works from any clone location.
REPO="${K3STPU_REPO:-$(cd "$(dirname "$0")/.." && pwd)}"
MARKER="/tmp/auto_capture_done_r${ROUND}"
cd "$REPO" || exit 1
POLL_LOG="artifacts/tunnel_poll_r$(printf '%02d' "$((10#$ROUND))").jsonl"
mkdir -p artifacts

log_poll() {  # $1=status $2=probe_seconds $3=poll_index
  printf '{"ts": "%s", "status": "%s", "probe_s": %s, "poll": %s}\n' \
    "$(date -u +%FT%TZ)" "$1" "$2" "$3" >> "$POLL_LOG"
}

commit_artifacts() {  # $1 = commit subject; retries around index-lock races
  # Benign no-op when artifacts/ has no changes (e.g. watcher launched
  # past its deadline) — the retry loop is for index-lock races only.
  [ -z "$(git status --porcelain -- artifacts/)" ] && return 0
  for _ in 1 2 3; do
    git add artifacts/ && \
      git commit -q -m "$1" \
        -m "No-Verification-Needed: artifact capture logs only, no source change" \
        -- artifacts/ \
      && { echo "$(date -u +%H:%M:%S) committed: $1"; return 0; }
    sleep 5
  done
  echo "$(date -u +%H:%M:%S) WARNING: could not commit artifacts"
  return 1
}

[ -e "$MARKER" ] && { echo "already captured (rm $MARKER to redo)"; exit 0; }

for i in $(seq 1 600); do
  if [ "$DEADLINE_EPOCH" -gt 0 ] && [ "$(date +%s)" -ge "$DEADLINE_EPOCH" ]; then
    # Stop BEFORE the driver's end-of-round bench: a capture firing while
    # the judge benchmarks would contend for the one chip.
    echo "$(date -u +%H:%M:%S) deadline reached; stopping watcher"
    commit_artifacts "Tunnel poll log: round-$ROUND watcher hit its deadline"
    exit 0
  fi
  t0=$(date +%s)
  out=$(timeout 70 python - <<'PY' 2>/dev/null
from k3stpu.utils.subproc import run_bounded
import sys
rc, _, _ = run_bounded([sys.executable, "-c",
    "import jax, jax.numpy as jnp; "
    "x = jnp.ones((256, 256), jnp.bfloat16); print(float((x @ x).sum()))"],
    60)
print("HEALTHY" if rc == 0 else "WEDGED")
PY
)
  dt=$(( $(date +%s) - t0 ))
  [ "$out" = "HEALTHY" ] || out="WEDGED"
  echo "$(date -u +%H:%M:%S) $out ${dt}s (poll $i)"
  log_poll "$out" "$dt" "$i"
  if [ "$out" = "HEALTHY" ]; then
    if [ "$DEADLINE_EPOCH" -gt 0 ] \
        && [ "$(( $(date +%s) + 600 ))" -ge "$DEADLINE_EPOCH" ]; then
      # Too close to the deadline for a multi-minute capture — a run
      # spilling past it would contend with the round-end bench.
      echo "$(date -u +%H:%M:%S) healthy but inside deadline margin; stop"
      commit_artifacts "Tunnel poll log: healthy inside round-$ROUND deadline margin"
      exit 0
    fi
    echo "$(date -u +%H:%M:%S) tunnel healthy -> capturing stages: $STAGES"
    # The capture honors the deadline itself (clamped subprocess bounds,
    # stage skips); 0 means "no deadline" on both sides.
    K3STPU_CAPTURE_DEADLINE="$DEADLINE_EPOCH" \
      python tools/capture_artifacts.py --round "$ROUND" --stages "$STAGES"
    rc=$?
    echo "$(date -u +%H:%M:%S) capture exited rc=$rc"
    touch "$MARKER"
    commit_artifacts "Capture round-$ROUND on-chip artifacts (watcher, rc=$rc)"
    exit "$rc"
  fi
  sleep 60
done
echo "gave up after 600 polls"
commit_artifacts "Tunnel poll log: round-$ROUND watcher exhausted its polls"
exit 1
