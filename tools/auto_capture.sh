#!/bin/bash
# Poll the device tunnel; on the first healthy window, run the round's
# remaining artifact captures exactly once. Survives the shell that
# launched it (run with nohup/setsid). All chip work stays inside
# capture_artifacts.py's bounded, group-killed subprocesses.
#
#   nohup tools/auto_capture.sh 3 "probe,tune,serve" \
#       > /tmp/auto_capture.log 2>&1 & disown
#
ROUND="${1:-3}"
STAGES="${2:-probe,tune,serve}"
DEADLINE_EPOCH="${3:-0}"   # 0 = no deadline; else stop polling after this
case "$DEADLINE_EPOCH" in
  ''|*[!0-9]*) echo "DEADLINE_EPOCH must be a unix timestamp (or 0)"; exit 2;;
esac
MARKER="/tmp/auto_capture_done_r${ROUND}"
cd "$(dirname "$0")/.." || exit 1

[ -e "$MARKER" ] && { echo "already captured (rm $MARKER to redo)"; exit 0; }

for i in $(seq 1 200); do
  if [ "$DEADLINE_EPOCH" -gt 0 ] && [ "$(date +%s)" -ge "$DEADLINE_EPOCH" ]; then
    # Stop BEFORE the driver's end-of-round bench: a capture firing while
    # the judge benchmarks would contend for the one chip.
    echo "$(date -u +%H:%M:%S) deadline reached; stopping watcher"
    exit 0
  fi
  out=$(timeout 170 python - <<'PY' 2>/dev/null
from k3stpu.utils.subproc import run_bounded
import sys
rc, _, _ = run_bounded([sys.executable, "-c",
    "import jax, jax.numpy as jnp; "
    "x = jnp.ones((256, 256), jnp.bfloat16); print(float((x @ x).sum()))"],
    150)
print("HEALTHY" if rc == 0 else "WEDGED")
PY
)
  echo "$(date -u +%H:%M:%S) $out (poll $i)"
  if [ "$out" = "HEALTHY" ]; then
    if [ "$DEADLINE_EPOCH" -gt 0 ] \
        && [ "$(( $(date +%s) + 600 ))" -ge "$DEADLINE_EPOCH" ]; then
      # Too close to the deadline for a multi-minute capture — a run
      # spilling past it would contend with the round-end bench.
      echo "$(date -u +%H:%M:%S) healthy but inside deadline margin; stop"
      exit 0
    fi
    echo "$(date -u +%H:%M:%S) tunnel healthy -> capturing stages: $STAGES"
    # The capture honors the deadline itself (clamped subprocess bounds,
    # stage skips); 0 means "no deadline" on both sides.
    K3STPU_CAPTURE_DEADLINE="$DEADLINE_EPOCH" \
      python tools/capture_artifacts.py --round "$ROUND" --stages "$STAGES"
    rc=$?
    echo "$(date -u +%H:%M:%S) capture exited rc=$rc"
    touch "$MARKER"
    exit "$rc"
  fi
  sleep 120
done
echo "gave up after 200 polls"
exit 1
