#!/usr/bin/env python
"""Static lint for the repo's Prometheus metric families.

The exposition layer is hand-rolled (no client library — see
obs/hist.py), which means nothing stops a new family from shipping
without HELP text, with a bare un-prefixed name, or with a unit baked
into the wrong place. This lint closes that gap and runs in tier-1
(tests/test_metrics_lint.py), so drift fails CI instead of landing in a
dashboard:

- every family name carries the ``k3stpu_`` prefix and matches the
  Prometheus name grammar;
- every family has non-empty ``# HELP`` text;
- counters end in ``_total``;
- a name that mentions a unit uses it as the proper suffix
  (``_seconds`` / ``_bytes``, with ``_seconds_total`` etc. for
  counters) — no ``k3stpu_seconds_spent_x``;
- histogram families never end in the reserved ``_bucket`` / ``_sum``
  / ``_count`` / ``_total`` suffixes (render() appends those);
- no two families share a name.

Families are collected from the real objects where that is cheap
(``ServeObs`` / ``TrainObs`` / the node exporter's ``NodeCollector``
all construct without jax), and from the ``_emit(lines, "name",
"type", "help", ...)`` call sites in serve/server.py by regex where
instantiation would need a device.

``lint_rules()`` extends the gate to the chart's Prometheus
recording/alerting rules (templates/rules.yaml): every ``k3stpu_*``
metric a rule expression references must exist in a linted family
(histograms count via their ``_bucket``/``_sum``/``_count`` series),
or be the output of another recording rule in the same bundle — so a
metric rename fails the lint instead of silently blanking an alert.

Run: python tools/metrics_lint.py   (exit 0 clean, 1 with findings)
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Prometheus metric name grammar (exposition format spec).
NAME_RE = re.compile(r"^[a-z_:][a-z0-9_:]*$")

# `_emit(lines, "<name>", "<type>", "<help head>"...)` call sites —
# multi-line, so the help string is whatever first literal follows the
# type. The _emit helper always renders # HELP from it; lint only that
# the literal is non-empty.
EMIT_RE = re.compile(
    r'emit\(\s*lines,\s*"([^"]+)",\s*"([a-z]+)",\s*\n?\s*"([^"]*)',
    re.S)

RESERVED_HIST_SUFFIXES = ("_bucket", "_sum", "_count", "_total")
UNITS = ("seconds", "bytes")

# Families that legitimately appear in more than one exposition: every
# metric server (serve, train rank-0, node exporter) declares its own
# k3stpu_build_info with a distinct ``component`` label, so the same
# name showing up three times in the scan is the design, not a clash.
DUPLICATE_EXEMPT = {"k3stpu_build_info"}

# Label keys whose value sets are bounded by construction: goodput
# buckets and health states are fixed enums, chips/files are bounded by
# the hardware inventory and live process count, version/component by
# the build, replica/instance by the configured fleet, reason by the
# router's fixed routing-decision enum. A Labeled* family declaring any
# OTHER key (rid, trace_id, pod, user...) is a cardinality bomb waiting
# for a dashboard, so the lint rejects it until the key is reviewed and
# added here. "backend" is the attention-backend enum (xla-gather /
# pallas-paged), fixed at construction on the decode-dispatch histogram;
# "direction" is the autoscaler's fixed {up, down} enum; "role" is the
# disagg serving-role enum (prefill / decode) on k3stpu_build_info;
# "shard" is bounded by --tp-shards (the per-shard pages-free series a
# TP replica appends, k3stpu_engine_pages_free{shard="i"}); "tp_shards"
# is the single configured shard count stamped on k3stpu_build_info.
BOUNDED_LABEL_KEYS = {"bucket", "state", "chip", "file",
                      "component", "version", "instance",
                      "replica", "reason", "backend", "direction",
                      "role", "shard", "tp_shards",
                      # "path" is the canary's fixed probe-path enum
                      # (router/replica/session/stream); "slo" the
                      # declared SloSpec names; "window" the fixed
                      # burn-rate horizon enum (5m/1h/6h/3d).
                      "path", "slo", "window",
                      # "class" is the QoS priority-class enum
                      # (interactive/batch, docs/QOS.md) on the
                      # per-class queue-depth and admission-rejection
                      # families.
                      "class"}

# OpenMetrics exemplar cap (spec): the combined length of the exemplar
# label names and values must not exceed 128 UTF-8 characters.
OPENMETRICS_EXEMPLAR_MAX_RUNES = 128


def _families_from_obs() -> "list[tuple[str, str, str]]":
    """(name, type, help) for every family object hanging off the two
    facades — the constructors are the single source of truth, so a new
    family is linted the moment it exists."""
    from k3stpu.obs import ServeObs
    from k3stpu.obs.hist import (
        Counter,
        Gauge,
        Histogram,
        InfoGauge,
        LabeledCounter,
        LabeledGauge,
    )
    from k3stpu.obs.train import TrainObs

    fams = []
    for facade in (ServeObs(), TrainObs()):
        for attr in vars(facade).values():
            if isinstance(attr, Histogram):
                fams.append((attr.name, "histogram", attr.help))
            elif isinstance(attr, (Counter, LabeledCounter)):
                fams.append((attr.name, "counter", attr.help))
            elif isinstance(attr, (Gauge, LabeledGauge, InfoGauge)):
                fams.append((attr.name, "gauge", attr.help))
    return fams


def _families_from_server() -> "list[tuple[str, str, str]]":
    src = open(os.path.join(REPO, "k3stpu", "serve", "server.py")).read()
    return [(n, t, h) for n, t, h in EMIT_RE.findall(src)]


def _families_from_node_exporter() -> "list[tuple[str, str, str]]":
    """The node exporter's families, from a real NodeCollector — same
    construct-and-scan discipline as the facades (the constructor never
    touches the filesystem; only collect() does)."""
    from k3stpu.obs.hist import (
        Counter,
        Gauge,
        Histogram,
        InfoGauge,
        LabeledCounter,
        LabeledGauge,
    )
    from k3stpu.obs.node_exporter import NodeCollector

    fams = []
    for attr in vars(NodeCollector(drop_dir="/nonexistent")).values():
        if isinstance(attr, Histogram):
            fams.append((attr.name, "histogram", attr.help))
        elif isinstance(attr, (Counter, LabeledCounter)):
            fams.append((attr.name, "counter", attr.help))
        elif isinstance(attr, (Gauge, LabeledGauge, InfoGauge)):
            fams.append((attr.name, "gauge", attr.help))
    return fams


def _families_from_router() -> "list[tuple[str, str, str]]":
    """The router tier's families, from a real RouterObs — the facade
    constructs without jax (the router never touches a device)."""
    from k3stpu.obs.hist import (
        Counter,
        Gauge,
        Histogram,
        InfoGauge,
        LabeledCounter,
        LabeledGauge,
    )
    from k3stpu.router.obs import RouterObs

    fams = []
    for attr in vars(RouterObs(instance="lint")).values():
        if isinstance(attr, Histogram):
            fams.append((attr.name, "histogram", attr.help))
        elif isinstance(attr, (Counter, LabeledCounter)):
            fams.append((attr.name, "counter", attr.help))
        elif isinstance(attr, (Gauge, LabeledGauge, InfoGauge)):
            fams.append((attr.name, "gauge", attr.help))
    return fams


def _families_from_autoscaler() -> "list[tuple[str, str, str]]":
    """The autoscaler's families, from a real AutoscalerObs — same
    no-jax construct-and-scan discipline as the router facade."""
    from k3stpu.autoscaler.obs import AutoscalerObs
    from k3stpu.obs.hist import (
        Counter,
        Gauge,
        Histogram,
        InfoGauge,
        LabeledCounter,
        LabeledGauge,
    )

    fams = []
    for attr in vars(AutoscalerObs(instance="lint")).values():
        if isinstance(attr, Histogram):
            fams.append((attr.name, "histogram", attr.help))
        elif isinstance(attr, (Counter, LabeledCounter)):
            fams.append((attr.name, "counter", attr.help))
        elif isinstance(attr, (Gauge, LabeledGauge, InfoGauge)):
            fams.append((attr.name, "gauge", attr.help))
    return fams


def _families_from_canary() -> "list[tuple[str, str, str]]":
    """The canary's families, from a real CanaryObs — same no-jax
    construct-and-scan discipline as the router facade."""
    from k3stpu.canary.obs import CanaryObs
    from k3stpu.obs.hist import (
        Counter,
        Gauge,
        Histogram,
        InfoGauge,
        LabeledCounter,
        LabeledGauge,
    )

    fams = []
    for attr in vars(CanaryObs(instance="lint")).values():
        if isinstance(attr, Histogram):
            fams.append((attr.name, "histogram", attr.help))
        elif isinstance(attr, (Counter, LabeledCounter)):
            fams.append((attr.name, "counter", attr.help))
        elif isinstance(attr, (Gauge, LabeledGauge, InfoGauge)):
            fams.append((attr.name, "gauge", attr.help))
    return fams


def _families_from_slo() -> "list[tuple[str, str, str]]":
    """The SLO engine's families. The burn-rate family is hand-rendered
    (two label dimensions — no Labeled* primitive carries that), so
    slo.py declares both via LINT_FAMILIES instead of construct-and-
    scan; the exposition renders from the same constants."""
    from k3stpu.obs.slo import LINT_FAMILIES

    return list(LINT_FAMILIES)


def _families_from_collector() -> "list[tuple[str, str, str]]":
    """The metrics pipeline's own families, from a real CollectorObs —
    the collector must be observable by the very rules it executes.
    (The synthetic ALERTS series is deliberately NOT here: it is
    hand-rendered without the k3stpu_ prefix because
    ``ALERTS{alertname=,alertstate=}`` is the Prometheus convention
    dashboards already query.)"""
    from k3stpu.obs.collector import CollectorObs
    from k3stpu.obs.hist import (
        Counter,
        Gauge,
        Histogram,
        InfoGauge,
        LabeledCounter,
        LabeledGauge,
    )

    fams = []
    for attr in vars(CollectorObs(instance="lint")).values():
        if isinstance(attr, Histogram):
            fams.append((attr.name, "histogram", attr.help))
        elif isinstance(attr, (Counter, LabeledCounter)):
            fams.append((attr.name, "counter", attr.help))
        elif isinstance(attr, (Gauge, LabeledGauge, InfoGauge)):
            fams.append((attr.name, "gauge", attr.help))
    return fams


def _all_families() -> "list[tuple[str, str, str]]":
    return (_families_from_obs() + _families_from_server()
            + _families_from_node_exporter() + _families_from_router()
            + _families_from_autoscaler() + _families_from_canary()
            + _families_from_slo() + _families_from_collector())


def lint() -> "list[str]":
    problems = []
    fams = _all_families()
    if len(fams) < 20:
        # The scan itself regressing (regex drift, facade rename) must
        # fail loudly, not pass an empty list.
        problems.append(f"scan found only {len(fams)} families — the "
                        f"collectors are broken, not the metrics")
    seen: "dict[str, str]" = {}
    for name, mtype, help_text in fams:
        where = f"{name} ({mtype})"
        if name in seen and name not in DUPLICATE_EXEMPT:
            problems.append(f"{where}: duplicate family (also {seen[name]})")
        seen[name] = mtype
        if not name.startswith("k3stpu_"):
            problems.append(f"{where}: missing k3stpu_ prefix")
        if not NAME_RE.match(name):
            problems.append(f"{where}: invalid Prometheus name")
        if not help_text.strip():
            problems.append(f"{where}: empty # HELP text")
        if mtype == "counter" and not name.endswith("_total"):
            problems.append(f"{where}: counter must end in _total")
        if mtype == "histogram":
            for suf in RESERVED_HIST_SUFFIXES:
                if name.endswith(suf):
                    problems.append(f"{where}: histogram name ends in "
                                    f"reserved suffix {suf}")
        for unit in UNITS:
            if unit in name.split("_"):
                ok = (name.endswith(f"_{unit}")
                      or name.endswith(f"_{unit}_total")
                      # pages_total counts pages, not seconds/bytes —
                      # only a unit mentioned mid-name is a misplacement.
                      )
                if not ok:
                    problems.append(f"{where}: mentions unit '{unit}' "
                                    f"but is not suffixed _{unit}")
    return problems


def _labeled_families() -> "list[tuple[str, tuple]]":
    """(family name, declared label keys) for every Labeled*/InfoGauge
    family — and every Histogram carrying a constant label set — on the
    real facades: the cardinality lint's scan surface."""
    from k3stpu.obs import ServeObs
    from k3stpu.obs.hist import (
        Histogram,
        InfoGauge,
        LabeledCounter,
        LabeledGauge,
    )
    from k3stpu.autoscaler.obs import AutoscalerObs
    from k3stpu.canary.obs import CanaryObs
    from k3stpu.obs.node_exporter import NodeCollector
    from k3stpu.obs.slo import LINT_LABELED
    from k3stpu.obs.train import TrainObs
    from k3stpu.router.obs import RouterObs

    out = [(name, tuple(keys)) for name, keys in LINT_LABELED]
    for owner in (ServeObs(), TrainObs(),
                  NodeCollector(drop_dir="/nonexistent"),
                  RouterObs(instance="lint"),
                  AutoscalerObs(instance="lint"),
                  CanaryObs(instance="lint")):
        for attr in vars(owner).values():
            if isinstance(attr, (LabeledCounter, LabeledGauge)):
                out.append((attr.name, (attr.label,)))
            elif isinstance(attr, InfoGauge):
                out.append((attr.name, tuple(sorted(attr.labels))))
            elif isinstance(attr, Histogram) and attr.labels:
                out.append((attr.name, tuple(sorted(attr.labels))))
    return out


def lint_label_keys(
        labeled: "list[tuple[str, tuple]] | None" = None) -> "list[str]":
    """Every labeled family must declare only label keys from the
    bounded-cardinality allow-list."""
    problems = []
    labeled = _labeled_families() if labeled is None else labeled
    if not labeled:
        return ["label-keys: scan found no labeled families — the "
                "collector drifted, not the metrics"]
    for name, keys in labeled:
        for key in keys:
            if key not in BOUNDED_LABEL_KEYS:
                problems.append(
                    f"{name}: label key '{key}' is not in the "
                    f"bounded-cardinality allow-list "
                    f"({', '.join(sorted(BOUNDED_LABEL_KEYS))})")
    return problems


# One exposition sample line: name, optional {labels}, then the value
# and optional timestamp/exemplar tail.
_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(.*)$")
# Exemplar tail: ` # {labelset} value [timestamp]`.
_EXEMPLAR_RE = re.compile(r"\s#\s+(\{[^}]*\})\s+\S+(\s+\S+)?\s*$")


def lint_openmetrics(text: str) -> "list[str]":
    """Lint a rendered OpenMetrics exposition for exemplar-placement
    and label-set-size violations:

    - exemplars may only ride on ``_bucket`` / ``_count`` sample lines
      (the spec allows histogram buckets and counters; gauges and
      ``_sum`` lines never carry one);
    - an exemplar label set stays within the spec's 128-rune cap
      (combined length of label names and values);
    - the exposition ends with the mandatory ``# EOF`` terminator.
    """
    problems = []
    lines = text.splitlines()
    if not lines or lines[-1].strip() != "# EOF":
        problems.append("openmetrics: missing '# EOF' terminator")
    for i, line in enumerate(lines, 1):
        if not line or line.startswith("#"):
            continue
        ex = _EXEMPLAR_RE.search(line)
        if not ex:
            continue
        m = _SAMPLE_RE.match(line)
        name = m.group(1) if m else "?"
        where = f"openmetrics line {i} ({name})"
        if not (name.endswith("_bucket") or name.endswith("_count")):
            problems.append(f"{where}: exemplar on a non-bucket/"
                            f"non-count sample line")
        labelset = ex.group(1)[1:-1]  # strip the braces
        pairs = re.findall(r'([a-zA-Z0-9_]+)="((?:[^"\\]|\\.)*)"', labelset)
        runes = sum(len(k) + len(v) for k, v in pairs)
        if runes > OPENMETRICS_EXEMPLAR_MAX_RUNES:
            problems.append(f"{where}: exemplar label set is {runes} "
                            f"runes (cap "
                            f"{OPENMETRICS_EXEMPLAR_MAX_RUNES})")
    return problems


def _rule_groups_from_chart() -> "list[dict]":
    """Rule groups out of the chart's rendered rules ConfigMap, with
    the nodeExporter, rules, AND QoS components forced on — the lint
    must see every rule the chart can ship, including the per-class
    burn-rate alert pair that only renders under inference.qos
    (a superset of the default render)."""
    import yaml

    from k3stpu.utils.helm_lite import render_chart

    chart = os.path.join(REPO, "deploy", "charts", "k3s-tpu")
    text = render_chart(chart, overrides={"nodeExporter.enabled": "true",
                                          "rules.enabled": "true",
                                          "inference.enabled": "true",
                                          "inference.qos.enabled":
                                              "true"})
    groups = []
    for doc in yaml.safe_load_all(text):
        if not doc or doc.get("kind") != "ConfigMap":
            continue
        if "rules" not in doc["metadata"]["name"]:
            continue
        for body in doc.get("data", {}).values():
            groups.extend(yaml.safe_load(body).get("groups", []))
    return groups


def lint_rules(fams: "list[tuple[str, str, str]] | None" = None,
               groups: "list[dict] | None" = None) -> "list[str]":
    """Recording/alerting rules vs the real families AND the embedded
    engine: every expr must parse in the PromQL subset the collector
    executes (obs/promql.py — an out-of-subset expression fails with
    the offending token, because the shipped collector could not run
    it), and every series name the parsed AST selects must be a linted
    family (histograms via _bucket/_sum/_count) or another rule's
    recorded output. The AST replaces the old regex token scan, so a
    metric name inside a label VALUE or annotation no longer counts as
    a reference."""
    from k3stpu.obs.promql import (
        PromQLError,
        metric_names,
        parse_duration,
        parse_expr,
    )

    problems = []
    fams = _all_families() if fams is None else fams
    known = set()
    for name, mtype, _ in fams:
        if mtype == "histogram":
            known.update(name + s for s in ("_bucket", "_sum", "_count"))
        else:
            known.add(name)
    if groups is None:
        groups = _rule_groups_from_chart()
    if not groups:
        return ["rules: chart rendered no rule groups — the rules "
                "template or this lint's render drifted"]
    recorded = {r["record"] for g in groups for r in g.get("rules", [])
                if "record" in r}
    for g in groups:
        gname = g.get("name", "?")
        for r in g.get("rules", []):
            rname = r.get("record") or r.get("alert") or "?"
            where = f"rule {gname}/{rname}"
            expr = str(r.get("expr", ""))
            if not expr.strip():
                problems.append(f"{where}: empty expr")
                continue
            if "record" in r and ":" not in r["record"]:
                problems.append(f"{where}: recording-rule name must use "
                                f"the level:metric:operation convention")
            try:
                node = parse_expr(expr)
            except PromQLError as e:
                problems.append(f"{where}: expr outside the embedded "
                                f"PromQL subset: {e}")
                continue
            if "for" in r:
                try:
                    parse_duration(str(r["for"]))
                except PromQLError as e:
                    problems.append(f"{where}: bad for duration: {e}")
            for tok in sorted(metric_names(node)):
                if tok not in known and tok not in recorded:
                    problems.append(
                        f"{where}: references '{tok}' which is neither "
                        f"a linted family nor a recorded rule")
    return problems


def _live_openmetrics() -> str:
    """A real rendered OpenMetrics exposition (ServeObs, one observed
    sample per histogram so exemplar lines exist to lint)."""
    from k3stpu.obs import ServeObs, new_trace_id

    obs = ServeObs()
    tid = new_trace_id()
    for h in (obs.ttft, obs.tpot, obs.e2e, obs.queue_wait):
        h.observe(0.01, trace_id=tid)
    return obs.render_openmetrics() + "\n# EOF\n"


def main() -> int:
    problems = (lint() + lint_label_keys()
                + lint_openmetrics(_live_openmetrics()) + lint_rules())
    if problems:
        for p in problems:
            print(f"metrics-lint: {p}")
        return 1
    fams = _all_families()
    labeled = _labeled_families()
    groups = _rule_groups_from_chart()
    rules = sum(len(g.get("rules", [])) for g in groups)
    print(f"metrics-lint: {len(fams)} families ({len(labeled)} labeled), "
          f"{rules} rules clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
